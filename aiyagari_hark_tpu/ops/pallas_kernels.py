"""Pallas TPU kernels for the framework's hottest loop.

The single hottest computation (bench phase breakdown, ``bench.py``) is the
stationary-wealth fixed point: thousands of sequential push-forward steps of
a [D, N] histogram.  Under a plain XLA ``while_loop`` every iteration
round-trips the distribution (and, in the dense formulation, re-reads the
[N, D, D] lottery operator) through HBM.  This kernel runs the ENTIRE fixed
point inside one ``pallas_call``: the operator ``S`` (~7 MB at the benchmark
config D=500, N=7, f32 — comfortably inside the ~16 MB VMEM budget), the
labor-mixing matrix ``P``, and the iterate all stay VMEM-resident, so each
step is two on-chip matmuls (batched matvec on the MXU + the [D,N]x[N,N]
mix) with zero HBM traffic.

Correctness shares the exact same iteration code as the XLA path
(``models.household.accelerated_distribution_fixed_point`` — including the
Aitken extrapolation and its certification semantics), so the kernel cannot
drift from the reference implementation; only the memory placement differs.

CPU fallback / tests run the same kernel with ``interpret=True`` (the
Pallas interpreter), asserting bit-level agreement with the XLA dense path.
Reference for the computation being accelerated: the reference's per-period
``np.searchsorted`` + Python-loop simulation (``Aiyagari_Support.py``
get_shocks/get_states hot loop #2, SURVEY.md §3.3), replaced here by Young's
deterministic method in operator form.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _fixed_point_kernel(S_ref, P_ref, d0_ref, out_ref, stats_ref, *,
                        tol, max_iter, accel_every):
    """Whole stationary fixed point on VMEM-resident operands."""
    from ..models.household import accelerated_distribution_fixed_point

    S = S_ref[:]          # [N, D, D] lottery operator
    P = P_ref[:]          # [N, N] labor mixing
    d0 = d0_ref[:]        # [D, N] initial distribution
    n_states = S.shape[0]

    def push(dist):
        # batched matvec moved[:, i] = S[i] @ dist[:, i], written as a
        # statically-unrolled list of plain 2D matmuls: Mosaic rejects the
        # batched-dot dimension numbers the einsum formulation lowers to
        # ("#tpu.dot_dimension_numbers ... expected integer value" on a
        # v5-lite), and N is a small static constant anyway
        cols = [jnp.matmul(S[i], dist[:, i:i + 1],
                           precision=jax.lax.Precision.HIGHEST,
                           preferred_element_type=dist.dtype)
                for i in range(n_states)]
        moved = jnp.concatenate(cols, axis=1)
        return jnp.matmul(moved, P, precision=jax.lax.Precision.HIGHEST,
                          preferred_element_type=dist.dtype)

    # status is dropped at the kernel boundary: the (iters, diff) stats
    # pair reconstructs it exactly (see ``stationary_wealth``)
    dist, it, diff, _ = accelerated_distribution_fixed_point(
        push, d0, tol, max_iter, accel_every)
    out_ref[:] = dist
    # full-row store: Mosaic rejects scalar stores into a VMEM ref
    stats_ref[:] = jnp.stack([it.astype(d0.dtype),
                              diff.astype(d0.dtype)]).reshape(1, 2)


def stationary_dense_pallas(S: jnp.ndarray, P: jnp.ndarray,
                            dist0: jnp.ndarray, tol: float,
                            max_iter: int = 20000, accel_every: int = 64,
                            interpret: bool | None = None):
    """Run the stationary-distribution fixed point as ONE Pallas kernel.

    Args: ``S`` [N, D, D] from ``models.household.dense_wealth_operator``,
    ``P`` [N, N] labor transition, ``dist0`` [D, N].  Returns
    (dist [D, N], n_iter, final_diff) — same contract as
    ``accelerated_distribution_fixed_point``.

    ``interpret``: None = interpret everywhere except a real TPU backend
    (the interpreter is the correctness path on CPU/GPU; the compiled
    Mosaic kernel is the TPU path).
    """
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")

    n, d, _ = S.shape
    kernel = functools.partial(_fixed_point_kernel, tol=tol,
                               max_iter=max_iter, accel_every=accel_every)
    call = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((d, n), dist0.dtype),
                   jax.ShapeDtypeStruct((1, 2), dist0.dtype)),
        interpret=interpret,
    )
    dist, stats = call(S, P, dist0)
    return dist, stats[0, 0].astype(jnp.int32), stats[0, 1]


def _fixed_point_kernel_lane(S_ref, P_ref, d0_ref, out_ref, stats_ref, *,
                             tol, max_iter, accel_every):
    """One sweep lane's whole fixed point; refs carry a leading lane axis of
    block size 1 (the pallas grid maps program instance -> lane)."""
    from ..models.household import accelerated_distribution_fixed_point

    S = S_ref[0]          # [N, D, D]
    P = P_ref[0]          # [N, N]
    d0 = d0_ref[0]        # [D, N]
    n_states = S.shape[0]

    def push(dist):
        cols = [jnp.matmul(S[i], dist[:, i:i + 1],
                           precision=jax.lax.Precision.HIGHEST,
                           preferred_element_type=dist.dtype)
                for i in range(n_states)]
        moved = jnp.concatenate(cols, axis=1)
        return jnp.matmul(moved, P, precision=jax.lax.Precision.HIGHEST,
                          preferred_element_type=dist.dtype)

    dist, it, diff, _ = accelerated_distribution_fixed_point(
        push, d0, tol, max_iter, accel_every)
    out_ref[0] = dist
    stats_ref[0] = jnp.stack([it.astype(d0.dtype),
                              diff.astype(d0.dtype)]).reshape(1, 2)


def stationary_dense_pallas_grid(S: jnp.ndarray, P: jnp.ndarray,
                                 dist0: jnp.ndarray, tol: float,
                                 max_iter: int = 20000,
                                 accel_every: int = 64,
                                 interpret: bool | None = None):
    """Batched fixed points as a Pallas GRID: one program instance per sweep
    lane, each lane's operator VMEM-resident for its own iterations only.

    This is the per-lane answer to the vmap-of-while straggler problem
    (VERDICT r2 weak-item 3): under ``vmap(dense)`` every push-forward step
    processes ALL lanes until the slowest converges (measured total-work
    skew 2.55 on the Table II sweep), and under ``vmap`` of the single-lane
    Pallas kernel all lanes land in ONE kernel whose operators exceed
    scoped VMEM.  Gridding runs lanes sequentially on the TensorCore, each
    exiting at its OWN convergence — total steps sum(iters) instead of
    lanes x max(iters) — with only lane c's ~7 MB operator resident at a
    time.

    Args: ``S`` [C, N, D, D], ``P`` [C, N, N], ``dist0`` [C, D, N].
    Returns (dist [C, D, N], iters [C] int32, diffs [C]).
    """
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")

    c, n, d, _ = S.shape
    kernel = functools.partial(_fixed_point_kernel_lane, tol=tol,
                               max_iter=max_iter, accel_every=accel_every)
    kwargs = {}
    if not interpret:
        from jax.experimental.pallas import tpu as pltpu

        # The lane pipeline double-buffers the next lane's ~7 MB operator
        # during compute, which blows the default 16 MB scoped-VMEM budget
        # (measured 21.6 MB at D=500, N=7, f32); raise the scoped limit —
        # physical VMEM is far larger — rather than shrink blocks.
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=2 * (4 * n * d * d) + 32 * 1024 * 1024)
    call = pl.pallas_call(
        kernel,
        grid=(c,),
        in_specs=[
            pl.BlockSpec((1, n, d, d), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, n, n), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, d, n), lambda i: (i, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, d, n), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, 2), lambda i: (i, 0, 0)),
        ),
        out_shape=(jax.ShapeDtypeStruct((c, d, n), dist0.dtype),
                   jax.ShapeDtypeStruct((c, 1, 2), dist0.dtype)),
        interpret=interpret,
        **kwargs,
    )
    dist, stats = call(S, P, dist0)
    return dist, stats[:, 0, 0].astype(jnp.int32), stats[:, 0, 1]


def _egm_scalars(s):
    """Unpack the packed per-lane scalar row (R, W, disc_fac, crra,
    borrow_limit) — one [1, 5] block instead of five scalar refs, because
    Mosaic wants >= 2-D VMEM operands."""
    return s[0], s[1], s[2], s[3], s[4]


def _egm_fixed_point_kernel(m0_ref, c0_ref, a_ref, lvl_ref, P_ref, scal_ref,
                            m_out, c_out, stats_ref, *, tol, max_iter,
                            accel_every):
    """Whole EGM policy fixed point on VMEM-resident operands.

    Exactly the distribution kernel's design: the iteration code is the
    SAME ``accelerated_policy_fixed_point`` + ``egm_step`` the XLA path
    runs (Anderson acceleration, certification semantics included), so the
    kernel cannot drift from the reference — only memory placement and the
    per-lane exit differ.  The status is dropped at the kernel boundary
    and reconstructed from (iters, diff) outside (this loop has no stall
    exit, so the classification is exact)."""
    from ..models.household import (
        HouseholdPolicy,
        SimpleModel,
        accelerated_policy_fixed_point,
        egm_step,
    )

    a = a_ref[0]          # [A] end-of-period asset grid
    lvl = lvl_ref[0]      # [N] labor levels
    P = P_ref[:]          # [N, N] labor transition
    R, W, disc_fac, crra, blim = _egm_scalars(scal_ref[0])
    # egm_step only touches a_grid/labor_levels/transition/borrow_limit;
    # the remaining SimpleModel fields are structural placeholders so the
    # kernel can reuse the exact production step function
    model = SimpleModel(a_grid=a, labor_levels=lvl, transition=P,
                        labor_stationary=lvl, dist_grid=a,
                        borrow_limit=blim)
    p0 = HouseholdPolicy(m_knots=m0_ref[:], c_knots=c0_ref[:])
    pol, it, diff, _ = accelerated_policy_fixed_point(
        lambda p: egm_step(p, R, W, model, disc_fac, crra),
        p0, tol, max_iter, accel_every)
    m_out[:] = pol.m_knots
    c_out[:] = pol.c_knots
    stats_ref[:] = jnp.stack([it.astype(a.dtype),
                              diff.astype(a.dtype)]).reshape(1, 2)


def egm_policy_pallas(m0: jnp.ndarray, c0: jnp.ndarray, a_grid: jnp.ndarray,
                      levels: jnp.ndarray, P: jnp.ndarray,
                      scalars: jnp.ndarray, tol: float, max_iter: int = 3000,
                      accel_every: int = 32, interpret: bool | None = None):
    """One cell's EGM policy fixed point as ONE Pallas kernel.

    Args: ``m0``/``c0`` [N, A+1] initial policy knots, ``a_grid`` [A],
    ``levels`` [N], ``P`` [N, N], ``scalars`` [5] packed
    (R, W, disc_fac, crra, borrow_limit).  Returns
    (m_knots, c_knots, n_iter, final_diff) — the
    ``accelerated_policy_fixed_point`` contract minus the status code,
    which ``solve_household`` reconstructs from (iters, diff).

    Grid-policy note (DESIGN §5b): this kernel runs the fixed REFERENCE
    knot layout ([N, A+1]: constraint + A endogenous) — the compact
    policies' analytic tail knot and coarse-to-fine ladder live on the
    XLA path only, so ``solve_household`` demotes ``method`` to "xla"
    under a non-reference ``grid`` exactly as it does under
    non-reference precision."""
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")

    n, a1 = m0.shape
    kernel = functools.partial(_egm_fixed_point_kernel, tol=tol,
                               max_iter=max_iter, accel_every=accel_every)
    call = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((n, a1), m0.dtype),
                   jax.ShapeDtypeStruct((n, a1), m0.dtype),
                   jax.ShapeDtypeStruct((1, 2), m0.dtype)),
        interpret=interpret,
    )
    m, c, stats = call(m0, c0, a_grid.reshape(1, -1), levels.reshape(1, -1),
                       P, scalars.reshape(1, -1))
    return m, c, stats[0, 0].astype(jnp.int32), stats[0, 1]


def _egm_fixed_point_kernel_lane(m0_ref, c0_ref, a_ref, lvl_ref, P_ref,
                                 scal_ref, m_out, c_out, stats_ref, *,
                                 tol, max_iter, accel_every):
    """One sweep lane's whole EGM fixed point; refs carry a leading lane
    axis of block size 1 (pallas grid maps program instance -> lane)."""
    from ..models.household import (
        HouseholdPolicy,
        SimpleModel,
        accelerated_policy_fixed_point,
        egm_step,
    )

    a = a_ref[0, 0]
    lvl = lvl_ref[0, 0]
    P = P_ref[0]
    R, W, disc_fac, crra, blim = _egm_scalars(scal_ref[0, 0])
    model = SimpleModel(a_grid=a, labor_levels=lvl, transition=P,
                        labor_stationary=lvl, dist_grid=a,
                        borrow_limit=blim)
    p0 = HouseholdPolicy(m_knots=m0_ref[0], c_knots=c0_ref[0])
    pol, it, diff, _ = accelerated_policy_fixed_point(
        lambda p: egm_step(p, R, W, model, disc_fac, crra),
        p0, tol, max_iter, accel_every)
    m_out[0] = pol.m_knots
    c_out[0] = pol.c_knots
    stats_ref[0] = jnp.stack([it.astype(a.dtype),
                              diff.astype(a.dtype)]).reshape(1, 2)


def egm_policy_pallas_grid(m0: jnp.ndarray, c0: jnp.ndarray,
                           a_grid: jnp.ndarray, levels: jnp.ndarray,
                           P: jnp.ndarray, scalars: jnp.ndarray, tol: float,
                           max_iter: int = 3000, accel_every: int = 32,
                           interpret: bool | None = None):
    """Batched EGM fixed points as a Pallas GRID: one program instance per
    sweep lane, each exiting at its OWN convergence.

    The per-lane answer to vmap-of-while lock-step for the POLICY loop
    (ISSUE 2 tentpole): under ``vmap(solve_household)`` every EGM backward
    step processes all lanes until the slowest cell's policy converges —
    a converged cell keeps burning MXU cycles on masked matmuls.  Gridding
    runs lanes sequentially on the TensorCore, total steps sum(iters)
    instead of lanes x max(iters), the same economics as the distribution
    lane grid (``stationary_dense_pallas_grid``).

    Args: ``m0``/``c0`` [C, N, A+1], ``a_grid`` [C, A], ``levels`` [C, N],
    ``P`` [C, N, N], ``scalars`` [C, 5].  Returns
    (m_knots [C, N, A+1], c_knots [C, N, A+1], iters [C] int32, diffs [C]).
    """
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")

    c, n, a1 = m0.shape
    a = a_grid.shape[1]
    kernel = functools.partial(_egm_fixed_point_kernel_lane, tol=tol,
                               max_iter=max_iter, accel_every=accel_every)
    kwargs = {}
    if not interpret:
        from jax.experimental.pallas import tpu as pltpu

        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("arbitrary",))
    call = pl.pallas_call(
        kernel,
        grid=(c,),
        in_specs=[
            pl.BlockSpec((1, n, a1), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, n, a1), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, a), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, n), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, n, n), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, 5), lambda i: (i, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, n, a1), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, n, a1), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, 2), lambda i: (i, 0, 0)),
        ),
        out_shape=(jax.ShapeDtypeStruct((c, n, a1), m0.dtype),
                   jax.ShapeDtypeStruct((c, n, a1), m0.dtype),
                   jax.ShapeDtypeStruct((c, 1, 2), m0.dtype)),
        interpret=interpret,
        **kwargs,
    )
    m, cc, stats = call(m0, c0, a_grid.reshape(c, 1, a),
                        levels.reshape(c, 1, n), P,
                        scalars.reshape(c, 1, 5))
    return m, cc, stats[:, 0, 0].astype(jnp.int32), stats[:, 0, 1]


# ---------------------------------------------------------------------------
# Fused EGM + push-forward megakernel (ISSUE 13 tentpole, DESIGN §4c).
# ---------------------------------------------------------------------------

def _fused_phases(m0_ref, c0_ref, a_ref, dg_ref, lvl_ref, P_ref, scal_ref,
                  h_ref, d0_ref, *, tol, max_iter, accel_every, dist_tol,
                  dist_max_iter, dist_accel, tail):
    """The shared body of both fused kernels: ONE supply evaluation's EGM
    policy fixed point AND distribution push-forward fixed point without
    leaving the kernel between phases (the latency-roofline fix, DESIGN
    §4c).  Refs arrive already lane-sliced ([N, K] policies, [1, A]/
    [1, D] grids).  Returns (policy, dist, egm_it, egm_diff, dist_it,
    dist_diff).

    Correctness shares the exact iteration code of the XLA paths
    (``accelerated_policy_fixed_point`` + ``egm_step``,
    ``accelerated_distribution_fixed_point``) so the kernel cannot drift
    from the reference logic; what changes is memory placement (grids,
    transition matrix, and both iterates stay VMEM-resident across both
    phases) and the push-forward layout (the tile-shaped
    ``ops.markov.tiled_wealth_push_forward`` contraction — reduction
    order differs from the reference matvec layout at float-fusion
    noise, which is why the fused path is opt-in, never default).

    ``tail`` (static): close every policy iterate with the PR 12
    analytic linear tail IN-KERNEL.  The human-wealth intercept ``h``
    needs an [N, N] linear solve, which neither Mosaic nor the kernel
    economics want per iteration — it depends only on (R, W, P), so the
    dispatch wrapper computes it ONCE outside and passes it in
    (``h_ref``); the MPC-limit slope is elementwise and computed
    in-kernel.
    """
    from ..models.household import (
        HouseholdPolicy,
        SimpleModel,
        _append_analytic_tail_knots,
        accelerated_distribution_fixed_point,
        accelerated_policy_fixed_point,
        egm_step,
        wealth_transition,
    )
    from ..ops.utility import asymptotic_mpc
    from .markov import tiled_wealth_push_forward

    a = a_ref[0]          # [A] end-of-period asset grid
    dg = dg_ref[0]        # [D] wealth-histogram support
    lvl = lvl_ref[0]      # [N] labor levels
    P = P_ref[:]          # [N, N] labor transition
    R, W, disc_fac, crra, blim = _egm_scalars(scal_ref[0])
    h = h_ref[0]          # [N] per-state human wealth (tail intercept)
    dt = a.dtype
    n_states = lvl.shape[0]
    d_size = dg.shape[0]
    # the remaining SimpleModel field (labor_stationary) is a structural
    # placeholder so the kernel can reuse the exact production step and
    # transition functions — nothing in this body reads it
    model = SimpleModel(a_grid=a, labor_levels=lvl, transition=P,
                        labor_stationary=lvl, dist_grid=dg,
                        borrow_limit=blim)

    def step(p):
        p = egm_step(p, R, W, model, disc_fac, crra)
        if tail:
            kappa = asymptotic_mpc(R, disc_fac, crra)
            mk, ck = _append_analytic_tail_knots(p.m_knots, p.c_knots,
                                                 kappa, h)
            p = HouseholdPolicy(m_knots=mk, c_knots=ck)
        return p

    p0 = HouseholdPolicy(m_knots=m0_ref[:], c_knots=c0_ref[:])
    pol, egm_it, egm_diff, _ = accelerated_policy_fixed_point(
        step, p0, tol, max_iter, accel_every)

    # -- push-forward phase, same VMEM residency ---------------------------
    # The Young lottery evaluated on the histogram support — the SAME
    # production code as the XLA path (the policy never leaves the
    # kernel between phases, but the lottery logic must not fork):
    trans = wealth_transition(pol, R, W, model)
    idx, w = trans.idx, trans.weight
    # Per-state lottery operator built WITHOUT scatter (Mosaic has no
    # .at[].add): column k of state n's block carries source gridpoint
    # k's two-point lottery, placed by one-hot row compares.  Laid out
    # directly as the [D, N·D] left factor of the tile-shaped
    # contraction (``ops.markov.tile_wealth_operator`` layout).
    rows = jax.lax.broadcasted_iota(jnp.int32, (d_size, d_size), 0)
    zero = jnp.zeros((), dtype=dt)
    blocks = []
    for i in range(n_states):
        left = jnp.where(rows == idx[:, i][None, :],
                         (1.0 - w[:, i])[None, :], zero)
        right = jnp.where(rows == (idx[:, i] + 1)[None, :],
                          w[:, i][None, :], zero)
        blocks.append(left + right)
    S_t = jnp.concatenate(blocks, axis=1)                # [D, N·D]

    def push(dist):
        return tiled_wealth_push_forward(dist, S_t, P)

    dist, dist_it, dist_diff, _ = accelerated_distribution_fixed_point(
        push, d0_ref[:], dist_tol, dist_max_iter, dist_accel)
    return pol, dist, egm_it, egm_diff, dist_it, dist_diff


def _fused_cell_kernel(m0_ref, c0_ref, a_ref, dg_ref, lvl_ref, P_ref,
                       scal_ref, h_ref, d0_ref, m_out, c_out, dist_out,
                       stats_ref, *, tol, max_iter, accel_every, dist_tol,
                       dist_max_iter, dist_accel, tail):
    """One cell's fused supply evaluation (see ``_fused_phases``).  The
    statuses are dropped at the kernel boundary and reconstructed from
    the (iters, diff) pairs outside — exact, as for the per-loop
    kernels."""
    pol, dist, egm_it, egm_diff, dist_it, dist_diff = _fused_phases(
        m0_ref, c0_ref, a_ref, dg_ref, lvl_ref, P_ref, scal_ref, h_ref,
        d0_ref, tol=tol, max_iter=max_iter, accel_every=accel_every,
        dist_tol=dist_tol, dist_max_iter=dist_max_iter,
        dist_accel=dist_accel, tail=tail)
    dt = dist.dtype
    m_out[:] = pol.m_knots
    c_out[:] = pol.c_knots
    dist_out[:] = dist
    stats_ref[:] = jnp.stack([egm_it.astype(dt), egm_diff.astype(dt),
                              dist_it.astype(dt),
                              dist_diff.astype(dt)]).reshape(1, 4)


def fused_cell_pallas(m0, c0, a_grid, dist_grid, levels, P, scalars, h, d0,
                      tol: float, max_iter: int = 3000,
                      accel_every: int = 32, dist_tol: float = 1e-11,
                      dist_max_iter: int = 20000, dist_accel: int = 64,
                      tail: bool = False, interpret: bool | None = None):
    """One cell's EGM policy fixed point AND distribution push-forward as
    ONE Pallas kernel launch (ISSUE 13 tentpole): the two phases of a
    supply evaluation run back to back with shared VMEM residency of the
    grids/transition matrix, never returning to the host (or HBM)
    between them.

    Args: ``m0``/``c0`` [N, K] policy knots (K = A+1 reference layout,
    A+3 tail-closed compact layout with ``tail=True``), ``a_grid`` [A],
    ``dist_grid`` [D], ``levels`` [N], ``P`` [N, N], ``scalars`` [5]
    packed (R, W, disc_fac, crra, borrow_limit), ``h`` [N] per-state
    perfect-foresight human wealth (the in-kernel tail's intercept —
    pass zeros when ``tail=False``), ``d0`` [D, N].  Returns
    (m_knots, c_knots, dist, egm_iters, egm_diff, dist_iters,
    dist_diff); the caller reconstructs both ``solver_health`` statuses
    from the (iters, diff) pairs (``classify_fixed_point_exit`` — the
    policy loop has no stall exit, the distribution loop's stall window
    is classified exactly).

    ``interpret``: None = interpret everywhere except a real TPU backend
    (interpret-mode is the CI correctness path on CPU; the compiled
    Mosaic kernel is the TPU path, probe-gated by
    ``probe_kernel("fused")``)."""
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")

    n, k = m0.shape
    d = dist_grid.shape[0]
    kernel = functools.partial(_fused_cell_kernel, tol=tol,
                               max_iter=max_iter, accel_every=accel_every,
                               dist_tol=dist_tol,
                               dist_max_iter=dist_max_iter,
                               dist_accel=dist_accel, tail=tail)
    call = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((n, k), m0.dtype),
                   jax.ShapeDtypeStruct((n, k), m0.dtype),
                   jax.ShapeDtypeStruct((d, n), d0.dtype),
                   jax.ShapeDtypeStruct((1, 4), d0.dtype)),
        interpret=interpret,
    )
    m, c, dist, stats = call(m0, c0, a_grid.reshape(1, -1),
                             dist_grid.reshape(1, -1),
                             levels.reshape(1, -1), P,
                             scalars.reshape(1, -1), h.reshape(1, -1), d0)
    return (m, c, dist, stats[0, 0].astype(jnp.int32), stats[0, 1],
            stats[0, 2].astype(jnp.int32), stats[0, 3])


def _fused_cell_kernel_lane(m0_ref, c0_ref, a_ref, dg_ref, lvl_ref, P_ref,
                            scal_ref, h_ref, d0_ref, m_out, c_out,
                            dist_out, stats_ref, *, tol, max_iter,
                            accel_every, dist_tol, dist_max_iter,
                            dist_accel, tail):
    """One sweep lane's fused supply evaluation; refs carry a leading
    lane axis of block size 1 (the pallas grid maps program instance ->
    lane), so each lane runs BOTH phases and exits at its own
    convergence — the straggler economics of the per-loop lane grids,
    now covering the whole evaluation."""
    pol, dist, egm_it, egm_diff, dist_it, dist_diff = _fused_phases(
        m0_ref[0], c0_ref[0], a_ref[0], dg_ref[0], lvl_ref[0], P_ref[0],
        scal_ref[0], h_ref[0], d0_ref[0], tol=tol, max_iter=max_iter,
        accel_every=accel_every, dist_tol=dist_tol,
        dist_max_iter=dist_max_iter, dist_accel=dist_accel, tail=tail)
    dt = dist.dtype
    m_out[0] = pol.m_knots
    c_out[0] = pol.c_knots
    dist_out[0] = dist
    stats_ref[0] = jnp.stack([egm_it.astype(dt), egm_diff.astype(dt),
                              dist_it.astype(dt),
                              dist_diff.astype(dt)]).reshape(1, 4)


def fused_cell_pallas_grid(m0, c0, a_grid, dist_grid, levels, P, scalars,
                           h, d0, tol: float, max_iter: int = 3000,
                           accel_every: int = 32, dist_tol: float = 1e-11,
                           dist_max_iter: int = 20000,
                           dist_accel: int = 64, tail: bool = False,
                           interpret: bool | None = None):
    """Batched fused supply evaluations as a Pallas GRID: one program
    instance per sweep lane, each running its EGM fixed point AND its
    push-forward fixed point device-resident and exiting at its OWN
    convergence (ISSUE 13 tentpole — a whole bucket's inner work becomes
    one launch instead of launch-per-loop-per-lane).

    Args as ``fused_cell_pallas`` with a leading lane axis C:
    ``m0``/``c0`` [C, N, K], ``a_grid`` [C, A], ``dist_grid`` [C, D],
    ``levels`` [C, N], ``P`` [C, N, N], ``scalars`` [C, 5], ``h``
    [C, N], ``d0`` [C, D, N].  Returns (m [C,N,K], c [C,N,K],
    dist [C,D,N], egm_iters [C] int32, egm_diffs [C], dist_iters [C]
    int32, dist_diffs [C])."""
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")

    cc, n, k = m0.shape
    a = a_grid.shape[1]
    d = dist_grid.shape[1]
    kernel = functools.partial(_fused_cell_kernel_lane, tol=tol,
                               max_iter=max_iter, accel_every=accel_every,
                               dist_tol=dist_tol,
                               dist_max_iter=dist_max_iter,
                               dist_accel=dist_accel, tail=tail)
    kwargs = {}
    if not interpret:
        from jax.experimental.pallas import tpu as pltpu

        # Same scoped-VMEM reasoning as the distribution lane grid: the
        # pipeline double-buffers the next lane's operands, and the
        # in-kernel [D, N·D] tiled operator is the dominant term.
        op_bytes = d0.dtype.itemsize * n * d * d
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=2 * op_bytes + 32 * 1024 * 1024)
    call = pl.pallas_call(
        kernel,
        grid=(cc,),
        in_specs=[
            pl.BlockSpec((1, n, k), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, n, k), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, a), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, d), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, n), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, n, n), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, 5), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, n), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, d, n), lambda i: (i, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, n, k), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, n, k), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, d, n), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, 4), lambda i: (i, 0, 0)),
        ),
        out_shape=(jax.ShapeDtypeStruct((cc, n, k), m0.dtype),
                   jax.ShapeDtypeStruct((cc, n, k), m0.dtype),
                   jax.ShapeDtypeStruct((cc, d, n), d0.dtype),
                   jax.ShapeDtypeStruct((cc, 1, 4), d0.dtype)),
        interpret=interpret,
        **kwargs,
    )
    m, c, dist, stats = call(m0, c0, a_grid.reshape(cc, 1, a),
                             dist_grid.reshape(cc, 1, d),
                             levels.reshape(cc, 1, n), P,
                             scalars.reshape(cc, 1, 5),
                             h.reshape(cc, 1, n), d0)
    return (m, c, dist, stats[:, 0, 0].astype(jnp.int32), stats[:, 0, 1],
            stats[:, 0, 2].astype(jnp.int32), stats[:, 0, 3])


# ---------------------------------------------------------------------------
# Kernel probes (ISSUE 13 satellite: ONE memoized prober + a registry).
# ---------------------------------------------------------------------------
#
# Every compiled Mosaic kernel must be probed once per process before the
# "auto"/policy dispatch trusts it: Mosaic lowering gaps vary by TPU
# generation and jax version (e.g. the batched-dot attribute bug the
# distribution kernel works around on a v5-lite), and a failed compile
# must degrade to the XLA path, never kill the caller.  The four historic
# copy-paste ``pallas_*_available`` functions shared exactly this
# skeleton — backend gate, dependency probe, tiny compiled run, broad
# except — so the skeleton now lives ONCE in ``probe_kernel`` and each
# kernel registers only its tiny instance; a new kernel gets its probe
# for free by adding a builder.

def _probe_args(c: int | None = None):
    """Tiny shared probe calibration; ``c`` adds a lane axis."""
    n, a, d = 2, 8, 16
    a_grid = jnp.linspace(0.01, 5.0, a)
    m0 = jnp.tile(jnp.concatenate([jnp.asarray([1e-7]),
                                   a_grid + 1e-7])[None, :], (n, 1))
    scal = jnp.asarray([1.02, 1.0, 0.96, 2.0, 0.0])
    P = jnp.full((n, n), 0.5)
    lvl = jnp.asarray([0.8, 1.2])
    dg = jnp.linspace(0.0, 5.0, d)
    d0 = jnp.full((d, n), 1.0 / (d * n))
    out = dict(n=n, a=a, d=d, a_grid=a_grid, m0=m0, scal=scal, P=P,
               lvl=lvl, dg=dg, d0=d0)
    if c is not None:
        out.update(
            c=c,
            a_grid=jnp.tile(a_grid[None, :], (c, 1)),
            m0=jnp.tile(m0[None], (c, 1, 1)),
            scal=jnp.tile(scal[None, :], (c, 1)),
            P=jnp.tile(P[None], (c, 1, 1)),
            lvl=jnp.tile(lvl[None, :], (c, 1)),
            dg=jnp.tile(dg[None, :], (c, 1)),
            d0=jnp.tile(d0[None], (c, 1, 1)))
    return out


def _probe_dense():
    n, d = 2, 16
    S = jnp.stack([jnp.eye(d), jnp.eye(d)])
    P = jnp.full((n, n), 0.5)
    d0 = jnp.full((d, n), 1.0 / (d * n))
    dist, _, _ = stationary_dense_pallas(S, P, d0, tol=1e-6,
                                         max_iter=8, interpret=False)
    return bool(jnp.isfinite(dist).all())


def _probe_dense_grid():
    c, n, d = 2, 2, 16
    S = jnp.broadcast_to(jnp.eye(d), (c, n, d, d))
    P = jnp.full((c, n, n), 0.5)
    d0 = jnp.full((c, d, n), 1.0 / (d * n))
    dist, _, _ = stationary_dense_pallas_grid(S, P, d0, tol=1e-6,
                                              max_iter=8, interpret=False)
    return bool(jnp.isfinite(dist).all())


def _probe_egm():
    p = _probe_args()
    m, c, _, _ = egm_policy_pallas(p["m0"], p["m0"], p["a_grid"], p["lvl"],
                                   p["P"], p["scal"], tol=1e-4, max_iter=8,
                                   interpret=False)
    return bool(jnp.isfinite(m).all() & jnp.isfinite(c).all())


def _probe_egm_grid():
    p = _probe_args(c=2)
    m, cc, _, _ = egm_policy_pallas_grid(
        p["m0"], p["m0"], p["a_grid"], p["lvl"], p["P"], p["scal"],
        tol=1e-4, max_iter=8, interpret=False)
    return bool(jnp.isfinite(m).all() & jnp.isfinite(cc).all())


def _probe_fused():
    p = _probe_args()
    h = jnp.zeros_like(p["lvl"])
    m, c, dist, _, _, _, _ = fused_cell_pallas(
        p["m0"], p["m0"], p["a_grid"], p["dg"], p["lvl"], p["P"],
        p["scal"], h, p["d0"], tol=1e-4, max_iter=8, dist_tol=1e-5,
        dist_max_iter=8, interpret=False)
    return bool(jnp.isfinite(m).all() & jnp.isfinite(c).all()
                & jnp.isfinite(dist).all())


def _probe_fused_grid():
    p = _probe_args(c=2)
    h = jnp.zeros_like(p["lvl"])
    m, c, dist, _, _, _, _ = fused_cell_pallas_grid(
        p["m0"], p["m0"], p["a_grid"], p["dg"], p["lvl"], p["P"],
        p["scal"], h, p["d0"], tol=1e-4, max_iter=8, dist_tol=1e-5,
        dist_max_iter=8, interpret=False)
    return bool(jnp.isfinite(m).all() & jnp.isfinite(c).all()
                & jnp.isfinite(dist).all())


# name -> (tiny compiled run, prerequisite probe).  Grid kernels require
# their single-lane twin first: grid lowering has materially different
# compile requirements (dimension_semantics, raised vmem_limit_bytes),
# and a backend where the single-lane probe passes but the grid lowering
# fails must fall back instead of dying at sweep compile time.
_PROBES = {
    "dense": (_probe_dense, None),
    "dense_grid": (_probe_dense_grid, "dense"),
    "egm": (_probe_egm, None),
    "egm_grid": (_probe_egm_grid, "egm"),
    "fused": (_probe_fused, None),
    "fused_grid": (_probe_fused_grid, "fused"),
}


@functools.lru_cache(maxsize=None)
def probe_kernel(name: str) -> bool:
    """Whether the named compiled Mosaic kernel works on the ambient TPU
    backend — probed once per process by compiling and running the tiny
    registered instance.  False off-TPU, False when the prerequisite
    probe fails, False on ANY compile/runtime failure (the caller falls
    back to the XLA path); an unknown name raises (a typo must not
    silently read as "unavailable")."""
    try:
        builder, dep = _PROBES[name]
    except KeyError:
        raise ValueError(
            f"unknown kernel probe {name!r}; registered: "
            f"{sorted(_PROBES)}") from None
    if jax.default_backend() not in ("tpu", "axon"):
        return False
    if dep is not None and not probe_kernel(dep):
        return False
    try:
        return bool(builder())
    except Exception:   # noqa: BLE001 — any compile/runtime failure means
        # the kernel is unusable here; the caller falls back to XLA
        return False


# The historic probe spellings, kept for callers/tests; each is now a
# thin alias of the registry prober.
def pallas_tpu_available() -> bool:
    return probe_kernel("dense")


def pallas_grid_tpu_available() -> bool:
    return probe_kernel("dense_grid")


def pallas_egm_tpu_available() -> bool:
    return probe_kernel("egm")


def pallas_egm_grid_tpu_available() -> bool:
    return probe_kernel("egm_grid")
