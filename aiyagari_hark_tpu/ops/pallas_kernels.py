"""Pallas TPU kernels for the framework's hottest loop.

The single hottest computation (bench phase breakdown, ``bench.py``) is the
stationary-wealth fixed point: thousands of sequential push-forward steps of
a [D, N] histogram.  Under a plain XLA ``while_loop`` every iteration
round-trips the distribution (and, in the dense formulation, re-reads the
[N, D, D] lottery operator) through HBM.  This kernel runs the ENTIRE fixed
point inside one ``pallas_call``: the operator ``S`` (~7 MB at the benchmark
config D=500, N=7, f32 — comfortably inside the ~16 MB VMEM budget), the
labor-mixing matrix ``P``, and the iterate all stay VMEM-resident, so each
step is two on-chip matmuls (batched matvec on the MXU + the [D,N]x[N,N]
mix) with zero HBM traffic.

Correctness shares the exact same iteration code as the XLA path
(``models.household.accelerated_distribution_fixed_point`` — including the
Aitken extrapolation and its certification semantics), so the kernel cannot
drift from the reference implementation; only the memory placement differs.

CPU fallback / tests run the same kernel with ``interpret=True`` (the
Pallas interpreter), asserting bit-level agreement with the XLA dense path.
Reference for the computation being accelerated: the reference's per-period
``np.searchsorted`` + Python-loop simulation (``Aiyagari_Support.py``
get_shocks/get_states hot loop #2, SURVEY.md §3.3), replaced here by Young's
deterministic method in operator form.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _fixed_point_kernel(S_ref, P_ref, d0_ref, out_ref, stats_ref, *,
                        tol, max_iter, accel_every):
    """Whole stationary fixed point on VMEM-resident operands."""
    from ..models.household import accelerated_distribution_fixed_point

    S = S_ref[:]          # [N, D, D] lottery operator
    P = P_ref[:]          # [N, N] labor mixing
    d0 = d0_ref[:]        # [D, N] initial distribution
    n_states = S.shape[0]

    def push(dist):
        # batched matvec moved[:, i] = S[i] @ dist[:, i], written as a
        # statically-unrolled list of plain 2D matmuls: Mosaic rejects the
        # batched-dot dimension numbers the einsum formulation lowers to
        # ("#tpu.dot_dimension_numbers ... expected integer value" on a
        # v5-lite), and N is a small static constant anyway
        cols = [jnp.matmul(S[i], dist[:, i:i + 1],
                           precision=jax.lax.Precision.HIGHEST,
                           preferred_element_type=dist.dtype)
                for i in range(n_states)]
        moved = jnp.concatenate(cols, axis=1)
        return jnp.matmul(moved, P, precision=jax.lax.Precision.HIGHEST,
                          preferred_element_type=dist.dtype)

    # status is dropped at the kernel boundary: the (iters, diff) stats
    # pair reconstructs it exactly (see ``stationary_wealth``)
    dist, it, diff, _ = accelerated_distribution_fixed_point(
        push, d0, tol, max_iter, accel_every)
    out_ref[:] = dist
    # full-row store: Mosaic rejects scalar stores into a VMEM ref
    stats_ref[:] = jnp.stack([it.astype(d0.dtype),
                              diff.astype(d0.dtype)]).reshape(1, 2)


def stationary_dense_pallas(S: jnp.ndarray, P: jnp.ndarray,
                            dist0: jnp.ndarray, tol: float,
                            max_iter: int = 20000, accel_every: int = 64,
                            interpret: bool | None = None):
    """Run the stationary-distribution fixed point as ONE Pallas kernel.

    Args: ``S`` [N, D, D] from ``models.household.dense_wealth_operator``,
    ``P`` [N, N] labor transition, ``dist0`` [D, N].  Returns
    (dist [D, N], n_iter, final_diff) — same contract as
    ``accelerated_distribution_fixed_point``.

    ``interpret``: None = interpret everywhere except a real TPU backend
    (the interpreter is the correctness path on CPU/GPU; the compiled
    Mosaic kernel is the TPU path).
    """
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")

    n, d, _ = S.shape
    kernel = functools.partial(_fixed_point_kernel, tol=tol,
                               max_iter=max_iter, accel_every=accel_every)
    call = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((d, n), dist0.dtype),
                   jax.ShapeDtypeStruct((1, 2), dist0.dtype)),
        interpret=interpret,
    )
    dist, stats = call(S, P, dist0)
    return dist, stats[0, 0].astype(jnp.int32), stats[0, 1]


def _fixed_point_kernel_lane(S_ref, P_ref, d0_ref, out_ref, stats_ref, *,
                             tol, max_iter, accel_every):
    """One sweep lane's whole fixed point; refs carry a leading lane axis of
    block size 1 (the pallas grid maps program instance -> lane)."""
    from ..models.household import accelerated_distribution_fixed_point

    S = S_ref[0]          # [N, D, D]
    P = P_ref[0]          # [N, N]
    d0 = d0_ref[0]        # [D, N]
    n_states = S.shape[0]

    def push(dist):
        cols = [jnp.matmul(S[i], dist[:, i:i + 1],
                           precision=jax.lax.Precision.HIGHEST,
                           preferred_element_type=dist.dtype)
                for i in range(n_states)]
        moved = jnp.concatenate(cols, axis=1)
        return jnp.matmul(moved, P, precision=jax.lax.Precision.HIGHEST,
                          preferred_element_type=dist.dtype)

    dist, it, diff, _ = accelerated_distribution_fixed_point(
        push, d0, tol, max_iter, accel_every)
    out_ref[0] = dist
    stats_ref[0] = jnp.stack([it.astype(d0.dtype),
                              diff.astype(d0.dtype)]).reshape(1, 2)


def stationary_dense_pallas_grid(S: jnp.ndarray, P: jnp.ndarray,
                                 dist0: jnp.ndarray, tol: float,
                                 max_iter: int = 20000,
                                 accel_every: int = 64,
                                 interpret: bool | None = None):
    """Batched fixed points as a Pallas GRID: one program instance per sweep
    lane, each lane's operator VMEM-resident for its own iterations only.

    This is the per-lane answer to the vmap-of-while straggler problem
    (VERDICT r2 weak-item 3): under ``vmap(dense)`` every push-forward step
    processes ALL lanes until the slowest converges (measured total-work
    skew 2.55 on the Table II sweep), and under ``vmap`` of the single-lane
    Pallas kernel all lanes land in ONE kernel whose operators exceed
    scoped VMEM.  Gridding runs lanes sequentially on the TensorCore, each
    exiting at its OWN convergence — total steps sum(iters) instead of
    lanes x max(iters) — with only lane c's ~7 MB operator resident at a
    time.

    Args: ``S`` [C, N, D, D], ``P`` [C, N, N], ``dist0`` [C, D, N].
    Returns (dist [C, D, N], iters [C] int32, diffs [C]).
    """
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")

    c, n, d, _ = S.shape
    kernel = functools.partial(_fixed_point_kernel_lane, tol=tol,
                               max_iter=max_iter, accel_every=accel_every)
    kwargs = {}
    if not interpret:
        from jax.experimental.pallas import tpu as pltpu

        # The lane pipeline double-buffers the next lane's ~7 MB operator
        # during compute, which blows the default 16 MB scoped-VMEM budget
        # (measured 21.6 MB at D=500, N=7, f32); raise the scoped limit —
        # physical VMEM is far larger — rather than shrink blocks.
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=2 * (4 * n * d * d) + 32 * 1024 * 1024)
    call = pl.pallas_call(
        kernel,
        grid=(c,),
        in_specs=[
            pl.BlockSpec((1, n, d, d), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, n, n), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, d, n), lambda i: (i, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, d, n), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, 2), lambda i: (i, 0, 0)),
        ),
        out_shape=(jax.ShapeDtypeStruct((c, d, n), dist0.dtype),
                   jax.ShapeDtypeStruct((c, 1, 2), dist0.dtype)),
        interpret=interpret,
        **kwargs,
    )
    dist, stats = call(S, P, dist0)
    return dist, stats[:, 0, 0].astype(jnp.int32), stats[:, 0, 1]


def _egm_scalars(s):
    """Unpack the packed per-lane scalar row (R, W, disc_fac, crra,
    borrow_limit) — one [1, 5] block instead of five scalar refs, because
    Mosaic wants >= 2-D VMEM operands."""
    return s[0], s[1], s[2], s[3], s[4]


def _egm_fixed_point_kernel(m0_ref, c0_ref, a_ref, lvl_ref, P_ref, scal_ref,
                            m_out, c_out, stats_ref, *, tol, max_iter,
                            accel_every):
    """Whole EGM policy fixed point on VMEM-resident operands.

    Exactly the distribution kernel's design: the iteration code is the
    SAME ``accelerated_policy_fixed_point`` + ``egm_step`` the XLA path
    runs (Anderson acceleration, certification semantics included), so the
    kernel cannot drift from the reference — only memory placement and the
    per-lane exit differ.  The status is dropped at the kernel boundary
    and reconstructed from (iters, diff) outside (this loop has no stall
    exit, so the classification is exact)."""
    from ..models.household import (
        HouseholdPolicy,
        SimpleModel,
        accelerated_policy_fixed_point,
        egm_step,
    )

    a = a_ref[0]          # [A] end-of-period asset grid
    lvl = lvl_ref[0]      # [N] labor levels
    P = P_ref[:]          # [N, N] labor transition
    R, W, disc_fac, crra, blim = _egm_scalars(scal_ref[0])
    # egm_step only touches a_grid/labor_levels/transition/borrow_limit;
    # the remaining SimpleModel fields are structural placeholders so the
    # kernel can reuse the exact production step function
    model = SimpleModel(a_grid=a, labor_levels=lvl, transition=P,
                        labor_stationary=lvl, dist_grid=a,
                        borrow_limit=blim)
    p0 = HouseholdPolicy(m_knots=m0_ref[:], c_knots=c0_ref[:])
    pol, it, diff, _ = accelerated_policy_fixed_point(
        lambda p: egm_step(p, R, W, model, disc_fac, crra),
        p0, tol, max_iter, accel_every)
    m_out[:] = pol.m_knots
    c_out[:] = pol.c_knots
    stats_ref[:] = jnp.stack([it.astype(a.dtype),
                              diff.astype(a.dtype)]).reshape(1, 2)


def egm_policy_pallas(m0: jnp.ndarray, c0: jnp.ndarray, a_grid: jnp.ndarray,
                      levels: jnp.ndarray, P: jnp.ndarray,
                      scalars: jnp.ndarray, tol: float, max_iter: int = 3000,
                      accel_every: int = 32, interpret: bool | None = None):
    """One cell's EGM policy fixed point as ONE Pallas kernel.

    Args: ``m0``/``c0`` [N, A+1] initial policy knots, ``a_grid`` [A],
    ``levels`` [N], ``P`` [N, N], ``scalars`` [5] packed
    (R, W, disc_fac, crra, borrow_limit).  Returns
    (m_knots, c_knots, n_iter, final_diff) — the
    ``accelerated_policy_fixed_point`` contract minus the status code,
    which ``solve_household`` reconstructs from (iters, diff).

    Grid-policy note (DESIGN §5b): this kernel runs the fixed REFERENCE
    knot layout ([N, A+1]: constraint + A endogenous) — the compact
    policies' analytic tail knot and coarse-to-fine ladder live on the
    XLA path only, so ``solve_household`` demotes ``method`` to "xla"
    under a non-reference ``grid`` exactly as it does under
    non-reference precision."""
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")

    n, a1 = m0.shape
    kernel = functools.partial(_egm_fixed_point_kernel, tol=tol,
                               max_iter=max_iter, accel_every=accel_every)
    call = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((n, a1), m0.dtype),
                   jax.ShapeDtypeStruct((n, a1), m0.dtype),
                   jax.ShapeDtypeStruct((1, 2), m0.dtype)),
        interpret=interpret,
    )
    m, c, stats = call(m0, c0, a_grid.reshape(1, -1), levels.reshape(1, -1),
                       P, scalars.reshape(1, -1))
    return m, c, stats[0, 0].astype(jnp.int32), stats[0, 1]


def _egm_fixed_point_kernel_lane(m0_ref, c0_ref, a_ref, lvl_ref, P_ref,
                                 scal_ref, m_out, c_out, stats_ref, *,
                                 tol, max_iter, accel_every):
    """One sweep lane's whole EGM fixed point; refs carry a leading lane
    axis of block size 1 (pallas grid maps program instance -> lane)."""
    from ..models.household import (
        HouseholdPolicy,
        SimpleModel,
        accelerated_policy_fixed_point,
        egm_step,
    )

    a = a_ref[0, 0]
    lvl = lvl_ref[0, 0]
    P = P_ref[0]
    R, W, disc_fac, crra, blim = _egm_scalars(scal_ref[0, 0])
    model = SimpleModel(a_grid=a, labor_levels=lvl, transition=P,
                        labor_stationary=lvl, dist_grid=a,
                        borrow_limit=blim)
    p0 = HouseholdPolicy(m_knots=m0_ref[0], c_knots=c0_ref[0])
    pol, it, diff, _ = accelerated_policy_fixed_point(
        lambda p: egm_step(p, R, W, model, disc_fac, crra),
        p0, tol, max_iter, accel_every)
    m_out[0] = pol.m_knots
    c_out[0] = pol.c_knots
    stats_ref[0] = jnp.stack([it.astype(a.dtype),
                              diff.astype(a.dtype)]).reshape(1, 2)


def egm_policy_pallas_grid(m0: jnp.ndarray, c0: jnp.ndarray,
                           a_grid: jnp.ndarray, levels: jnp.ndarray,
                           P: jnp.ndarray, scalars: jnp.ndarray, tol: float,
                           max_iter: int = 3000, accel_every: int = 32,
                           interpret: bool | None = None):
    """Batched EGM fixed points as a Pallas GRID: one program instance per
    sweep lane, each exiting at its OWN convergence.

    The per-lane answer to vmap-of-while lock-step for the POLICY loop
    (ISSUE 2 tentpole): under ``vmap(solve_household)`` every EGM backward
    step processes all lanes until the slowest cell's policy converges —
    a converged cell keeps burning MXU cycles on masked matmuls.  Gridding
    runs lanes sequentially on the TensorCore, total steps sum(iters)
    instead of lanes x max(iters), the same economics as the distribution
    lane grid (``stationary_dense_pallas_grid``).

    Args: ``m0``/``c0`` [C, N, A+1], ``a_grid`` [C, A], ``levels`` [C, N],
    ``P`` [C, N, N], ``scalars`` [C, 5].  Returns
    (m_knots [C, N, A+1], c_knots [C, N, A+1], iters [C] int32, diffs [C]).
    """
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")

    c, n, a1 = m0.shape
    a = a_grid.shape[1]
    kernel = functools.partial(_egm_fixed_point_kernel_lane, tol=tol,
                               max_iter=max_iter, accel_every=accel_every)
    kwargs = {}
    if not interpret:
        from jax.experimental.pallas import tpu as pltpu

        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("arbitrary",))
    call = pl.pallas_call(
        kernel,
        grid=(c,),
        in_specs=[
            pl.BlockSpec((1, n, a1), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, n, a1), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, a), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, n), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, n, n), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, 5), lambda i: (i, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, n, a1), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, n, a1), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, 2), lambda i: (i, 0, 0)),
        ),
        out_shape=(jax.ShapeDtypeStruct((c, n, a1), m0.dtype),
                   jax.ShapeDtypeStruct((c, n, a1), m0.dtype),
                   jax.ShapeDtypeStruct((c, 1, 2), m0.dtype)),
        interpret=interpret,
        **kwargs,
    )
    m, cc, stats = call(m0, c0, a_grid.reshape(c, 1, a),
                        levels.reshape(c, 1, n), P,
                        scalars.reshape(c, 1, 5))
    return m, cc, stats[:, 0, 0].astype(jnp.int32), stats[:, 0, 1]


@functools.lru_cache(maxsize=1)
def pallas_egm_tpu_available() -> bool:
    """Whether the compiled Mosaic EGM kernel works on the ambient TPU —
    probed once per process (same policy as ``pallas_tpu_available``).
    The EGM step leans on searchsorted-style gathers the Mosaic lowering
    may not support on every generation; a failed probe degrades the
    policy loop to the XLA lock-step path, never kills the caller."""
    if jax.default_backend() not in ("tpu", "axon"):
        return False
    try:
        n, a = 2, 8
        a_grid = jnp.linspace(0.01, 5.0, a)
        m0 = jnp.tile(jnp.concatenate([jnp.asarray([1e-7]),
                                       a_grid + 1e-7])[None, :], (n, 1))
        scal = jnp.asarray([1.02, 1.0, 0.96, 2.0, 0.0])
        P = jnp.full((n, n), 0.5)
        lvl = jnp.asarray([0.8, 1.2])
        m, c, _, _ = egm_policy_pallas(m0, m0, a_grid, lvl, P, scal,
                                       tol=1e-4, max_iter=8,
                                       interpret=False)
        return bool(jnp.isfinite(m).all() & jnp.isfinite(c).all())
    except Exception:   # noqa: BLE001 — any compile/runtime failure means
        # the kernel is unusable here; the caller falls back to XLA
        return False


@functools.lru_cache(maxsize=1)
def pallas_egm_grid_tpu_available() -> bool:
    """Same probe for the lane-GRID EGM kernel the batched sweep runs
    (separate probe for the same reason as ``pallas_grid_tpu_available``:
    grid lowering can fail where the single-lane kernel compiles)."""
    if not pallas_egm_tpu_available():
        return False
    try:
        c, n, a = 2, 2, 8
        a_grid = jnp.linspace(0.01, 5.0, a)
        m0 = jnp.tile(jnp.concatenate([jnp.asarray([1e-7]),
                                       a_grid + 1e-7])[None, None, :],
                      (c, n, 1))
        scal = jnp.tile(jnp.asarray([1.02, 1.0, 0.96, 2.0, 0.0])[None, :],
                        (c, 1))
        P = jnp.full((c, n, n), 0.5)
        lvl = jnp.tile(jnp.asarray([0.8, 1.2])[None, :], (c, 1))
        m, cc, _, _ = egm_policy_pallas_grid(
            m0, m0, jnp.tile(a_grid[None, :], (c, 1)), lvl, P, scal,
            tol=1e-4, max_iter=8, interpret=False)
        return bool(jnp.isfinite(m).all() & jnp.isfinite(cc).all())
    except Exception:   # noqa: BLE001 — fall back to the XLA policy loop
        return False


@functools.lru_cache(maxsize=1)
def pallas_tpu_available() -> bool:
    """Whether the compiled Mosaic kernel actually works on the ambient TPU
    backend — probed once per process by compiling and running a tiny
    instance.  Guards the "auto" method choice: a Mosaic lowering gap (e.g.
    the batched-dot attribute bug this kernel had to work around on a
    v5-lite) must degrade to the XLA dense path, not kill the caller."""
    if jax.default_backend() not in ("tpu", "axon"):
        return False
    try:
        n, d = 2, 16
        S = jnp.stack([jnp.eye(d), jnp.eye(d)])
        P = jnp.full((n, n), 0.5)
        d0 = jnp.full((d, n), 1.0 / (d * n))
        dist, _, _ = stationary_dense_pallas(S, P, d0, tol=1e-6,
                                             max_iter=8, interpret=False)
        return bool(jnp.isfinite(dist).all())
    except Exception:   # noqa: BLE001 — any compile/runtime failure means
        # the kernel is unusable here; the caller falls back to XLA
        return False


@functools.lru_cache(maxsize=1)
def pallas_grid_tpu_available() -> bool:
    """Same probe for the LANE-GRID kernel, which the batched (sweep) path
    actually runs.  Separate from ``pallas_tpu_available`` because the grid
    kernel has materially different compile requirements (grid
    dimension_semantics, a raised ``vmem_limit_bytes`` for the
    double-buffered lane operators) — a backend where the single-lane probe
    passes but the grid lowering fails must fall back to dense instead of
    dying at sweep compile time."""
    if not pallas_tpu_available():
        return False
    try:
        c, n, d = 2, 2, 16
        S = jnp.broadcast_to(jnp.eye(d), (c, n, d, d))
        P = jnp.full((c, n, n), 0.5)
        d0 = jnp.full((c, d, n), 1.0 / (d * n))
        dist, _, _ = stationary_dense_pallas_grid(S, P, d0, tol=1e-6,
                                                  max_iter=8,
                                                  interpret=False)
        return bool(jnp.isfinite(dist).all())
    except Exception:   # noqa: BLE001 — fall back to dense
        return False
