"""Numerics core: grids, Markov machinery, CRRA utility, batched
interpolation, and masked OLS — the L1-equivalent layer (SURVEY.md §1)."""

from .grids import (
    GRID_POLICIES,
    GridSpec,
    build_asset_grids,
    compact_knee,
    grid_point_counts,
    make_asset_grid,
    make_grid_exp_mult,
    resolve_grid,
)
from .interp import (
    append_tail_knot,
    eval_policy_agents,
    interp1d,
    interp1d_rowwise,
    interp_on_interp,
    locate_in_grid,
)
from .markov import (
    TauchenResult,
    aggregate_markov_matrix,
    employment_markov_matrix,
    full_idiosyncratic_matrix,
    normalized_labor_states,
    stationary_distribution,
    tauchen_ar1,
    tauchen_labor_process,
)
from .regression import OLSResult, masked_ols
from .utility import (
    asymptotic_mpc,
    crra_utility,
    inverse_marginal_utility,
    marginal_utility,
)

__all__ = [
    "make_asset_grid", "make_grid_exp_mult",
    "GRID_POLICIES", "GridSpec", "resolve_grid", "build_asset_grids",
    "compact_knee", "grid_point_counts",
    "append_tail_knot", "asymptotic_mpc",
    "eval_policy_agents", "interp1d", "interp1d_rowwise", "interp_on_interp",
    "locate_in_grid",
    "TauchenResult", "aggregate_markov_matrix", "employment_markov_matrix",
    "full_idiosyncratic_matrix", "normalized_labor_states",
    "stationary_distribution", "tauchen_ar1", "tauchen_labor_process",
    "OLSResult", "masked_ols",
    "crra_utility", "inverse_marginal_utility", "marginal_utility",
]
