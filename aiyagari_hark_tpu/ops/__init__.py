"""Numerics core: grids, Markov machinery, CRRA utility, batched
interpolation, and masked OLS — the L1-equivalent layer (SURVEY.md §1)."""

from .grids import make_asset_grid, make_grid_exp_mult
from .interp import (
    eval_policy_agents,
    interp1d,
    interp1d_rowwise,
    interp_on_interp,
    locate_in_grid,
)
from .markov import (
    TauchenResult,
    aggregate_markov_matrix,
    employment_markov_matrix,
    full_idiosyncratic_matrix,
    normalized_labor_states,
    stationary_distribution,
    tauchen_ar1,
    tauchen_labor_process,
)
from .regression import OLSResult, masked_ols
from .utility import crra_utility, inverse_marginal_utility, marginal_utility

__all__ = [
    "make_asset_grid", "make_grid_exp_mult",
    "eval_policy_agents", "interp1d", "interp1d_rowwise", "interp_on_interp",
    "locate_in_grid",
    "TauchenResult", "aggregate_markov_matrix", "employment_markov_matrix",
    "full_idiosyncratic_matrix", "normalized_labor_states",
    "stationary_distribution", "tauchen_ar1", "tauchen_labor_process",
    "OLSResult", "masked_ols",
    "crra_utility", "inverse_marginal_utility", "marginal_utility",
]
