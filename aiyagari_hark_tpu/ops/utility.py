"""CRRA utility family.

The reference reaches these through HARK's ``MargValueFuncCRRA`` (u' composed
with the consumption function, ``Aiyagari_Support.py:1514-1515``) and the FOC
inversion ``c = EndOfPrdvP ** (-1/CRRA)`` (``Aiyagari_Support.py:1490``).
Closed forms, elementwise, fuse into surrounding XLA computations.
"""

from __future__ import annotations

import jax.numpy as jnp


def crra_utility(c: jnp.ndarray, crra: float) -> jnp.ndarray:
    """u(c); log utility at crra == 1 (static Python branch — crra is a
    compile-time constant, so no lax.cond is needed)."""
    if crra == 1.0:
        return jnp.log(c)
    return c ** (1.0 - crra) / (1.0 - crra)


def marginal_utility(c: jnp.ndarray, crra: float) -> jnp.ndarray:
    """u'(c) = c^(-crra)."""
    return c ** (-crra)


def inverse_marginal_utility(vp: jnp.ndarray, crra: float) -> jnp.ndarray:
    """(u')^{-1}(x) = x^(-1/crra) — the EGM first-order-condition inversion."""
    return vp ** (-1.0 / crra)
