"""CRRA utility family.

The reference reaches these through HARK's ``MargValueFuncCRRA`` (u' composed
with the consumption function, ``Aiyagari_Support.py:1514-1515``) and the FOC
inversion ``c = EndOfPrdvP ** (-1/CRRA)`` (``Aiyagari_Support.py:1490``).
Closed forms, elementwise, fuse into surrounding XLA computations.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def crra_utility(c: jnp.ndarray, crra) -> jnp.ndarray:
    """u(c); log utility at crra == 1.

    ``crra`` may be a traced scalar (it is a vmapped sweep axis): the
    branch must then be data-dependent, so both limbs are evaluated and
    selected with ``jnp.where``.  The power limb is guarded against the
    crra == 1 pole (division by 1-crra) with the usual double-where.
    A concrete Python float keeps the old static branch (one limb compiled).
    """
    if not isinstance(crra, jax.core.Tracer):
        crra = float(crra)
        if crra == 1.0:
            return jnp.log(c)
        return c ** (1.0 - crra) / (1.0 - crra)
    is_log = crra == 1.0
    safe = jnp.where(is_log, 2.0, crra)          # keep 1-crra away from 0
    power = c ** (1.0 - safe) / (1.0 - safe)
    return jnp.where(is_log, jnp.log(c), power)


def marginal_utility(c: jnp.ndarray, crra: float) -> jnp.ndarray:
    """u'(c) = c^(-crra)."""
    return c ** (-crra)


def inverse_marginal_utility(vp: jnp.ndarray, crra: float) -> jnp.ndarray:
    """(u')^{-1}(x) = x^(-1/crra) — the EGM first-order-condition inversion."""
    return vp ** (-1.0 / crra)


def asymptotic_mpc(R, disc_fac, crra):
    """The asymptotic marginal propensity to consume — the grid-compaction
    tail slope (ISSUE 12, DESIGN §5b).

    Ma-Stachurski-Toda (arXiv:2002.09108) show the income-fluctuation
    consumption function is asymptotically linear, ``c(m)/m -> kappa``,
    and with CERTAIN returns the limit slope is the perfect-foresight
    MPC::

        kappa = 1 - (beta R)^(1/crra) / R

    On the economic bisection bracket ``r < (1-beta)/beta`` we have
    ``beta R < 1`` so ``0 < kappa < 1`` — the analytic tail's slope is a
    valid consumption slope and the implied savings slope ``R (1-kappa)
    = (beta R)^(1/crra)`` lies in (0, 1): savings grow sublinearly, the
    ordering the committed ``afunc_slope`` artifact pins for the
    aggregate law (``tests/test_artifacts.py``: slopes in (0, 1.2)).
    All arguments may be traced (sweep axes)."""
    return 1.0 - (disc_fac * R) ** (1.0 / crra) / R


def inverse_utility(v: jnp.ndarray, crra) -> jnp.ndarray:
    """u^{-1}(v): the consumption level whose one-period utility is ``v`` —
    the "value-inverse" (HARK's vNvrs) transform that makes CRRA value
    functions near-linear in resources, so piecewise-linear knots represent
    them accurately (``models.value``).  Same traced-``crra`` handling as
    ``crra_utility``."""
    if not isinstance(crra, jax.core.Tracer):
        crra = float(crra)
        if crra == 1.0:
            return jnp.exp(v)
        return ((1.0 - crra) * v) ** (1.0 / (1.0 - crra))
    is_log = crra == 1.0
    safe = jnp.where(is_log, 2.0, crra)
    power = ((1.0 - safe) * v) ** (1.0 / (1.0 - safe))
    return jnp.where(is_log, jnp.exp(v), power)
