"""Closed-form OLS for the Krusell-Smith aggregate-law regression.

The reference calls ``scipy.stats.linregress(logM[these], logA[these])`` per
aggregate Markov state (``Aiyagari_Support.py:1931-1935``).  Boolean fancy
indexing has no jit-able analog, so the TPU-native version is *masked* OLS:
a weighted closed form where the mask is the weight vector.  Identical
estimates, fixed shapes, fuses into the simulation postprocessing.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp


class OLSResult(NamedTuple):
    slope: jnp.ndarray
    intercept: jnp.ndarray
    r_squared: jnp.ndarray


def masked_ols(x: jnp.ndarray, y: jnp.ndarray, mask: jnp.ndarray) -> OLSResult:
    """Simple OLS of y on x using only entries where ``mask`` is true.

    All arrays are [T]; the mask enters as 0/1 weights so shapes stay static
    under jit.  Matches ``scipy.stats.linregress`` estimates on the selected
    subsample.
    """
    w = mask.astype(x.dtype)
    n = jnp.sum(w)
    # Empty mask -> NaN slope/intercept (the caller must notice, not silently
    # proceed); degenerate variance -> r_squared 0 (scipy's convention).
    n_safe = jnp.maximum(n, 1.0)
    xm = jnp.sum(w * x) / n_safe
    ym = jnp.sum(w * y) / n_safe
    sxx = jnp.sum(w * (x - xm) ** 2)
    sxy = jnp.sum(w * (x - xm) * (y - ym))
    syy = jnp.sum(w * (y - ym) ** 2)
    nan = jnp.full_like(xm, jnp.nan)
    slope = jnp.where(n > 0, sxy / sxx, nan)
    intercept = jnp.where(n > 0, ym - slope * xm, nan)
    r_squared = jnp.where((syy > 0) & (sxx > 0) & (n > 0),
                          sxy ** 2 / (sxx * syy), jnp.zeros_like(syy))
    return OLSResult(slope=slope, intercept=intercept, r_squared=r_squared)
