"""Epstein-Zin recursive preferences: risk aversion decoupled from the
elasticity of intertemporal substitution.

The reference (and the CRRA core here) ties the two together: one
parameter controls both how much households dislike consumption risk and
how willing they are to shift consumption over time.  Epstein-Zin-Weil
utility separates them,

    V_t = [ (1-beta) c_t^(1-rho) + beta mu_t^(1-rho) ]^(1/(1-rho)),
    mu_t = ( E_t[ V_{t+1}^(1-gamma) ] )^(1/(1-gamma)),

with ``rho = 1/EIS`` and ``gamma`` the relative risk aversion; at
``gamma = rho`` it collapses to CRRA (the test oracle).  The Euler
equation gains the risk-adjustment weights (V'/mu)^(rho-gamma):

    c^(-rho) = beta R E[ (V'/mu)^(rho-gamma) c'^(-rho) ].

TPU shape: the EGM backward step carries the VALUE function alongside
the policy (both as per-state knots on the same endogenous grid — V is
homogeneous of degree one in the consumption stream, so it lives in
consumption units and interpolates as well as c does), and the
expectation/certainty-equivalent reductions are the same batched
matmul/power pattern as the CRRA step.  Everything downstream of the
policy (stationary distribution, bisection equilibrium) is REUSED
unchanged: an ``EZPolicy``'s (m, c) knots are a valid
``HouseholdPolicy``.

Domain: rho != 1 and gamma != 1 (the log limits need the exponential
aggregator; not implemented).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from .equilibrium import _bisect, _bisection_setup
from .firm import k_to_l_from_r, output, wage_rate
from .household import (
    CONSTRAINT_EPS,
    HouseholdPolicy,
    SimpleModel,
    accelerated_policy_fixed_point,
    aggregate_capital,
    aggregate_labor,
    initial_policy,
    stationary_wealth,
)
from ..ops.interp import interp1d_rowwise


class EZPolicy(NamedTuple):
    """Consumption policy and value function on shared endogenous knots,
    each [N, A+1]; ``(m_knots, c_knots)`` is a valid ``HouseholdPolicy``."""

    m_knots: jnp.ndarray
    c_knots: jnp.ndarray
    v_knots: jnp.ndarray     # V in consumption units


def as_household_policy(policy: EZPolicy) -> HouseholdPolicy:
    return HouseholdPolicy(m_knots=policy.m_knots, c_knots=policy.c_knots)


def initial_ez_policy(model: SimpleModel) -> EZPolicy:
    """Terminal guess: the CRRA terminal policy (consume everything)
    with V = c — one period to live."""
    p = initial_policy(model)
    return EZPolicy(m_knots=p.m_knots, c_knots=p.c_knots,
                    v_knots=p.c_knots)


def egm_step_ez(policy: EZPolicy, R, W, model: SimpleModel, disc_fac,
                rho, gamma) -> EZPolicy:
    """One EZ-EGM backward step: interpolate (c', V') at next-period
    resources, form the certainty equivalent mu and the risk-adjustment
    weights, invert the risk-adjusted Euler equation, and update the
    value on the new endogenous grid."""
    a = model.a_grid                                   # [A]
    m_next = R * a[:, None] + W * model.labor_levels[None, :]   # [A, N']
    c_next = interp1d_rowwise(m_next.T, policy.m_knots, policy.c_knots).T
    v_next = interp1d_rowwise(m_next.T, policy.m_knots, policy.v_knots).T
    v_next = jnp.maximum(v_next, jnp.finfo(v_next.dtype).tiny)
    P = model.transition                               # [N, N']
    hp = jax.lax.Precision.HIGHEST
    # certainty equivalent mu(a, s) = (E[V'^(1-gamma)])^(1/(1-gamma))
    mu = jnp.matmul(v_next ** (1.0 - gamma), P.T,
                    precision=hp) ** (1.0 / (1.0 - gamma))   # [A, N]
    # risk-adjusted marginal continuation: E[(V')^(rho-gamma) c'^(-rho)],
    # the mu^(rho-gamma) factor pulled out of the expectation
    emv = jnp.matmul(v_next ** (rho - gamma) * c_next ** (-rho), P.T,
                     precision=hp)
    end_vp = disc_fac * R * mu ** (gamma - rho) * emv
    c_now = end_vp ** (-1.0 / rho)
    m_now = a[:, None] + c_now
    v_now = ((1.0 - disc_fac) * c_now ** (1.0 - rho)
             + disc_fac * mu ** (1.0 - rho)) ** (1.0 / (1.0 - rho))
    # constraint knot: at m = b + eps consumption is eps and savings sit
    # at the limit, so the continuation CE is the first-gridpoint mu row.
    # mu[:1] is mu at a_grid[0] = borrow_limit + a_min, not exactly at
    # savings = borrow_limit — an O(a_min) approximation (fine at the
    # default a_min=1e-3) that the CRRA path doesn't need (its constraint
    # knot reads no continuation value); not an exact identity.
    eps = jnp.full((1, c_now.shape[1]), CONSTRAINT_EPS, dtype=c_now.dtype)
    b = jnp.asarray(model.borrow_limit, dtype=c_now.dtype)
    v_con = ((1.0 - disc_fac) * eps ** (1.0 - rho)
             + disc_fac * mu[:1] ** (1.0 - rho)) ** (1.0 / (1.0 - rho))
    return EZPolicy(
        m_knots=jnp.concatenate([b + eps, m_now], axis=0).T,
        c_knots=jnp.concatenate([eps, c_now], axis=0).T,
        v_knots=jnp.concatenate([v_con, v_now], axis=0).T)


def solve_ez_household(R, W, model: SimpleModel, disc_fac, rho, gamma,
                       tol: float = 1e-6, max_iter: int = 5000,
                       init_policy: EZPolicy | None = None,
                       accel_every: int = 32):
    """Infinite-horizon fixed point of the EZ-EGM step via the shared
    certified-Anderson iterator.  The convergence certificate covers the
    VALUE knots too — V's scale mode is invisible to the Euler step
    (homogeneity cancels it in the risk weights), so it converges at the
    plain beta rate regardless of c, and a c-only certificate would hand
    ``aggregate_ez_welfare`` an under-converged V (measured ~40x).
    ``accel_every=0`` disables acceleration.  Returns
    (EZPolicy, n_iter, final_diff, status)."""
    p0 = initial_ez_policy(model) if init_policy is None else init_policy
    return accelerated_policy_fixed_point(
        lambda p: egm_step_ez(p, R, W, model, disc_fac, rho, gamma),
        p0, tol, max_iter, accel_every=accel_every)


def aggregate_ez_welfare(policy: EZPolicy, dist, R, W,
                         model: SimpleModel):
    """Population welfare E[V(m, s)] under a wealth histogram [D, N]:
    each cell enters the period with m = R x + W l_s.  Because V is
    already in consumption units (degree-one homogeneous), the result
    reads as a permanent-consumption level, and the consumption
    equivalent between two allocations under the SAME (rho, gamma) is
    simply ``welfare_alt / welfare_base - 1`` — no curvature transform
    (contrast ``value.consumption_equivalent`` for CRRA levels)."""
    m = R * model.dist_grid[:, None] + W * model.labor_levels[None, :]
    v = interp1d_rowwise(m.T, policy.m_knots, policy.v_knots)    # [N, D]
    return jnp.sum(dist * v.T)


class EZEquilibrium(NamedTuple):
    r_star: jnp.ndarray
    wage: jnp.ndarray
    capital: jnp.ndarray
    labor: jnp.ndarray
    saving_rate: jnp.ndarray
    excess: jnp.ndarray
    policy: EZPolicy
    distribution: jnp.ndarray
    bisect_iters: jnp.ndarray
    status: jnp.ndarray = 0    # solver_health code of the bisection exit


def solve_ez_equilibrium(model: SimpleModel, disc_fac, rho, gamma,
                         cap_share, depr_fac,
                         r_tol: float | None = None, max_bisect: int = 60,
                         egm_tol: float | None = None,
                         dist_tol: float | None = None) -> EZEquilibrium:
    """Aiyagari general equilibrium under Epstein-Zin preferences: the
    same bracketed bisection on r, with the EZ household inside.  The
    distribution machinery runs on the (m, c) knots unchanged.

    Economics pinned by the tests: at gamma = rho this IS the CRRA
    equilibrium; raising gamma at fixed rho strengthens precautionary
    saving and lowers r* (risk aversion alone drives the buffer even
    when intertemporal substitution is unchanged)."""
    r_tol, egm_tol, dist_tol, r_lo, r_hi = _bisection_setup(
        model, disc_fac, depr_fac, r_tol, egm_tol, dist_tol)
    labor = aggregate_labor(model)

    # COLD solves at every midpoint, deliberately (matching
    # solve_bisection_equilibrium, not the lean/huggett warm-start
    # carry): a warm-started inner fixed point stops wherever its c-diff
    # certificate first fires, making the excess map history-dependent
    # at the ~1e-3-supply level — measured here, that noise lands
    # verbatim in the REPORTED clearing residual (the bracket still
    # pins r*, but `excess` is a diagnostic users gate on).  Cold
    # evaluations keep the map deterministic and the residual at the
    # deterministic-root level (~1e-7 relative).
    def supply_at(r):
        k_to_l = k_to_l_from_r(r, cap_share, depr_fac)
        W = wage_rate(k_to_l, cap_share)
        pol, _, _, _ = solve_ez_household(1.0 + r, W, model, disc_fac, rho,
                                       gamma, tol=egm_tol)
        dist, _, _, _ = stationary_wealth(as_household_policy(pol), 1.0 + r,
                                       W, model, tol=dist_tol)
        return aggregate_capital(dist, model), pol, dist, W

    def excess(r):
        supply, _, _, _ = supply_at(r)
        return supply - k_to_l_from_r(r, cap_share, depr_fac) * labor

    r_star, iters, status = _bisect(excess, r_lo, r_hi, r_tol, max_bisect)
    supply, pol, dist, W = supply_at(r_star)
    demand = k_to_l_from_r(r_star, cap_share, depr_fac) * labor
    y = output(supply, labor, cap_share)
    return EZEquilibrium(r_star=r_star, wage=W, capital=supply,
                         labor=labor, saving_rate=depr_fac * supply / y,
                         excess=supply - demand, policy=pol,
                         distribution=dist, bisect_iters=iters,
                         status=status)
