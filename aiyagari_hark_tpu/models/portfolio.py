"""Two-asset (safe + risky) portfolio-choice household — the BASELINE.json
"Portfolio-choice Aiyagari" extension (HARK's ``ConsPortfolioModel`` family;
the reference repo itself has no working aggregate-shock or portfolio solver,
SURVEY.md §2.2).

Model: end of period the household holds assets ``a`` split between a safe
asset returning ``R_f`` and a risky asset returning a discrete draw ``R_k``
(probability ``p_k``), chosen as a share ``omega ∈ [0, 1]``; labor income
follows the same Tauchen process as the Aiyagari model.

Solution is EGM with a portfolio-share first-order condition, all batched
array math (no per-state Python objects):

    FOC(share):  f(omega; a, s) = E_{k, s'} [ (R_k − R_f) u'(c'(m')) ] = 0
                 m' = (R_f + omega (R_k − R_f)) a + W l_{s'}
    f is decreasing in omega (u' convex, c' increasing in m'), so the
    optimum is the sign change of f on a share grid, refined by linear
    interpolation and clamped to [0, 1].
    EGM:         EndOfPrdvP(a, s) = beta E_{k, s'} [ R_p(omega*) u'(c'(m')) ]
                 c = EndOfPrdvP^{−1/gamma};  m = a + c   (+ constraint knot)

Shapes: the FOC tensor is ``[A, S_shares, K_draws, N']`` reduced by one
einsum against ``p ⊗ P`` — MXU-friendly, vmap/jit-safe, static shapes.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..ops.grids import make_asset_grid  # grid-ok: portfolio family predates the grid policy
from ..ops.interp import interp1d_rowwise
from ..ops.markov import (
    normalized_labor_states,
    stationary_distribution,
    tauchen_labor_process,
)
from ..ops.utility import inverse_marginal_utility, marginal_utility
from .household import (
    CONSTRAINT_EPS,
    HouseholdPolicy,
    accelerated_distribution_fixed_point,
    initial_distribution,
)


class PortfolioModel(NamedTuple):
    """Static calibration for the two-asset household."""

    a_grid: jnp.ndarray         # [A] end-of-period total assets
    labor_levels: jnp.ndarray   # [N]
    transition: jnp.ndarray     # [N, N]
    labor_stationary: jnp.ndarray  # [N]
    risky_returns: jnp.ndarray  # [K] gross return draws
    risky_probs: jnp.ndarray    # [K]
    share_grid: jnp.ndarray     # [S] candidate risky shares in [0, 1]
    dist_grid: jnp.ndarray = None  # [D] wealth-histogram support (GE path)


class PortfolioPolicy(NamedTuple):
    """Consumption knots per labor state plus the risky share on the
    end-of-period asset grid."""

    m_knots: jnp.ndarray   # [N, A+1]
    c_knots: jnp.ndarray   # [N, A+1]
    share: jnp.ndarray     # [N, A] omega*(a_i, s)


def lognormal_risky_returns(mean: float, std: float, n: int = 7,
                            dtype=None) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Equiprobable lognormal discretization of the gross risky return:
    ``n`` conditional means of equal-probability slices (HARK's
    ``Lognormal.discretize`` approach), matching ``mean``/``std``."""
    import numpy as np
    from scipy.stats import norm as scipy_norm

    sigma2 = np.log(1.0 + (std / mean) ** 2)
    sigma = np.sqrt(sigma2)
    mu = np.log(mean) - 0.5 * sigma2
    edges = scipy_norm.ppf(np.linspace(0.0, 1.0, n + 1))
    # conditional mean of a lognormal over each z-slice:
    # E[X | z in (a,b)] = e^{mu+s^2/2} (Phi(b-s) - Phi(a-s)) / (Phi(b)-Phi(a))
    cdf = scipy_norm.cdf
    num = cdf(edges[1:] - sigma) - cdf(edges[:-1] - sigma)
    den = cdf(edges[1:]) - cdf(edges[:-1])
    vals = np.exp(mu + 0.5 * sigma2) * num / den
    probs = np.full(n, 1.0 / n)
    return (jnp.asarray(vals, dtype=dtype), jnp.asarray(probs, dtype=dtype))


def build_portfolio_model(labor_states: int = 7, labor_ar: float = 0.6,
                          labor_sd: float = 0.2, labor_bound: float = 3.0,
                          a_min: float = 0.001, a_max: float = 50.0,
                          a_count: int = 48, a_nest_fac: int = 2,
                          risky_mean: float = 1.08, risky_std: float = 0.20,
                          risky_count: int = 7, share_count: int = 25,
                          dist_count: int = 300,
                          dtype=None) -> PortfolioModel:
    from ..ops.grids import make_grid_exp_mult  # grid-ok: portfolio family predates the grid policy

    a_grid = make_asset_grid(a_min, a_max, a_count, a_nest_fac, dtype=dtype)  # grid-ok
    tauchen = tauchen_labor_process(labor_states, labor_ar, labor_sd,
                                    bound=labor_bound, dtype=dtype)
    returns, probs = lognormal_risky_returns(risky_mean, risky_std,
                                             risky_count, dtype=dtype)
    # Wealth-histogram support, same shape as the single-asset model's:
    # a zero point for the borrowing limit, then exp-mult spacing.
    inner = make_grid_exp_mult(a_min, a_max, dist_count - 1, a_nest_fac,  # grid-ok
                               dtype=dtype)
    dist_grid = jnp.concatenate([jnp.zeros((1,), dtype=inner.dtype), inner])
    return PortfolioModel(
        a_grid=a_grid,
        labor_levels=normalized_labor_states(tauchen.grid),
        transition=tauchen.transition,
        labor_stationary=stationary_distribution(tauchen.transition),
        risky_returns=returns, risky_probs=probs,
        share_grid=jnp.linspace(0.0, 1.0, share_count, dtype=a_grid.dtype),
        dist_grid=dist_grid)


def initial_portfolio_policy(model: PortfolioModel) -> PortfolioPolicy:
    n = model.labor_levels.shape[0]
    eps = jnp.asarray(CONSTRAINT_EPS, dtype=model.a_grid.dtype)
    m_row = jnp.concatenate([eps[None], model.a_grid + eps])
    knots = jnp.tile(m_row, (n, 1))
    share = jnp.zeros((n, model.a_grid.shape[0]), dtype=model.a_grid.dtype)
    return PortfolioPolicy(m_knots=knots, c_knots=knots, share=share)


def _optimal_share(gap_foc: jnp.ndarray, share_grid: jnp.ndarray):
    """Zero crossing of the (decreasing-in-omega) excess-return FOC on the
    share grid, linearly refined; corners when no sign change.

    ``gap_foc``: [..., S] values of f(omega_j).  Returns omega* [...] .
    """
    pos = gap_foc >= 0
    # index of last gridpoint with f >= 0 (f decreasing); 0 if none
    idx = jnp.sum(pos.astype(jnp.int32), axis=-1) - 1
    idx = jnp.clip(idx, 0, share_grid.shape[0] - 2)
    f0 = jnp.take_along_axis(gap_foc, idx[..., None], axis=-1)[..., 0]
    f1 = jnp.take_along_axis(gap_foc, idx[..., None] + 1, axis=-1)[..., 0]
    w0 = share_grid[idx]
    w1 = share_grid[idx + 1]
    t = jnp.where(jnp.abs(f1 - f0) > 1e-30, f0 / (f0 - f1), 0.0)
    omega = w0 + jnp.clip(t, 0.0, 1.0) * (w1 - w0)
    all_neg = ~pos[..., 0]          # f(0) < 0  -> corner omega = 0
    all_pos = pos[..., -1]          # f(1) >= 0 -> corner omega = 1
    omega = jnp.where(all_neg, share_grid[0], omega)
    omega = jnp.where(all_pos, share_grid[-1], omega)
    return omega


def egm_step_portfolio(policy: PortfolioPolicy, r_free, wage,
                       model: PortfolioModel, disc_fac,
                       crra) -> PortfolioPolicy:
    """One backward step: share FOC on the [A, S, K, N'] tensor, then EGM on
    consumption at the optimal share."""
    a = model.a_grid                                   # [A]
    excess = model.risky_returns - r_free              # [K]
    # portfolio return per (share, draw): [S, K]
    r_port = r_free + model.share_grid[:, None] * excess[None, :]
    # m'[A, S, K, N'] = R_p a + W l'
    m_next = (r_port[None, :, :, None] * a[:, None, None, None]
              + wage * model.labor_levels[None, None, None, :])
    n = model.labor_levels.shape[0]
    # c'(m') with per-next-state knots: rowwise over N'
    flat = m_next.reshape(-1, n).T                     # [N', A*S*K]
    c_next = interp1d_rowwise(flat, policy.m_knots, policy.c_knots)
    vp = marginal_utility(c_next.T.reshape(m_next.shape), crra)  # [A,S,K,N']
    # joint weights over (K, N') given current state j: p_k * P[j, n'];
    # FOC tensor f[A, j, S] = sum_{k, n'} p_k P[j,n'] (R_k - R_f) vp
    foc = jnp.einsum("askn,k,jn->ajs", vp, excess * model.risky_probs,
                     model.transition,
                     precision=jax.lax.Precision.HIGHEST)
    omega = _optimal_share(foc, model.share_grid)      # [A, j]
    # marginal value at omega*: E[(R_f + omega* (R_k - R_f)) u'(c')]
    # evaluate vp at the interpolated share by re-deriving m' at omega*
    r_opt = r_free + omega[:, :, None] * excess[None, None, :]   # [A, s, K]
    m_opt = (r_opt[:, :, :, None] * a[:, None, None, None]
             + wage * model.labor_levels[None, None, None, :])   # [A,s,K,N']
    flat = m_opt.reshape(-1, n).T
    c_opt = interp1d_rowwise(flat, policy.m_knots, policy.c_knots)
    vp_opt = marginal_utility(c_opt.T.reshape(m_opt.shape), crra)
    weighted = r_opt[..., None] * vp_opt               # [A, s, K, N']
    end_vp = disc_fac * jnp.einsum("ajkn,k,jn->aj", weighted,
                                   model.risky_probs, model.transition,
                                   precision=jax.lax.Precision.HIGHEST)
    c_now = inverse_marginal_utility(end_vp, crra)     # [A, s]
    m_now = a[:, None] + c_now
    eps = jnp.full((1, n), CONSTRAINT_EPS, dtype=c_now.dtype)
    return PortfolioPolicy(
        m_knots=jnp.concatenate([eps, m_now], axis=0).T,
        c_knots=jnp.concatenate([eps, c_now], axis=0).T,
        share=omega.T)                                 # [N, A]


def solve_portfolio_household(r_free, wage, model: PortfolioModel, disc_fac,
                              crra, tol: float = 1e-6, max_iter: int = 3000,
                              init_policy: PortfolioPolicy | None = None):
    """Infinite-horizon fixed point (sup-norm on consumption knots).
    Returns (PortfolioPolicy, n_iter, final_diff).  ``init_policy``
    warm-starts the iteration (previous bisection midpoint's policy)."""
    p0 = (initial_portfolio_policy(model) if init_policy is None
          else init_policy)
    big = jnp.asarray(jnp.inf, dtype=p0.c_knots.dtype)

    def cond(state):
        _, diff, it = state
        return (diff > tol) & (it < max_iter)

    def body(state):
        policy, _, it = state
        new = egm_step_portfolio(policy, r_free, wage, model, disc_fac, crra)
        diff = jnp.max(jnp.abs(new.c_knots - policy.c_knots))
        return new, diff, it + 1

    policy, diff, it = jax.lax.while_loop(cond, body,
                                          (p0, big, jnp.asarray(0)))
    return policy, it, diff


def consumption_policy(policy: PortfolioPolicy) -> HouseholdPolicy:
    """View the consumption part as a plain ``HouseholdPolicy`` so the
    single-asset analytics (interp evaluation, Lorenz pipelines) apply."""
    return HouseholdPolicy(m_knots=policy.m_knots, c_knots=policy.c_knots)


def share_at(policy: PortfolioPolicy, a, model: PortfolioModel,
             state_idx=None):
    """Risky share omega*(a) per labor state (rowwise interpolation on the
    end-of-period asset grid)."""
    grid = model.a_grid
    if state_idx is None:
        n = policy.share.shape[0]
        queries = jnp.broadcast_to(jnp.asarray(a), (n,) + jnp.shape(a))
        grids = jnp.broadcast_to(grid, (n,) + grid.shape)
        return interp1d_rowwise(queries, grids, policy.share)
    from ..ops.interp import interp1d
    return interp1d(a, grid, policy.share[state_idx])


# --------------------------------------------------------------------------
# General equilibrium: stationary distribution + capital-market bisection
# (VERDICT r1 missing-item: "no general equilibrium, no stationary
# distribution over (assets, state) for the two-asset model").
#
# Model closure, documented precisely because it is a choice:
#  - Productive capital is the RISKY asset.  The firm pays capital its
#    expected marginal product, so the mean gross risky return at candidate
#    net rate r is (1+r), with multiplicative mean-one return risk
#    eps_k (idiosyncratic capital-quality shocks): R_k = (1+r) * eps_k.
#  - The SAFE asset is supplied elastically at an exogenous spread
#    ``premium`` below the mean risky return (a storage/bond technology):
#    R_f = 1 + r - premium.  Only the capital market clears:
#        E[omega(a,s) * a]  =  K_demand(r).
#  - When the risky asset degenerates (risky_std -> 0, premium > 0) the
#    share goes to 1 everywhere and the model IS the single-asset Aiyagari
#    economy, equilibrium included (tested in test_portfolio.py).
# --------------------------------------------------------------------------

from ..ops.interp import locate_in_grid  # noqa: E402  (grouped with GE code)


class PortfolioTransition(NamedTuple):
    """Young-method lottery for the two-asset model: where each end-of-period
    (asset-gridpoint d, labor state n) cell's next-period savings land, per
    (risky draw k, next labor state n')."""

    idx: jnp.ndarray     # [D, N, K, N'] left-neighbor index into dist_grid
    weight: jnp.ndarray  # [D, N, K, N'] mass share on the right neighbor
    omega: jnp.ndarray   # [D, N] risky share at each histogram point


def _require_dist_grid(model: PortfolioModel) -> None:
    if model.dist_grid is None:
        raise ValueError(
            "PortfolioModel.dist_grid is required for the distribution/GE "
            "path — construct the model via build_portfolio_model("
            "dist_count=...) or _replace(dist_grid=...)")


def _share_on_dist_grid(policy: PortfolioPolicy,
                        model: PortfolioModel) -> jnp.ndarray:
    """omega(a, s) interpolated onto the histogram support, [D, N]."""
    n = model.labor_levels.shape[0]
    queries = jnp.broadcast_to(model.dist_grid,
                               (n,) + model.dist_grid.shape)   # [N, D]
    grids = jnp.broadcast_to(model.a_grid, (n,) + model.a_grid.shape)
    return interp1d_rowwise(queries, grids, policy.share).T


def portfolio_wealth_transition(policy: PortfolioPolicy, r_free, wage,
                                model: PortfolioModel) -> PortfolioTransition:
    """State is END-of-period (assets a, labor state s) — the information
    set at which the share ``omega(a, s)`` is chosen.  From (a, s), with
    probability ``p_k * P[s, s']``:
        m' = (R_f + omega (R_k - R_f)) a + W l_{s'}
        a' = m' - c(m', s')   -> lottery onto dist_grid."""
    _require_dist_grid(model)
    x = model.dist_grid                                   # [D]
    n = model.labor_levels.shape[0]
    omega = _share_on_dist_grid(policy, model)            # [D, N]
    excess = model.risky_returns - r_free                 # [K]
    r_port = r_free + omega[..., None] * excess           # [D, N, K]
    m_next = (r_port[..., None] * x[:, None, None, None]
              + wage * model.labor_levels)                # [D, N, K, N']
    flat = m_next.reshape(-1, n).T                        # [N', D*N*K]
    c_next = interp1d_rowwise(flat, policy.m_knots, policy.c_knots)
    a_next = jnp.clip(m_next - c_next.T.reshape(m_next.shape),
                      0.0, x[-1])
    idx, w = locate_in_grid(a_next, x)
    return PortfolioTransition(idx=idx, weight=w, omega=omega)


def _push_forward_portfolio(dist, trans: PortfolioTransition,
                            model: PortfolioModel):
    """One distribution-iteration step.  Mass from (d, n) splits over
    (k, n') with weight ``p_k P[n, n']`` and scatters along the asset
    lottery into column n'."""
    d_size = dist.shape[0]
    # mass[d, n, k, n'] = dist[d, n] p_k P[n, n']
    mass = (dist[:, :, None, None] * model.risky_probs[None, None, :, None]
            * model.transition[None, :, None, :])

    def scatter_col(m_col, idx_col, w_col):
        # m_col/idx_col/w_col: [D, N, K] contributions into one n' column
        z = jnp.zeros((d_size,), dtype=m_col.dtype)
        z = z.at[idx_col.ravel()].add((m_col * (1.0 - w_col)).ravel())
        z = z.at[idx_col.ravel() + 1].add((m_col * w_col).ravel())
        return z

    return jax.vmap(scatter_col, in_axes=3, out_axes=1)(
        mass, trans.idx, trans.weight)


def stationary_portfolio_wealth(policy: PortfolioPolicy, r_free, wage,
                                model: PortfolioModel, tol: float = 1e-10,
                                max_iter: int = 20000, init_dist=None,
                                accel_every: int = 64):
    """Stationary joint distribution over (end-of-period assets, labor
    state), [D, N].  Returns (dist, n_iter, final_diff, status).  Uses
    the shared
    Aitken-accelerated iteration (``accelerated_distribution_fixed_point``;
    ``accel_every=0`` disables extrapolation); ``init_dist`` warm-starts."""
    trans = portfolio_wealth_transition(policy, r_free, wage, model)
    dist0 = initial_distribution(model) if init_dist is None else init_dist
    return accelerated_distribution_fixed_point(
        lambda d: _push_forward_portfolio(d, trans, model),
        dist0, tol, max_iter, accel_every)


class PortfolioEquilibrium(NamedTuple):
    r_star: jnp.ndarray        # net expected return on capital
    r_free: jnp.ndarray        # net safe rate (r_star - premium)
    wage: jnp.ndarray
    capital: jnp.ndarray       # E[omega a] = risky holdings = K
    total_assets: jnp.ndarray  # E[a] (risky + safe holdings)
    risky_share_mean: jnp.ndarray  # capital / total_assets
    labor: jnp.ndarray
    saving_rate: jnp.ndarray   # delta K / Y
    excess: jnp.ndarray        # K - K_demand at r_star
    policy: PortfolioPolicy
    distribution: jnp.ndarray  # [D, N]
    bisect_iters: jnp.ndarray


def _portfolio_supply(r, base: PortfolioModel, eps_draws, premium, disc_fac,
                      crra, cap_share, depr_fac, prod, egm_tol, dist_tol,
                      init_policy=None, init_dist=None):
    """Household side at candidate rate r: returns (K_supply, total assets,
    policy, distribution, model-at-r, r_free).  ``init_policy``/``init_dist``
    warm-start the inner fixed points from the previous midpoint."""
    from . import firm

    r_free = 1.0 + r - premium
    model = base._replace(risky_returns=(1.0 + r) * eps_draws)
    k_to_l = firm.k_to_l_from_r(r, cap_share, depr_fac, prod)
    wage = firm.wage_rate(k_to_l, cap_share, prod)
    policy, _, _ = solve_portfolio_household(r_free, wage, model, disc_fac,
                                             crra, tol=egm_tol,
                                             init_policy=init_policy)
    dist, _, _, _ = stationary_portfolio_wealth(policy, r_free, wage, model,
                                             tol=dist_tol,
                                             init_dist=init_dist)
    omega = _share_on_dist_grid(policy, model)
    x = model.dist_grid
    total = jnp.sum(dist * x[:, None])
    risky = jnp.sum(dist * omega * x[:, None])
    return risky, total, policy, dist, model, r_free, wage, k_to_l


def solve_portfolio_equilibrium(model: PortfolioModel, disc_fac, crra,
                                cap_share, depr_fac, prod=1.0,
                                premium: float = 0.04,
                                r_tol: float | None = None,
                                max_bisect: int = 40,
                                egm_tol: float | None = None,
                                dist_tol: float | None = None
                                ) -> PortfolioEquilibrium:
    """Bisect the expected capital return r until the capital market clears:
    household risky holdings E[omega a] = firm demand K(r).

    ``model.risky_returns`` is reinterpreted as MEAN-ONE multiplicative
    return shocks scaled to (1+r) at each candidate rate (see the closure
    note above); build it with ``risky_mean=1.0`` and the desired
    ``risky_std``.  Jit-able; the bracket is the single-asset one
    (supply diverges at (1-beta)/beta, demand at -delta).
    """
    from . import firm

    dtype = model.a_grid.dtype
    f64 = dtype == jnp.float64
    if r_tol is None:
        r_tol = 1e-9 if f64 else 1e-5
    if egm_tol is None:
        egm_tol = 1e-6 if f64 else 1e-5
    if dist_tol is None:
        dist_tol = 1e-10 if f64 else 1e-8
    _require_dist_grid(model)
    eps_draws = model.risky_returns / jnp.sum(
        model.risky_returns * model.risky_probs)   # renormalize to mean one
    labor = jnp.sum(model.labor_stationary * model.labor_levels)
    # Economic bracket: supply diverges at (1-beta)/beta; the safe rate must
    # stay above -delta, so the premium shifts the lower end up.  Unlike the
    # single-asset bracket this CAN invert (e.g. beta=0.99, delta=0.025,
    # premium=0.04) — fail loudly instead of returning a non-equilibrium.
    r_hi_f = 1.0 / disc_fac - 1.0 - 1e-4
    r_lo_f = -depr_fac + premium + 1e-3
    if r_lo_f >= r_hi_f:
        raise ValueError(
            f"empty bisection bracket [{r_lo_f:.4f}, {r_hi_f:.4f}]: "
            f"premium={premium} is too large relative to the discount "
            f"rate bound (1-beta)/beta={1.0 / disc_fac - 1.0:.4f} and "
            f"depreciation {depr_fac}")
    r_hi = jnp.asarray(r_hi_f, dtype=dtype)
    r_lo = jnp.asarray(r_lo_f, dtype=dtype)

    # warm-start carry across midpoints (same pattern as the single-asset
    # lean solver: nearby r -> nearby fixed points)
    p0 = initial_portfolio_policy(model)
    d0 = initial_distribution(model)

    def cond(state):
        lo, hi, it, _, _ = state
        return ((hi - lo) > r_tol) & (it < max_bisect)

    def body(state):
        lo, hi, it, policy, dist = state
        mid = 0.5 * (lo + hi)
        risky, _, pol, dst, *_ = _portfolio_supply(
            mid, model, eps_draws, premium, disc_fac, crra, cap_share,
            depr_fac, prod, egm_tol, dist_tol,
            init_policy=policy, init_dist=dist)
        demand = firm.k_to_l_from_r(mid, cap_share, depr_fac, prod) * labor
        ex = risky - demand
        lo = jnp.where(ex > 0, lo, mid)
        hi = jnp.where(ex > 0, mid, hi)
        return lo, hi, it + 1, pol, dst

    lo, hi, iters, _, _ = jax.lax.while_loop(
        cond, body, (r_lo, r_hi, jnp.asarray(0), p0, d0))
    r_star = 0.5 * (lo + hi)
    risky, total, policy, dist, _, r_free, wage, k_to_l = _portfolio_supply(
        r_star, model, eps_draws, premium, disc_fac, crra, cap_share,
        depr_fac, prod, egm_tol, dist_tol)
    demand = k_to_l * labor
    output = prod * risky ** cap_share * labor ** (1.0 - cap_share)
    return PortfolioEquilibrium(
        r_star=r_star, r_free=r_free - 1.0, wage=wage, capital=risky,
        total_assets=total, risky_share_mean=risky / total, labor=labor,
        saving_rate=depr_fac * risky / output, excess=risky - demand,
        policy=policy, distribution=dist, bisect_iters=iters)
