"""Fiscal redistribution in the Aiyagari economy: revenue-neutral labor
taxation as a general-equilibrium experiment.

Beyond the reference (which has no government), but built entirely on the
reference-parity machinery: the key observation is that both canonical
balanced-budget schemes are STATIC relabelings of the labor states, so the
whole equilibrium stack (EGM, stationary distribution, bisection, sweeps,
welfare) applies unchanged:

- **Linear tax + lump-sum transfer** (tax rate ``tau``, transfer
  ``T = tau * W * L_bar``): post-fiscal earnings
  ``(1-tau) W l_s + T = W ((1-tau) l_s + tau L_bar)`` — a mean-preserving
  compression of the labor levels toward ``L_bar``.
- **HSV progressivity** (Heathcote-Storesletten-Violante 2017: post-tax
  earnings ``lambda (W l)^(1-p)`` with ``lambda`` set for revenue
  neutrality at equilibrium prices): the wage factors cancel,
  ``y_eff = W * L_bar * l^(1-p) / E[l^(1-p)]`` — again a static,
  mean-preserving compression, for ANY equilibrium W.

Because both transforms preserve the stationary mean of labor, the firm's
labor input ``aggregate_labor(model)`` is unchanged and the government
budget balances identically at every interest rate the bisection visits —
no extra fixed point.

Economics these experiments expose (tested): redistribution insures
idiosyncratic risk, so precautionary saving falls, capital supply shifts
in, and the equilibrium interest rate RISES toward the complete-markets
1/beta - 1 (Aiyagari 1994 §III's mechanism run in reverse); utilitarian
welfare trades that crowding-out against the insurance gain.

Reference anchor: the machinery reused here is the reference's Aiyagari
stack (SURVEY.md §1 L4); the reference itself has no fiscal block.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from .equilibrium import EquilibriumResult, solve_bisection_equilibrium
from .household import SimpleModel, aggregate_labor, build_simple_model


def redistributive_labor_levels(labor_levels, stationary, tax_rate):
    """Post-fiscal labor levels under a linear tax + lump-sum transfer:
    ``(1-tau) l + tau L_bar`` (mean-preserving compression toward the
    stationary mean).  ``tax_rate`` may be a traced scalar (sweep axis)."""
    l_bar = jnp.sum(stationary * labor_levels)
    return (1.0 - tax_rate) * labor_levels + tax_rate * l_bar


def progressive_labor_levels(labor_levels, stationary, progressivity):
    """Post-fiscal labor levels under revenue-neutral HSV progressivity:
    ``L_bar * l^(1-p) / E[l^(1-p)]``.  ``p=0`` is the identity; ``p=1``
    full pooling.  ``progressivity`` may be a traced scalar."""
    l_bar = jnp.sum(stationary * labor_levels)
    compressed = labor_levels ** (1.0 - progressivity)
    return l_bar * compressed / jnp.sum(stationary * compressed)


class FiscalEquilibrium(NamedTuple):
    """Equilibrium of the fiscal economy plus the fiscal-account readout."""

    equilibrium: EquilibriumResult
    model: SimpleModel            # the transformed (post-fiscal) model
    tax_rate: jnp.ndarray         # linear rate (0 when using progressivity)
    progressivity: jnp.ndarray    # HSV p (0 when using the linear scheme)
    transfer: jnp.ndarray         # lump-sum transfer at equilibrium prices
    revenue: jnp.ndarray          # tax revenue (= transfer: balanced)
    post_tax_income_sd: jnp.ndarray   # sd of post-fiscal earnings / W


def build_fiscal_model(tax_rate=0.0, progressivity=0.0,
                       **model_kwargs) -> SimpleModel:
    """An Aiyagari model whose labor levels carry the balanced-budget
    fiscal transform.  Exactly one of ``tax_rate``/``progressivity`` should
    be nonzero (they compose mathematically, but calibrations don't)."""
    base = build_simple_model(**model_kwargs)
    levels = redistributive_labor_levels(base.labor_levels,
                                         base.labor_stationary, tax_rate)
    levels = progressive_labor_levels(levels, base.labor_stationary,
                                      progressivity)
    return base._replace(labor_levels=levels)


def solve_fiscal_equilibrium(disc_fac, crra, cap_share, depr_fac,
                             tax_rate=0.0, progressivity=0.0,
                             prod: float = 1.0,
                             **kwargs) -> FiscalEquilibrium:
    """General equilibrium of the fiscal economy (bisection engine on the
    transformed model) with the fiscal accounts evaluated at equilibrium
    prices.  Extra kwargs split between ``build_simple_model`` sizes and
    solver settings the same way ``models.equilibrium._solve_cell`` does —
    pass grid settings (``a_count=...``) or solver tolerances."""
    model_keys = ("labor_states", "labor_ar", "labor_sd", "labor_bound",
                  "a_min", "a_max", "a_count", "a_nest_fac", "dist_count",
                  "borrow_limit", "dtype")
    model_kwargs = {k: kwargs.pop(k) for k in list(kwargs)
                    if k in model_keys}
    model = build_fiscal_model(tax_rate=tax_rate,
                               progressivity=progressivity, **model_kwargs)
    eq = solve_bisection_equilibrium(model, disc_fac, crra, cap_share,
                                     depr_fac, prod=prod, **kwargs)
    # fiscal accounts at equilibrium prices (pre-tax labor aggregates are
    # invariant to the transform, so eq.wage IS the untransformed
    # economy's wage)
    W = eq.wage
    l_bar = aggregate_labor(model)        # == pre-tax mean by construction
    revenue = tax_rate * W * l_bar
    pi = model.labor_stationary
    mean_l = jnp.sum(pi * model.labor_levels)
    sd_l = jnp.sqrt(jnp.sum(pi * (model.labor_levels - mean_l) ** 2))
    return FiscalEquilibrium(
        equilibrium=eq, model=model,
        tax_rate=jnp.asarray(tax_rate),
        progressivity=jnp.asarray(progressivity),
        transfer=revenue, revenue=revenue, post_tax_income_sd=sd_l)
