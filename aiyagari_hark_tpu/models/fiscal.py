"""Fiscal redistribution in the Aiyagari economy: revenue-neutral labor
taxation as a general-equilibrium experiment.

Beyond the reference (which has no government), but built entirely on the
reference-parity machinery: the key observation is that both canonical
balanced-budget schemes are STATIC relabelings of the labor states, so the
whole equilibrium stack (EGM, stationary distribution, bisection, sweeps,
welfare) applies unchanged:

- **Linear tax + lump-sum transfer** (tax rate ``tau``, transfer
  ``T = tau * W * L_bar``): post-fiscal earnings
  ``(1-tau) W l_s + T = W ((1-tau) l_s + tau L_bar)`` — a mean-preserving
  compression of the labor levels toward ``L_bar``.
- **HSV progressivity** (Heathcote-Storesletten-Violante 2017: post-tax
  earnings ``lambda (W l)^(1-p)`` with ``lambda`` set for revenue
  neutrality at equilibrium prices): the wage factors cancel,
  ``y_eff = W * L_bar * l^(1-p) / E[l^(1-p)]`` — again a static,
  mean-preserving compression, for ANY equilibrium W.

Because both transforms preserve the stationary mean of labor, the firm's
labor input ``aggregate_labor(model)`` is unchanged and the government
budget balances identically at every interest rate the bisection visits —
no extra fixed point.

Economics these experiments expose (tested): redistribution insures
idiosyncratic risk, so precautionary saving falls, capital supply shifts
in, and the equilibrium interest rate RISES toward the complete-markets
1/beta - 1 (Aiyagari 1994 §III's mechanism run in reverse); utilitarian
welfare trades that crowding-out against the insurance gain.

Reference anchor: the machinery reused here is the reference's Aiyagari
stack (SURVEY.md §1 L4); the reference itself has no fiscal block.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .equilibrium import EquilibriumResult, solve_bisection_equilibrium
from .household import SimpleModel, aggregate_labor, build_simple_model

_MODEL_KEYS = ("labor_states", "labor_ar", "labor_sd", "labor_bound",
               "a_min", "a_max", "a_count", "a_nest_fac", "dist_count",
               "borrow_limit", "dtype")


def _split_model_kwargs(kwargs: dict) -> dict:
    """Pop ``build_simple_model`` settings out of a mixed kwargs dict,
    leaving solver settings (r_tol, max_bisect, ...) behind — the same
    split ``models.equilibrium._solve_cell`` encodes in its signature."""
    return {k: kwargs.pop(k) for k in list(kwargs) if k in _MODEL_KEYS}


def redistributive_labor_levels(labor_levels, stationary, tax_rate):
    """Post-fiscal labor levels under a linear tax + lump-sum transfer:
    ``(1-tau) l + tau L_bar`` (mean-preserving compression toward the
    stationary mean).  ``tax_rate`` may be a traced scalar (sweep axis)."""
    l_bar = jnp.sum(stationary * labor_levels)
    return (1.0 - tax_rate) * labor_levels + tax_rate * l_bar


def progressive_labor_levels(labor_levels, stationary, progressivity):
    """Post-fiscal labor levels under revenue-neutral HSV progressivity:
    ``L_bar * l^(1-p) / E[l^(1-p)]``.  ``p=0`` is the identity; ``p=1``
    full pooling.  ``progressivity`` may be a traced scalar."""
    l_bar = jnp.sum(stationary * labor_levels)
    compressed = labor_levels ** (1.0 - progressivity)
    return l_bar * compressed / jnp.sum(stationary * compressed)


class FiscalEquilibrium(NamedTuple):
    """Equilibrium of the fiscal economy plus the fiscal-account readout."""

    equilibrium: EquilibriumResult
    model: SimpleModel            # the transformed (post-fiscal) model
    tax_rate: jnp.ndarray         # linear rate (0 when using progressivity)
    progressivity: jnp.ndarray    # HSV p (0 when using the linear scheme)
    transfer: jnp.ndarray         # lump-sum transfer at equilibrium prices
    revenue: jnp.ndarray          # tax revenue (= transfer: balanced)
    post_tax_income_sd: jnp.ndarray   # sd of post-fiscal earnings / W


def build_fiscal_model(tax_rate=0.0, progressivity=0.0,
                       **model_kwargs) -> SimpleModel:
    """An Aiyagari model whose labor levels carry the balanced-budget
    fiscal transform.  Exactly one of ``tax_rate``/``progressivity`` should
    be nonzero (they compose mathematically, but calibrations don't)."""
    base = build_simple_model(**model_kwargs)
    levels = redistributive_labor_levels(base.labor_levels,
                                         base.labor_stationary, tax_rate)
    levels = progressive_labor_levels(levels, base.labor_stationary,
                                      progressivity)
    return base._replace(labor_levels=levels)


class TaxSweepResult(NamedTuple):
    """Per-rate equilibrium outcomes of a vmapped tax sweep, [T]-leading."""

    tax_rates: jnp.ndarray
    r_star: jnp.ndarray
    capital: jnp.ndarray
    welfare: jnp.ndarray          # utilitarian E[v] at each equilibrium


@functools.lru_cache(maxsize=None)
def _batched_tax_solver(disc_fac, crra, cap_share, depr_fac, prod,
                        with_welfare, model_items, solver_items):
    """Jitted vmapped (GE + welfare) lane solver, memoized on the static
    configuration so refining the tax grid (or re-calling with identical
    settings) hits the jit cache instead of recompiling the whole batched
    program — the `parallel.sweep._batched_solver` pattern."""
    from .equilibrium import solve_equilibrium_lean
    from .value import aggregate_welfare, policy_value_direct

    base = build_simple_model(**dict(model_items))
    solver_kwargs = dict(solver_items)

    def solve_one(tau):
        model = base._replace(labor_levels=redistributive_labor_levels(
            base.labor_levels, base.labor_stationary, tau))
        if not with_welfare:
            # scalars-only solver: the same small compiled program the
            # Table II sweep uses (no post-loop policy/distribution
            # re-solve per lane)
            lean = solve_equilibrium_lean(model, disc_fac, crra, cap_share,
                                          depr_fac, prod=prod,
                                          **solver_kwargs)
            return (lean.r_star, lean.capital,
                    jnp.full_like(lean.r_star, jnp.nan))
        eq = solve_bisection_equilibrium(model, disc_fac, crra, cap_share,
                                         depr_fac, prod=prod,
                                         **solver_kwargs)
        R = 1.0 + eq.r_star
        # bounded-cost value recovery (linear solve + fixed polish): a
        # value-iteration while_loop here, vmapped on top of the nested
        # bisection, was the r3 XLA compile pathology that wedged the TPU
        # tunnel (>10 min compile; VERDICT r3) — see policy_value_direct
        vf, _, _ = policy_value_direct(eq.policy, R, eq.wage, model,
                                       disc_fac, crra)
        w = aggregate_welfare(vf, eq.distribution, R, eq.wage, model, crra)
        return eq.r_star, eq.capital, w

    return jax.jit(jax.vmap(solve_one))


def tax_rate_sweep(tax_rates, disc_fac, crra, cap_share, depr_fac,
                   prod: float = 1.0, with_welfare: bool = True,
                   **kwargs) -> TaxSweepResult:
    """The optimal-redistribution search as ONE batched XLA program: vmap
    whole general-equilibrium solves (plus the welfare recovery) over the
    tax-rate axis — the same lanes-are-cheap thesis as the Table II sweep
    (`parallel.sweep`), applied to a policy question the reference could
    never ask.  The welfare curve is hump-shaped (see
    ``tests/test_fiscal.py``), so its argmax is the optimal linear
    redistribution rate at this calibration.  Extra kwargs split between
    ``build_simple_model`` sizes and solver settings (r_tol, max_bisect,
    ...) like ``solve_fiscal_equilibrium``.

    ``with_welfare=False`` skips the vmapped value recovery (welfare
    comes back NaN): the rate/capital sweep then compiles like the
    Table II sweep.  The welfare path recovers each lane's value function
    with ``value.policy_value_direct`` — one fixed-size linear solve plus
    a fixed-trip polish — because the round-3 iterative path (a
    value-iteration ``while_loop`` vmapped on top of the nested bisection)
    was an XLA compile pathology on TPU: >10 minutes without finishing,
    and killing it mid-compile wedged the tunnel for hours (VERDICT r3
    weak-item 2).  Bounded control flow restores a normal compile."""
    from ..parallel.sweep import _hashable_kwargs

    model_kwargs = _split_model_kwargs(kwargs)
    fn = _batched_tax_solver(disc_fac, crra, cap_share, depr_fac, prod,
                             bool(with_welfare),
                             _hashable_kwargs(model_kwargs),
                             _hashable_kwargs(kwargs))
    taus = jnp.asarray(tax_rates)
    r, k, w = fn(taus)
    return TaxSweepResult(tax_rates=taus, r_star=r, capital=k, welfare=w)


def solve_fiscal_equilibrium(disc_fac, crra, cap_share, depr_fac,
                             tax_rate=0.0, progressivity=0.0,
                             prod: float = 1.0,
                             **kwargs) -> FiscalEquilibrium:
    """General equilibrium of the fiscal economy (bisection engine on the
    transformed model) with the fiscal accounts evaluated at equilibrium
    prices.  Extra kwargs split between ``build_simple_model`` sizes and
    solver settings the same way ``models.equilibrium._solve_cell`` does —
    pass grid settings (``a_count=...``) or solver tolerances."""
    model_kwargs = _split_model_kwargs(kwargs)
    model = build_fiscal_model(tax_rate=tax_rate,
                               progressivity=progressivity, **model_kwargs)
    eq = solve_bisection_equilibrium(model, disc_fac, crra, cap_share,
                                     depr_fac, prod=prod, **kwargs)
    # fiscal accounts at equilibrium prices (pre-tax labor aggregates are
    # invariant to the transform, so eq.wage IS the untransformed
    # economy's wage)
    W = eq.wage
    l_bar = aggregate_labor(model)        # == pre-tax mean by construction
    revenue = tax_rate * W * l_bar
    pi = model.labor_stationary
    mean_l = jnp.sum(pi * model.labor_levels)
    sd_l = jnp.sqrt(jnp.sum(pi * (model.labor_levels - mean_l) ** 2))
    return FiscalEquilibrium(
        equilibrium=eq, model=model,
        tax_rate=jnp.asarray(tax_rate),
        progressivity=jnp.asarray(progressivity),
        transfer=revenue, revenue=revenue, post_tax_income_sd=sd_l)
