"""The Krusell-Smith outer fixed point: simulate -> regress -> damp -> repeat.

Reference: ``Market.solve`` drives solve_agents / make_history /
update_dynamics until the aggregate saving rule stops moving
(SURVEY.md §3.1); the regression and damping live in ``calc_AFunc``
(``Aiyagari_Support.py:1896-1964``).  Per the north star (BASELINE.json) the
outer loop stays in host Python; everything inside an iteration — the 4N-state
EGM fixed point, the 11,000-period panel scan, and the per-state masked
regression — is one jitted call each.

The convergence metric is HARK's distance on the rule parameters:
``max_i max(|d slope_i|, |d intercept_i|)`` (``distance_criteria`` at
``Aiyagari_Support.py:1989``), against ``EconomyConfig.tolerance``.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import List

import jax
import jax.numpy as jnp

from ..ops.regression import masked_ols
from ..utils.config import AgentConfig, EconomyConfig
from .ks_model import (
    AFuncParams,
    KSCalibration,
    KSPolicy,
    build_ks_calibration,
    solve_ks_household,
)
from .simulate import (
    PanelHistory,
    initial_panel,
    simulate_markov_history,
    simulate_panel,
)


def calc_afunc_update(history: PanelHistory, mrkv_hist: jnp.ndarray,
                      afunc: AFuncParams, t_discard: int, damping: float):
    """New saving-rule parameters from a simulated history (``calc_AFunc``):
    per aggregate state, OLS of log A_t on log M_{t-1}, then a damped merge
    with the previous parameters.  Returns (new_params, r_squared[2])."""
    log_a = jnp.log(history.A_prev[t_discard:])
    log_m = jnp.log(history.M_now[t_discard - 1:-1])
    states = mrkv_hist[t_discard - 1:-1]
    w = 1.0 - damping

    def one_state(i):
        res = masked_ols(log_m, log_a, states == i)
        intercept = w * res.intercept + damping * afunc.intercept[i]
        slope = w * res.slope + damping * afunc.slope[i]
        return intercept, slope, res.r_squared

    intercepts, slopes, rsqs = jax.vmap(one_state)(jnp.arange(2))
    return AFuncParams(intercept=intercepts, slope=slopes), rsqs


@dataclass
class KSIterationRecord:
    """Structured observability per outer iteration (replaces the reference's
    ``verbose`` print at ``Aiyagari_Support.py:1954-1962``)."""

    iteration: int
    intercept: List[float]
    slope: List[float]
    r_squared: List[float]
    distance: float
    egm_iters: int
    wall_seconds: float


@dataclass
class KSSolution:
    afunc: AFuncParams
    policy: KSPolicy
    calibration: KSCalibration
    history: PanelHistory
    mrkv_hist: object = None     # [T] aggregate-state chain used
    final_panel: object = None   # PanelState at the last simulated period
    records: List[KSIterationRecord] = field(default_factory=list)
    converged: bool = False

    @property
    def equilibrium_r_pct(self) -> float:
        """(R-1)*100 at the final simulated period — the notebook's
        equilibrium-return readout (``Aiyagari-HARK.py:257``)."""
        A = float(self.history.A_prev[-1])
        cal = self.calibration
        z = int(self.history.mrkv[-1])
        agg_l = float((1.0 - cal.urate_by_agg[z]) * cal.lbr_ind)
        from . import firm
        R = firm.interest_factor(A / agg_l, cal.cap_share, cal.depr_fac,
                                 cal.prod_by_agg[z])
        return (float(R) - 1.0) * 100.0


def solve_ks_economy(agent: AgentConfig, econ: EconomyConfig,
                     seed: int = 0, ks_employment: bool = False,
                     dtype=None, egm_tol: float = 1e-6,
                     resample_each_iteration: bool = False,
                     mrkv_hist=None, callback=None,
                     checkpoint_path=None, timer=None,
                     sim_method: str = "panel",
                     dist_count: int = 500) -> KSSolution:
    """Full reference-parity solve: the Krusell-Smith fixed point over the
    aggregate saving rule.

    ``resample_each_iteration=False`` holds the shock panel fixed across
    outer iterations (deterministic fixed point — the reference instead
    leaks fresh global-RNG draws every iteration, quirk §3.6-3, which makes
    its outer loop stochastic).  Set True to mimic that behavior with
    properly split keys.  ``mrkv_hist`` injects a pre-drawn aggregate chain
    (the facade's ``make_Mrkv_history``); default draws one from ``seed``.

    ``checkpoint_path``: save the outer-loop state (saving rule, iteration,
    seed) there every iteration; if the file already exists and matches this
    ``seed``, resume from it instead of the config's initial guesses.
    ``timer``: an optional ``utils.timing.PhaseTimer`` accumulating
    solve/simulate/regress phases.

    ``sim_method``: "panel" (reference parity — ``agent_count`` Monte-Carlo
    agents) or "distribution" (deterministic: push a ``dist_count``-point
    wealth histogram through the same per-period operator — zero sampling
    noise in the regression inputs; ``final_panel`` is then the final
    ``DistPanelState`` instead of a ``PanelState``).
    """
    from ..utils.checkpoint import (
        config_fingerprint,
        load_ks_checkpoint,
        save_ks_checkpoint,
    )
    from ..utils.timing import PhaseTimer
    if timer is None:
        timer = PhaseTimer()
    fingerprint = config_fingerprint(agent, econ, mrkv_hist,
                                     ks_employment, egm_tol)
    cal = build_ks_calibration(agent, econ, ks_employment=ks_employment,
                               dtype=dtype)
    key = jax.random.PRNGKey(seed)
    k_hist, k_birth, k_panel = jax.random.split(key, 3)
    if mrkv_hist is None:
        mrkv_hist = simulate_markov_history(cal.agg_transition,
                                            econ.mrkv_now_init,
                                            econ.act_T, k_hist)
    else:
        mrkv_hist = jnp.asarray(mrkv_hist)
    # Warm start: each outer iteration's EGM fixed point seeds the next one
    # (the damped rule update moves the perceived law only a little, so the
    # household fixed points are close — same trick as the bisection carry).
    from .ks_model import initial_ks_policy
    solve_hh = jax.jit(lambda af, p0: solve_ks_household(
        af, cal, tol=egm_tol, init_policy=p0))
    policy_seed = initial_ks_policy(cal)
    if sim_method == "panel":
        init = initial_panel(cal, agent.agent_count, econ.mrkv_now_init,
                             k_birth)
        run_panel = jax.jit(lambda pol, k: simulate_panel(
            pol, cal, mrkv_hist, init, k))
    elif sim_method == "distribution":
        from .simulate import (
            initial_distribution_panel,
            make_sim_dist_grid,
            simulate_distribution_history,
        )
        dist_grid = make_sim_dist_grid(cal, dist_count)
        init = initial_distribution_panel(cal, dist_grid,
                                          econ.mrkv_now_init)
        run_panel = jax.jit(lambda pol, k: simulate_distribution_history(
            pol, cal, mrkv_hist, dist_grid, init))   # key unused
    else:
        raise ValueError(f"sim_method must be 'panel' or 'distribution', "
                         f"got {sim_method!r}")
    update = jax.jit(lambda hist, af: calc_afunc_update(
        hist, mrkv_hist, af, econ.t_discard, econ.damping_fac))

    afunc = AFuncParams(
        intercept=jnp.asarray(econ.intercept_prev, dtype=cal.a_grid.dtype),
        slope=jnp.asarray(econ.slope_prev, dtype=cal.a_grid.dtype))
    it_start = 0
    resumed_converged = False
    if checkpoint_path is not None and os.path.exists(checkpoint_path):
        ck = load_ks_checkpoint(checkpoint_path)
        if int(ck.seed) != seed or int(ck.fingerprint) != fingerprint:
            raise ValueError(
                f"checkpoint {checkpoint_path} was written by a different "
                f"run (seed {int(ck.seed)} vs {seed}, config fingerprint "
                f"mismatch: {int(ck.fingerprint) != fingerprint}) — delete "
                f"it or use a different checkpoint_path; refusing to "
                f"silently overwrite")
        afunc = AFuncParams(
            intercept=jnp.asarray(ck.intercept, dtype=cal.a_grid.dtype),
            slope=jnp.asarray(ck.slope, dtype=cal.a_grid.dtype))
        resumed_converged = bool(ck.converged)
        # always leave at least one pass to (re)generate the policy/history
        # the checkpoint does not carry
        it_start = max(0, min(int(ck.iteration), econ.max_loops - 1))
        if econ.verbose:
            print(f"[ks] resumed from {checkpoint_path} at outer "
                  f"iteration {it_start}"
                  + (" (already converged)" if resumed_converged else ""))

    if resumed_converged:
        # idempotent reload: rebuild the policy/history the checkpoint does
        # not carry, but leave the converged rule (and the file) untouched
        with timer.phase("solve"):
            policy, _, _ = jax.block_until_ready(solve_hh(afunc,
                                                          policy_seed))
        with timer.phase("simulate"):
            history, final_panel = jax.block_until_ready(
                run_panel(policy, k_panel))
        return KSSolution(afunc=afunc, policy=policy, calibration=cal,
                          history=history, mrkv_hist=mrkv_hist,
                          final_panel=final_panel, records=[],
                          converged=True)

    records: List[KSIterationRecord] = []
    history = None
    final_panel = None
    policy = None
    converged = False
    for it in range(it_start, econ.max_loops):
        t0 = time.time()
        with timer.phase("solve"):
            policy, egm_iters, _ = jax.block_until_ready(
                solve_hh(afunc, policy_seed))
            policy_seed = policy
        k_it = jax.random.fold_in(k_panel, it) if resample_each_iteration \
            else k_panel
        with timer.phase("simulate"):
            history, final_panel = jax.block_until_ready(
                run_panel(policy, k_it))
        with timer.phase("regress"):
            new_afunc, rsq = jax.block_until_ready(update(history, afunc))
        if not (bool(jnp.all(jnp.isfinite(new_afunc.intercept)))
                and bool(jnp.all(jnp.isfinite(new_afunc.slope)))):
            raise RuntimeError(
                f"KS outer iteration {it}: saving-rule regression produced "
                f"non-finite parameters (intercept={new_afunc.intercept}, "
                f"slope={new_afunc.slope}). Usually an aggregate state never "
                f"appears in the post-discard window — increase act_T or "
                f"decrease t_discard.")
        distance = float(jnp.max(jnp.maximum(
            jnp.abs(new_afunc.intercept - afunc.intercept),
            jnp.abs(new_afunc.slope - afunc.slope))))
        afunc = new_afunc
        rec = KSIterationRecord(
            iteration=it,
            intercept=[float(x) for x in afunc.intercept],
            slope=[float(x) for x in afunc.slope],
            r_squared=[float(x) for x in rsq],
            distance=distance, egm_iters=int(egm_iters),
            wall_seconds=time.time() - t0)
        records.append(rec)
        if econ.verbose:
            print(f"[ks] iter {it}: intercept={rec.intercept} "
                  f"slope={rec.slope} r2={rec.r_squared} dist={distance:.5f}")
        if callback is not None:
            callback(rec)
        if distance < econ.tolerance:
            converged = True
        if checkpoint_path is not None:
            save_ks_checkpoint(checkpoint_path, afunc, it + 1, seed,
                               converged, fingerprint)
        if converged:
            break

    return KSSolution(afunc=afunc, policy=policy, calibration=cal,
                      history=history, mrkv_hist=mrkv_hist,
                      final_panel=final_panel, records=records,
                      converged=converged)
