"""The Krusell-Smith outer fixed point: simulate -> regress -> damp -> repeat.

Reference: ``Market.solve`` drives solve_agents / make_history /
update_dynamics until the aggregate saving rule stops moving
(SURVEY.md §3.1); the regression and damping live in ``calc_AFunc``
(``Aiyagari_Support.py:1896-1964``).  Per the north star (BASELINE.json) the
outer loop stays in host Python; everything inside an iteration — the 4N-state
EGM fixed point, the 11,000-period panel scan, and the per-state masked
regression — is one jitted call each.

The convergence metric is HARK's distance on the rule parameters:
``max_i max(|d slope_i|, |d intercept_i|)`` (``distance_criteria`` at
``Aiyagari_Support.py:1989``), against ``EconomyConfig.tolerance``.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.regression import masked_ols
from ..solver_health import (
    CONVERGED,
    MAX_ITER,
    NONFINITE,
    SolverDivergenceError,
    combine_status,
    status_name,
)
from ..utils.config import AgentConfig, EconomyConfig
from .ks_model import (
    AFuncParams,
    KSCalibration,
    KSPolicy,
    build_ks_calibration,
    solve_ks_household,
)
from .simulate import (
    PanelHistory,
    initial_panel,
    simulate_markov_history,
    simulate_panel,
)


def calc_afunc_update(history: PanelHistory, mrkv_hist: jnp.ndarray,
                      afunc: AFuncParams, t_discard: int, damping: float):
    """New saving-rule parameters from a simulated history (``calc_AFunc``):
    per aggregate state, OLS of log A_t on log M_{t-1}, then a damped merge
    with the previous parameters.  Returns (new_params, r_squared[2]).

    ``history`` arrays may carry a leading fan axis ``[F, T]`` (the
    deterministic initial-condition fan, ``initial_distribution_fan``): the
    per-path (log M, log A) pairs are pooled into one regression sample.
    """
    a_prev = jnp.atleast_2d(history.A_prev)   # [F, T]; F=1 for one path
    m_now = jnp.atleast_2d(history.M_now)
    log_a = jnp.log(a_prev[:, t_discard:]).ravel()
    log_m = jnp.log(m_now[:, t_discard - 1:-1]).ravel()
    states = jnp.broadcast_to(
        mrkv_hist[t_discard - 1:-1],
        (a_prev.shape[0], m_now.shape[1] - t_discard)).ravel()
    w = 1.0 - damping

    def one_state(i):
        res = masked_ols(log_m, log_a, states == i)
        intercept = w * res.intercept + damping * afunc.intercept[i]
        slope = w * res.slope + damping * afunc.slope[i]
        return intercept, slope, res.r_squared

    intercepts, slopes, rsqs = jax.vmap(one_state)(jnp.arange(2))
    return AFuncParams(intercept=intercepts, slope=slopes), rsqs


class _PinnedSecant:
    """Safeguarded secant iteration on the scalar residual
    ``g(i) = mean log A_settled(i) - i`` of the slope-pinned saving rule.

    Plain damped iteration diverges here: the notebook calibration sits at
    Aiyagari's knife edge (equilibrium r* just below 1/beta - 1 = 4.17%),
    where ergodic asset supply is extremely elastic in the perceived return
    — measured d(log A)/d(intercept) ~ -3, so the damped map has modulus
    > 1 for any damping < 0.75.  The secant step handles the steep monotone
    residual; a bracket on the sign change plus a step clamp keeps it safe.
    """

    def __init__(self, max_step: float = 0.10, probe: float = 0.25):
        self.i_prev = None
        self.g_prev = None
        self.lo = None    # highest intercept seen with g > 0
        self.hi = None    # lowest intercept seen with g < 0
        self.max_step = max_step
        self.probe = probe

    def step(self, i: float, g: float) -> float:
        # The residual map is monotone decreasing in i, but with carried
        # simulation state early evaluations are transient-biased: a bound
        # recorded from a stale evaluation can contradict fresh data and
        # pinch the bracket onto a non-root (seen as a frozen intercept with
        # the bisect fallback halving a width of ~1e-13 while |g| > tol).
        # A fresh evaluation that contradicts a stored bound evicts it.
        if self.lo is not None and self.hi is not None:
            width = self.hi - self.lo
            # a genuine root inside a width-w bracket legitimately carries
            # |g| up to (local slope) * w — the residual's measured
            # log-slope here is ~ -190, so absolute thresholds in
            # intercept units evict VALID bounds near the root (round-3
            # review).  Scale the pinch test by the secant's own slope
            # estimate (fallback: the measured ~200) with a 10x margin.
            slope_est = 200.0
            if (self.g_prev is not None and self.i_prev is not None
                    and abs(i - self.i_prev) > 1e-12):
                slope_est = max(
                    abs((g - self.g_prev) / (i - self.i_prev)), 1.0)
            if (width < 1e-6
                    and abs(g) > 10.0 * slope_est * max(width, 1e-12)):
                # bracket pinched to numerical nothing around a point that
                # is demonstrably not a root: every recorded bound is stale
                self.lo = self.hi = None
        if g > 0:
            if self.hi is not None and i >= self.hi:
                self.hi = None   # stale: g>0 cannot sit at/above the hi bound
            self.lo = i if self.lo is None else max(self.lo, i)
        else:
            if self.lo is not None and i <= self.lo:
                self.lo = None
            self.hi = i if self.hi is None else min(self.hi, i)
        if (self.g_prev is not None and abs(g - self.g_prev) > 1e-14
                and abs(i - self.i_prev) > 1e-12):
            cand = i - g * (i - self.i_prev) / (g - self.g_prev)
        else:
            # seed the secant — or recover from a frozen iterate, where the
            # slope estimate degenerates to 0/dg (g still moves between
            # identical iterates while the carried simulation state relaxes)
            cand = i + self.probe * g
        cand = min(max(cand, i - self.max_step), i + self.max_step)
        if self.lo is not None and self.hi is not None and not (
                self.lo < cand < self.hi):
            cand = 0.5 * (self.lo + self.hi)   # bisect when secant escapes
        self.i_prev, self.g_prev = i, g
        return cand

    def to_array(self):
        """(i_prev, g_prev, lo, hi) with NaN for unset — checkpoint form."""
        import numpy as np
        return np.asarray([np.nan if v is None else v for v in
                           (self.i_prev, self.g_prev, self.lo, self.hi)])

    def restore(self, arr) -> None:
        import numpy as np
        vals = [None if np.isnan(v) else float(v) for v in np.asarray(arr)]
        self.i_prev, self.g_prev, self.lo, self.hi = vals


@dataclass
class KSIterationRecord:
    """Structured observability per outer iteration (replaces the reference's
    ``verbose`` print at ``Aiyagari_Support.py:1954-1962``)."""

    iteration: int
    intercept: List[float]
    slope: List[float]
    r_squared: List[float]
    distance: float
    egm_iters: int
    wall_seconds: float
    egm_status: int = CONVERGED   # solver_health code of the EGM inner solve


@dataclass
class KSSolution:
    afunc: AFuncParams
    policy: KSPolicy
    calibration: KSCalibration
    history: PanelHistory
    mrkv_hist: object = None     # [T] aggregate-state chain used
    final_panel: object = None   # PanelState at the last simulated period
    # (``DistPanelState`` under sim_method="distribution")
    dist_grid: object = None     # [D] histogram support (distribution mode)
    records: List[KSIterationRecord] = field(default_factory=list)
    converged: bool = False
    status: int = CONVERGED      # worst-of-run solver_health code:
    # CONVERGED, or MAX_ITER when the outer loop exhausted max_loops /
    # an inner EGM solve left its budget uncertified (a NONFINITE run
    # never returns — solve_ks_economy raises SolverDivergenceError)

    @property
    def equilibrium_r_pct(self) -> float:
        """(R-1)*100 at the final simulated period — the notebook's
        equilibrium-return readout (``Aiyagari-HARK.py:257``)."""
        A = float(self.history.A_prev[-1])
        cal = self.calibration
        z = int(self.history.mrkv[-1])
        agg_l = float((1.0 - cal.urate_by_agg[z]) * cal.lbr_ind)
        from . import firm
        R = firm.interest_factor(A / agg_l, cal.cap_share, cal.depr_fac,
                                 cal.prod_by_agg[z])
        return (float(R) - 1.0) * 100.0


def solve_ks_economy(agent: AgentConfig, econ: EconomyConfig,
                     seed: int = 0, ks_employment: bool = False,
                     dtype=None, egm_tol: float = 1e-6,
                     resample_each_iteration: bool = False,
                     mrkv_hist=None, callback=None,
                     checkpoint_path=None, timer=None,
                     sim_method: str = "panel",
                     dist_count: int = 500,
                     dist_fan: int | None = None,
                     dist_discard: int | None = None,
                     dist_pin_slope: bool | None = None,
                     retry=None) -> KSSolution:
    """Full reference-parity solve: the Krusell-Smith fixed point over the
    aggregate saving rule.

    ``resample_each_iteration=False`` holds the shock panel fixed across
    outer iterations (deterministic fixed point — the reference instead
    leaks fresh global-RNG draws every iteration, quirk §3.6-3, which makes
    its outer loop stochastic).  Set True to mimic that behavior with
    properly split keys.  ``mrkv_hist`` injects a pre-drawn aggregate chain
    (the facade's ``make_Mrkv_history``); default draws one from ``seed``.

    ``checkpoint_path``: save the outer-loop state (saving rule, iteration,
    seed) there every iteration; if the file already exists and matches this
    ``seed``, resume from it instead of the config's initial guesses.
    ``timer``: an optional ``utils.timing.PhaseTimer`` accumulating
    solve/simulate/regress phases.

    Resilience (ISSUE 3, ``utils.resilience``): inside a
    ``preemption_guard()`` a SIGTERM/SIGINT is honored at the next OUTER
    iteration boundary — the just-written checkpoint (sidecar-first write
    order, see below) is the flushed state and the typed
    ``resilience.Interrupted`` is raised instead of dying mid-write; a
    rerun with the same ``checkpoint_path`` continues the trajectory.
    The heavy device calls (household solve, panel/distribution
    simulation) run under ``retry_transient`` with the deterministic
    backoff of ``retry`` (default ``RetryPolicy()``): transient
    device/RPC faults are replayed — pure jitted launches, so a replay
    computes the same bits — while ``SolverDivergenceError`` is never
    retried (the solver-health layer owns numeric failure).

    ``sim_method``: "panel" (reference parity — ``agent_count`` Monte-Carlo
    agents) or "distribution" (deterministic: push a ``dist_count``-point
    wealth histogram through the same per-period operator — zero sampling
    noise in the regression inputs; ``final_panel`` is then the final
    ``DistPanelState`` instead of a ``PanelState``).

    ``dist_pin_slope``: constrain the perceived saving rule to a *constant*
    (slope 0, ``K' = exp(intercept)``) and solve the intercept by a
    safeguarded secant iteration on the settled aggregate (see
    ``_PinnedSecant`` for why plain damping diverges).  Default: True exactly
    when the calibration is aggregate-degenerate (the Aiyagari
    configuration, ProdB=ProdG and UrateB=UrateG,
    ``Aiyagari_Support.py:1538-1547``).  Why this is the right default —
    a finding this framework documents rather than inherits: with no
    aggregate shocks the rational-expectations law of motion is the
    constant ``K' = K*``, but the *transition map* ``log A' ~ log M`` has
    local slope ~1.2, and a log-linear rule fit to deterministic data
    converges to that slope, whose off-path explosiveness distorts
    household expectations enough to settle ~1.8pp above the true
    equilibrium r*.  The reference's Monte-Carlo version lands near the
    truth only by accident: sampling noise in log M attenuates its OLS
    slope (errors-in-variables) toward the stable region.  Pinning the
    slope makes the deterministic method converge to the same equilibrium
    as the independent bisection engine (``models/equilibrium.py``).

    ``dist_fan``: number of deterministic initial-condition paths for the
    *unpinned* distribution regression (``initial_distribution_fan``) —
    with one deterministic path and no aggregate variation the slope is
    unidentified; a fan of transients from spread initial capital levels
    identifies the true transition map.  Default 1 (pinned mode and
    true-KS chains don't need it); set >1 only to *measure* the
    unconstrained map.  ``dist_discard``: periods dropped per path before
    the regression (default: ``econ.t_discard`` for a single path, else a
    short mixing window — the transient *is* the signal for a fan).
    """
    from ..utils.checkpoint import (
        CheckpointMismatchError,
        config_fingerprint,
        load_ks_checkpoint,
        save_ks_checkpoint,
    )
    from ..utils.resilience import (
        RetryPolicy,
        raise_if_interrupted,
        retry_transient,
    )
    from ..utils.timing import PhaseTimer, Stopwatch
    if timer is None:
        timer = PhaseTimer()
    retry_policy = retry if retry is not None else RetryPolicy()

    def _device(label, f):
        """Transient-retry wrapper for the jitted launches (safe to
        replay: pure programs of immutable inputs)."""
        return retry_transient(f, retry_policy, label=label)
    cal = build_ks_calibration(agent, econ, ks_employment=ks_employment,
                               dtype=dtype)
    key = jax.random.PRNGKey(seed)
    k_hist, k_birth, k_panel = jax.random.split(key, 3)
    if mrkv_hist is None:
        mrkv_hist = simulate_markov_history(cal.agg_transition,
                                            econ.mrkv_now_init,
                                            econ.act_T, k_hist)
    else:
        mrkv_hist = jnp.asarray(mrkv_hist)
    # Warm start: each outer iteration's EGM fixed point seeds the next one
    # (the damped rule update moves the perceived law only a little, so the
    # household fixed points are close — same trick as the bisection carry).
    from .ks_model import initial_ks_policy
    solve_hh = jax.jit(lambda af, p0: solve_ks_household(
        af, cal, tol=egm_tol, init_policy=p0))
    policy_seed = initial_ks_policy(cal)
    if sim_method == "panel":
        init = initial_panel(cal, agent.agent_count, econ.mrkv_now_init,
                             k_birth)
        run_panel = jax.jit(lambda pol, k, i0, kbar: simulate_panel(
            pol, cal, mrkv_hist, i0, k))   # kbar unused: realized prices
        carry_init = False    # reference parity: fresh birth panel per loop
    elif sim_method == "distribution":
        from .simulate import (
            initial_distribution_fan,
            make_sim_dist_grid,
            simulate_distribution_history,
        )
        degenerate = (bool(jnp.all(cal.prod_by_agg == cal.prod_by_agg[0]))
                      and bool(jnp.all(cal.urate_by_agg
                                       == cal.urate_by_agg[0])))
        if dist_pin_slope is None:
            dist_pin_slope = degenerate
        if dist_fan is None:
            dist_fan = 1
        dist_grid = make_sim_dist_grid(cal, dist_count)
        init = initial_distribution_fan(cal, dist_grid, econ.mrkv_now_init,
                                        dist_fan)
        # Pinned mode simulates under FIXED prices R(K-bar): the measured
        # path is then the household supply curve and the secant root is
        # the bisection engine's market-clearing condition — realized-price
        # feedback at this calibration stabilizes a truncation
        # pseudo-equilibrium instead (see simulate_distribution_history's
        # docstring for the measured mechanism).
        fixed_prices = bool(dist_pin_slope)
        run_panel = jax.jit(lambda pol, k, i0, kbar: jax.vmap(  # key unused
            lambda one: simulate_distribution_history(
                pol, cal, mrkv_hist, dist_grid, one,
                fixed_K=(kbar if fixed_prices else None)))(i0))
        # Carry each outer iteration's final distribution into the next
        # iteration's initial condition.  From a point mass at the
        # perfect-foresight steady state — where r sits exactly at the
        # 1/beta - 1 supply cap, so wealth mixes glacially — a single
        # act_T window never reaches the ergodic distribution: the
        # time-mean the rule update reads is transient-biased, and the
        # secant can settle on a truncation pseudo-equilibrium (measured
        # at the notebook calibration: r 4.32% > the 4.1667% cap with 2.3%
        # of mass clipped at the grid top).  Carrying the state makes the
        # effective chain length grow with the outer iteration count, the
        # same warm-start trick the EGM policy seed uses.  Not in fan
        # mode: its spread initial conditions ARE the identification.
        carry_init = dist_fan == 1
    else:
        raise ValueError(f"sim_method must be 'panel' or 'distribution', "
                         f"got {sim_method!r}")
    sim_init = init
    if dist_discard is None:
        dist_discard = (econ.t_discard if dist_fan in (None, 1)
                        else min(25, econ.act_T // 4))
    discard = (dist_discard if sim_method == "distribution"
               else econ.t_discard)
    # fingerprint AFTER parameter resolution so a checkpoint written under a
    # different simulation mode (panel vs distribution, fan/pin settings) is
    # refused, not silently resumed with the wrong rule class.  Run-control
    # fields (max_loops, verbose, tolerance) are excluded: resuming with a
    # larger iteration budget or tighter tolerance IS the resume use case —
    # it extends the same trajectory rather than defining a different run.
    # The initial-guess fields (intercept_prev/slope_prev) are excluded for
    # the same reason: a resume replaces the rule with the checkpoint's
    # wholesale, so the guess cannot affect the continued trajectory — and
    # gating on it made a checkpoint frozen under a cold config unusable
    # from a warm-started one (the round-4 committed-checkpoint fixture).
    import dataclasses
    econ_fp = tuple(sorted(
        (k, v) for k, v in dataclasses.asdict(econ).items()
        if k not in ("max_loops", "verbose", "tolerance",
                     "intercept_prev", "slope_prev")))
    fingerprint = config_fingerprint(agent, econ_fp, mrkv_hist,
                                     ks_employment, egm_tol, sim_method,
                                     dist_count, dist_fan, dist_discard,
                                     dist_pin_slope)
    pinned = sim_method == "distribution" and bool(dist_pin_slope)
    last_residual = [float("inf")]   # pinned mode's |g| at the last update
    if pinned:
        secant = _PinnedSecant()
        measured = jax.jit(
            lambda hist: jnp.log(hist.A_prev[..., discard:]).mean())

        def update(hist, af):
            i_cur = float(af.intercept[0])
            g = float(measured(hist)) - i_cur
            last_residual[0] = abs(g)
            i_new = secant.step(i_cur, g)
            new = AFuncParams(
                intercept=jnp.full((2,), i_new, dtype=cal.a_grid.dtype),
                slope=jnp.zeros((2,), dtype=cal.a_grid.dtype))
            # no regression ran: report NaN so records/verbose output never
            # claim a fit quality that does not exist
            return new, jnp.full((2,), jnp.nan, dtype=cal.a_grid.dtype)
    else:
        update = jax.jit(lambda hist, af: calc_afunc_update(
            hist, mrkv_hist, af, discard, econ.damping_fac))

    def finalize(history, final_panel):
        """Collapse the fan axis to the central (factor ~1.0) path so
        ``KSSolution.history``/``final_panel`` keep the single-path
        contract regardless of ``sim_method`` — and flag histogram-top
        truncation, which can silently absorb a divergent wealth tail and
        stabilize a pseudo-equilibrium (an r* above 1/beta - 1 is the
        telltale: true supply there is infinite)."""
        if sim_method == "distribution":   # fan axis exists even for fan=1
            c = dist_fan // 2
            history = jax.tree.map(lambda x: x[c], history)
            final_panel = jax.tree.map(lambda x: x[c], final_panel)
            top_mass = float(final_panel.dist[-1].sum())
            if top_mass > 1e-6:
                import warnings
                warnings.warn(
                    f"histogram top node holds {top_mass:.2e} mass — the "
                    f"ergodic wealth tail is being truncated at "
                    f"dist_grid[-1] and the reported equilibrium may be a "
                    f"clip artifact (check r* < 1/beta - 1; raise "
                    f"make_sim_dist_grid's top_factor or refine the "
                    f"solution grids)", stacklevel=2)
        return history, final_panel

    afunc = AFuncParams(
        intercept=jnp.asarray(econ.intercept_prev, dtype=cal.a_grid.dtype),
        slope=jnp.asarray(econ.slope_prev, dtype=cal.a_grid.dtype))
    if pinned:
        # pinned mode starts inside the rule class it iterates in: a
        # CONSTANT perceived capital.  A configured guess that already has
        # slope 0 is honored (warm starts from a committed converged
        # intercept — tests/fixture_configs.py); the default identity-rule
        # guess (slope 1) lies outside the class and its explosive
        # perception produces a fat-tailed transient the histogram would
        # truncate, so anything with nonzero slope falls back to the
        # analytic steady state.
        if all(abs(float(s)) < 1e-12 for s in econ.slope_prev):
            start = jnp.asarray(econ.intercept_prev,
                                dtype=cal.a_grid.dtype)
        else:
            start = jnp.full((2,), jnp.log(cal.steady_state.K),
                             dtype=cal.a_grid.dtype)
        afunc = AFuncParams(
            intercept=start,
            slope=jnp.zeros((2,), dtype=cal.a_grid.dtype))
    it_start = 0
    resumed_converged = False
    if checkpoint_path is not None and os.path.exists(checkpoint_path):
        ck = load_ks_checkpoint(checkpoint_path)
        if int(ck.seed) != seed or int(ck.fingerprint) != fingerprint:
            raise CheckpointMismatchError(
                f"checkpoint {checkpoint_path} was written by a different "
                f"run (seed {int(ck.seed)} vs {seed}, config fingerprint "
                f"mismatch: {int(ck.fingerprint) != fingerprint}) — delete "
                f"it or use a different checkpoint_path; refusing to "
                f"silently overwrite")
        afunc = AFuncParams(
            intercept=jnp.asarray(ck.intercept, dtype=cal.a_grid.dtype),
            slope=jnp.asarray(ck.slope, dtype=cal.a_grid.dtype))
        if pinned:
            # continue the same secant trajectory (bracket + last residual),
            # not a cold re-probe
            secant.restore(ck.secant)
        # a checkpoint is only "converged" relative to the tolerance it was
        # written under (excluded from the fingerprint so resumes may
        # tighten it); re-check against the CURRENT tolerance so a resume
        # with a tighter one keeps iterating instead of short-circuiting.
        # Pinned mode re-checks the fixed-point residual |g| too.
        resumed_converged = bool(ck.converged) and (
            float(ck.last_distance) < econ.tolerance) and (
            not pinned or float(ck.last_residual) < econ.tolerance)
        # always leave at least one pass to (re)generate the policy/history
        # the checkpoint does not carry
        it_start = max(0, min(int(ck.iteration), econ.max_loops - 1))
        # The carried simulation state rides in a sidecar (shape depends on
        # the dist config, which the fingerprint already gates); restoring
        # it keeps a resumed trajectory identical to the uninterrupted one.
        # The sidecar is written BEFORE the main checkpoint each iteration
        # and carries the iteration tag, so a half-written pair (kill
        # between the two writes) or a checkpoint copied without its
        # sidecar degrades to a LOUD approximate resume, never a silently
        # divergent "exact" one.
        sidecar = checkpoint_path + ".dist.npz"
        if carry_init:
            import warnings

            from ..utils.checkpoint import load_pytree
            if os.path.exists(sidecar):
                tag = None
                try:
                    # KS distribution sidecars are guarded by the
                    # checkpoint-tag match below (a torn pair is detected
                    # and degrades to an approximate resume); they are
                    # re-derivable simulation state, not part of the
                    # checksummed solution chain (DESIGN §9)
                    tag, state = load_pytree(  # integrity-ok
                        sidecar, (np.zeros((), np.int64), sim_init))
                except ValueError as e:
                    # structural mismatch (e.g. a sidecar written by an
                    # older state layout): the promised degradation is a
                    # LOUD approximate resume, not a crash
                    warnings.warn(
                        f"checkpoint sidecar {sidecar} does not match the "
                        f"current panel-state structure ({e}) — resuming "
                        f"from a fresh initial distribution; the continued "
                        f"trajectory is approximate, not exact",
                        stacklevel=2)
                if tag is not None and int(tag) == int(ck.iteration):
                    sim_init = jax.tree.map(
                        lambda leaf, like: jnp.asarray(leaf,
                                                       dtype=like.dtype),
                        state, sim_init)
                elif tag is not None:
                    warnings.warn(
                        f"checkpoint sidecar {sidecar} is tagged for "
                        f"iteration {int(tag)} but the checkpoint is at "
                        f"{int(ck.iteration)} (interrupted between the "
                        f"two writes?) — resuming from a fresh initial "
                        f"distribution; the continued trajectory is "
                        f"approximate, not exact", stacklevel=2)
            elif int(ck.iteration) > 0:
                warnings.warn(
                    f"no {sidecar} next to the checkpoint — resuming from "
                    f"a fresh initial distribution; the continued "
                    f"trajectory is approximate, not exact", stacklevel=2)
        if econ.verbose:
            print(f"[ks] resumed from {checkpoint_path} at outer "
                  f"iteration {it_start}"
                  + (" (already converged)" if resumed_converged else ""))

    if resumed_converged:
        # idempotent reload: rebuild the policy/history the checkpoint does
        # not carry, but leave the converged rule (and the file) untouched
        with timer.phase("solve"):
            policy, _, _, egm_status = jax.block_until_ready(
                solve_hh(afunc, policy_seed))
        with timer.phase("simulate"):
            history, final_panel = jax.block_until_ready(
                run_panel(policy, k_panel, sim_init,
                          jnp.exp(afunc.intercept[0])))
        history, final_panel = finalize(history, final_panel)
        return KSSolution(afunc=afunc, policy=policy, calibration=cal,
                          history=history, mrkv_hist=mrkv_hist,
                          final_panel=final_panel,
                          dist_grid=(dist_grid if sim_method == "distribution"
                                     else None),
                          records=[], converged=True,
                          status=int(egm_status))

    records: List[KSIterationRecord] = []
    history = None
    final_panel = None
    policy = None
    converged = False
    for it in range(it_start, econ.max_loops):
        iter_sw = Stopwatch()
        with timer.phase("solve"):
            policy, egm_iters, _, egm_status = _device(
                f"KS household solve (iter {it})",
                lambda: jax.block_until_ready(
                    solve_hh(afunc, policy_seed)))
            policy_seed = policy
        k_it = jax.random.fold_in(k_panel, it) if resample_each_iteration \
            else k_panel
        with timer.phase("simulate"):
            history, final_panel = _device(
                f"KS panel simulation (iter {it})",
                lambda: jax.block_until_ready(
                    run_panel(policy, k_it, sim_init,
                              jnp.exp(afunc.intercept[0]))))
            if carry_init:
                sim_init = final_panel
        with timer.phase("regress"):
            new_afunc, rsq = jax.block_until_ready(update(history, afunc))
        if not (bool(jnp.all(jnp.isfinite(new_afunc.intercept)))
                and bool(jnp.all(jnp.isfinite(new_afunc.slope)))):
            from ..obs.runtime import emit_event

            emit_event("SOLVER_DIVERGED", where="ks_outer", iteration=it,
                       status="NONFINITE")
            raise SolverDivergenceError(
                f"KS outer iteration {it}: saving-rule regression produced "
                f"non-finite parameters (intercept={new_afunc.intercept}, "
                f"slope={new_afunc.slope}). Usually an aggregate state never "
                f"appears in the post-discard window — increase act_T or "
                f"decrease t_discard.",
                status=NONFINITE,
                trail=[dataclasses.asdict(r) for r in records] + [{
                    "iteration": it,
                    "intercept": [float(x) for x in new_afunc.intercept],
                    "slope": [float(x) for x in new_afunc.slope],
                    "egm_status": int(egm_status),
                    "egm_status_name": status_name(egm_status),
                }])
        distance = float(jnp.max(jnp.maximum(
            jnp.abs(new_afunc.intercept - afunc.intercept),
            jnp.abs(new_afunc.slope - afunc.slope))))
        afunc = new_afunc
        rec = KSIterationRecord(
            iteration=it,
            intercept=[float(x) for x in afunc.intercept],
            slope=[float(x) for x in afunc.slope],
            r_squared=[float(x) for x in rsq],
            distance=distance, egm_iters=int(egm_iters),
            wall_seconds=iter_sw.elapsed(),
            egm_status=int(egm_status))
        records.append(rec)
        if econ.verbose:
            print(f"[ks] iter {it}: intercept={rec.intercept} "
                  f"slope={rec.slope} r2={rec.r_squared} dist={distance:.5f}")
        if callback is not None:
            callback(rec)
        # Pinned mode must ALSO clear the fixed-point residual |g|: near the
        # 1/beta - 1 cap the supply map's log-slope is O(100) (measured
        # ~-190 at the notebook calibration), so a small secant STEP does
        # not imply a small residual — the step-only criterion accepted a
        # point with |g| = 0.56 (measured), i.e. supply 43% off the
        # perceived stock.
        if distance < econ.tolerance and (
                not pinned or last_residual[0] < econ.tolerance):
            converged = True
        if checkpoint_path is not None:
            # sidecar first: the main checkpoint is the commit point, so a
            # kill between the writes leaves (old checkpoint, new sidecar)
            # — detected on resume via the iteration tag
            if carry_init:
                from ..utils.checkpoint import save_pytree
                save_pytree(checkpoint_path + ".dist.npz",
                            (np.asarray(it + 1, np.int64), sim_init))
            save_ks_checkpoint(checkpoint_path, afunc, it + 1, seed,
                               converged, fingerprint,
                               secant=secant.to_array() if pinned else None,
                               last_distance=distance,
                               last_residual=last_residual[0])
        if converged:
            break
        # Outer-iteration boundary: the checkpoint (when configured) was
        # just flushed, so a shutdown request exits HERE with resumable
        # state instead of dying inside the next iteration's launches.
        raise_if_interrupted("KS outer loop", checkpoint_path,
                             progress={"iteration": it + 1,
                                       "max_loops": econ.max_loops,
                                       "distance": distance})

    history, final_panel = finalize(history, final_panel)
    # worst-of-run health code: the outer loop's own exit combined with
    # the last inner EGM solve's (a NONFINITE anywhere raised above)
    last_egm = records[-1].egm_status if records else CONVERGED
    status = int(combine_status(CONVERGED if converged else MAX_ITER,
                                last_egm))
    return KSSolution(afunc=afunc, policy=policy, calibration=cal,
                      history=history, mrkv_hist=mrkv_hist,
                      final_panel=final_panel,
                      dist_grid=(dist_grid if sim_method == "distribution"
                                 else None),
                      records=records, converged=converged, status=status)
