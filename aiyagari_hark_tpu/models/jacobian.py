"""Sequence-space Jacobians: linearized general-equilibrium dynamics by
automatic differentiation of the transition path map.

The modern workhorse of heterogeneous-agent macro (Auclert, Bardóczy,
Rognlie & Straub 2021, "Using the Sequence-Space Jacobian to Solve and
Estimate Heterogeneous-Agent Models") represents the economy as maps
between perfect-foresight *paths* and differentiates them around the
stationary equilibrium: the household block's Jacobian ``J[s, t] =
∂(aggregate at s)/∂(price at t)`` turns nonlinear MIT-shock computation
(``models/transition.py``, ~60 damped fixed-point iterations per shock)
into ONE linear solve per shock — impulse responses to *any* foreseen
shock path, simulation of first-order aggregate dynamics, and likelihood
evaluation all become matrix algebra.

The reference has nothing in this family (its only dynamics is the
stochastic Krusell-Smith simulation, SURVEY.md §3.1).  Where the original
SSJ toolkit hand-derives the household Jacobian with its "fake news"
algorithm (NumPy, forward accumulation), here the whole backward-scan +
forward-scan path map (``transition.household_path_response``) is already
a differentiable JAX program, so the exact Jacobian is one
``jax.jacrev`` — reverse-mode through both ``lax.scan``s, T cotangent
sweeps batched by XLA, no hand-derived chain rule to maintain as the
model grows (two-asset blocks, life-cycle blocks, …).

Shapes: all Jacobians are dense ``[T, T]`` — row s = response date,
column t = shock date.  Columns with t > s are *anticipation* effects
(households react today to foreseen future prices); they are generically
nonzero here, which is exactly what distinguishes these objects from a
VAR.  Row 0 of the capital Jacobians is zero because K_0 is
predetermined (``household_path_response`` pins it to E[a] under the
initial distribution).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from . import firm
from .equilibrium import EquilibriumResult
from .household import SimpleModel, aggregate_labor
from .transition import household_path_response


class HouseholdJacobians(NamedTuple):
    """Partial-equilibrium (household-block) path Jacobians at the
    stationary equilibrium: responses of aggregate capital-in-production
    K_s and aggregate consumption C_s to perfectly foreseen one-date
    price perturbations dr_t, dw_t."""

    k_r: jnp.ndarray     # [T, T]  ∂K_s/∂r_t
    k_w: jnp.ndarray     # [T, T]  ∂K_s/∂w_t
    c_r: jnp.ndarray     # [T, T]  ∂C_s/∂r_t
    c_w: jnp.ndarray     # [T, T]  ∂C_s/∂w_t


class SequenceJacobians(NamedTuple):
    """General-equilibrium sequence-space Jacobians wrt a foreseen TFP
    path, plus the building blocks they were assembled from."""

    g_k: jnp.ndarray     # [T, T]  dK_s/dZ_t in general equilibrium
    g_r: jnp.ndarray     # [T, T]  dr_s/dZ_t
    g_w: jnp.ndarray     # [T, T]  dw_s/dZ_t
    g_c: jnp.ndarray     # [T, T]  dC_s/dZ_t
    g_y: jnp.ndarray     # [T, T]  dY_s/dZ_t (output)
    household: HouseholdJacobians
    h_k: jnp.ndarray     # [T, T]  ∂H_s/∂K_t of the K-path fixed-point map
    h_z: jnp.ndarray     # [T, T]  ∂H_s/∂Z_t
    k_ss: jnp.ndarray
    r_ss: jnp.ndarray
    w_ss: jnp.ndarray
    y_ss: jnp.ndarray


class LinearIRF(NamedTuple):
    """First-order impulse responses to one foreseen TFP path."""

    dk: jnp.ndarray      # [T] capital deviation from steady state
    dr: jnp.ndarray      # [T] net-interest-rate deviation
    dw: jnp.ndarray      # [T] wage deviation
    dc: jnp.ndarray      # [T] aggregate-consumption deviation
    dy: jnp.ndarray      # [T] output deviation


def household_jacobians(model: SimpleModel, disc_fac, crra,
                        eq: EquilibriumResult,
                        horizon: int) -> HouseholdJacobians:
    """Differentiate the household block's path map at the stationary
    equilibrium: one ``jax.jacrev`` of ``household_path_response`` wrt
    the price paths, evaluated at the flat steady-state paths.

    Cost: 2·T reverse sweeps of (backward EGM scan + forward histogram
    scan), batched by XLA — seconds at test sizes on CPU.  For well
    converged Jacobians use an f64 steady state from
    ``solve_bisection_equilibrium`` (the map is evaluated AT ``eq``; any
    residual in ``eq``'s fixed point shows up as a small constant, not a
    derivative error, since autodiff differentiates the exact discretized
    program).
    """
    dtype = model.a_grid.dtype
    r_flat = jnp.full((horizon,), eq.r_star, dtype=dtype)
    w_flat = jnp.full((horizon,), eq.wage, dtype=dtype)

    def response(r_path, w_path):
        return household_path_response(r_path, w_path, model, disc_fac,
                                       crra, eq.distribution, eq.policy)

    (k_r, k_w), (c_r, c_w) = jax.jacrev(response, argnums=(0, 1))(r_flat,
                                                                  w_flat)
    return HouseholdJacobians(k_r=k_r, k_w=k_w, c_r=c_r, c_w=c_w)


def sequence_jacobians(model: SimpleModel, disc_fac, crra, cap_share,
                       depr_fac, eq: EquilibriumResult,
                       horizon: int) -> SequenceJacobians:
    """Assemble the general-equilibrium Jacobians wrt a TFP path.

    Chain rule around the firm block: prices are elementwise functions of
    (K_t, Z_t), so with scalar steady-state derivatives (r_K, r_Z, w_K,
    w_Z) the K-path fixed-point map H(K, Z) = household(r(K,Z), w(K,Z))
    has ``H_K = r_K·J^{K,r} + w_K·J^{K,w}`` and ``H_Z = r_Z·J^{K,r} +
    w_Z·J^{K,w}``; the equilibrium response is the implicit-function
    solve ``G = (I − H_K)^{-1} H_Z`` (nonsingular: row 0 of H_K is zero
    because K_0 is predetermined).  Everything downstream (prices,
    consumption) is more chain rule.
    """
    hh = household_jacobians(model, disc_fac, crra, eq, horizon)
    dtype = model.a_grid.dtype
    labor = aggregate_labor(model)
    one = jnp.asarray(1.0, dtype=dtype)

    def r_of(k, z):
        return firm.interest_factor(k / labor, cap_share, depr_fac,
                                    z) - 1.0

    def w_of(k, z):
        return firm.wage_rate(k / labor, cap_share, z)

    def y_of(k, z):
        return firm.output(k, labor, cap_share, z)

    r_k, r_z = jax.grad(r_of, argnums=(0, 1))(eq.capital, one)
    w_k, w_z = jax.grad(w_of, argnums=(0, 1))(eq.capital, one)
    y_k, y_z = jax.grad(y_of, argnums=(0, 1))(eq.capital, one)

    h_k = r_k * hh.k_r + w_k * hh.k_w
    h_z = r_z * hh.k_r + w_z * hh.k_w
    eye = jnp.eye(horizon, dtype=dtype)
    g_k = jnp.linalg.solve(eye - h_k, h_z)
    g_r = r_k * g_k + r_z * eye
    g_w = w_k * g_k + w_z * eye
    g_y = y_k * g_k + y_z * eye
    g_c = (r_k * hh.c_r + w_k * hh.c_w) @ g_k + (r_z * hh.c_r
                                                 + w_z * hh.c_w)
    return SequenceJacobians(g_k=g_k, g_r=g_r, g_w=g_w, g_c=g_c, g_y=g_y,
                             household=hh, h_k=h_k, h_z=h_z,
                             k_ss=eq.capital, r_ss=eq.r_star, w_ss=eq.wage,
                             y_ss=y_of(eq.capital, one))


def linear_impulse_response(jac: SequenceJacobians,
                            dz_path: jnp.ndarray) -> LinearIRF:
    """First-order GE impulse responses to a foreseen TFP deviation path
    ``dz_path`` [T] (e.g. ``0.02 * 0.8**t``): four matvecs.  Compare with
    ``transition.solve_transition(prod_path=1 + dz_path)`` — the
    nonlinear answer this linearizes (they agree to O(‖dz‖²);
    ``tests/test_jacobian.py`` checks it)."""
    dz = jnp.asarray(dz_path, dtype=jac.g_k.dtype)
    return LinearIRF(dk=jac.g_k @ dz, dr=jac.g_r @ dz, dw=jac.g_w @ dz,
                     dc=jac.g_c @ dz, dy=jac.g_y @ dz)


# ---------------------------------------------------------------------------
# Linearized stochastic aggregate dynamics: once TFP follows an AR(1)
# log-deviation process dz_t = rho dz_{t-1} + eps_t, certainty equivalence
# makes the date-0 innovation IRF (response to the foreseen path rho^s)
# the MA(infinity) kernel of every aggregate, and business-cycle second
# moments are inner products of those kernels — no simulation, no
# sampling noise.  This is the "estimate" half of the sequence-space
# method: a likelihood needs exactly these model-implied covariances.
# ---------------------------------------------------------------------------


class BusinessCycleMoments(NamedTuple):
    """Model-implied second moments of the linearized aggregates under
    AR(1) TFP innovations with std ``sigma_eps``."""

    std: dict            # {"k","r","w","c","y","z"} -> unconditional std
    autocorr1: dict      # first-order autocorrelations
    corr_with_y: dict    # contemporaneous correlations with output


def innovation_irf(jac: SequenceJacobians, rho: float) -> LinearIRF:
    """IRF to a UNIT TFP innovation at date 0 under AR(1) persistence
    ``rho``: the foreseen path is rho^s, so this is one matvec per
    aggregate.  In the stationary linear model the same kernel, shifted,
    is the response to an innovation at any date — i.e. the MA
    coefficients.  Validity of the truncation-at-T reading is checked by
    the horizon-invariance and IRF-decay tests in
    ``tests/test_jacobian.py``."""
    T = jac.g_k.shape[0]
    rho = jnp.asarray(rho, dtype=jac.g_k.dtype)
    return linear_impulse_response(jac, rho ** jnp.arange(T))


def _ma_moments(kernels: dict, sigma_eps) -> BusinessCycleMoments:
    """Second moments from MA kernels: for X_t = sum_j m_j eps_{t-j},
    cov(X_t, Y_{t-k}) = sigma² sum_j mX_{j+k} mY_j, truncated at the
    Jacobian horizon (the kernels have decayed — the IRF-decay test pins
    this)."""

    def cov(mx, my, lag=0):
        return sigma_eps ** 2 * jnp.sum(mx[lag:] * my[:mx.shape[0] - lag])

    std = {k: jnp.sqrt(cov(m, m)) for k, m in kernels.items()}
    autocorr1 = {k: cov(m, m, lag=1) / cov(m, m)
                 for k, m in kernels.items()}
    my = kernels["y"]
    corr_with_y = {k: cov(m, my) / (std[k] * std["y"])
                   for k, m in kernels.items()}
    return BusinessCycleMoments(std=std, autocorr1=autocorr1,
                                corr_with_y=corr_with_y)


def _ma_kernels(jac: SequenceJacobians, rho: float) -> dict:
    """The MA kernels of every aggregate (plus the exogenous z itself)
    under AR(1) TFP — the ONE place the kernel dict is built, shared by
    the analytic moments and the simulator so they cannot diverge."""
    irf = innovation_irf(jac, rho)
    T = jac.g_k.shape[0]
    z_kernel = jnp.asarray(rho, dtype=jac.g_k.dtype) ** jnp.arange(T)
    return {"k": irf.dk, "r": irf.dr, "w": irf.dw, "c": irf.dc,
            "y": irf.dy, "z": z_kernel}


def business_cycle_moments(jac: SequenceJacobians, rho: float,
                           sigma_eps: float) -> BusinessCycleMoments:
    """Unconditional second moments of (K, r, w, C, Y, Z) in the
    linearized economy with AR(1) TFP (persistence ``rho``, innovation
    std ``sigma_eps``) — closed form from the innovation IRF."""
    return _ma_moments(_ma_kernels(jac, rho), sigma_eps)


class ShockFit(NamedTuple):
    rho: jnp.ndarray
    sigma_eps: jnp.ndarray
    loss: jnp.ndarray        # final squared relative moment distance
    iterations: jnp.ndarray
    converged: jnp.ndarray


def fit_shock_process(jac: SequenceJacobians, target_std_y,
                      target_autocorr1_y, max_iter: int = 50,
                      tol: float | None = None) -> ShockFit:
    """Estimate the AR(1) TFP process (rho, sigma_eps) from observed
    output moments — the simplest instance of sequence-space estimation
    (Auclert et al. 2021 §5): model moments are *differentiable*
    functions of the shock parameters through the MA kernels, so the
    two-moment match is a square system solved by Newton with
    ``jax.jacfwd`` — no simulation anywhere in the loop.

    Matches (std(Y), autocorr1(Y)).  Parameters live in unconstrained
    space (logit rho, log sigma); residuals are relative so the two
    targets are comparably scaled; steps are clipped to ±1 in the
    unconstrained space to keep early iterations inside the basin.  The
    Jacobians ``jac`` are fixed — only the shock process is
    re-estimated, which is exactly the division of labor that makes
    sequence-space estimation fast (the expensive household block
    enters through kernels computed once)."""
    dtype = jac.g_k.dtype
    if tol is None:
        # squared relative residuals bottom out near dtype epsilon²; an
        # f64 tolerance on f32 would burn max_iter without certifying
        # (the same hazard _bisection_setup documents)
        tol = 1e-12 if dtype == jnp.float64 else 1e-10
    t_std = jnp.asarray(target_std_y, dtype=dtype)
    t_ac = jnp.asarray(target_autocorr1_y, dtype=dtype)
    T = jac.g_k.shape[0]
    idx = jnp.arange(T, dtype=dtype)

    def residuals(theta):
        rho = jax.nn.sigmoid(theta[0])
        sigma = jnp.exp(theta[1])
        # inline MA moments for Y only (differentiable in rho via rho**t)
        kernel = jac.g_y @ (rho ** idx)
        var = sigma ** 2 * jnp.sum(kernel * kernel)
        cov1 = sigma ** 2 * jnp.sum(kernel[1:] * kernel[:-1])
        return jnp.asarray([jnp.sqrt(var) / t_std - 1.0,
                            (cov1 / var - t_ac) / jnp.maximum(t_ac, 0.1)])

    jac_fn = jax.jacfwd(residuals)

    def loss_of(r):
        return jnp.sum(r * r)

    def cond(state):
        _, r, it = state
        return (loss_of(r) > tol) & (it < max_iter)

    def body(state):
        theta, r, it = state
        # Levenberg-damped Gauss-Newton instead of a raw 2x2 solve: near the
        # rho->1 boundary the Jacobian goes singular and jnp.linalg.solve
        # would propagate NaN into theta (silent NaN exit with
        # converged=False); the tiny trace-scaled ridge keeps the system
        # nonsingular while matching Newton to ~1e-9 when well-conditioned.
        J = jac_fn(theta)
        JtJ = J.T @ J
        lam = 1e-9 * (jnp.trace(JtJ) + 1.0)
        step = jnp.linalg.solve(JtJ + lam * jnp.eye(2, dtype=dtype), J.T @ r)
        theta = theta - jnp.clip(step, -1.0, 1.0)
        return theta, residuals(theta), it + 1

    theta0 = jnp.asarray([jnp.log(0.9 / 0.1), jnp.log(0.01)], dtype=dtype)
    theta, r, iters = jax.lax.while_loop(
        cond, body, (theta0, residuals(theta0), jnp.asarray(0)))
    loss = loss_of(r)
    return ShockFit(rho=jax.nn.sigmoid(theta[0]),
                    sigma_eps=jnp.exp(theta[1]), loss=loss,
                    iterations=iters, converged=loss <= tol)


# ---------------------------------------------------------------------------
# The labor-supply economy: the same sequence-space construction on the
# JOINT (K, L) path map — hours become an equilibrium kernel, so the
# linearized model produces the hours/output statistics the fixed-labor
# block cannot (std(hours)/std(Y), hours-output correlation).
# ---------------------------------------------------------------------------


class LaborSequenceJacobians(NamedTuple):
    """GE Jacobians of the labor economy wrt a foreseen TFP path."""

    g_k: jnp.ndarray     # [T, T] dK/dZ
    g_l: jnp.ndarray     # [T, T] d(effective labor)/dZ
    g_h: jnp.ndarray     # [T, T] d(mean hours)/dZ
    g_c: jnp.ndarray     # [T, T] dC/dZ
    g_y: jnp.ndarray     # [T, T] dY/dZ
    k_ss: jnp.ndarray
    l_ss: jnp.ndarray
    h_ss: jnp.ndarray
    y_ss: jnp.ndarray


def labor_sequence_jacobians(model, disc_fac, crra, cap_share, depr_fac,
                             eq, horizon: int) -> LaborSequenceJacobians:
    """Differentiate the labor economy's joint path map
    (``labor.labor_path_map``) with one ``jax.jacrev`` and solve the
    2T-by-2T implicit-function system

        [dK; dL] = (I - F_x)^{-1} F_z dZ,

    where F maps stacked (K, L) paths to their household-implied values
    (K_0 predetermined, L free).  Consumption, hours, and output
    responses follow by chain rule.  ``eq`` is a
    ``labor.LaborEquilibrium``; everything is evaluated at its
    stationary point."""
    from .labor import labor_path_map

    dtype = model.base.a_grid.dtype
    T = horizon
    k_flat = jnp.full((T,), eq.capital, dtype=dtype)
    l_flat = jnp.full((T,), eq.effective_labor, dtype=dtype)
    z_flat = jnp.ones((T,), dtype=dtype)

    def stacked(x, z):
        k_i, l_i, hours, c = labor_path_map(
            x[:T], x[T:], z, model, disc_fac, crra, cap_share, depr_fac,
            eq.distribution, eq.policy)
        return jnp.concatenate([k_i, l_i]), hours, c

    x0 = jnp.concatenate([k_flat, l_flat])
    (f_x, f_z), (h_x, h_z), (c_x, c_z) = jax.jacrev(
        stacked, argnums=(0, 1))(x0, z_flat)
    eye = jnp.eye(2 * T, dtype=dtype)
    g_x = jnp.linalg.solve(eye - f_x, f_z)       # [2T, T]
    g_k, g_l = g_x[:T], g_x[T:]
    g_h = h_x @ g_x + h_z
    g_c = c_x @ g_x + c_z

    def y_of(k, l, z):
        return firm.output(k, l, cap_share, z)

    y_k, y_l, y_z = jax.grad(y_of, argnums=(0, 1, 2))(
        eq.capital, eq.effective_labor, jnp.asarray(1.0, dtype=dtype))
    g_y = y_k * g_k + y_l * g_l + y_z * jnp.eye(T, dtype=dtype)
    return LaborSequenceJacobians(
        g_k=g_k, g_l=g_l, g_h=g_h, g_c=g_c, g_y=g_y,
        k_ss=eq.capital, l_ss=eq.effective_labor, h_ss=eq.mean_hours,
        y_ss=y_of(eq.capital, eq.effective_labor, 1.0))


def labor_business_cycle_moments(jac: LaborSequenceJacobians, rho: float,
                                 sigma_eps: float) -> BusinessCycleMoments:
    """Second moments of the linearized labor economy under AR(1) TFP —
    now including hours and effective labor, so the RBC ratios
    (std(hours)/std(Y), corr(hours, Y)) are model outputs."""
    dtype = jac.g_k.dtype
    T = jac.g_k.shape[0]
    rho_t = jnp.asarray(rho, dtype=dtype) ** jnp.arange(T)
    kernels = {"k": jac.g_k @ rho_t, "l": jac.g_l @ rho_t,
               "h": jac.g_h @ rho_t, "c": jac.g_c @ rho_t,
               "y": jac.g_y @ rho_t, "z": rho_t}
    return _ma_moments(kernels, sigma_eps)


def simulate_linear(jac: SequenceJacobians, rho: float, sigma_eps: float,
                    length: int, key) -> dict:
    """Monte-Carlo sample path of the linearized aggregates: draw
    innovations, convolve with the MA kernels.  Mainly a cross-check on
    ``business_cycle_moments`` (the analytic moments are exact; the
    simulated ones carry O(1/sqrt(length)) sampling error) and a way to
    produce aggregate paths for external consumers.  Returns
    ``{"k","r","w","c","y","z"}`` -> [length] deviation paths (the first
    ``T`` entries carry kernel warm-up and are dropped)."""
    T = jac.g_k.shape[0]
    eps = sigma_eps * jax.random.normal(key, (length + T,),
                                        dtype=jac.g_k.dtype)
    out = {}
    for name, m in _ma_kernels(jac, rho).items():
        full = jnp.convolve(eps, m, mode="full")[:length + T]
        out[name] = full[T:]
    return out
