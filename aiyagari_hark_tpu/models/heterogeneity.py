"""Preference heterogeneity: general equilibrium with a distribution of
discount factors ("beta-dist" economies).

The homogeneous Aiyagari model famously concentrates too little wealth
(the reference's own Lorenz comparison against the SCF shows it,
`Aiyagari-HARK.py:299-335`); Krusell & Smith (1998, §3) and Carroll,
Slacalek, Tokuoka & White (2017) fix this with a small spread of
discount factors — patient types accumulate most of the wealth, matching
the empirical concentration.  The reference repo has no machinery for
this at all (one agent type, one beta).

TPU shape: a type is just one more batch axis.  The per-type capital
supply A_j(r) is the existing ``household_capital_supply`` vmapped over
``disc_fac``; aggregate supply is the population-weighted sum; the
equilibrium is the same fixed-trip bisection as the homogeneous engine.
J types cost one vmap lane each inside the same jitted program — no
Python loop over types, and the whole solve remains vmappable over
calibration cells (a beta-dist Table II sweep is a nested vmap).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from . import firm
from .equilibrium import _bisect, _bisection_setup, household_capital_supply
from .household import (
    HouseholdPolicy,
    SimpleModel,
    aggregate_labor,
)


class HeterogeneousEquilibrium(NamedTuple):
    r_star: jnp.ndarray
    wage: jnp.ndarray
    capital: jnp.ndarray         # aggregate K = sum_j weight_j * A_j(r*)
    labor: jnp.ndarray
    saving_rate: jnp.ndarray
    excess: jnp.ndarray
    type_capital: jnp.ndarray    # [J] per-type mean asset holdings
    policies: HouseholdPolicy    # [J, ...] stacked per-type policies
    distributions: jnp.ndarray   # [J, D, N] per-type stationary wealth
    weights: jnp.ndarray         # [J] population shares (echoed back)
    bisect_iters: jnp.ndarray
    status: jnp.ndarray = 0      # solver_health code of the bisection exit


def uniform_beta_types(center: float, spread: float,
                       n_types: int) -> jnp.ndarray:
    """Carroll et al. (2017)-style discrete uniform approximation of a
    beta distribution on ``[center - spread, center + spread]``: type j
    sits at the midpoint of the j-th of ``n_types`` equal bands."""
    j = jnp.arange(n_types, dtype=jnp.result_type(float))
    return center - spread + spread * (2.0 * j + 1.0) / n_types


def heterogeneous_capital_supply(r, model: SimpleModel, disc_facs,
                                 weights, crra, cap_share, depr_fac,
                                 prod=1.0, egm_tol=1e-6, dist_tol=1e-11):
    """Population capital supply at rate ``r``: vmap the per-type supply
    over the discount-factor axis and weight (weights are normalized to
    population shares internally, so counts are fine).  Returns
    (aggregate supply, per-type supply [J], stacked policies, stacked
    distributions, wage)."""
    disc_facs = jnp.asarray(disc_facs, dtype=model.a_grid.dtype)
    weights = jnp.asarray(weights, dtype=model.a_grid.dtype)
    weights = weights / jnp.sum(weights)

    def one_type(beta):
        ev = household_capital_supply(r, model, beta, crra, cap_share,
                                      depr_fac, prod, egm_tol=egm_tol,
                                      dist_tol=dist_tol)
        return ev.supply, ev.policy, ev.distribution, ev.wage

    supply_j, policies, dists, wage_j = jax.vmap(one_type)(disc_facs)
    return (jnp.sum(weights * supply_j), supply_j, policies, dists,
            wage_j[0])


def solve_heterogeneous_equilibrium(model: SimpleModel, disc_facs,
                                    weights, crra, cap_share, depr_fac,
                                    prod=1.0, r_tol: float | None = None,
                                    max_bisect: int = 60,
                                    egm_tol: float | None = None,
                                    dist_tol: float | None = None
                                    ) -> HeterogeneousEquilibrium:
    """Bisect r until the capital market clears against the
    population-weighted supply of all discount-factor types.

    The stationarity requirement caps the most patient type:
    ``max(disc_facs) * (1 + r*) < 1`` must hold, so the bisection's upper
    bracket is set by ``max(disc_facs)`` (the impatient types just hold
    less wealth).  Weights are normalized internally.

    Degenerate check (tests): with all types at the same beta this
    reproduces ``solve_bisection_equilibrium`` exactly.
    """
    disc_facs = jnp.asarray(disc_facs, dtype=model.a_grid.dtype)
    weights = jnp.asarray(weights, dtype=model.a_grid.dtype)
    weights = weights / jnp.sum(weights)
    # the binding stationarity bound is the most patient type's; keep it
    # traced so the whole solver jits/vmaps (a beta-dist sweep is a
    # nested vmap over calibration cells)
    r_tol, egm_tol, dist_tol, r_lo, r_hi = _bisection_setup(
        model, jnp.max(disc_facs), depr_fac, r_tol, egm_tol, dist_tol)
    labor = aggregate_labor(model)

    def excess_supply(r):
        supply, _, _, _, _ = heterogeneous_capital_supply(
            r, model, disc_facs, weights, crra, cap_share, depr_fac,
            prod, egm_tol=egm_tol, dist_tol=dist_tol)
        demand = firm.k_to_l_from_r(r, cap_share, depr_fac, prod) * labor
        return supply - demand

    r_star, iters, status = _bisect(excess_supply, r_lo, r_hi, r_tol,
                                    max_bisect)

    supply, supply_j, policies, dists, wage = heterogeneous_capital_supply(
        r_star, model, disc_facs, weights, crra, cap_share, depr_fac,
        prod, egm_tol=egm_tol, dist_tol=dist_tol)
    demand = firm.k_to_l_from_r(r_star, cap_share, depr_fac, prod) * labor
    y = firm.output(supply, labor, cap_share, prod)
    return HeterogeneousEquilibrium(
        r_star=r_star, wage=wage, capital=supply, labor=labor,
        saving_rate=depr_fac * supply / y, excess=supply - demand,
        type_capital=supply_j, policies=policies, distributions=dists,
        weights=weights, bisect_iters=iters, status=status)


def population_distribution(eq: HeterogeneousEquilibrium) -> jnp.ndarray:
    """The economy-wide stationary wealth distribution: the
    population-weighted mixture of the per-type distributions, on the
    shared ``dist_grid`` — feed it to ``utils.stats`` for Lorenz/Gini."""
    return jnp.einsum("j,jdn->dn", eq.weights, eq.distributions)
