"""Value functions and welfare analytics for the compact household model.

The reference *intends* to carry value-function machinery — ``MargValueFunc2D``
is defined at ``Aiyagari_Support.py:71-102`` — but never instantiates it
(dead component D1, SURVEY.md §2.2), and its one live value object is the
marginal-value wrapper rebuilt inside the solver
(``MargValueFuncCRRA``, ``Aiyagari_Support.py:1514-1515``).  This module is
the *working* replacement: given a converged consumption policy, recover the
level value function v(m, s) by policy evaluation, expose the marginal value
through the envelope condition, and provide the welfare comparisons (aggregate
welfare, consumption equivalents) the level function exists for.

Numerics: v is stored through the *constant-equivalent consumption*
transform ``vnvrs = u^{-1}((1 - beta) v)`` — the constant consumption stream
whose discounted utility equals v (a sharper version of HARK's "vNvrs"
inverse-utility trick).  Along any policy with consumption proportional to
resources, v is homogeneous of the same degree as u, so this vnvrs is
*linear* in m for every CRRA including log (plain ``u^{-1}(v)`` is linear
only for crra != 1; for log utility it is ``m^{1/(1-beta)}``, hopeless for
piecewise-linear knots).  Storing raw v instead would put a ``-1e7``-scale
kink at the borrowing-constraint knot and poison every interpolation below
the second gridpoint.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..ops.interp import interp1d, interp1d_rowwise
from ..ops.utility import (
    crra_utility,
    inverse_utility,
    marginal_utility,
)
from .household import HouseholdPolicy, SimpleModel


class ValueFunction(NamedTuple):
    """v(m, s) as data: per-state knots on the policy's endogenous grid.
    ``vnvrs_knots`` holds the constant-equivalent consumption
    ``u^{-1}((1-beta) v)``; evaluate with ``value_at``.  ``disc_fac`` rides
    along because the transform needs it."""

    m_knots: jnp.ndarray       # [N, K] same knots as the policy
    vnvrs_knots: jnp.ndarray   # [N, K] u^{-1}((1-beta) v) at the knots
    disc_fac: jnp.ndarray      # scalar beta


def _clamp_positive(x):
    """vnvrs is a consumption equivalent, nonnegative by construction;
    linear extrapolation below the borrowing-constraint knot can cross zero
    (query m' = 0 happens when W = 0), which u(.) would turn into NaN —
    clamp to the smallest positive normal instead (u then reports the
    appropriately catastrophic value).  Single clamping policy for every
    vnvrs evaluation path."""
    return jnp.maximum(x, jnp.finfo(x.dtype).tiny)


def _eval_vnvrs(vf_m, vf_vnvrs, m):
    """Interpolate vnvrs rowwise ([N, ...] queries with per-state knots)."""
    return _clamp_positive(interp1d_rowwise(m, vf_m, vf_vnvrs))


def augment_constrained_knots(m_knots, c_knots, borrow_limit,
                              constrained_knots: int):
    """Insert log-spaced knots into the borrowing-constrained segment
    (below the first endogenous gridpoint, where the exact policy is
    ``c = m - b``): the policy is linear there, but any *value* object
    built on it is a concave hyperbola, and one chord understates
    continuation values model-wide (see ``policy_value``).  Returns
    (m_knots, c_knots) with ``constrained_knots`` extra columns."""
    if constrained_knots <= 0:
        return m_knots, c_knots
    from .household import CONSTRAINT_EPS
    b = jnp.asarray(borrow_limit, dtype=m_knots.dtype)
    eps = jnp.asarray(10.0 * CONSTRAINT_EPS, dtype=m_knots.dtype)
    m1 = m_knots[:, 1][:, None]             # first endogenous knot [N,1]
    # log-spaced DISTANCE above the borrowing limit (m itself may be
    # negative under a debt limit b < 0)
    frac = jnp.linspace(0.0, 1.0, constrained_knots + 1,
                        dtype=m_knots.dtype)[:-1]
    extra = b + jnp.exp(
        jnp.log(eps) + frac[None, :] * (jnp.log((m1 - b) * (1.0 - 1e-6))
                                        - jnp.log(eps)))   # [N, E]
    m_aug = jnp.sort(jnp.concatenate([m_knots, extra], axis=1), axis=1)
    c_aug = interp1d_rowwise(m_aug, m_knots, c_knots)
    # exact constrained policy c = m - b below the first endogenous knot
    c_aug = jnp.where(m_aug <= m1, m_aug - b, c_aug)
    return m_aug, c_aug


def bellman_vnvrs_step(c_knots, m_next, next_m_knots, next_vnvrs,
                       transition, disc_fac, crra):
    """One Bellman policy-evaluation step in constant-equivalent form:
    u(c at the knots) + beta E[v'] with v' read from a next-period
    ``(m_knots, vnvrs)`` pair at resources ``m_next [N, K, N']``,
    recombined through the vnvrs transform.  The ONE implementation of
    the value numerics (clamping, HIGHEST-precision expectation,
    transform) — shared by the stationary fixed point
    (``policy_value``) and the non-stationary backward recursion
    (``transition.transition_welfare``), whose error-cancellation
    argument requires them to be identical."""
    n = next_m_knots.shape[0]
    one_minus_beta = 1.0 - disc_fac
    q = jnp.moveaxis(m_next, 2, 0).reshape(n, -1)       # [N', N*K]
    v_next = crra_utility(_eval_vnvrs(next_m_knots, next_vnvrs, q),
                          crra) / one_minus_beta
    v_next = jnp.moveaxis(v_next.reshape(n, n, -1), 0, 2)   # [N, K, N']
    ev = jnp.einsum("nkj,nj->nk", v_next, transition,
                    precision=jax.lax.Precision.HIGHEST)
    return inverse_utility(
        one_minus_beta * (crra_utility(c_knots, crra) + disc_fac * ev),
        crra)


def policy_value(policy: HouseholdPolicy, R, W, model: SimpleModel,
                 disc_fac, crra, tol: float = 1e-9,
                 max_iter: int = 5000, constrained_knots: int = 24):
    """Recover v(m, s) for a fixed consumption policy by iterating the policy
    evaluation operator

        v(m, s) = u(c(m, s)) + beta * sum_{s'} P[s, s'] v(R a + W l', s'),
        a = m - c(m, s)

    on the policy's knots to its fixed point (a beta-contraction).
    Returns (ValueFunction, n_iter, final_diff) with the diff measured
    sup-norm on the vnvrs knots.

    ``constrained_knots``: extra log-spaced knots inserted into the
    borrowing-constrained segment (below the first endogenous gridpoint,
    where the exact policy is c = m).  The policy is *linear* there, so one
    chord represents it exactly — but vnvrs is a concave hyperbola there
    (``u^{-1}`` of ``u(m) + const``), and leaving it as one chord
    understates continuation values enough to bias v by several percent
    even far from the constraint (the error rides expectations up the whole
    state space; grid refinement in ``a`` cannot fix it because EGM never
    places knots below the first endogenous point).  Validated against a
    Monte-Carlo discounted-utility oracle in ``tests/test_value.py``.

    All scalars (R, W, disc_fac, crra) may be traced — the sweep vmaps
    welfare over calibration cells like everything else.
    """
    m_knots, c_knots = augment_constrained_knots(
        policy.m_knots, policy.c_knots,
        getattr(model, "borrow_limit", 0.0), constrained_knots)
    a_knots = m_knots - c_knots                 # end-of-period assets
    # next-period resources per (state-knot, next-state): [N, K, N']
    m_next = R * a_knots[:, :, None] + W * model.labor_levels[None, None, :]

    def bellman_rhs(vnvrs):
        return bellman_vnvrs_step(c_knots, m_next, m_knots, vnvrs,
                                  model.transition, disc_fac, crra)

    # start at v = u(c)/(1-beta) (consume current c forever), whose
    # constant-equivalent is exactly the consumption knots
    v0 = c_knots
    big = jnp.asarray(jnp.inf, dtype=m_knots.dtype)

    def cond(state):
        _, diff, it = state
        return (diff > tol) & (it < max_iter)

    def body(state):
        vnvrs, _, it = state
        new = bellman_rhs(vnvrs)
        return new, jnp.max(jnp.abs(new - vnvrs)), it + 1

    vnvrs, diff, it = jax.lax.while_loop(cond, body,
                                         (v0, big, jnp.asarray(0)))
    return (ValueFunction(m_knots=m_knots, vnvrs_knots=vnvrs,
                          disc_fac=jnp.asarray(disc_fac)), it, diff)


def _linear_interp_weights(q, xp):
    """The ``ops.interp.interp1d`` evaluation expressed as a LINEAR operator
    on the knot values: weight rows ``[..., K]`` such that
    ``weights @ fp == interp1d(q, xp, fp)`` for every knot-value vector
    ``fp`` — including the linear extrapolation beyond the knot span, whose
    bracket weights simply leave [0, 1].  Rows always sum to 1."""
    k = xp.shape[0]
    i = jnp.clip(jnp.searchsorted(xp, q, side="right") - 1, 0, k - 2)
    t = (q - xp[i]) / (xp[i + 1] - xp[i])
    return (jax.nn.one_hot(i, k, dtype=q.dtype) * (1.0 - t)[..., None]
            + jax.nn.one_hot(i + 1, k, dtype=q.dtype) * t[..., None])


def policy_value_direct(policy: HouseholdPolicy, R, W, model: SimpleModel,
                        disc_fac, crra, constrained_knots: int = 24,
                        newton_steps: int = 5):
    """``policy_value`` with BOUNDED compile-time and run-time cost: the
    value-iteration ``while_loop`` replaced by one linear solve plus a few
    unrolled Newton steps — NO ``lax`` control flow at all.  This is the
    welfare path the vmapped tax sweep uses (``fiscal.tax_rate_sweep``):
    the round-3 iterative evaluation under ``vmap`` (a while_loop on top
    of the nested bisection) was an XLA compile pathology — >10 min on the
    TPU, and killing it mid-compile wedged the tunnel (VERDICT r3
    weak-item 2).

    Stage 1 — raw-v linear solve.  Policy evaluation is *linear* in the
    value function: for fixed interpolation points the Bellman RHS is
    ``v = u(c) + beta * B v`` with ``B[(s,k),(s',k')] = P[s,s'] *
    w[(s,k),(s',k')]`` combining the Markov transition with the (fixed)
    linear-interpolation weights of the next-period queries on the
    next-period knots.  So v at the knots solves ``(I - beta B) v = u(c)``
    — one LU of size ``[S*K, S*K]``, the exact pattern
    ``household._stationary_solve`` uses for distributions.

    Stage 2 — Newton on the vnvrs fixed point.  The accurate storage
    scheme interpolates the CONSTANT-EQUIVALENT transform
    ``vnvrs = u^{-1}((1-beta) v)`` (module docstring), whose fixed-point
    operator is ``F = u^{-1} ∘ affine ∘ u ∘ interp`` — nonlinear only
    through the elementwise ``u``/``u^{-1}`` wrappers, so its Jacobian is
    ``diag((u^{-1})'(z)) · M`` with ``M`` assembled from the SAME weight
    tensor scaled by ``u'`` at the interpolated values (and
    ``(u^{-1})'(z) = F(x)^crra`` for every CRRA including log).  Each
    Newton step is one more small LU; convergence is quadratic from the
    stage-1 seed (measured: scheme gap ~3e-2 → 1e-15 in 3 steps), where
    plain Bellman polishing contracts only by beta = 0.96 per sweep (120
    sweeps still left 5e-4).  The iteration runs in LOG-vnvrs coordinates
    (see inline comment) so the constrained segment — where vnvrs sits
    orders of magnitude below the rest and plain-coordinate sup-norms are
    blind — is controlled uniformly.  The returned ``diff`` is
    correspondingly the sup-norm of the LOG-space Bellman residual (one
    extra application), a *relative*-vnvrs certificate; for log utility it
    bounds the value error directly as ``|Δv| ≤ diff/(1-beta)``.

    Cost note: the weight tensor and LUs are ``O((S*K)^2)`` memory and
    ``O((S*K)^3)`` FLOPs — at sweep sizes (S=7, K≈57: 0.6 MB, ~0.1 GFLOP
    per LU) trivial and MXU-shaped; at fine-grid sizes (S*K ≈ 15k) use
    ``policy_value``, whose iteration is the right trade there.

    Returns ``(ValueFunction, newton_steps, diff)`` — same shape of
    contract as ``policy_value``.
    """
    m_knots, c_knots = augment_constrained_knots(
        policy.m_knots, policy.c_knots,
        getattr(model, "borrow_limit", 0.0), constrained_knots)
    a_knots = m_knots - c_knots
    n, k = m_knots.shape
    dtype = m_knots.dtype
    # next-period resources per (state, knot, next-state): [N, K, N']
    m_next = R * a_knots[:, :, None] + W * model.labor_levels[None, None, :]

    # interpolation weights of every query on next-state knot vectors:
    # vmap over the next-state axis pairs q=[N,K] with its knots [K]
    wts = jax.vmap(_linear_interp_weights, in_axes=(2, 0))(
        m_next, m_knots)                            # [N', N, K, K']
    wts = jnp.moveaxis(wts, 0, 2)                   # [N, K, N', K']
    u_c = crra_utility(c_knots, crra)
    ident = jnp.eye(n * k, dtype=dtype)

    # stage 1: raw-v solve (exact for linear interpolation of raw v)
    B = (model.transition[:, None, :, None] * wts).reshape(n * k, n * k)
    v = jnp.linalg.solve(ident - disc_fac * B,
                         u_c.reshape(n * k)).reshape(n, k)
    # seed the vnvrs Newton from the raw-v solution; anywhere the
    # transform leaves u's range (possible only from extrapolated weights
    # pushing v out of domain) fall back to policy_value's cold start
    x = inverse_utility((1.0 - disc_fac) * v, crra)
    x = jnp.where(jnp.isfinite(x) & (x > 0), x, c_knots)

    one_minus_beta = 1.0 - disc_fac
    tiny = jnp.finfo(dtype).tiny

    def f_and_jacobian(x):
        """F(x) and the pieces of J_F = diag(F^crra) · M at x, where
        M[(n,k),(n',k')] = beta * P[n,n'] * u'(val[n,k,n']) * wts[...] and
        val is the clamped interpolated vnvrs (zero derivative where the
        clamp binds, matching ``_clamp_positive``)."""
        val_raw = jnp.einsum("nkjl,jl->nkj", wts, x)
        val = jnp.maximum(val_raw, tiny)
        z = one_minus_beta * u_c + disc_fac * jnp.einsum(
            "nj,nkj->nk", model.transition, crra_utility(val, crra),
            precision=jax.lax.Precision.HIGHEST)
        f = inverse_utility(z, crra)
        mu = jnp.where(val_raw > tiny,
                       marginal_utility(val, crra), 0.0)   # u'(val), clamped
        m4 = (disc_fac * model.transition[:, None, :, None]
              * mu[:, :, :, None] * wts)
        jac = (f.reshape(n * k, 1) ** crra
               * m4.reshape(n * k, n * k))          # diag(F^crra) · M
        return f, jac

    # Newton in LOG-vnvrs coordinates, y = log x: H(y) = log F(e^y),
    # J_H = diag(1/F) J_F diag(x).  vnvrs sup-norm is blind near zero
    # (the constrained segment, where vnvrs ~ 1e-7 but v = u(vnvrs)/(1-b)
    # swings by O(10) per relative step) — measured: plain-coordinate
    # Newton "converged" at residual 4e-8 while v(2.0) was off by 1e-2 in
    # the W = 0 oracle case.  Log coordinates stretch that region so both
    # the steps and the ``diff`` certificate control v uniformly (for log
    # utility, y IS (1-beta) v).
    for _ in range(newton_steps):
        f, jac = f_and_jacobian(x)
        jac_y = jac * (x.reshape(1, n * k) / f.reshape(n * k, 1))
        delta_y = jnp.linalg.solve(ident - jac_y,
                                   jnp.log(f / x).reshape(n * k)
                                   ).reshape(n, k)
        x = x * jnp.exp(delta_y)

    diff = jnp.max(jnp.abs(jnp.log(f_and_jacobian(x)[0] / x)))
    return (ValueFunction(m_knots=m_knots, vnvrs_knots=x,
                          disc_fac=jnp.asarray(disc_fac)), newton_steps,
            diff)


def value_at(vf: ValueFunction, m, crra, state_idx=None):
    """v(m, s): interpolate vnvrs, then undo the constant-equivalent
    transform (v = u(vnvrs)/(1-beta)).  ``m`` is rowwise per state
    ([N, ...]) by default, or per-state-indexed when ``state_idx`` given."""
    scale = 1.0 - vf.disc_fac
    if state_idx is None:
        vn = _eval_vnvrs(vf.m_knots, vf.vnvrs_knots, m)
        return crra_utility(vn, crra) / scale
    vn = _clamp_positive(
        interp1d(m, vf.m_knots[state_idx], vf.vnvrs_knots[state_idx]))
    return crra_utility(vn, crra) / scale


def marginal_value_at(policy: HouseholdPolicy, m, crra, state_idx=None):
    """v'(m, s) = u'(c(m, s)) — the envelope condition.  This is the working
    analog of the reference's marginal-value wrappers (``MargValueFuncCRRA``
    at ``Aiyagari_Support.py:1514``, dead ``MargValueFunc2D`` at ``:71-102``):
    marginal value is *data derived from the policy*, not a stored object."""
    from .household import consumption_at
    return marginal_utility(consumption_at(policy, m, state_idx), crra)


def value_on_histogram(vf: ValueFunction, R, W, model: SimpleModel,
                       crra):
    """v evaluated at every histogram cell's period-entry resources
    m = R x + W l_s — the [D, N] field behind both the aggregate welfare
    scalar and distributional incidence."""
    m = R * model.dist_grid[:, None] + W * model.labor_levels[None, :]
    return value_at(vf, m.T, crra).T            # [D, N]


def aggregate_welfare(vf: ValueFunction, dist, R, W, model: SimpleModel,
                      crra):
    """Population welfare E[v(m, s)] under a wealth histogram ``dist``
    [D, N] over ``model.dist_grid`` (e.g. the stationary distribution)."""
    return jnp.sum(dist * value_on_histogram(vf, R, W, model, crra))


def consumption_equivalent(v_base, v_alt, crra, disc_fac):
    """The permanent consumption change lambda making the base allocation as
    good as the alternative: scale all base-path consumption by (1+lambda).

    CRRA utility is homogeneous of degree 1-crra, so
    ``v((1+lam) c-path) = (1+lam)^(1-crra) v`` and
    ``lam = (v_alt/v_base)^(1/(1-crra)) - 1``; for log utility the scaling
    is additive, ``lam = exp((1-beta)(v_alt - v_base)) - 1``.
    """
    v_base = jnp.asarray(v_base)
    v_alt = jnp.asarray(v_alt)
    if not isinstance(crra, jax.core.Tracer):
        crra = float(crra)
        if crra == 1.0:
            return jnp.expm1((1.0 - disc_fac) * (v_alt - v_base))
        return (v_alt / v_base) ** (1.0 / (1.0 - crra)) - 1.0
    is_log = crra == 1.0
    safe = jnp.where(is_log, 2.0, crra)
    power = (v_alt / v_base) ** (1.0 / (1.0 - safe)) - 1.0
    return jnp.where(is_log,
                     jnp.expm1((1.0 - disc_fac) * (v_alt - v_base)), power)
