"""Calibration utilities: invert the equilibrium map for a structural
parameter.

The reference hard-codes its calibration (SURVEY.md §6); real workflows
run the inverse problem — "what discount factor makes the equilibrium
return 4.09%?", "what disutility weight makes mean hours 1/3?".  Each
target here is monotone in its parameter, so the robust tool is the same
fixed-trip bracketed bisection the equilibrium solvers already use
(``equilibrium._bisect``), wrapped around a full jitted equilibrium
solve per evaluation.  Derivative-free on purpose: a bisection's output
is piecewise-constant in its inputs at the bracket tolerance, so
autodiff through the nested solve returns zero a.e. — gradients are the
wrong tool for this outer problem.

Everything compiles to one XLA program (nested ``while_loop``s), so a
calibration is itself vmappable — e.g. a whole row of Table II
re-calibrated to the paper's target return in one batched call.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from .equilibrium import _bisect, solve_equilibrium_lean
from .household import SimpleModel
from .labor import LaborModel, solve_labor_equilibrium


class CalibrationResult(NamedTuple):
    value: jnp.ndarray       # the calibrated parameter
    achieved: jnp.ndarray    # target quantity at the last evaluated
                             # parameter (within bracket tol of `value`)
    iterations: jnp.ndarray
    converged: jnp.ndarray   # |achieved - target| <= target_tol; False
                             # when the target is outside the bracket's
                             # range (bisection collapses to an endpoint)


def calibrate_discount_factor(model: SimpleModel, target_r, crra,
                              cap_share, depr_fac,
                              beta_lo: float = 0.90,
                              beta_hi: float = 0.995,
                              beta_tol: float = 1e-6,
                              max_iter: int = 40,
                              target_tol: float = 1e-4,
                              **solver_kwargs) -> CalibrationResult:
    """Find the discount factor whose equilibrium interest rate is
    ``target_r``: r*(beta) is decreasing (patience raises supply,
    depressing the return), so ``target_r - r*(beta)`` is increasing in
    beta — a ``_bisect`` root.  The bracket must satisfy
    ``beta_hi * (1 + r*(beta_hi)) < 1`` (stationarity); the default
    upper end is safe for standard calibrations.

    Each evaluation is one full ``solve_equilibrium_lean``; the whole
    nested program jits/vmaps.  Self-consistency is the test oracle:
    calibrating to the r* of a known beta recovers that beta."""
    dtype = model.a_grid.dtype
    target_r = jnp.asarray(target_r, dtype=dtype)

    def excess(beta):
        eq = solve_equilibrium_lean(model, beta, crra, cap_share,
                                    depr_fac, **solver_kwargs)
        return target_r - eq.r_star, eq.r_star

    beta, iters, achieved = _bisect(excess,
                                    jnp.asarray(beta_lo, dtype=dtype),
                                    jnp.asarray(beta_hi, dtype=dtype),
                                    beta_tol, max_iter,
                                    aux_init=jnp.zeros((), dtype=dtype))
    return CalibrationResult(
        value=beta, achieved=achieved, iterations=iters,
        converged=jnp.abs(achieved - target_r) <= target_tol)


def calibrate_labor_weight(model: LaborModel, target_hours, disc_fac,
                           crra, cap_share, depr_fac,
                           chi_lo: float = 1.0, chi_hi: float = 200.0,
                           chi_tol: float = 1e-4,
                           max_iter: int = 40,
                           target_tol: float = 1e-3,
                           egm_tol: float = 1e-6,
                           dist_tol: float = 1e-11) -> CalibrationResult:
    """Find the disutility weight chi whose GENERAL-EQUILIBRIUM mean
    hours hit ``target_hours`` (e.g. 1/3): hours are decreasing in chi,
    so ``target - hours(chi)`` is increasing — bisected in log space
    (chi is a scale parameter spanning orders of magnitude).

    Each evaluation solves the full labor-supply equilibrium at the
    trial chi (its own inner bisection on r)."""
    base_dtype = model.base.a_grid.dtype
    target_hours = jnp.asarray(target_hours, dtype=base_dtype)

    def excess(log_chi):
        trial = model._replace(labor_weight=jnp.exp(log_chi))
        eq = solve_labor_equilibrium(trial, disc_fac, crra, cap_share,
                                     depr_fac, egm_tol=egm_tol,
                                     dist_tol=dist_tol)
        return target_hours - eq.mean_hours, eq.mean_hours

    log_chi, iters, achieved = _bisect(
        excess,
        jnp.asarray(jnp.log(chi_lo), dtype=base_dtype),
        jnp.asarray(jnp.log(chi_hi), dtype=base_dtype),
        chi_tol, max_iter, aux_init=jnp.zeros((), dtype=base_dtype))
    return CalibrationResult(
        value=jnp.exp(log_chi), achieved=achieved, iterations=iters,
        converged=jnp.abs(achieved - target_hours) <= target_tol)
