"""Calibration utilities: invert the equilibrium map for a structural
parameter.

The reference hard-codes its calibration (SURVEY.md §6); real workflows
run the inverse problem — "what discount factor makes the equilibrium
return 4.09%?", "what disutility weight makes mean hours 1/3?".  Each
target here is monotone in its parameter, so the robust tool is the same
fixed-trip bracketed bisection the equilibrium solvers already use
(``equilibrium._bisect``), wrapped around a full jitted equilibrium
solve per evaluation.  Derivative-free on purpose: a bisection's output
is piecewise-constant in its inputs at the bracket tolerance, so
autodiff through the nested solve returns zero a.e. — gradients are the
wrong tool for this outer problem.

Everything compiles to one XLA program (nested ``while_loop``s), so a
calibration is itself vmappable — e.g. a whole row of Table II
re-calibrated to the paper's target return in one batched call.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from ..solver_health import CONVERGED, is_failure
from .equilibrium import _bisect, solve_equilibrium_lean
from .heterogeneity import (
    population_distribution,
    solve_heterogeneous_equilibrium,
    uniform_beta_types,
)
from .household import SimpleModel
from .labor import LaborModel, solve_labor_equilibrium


class CalibrationResult(NamedTuple):
    value: jnp.ndarray       # the calibrated parameter
    achieved: jnp.ndarray    # target quantity at the last evaluated
                             # parameter (within bracket tol of `value`)
    iterations: jnp.ndarray
    converged: jnp.ndarray   # |achieved - target| <= target_tol AND the
                             # bisection exited healthy; False when the
                             # target is outside the bracket's range
                             # (bisection collapses to an endpoint) or
                             # the solve tripped a solver_health failure
    status: jnp.ndarray = CONVERGED  # the _bisect exit's solver_health
                             # code (NONFINITE = a trial solve went NaN)


def calibrate_discount_factor(model: SimpleModel, target_r, crra,
                              cap_share, depr_fac,
                              beta_lo: float = 0.90,
                              beta_hi: float = 0.995,
                              beta_tol: float = 1e-6,
                              max_iter: int = 40,
                              target_tol: float = 1e-4,
                              **solver_kwargs) -> CalibrationResult:
    """Find the discount factor whose equilibrium interest rate is
    ``target_r``: r*(beta) is decreasing (patience raises supply,
    depressing the return), so ``target_r - r*(beta)`` is increasing in
    beta — a ``_bisect`` root.  The bracket must satisfy
    ``beta_hi * (1 + r*(beta_hi)) < 1`` (stationarity); the default
    upper end is safe for standard calibrations.

    Each evaluation is one full ``solve_equilibrium_lean``; the whole
    nested program jits/vmaps.  Self-consistency is the test oracle:
    calibrating to the r* of a known beta recovers that beta."""
    dtype = model.a_grid.dtype
    target_r = jnp.asarray(target_r, dtype=dtype)

    def excess(beta):
        eq = solve_equilibrium_lean(model, beta, crra, cap_share,
                                    depr_fac, **solver_kwargs)
        return target_r - eq.r_star, eq.r_star

    beta, iters, achieved, status = _bisect(excess,
                                    jnp.asarray(beta_lo, dtype=dtype),
                                    jnp.asarray(beta_hi, dtype=dtype),
                                    beta_tol, max_iter,
                                    aux_init=jnp.zeros((), dtype=dtype))
    return CalibrationResult(
        value=beta, achieved=achieved, iterations=iters,
        converged=((jnp.abs(achieved - target_r) <= target_tol)
                   & ~is_failure(status)), status=status)


def gini_histogram(grid, masses):
    """Gini coefficient of a wealth histogram on a SORTED nonnegative
    support — jit-able (unlike ``utils.stats.gini``, which is the
    host-side numpy tool): 1 - 2 * trapezoid area under the Lorenz
    curve built from cumulative mass and cumulative wealth."""
    w = masses / jnp.sum(masses)
    cum_pop = jnp.concatenate([jnp.zeros((1,), dtype=w.dtype),
                               jnp.cumsum(w)])
    cw = jnp.cumsum(grid * w)
    # floor the total-wealth normalizer: all mass at zero wealth would give
    # 0/0 -> NaN, and a NaN here silently one-sides calibrate_beta_spread's
    # bisection (NaN comparisons are False); with the floor, zero aggregate
    # wealth reads as Gini 1 (all-zero Lorenz curve) — a finite, documented
    # value instead of a NaN that corrupts the bracket
    cum_wealth = jnp.concatenate([jnp.zeros((1,), dtype=w.dtype),
                                  cw / jnp.maximum(cw[-1],
                                                   jnp.finfo(w.dtype).tiny)])
    area = jnp.sum(0.5 * (cum_wealth[1:] + cum_wealth[:-1])
                   * jnp.diff(cum_pop))
    # NEGATIVE aggregate wealth (possible with borrow_limit < 0) would ride
    # the same floor and return an astronomically scaled non-number-like
    # Gini; the standard coefficient is undefined there, so report NaN
    # explicitly (callers that bisect on Gini target nonnegative-wealth
    # economies; a NaN marks the config as out of the measure's domain
    # rather than smuggling in a garbage magnitude — round-3 review)
    return jnp.where(cw[-1] < 0, jnp.nan, 1.0 - 2.0 * area)


def calibrate_beta_spread(model: SimpleModel, target_gini, center, crra,
                          cap_share, depr_fac, n_types: int = 4,
                          spread_lo: float = 1e-4,
                          spread_hi: float = 0.03,
                          spread_tol: float = 1e-5,
                          max_iter: int = 30,
                          target_tol: float = 5e-3,
                          **solver_kwargs) -> CalibrationResult:
    """The Carroll-Slacalek-Tokuoka-White (2017) "beta-dist" workflow:
    find the discount-factor SPREAD whose general-equilibrium wealth
    Gini hits the data.  Wealth concentration is increasing in the
    spread (patient types absorb the capital stock), so the match is one
    more ``_bisect`` — each evaluation a full heterogeneous equilibrium
    (``solve_heterogeneous_equilibrium`` over ``uniform_beta_types``).

    The upper bracket must respect stationarity at the equilibrium the
    spread itself produces (``(center + spread) * (1 + r*) < 1``); the
    default 0.03 is safe for standard calibrations — the solver's own
    bracket pins r* below ``1/beta_max - 1`` regardless, so an
    aggressive ``spread_hi`` degrades into ``converged=False`` rather
    than an error."""
    dtype = model.a_grid.dtype
    target_gini = jnp.asarray(target_gini, dtype=dtype)
    weights = jnp.ones((n_types,), dtype=dtype)

    def excess(spread):
        betas = uniform_beta_types(center, spread, n_types)
        eq = solve_heterogeneous_equilibrium(
            model, betas, weights, crra, cap_share, depr_fac,
            **solver_kwargs)
        g = gini_histogram(model.dist_grid,
                           population_distribution(eq).sum(axis=1))
        # Gini increasing in spread, so g - target satisfies _bisect's
        # increasing-excess contract directly
        return g - target_gini, g

    spread, iters, achieved, status = _bisect(
        excess, jnp.asarray(spread_lo, dtype=dtype),
        jnp.asarray(spread_hi, dtype=dtype), spread_tol, max_iter,
        aux_init=jnp.zeros((), dtype=dtype))
    return CalibrationResult(
        value=spread, achieved=achieved, iterations=iters,
        converged=((jnp.abs(achieved - target_gini) <= target_tol)
                   & ~is_failure(status)), status=status)


class LorenzFit(NamedTuple):
    """Result of fitting the discount-factor spread to the SCF Lorenz
    curve: the best spread, the achieved Euclidean Lorenz distance, the
    implied equilibrium return, and the homogeneous-model baseline
    distance for comparison (the reference's own model-vs-SCF gap)."""

    spread: float
    distance: float
    r_star_pct: float
    distance_homogeneous: float
    evaluations: int


def calibrate_spread_to_lorenz(model: SimpleModel, center, crra,
                               cap_share, depr_fac, n_types: int = 5,
                               spread_lo: float = 0.0,
                               spread_hi: float = 0.03,
                               spread_tol: float = 2e-4,
                               scf_path=None, retry=None,
                               **solver_kwargs) -> LorenzFit:
    """Fit the beta-dist spread to the REAL SCF wealth Lorenz curve —
    the cstwMPC estimation (Carroll et al. 2017) run against the curve
    this repo vendors from the reference's own committed figure
    (``utils.stats.load_scf_lorenz``).

    The reference's headline comparison is that its homogeneous model
    MISSES the SCF badly (Euclidean Lorenz distance 0.9714, "too little
    inequality"); this routine closes that gap: golden-section
    minimization of the distance over the spread, each evaluation a full
    heterogeneous general equilibrium.  Measured at the test calibration:
    homogeneous distance 0.894 -> fitted 0.12 at spread ~ 0.010.

    Host-side minimization (the objective is smooth but not monotone, so
    the jit-side ``_bisect`` root-finder does not apply); each evaluation
    is jitted work, and repeated shapes hit the jit cache.

    Resilience (ISSUE 3): each evaluation is a calibration STEP boundary
    — inside a ``preemption_guard()`` a shutdown request raises the typed
    ``resilience.Interrupted`` between solves instead of dying inside
    one, and every equilibrium solve runs under ``retry_transient`` with
    the deterministic backoff of ``retry`` (default ``RetryPolicy()``).
    """
    import jax
    import numpy as np

    from ..utils.resilience import (
        RetryPolicy,
        raise_if_interrupted,
        retry_transient,
    )
    from ..utils.stats import lorenz_distance_vs_scf

    retry_policy = retry if retry is not None else RetryPolicy()
    weights = jnp.ones((n_types,), dtype=model.a_grid.dtype)
    grid = np.asarray(model.dist_grid)
    n_eval = [0]

    def fit_at(spread):
        """(distance, r_star) at a trial spread — ONE definition of the
        objective, shared with the headline golden via
        ``lorenz_distance_vs_scf``."""
        raise_if_interrupted("Lorenz-spread calibration",
                             progress={"evaluations": n_eval[0]})
        n_eval[0] += 1
        betas = uniform_beta_types(center, float(spread), n_types)
        eq = retry_transient(
            lambda: jax.block_until_ready(solve_heterogeneous_equilibrium(
                model, betas, weights, crra, cap_share, depr_fac,
                **solver_kwargs)),
            retry_policy, label=f"calibration solve {n_eval[0]}")
        pop = np.asarray(population_distribution(eq).sum(axis=1))
        return (lorenz_distance_vs_scf(grid, pop, path=scf_path),
                float(eq.r_star))

    d_hom, _ = fit_at(0.0)

    # golden-section on [lo, hi]; keep (distance, r_star) pairs so the
    # winner needs no re-solve
    invphi = (np.sqrt(5.0) - 1.0) / 2.0
    lo, hi = float(spread_lo), float(spread_hi)
    c = hi - invphi * (hi - lo)
    d = lo + invphi * (hi - lo)
    fc, fd = fit_at(c), fit_at(d)
    while hi - lo > spread_tol:
        if fc[0] < fd[0]:
            hi, d, fd = d, c, fc
            c = hi - invphi * (hi - lo)
            fc = fit_at(c)
        else:
            lo, c, fc = c, d, fd
            d = lo + invphi * (hi - lo)
            fd = fit_at(d)
    best, (dist, r_star) = (c, fc) if fc[0] < fd[0] else (d, fd)
    return LorenzFit(spread=float(best), distance=dist,
                     r_star_pct=100.0 * r_star,
                     distance_homogeneous=d_hom,
                     evaluations=n_eval[0])


def calibrate_labor_weight(model: LaborModel, target_hours, disc_fac,
                           crra, cap_share, depr_fac,
                           chi_lo: float = 1.0, chi_hi: float = 200.0,
                           chi_tol: float = 1e-4,
                           max_iter: int = 40,
                           target_tol: float = 1e-3,
                           egm_tol: float = 1e-6,
                           dist_tol: float = 1e-11) -> CalibrationResult:
    """Find the disutility weight chi whose GENERAL-EQUILIBRIUM mean
    hours hit ``target_hours`` (e.g. 1/3): hours are decreasing in chi,
    so ``target - hours(chi)`` is increasing — bisected in log space
    (chi is a scale parameter spanning orders of magnitude).

    Each evaluation solves the full labor-supply equilibrium at the
    trial chi (its own inner bisection on r)."""
    base_dtype = model.base.a_grid.dtype
    target_hours = jnp.asarray(target_hours, dtype=base_dtype)

    def excess(log_chi):
        trial = model._replace(labor_weight=jnp.exp(log_chi))
        eq = solve_labor_equilibrium(trial, disc_fac, crra, cap_share,
                                     depr_fac, egm_tol=egm_tol,
                                     dist_tol=dist_tol)
        return target_hours - eq.mean_hours, eq.mean_hours

    log_chi, iters, achieved, status = _bisect(
        excess,
        jnp.asarray(jnp.log(chi_lo), dtype=base_dtype),
        jnp.asarray(jnp.log(chi_hi), dtype=base_dtype),
        chi_tol, max_iter, aux_init=jnp.zeros((), dtype=base_dtype))
    return CalibrationResult(
        value=jnp.exp(log_chi), achieved=achieved, iterations=iters,
        converged=((jnp.abs(achieved - target_hours) <= target_tol)
                   & ~is_failure(status)), status=status)
