"""General-equilibrium solvers: capital-market bisection on the interest rate.

The reference finds equilibrium Krusell-Smith style (simulate + regress the
aggregate law, ``Aiyagari_Support.py:1896-1964``) because it inherits the KS
machinery; the textbook Aiyagari equilibrium is the fixed point of
    r  ->  household capital supply A(r)  vs  firm capital demand K(r)
bisected on r (BASELINE.json's north star keeps this outer loop in Python but
jits everything inside; here even the bisection itself is a ``lax.while_loop``
so one XLA program solves a whole calibration cell — and a vmap of it solves
the whole Table II sweep as one batched program).

The bracket is economic: r must lie below the discount rate (1-beta)/beta
(supply diverges there) and above -delta (demand diverges).  Excess supply
A(r) - K(r) is increasing in r, so bisection is globally convergent.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from . import firm
from ..solver_health import (
    CONVERGED,
    MAX_ITER,
    NONFINITE,
    combine_status,
)
from ..utils.config import (resolve_grid, resolve_kernel, resolve_precision,
                            resolve_state)
from .household import (
    R_DESCENT_WIDTH_SCALE,
    HouseholdPolicy,
    SimpleModel,
    aggregate_capital,
    aggregate_labor,
    build_simple_model,
    descent_dtype,
    fused_supply_phases,
    initial_distribution,
    initial_policy,
    solve_household,
    stationary_wealth,
)


class EquilibriumResult(NamedTuple):
    r_star: jnp.ndarray          # equilibrium net interest rate
    wage: jnp.ndarray
    capital: jnp.ndarray         # K = household asset supply at r_star
    labor: jnp.ndarray           # effective aggregate labor
    saving_rate: jnp.ndarray     # delta*K / Y (net saving identity in SS)
    excess: jnp.ndarray          # residual excess supply at r_star
    policy: HouseholdPolicy
    distribution: jnp.ndarray    # [D, N] stationary wealth distribution
    bisect_iters: jnp.ndarray
    status: jnp.ndarray = CONVERGED  # worst solver_health code observed


class SupplyEval(NamedTuple):
    """One household-side evaluation A(r) with its work counters.

    ``descent_steps``/``polish_steps`` split the inner-loop work by
    precision-ladder phase (DESIGN §5; all-polish under the "reference"
    policy), and ``escalations`` counts inner loops whose descent phase
    fell back to a pure-reference solve
    (``solver_health.PRECISION_ESCALATED``)."""

    supply: jnp.ndarray
    policy: HouseholdPolicy
    distribution: jnp.ndarray
    wage: jnp.ndarray
    k_to_l: jnp.ndarray
    egm_iters: jnp.ndarray       # EGM backward steps taken to the fixed point
    dist_iters: jnp.ndarray      # distribution-iteration steps taken
    status: jnp.ndarray = CONVERGED  # worst of the two inner loops' codes
    descent_steps: jnp.ndarray = 0   # cheap-phase steps (both loops)
    polish_steps: jnp.ndarray = 0    # reference-phase steps (both loops)
    escalations: jnp.ndarray = 0     # inner loops escalated to reference


def household_capital_supply(r, model: SimpleModel, disc_fac, crra,
                             cap_share, depr_fac, prod=1.0,
                             egm_tol=1e-6, dist_tol=1e-11,
                             init_policy=None, init_dist=None,
                             dist_method: str = "auto",
                             egm_method: str = "xla",
                             accel_every: int | None = None,
                             precision: str = "reference",
                             grid="reference",
                             kernel="reference",
                             state="replicated",
                             descent_fault_iter: int | None = None,
                             descent_fault_mode: str = "nan",
                             ) -> SupplyEval:
    """A(r): solve the household at prices implied by r, return stationary
    capital plus the objects (policy, distribution, W), iteration counts
    (the work model behind the grid-points/sec benchmark metric), and the
    worst ``solver_health`` status of the two inner fixed points.

    ``init_policy``/``init_dist`` warm-start the two inner fixed points —
    the bisection loop passes the previous midpoint's solution, cutting the
    inner iteration counts severalfold at identical answers (both loops
    converge to r-dependent fixed points regardless of start).

    ``egm_method`` selects the EGM fixed-point engine ("xla" lock-step
    while_loop, "pallas" per-lane kernel, "auto" by backend — see
    ``solve_household``); ``dist_method`` the distribution engine.

    ``accel_every=0`` disables the Anderson extrapolation in BOTH inner
    loops (plain damped iteration — the sweep retry ladder's safe mode);
    ``None`` keeps each loop's own default cadence.

    ``precision`` threads the mixed-precision ladder policy (DESIGN §5)
    into BOTH inner fixed points; the per-phase step split rides the
    returned counters.  ``grid`` threads the grid policy (DESIGN §5b)
    into the POLICY fixed point (analytic tail + coarse-to-fine
    ladder); the distribution loop reaches compaction through the
    model's own (compacted) histogram support — a support LADDER there
    was built and measured to fight the bisection's warm-start carry
    (see ``stationary_wealth``'s grid-policy note), so it does not run.  ``descent_fault_iter`` (tests; ISSUE 7 event
    drills) poisons both inner DESCENT phases at that iteration so the
    ladder's escalation path is deterministically injectable from the
    sweep level — compiled out when None, like the bisection's
    ``fault_iter``; ``descent_fault_mode`` picks the poison ("nan" |
    "stall" — a stall escalates WITHOUT contaminating the descent-only
    bracket trips' finite excess, so the cell stays healthy end to
    end).

    ``kernel`` (ISSUE 13, DESIGN §4c): under ``kernel="fused"`` with a
    SINGLE-phase precision policy, the two inner fixed points run as
    ONE device-resident megakernel launch
    (``household.fused_supply_phases`` — ``dist_method``/``egm_method``
    are then moot and ignored; the coarse-to-fine grid ladder is an
    XLA-path feature, so a compact ``grid`` runs tail-closed without
    it); under a two-phase policy the ladders gain the bf16 descent
    rung instead (threaded through both inner solvers).

    ``state`` (ISSUE 20, DESIGN §6b) threads the state-sharding policy
    into both inner solvers; ``state="sharded"`` disables the fused
    megakernel (a single-device VMEM program by construction — the
    row-block contraction is what actually shards) and routes the
    distribution loop through the sharded push-forward."""
    k_to_l = firm.k_to_l_from_r(r, cap_share, depr_fac, prod)
    W = firm.wage_rate(k_to_l, cap_share, prod)
    R = 1.0 + r
    kspec = resolve_kernel(kernel)
    sharded_state = resolve_state(state).sharded
    use_fused = (kspec.fused and not resolve_precision(precision).two_phase
                 and not sharded_state)
    if use_fused and jax.default_backend() in ("tpu", "axon"):
        # the probe gate the policy promises: a Mosaic lowering gap in
        # the fused kernel must degrade to the launch-per-loop XLA
        # engines below, never die at sweep compile time.  The GRID
        # probe subsumes the single-lane one — a fused caller may be
        # vmapped later (the sweep), where the custom_vmap rule
        # dispatches the lane-grid kernel.
        from ..ops.pallas_kernels import probe_kernel
        use_fused = probe_kernel("fused_grid")
    if use_fused:
        policy, dist, egm_it, dist_it, egm_status, dist_status = \
            fused_supply_phases(
                R, W, model, disc_fac, crra, egm_tol, dist_tol,
                init_policy_knots=init_policy, init_dist=init_dist,
                egm_accel=(32 if accel_every is None else accel_every),
                dist_accel=(64 if accel_every is None else accel_every),
                grid=grid)
        it_dtype = jnp.asarray(egm_it).dtype
        zero = jnp.zeros((), dtype=it_dtype)
        return SupplyEval(aggregate_capital(dist, model), policy, dist, W,
                          k_to_l, egm_it, dist_it,
                          combine_status(egm_status, dist_status),
                          descent_steps=zero,
                          polish_steps=(jnp.asarray(egm_it, it_dtype)
                                        + jnp.asarray(dist_it, it_dtype)),
                          escalations=zero)
    egm_kw = {} if accel_every is None else {"accel_every": accel_every}
    if descent_fault_iter is not None:
        egm_kw["descent_fault_iter"] = int(descent_fault_iter)
        egm_kw["descent_fault_mode"] = str(descent_fault_mode)
    policy, egm_it, _, egm_status, egm_ph = solve_household(
        R, W, model, disc_fac, crra, tol=egm_tol, init_policy=init_policy,
        method=egm_method, precision=precision, grid=grid, kernel=kernel,
        state=state, return_phases=True, **egm_kw)
    dist, dist_it, _, dist_status, dist_ph = stationary_wealth(
        policy, R, W, model, tol=dist_tol, init_dist=init_dist,
        method=dist_method, precision=precision, kernel=kernel,
        state=state, return_phases=True, **egm_kw)
    it_dtype = jnp.asarray(egm_it).dtype
    return SupplyEval(aggregate_capital(dist, model), policy, dist, W,
                      k_to_l, egm_it, dist_it,
                      combine_status(egm_status, dist_status),
                      descent_steps=(egm_ph.descent_steps.astype(it_dtype)
                                     + dist_ph.descent_steps.astype(it_dtype)),
                      polish_steps=(egm_ph.polish_steps.astype(it_dtype)
                                    + dist_ph.polish_steps.astype(it_dtype)),
                      escalations=(egm_ph.escalated.astype(it_dtype)
                                   + dist_ph.escalated.astype(it_dtype)))


def _bisection_setup(model: SimpleModel, disc_fac, depr_fac,
                     r_tol, egm_tol, dist_tol, bracket_pad: float = 1.0):
    """Shared bisection machinery: dtype-aware tolerance defaults (the f64
    values are unreachable in f32 and would force every inner loop to its
    iteration cap) and the economic bracket [-delta+eps, (1-beta)/beta-eps]
    (supply diverges at the top, demand at the bottom).

    ``bracket_pad`` scales the edge margins: the supply map loses
    contraction near the bracket edges (Cao-Luo-Nie 1905.13045 /
    Ma-Stachurski-Toda 1812.01320), so the sweep's retry ladder re-runs a
    failed cell with a larger pad, trading a few basis points of bracket
    reach for distance from the singular endpoints."""
    dtype = model.a_grid.dtype
    f64 = dtype == jnp.float64   # dtype-ok: dispatch on the model dtype,
    #                              not a hard-coded compute dtype
    if r_tol is None:
        r_tol = 1e-10 if f64 else 1e-6
    if egm_tol is None:
        egm_tol = 1e-6 if f64 else 1e-5
    if dist_tol is None:
        dist_tol = 1e-11 if f64 else 1e-8
    r_hi = jnp.asarray(1.0 / disc_fac - 1.0 - 1e-4 * bracket_pad,
                       dtype=dtype)
    r_lo = jnp.asarray(-depr_fac + 1e-3 * bracket_pad, dtype=dtype)
    return r_tol, egm_tol, dist_tol, r_lo, r_hi


def _bisect(excess_fn, r_lo, r_hi, r_tol, max_bisect: int,
            aux_init=None):
    """Fixed-trip bisection on an excess map that is increasing in r:
    positive excess moves the upper bracket down.  Shared by every
    interest-rate market-clearing loop (homogeneous, beta-dist) and the
    calibration inversions.  Returns ``(r_star, iterations, status)``;
    fully jit/vmap-safe.

    Solver health: a non-finite excess evaluation trips the in-carry
    flag — the bracket is NOT moved by the garbage sign (``NaN > 0`` is
    False, which would silently collapse the upper bracket) and the loop
    exits immediately with status NONFINITE.  A bracket still wider than
    ``r_tol`` at the trip cap is MAX_ITER; otherwise CONVERGED.

    ``aux_init``: if given, ``excess_fn`` must return ``(excess, aux)``
    and the last evaluation's aux rides the loop state — callers that
    want the quantity AT the root (e.g. calibration's "achieved") get it
    without re-solving after the loop.  Returns
    ``(r_star, iterations, aux_last, status)`` in that mode.  The first
    midpoint evaluation runs eagerly (before the ``while_loop``) so aux
    is a real evaluation even when the loop body never executes (initial
    bracket already within ``r_tol``, or ``max_bisect=0`` — which
    therefore still costs one evaluation in aux mode); the total
    evaluation cap stays ``max_bisect``."""
    with_aux = aux_init is not None

    def cond(state):
        lo, hi, it, ok = state[0], state[1], state[2], state[3]
        return ((hi - lo) > r_tol) & (it < max_bisect) & ok

    def body(state):
        lo, hi, it = state[0], state[1], state[2]
        mid = 0.5 * (lo + hi)
        if with_aux:
            ex, aux = excess_fn(mid)
        else:
            ex = excess_fn(mid)
        ok = jnp.isfinite(ex)
        up = ex > 0
        lo = jnp.where(ok & ~up, mid, lo)
        hi = jnp.where(ok & up, mid, hi)
        return (lo, hi, it + 1, ok, aux) if with_aux else (lo, hi, it + 1,
                                                           ok)

    if with_aux:
        init = body((r_lo, r_hi, jnp.asarray(0), jnp.asarray(True),
                     aux_init))
    else:
        init = (r_lo, r_hi, jnp.asarray(0), jnp.asarray(True))
    out = jax.lax.while_loop(cond, body, init)
    lo, hi, it, ok = out[0], out[1], out[2], out[3]
    status = jnp.where(~ok, jnp.int32(NONFINITE),
                       jnp.where((hi - lo) > r_tol, jnp.int32(MAX_ITER),
                                 jnp.int32(CONVERGED)))
    if with_aux:
        return 0.5 * (lo + hi), it, out[4], status
    return 0.5 * (lo + hi), it, status


def solve_bisection_equilibrium(model: SimpleModel, disc_fac, crra,
                                cap_share, depr_fac, prod=1.0,
                                r_tol: float | None = None,
                                max_bisect: int = 60,
                                egm_tol: float | None = None,
                                dist_tol: float | None = None,
                                precision: str = "reference",
                                grid="reference",
                                kernel="reference",
                                state="replicated") -> EquilibriumResult:
    """Bisect r until the capital market clears.

    Fully jit-able/vmappable: a fixed-trip ``while_loop`` whose body solves
    the household problem at the midpoint rate.  ``crra`` (and the traced
    calibration inside ``model``) may be batch axes.  Returns the full
    equilibrium objects (policy, distribution) — the sweep/bench path uses
    ``solve_equilibrium_lean`` instead, which skips the final re-solve.
    """
    r_tol, egm_tol, dist_tol, r_lo, r_hi = _bisection_setup(
        model, disc_fac, depr_fac, r_tol, egm_tol, dist_tol)
    labor = aggregate_labor(model)

    def excess_supply(r):
        supply = household_capital_supply(
            r, model, disc_fac, crra, cap_share, depr_fac, prod,
            egm_tol=egm_tol, dist_tol=dist_tol,
            precision=precision, grid=grid, kernel=kernel,
            state=state).supply
        demand = firm.k_to_l_from_r(r, cap_share, depr_fac, prod) * labor
        return supply - demand

    r_star, iters, bisect_status = _bisect(excess_supply, r_lo, r_hi,
                                           r_tol, max_bisect)

    ev = household_capital_supply(
        r_star, model, disc_fac, crra, cap_share, depr_fac, prod,
        egm_tol=egm_tol, dist_tol=dist_tol, precision=precision,
        grid=grid, kernel=kernel, state=state)
    supply, wage, k_to_l = ev.supply, ev.wage, ev.k_to_l
    demand = k_to_l * labor
    output = prod * supply ** cap_share * labor ** (1.0 - cap_share)
    saving_rate = depr_fac * supply / output
    return EquilibriumResult(
        r_star=r_star, wage=wage, capital=supply, labor=labor,
        saving_rate=saving_rate, excess=supply - demand, policy=ev.policy,
        distribution=ev.distribution, bisect_iters=iters,
        status=combine_status(bisect_status, ev.status))


class LeanEquilibrium(NamedTuple):
    """Scalar-only equilibrium outputs for sweeps: everything else a sweep
    reports (wage, demand, excess, saving rate) is closed-form in these.

    ``egm_iters``/``dist_iters`` are summed over all bisection midpoints —
    the cell's total inner-loop work, which (a) feeds the benchmark's
    grid-points/sec/chip metric and (b) quantifies vmap-of-while skew
    across sweep lanes (VERDICT r1 weak-item 7)."""

    r_star: jnp.ndarray
    capital: jnp.ndarray     # household supply at the last evaluated rate
                             # (bisection midpoint, or Illinois secant point)
    labor: jnp.ndarray
    bisect_iters: jnp.ndarray
    egm_iters: jnp.ndarray   # total EGM backward steps across all midpoints
    dist_iters: jnp.ndarray  # total distribution-iteration steps
    status: jnp.ndarray = CONVERGED  # solver_health code for the cell:
    # worst of (bracket exit, last midpoint's inner fixed points, the
    # non-finite tripwire); `parallel.sweep` quarantines on is_failure()
    descent_steps: jnp.ndarray = 0   # cheap-phase inner steps, all midpoints
    polish_steps: jnp.ndarray = 0    # reference-phase inner steps (== the
    #                                  total under precision="reference")
    escalations: jnp.ndarray = 0     # inner fixed points whose descent fell
    #                                  back to a pure-reference solve
    #                                  (solver_health.PRECISION_ESCALATED)


def solve_equilibrium_lean(model: SimpleModel, disc_fac, crra,
                           cap_share, depr_fac, prod=1.0,
                           r_tol: float | None = None, max_bisect: int = 60,
                           egm_tol: float | None = None,
                           dist_tol: float | None = None,
                           dist_method: str = "auto",
                           egm_method: str = "xla",
                           root_method: str = "bisect",
                           accel_every: int | None = None,
                           bracket_pad: float = 1.0,
                           bracket_init=None,
                           precision: str = "reference",
                           grid="reference",
                           kernel="reference",
                           state="replicated",
                           fault_iter=None,
                           fault_mode: str = "nan",
                           descent_fault_iter: int | None = None,
                           descent_fault_mode: str = "nan",
                           ) -> LeanEquilibrium:
    """Bracketed root-finding equilibrium that carries the supply evaluation
    through the loop state instead of re-solving the household at ``r_star``
    afterwards.

    Halves the compiled program relative to ``solve_bisection_equilibrium``
    (no duplicated solve subgraph after the ``while_loop``) — the sweep/bench
    path, where only scalars are consumed.  ``capital`` is the supply at the
    final evaluation point, within one bracket width (< ``r_tol``) of supply
    at ``r_star``.

    ``root_method``: "bisect" (default) or "illinois" (modified regula
    falsi at the secant point).  Illinois needs ~40% fewer evaluations to
    the same ``r_tol`` bracket certificate (31 -> 18-24 per f64 Table II
    cell), but measured on the TPU sweep it is net SLOWER (2.29s vs
    2.17s, BENCH r2): its early secant points jump across the bracket,
    degrading the warm-start carry exactly on the expensive early solves,
    and under vmap the slowest lane prices the batch (max per-cell work
    rose ~17%).  Fewer-but-colder beats more-but-warmer only without the
    warm-start carry — use "illinois" for single cold solves at loose
    inner tolerances, "bisect" for warm-started sweep lanes.

    Solver health: the returned ``status`` is the worst ``solver_health``
    code seen — the bracket exit (MAX_ITER when the trip cap leaves the
    bracket wider than ``r_tol``), the LAST midpoint's inner fixed-point
    statuses (they ride the loop state like the supply does), and an
    in-loop non-finite tripwire on the excess (a NaN excess would
    otherwise one-side the bracket silently AND poison every later
    midpoint through the warm-start carry; the loop instead freezes the
    bracket and exits NONFINITE immediately).  ``accel_every=0`` /
    ``bracket_pad`` are the sweep retry ladder's knobs (see
    ``household_capital_supply`` / ``_bisection_setup``).

    ``precision`` (DESIGN §5): the mixed-precision ladder policy threaded
    into every inner fixed point of every midpoint evaluation —
    "reference" (default, bit-identical single-phase), "mixed" (cheap
    descent + reference polish, final tolerance contract unchanged),
    "fast" (descent only, tolerance relaxed).  ``descent_steps``/
    ``polish_steps``/``escalations`` on the result split the inner work
    by phase; a descent-phase NONFINITE/STALLED is absorbed INSIDE the
    ladder (pure-reference fallback, counted in ``escalations``), so
    quarantine only sees failures the reference path would also produce.

    ``kernel`` (ISSUE 13, DESIGN §4c): the kernel policy threaded into
    every midpoint evaluation — "reference" (default, bit-identical
    launch-per-loop engines), "fused" (single-phase precision: both
    inner fixed points as ONE device-resident megakernel launch per
    midpoint; two-phase: the bf16 descent rung).  The warm-start carry,
    bracket continuation, and status semantics are unchanged — only the
    engine under each evaluation moves.

    ``fault_iter``/``fault_mode`` are the deterministic fault-injection
    hook (``solver_health``): at bisection trip ``fault_iter`` (may be
    traced; negative = never, which is the vmapped sweep's "this lane is
    healthy" encoding), mode "nan" poisons the excess evaluation (the
    NONFINITE tripwire path), mode "stall" freezes the bracket so the
    loop burns its trip cap (the MAX_ITER path).  ``None`` compiles the
    hook out entirely.

    ``bracket_init``: optional ``(lo0, hi0, it0)`` warm-started bracket
    (traced scalars — the sweep scheduler's per-lane continuation seeds,
    ``parallel.sweep``).  The triple must be a *dyadic descendant* of the
    economic bracket: endpoints produced by iterating ``mid = 0.5*(lo+hi)``
    from ``(r_lo, r_hi)`` and keeping the half predicted to contain the
    root, with ``it0`` the number of levels descended.  The seed is
    VERIFIED before it is trusted: the excess is evaluated at both warm
    endpoints, and only when they actually bracket the root
    (``excess(lo0) <= 0 < excess(hi0)``, both finite) does the loop start
    from the warm triple — excess supply is increasing in r, so a verified
    dyadic sub-bracket certifies every skipped trip's sign and the
    continuation replays the exact cold midpoint sequence (bit-identical
    ``r_star``/``status`` up to inner-solver noise at ``|excess| ~``
    solver tolerance; exactly bit-identical when the seed fails
    verification, because the loop then falls back to the untouched cold
    bracket AND the cold inner warm-start carry).  ``bisect_iters``
    reports actual excess evaluations (2 verification solves + the
    continuation trips), not the replayed level count — the honest work
    number the scheduler's savings are measured by.
    """
    r_tol, egm_tol, dist_tol, r_lo, r_hi = _bisection_setup(
        model, disc_fac, depr_fac, r_tol, egm_tol, dist_tol,
        bracket_pad=bracket_pad)
    labor = aggregate_labor(model)
    dtype = model.a_grid.dtype
    zero = jnp.zeros((), dtype=dtype)
    zi = jnp.asarray(0)
    # Warm-start carry: each midpoint's household solution seeds the next
    # one's inner fixed points (nearby r -> nearby policy/distribution),
    # cutting inner iterations severalfold vs cold starts at every midpoint.
    # Every midpoint still solves to the FULL dist_tol: a looser tolerance
    # at wide brackets risks flipping the excess sign when the root happens
    # to sit near an early midpoint, silently excluding it from the bracket.
    # Under a compact grid policy (DESIGN §5b) the carried policy is
    # tail-closed — the initial iterate must share that shape.
    gspec = resolve_grid(grid)
    p0 = initial_policy(model, analytic_tail=gspec.compact)
    d0 = initial_distribution(model)
    use_illinois = root_method == "illinois"
    if root_method not in ("illinois", "bisect"):
        raise ValueError(f"root_method={root_method!r}: "
                         "expected 'illinois' or 'bisect'")
    one = jnp.asarray(1.0, dtype=dtype)

    spec = resolve_precision(precision)

    def make_eval(prec):
        def eval_at(r, pol, dist):
            return household_capital_supply(
                r, model, disc_fac, crra, cap_share, depr_fac, prod,
                egm_tol=egm_tol, dist_tol=dist_tol,
                init_policy=pol, init_dist=dist, dist_method=dist_method,
                egm_method=egm_method, accel_every=accel_every,
                precision=prec, grid=grid, kernel=kernel, state=state,
                descent_fault_iter=descent_fault_iter,
                descent_fault_mode=descent_fault_mode)
        return eval_at

    # The final-grade evaluation (used by the polish trips and the warm-seed
    # verification): the caller's own policy.  Under "mixed" each of its
    # inner fixed points runs the per-loop ladder — warm-started descent in
    # the cheap dtype, reference polish to the full inner tolerances.
    eval_supply = make_eval(precision)

    def excess_at(r, ev):
        return ev.supply - firm.k_to_l_from_r(r, cap_share, depr_fac,
                                              prod) * labor

    # Warm-started bracket (see docstring): verify the dyadic seed by
    # evaluating the excess at both warm endpoints, fall back to the cold
    # bracket — including the COLD inner-loop inits, so a rejected seed
    # reproduces the cold trajectory exactly — when the seed does not
    # bracket the root.  The two verification solves are charged to the
    # cell's counters; their inner statuses do NOT fold into the final
    # status (they only pick the starting bracket, exactly as the cold
    # bracket's implicit endpoint signs are never certified either).
    it0 = zi
    f_lo0, f_hi0 = -one, one
    egm0 = zi
    dist0 = zi
    desc0 = zi
    pol0 = zi
    esc0 = zi
    n_verify = 0
    if bracket_init is not None:
        lo_w = jnp.asarray(bracket_init[0], dtype=dtype)
        hi_w = jnp.asarray(bracket_init[1], dtype=dtype)
        it_w = jnp.asarray(bracket_init[2])
        # An endpoint still AT the economic bracket needs no verification —
        # the cold path assumes those signs too (and the hi end is the
        # expensive near-singular regime: supply explodes toward
        # (1-beta)/beta, so an evaluation there could cost more than the
        # whole cold solve).  The unneeded slot re-evaluates at the lo
        # point instead: the carry is already its solution, so it
        # converges in a handful of steps and its value is ignored.
        need_lo = lo_w > r_lo
        need_hi = hi_w < r_hi
        ev_lo = eval_supply(lo_w, p0, d0)
        ex_lo = excess_at(lo_w, ev_lo)
        pt_hi = jnp.where(need_hi, hi_w, lo_w)
        ev_hi = eval_supply(pt_hi, ev_lo.policy, ev_lo.distribution)
        ex_hi = excess_at(pt_hi, ev_hi)
        ok_w = ((~need_lo | (jnp.isfinite(ex_lo) & (ex_lo <= 0)))
                & (~need_hi | (jnp.isfinite(ex_hi) & (ex_hi > 0)))
                & (lo_w >= r_lo) & (hi_w <= r_hi) & (hi_w > lo_w)
                # a zero-level seed IS the cold bracket: take the exact
                # cold path (cold inner inits), never a half-warm hybrid
                & (it_w > 0))
        r_lo = jnp.where(ok_w, lo_w, r_lo)
        r_hi = jnp.where(ok_w, hi_w, r_hi)
        it0 = jnp.where(ok_w, it_w.astype(it0.dtype), it0)
        f_lo0 = jnp.where(ok_w & need_lo, ex_lo, f_lo0)
        f_hi0 = jnp.where(ok_w & need_hi, ex_hi, f_hi0)
        p0 = jax.tree_util.tree_map(
            lambda a, b: jnp.where(ok_w, a, b), ev_hi.policy, p0)
        d0 = jnp.where(ok_w, ev_hi.distribution, d0)
        egm0 = egm0 + ev_lo.egm_iters + ev_hi.egm_iters
        dist0 = dist0 + ev_lo.dist_iters + ev_hi.dist_iters
        desc0 = desc0 + ev_lo.descent_steps + ev_hi.descent_steps
        pol0 = pol0 + ev_lo.polish_steps + ev_hi.polish_steps
        esc0 = esc0 + ev_lo.escalations + ev_hi.escalations
        n_verify = 2

    def make_cond(width_tol):
        def cond(state):
            lo, hi = state[0], state[1]
            it = state[4]
            ok = state[11]
            return ((hi - lo) > width_tol) & (it < max_bisect) & ok
        return cond

    def make_body(ev_fn):
        def body(state):
            (lo, hi, f_lo, f_hi, it, _, egm_acc, dist_acc, policy, dist,
             _, _, desc_acc, pol_acc, esc_acc) = state
            if use_illinois:
                # Illinois (modified regula falsi): secant point from the
                # stored endpoint values, clipped to the bracket interior.
                # Endpoint values start as sign-correct placeholders (±1) —
                # evaluating at the raw bracket ends would cost two solves
                # at the pathological extremes (supply near r_hi mixes
                # slowest); the placeholders only misplace the first point
                # or two (the first step IS the midpoint), and the halving
                # rule below guarantees bracket progress regardless.
                mid = hi - f_hi * (hi - lo) / (f_hi - f_lo)
                pad = 0.01 * (hi - lo)
                mid = jnp.clip(mid, lo + pad, hi - pad)
            else:
                mid = 0.5 * (lo + hi)
            ev = ev_fn(mid, policy, dist)
            ex = excess_at(mid, ev)
            freeze = jnp.asarray(False)
            if fault_iter is not None:
                # deterministic fault injection (see docstring): active
                # only when the traced fault_iter is non-negative.  The
                # trip counter runs ACROSS the ladder's descent and polish
                # loops, so an injection at trip k fires in whichever
                # phase reaches k — a poisoned reference excess is a real
                # failure and must surface as NONFINITE, never be healed
                # by the bisection-level escalation.
                hit = (jnp.asarray(fault_iter) >= 0) & (
                    it >= jnp.asarray(fault_iter))
                if fault_mode == "nan":
                    ex = jnp.where(hit, jnp.nan, ex)
                elif fault_mode == "stall":
                    freeze = hit
                else:
                    raise ValueError(f"fault_mode={fault_mode!r}: expected "
                                     "'nan' or 'stall'")
            ok = jnp.isfinite(ex)
            up = ex > 0   # excess supply increasing in r: root below mid
            # a non-finite excess (or an injected stall) must not move the
            # bracket: NaN > 0 is False, which would silently collapse the
            # upper end — freeze it and let the tripwire exit the loop
            move = ok & ~freeze
            new_lo = jnp.where(move & ~up, mid, lo)
            new_hi = jnp.where(move & up, mid, hi)
            # replace the moved endpoint's value with the real one; HALVE
            # the retained endpoint's value (the Illinois anti-stagnation
            # rule — pulls the next secant point toward the stale side)
            new_f_lo = jnp.where(up, 0.5 * f_lo, ex)
            new_f_hi = jnp.where(up, ex, 0.5 * f_hi)
            return (new_lo, new_hi, new_f_lo, new_f_hi, it + 1, ev.supply,
                    egm_acc + ev.egm_iters, dist_acc + ev.dist_iters,
                    ev.policy, ev.distribution, ev.status, ok,
                    desc_acc + ev.descent_steps, pol_acc + ev.polish_steps,
                    esc_acc + ev.escalations)
        return body

    init = (r_lo, r_hi, f_lo0, f_hi0, it0, zero, egm0, dist0, p0, d0,
            jnp.int32(CONVERGED), jnp.asarray(True), desc0, pol0, esc0)
    esc_trips = zi   # descent trips re-granted to an escalated polish
    if not spec.two_phase:
        final = jax.lax.while_loop(make_cond(r_tol), make_body(eval_supply),
                                   init)
        width_tol = r_tol
    else:
        # Bisection-level ladder (DESIGN §5): while the bracket is WIDE,
        # the midpoint evaluations only steer it — their fine-scale error
        # is erased by later trips — so they run descent-only ("fast"
        # inner solves: cheap dtype, tolerances floored at what it can
        # certify).  The switch width is set so the cheap phase's root-
        # placement noise (measured f32-vs-f64 drift: ~1e-6 in r units,
        # 0.097 bp over all 12 Table II cells) is orders of magnitude
        # smaller than the remaining bracket.
        cheap_eps = float(jnp.finfo(descent_dtype(dtype)).eps)
        r_switch = max(float(r_tol), R_DESCENT_WIDTH_SCALE * cheap_eps)
        state_a = jax.lax.while_loop(make_cond(r_switch),
                                     make_body(make_eval("fast")), init)
        if not spec.polish:
            final = state_a
            width_tol = r_switch   # "fast": contract relaxed, honestly
        else:
            (lo_a, hi_a, _, _, it_a, sup_a, egm_a, dist_a, pol_a, d_a,
             _, ok_a, desc_a, polish_a, esc_a) = state_a
            # Bisection-level escalation: a NONFINITE excess in the cheap
            # descent must not steer (or seed) the polish — restart it
            # from the untouched bracket and cold inner inits, exactly a
            # reference-grade solve (PRECISION_ESCALATED; quarantine only
            # ever sees failures the reference path would also produce).
            esc_b = ~ok_a
            # Re-bracket with a half-width safety margin on each side:
            # the cheap phase places the root to ~1e-6 while the margin is
            # ~0.5 * r_switch, so the widened bracket contains the true
            # root with two orders of magnitude to spare (the same
            # unverified-sign assumption the economic bracket itself
            # rests on), at the cost of a single extra trip.
            w_a = hi_a - lo_a
            lo_b = jnp.maximum(r_lo, lo_a - 0.5 * w_a)
            hi_b = jnp.minimum(r_hi, hi_a + 0.5 * w_a)
            lo_b = jnp.where(esc_b, r_lo, lo_b)
            hi_b = jnp.where(esc_b, r_hi, hi_b)
            pol_b = jax.tree_util.tree_map(
                lambda cold, warm: jnp.where(esc_b, cold, warm), p0, pol_a)
            d_b = jnp.where(esc_b, d0, d_a)
            # An escalated lane's restart is a FULL reference-grade solve:
            # reset its trip counter to the pre-loop value so the polish
            # gets the whole max_bisect budget (the descent trips it
            # burned must not make the fallback MAX_ITER where a plain
            # reference solve would converge — quarantine may only see
            # failures the reference path would also produce).  The burnt
            # trips are added back into the honest eval count below.
            it_b0 = jnp.where(esc_b, it0, it_a)
            esc_trips = jnp.where(esc_b, it_a - it0,
                                  jnp.zeros_like(it_a))
            init_b = (lo_b, hi_b, -one, one, it_b0, sup_a, egm_a, dist_a,
                      pol_b, d_b, jnp.int32(CONVERGED), jnp.asarray(True),
                      desc_a, polish_a,
                      esc_a + esc_b.astype(esc_a.dtype))
            final = jax.lax.while_loop(make_cond(r_tol),
                                       make_body(eval_supply), init_b)
            width_tol = r_tol

    (lo, hi, _, _, iters, supply, egm_iters, dist_iters, _, _,
     inner_status, ok, descent_steps, polish_steps, escalations) = final
    # worst of: the non-finite tripwire, the bracket exit, and the LAST
    # midpoint's inner fixed-point statuses (earlier midpoints' inner
    # exits don't certify anything about the returned objects; a
    # NONFINITE one cannot be missed — it poisons the excess and trips
    # `ok` on that very evaluation)
    status = combine_status(
        jnp.where(~ok, jnp.int32(NONFINITE), jnp.int32(CONVERGED)),
        jnp.where((hi - lo) > width_tol, jnp.int32(MAX_ITER),
                  jnp.int32(CONVERGED)),
        inner_status)
    # honest work accounting: evaluations actually performed (continuation
    # trips + the 2 warm-seed verification solves), not the replayed level
    # count — identical to the trip count on the cold path
    evals = iters - it0 + n_verify + esc_trips
    return LeanEquilibrium(r_star=0.5 * (lo + hi), capital=supply,
                           labor=labor, bisect_iters=evals,
                           egm_iters=egm_iters, dist_iters=dist_iters,
                           status=status, descent_steps=descent_steps,
                           polish_steps=polish_steps,
                           escalations=escalations)


def _solve_cell(solver, crra, labor_ar, labor_sd=0.2, labor_states=7,
                disc_fac=0.96, cap_share=0.36, depr_fac=0.08,
                a_min=0.001, a_max=50.0, a_count=32, a_nest_fac=2,
                dist_count=500, grid="reference", dtype=None,
                **solver_kwargs):
    """Build the model for one (crra, rho, sd) cell and run ``solver`` on it.
    ``crra``/``labor_ar``/``labor_sd`` may be traced (vmap over cells); every
    other argument is static structure.  ``grid`` (DESIGN §5b) shapes BOTH
    sides: the model build (compacted asset/histogram grids) and the
    solver (analytic tail + coarse-to-fine ladder)."""
    model = build_simple_model(
        labor_states=labor_states, labor_ar=labor_ar, labor_sd=labor_sd,
        a_min=a_min, a_max=a_max, a_count=a_count, a_nest_fac=a_nest_fac,
        dist_count=dist_count, grid=grid, dtype=dtype)
    return solver(model, disc_fac, crra, cap_share, depr_fac, grid=grid,
                  **solver_kwargs)


def solve_calibration(crra: float, labor_ar: float,
                      **kwargs) -> EquilibriumResult:
    """One Table II cell with the full equilibrium objects."""
    return _solve_cell(solve_bisection_equilibrium, crra, labor_ar, **kwargs)


def solve_calibration_lean(crra: float, labor_ar: float,
                           **kwargs) -> LeanEquilibrium:
    """One Table II cell, scalars only — the sweep/bench fast path."""
    return _solve_cell(solve_equilibrium_lean, crra, labor_ar, **kwargs)
