"""Finite-horizon (life-cycle) household problem: backward induction as a
``lax.scan`` over ages, plus a cohort simulator.

The reference inherits HARK's finite-horizon machinery (``AgentType`` with
``cycles >= 1`` — the lifecycle mode of the same ``solve_one_period``
apparatus the notebook runs with ``cycles=0`` at ``Aiyagari-HARK.py:237``)
but never exercises it.  This module provides the working TPU-native
equivalent: the same EGM backward step as the infinite-horizon solver
(``models.household.egm_step``), scanned ``horizon`` times from the terminal
consume-everything solution, with optional age-varying income profiles and
survival probabilities — enough to express the standard life-cycle
consumption/saving model (hump-shaped wealth, retirement dissaving).

Everything is one jitted program: ages are a scan axis, the age-stacked
policy is a single ``[T, N, K]`` array pytree, and the cohort simulator
scans forward over the same arrays.  No Python loops over ages or agents.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

import jax.numpy as _jnp

from ..ops.interp import interp1d_rowwise
from .household import (
    CONSTRAINT_EPS,
    HouseholdPolicy,
    SimpleModel,
    egm_step,
)


def _terminal_consume_everything(model: SimpleModel) -> HouseholdPolicy:
    """Finite-horizon terminal policy: c = m exactly (die with nothing —
    no terminal debt).  NOT ``initial_policy``: that returns c = m - b,
    correct as an infinite-horizon seed but wrong as a last age under a
    negative borrowing limit (agents would die owing b).  Knot positions
    are irrelevant for representing the identity — any increasing positive
    knots on the line c = m interpolate AND extrapolate it exactly."""
    n = model.labor_levels.shape[0]
    eps = _jnp.asarray(CONSTRAINT_EPS, dtype=model.a_grid.dtype)
    m_row = _jnp.concatenate(
        [eps[None], model.a_grid - model.a_grid[0] + 2.0 * eps])
    m_knots = _jnp.tile(m_row, (n, 1))
    return HouseholdPolicy(m_knots=m_knots, c_knots=m_knots)


class LifecyclePolicy(NamedTuple):
    """Age-stacked consumption policy: index 0 is the first age."""

    m_knots: jnp.ndarray    # [T, N, K]
    c_knots: jnp.ndarray    # [T, N, K]


def solve_lifecycle(R, W, model: SimpleModel, disc_fac, crra,
                    horizon: int, income_profile=None,
                    survival=None) -> LifecyclePolicy:
    """Backward induction over ``horizon`` ages.

    ``income_profile`` ([T], default ones): age-specific scaling of labor
    income — age t earns ``W * income_profile[t] * l``.  ``survival``
    ([T], default ones): probability of reaching age t+1 from age t,
    multiplying the discount factor (utility after death is zero, the
    standard perishable-annuity-free formulation).  The terminal age
    consumes everything (c = m), the reference's ``IdentityFunction``
    terminal guess made exact (``Aiyagari_Support.py:898``).

    Returns the age-stacked policy; scalars may be traced.
    """
    dtype = model.a_grid.dtype
    if income_profile is None:
        income_profile = jnp.ones((horizon,), dtype=dtype)
    else:
        income_profile = jnp.asarray(income_profile, dtype=dtype)
    if survival is None:
        survival = jnp.ones((horizon,), dtype=dtype)
    else:
        survival = jnp.asarray(survival, dtype=dtype)
    terminal = _terminal_consume_everything(model)

    def step(pol_next, x):
        w_next_scale, disc_t = x
        pol = egm_step(pol_next, R, W * w_next_scale, model, disc_t, crra)
        return pol, pol

    # age t's step consumes age t+1's policy, income scale, and t's survival
    xs = (income_profile[1:][::-1], disc_fac * survival[:-1][::-1])
    _, stacked = jax.lax.scan(step, terminal, xs)
    m_all = jnp.concatenate([stacked.m_knots[::-1],
                             terminal.m_knots[None]], axis=0)
    c_all = jnp.concatenate([stacked.c_knots[::-1],
                             terminal.c_knots[None]], axis=0)
    return LifecyclePolicy(m_knots=m_all, c_knots=c_all)


class CohortProfile(NamedTuple):
    """Mean per-age outcomes of a simulated cohort."""

    assets: jnp.ndarray        # [T] mean end-of-age assets
    consumption: jnp.ndarray   # [T] mean consumption
    income: jnp.ndarray        # [T] mean labor income


def simulate_cohort(policy: LifecyclePolicy, R, W, model: SimpleModel,
                    n_agents: int, key: jax.Array, income_profile=None,
                    a0: float = 0.0) -> CohortProfile:
    """Forward-simulate a birth cohort through the whole life cycle.

    Agents are born with assets ``a0`` and labor states drawn from the
    ergodic distribution; each age is one scan step (categorical labor
    draw over the panel, age-indexed policy evaluation, budget identity) —
    the lifecycle analog of ``models.simulate.simulate_panel``.
    """
    horizon = policy.m_knots.shape[0]
    dtype = model.a_grid.dtype
    if income_profile is None:
        income_profile = jnp.ones((horizon,), dtype=dtype)
    else:
        income_profile = jnp.asarray(income_profile, dtype=dtype)
    k_birth, k_sim = jax.random.split(key)
    logp = jnp.log(model.transition)
    s0 = jax.random.categorical(k_birth, jnp.log(model.labor_stationary),
                                shape=(n_agents,))
    a_init = jnp.full((n_agents,), a0, dtype=dtype)

    def step(carry, x):
        a, s = carry
        t, k = x
        s = jax.random.categorical(k, logp[s]).astype(s.dtype)
        income = W * income_profile[t] * model.labor_levels[s]
        m = R * a + income
        # rowwise interp with per-agent gathered knots (agent i uses its
        # state's knot row of the age-t policy)
        c = interp1d_rowwise(m, policy.m_knots[t][s], policy.c_knots[t][s])
        a_new = m - c
        return (a_new, s), (jnp.mean(a_new), jnp.mean(c), jnp.mean(income))

    keys = jax.random.split(k_sim, horizon)
    (_, _), (a_prof, c_prof, y_prof) = jax.lax.scan(
        step, (a_init, s0), (jnp.arange(horizon), keys))
    return CohortProfile(assets=a_prof, consumption=c_prof, income=y_prof)
