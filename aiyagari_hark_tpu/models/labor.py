"""Endogenous labor supply: the Aiyagari economy with a
consumption-leisure choice (Pijoan-Mas 2006-style).

The reference fixes hours exogenously (its `IdioLS` grid is a pure
endowment process, `Aiyagari_Support.py:985-1018`); here households also
choose hours n with separable preferences

    u(c) - chi * n^(1+1/nu) / (1 + 1/nu)

(CRRA consumption, constant Frisch elasticity ``nu``), so effective
labor ``E[e·n]`` — and with it the firm's labor input — becomes an
equilibrium object.

TPU shape: the intratemporal first-order condition
``chi n^(1/nu) = W e u'(c)`` has the closed form ``n = (W e u'(c)/chi)^nu``,
so the EGM backward step stays one batched array program: expectation
matmul → FOC inversion → hours from the closed form → endogenous
BEGINNING-OF-PERIOD asset knots from the budget (the state is beginning
assets ``a``, not cash-on-hand, because income now depends on the
choice).  Only the borrowing-constrained region has no closed form —
there consumption and hours solve a one-equation static problem, handled
by a vectorized, fixed-trip Newton at *evaluation* points (masked where
the constraint doesn't bind) instead of interpolated constrained knots,
so the constrained policy is exact, shapes stay static, and the knot
arrays stay sorted by construction.

The wealth-distribution machinery (Young lottery, accelerated power
iteration) is reused from ``household`` unchanged; the equilibrium
bisection reuses ``equilibrium._bisect`` with BOTH capital supply and
effective labor supply endogenous.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..ops.interp import interp1d_rowwise
from ..ops.utility import inverse_marginal_utility, marginal_utility
from . import firm
from .equilibrium import _bisect, _bisection_setup
from .household import (
    SimpleModel,
    WealthTransition,
    _push_forward,
    accelerated_distribution_fixed_point,
    aggregate_capital,
    build_simple_model,
    initial_distribution,
    locate_in_grid,
)


class LaborModel(NamedTuple):
    """A ``SimpleModel`` plus labor-supply preferences.  ``base.labor_levels``
    is reinterpreted as idiosyncratic PRODUCTIVITY e (hours get chosen)."""

    base: SimpleModel
    frisch: jnp.ndarray        # nu: constant Frisch elasticity of hours
    labor_weight: jnp.ndarray  # chi: disutility weight (calibrates mean hours)


class LaborPolicy(NamedTuple):
    """Per-state endogenous knots on BEGINNING-OF-PERIOD assets, [N, A]."""

    a_knots: jnp.ndarray
    c_knots: jnp.ndarray
    n_knots: jnp.ndarray


def build_labor_model(frisch: float = 1.0, labor_weight: float = 12.0,
                      **kwargs) -> LaborModel:
    """Calibration arrays for the labor-choice economy; ``kwargs`` pass
    through to ``build_simple_model``.  The default ``labor_weight`` puts
    mean hours around 1/3 at the notebook prices."""
    base = build_simple_model(**kwargs)
    dtype = base.a_grid.dtype
    return LaborModel(base=base,
                      frisch=jnp.asarray(frisch, dtype=dtype),
                      labor_weight=jnp.asarray(labor_weight, dtype=dtype))


def hours_from_foc(c, e, W, model: LaborModel, crra):
    """The intratemporal FOC in closed form: n = (W e u'(c)/chi)^nu."""
    return (W * e * marginal_utility(c, crra)
            / model.labor_weight) ** model.frisch


def _constrained_solve(a_beg, e, R, W, model: LaborModel, crra,
                       newton_iters: int = 40):
    """Static problem where the borrowing constraint binds (a' = b):
    solve chi n^(1/nu) = W e u'(R a + W e n - b) for hours by fixed-trip
    Newton — the residual is strictly increasing in n, so the root is
    unique; iterates are clipped to keep consumption positive.  All
    arguments broadcast elementwise."""
    b = model.base.borrow_limit
    we = W * e
    c_floor = jnp.asarray(1e-10, dtype=model.base.a_grid.dtype)
    # feasibility: c = R a + we n - b > 0
    n_min = jnp.maximum((b + c_floor - R * a_beg) / we, 1e-9)

    def body(n, _):
        c = jnp.maximum(R * a_beg + we * n - b, c_floor)
        g = (model.labor_weight * n ** (1.0 / model.frisch)
             - we * marginal_utility(c, crra))
        gp = (model.labor_weight / model.frisch
              * n ** (1.0 / model.frisch - 1.0)
              + we * we * crra * c ** (-crra - 1.0))
        n = jnp.maximum(n - g / gp, n_min)
        return n, None

    n0 = jnp.maximum(jnp.full_like(a_beg + e, 0.3), n_min)
    n, _ = jax.lax.scan(body, n0, None, length=newton_iters)
    c = jnp.maximum(R * a_beg + we * n - b, c_floor)
    return c, n


def labor_policy_at(policy: LaborPolicy, a, R, W, model: LaborModel,
                    crra, constrained_values=None):
    """Evaluate (c, n, a') at beginning-of-period assets ``a`` [P] for
    every productivity state: interpolation on the endogenous knots where
    unconstrained, the exact Newton static solve where the constraint
    binds (a below the state's first endogenous knot).  Returns
    [P, N] arrays; the budget identity a' = R a + W e n - c holds
    exactly in the unconstrained region and a' = b exactly in the
    constrained one.

    ``constrained_values``: optional precomputed ``(c_con, n_con)`` at
    these evaluation points — the static problem depends only on
    (a, e, R, W), not on the evolving policy, so fixed-point loops hoist
    the 40-trip Newton out of the iteration (XLA's loop-invariant motion
    is not guaranteed across a nested scan)."""
    e = model.base.labor_levels                         # [N]
    a_tiled = jnp.broadcast_to(a[None, :],
                               (e.shape[0], a.shape[0]))  # [N, P]
    c_i = interp1d_rowwise(a_tiled, policy.a_knots, policy.c_knots).T
    n_i = interp1d_rowwise(a_tiled, policy.a_knots, policy.n_knots).T
    a_next_i = R * a[:, None] + W * e[None, :] * n_i - c_i
    if constrained_values is None:
        constrained_values = _constrained_solve(a[:, None], e[None, :],
                                                R, W, model, crra)
    c_con, n_con = constrained_values
    constrained = a[:, None] < policy.a_knots.T[0][None, :]
    c = jnp.where(constrained, c_con, c_i)
    n = jnp.where(constrained, n_con, n_i)
    a_next = jnp.where(constrained, model.base.borrow_limit, a_next_i)
    return c, n, a_next


def initial_labor_policy(model: LaborModel) -> LaborPolicy:
    """Terminal-style guess: consume beginning resources at fixed hours
    1/3 — only a starting point for the fixed-point iteration."""
    base = model.base
    n = base.labor_levels.shape[0]
    a = jnp.tile(base.a_grid[None, :], (n, 1))          # [N, A]
    n0 = jnp.full_like(a, 1.0 / 3.0)
    c0 = jnp.maximum(a - base.borrow_limit, 1e-3) + 0.5
    return LaborPolicy(a_knots=a, c_knots=c0, n_knots=n0)


def egm_step_labor(policy: LaborPolicy, R, W, model: LaborModel,
                   disc_fac, crra, constrained_values=None,
                   R_today=None, W_today=None) -> LaborPolicy:
    """One EGM backward step.  Next-period consumption is evaluated at
    beginning assets = today's end-of-period grid (constraint-exact via
    ``labor_policy_at``); the envelope v'(a) = R u'(c) makes the
    expectation one [A,N']x[N',N] matmul; hours come from the closed-form
    intratemporal FOC; the endogenous knot is beginning assets from the
    budget.  ``constrained_values``: see ``labor_policy_at``.

    ``(R, W)`` price the CONTINUATION (next period's resources and
    policy); today's hours FOC and budget use ``(R_today, W_today)``,
    defaulting to the same prices — the stationary case.  Transition
    paths pass both (date-t step: R/W at t+1, R_today/W_today at t)."""
    base = model.base
    a = base.a_grid                                     # [A] end-of-period
    e = base.labor_levels
    R_today = R if R_today is None else R_today
    W_today = W if W_today is None else W_today
    c_next, _, _ = labor_policy_at(policy, a, R, W, model, crra,
                                   constrained_values)  # [A, N']
    vp_next = marginal_utility(c_next, crra)
    end_vp = disc_fac * R * jnp.matmul(
        vp_next, base.transition.T, precision=jax.lax.Precision.HIGHEST)
    c_now = inverse_marginal_utility(end_vp, crra)      # [A, N]
    n_now = hours_from_foc(c_now, e[None, :], W_today, model, crra)
    a_beg = (c_now + a[:, None]
             - W_today * e[None, :] * n_now) / R_today
    return LaborPolicy(a_knots=a_beg.T, c_knots=c_now.T,
                       n_knots=n_now.T)


def solve_labor_household(R, W, model: LaborModel, disc_fac, crra,
                          tol: float = 1e-6, max_iter: int = 3000,
                          init_policy: LaborPolicy | None = None):
    """Infinite-horizon fixed point of ``egm_step_labor`` (sup-norm on
    consumption knots).  Returns (policy, n_iter, final_diff)."""
    p0 = initial_labor_policy(model) if init_policy is None else init_policy
    big = jnp.asarray(jnp.inf, dtype=p0.c_knots.dtype)
    base = model.base
    # policy-independent: hoist the constrained-region Newton out of the
    # fixed-point loop (one solve per (R, W), not one per EGM step)
    con = _constrained_solve(base.a_grid[:, None],
                             base.labor_levels[None, :], R, W, model,
                             crra)

    def cond(state):
        _, diff, it = state
        return (diff > tol) & (it < max_iter)

    def body(state):
        policy, _, it = state
        new = egm_step_labor(policy, R, W, model, disc_fac, crra,
                             constrained_values=con)
        diff = jnp.max(jnp.abs(new.c_knots - policy.c_knots))
        return new, diff, it + 1

    policy, diff, it = jax.lax.while_loop(
        cond, body, (p0, big, jnp.asarray(0)))
    return policy, it, diff


def labor_wealth_transition(policy: LaborPolicy, R, W,
                            model: LaborModel, crra):
    """Young-lottery transition on the histogram support, plus the (c, n)
    policies on that support (reused for the aggregates)."""
    base = model.base
    c, n, a_next = labor_policy_at(policy, base.dist_grid, R, W, model,
                                   crra)
    a_next = jnp.clip(a_next, base.borrow_limit, base.dist_grid[-1])
    idx, w = locate_in_grid(a_next, base.dist_grid)
    return WealthTransition(idx=idx, weight=w, a_next=a_next), c, n


def stationary_labor_wealth(policy: LaborPolicy, R, W, model: LaborModel,
                            crra, tol: float = 1e-11,
                            max_iter: int = 20000, init_dist=None):
    """Stationary joint distribution over (wealth, productivity) via the
    shared accelerated power iteration.  Returns (dist, c, n, iters,
    diff) with the policies on the histogram support."""
    base = model.base
    trans, c, n = labor_wealth_transition(policy, R, W, model, crra)
    dist0 = (initial_distribution(base) if init_dist is None
             else init_dist)
    dist, it, diff, _ = accelerated_distribution_fixed_point(
        lambda d: _push_forward(d, trans, base.transition),
        dist0, tol, max_iter)
    return dist, c, n, it, diff


class LaborEquilibrium(NamedTuple):
    r_star: jnp.ndarray
    wage: jnp.ndarray
    capital: jnp.ndarray
    effective_labor: jnp.ndarray   # E[e n] — now an equilibrium object
    mean_hours: jnp.ndarray        # E[n]
    saving_rate: jnp.ndarray
    excess: jnp.ndarray
    policy: LaborPolicy
    distribution: jnp.ndarray
    bisect_iters: jnp.ndarray
    status: jnp.ndarray = 0        # solver_health code of the bisection exit


def _labor_supply_eval(r, model: LaborModel, disc_fac, crra, cap_share,
                       depr_fac, egm_tol, dist_tol):
    """Household side at rate r: (capital supply, effective labor supply,
    mean hours, policy, distribution, wage)."""
    base = model.base
    k_to_l = firm.k_to_l_from_r(r, cap_share, depr_fac)
    W = firm.wage_rate(k_to_l, cap_share)
    policy, _, _ = solve_labor_household(1.0 + r, W, model, disc_fac,
                                         crra, tol=egm_tol)
    dist, _, n, _, _ = stationary_labor_wealth(policy, 1.0 + r, W, model,
                                               crra, tol=dist_tol)
    k_supply = aggregate_capital(dist, base)
    l_supply = jnp.sum(dist * base.labor_levels[None, :] * n)
    hours = jnp.sum(dist * n)
    return k_supply, l_supply, hours, policy, dist, W


class LaborTransitionResult(NamedTuple):
    """Perfect-foresight path of the labor-supply economy after a TFP
    impulse: with hours chosen each period, BOTH factor inputs are
    equilibrium paths."""

    k_path: jnp.ndarray        # [T] capital in production at t
    l_path: jnp.ndarray        # [T] effective labor at t
    hours_path: jnp.ndarray    # [T] mean hours
    r_path: jnp.ndarray        # [T]
    w_path: jnp.ndarray        # [T]
    y_path: jnp.ndarray        # [T] output
    c_agg_path: jnp.ndarray    # [T]
    converged: jnp.ndarray
    iterations: jnp.ndarray
    max_diff: jnp.ndarray


def _labor_prices(k_path, l_path, prod_path, cap_share, depr_fac):
    """Factor prices along a joint (K, L) path — the ONE price block
    shared by the path map and the transition epilogue."""
    k_to_l = k_path / l_path
    r = firm.interest_factor(k_to_l, cap_share, depr_fac, prod_path) - 1.0
    w = firm.wage_rate(k_to_l, cap_share, prod_path)
    return r, w


def labor_path_map(k_path, l_path, prod_path, model: LaborModel,
                   disc_fac, crra, cap_share, depr_fac, init_dist,
                   terminal_policy: LaborPolicy):
    """The labor economy's sequence-space map: guessed (K, L) paths and a
    TFP path in, household-implied (K, L) paths plus the consumption and
    mean-hours paths out — one backward labor-EGM scan (continuation
    prices at t+1, intratemporal FOC/budget at t, per-date constrained
    Newton) and one forward histogram scan.  ``solve_labor_transition``
    iterates it to a fixed point; ``jacobian.labor_sequence_jacobians``
    differentiates it.  K_0 is pinned to E[a] under ``init_dist``
    (constant in the inputs), L has no predetermined entry.

    Returns ``(k_implied [T], l_implied [T], hours [T], c_agg [T])``.
    """
    base = model.base
    e = base.labor_levels
    k0 = aggregate_capital(init_dist, base)
    r_path, w_path = _labor_prices(k_path, l_path, prod_path, cap_share,
                                   depr_fac)

    def backward_step(pol_next, inputs):
        r_next, w_next, r_t, w_t = inputs
        con = _constrained_solve(base.a_grid[:, None], e[None, :],
                                 1.0 + r_next, w_next, model, crra)
        pol = egm_step_labor(pol_next, 1.0 + r_next, w_next, model,
                             disc_fac, crra, constrained_values=con,
                             R_today=1.0 + r_t, W_today=w_t)
        return pol, pol

    # date t consumes t+1's continuation prices; beyond the horizon the
    # terminal steady state applies
    r_next = jnp.concatenate([r_path[1:], r_path[-1:]])
    w_next = jnp.concatenate([w_path[1:], w_path[-1:]])
    _, pols = jax.lax.scan(backward_step, terminal_policy,
                           (r_next, w_next, r_path, w_path),
                           reverse=True)

    def forward_step(dist, inputs):
        pol, r_t, w_t = inputs
        trans, c, n = labor_wealth_transition(pol, 1.0 + r_t, w_t,
                                              model, crra)
        k_next = jnp.sum(dist * trans.a_next)
        l_t = jnp.sum(dist * e[None, :] * n)
        hours = jnp.sum(dist * n)
        # budget-consistent consumption against the FEASIBLE (post-clip)
        # savings, so C_t + K_{t+1} = (1-d)K_t + Y_t holds exactly along
        # the reported path — the same invariant transition._forward_step
        # keeps
        income = ((1.0 + r_t) * base.dist_grid[:, None]
                  + w_t * e[None, :] * n)
        c_agg = jnp.sum(dist * (income - trans.a_next))
        new = _push_forward(dist, trans, base.transition)
        return new, (k_next, l_t, hours, c_agg)

    _, (k_next, l_t, hours, c_agg) = jax.lax.scan(
        forward_step, init_dist, (pols, r_path, w_path))
    k_implied = jnp.concatenate([k0[None], k_next[:-1]])
    return k_implied, l_t, hours, c_agg


def solve_labor_transition(model: LaborModel, disc_fac, crra, cap_share,
                           depr_fac, init_dist: jnp.ndarray,
                           terminal_policy: LaborPolicy,
                           k_terminal, l_terminal, horizon: int,
                           prod_path=None, damping: float = 0.85,
                           tol: float = 1e-6,
                           max_iter: int = 400) -> LaborTransitionResult:
    """MIT-shock transition with endogenous hours: the fixed point runs
    on the JOINT (K, L) path — prices from both marginal products,
    backward ``lax.scan`` of the labor-EGM step (continuation prices at
    t+1, intratemporal FOC and budget at t, per-date constrained Newton),
    forward histogram scan giving implied capital AND effective labor.

    This is where the labor margin earns its keep dynamically: a TFP
    impulse raises the wage, hours rise on impact (substitution beats
    the wealth effect for the calibrated Frisch), and output amplifies
    above the TFP shock itself — the RBC hallmark the fixed-labor
    transition cannot produce (its L is a constant).  ``l_terminal``
    comes from the terminal stationary equilibrium
    (``solve_labor_equilibrium(...).effective_labor``)."""
    base = model.base
    dtype = base.a_grid.dtype
    if prod_path is None:
        prod_path = jnp.ones((horizon,), dtype=dtype)
    else:
        prod_path = jnp.asarray(prod_path, dtype=dtype)
    k0 = aggregate_capital(init_dist, base)
    frac = jnp.linspace(0.0, 1.0, horizon, dtype=dtype)
    k_guess = jnp.exp((1.0 - frac) * jnp.log(k0)
                      + frac * jnp.log(jnp.asarray(k_terminal,
                                                   dtype=dtype)))
    l_guess = jnp.full((horizon,), l_terminal, dtype=dtype)

    def implied(k_path, l_path):
        return labor_path_map(k_path, l_path, prod_path, model, disc_fac,
                              crra, cap_share, depr_fac, init_dist,
                              terminal_policy)

    big = jnp.asarray(jnp.inf, dtype=dtype)

    def cond(state):
        _, _, diff, it = state
        return (diff > tol) & (it < max_iter)

    def body(state):
        k_path, l_path, _, it = state
        k_implied, l_implied, _, _ = implied(k_path, l_path)
        diff = jnp.maximum(jnp.max(jnp.abs(k_implied - k_path)),
                           jnp.max(jnp.abs(l_implied - l_path)))
        k_new = damping * k_path + (1.0 - damping) * k_implied
        l_new = damping * l_path + (1.0 - damping) * l_implied
        return k_new, l_new, diff, it + 1

    k_path, l_path, diff, it = jax.lax.while_loop(
        cond, body, (k_guess, l_guess, big, jnp.asarray(0)))
    r_path, w_path = _labor_prices(k_path, l_path, prod_path, cap_share,
                                   depr_fac)
    _, _, hours, c_agg = implied(k_path, l_path)
    y_path = firm.output(k_path, l_path, cap_share, prod_path)
    return LaborTransitionResult(
        k_path=k_path, l_path=l_path, hours_path=hours, r_path=r_path,
        w_path=w_path, y_path=y_path, c_agg_path=c_agg,
        converged=diff <= tol, iterations=it, max_diff=diff)


def solve_labor_equilibrium(model: LaborModel, disc_fac, crra, cap_share,
                            depr_fac, r_tol: float | None = None,
                            max_bisect: int = 60,
                            egm_tol: float | None = None,
                            dist_tol: float | None = None
                            ) -> LaborEquilibrium:
    """Bisect r until the capital market clears with BOTH sides moving:
    household capital supply and effective labor supply respond to r, the
    firm's demand is ``k_to_l(r) * L_supply(r)``.  Excess supply is still
    increasing in r (labor supply falls with the wealth effect as r
    rises, lowering demand further), so the shared bisection applies."""
    r_tol, egm_tol, dist_tol, r_lo, r_hi = _bisection_setup(
        model.base, disc_fac, depr_fac, r_tol, egm_tol, dist_tol)

    def excess(r):
        k_s, l_s, _, _, _, _ = _labor_supply_eval(
            r, model, disc_fac, crra, cap_share, depr_fac, egm_tol,
            dist_tol)
        demand = firm.k_to_l_from_r(r, cap_share, depr_fac) * l_s
        return k_s - demand

    r_star, iters, status = _bisect(excess, r_lo, r_hi, r_tol, max_bisect)
    k_s, l_s, hours, policy, dist, W = _labor_supply_eval(
        r_star, model, disc_fac, crra, cap_share, depr_fac, egm_tol,
        dist_tol)
    demand = firm.k_to_l_from_r(r_star, cap_share, depr_fac) * l_s
    y = firm.output(k_s, l_s, cap_share)
    return LaborEquilibrium(
        r_star=r_star, wage=W, capital=k_s, effective_labor=l_s,
        mean_hours=hours, saving_rate=depr_fac * k_s / y,
        excess=k_s - demand, policy=policy, distribution=dist,
        bisect_iters=iters, status=status)
