"""Krusell-Smith-machinery Aiyagari model — the reference-parity path.

This is the TPU-native rebuild of the reference's full apparatus
(``Aiyagari_Support.py``): the 4N-state space (2 aggregate x 2 employment x N
labor states, ordered ``s = 4*labor + (2*agg + employed)`` exactly as the
reference's ``MrkvIndArray``), the aggregate-resource grid M, the perceived
aggregate saving rule ``A = exp(intercept + slope log M)``, the EGM solver
over ``[aCount, Mcount, 4N]``, and the precomputed-array factory.  The
reference runs this machinery with the aggregate shock switched off
(ProdB=ProdG=1, UrateB=UrateG=0 — SURVEY.md §0); with those parameters
changed it *is* a working true Krusell-Smith model (the reference's broken
D2/D3 intent, SURVEY.md §2.2).

Design: a solution is a pair of knot arrays ``[S, Mc, A+1]`` (not 28x16
interpolator objects); precompute is a pure jitted function of the AFunc
parameters (re-run each outer iteration, as the reference does at
``Aiyagari_Support.py:923-927``); the expectation step is one batched matmul
over the composite transition matrix.  All shapes static, N-generic.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..ops.grids import make_asset_grid  # grid-ok: KS reference-parity path, no grid policy
from ..ops.interp import interp_on_interp
from ..ops.markov import (
    aggregate_markov_matrix,
    employment_markov_matrix,
    full_idiosyncratic_matrix,
    normalized_labor_states,
    tauchen_labor_process,
)
from ..ops.utility import inverse_marginal_utility, marginal_utility
from ..utils.config import AgentConfig, EconomyConfig
from . import firm
from .household import CONSTRAINT_EPS


class KSCalibration(NamedTuple):
    """Static calibration arrays + scalars for the 4N-state model.

    Columns indexed by next-period state s' carry the aggregate objects the
    reference tiles into [.., 4N] blocks (``Aiyagari_Support.py:935-1018``):
    ``agg_of_state`` maps s' to 0/1 (Bad/Good), ``emp_of_state`` to 0/1.
    """

    a_grid: jnp.ndarray           # [A]
    m_grid: jnp.ndarray           # [Mc] aggregate-resource grid (MSS * MgridBase)
    labor_levels: jnp.ndarray     # [N]
    ind_transition: jnp.ndarray   # [S, S] composite idiosyncratic matrix
    tauchen_transition: jnp.ndarray  # [N, N]
    empl_transition: jnp.ndarray  # [4, 4]
    agg_transition: jnp.ndarray   # [2, 2]
    agg_of_state: jnp.ndarray     # [S] int 0/1
    emp_of_state: jnp.ndarray     # [S] int 0/1
    labor_of_state: jnp.ndarray   # [S] int 0..N-1
    prod_by_agg: jnp.ndarray      # [2] (ProdB, ProdG)
    urate_by_agg: jnp.ndarray     # [2] (UrateB, UrateG)
    disc_fac: jnp.ndarray
    crra: jnp.ndarray
    lbr_ind: jnp.ndarray
    cap_share: jnp.ndarray
    depr_fac: jnp.ndarray
    steady_state: firm.SteadyState
    ks_employment: bool           # True: unemployed earn 0 (true KS);
                                  # False: reference-parity Aiyagari mode
                                  # (labor level regardless of employment,
                                  #  Aiyagari_Support.py:991-1018)


class KSPolicy(NamedTuple):
    """Per-state consumption policy over (m, M): knots ``[S, Mc, A+1]``."""

    m_knots: jnp.ndarray
    c_knots: jnp.ndarray


class AFuncParams(NamedTuple):
    """The perceived log-linear aggregate saving rules, one per aggregate
    state (``AggregateSavingRule``, ``Aiyagari_Support.py:1991-2005``)."""

    intercept: jnp.ndarray  # [2]
    slope: jnp.ndarray      # [2]

    def __call__(self, M, agg_idx):
        return jnp.exp(self.intercept[agg_idx] + self.slope[agg_idx] * jnp.log(M))


def build_ks_calibration(agent: AgentConfig, econ: EconomyConfig,
                         ks_employment: bool = False,
                         dtype=None) -> KSCalibration:
    """Assemble all static arrays from the two configs (the work the
    reference spreads across ``update``/``make_MrkvArray``/
    ``get_economy_data``, ``Aiyagari_Support.py:1593-1791, 817-873``)."""
    n = agent.labor_states
    s_count = 4 * n
    a_grid = make_asset_grid(agent.a_min, agent.a_max, agent.a_count,  # grid-ok: KS reference parity
                             agent.a_nest_fac, dtype=dtype)
    tauchen = tauchen_labor_process(n, econ.labor_ar, econ.labor_sd,
                                    bound=agent.labor_bound, dtype=dtype)
    levels = normalized_labor_states(tauchen.grid)
    empl = employment_markov_matrix(
        econ.dur_mean_b, econ.dur_mean_g, econ.spell_mean_b, econ.spell_mean_g,
        econ.urate_b, econ.urate_g, econ.rel_prob_bg, econ.rel_prob_gb,
        dtype=dtype)
    agg = aggregate_markov_matrix(econ.dur_mean_b, econ.dur_mean_g, dtype=dtype)
    ind = full_idiosyncratic_matrix(tauchen.transition, empl)
    ss = firm.perfect_foresight_steady_state(
        econ.disc_fac, econ.cap_share, econ.depr_fac, econ.lbr_ind)
    m_grid = ss.M * jnp.asarray(agent.mgrid_base, dtype=a_grid.dtype)
    states = jnp.arange(s_count)
    k = states % 4
    return KSCalibration(
        a_grid=a_grid, m_grid=m_grid, labor_levels=levels,
        ind_transition=ind, tauchen_transition=tauchen.transition,
        empl_transition=empl, agg_transition=agg,
        agg_of_state=k // 2, emp_of_state=k % 2, labor_of_state=states // 4,
        prod_by_agg=jnp.asarray([econ.prod_b, econ.prod_g], dtype=a_grid.dtype),
        urate_by_agg=jnp.asarray([econ.urate_b, econ.urate_g], dtype=a_grid.dtype),
        disc_fac=jnp.asarray(econ.disc_fac, dtype=a_grid.dtype),
        crra=jnp.asarray(econ.crra, dtype=a_grid.dtype),
        lbr_ind=jnp.asarray(econ.lbr_ind, dtype=a_grid.dtype),
        cap_share=jnp.asarray(econ.cap_share, dtype=a_grid.dtype),
        depr_fac=jnp.asarray(econ.depr_fac, dtype=a_grid.dtype),
        steady_state=ss, ks_employment=ks_employment)


class PrecomputedArrays(NamedTuple):
    """Everything the one-period solver consumes, as a pure function of the
    AFunc parameters (the reference's ``precompute_arrays``,
    ``Aiyagari_Support.py:906-1037``, minus the redundant current-state
    tiling: none of these depend on the current state s)."""

    m_next: jnp.ndarray   # [A, Mc, S'] idiosyncratic resources next period
    M_next: jnp.ndarray   # [Mc, S'] aggregate resources next period
    R_next: jnp.ndarray   # [Mc, S'] interest factor next period


def precompute(afunc: AFuncParams, cal: KSCalibration) -> PrecomputedArrays:
    """K' = AFunc[agg(s')](M); prices and resources next period per
    (M-gridpoint, next state).  Replaces the reference's 28-column literal
    concatenations with N-generic gathers (fixes SURVEY.md §3.6-2)."""
    agg_idx = cal.agg_of_state                       # [S']
    K_next = afunc(cal.m_grid[:, None], agg_idx[None, :])   # [Mc, S']
    L_next = (1.0 - cal.urate_by_agg[agg_idx]) * cal.lbr_ind  # [S']
    Z_next = cal.prod_by_agg[agg_idx]                # [S']
    k_to_l = K_next / L_next[None, :]
    R_next = firm.interest_factor(k_to_l, cal.cap_share, cal.depr_fac, Z_next)
    W_next = firm.wage_rate(k_to_l, cal.cap_share, Z_next)
    M_next = firm.aggregate_resources(K_next, L_next[None, :], cal.cap_share,
                                      cal.depr_fac, Z_next)
    # Idiosyncratic effective labor next period: the labor level of s' —
    # times the employment indicator only in true-KS mode
    # (reference Aiyagari mode pays the level regardless: :991-1018).
    l_next = cal.labor_levels[cal.labor_of_state]    # [S']
    if cal.ks_employment:
        l_next = l_next * cal.emp_of_state
    m_next = (R_next[None, :, :] * cal.a_grid[:, None, None]
              + W_next[None, :, :] * l_next[None, None, :])
    return PrecomputedArrays(m_next=m_next, M_next=M_next, R_next=R_next)


def initial_ks_policy(cal: KSCalibration) -> KSPolicy:
    """c(m, M) = m per state — the reference's ``IdentityFunction`` terminal
    guess (``Aiyagari_Support.py:898``)."""
    s_count = cal.ind_transition.shape[0]
    mc = cal.m_grid.shape[0]
    eps = jnp.asarray(CONSTRAINT_EPS, dtype=cal.a_grid.dtype)
    row = jnp.concatenate([eps[None], cal.a_grid + eps])
    knots = jnp.tile(row, (s_count, mc, 1))
    return KSPolicy(m_knots=knots, c_knots=knots)


def egm_step_ks(policy: KSPolicy, pre: PrecomputedArrays,
                cal: KSCalibration,
                matmul_precision=jax.lax.Precision.HIGHEST) -> KSPolicy:
    """One EGM backward step over the ``[A, Mc, S]`` block
    (``solve_Aiyagari``, ``Aiyagari_Support.py:1423-1520``, as pure array
    math: the 28-interpolator Python loop becomes a vmapped two-level interp,
    the probability-weighted sum becomes one matmul).  ``matmul_precision``
    follows ``household.egm_step``'s ladder semantics (DESIGN §5)."""
    # c'(m', M') for every next state: vmap over (Mc, S') columns; each
    # column interpolates the A-vector of m' queries at scalar M'.
    def eval_col(m_col, M_scalar, s_idx):
        return interp_on_interp(m_col, M_scalar, cal.m_grid,
                                policy.m_knots[s_idx], policy.c_knots[s_idx])

    s_count = cal.ind_transition.shape[0]
    sp = jnp.arange(s_count)
    # [Mc, S'] -> vmap over both: result [Mc, S', A] -> transpose to [A, Mc, S']
    c_next = jax.vmap(
        jax.vmap(eval_col, in_axes=(1, 0, 0)),   # over S' (m [A,S'], M [S'], s [S'])
        in_axes=(1, 0, None),                     # over Mc
    )(pre.m_next, pre.M_next, sp)                 # [Mc, S', A]
    c_next = jnp.moveaxis(c_next, 2, 0)           # [A, Mc, S']
    vp_next = marginal_utility(c_next, cal.crra)
    weighted = pre.R_next[None, :, :] * vp_next   # [A, Mc, S']
    # EndOfPrdvP[a, mc, s] = beta * sum_{s'} P[s, s'] weighted[a, mc, s']
    end_vp = cal.disc_fac * jnp.einsum("ams,ks->amk", weighted,
                                       cal.ind_transition,
                                       precision=matmul_precision,
                                       preferred_element_type=weighted.dtype)
    c_now = inverse_marginal_utility(end_vp, cal.crra)    # [A, Mc, S]
    m_now = cal.a_grid[:, None, None] + c_now
    eps = jnp.full((1,) + c_now.shape[1:], CONSTRAINT_EPS, dtype=c_now.dtype)
    # [A+1, Mc, S] -> [S, Mc, A+1]
    c_knots = jnp.transpose(jnp.concatenate([eps, c_now], axis=0), (2, 1, 0))
    m_knots = jnp.transpose(jnp.concatenate([eps, m_now], axis=0), (2, 1, 0))
    return KSPolicy(m_knots=m_knots, c_knots=c_knots)


def solve_ks_household(afunc: AFuncParams, cal: KSCalibration,
                       tol: float = 1e-6, max_iter: int = 2000,
                       init_policy: KSPolicy | None = None,
                       accel_every: int = 32,
                       precision: str = "reference"):
    """Infinite-horizon fixed point of the 4N-state EGM step under the given
    perceived aggregate law.  Sup-norm convergence on consumption knots (the
    array analog of HARK's solution distance).  Returns
    (policy, iters, diff, status) — ``status`` a ``solver_health`` code.

    ``init_policy`` warm-starts the backward iteration — the KS outer loop
    passes the previous outer iteration's policy (the perceived law moves a
    little per damped update, so the fixed points are close).

    ``accel_every``: certified Anderson(1)/Aitken extrapolation — the
    shared safeguarded machinery of
    ``household.accelerated_policy_fixed_point`` (KSPolicy carries the
    same ``m_knots``/``c_knots`` interface).  0 disables.

    ``precision`` (DESIGN §5): "reference" (default) is the single-phase
    solve, bit-identical to pre-ladder behavior; "mixed"/"fast" run the
    cheap-dtype descent (+ reference polish) ladder exactly as the
    compact Aiyagari policy loop does (``household.solve_household``).
    """
    from ..utils.config import resolve_precision
    from .household import (
        POLICY_DESCENT_TOL_SCALE,
        accelerated_policy_fixed_point,
        cast_floating,
        descent_dtype,
        descent_tolerance,
        ladder_policy_fixed_point,
        DESCENT_MATMUL_PRECISION,
    )

    spec = resolve_precision(precision)
    pre = precompute(afunc, cal)
    p0 = initial_ks_policy(cal) if init_policy is None else init_policy
    if not spec.two_phase:
        return accelerated_policy_fixed_point(
            lambda p: egm_step_ks(p, pre, cal), p0, tol, max_iter,
            accel_every)
    cheap = descent_dtype(cal.a_grid.dtype)
    cal_c = cast_floating(cal, cheap)
    pre_c = cast_floating(pre, cheap)
    pol, it, diff, status, _ = ladder_policy_fixed_point(
        lambda p: egm_step_ks(p, pre_c, cal_c,
                              matmul_precision=DESCENT_MATMUL_PRECISION),
        lambda p: egm_step_ks(p, pre, cal),
        p0, tol, descent_tolerance(tol, cheap, POLICY_DESCENT_TOL_SCALE),
        max_iter, accel_every, polish=spec.polish, cheap_dtype=cheap)
    return pol, it, diff, status
