"""Huggett (1993): the pure-exchange complement to Aiyagari's production
economy — households trade a bond in ZERO net supply under idiosyncratic
endowment risk and an ad-hoc debt limit, and the interest rate clears the
credit market.

The reference framework covers only the production (Aiyagari) economy;
this module reuses the identical household machinery — EGM solver,
stationary histogram, bisection — with two substitutions: labor income is
an endowment (no firm, wage = 1) and market clearing is ``E[a] = 0``
instead of capital supply = firm demand.  The borrowing-limit
generalization it rides on (``SimpleModel.borrow_limit``) is exact for
b = 0, so the Aiyagari path is untouched.

Economics pinned by the tests: r* < (1-beta)/beta (the autarky bound —
with binding debt limits the bond carries a liquidity premium), a strictly
positive mass of borrowers in equilibrium, and r* increasing in the debt
limit's looseness (easier credit -> less precautionary demand for the
bond -> a higher rate clears the market).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

import numpy as np

from ..ops.grids import make_asset_grid  # grid-ok: credit-crunch per-date grids (below)
from .household import (
    HouseholdPolicy,
    SimpleModel,
    aggregate_capital,
    anderson_rate,
    egm_step,
    initial_distribution,
    initial_policy,
    solve_household,
    stationary_wealth,
)
from .transition import _forward_step


class HuggettEquilibrium(NamedTuple):
    r_star: jnp.ndarray         # equilibrium net bond rate
    net_demand: jnp.ndarray     # E[a] at r_star (~0)
    policy: object              # HouseholdPolicy at r_star
    distribution: jnp.ndarray   # [D, N] stationary wealth distribution
    borrower_share: jnp.ndarray  # stationary mass with a < 0
    bisect_iters: jnp.ndarray
    bracketed: jnp.ndarray      # bool: net demand was negative at the low
    # end of the (auto-widened) bracket; False means r_star is NOT an
    # equilibrium (check net_demand)


def net_bond_demand(r, model: SimpleModel, disc_fac, crra,
                    egm_tol=1e-6, dist_tol=1e-11,
                    init_policy_=None, init_dist=None,
                    dist_method: str = "auto",
                    precision: str = "reference"):
    """E[a] at rate ``r``: aggregate net bond position of the household
    sector (positive = net savers).  Endowment economy: R = 1 + r, W = 1.
    ``precision`` threads the mixed-precision ladder (DESIGN §5) into
    both inner fixed points."""
    policy, _, _, _ = solve_household(1.0 + r, 1.0, model, disc_fac, crra,
                                   tol=egm_tol, init_policy=init_policy_,
                                   precision=precision)
    dist, _, _, _ = stationary_wealth(policy, 1.0 + r, 1.0, model,
                                   tol=dist_tol, init_dist=init_dist,
                                   method=dist_method, precision=precision)
    return aggregate_capital(dist, model), policy, dist


def solve_huggett_equilibrium(model: SimpleModel, disc_fac, crra,
                              r_tol: float | None = None,
                              max_bisect: int = 60,
                              egm_tol: float | None = None,
                              dist_tol: float | None = None,
                              r_lo: float = -0.10,
                              dist_method: str = "auto",
                              precision: str = "reference"
                              ) -> HuggettEquilibrium:
    """Bisect the bond rate until the credit market clears (E[a] = 0).

    Net demand is increasing in r (the same monotonicity as Aiyagari's
    asset supply) and diverges as r approaches the discount rate from
    below, so the upper end always brackets; the LOWER end is validated —
    tight debt limits can keep net demand positive at ``r_lo`` — and
    widened toward -90% for up to 6 doublings.  If it still fails to turn
    negative, ``bracketed=False`` is returned and ``r_star`` is NOT an
    equilibrium (a hard error is impossible here: the function is
    jit/vmap-able, so the condition is data).  Warm-starts the household
    fixed points across midpoints like the Aiyagari bisection.
    """
    dtype = model.a_grid.dtype
    f64 = dtype == jnp.float64
    if r_tol is None:
        r_tol = 1e-10 if f64 else 1e-6
    if egm_tol is None:
        egm_tol = 1e-6 if f64 else 1e-5
    if dist_tol is None:
        dist_tol = 1e-11 if f64 else 1e-8
    hi0 = jnp.asarray(1.0 / disc_fac - 1.0 - 1e-4, dtype=dtype)
    lo0 = jnp.asarray(r_lo, dtype=dtype)
    p0 = initial_policy(model)
    d0 = initial_distribution(model)
    zi = jnp.asarray(0)

    # validate / widen the lower bracket end: walk lo toward -90% until
    # net demand turns negative (bounded — each probe is a full solve)
    def widen_cond(state):
        lo, ex, k = state
        return (ex > 0) & (k < 6) & (lo > -0.9)

    def widen_body(state):
        lo, _, k = state
        lo = jnp.maximum(jnp.asarray(-0.9, dtype=dtype),
                         lo - (2.0 ** k) * 0.1)
        ex, _, _ = net_bond_demand(lo, model, disc_fac, crra,
                                   egm_tol=egm_tol, dist_tol=dist_tol,
                                   dist_method=dist_method,
                                   precision=precision)
        return lo, ex, k + 1

    ex_lo0, _, _ = net_bond_demand(lo0, model, disc_fac, crra,
                                   egm_tol=egm_tol, dist_tol=dist_tol,
                                   dist_method=dist_method,
                                   precision=precision)
    lo0, ex_lo, _ = jax.lax.while_loop(widen_cond, widen_body,
                                       (lo0, ex_lo0, zi))
    bracketed = ex_lo <= 0

    def cond(state):
        lo, hi, it, _, _ = state
        return ((hi - lo) > r_tol) & (it < max_bisect)

    def body(state):
        lo, hi, it, policy, dist = state
        mid = 0.5 * (lo + hi)
        ex, policy, dist = net_bond_demand(
            mid, model, disc_fac, crra, egm_tol=egm_tol, dist_tol=dist_tol,
            init_policy_=policy, init_dist=dist, dist_method=dist_method,
            precision=precision)
        lo = jnp.where(ex > 0, lo, mid)
        hi = jnp.where(ex > 0, mid, hi)
        return lo, hi, it + 1, policy, dist

    lo, hi, iters, policy, dist = jax.lax.while_loop(
        cond, body, (lo0, hi0, zi, p0, d0))
    r_star = 0.5 * (lo + hi)
    ex, policy, dist = net_bond_demand(
        r_star, model, disc_fac, crra, egm_tol=egm_tol, dist_tol=dist_tol,
        init_policy_=policy, init_dist=dist, dist_method=dist_method,
        precision=precision)
    borrowers = jnp.sum(jnp.where(model.dist_grid[:, None] < 0, dist, 0.0))
    return HuggettEquilibrium(r_star=r_star, net_demand=ex, policy=policy,
                              distribution=dist, borrower_share=borrowers,
                              bisect_iters=iters, bracketed=bracketed)


class CreditCrunchResult(NamedTuple):
    """Perfect-foresight deleveraging path after a foreseen tightening of
    the debt limit (Guerrieri-Lorenzoni 2017-style experiment)."""

    r_path: jnp.ndarray             # [T] bond rate clearing each market
    excess_path: jnp.ndarray        # [T] residual net bond demand E[a_t]
    c_agg_path: jnp.ndarray         # [T] aggregate consumption
    borrower_share_path: jnp.ndarray  # [T] mass with assets < 0
    debt_path: jnp.ndarray          # [T] gross debt per capita E[max(-a,0)]
    converged: jnp.ndarray
    iterations: jnp.ndarray
    max_excess: jnp.ndarray


def solve_credit_crunch(model_loose: SimpleModel, disc_fac, crra,
                        b_path, init_dist: jnp.ndarray,
                        terminal_policy, r_pre, r_terminal,
                        a_nest_fac: int = 2,
                        damping: float = 0.02, tol: float | None = None,
                        max_iter: int = 4000) -> CreditCrunchResult:
    """The credit-crunch experiment: the economy sits in the loose-limit
    stationary equilibrium, the debt limit tightens along the (foreseen)
    path ``b_path`` [T], and the bond market must clear at EVERY date of
    the deleveraging transition — Guerrieri & Lorenzoni (2017)'s
    "Credit Crises, Precautionary Savings, and the Liquidity Trap"
    exercise, which the reference framework has no machinery for at all.

    Unkn. is the whole rate path: bonds bought at t pay ``r_{t+1}``, so
    clearing ``E[a_t] = 0`` pairs with ``r_{t+1}`` (``r_0 = r_pre`` is
    the return promised before the shock; beyond the horizon the
    tight-limit stationary rate ``r_terminal`` applies — pass a horizon
    long enough that the path has settled).  The solver is a damped
    tatonnement inside one ``lax.while_loop``: backward ``lax.scan`` of
    the EGM step along the trial rate path with the DATE-SPECIFIC debt
    limit (per-date end-of-period grids are precomputed host-side — grid
    construction is host NumPy by design, ``ops/grids.py``), forward
    histogram scan on the loose-limit support (households caught beyond
    a tightened limit are forced to the limit by the constrained
    segment of that date's policy), then ``r_{t+1} -= damping * E[a_t]``.

    Economics pinned by the tests: the rate OVERSHOOTS below its new
    long-run level while borrowers deleverage (GL's headline result),
    gross debt contracts, and the path ends at the tight-limit
    stationary equilibrium.

    Stability: the tatonnement Jacobian is dense (savings at t respond
    to the WHOLE future rate path), so ``damping`` must be small —
    measured on the Δb = 0.5, 24-period phase-in experiment, 0.02
    converges (≈2300 iterations, each a cheap jitted backward+forward
    scan) while 0.05 oscillates and diverges.  Phase the limit in over
    enough periods that households at the old limit can deleverage with
    positive consumption (an instant large tightening makes the date-0
    market literally unclearable: constrained borrowers' savings are
    rate-inelastic, and no rate makes unconstrained savers hold zero).
    """
    dtype = model_loose.a_grid.dtype
    if tol is None:
        # f32 histogram sums carry rounding noise ~1e-6; an f64 tolerance
        # would burn max_iter without certifying (same policy as
        # solve_huggett_equilibrium's inner tolerances)
        tol = 1e-7 if dtype == jnp.float64 else 1e-5
    b_path = np.asarray(b_path, dtype=np.float64)
    T = b_path.shape[0]
    a_count = model_loose.a_grid.shape[0]
    a_max = float(model_loose.a_grid[-1])
    b_loose = float(model_loose.borrow_limit)
    # the grid offset above the limit is derivable from the loose model,
    # so date-t grids stay consistent with the one the pre-shock
    # equilibrium was solved on (only nest_fac is not recoverable)
    a_min = float(model_loose.a_grid[0]) - b_loose
    # per-date end-of-period grids, host-built like build_simple_model's
    a_grids = jnp.asarray(np.stack([
        b + np.asarray(make_asset_grid(a_min, a_max - b, a_count,  # grid-ok: per-date grids must stay consistent with model_loose's reference layout
                                       a_nest_fac, dtype=jnp.float64))
        for b in b_path]), dtype=dtype)
    if np.isclose(b_path[0], b_loose) and not np.allclose(
            np.asarray(a_grids[0]), np.asarray(model_loose.a_grid),
            rtol=1e-6):
        raise ValueError(
            "date-0 asset grid does not reproduce model_loose.a_grid — "
            "model_loose was built with a non-default a_nest_fac; pass "
            "the same value to solve_credit_crunch(a_nest_fac=...)")
    b_arr = jnp.asarray(b_path, dtype=dtype)
    r_pre = jnp.asarray(r_pre, dtype=dtype)
    r_term = jnp.asarray(r_terminal, dtype=dtype)
    grid = model_loose.dist_grid
    neg = jnp.where(grid < 0, -grid, 0.0)

    # initial guess: pre-shock rate relaxing linearly to the terminal
    frac = jnp.linspace(0.0, 1.0, T, dtype=dtype)
    r_guess = (1.0 - frac) * r_pre + frac * r_term
    r_guess = r_guess.at[0].set(r_pre)
    r_cap = jnp.asarray(1.0 / disc_fac - 1.0 - 1e-4, dtype=dtype)

    def model_at(t_slice_a_grid, b_t):
        return model_loose._replace(a_grid=t_slice_a_grid,
                                    borrow_limit=b_t)

    def implied_excess(r_path):
        # continuation rates: date t's saving earns r_{t+1}; beyond the
        # horizon the terminal stationary rate
        r_next = jnp.concatenate([r_path[1:], r_term[None]])

        def backward_step(pol_next, inputs):
            a_grid_t, b_t, rn = inputs
            pol = egm_step(pol_next, 1.0 + rn, 1.0,
                           model_at(a_grid_t, b_t), disc_fac, crra)
            return pol, pol

        _, pols = jax.lax.scan(backward_step, terminal_policy,
                               (a_grids, b_arr, r_next), reverse=True)

        def forward_step(dist, inputs):
            pol_m, pol_c, r_t = inputs
            pol = HouseholdPolicy(m_knots=pol_m, c_knots=pol_c)
            # the ONE forward-step implementation (clipping + budget-
            # consistent c_agg semantics live in transition._forward_step)
            new, c_agg, a_agg = _forward_step(dist, pol, 1.0 + r_t, 1.0,
                                              model_loose)
            borrowers = jnp.sum(jnp.where(grid[:, None] < 0, dist, 0.0))
            debt = jnp.sum(dist * neg[:, None])
            return new, (a_agg, c_agg, borrowers, debt)

        _, (a_agg, c_agg, borrowers, debt) = jax.lax.scan(
            forward_step, init_dist,
            (pols.m_knots, pols.c_knots, r_path))
        return a_agg, c_agg, borrowers, debt

    big = jnp.asarray(jnp.inf, dtype=dtype)
    accel_every = 32

    def cond(state):
        ex_best = state[3]
        it = state[4]
        return (ex_best > tol) & (it < max_iter)

    def body(state):
        r_path, r_prev, r_best, ex_best, it = state
        a_agg, _, _, _ = implied_excess(r_path)
        ex_max = jnp.max(jnp.abs(a_agg[:-1]))
        # best-iterate carry: whatever the loop hands back on ANY exit
        # (tolerance or max_iter) is the iterate its ex_best certifies —
        # an extrapolation can only ever be the next trial, never the
        # result (same guarantee as the policy/distribution iterators)
        improved = ex_max < ex_best
        r_best = jnp.where(improved, r_path, r_best)
        ex_best = jnp.minimum(ex_best, ex_max)
        # r_{t+1} clears E[a_t]; excess demand for bonds -> rate falls.
        # The last market (t = T-1) is closed by the terminal condition.
        r_new = r_path.at[1:].add(-damping * a_agg[:-1])
        r_new = jnp.clip(r_new, -0.5, r_cap).at[0].set(r_pre)
        # Anderson(1)/Aitken every accel_every steps: the small damping
        # the dense cross-period Jacobian forces makes the plain map a
        # slow contraction, so jump along its dominant mode; clipped,
        # pinned, and never returned directly (see best-iterate carry)
        lam = anderson_rate(r_path - r_prev, r_new - r_path)
        r_x = jnp.clip(r_new + lam / (1.0 - lam) * (r_new - r_path),
                       -0.5, r_cap).at[0].set(r_pre)
        use_accel = (jnp.mod(it + 1, accel_every) == 0) & (ex_max > tol)
        r_next = jnp.where(use_accel, r_x, r_new)
        return r_next, r_path, r_best, ex_best, it + 1

    _, _, r_path, ex_max, it = jax.lax.while_loop(
        cond, body, (r_guess, r_guess, r_guess, big, jnp.asarray(0)))
    a_agg, c_agg, borrowers, debt = implied_excess(r_path)
    return CreditCrunchResult(
        r_path=r_path, excess_path=a_agg, c_agg_path=c_agg,
        borrower_share_path=borrowers, debt_path=debt,
        converged=ex_max <= tol, iterations=it, max_excess=ex_max)
