"""Huggett (1993): the pure-exchange complement to Aiyagari's production
economy — households trade a bond in ZERO net supply under idiosyncratic
endowment risk and an ad-hoc debt limit, and the interest rate clears the
credit market.

The reference framework covers only the production (Aiyagari) economy;
this module reuses the identical household machinery — EGM solver,
stationary histogram, bisection — with two substitutions: labor income is
an endowment (no firm, wage = 1) and market clearing is ``E[a] = 0``
instead of capital supply = firm demand.  The borrowing-limit
generalization it rides on (``SimpleModel.borrow_limit``) is exact for
b = 0, so the Aiyagari path is untouched.

Economics pinned by the tests: r* < (1-beta)/beta (the autarky bound —
with binding debt limits the bond carries a liquidity premium), a strictly
positive mass of borrowers in equilibrium, and r* increasing in the debt
limit's looseness (easier credit -> less precautionary demand for the
bond -> a higher rate clears the market).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from .household import (
    SimpleModel,
    aggregate_capital,
    initial_distribution,
    initial_policy,
    solve_household,
    stationary_wealth,
)


class HuggettEquilibrium(NamedTuple):
    r_star: jnp.ndarray         # equilibrium net bond rate
    net_demand: jnp.ndarray     # E[a] at r_star (~0)
    policy: object              # HouseholdPolicy at r_star
    distribution: jnp.ndarray   # [D, N] stationary wealth distribution
    borrower_share: jnp.ndarray  # stationary mass with a < 0
    bisect_iters: jnp.ndarray
    bracketed: jnp.ndarray      # bool: net demand was negative at the low
    # end of the (auto-widened) bracket; False means r_star is NOT an
    # equilibrium (check net_demand)


def net_bond_demand(r, model: SimpleModel, disc_fac, crra,
                    egm_tol=1e-6, dist_tol=1e-11,
                    init_policy_=None, init_dist=None,
                    dist_method: str = "auto"):
    """E[a] at rate ``r``: aggregate net bond position of the household
    sector (positive = net savers).  Endowment economy: R = 1 + r, W = 1."""
    policy, _, _ = solve_household(1.0 + r, 1.0, model, disc_fac, crra,
                                   tol=egm_tol, init_policy=init_policy_)
    dist, _, _ = stationary_wealth(policy, 1.0 + r, 1.0, model,
                                   tol=dist_tol, init_dist=init_dist,
                                   method=dist_method)
    return aggregate_capital(dist, model), policy, dist


def solve_huggett_equilibrium(model: SimpleModel, disc_fac, crra,
                              r_tol: float | None = None,
                              max_bisect: int = 60,
                              egm_tol: float | None = None,
                              dist_tol: float | None = None,
                              r_lo: float = -0.10,
                              dist_method: str = "auto"
                              ) -> HuggettEquilibrium:
    """Bisect the bond rate until the credit market clears (E[a] = 0).

    Net demand is increasing in r (the same monotonicity as Aiyagari's
    asset supply) and diverges as r approaches the discount rate from
    below, so the upper end always brackets; the LOWER end is validated —
    tight debt limits can keep net demand positive at ``r_lo`` — and
    widened toward -90% for up to 6 doublings.  If it still fails to turn
    negative, ``bracketed=False`` is returned and ``r_star`` is NOT an
    equilibrium (a hard error is impossible here: the function is
    jit/vmap-able, so the condition is data).  Warm-starts the household
    fixed points across midpoints like the Aiyagari bisection.
    """
    dtype = model.a_grid.dtype
    f64 = dtype == jnp.float64
    if r_tol is None:
        r_tol = 1e-10 if f64 else 1e-6
    if egm_tol is None:
        egm_tol = 1e-6 if f64 else 1e-5
    if dist_tol is None:
        dist_tol = 1e-11 if f64 else 1e-8
    hi0 = jnp.asarray(1.0 / disc_fac - 1.0 - 1e-4, dtype=dtype)
    lo0 = jnp.asarray(r_lo, dtype=dtype)
    p0 = initial_policy(model)
    d0 = initial_distribution(model)
    zi = jnp.asarray(0)

    # validate / widen the lower bracket end: walk lo toward -90% until
    # net demand turns negative (bounded — each probe is a full solve)
    def widen_cond(state):
        lo, ex, k = state
        return (ex > 0) & (k < 6) & (lo > -0.9)

    def widen_body(state):
        lo, _, k = state
        lo = jnp.maximum(jnp.asarray(-0.9, dtype=dtype),
                         lo - (2.0 ** k) * 0.1)
        ex, _, _ = net_bond_demand(lo, model, disc_fac, crra,
                                   egm_tol=egm_tol, dist_tol=dist_tol,
                                   dist_method=dist_method)
        return lo, ex, k + 1

    ex_lo0, _, _ = net_bond_demand(lo0, model, disc_fac, crra,
                                   egm_tol=egm_tol, dist_tol=dist_tol,
                                   dist_method=dist_method)
    lo0, ex_lo, _ = jax.lax.while_loop(widen_cond, widen_body,
                                       (lo0, ex_lo0, zi))
    bracketed = ex_lo <= 0

    def cond(state):
        lo, hi, it, _, _ = state
        return ((hi - lo) > r_tol) & (it < max_bisect)

    def body(state):
        lo, hi, it, policy, dist = state
        mid = 0.5 * (lo + hi)
        ex, policy, dist = net_bond_demand(
            mid, model, disc_fac, crra, egm_tol=egm_tol, dist_tol=dist_tol,
            init_policy_=policy, init_dist=dist, dist_method=dist_method)
        lo = jnp.where(ex > 0, lo, mid)
        hi = jnp.where(ex > 0, mid, hi)
        return lo, hi, it + 1, policy, dist

    lo, hi, iters, policy, dist = jax.lax.while_loop(
        cond, body, (lo0, hi0, zi, p0, d0))
    r_star = 0.5 * (lo + hi)
    ex, policy, dist = net_bond_demand(
        r_star, model, disc_fac, crra, egm_tol=egm_tol, dist_tol=dist_tol,
        init_policy_=policy, init_dist=dist, dist_method=dist_method)
    borrowers = jnp.sum(jnp.where(model.dist_grid[:, None] < 0, dist, 0.0))
    return HuggettEquilibrium(r_star=r_star, net_demand=ex, policy=policy,
                              distribution=dist, borrower_share=borrowers,
                              bisect_iters=iters, bracketed=bracketed)
