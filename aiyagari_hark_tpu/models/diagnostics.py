"""Solution-accuracy diagnostics for the Krusell-Smith equilibrium.

The reference's only quality signal is the regression R² printed per outer
iteration (``verbose`` at ``Aiyagari_Support.py:1954-1962``), which den Haan
(2010, JEDC, "Assessing the accuracy of the aggregate law of motion")
showed to be a weak test: a rule can fit one-step-ahead data with R² ≈ 0.9999
while its *dynamic* forecast — iterating the perceived law on its own output
with no feedback from the simulation — drifts badly.  This module provides
the den Haan diagnostic for this framework's solutions: run the perceived
law forward over the realized aggregate-shock path and report the maximum
and mean percent error against the actually-simulated aggregates.

Accepted practice: a KS solution is considered accurate when the max
dynamic forecast error over a long simulation is a fraction of a percent.

Which engine meets that bar here is a measured and explained fact, not an
aspiration.  The deterministic pinned-histogram engine does: its rule is
a constant (slope 0), so there is no off-path slope to be wrong about,
and its forecast error is bounded by the secant tolerance plus settled-
path drift — measured max 0.43% / mean 0.13% on the committed parity
calibration (``results.json`` ``den_haan_pinned_*``), asserted <0.3% at
the test config (``tests/test_diagnostics.py``).  The reference-parity
Monte-Carlo panel rule does NOT — the same committed run measures
max 2.28% / mean 0.42%, reported side by side.  That is a property of the reference's
own construction, not a solver bug (DESIGN §3): at the aggregate-
degenerate Aiyagari calibration the correct rational-expectations law is
the CONSTANT ``K' = K*`` (slope 0), the deterministic transition map
``log A' ~ log M`` has local slope ~1.2, and the MC regression's fitted
slope (~1.11) sits between them only by errors-in-variables attenuation
from sampling noise in log M.  Iterated forward with no feedback — the
den Haan test — any slope that large compounds each period's sampling
deviation instead of forgetting it, which is exactly the off-path
behavior the dynamic forecast scores.  The panel rule's error is
therefore bounded as *moderate* (<5%
mean, <10% max at the test config) to catch regressions; the accuracy
standard above belongs to, and is asserted for, the pinned engine.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class DenHaanStats(NamedTuple):
    """Dynamic-forecast accuracy of the perceived aggregate law."""

    max_error_pct: jnp.ndarray    # max |log Â - log A| x 100
    mean_error_pct: jnp.ndarray   # mean |log Â - log A| x 100
    forecast: jnp.ndarray         # [T'] the dynamic forecast path Â_t


def den_haan_forecast(sol, t_start: int | None = None) -> DenHaanStats:
    """Iterate the converged rule on its own output along the realized
    shock path (no resets — the den Haan test), starting from the simulated
    aggregate at ``t_start`` (default: the solve's discard window).

    Timing matches the simulator and regression exactly
    (``calc_afunc_update``): ``A_t = f_{z_{t-1}}(M_{t-1})`` and
    ``M_t = mill(A_t, z_t)``.
    """
    from .simulate import mill_aggregates

    cal = sol.calibration
    afunc = sol.afunc
    hist = sol.history
    mrkv = jnp.asarray(sol.mrkv_hist)
    if t_start is None:
        # NOTE: the solution object does not carry the solve's t_discard,
        # so the default scores from T//8 onward; callers that know the
        # discard window (reproduce.py does) should pass it explicitly so
        # the forecast is judged on exactly the regression's sample.
        t_start = max(1, hist.A_prev.shape[0] // 8)

    def mill_m(A, z):
        return mill_aggregates(cal, A, z)[2]

    def step(m_hat, zz):
        z_prev, z_now = zz
        a_hat = afunc(m_hat, z_prev)   # the ONE perceived-law implementation
        return mill_m(a_hat, z_now), a_hat

    a0 = hist.A_prev[t_start]
    m0 = mill_m(a0, mrkv[t_start])
    _, a_hat = jax.lax.scan(step, m0,
                            (mrkv[t_start:-1], mrkv[t_start + 1:]))
    actual = hist.A_prev[t_start + 1:]
    log_err = jnp.abs(jnp.log(a_hat) - jnp.log(actual)) * 100.0
    return DenHaanStats(max_error_pct=jnp.max(log_err),
                        mean_error_pct=jnp.mean(log_err),
                        forecast=a_hat)
