"""Perfect-foresight transition dynamics (MIT shocks): the economy's
deterministic path after an unanticipated aggregate disturbance, converging
back to the stationary equilibrium.

The reference has no transition machinery at all — its only notion of
dynamics is the stochastic Krusell-Smith simulation.  Transition paths are
the workhorse of modern heterogeneous-agent macro (they underlie the
sequence-space methods of Boppart-Krusell-Mitman 2018 and
Auclert et al. 2021): hit the stationary economy with a known path of
aggregates (e.g. a TFP shock that decays), let every household foresee the
implied price path, and find the capital path consistent with their
behavior.

TPU shape: one outer fixed point on the capital path K_{0..T}; each
iteration is a *backward* ``lax.scan`` of the EGM step along the price path
(policies for every t in one compiled sweep) and a *forward* ``lax.scan``
of the histogram push-forward — no Python loops over time.  The whole
solver is one jitted ``lax.while_loop``.

Timing: ``K_t`` is capital used in production at t (saved at t-1), so
``K_0 = E[a]`` under the initial distribution is FIXED; prices at t are
``R_t = 1 + r(K_t/L, Z_t)``, ``W_t = w(K_t/L, Z_t)``; the EGM step for
period t consumes period t+1's policy and prices (the same convention as
``household.egm_step``: the backward step's (R, W) are next period's).
Beyond the horizon the economy sits at the terminal stationary
equilibrium, whose policy seeds the backward scan.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from . import firm
from .household import (
    HouseholdPolicy,
    SimpleModel,
    _push_forward,
    aggregate_capital,
    aggregate_labor,
    egm_step,
    wealth_transition,
)


class TransitionResult(NamedTuple):
    k_path: jnp.ndarray        # [T] capital in production at t = 0..T-1
    r_path: jnp.ndarray        # [T] net rate at each t
    w_path: jnp.ndarray        # [T] wage at each t
    c_agg_path: jnp.ndarray    # [T] aggregate consumption at each t
    converged: jnp.ndarray     # bool: path fixed point reached
    iterations: jnp.ndarray
    max_diff: jnp.ndarray      # final sup-norm of the K-path update


def _forward_step(dist, policy_t, R, W, model: SimpleModel):
    """One histogram push-forward at prices (R, W) under ``policy_t``:
    returns (next distribution, aggregate consumption, E[savings]).
    Reuses the ONE lottery implementation (``household.wealth_transition``
    + ``_push_forward``) so clipping and scatter semantics cannot diverge
    from the stationary-distribution solvers'."""
    trans = wealth_transition(policy_t, R, W, model)
    m = R * model.dist_grid[:, None] + W * model.labor_levels[None, :]
    # budget-consistent consumption: c = m - a' with the FEASIBLE savings
    # (post-clip), so aggregate budget identities hold exactly
    c_agg = jnp.sum(dist * (m - trans.a_next))
    k_next = jnp.sum(dist * trans.a_next)
    new_dist = _push_forward(dist, trans, model.transition)
    return new_dist, c_agg, k_next


def _transition_prices(k_path, prod_path, model: SimpleModel, cap_share,
                       depr_fac):
    labor = aggregate_labor(model)
    k_to_l = k_path / labor
    r = firm.interest_factor(k_to_l, cap_share, depr_fac, prod_path) - 1.0
    w = firm.wage_rate(k_to_l, cap_share, prod_path)
    return r, w


def path_policies(r_path, w_path, model: SimpleModel, disc_fac, crra,
                  terminal_policy: HouseholdPolicy) -> HouseholdPolicy:
    """Policies for every date of a foreseen price path, as one stacked
    pytree [T, ...]: a backward ``lax.scan`` of the EGM step seeded by
    the terminal stationary policy.  The step for date t consumes date
    t+1's prices; date T-1 uses the terminal policy (beyond the horizon
    the economy is stationary)."""

    def backward_step(pol_next, rw):
        r_next, w_next = rw
        pol = egm_step(pol_next, 1.0 + r_next, w_next, model, disc_fac,
                       crra)
        return pol, pol

    _, pols = jax.lax.scan(backward_step, terminal_policy,
                           (r_path[1:][::-1], w_path[1:][::-1]))
    return jax.tree.map(
        lambda s, term: jnp.concatenate([s[::-1], term[None]], axis=0),
        pols, terminal_policy)


def household_path_response(r_path, w_path, model: SimpleModel, disc_fac,
                            crra, init_dist,
                            terminal_policy: HouseholdPolicy):
    """The heterogeneous-agent block as a map on PRICE paths: perfectly
    foreseen ``(r_path, w_path)`` in, the implied aggregate capital and
    consumption paths out.

    One evaluation is a *backward* ``lax.scan`` of the EGM step along the
    price path (seeded by the terminal stationary policy) followed by a
    *forward* ``lax.scan`` of the histogram push-forward from
    ``init_dist`` — both differentiable, no Python loops.  This is the map
    whose derivative is the household sequence-space Jacobian
    (``models/jacobian.py`` takes it with one ``jax.jacrev``).

    The first implied-capital entry is ``E[a]`` under ``init_dist``
    (capital in production at t=0 was saved before the paths began), a
    CONSTANT in the price paths — so the implied path never moves ``K_0``
    and ``I - dH/dK`` is nonsingular for the general-equilibrium solve.

    Returns ``(k_implied [T], c_agg [T])``.
    """

    pols = path_policies(r_path, w_path, model, disc_fac, crra,
                         terminal_policy)

    def forward_step(dist, inputs):
        pol, r, w = inputs
        new_dist, c_agg, k_next = _forward_step(dist, pol, 1.0 + r, w,
                                                model)
        return new_dist, (c_agg, k_next)

    _, (c_agg, k_next) = jax.lax.scan(forward_step, init_dist,
                                      (pols, r_path, w_path))
    k0 = aggregate_capital(init_dist, model)
    k_implied = jnp.concatenate([k0[None], k_next[:-1]])
    return k_implied, c_agg


def transition_path_map(k_path, prod_path, model: SimpleModel, disc_fac,
                        crra, cap_share, depr_fac, init_dist,
                        terminal_policy: HouseholdPolicy):
    """The sequence-space map ``H``: a guessed capital path and a TFP path
    in, the household-implied capital path (and the aggregate-consumption
    path) out — prices from the firm block composed with
    ``household_path_response``.  ``solve_transition`` iterates ``H`` to
    its fixed point.  Returns ``(k_implied [T], c_agg [T])``."""
    r_path, w_path = _transition_prices(k_path, prod_path, model, cap_share,
                                        depr_fac)
    return household_path_response(r_path, w_path, model, disc_fac, crra,
                                   init_dist, terminal_policy)


def solve_transition(model: SimpleModel, disc_fac, crra, cap_share,
                     depr_fac, init_dist: jnp.ndarray,
                     terminal_policy: HouseholdPolicy,
                     k_terminal, horizon: int,
                     prod_path=None, damping: float = 0.85,
                     tol: float = 1e-6,
                     max_iter: int = 400) -> TransitionResult:
    """Find the perfect-foresight capital path.

    Inputs: the initial wealth distribution (e.g. the pre-shock stationary
    distribution), the TERMINAL stationary equilibrium's policy and
    capital (solve them once with ``solve_bisection_equilibrium`` at the
    post-shock long-run calibration), the horizon (long enough that the
    economy has settled — check ``k_path[-1]`` against ``k_terminal``),
    and an optional TFP path ``prod_path`` [T] (default ones — then the
    only "shock" is an out-of-steady-state ``init_dist``).

    Outer loop: damped fixed-point iteration on K_{1..T-1} (K_0 is pinned
    by ``init_dist``; beyond T the path is the terminal steady state).
    ``damping`` must be heavy: household savings are extremely elastic in
    the foreseen price path near Aiyagari's knife edge (the same
    steepness that forces the secant in the pinned KS mode), and 0.7
    visibly diverges where the 0.85 default converges in ~60 iterations.
    Returns the path with aggregate consumption and convergence info.
    """
    dtype = model.a_grid.dtype
    if prod_path is None:
        prod_path = jnp.ones((horizon,), dtype=dtype)
    else:
        prod_path = jnp.asarray(prod_path, dtype=dtype)
    k0 = aggregate_capital(init_dist, model)
    # initial guess: geometric interpolation from K_0 to the terminal K
    frac = jnp.linspace(0.0, 1.0, horizon, dtype=dtype)
    k_guess = jnp.exp((1.0 - frac) * jnp.log(k0)
                      + frac * jnp.log(jnp.asarray(k_terminal, dtype=dtype)))

    big = jnp.asarray(jnp.inf, dtype=dtype)

    def cond(state):
        _, diff, it = state
        return (diff > tol) & (it < max_iter)

    def body(state):
        k_path, _, it = state
        k_implied, _ = transition_path_map(k_path, prod_path, model,
                                           disc_fac, crra, cap_share,
                                           depr_fac, init_dist,
                                           terminal_policy)
        diff = jnp.max(jnp.abs(k_implied - k_path))
        new = damping * k_path + (1.0 - damping) * k_implied
        return new, diff, it + 1

    k_path, diff, it = jax.lax.while_loop(
        cond, body, (k_guess, big, jnp.asarray(0)))
    r_path, w_path = _transition_prices(k_path, prod_path, model, cap_share,
                                        depr_fac)
    _, c_agg = household_path_response(r_path, w_path, model, disc_fac,
                                       crra, init_dist, terminal_policy)
    return TransitionResult(k_path=k_path, r_path=r_path, w_path=w_path,
                            c_agg_path=c_agg, converged=diff <= tol,
                            iterations=it, max_diff=diff)


class TransitionWelfare(NamedTuple):
    """Welfare accounting of a transition path for the date-0 population."""

    ce: jnp.ndarray                 # consumption-equivalent of the path vs
                                    # staying at the terminal steady state
    welfare_path: jnp.ndarray       # E[v_0] living through the path
    welfare_steady: jnp.ndarray     # E[v] at the terminal steady state
    ce_by_cell: jnp.ndarray         # [D, N] per-household CE — the
                                    # distributional incidence the
                                    # aggregate scalar hides (who gains:
                                    # workers via the wage path, the
                                    # wealthy via the return path)


def transition_welfare(model: SimpleModel, disc_fac, crra,
                       init_dist: jnp.ndarray,
                       terminal_policy: HouseholdPolicy,
                       r_path, w_path,
                       constrained_knots: int = 24,
                       value_tol: float = 1e-9) -> TransitionWelfare:
    """The welfare question a transition exists to answer: what is the
    shock path WORTH to the initial population, in permanent-consumption
    units?

    One backward value recursion along the price path (a ``lax.scan``
    of the non-stationary Bellman evaluation, seeded by the terminal
    stationary value function), then utilitarian aggregation of date-0
    values over ``init_dist`` and the consumption-equivalent against
    remaining at the (terminal) steady state forever.  Values are
    carried in constant-equivalent-consumption form on
    constraint-augmented knots — the same numerics as
    ``value.policy_value`` (and its accuracy caveats).

    ``r_path``/``w_path`` come from a solved ``TransitionResult``.  The
    steady-state comparison uses the terminal prices (the path's tail),
    so for a transitory shock — where initial and terminal steady states
    coincide — ``ce`` is the pure value of the shock: positive for a
    beneficial TFP impulse, ~0 for a no-shock path (tested)."""
    from .value import (
        augment_constrained_knots,
        bellman_vnvrs_step,
        consumption_equivalent,
        policy_value,
        value_on_histogram,
        ValueFunction,
    )

    r_term, w_term = r_path[-1], w_path[-1]
    vf_term, _, _ = policy_value(terminal_policy, 1.0 + r_term, w_term,
                                 model, disc_fac, crra, tol=value_tol,
                                 constrained_knots=constrained_knots)
    pols = path_policies(r_path, w_path, model, disc_fac, crra,
                         terminal_policy)
    b = getattr(model, "borrow_limit", 0.0)
    levels = model.labor_levels

    def backward(carry, inputs):
        m_next_knots, vnvrs_next = carry
        pol_m, pol_c, r_next, w_next = inputs
        m_aug, c_aug = augment_constrained_knots(pol_m, pol_c, b,
                                                 constrained_knots)
        a_knots = m_aug - c_aug
        m_next = ((1.0 + r_next) * a_knots[:, :, None]
                  + w_next * levels[None, None, :])       # [N, K, N']
        vnvrs = bellman_vnvrs_step(c_aug, m_next, m_next_knots,
                                   vnvrs_next, model.transition,
                                   disc_fac, crra)
        return (m_aug, vnvrs), None

    # date-t continuation prices are date t+1's; beyond the horizon the
    # terminal steady state applies
    r_shift = jnp.concatenate([r_path[1:], r_term[None]])
    w_shift = jnp.concatenate([w_path[1:], w_term[None]])
    (m0_knots, vnvrs0), _ = jax.lax.scan(
        backward, (vf_term.m_knots, vf_term.vnvrs_knots),
        (pols.m_knots, pols.c_knots, r_shift, w_shift), reverse=True)
    vf0 = ValueFunction(m_knots=m0_knots, vnvrs_knots=vnvrs0,
                        disc_fac=jnp.asarray(disc_fac))
    v_path = value_on_histogram(vf0, 1.0 + r_path[0], w_path[0], model,
                                crra)                         # [D, N]
    v_steady = value_on_histogram(vf_term, 1.0 + r_term, w_term, model,
                                  crra)
    welfare_path = jnp.sum(init_dist * v_path)
    welfare_steady = jnp.sum(init_dist * v_steady)
    ce = consumption_equivalent(welfare_steady, welfare_path, crra,
                                disc_fac)
    return TransitionWelfare(
        ce=ce, welfare_path=welfare_path, welfare_steady=welfare_steady,
        ce_by_cell=consumption_equivalent(v_steady, v_path, crra,
                                          disc_fac))
