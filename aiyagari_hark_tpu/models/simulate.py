"""Simulators: the Monte-Carlo agent panel as one ``lax.scan`` program, and
the aggregate-Markov history generator.

The reference simulates 11,000 periods by calling four Python hooks per
period per agent, with a per-agent ``np.random.choice`` in the inner loop —
3.85M Python RNG calls per history (SURVEY.md §3.3, hot loop #2), drawn from
the *global* NumPy RNG (reproducibility bug §3.6-3).  Here one period is a
scan step: a single ``jax.random.categorical`` over the whole panel, explicit
key threading (seed-reproducible by construction), and the factor-pricing
"mill" fused into the same step.

Timing matches HARK's ``Market.make_history`` (sow -> cultivate -> reap ->
mill -> store, SURVEY.md §3.1): agents act at period t on the prices milled
at t-1; the mill at t consumes ``MrkvNow_hist[t]`` and the just-saved assets.
Employment transitions use *exact-count* draws (the reference's permutation
machinery, ``make_emp_idx_arrays``/``get_shocks``): the number of agents
switching employment status is deterministic given the aggregate transition;
*which* agents switch is random.  The previous aggregate state is carried
explicitly instead of re-derived from the realized unemployment rate (fixes
quirk §3.6-4).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..ops.interp import eval_policy_agents
from . import firm
from .ks_model import KSCalibration, KSPolicy


def simulate_markov_history(transition: jnp.ndarray, init_state: int, length: int,
                            key: jax.Array) -> jnp.ndarray:
    """The aggregate Bad/Good chain (``make_Mrkv_history``,
    ``Aiyagari_Support.py:1793-1805``) as a scan of categorical draws."""
    logp = jnp.log(transition)

    def step(state, k):
        new = jax.random.categorical(k, logp[state])
        return new, state

    keys = jax.random.split(key, length)
    _, hist = jax.lax.scan(step, jnp.asarray(init_state), keys)
    return hist


class PanelState(NamedTuple):
    assets: jnp.ndarray       # [Nag] end-of-period assets
    labor_state: jnp.ndarray  # [Nag] int labor-supply state
    employed: jnp.ndarray     # [Nag] bool
    M_now: jnp.ndarray        # aggregate resources agents will see next period
    R_now: jnp.ndarray
    W_now: jnp.ndarray
    mrkv: jnp.ndarray         # aggregate state agents will see next period


class PanelHistory(NamedTuple):
    """The economy's ``track_vars`` (``Aiyagari_Support.py:1587``)."""

    mrkv: jnp.ndarray    # [T] aggregate state consumed by the mill at t
    A_prev: jnp.ndarray  # [T] mean end-of-period assets at t
    M_now: jnp.ndarray   # [T] aggregate resources computed by the mill at t
    urate: jnp.ndarray   # [T] realized unemployment rate at t


def initial_panel(cal: KSCalibration, agent_count: int, mrkv_init: int,
                  key: jax.Array) -> PanelState:
    """Birth the panel at the steady state (``sim_birth``,
    ``Aiyagari_Support.py:1173-1214``): assets at KSS, labor states spread
    evenly then shuffled, employment at the state's unemployment rate.
    Works for any agent count (the reference requires divisibility by N and
    silently corrupts otherwise — here the remainder is spread by rounding).
    """
    n = cal.labor_levels.shape[0]
    k1, k2 = jax.random.split(key)
    ls = jnp.arange(agent_count) % n
    ls = jax.random.permutation(k1, ls)
    urate = cal.urate_by_agg[mrkv_init]
    unemp_n = jnp.round(urate * agent_count).astype(jnp.int32)
    emp = jax.random.permutation(k2, jnp.arange(agent_count) >= unemp_n)
    ss = cal.steady_state
    return PanelState(
        assets=jnp.full((agent_count,), ss.K, dtype=cal.a_grid.dtype),
        labor_state=ls, employed=emp,
        M_now=ss.M.astype(cal.a_grid.dtype), R_now=ss.R.astype(cal.a_grid.dtype),
        W_now=ss.W.astype(cal.a_grid.dtype),
        mrkv=jnp.asarray(mrkv_init))


def mill_aggregates(cal: KSCalibration, A, z):
    """The factor-pricing "mill" (``calc_R_and_W``,
    ``Aiyagari_Support.py:1839-1894``): aggregate capital ``A`` and
    aggregate state ``z`` -> (R, W, M).  ONE implementation shared by the
    panel step, the histogram step, and the den Haan forecast diagnostic —
    the diagnostic's validity depends on exact timing parity with the
    simulators, so the formula must not fork."""
    prod = cal.prod_by_agg[z]
    agg_l = (1.0 - cal.urate_by_agg[z]) * cal.lbr_ind
    k_to_l = A / agg_l
    R = firm.interest_factor(k_to_l, cal.cap_share, cal.depr_fac, prod)
    W = firm.wage_rate(k_to_l, cal.cap_share, prod)
    return R, W, R * A + W * agg_l


def _conditional_emp_probs(mrkv_prev, mrkv_now, cal: KSCalibration):
    """Employment switch probabilities conditional on the aggregate move,
    from the 4x4 joint (BU,BE,GU,GE) matrix: rows ``2z+emp``, columns
    ``2z'+emp'``; ``P(emp'|emp, z->z') = M[2z+emp, 2z'+emp'] / P_agg[z,z']``.
    Shared by the exact-count panel draw and the expected-mass histogram
    flow so the subtle indexing lives in exactly one place."""
    p_agg = cal.agg_transition[mrkv_prev, mrkv_now]
    p_u_to_e = cal.empl_transition[2 * mrkv_prev + 0, 2 * mrkv_now + 1] / p_agg
    p_e_to_u = cal.empl_transition[2 * mrkv_prev + 1, 2 * mrkv_now + 0] / p_agg
    return p_u_to_e, p_e_to_u


def _transition_employment_exact(key, employed, mrkv_prev, mrkv_now,
                                 cal: KSCalibration):
    """Exact-count employment transitions, conditional on the aggregate move.

    The number of switchers is the rounded expected count (the reference's
    permutation apparatus achieves the same invariant); the identity of
    switchers is a uniform random choice implemented by ranking uniform keys.
    """
    p_u_to_e, p_e_to_u = _conditional_emp_probs(mrkv_prev, mrkv_now, cal)

    n_emp = jnp.sum(employed)
    n_unemp = employed.shape[0] - n_emp
    n_fire = jnp.round(n_emp * p_e_to_u).astype(jnp.int32)
    n_hire = jnp.round(n_unemp * p_u_to_e).astype(jnp.int32)

    # Rank agents within each group by a uniform draw; the top-k switch.
    u = jax.random.uniform(key, employed.shape)
    emp_rank = jnp.argsort(jnp.argsort(jnp.where(employed, u, 2.0)))
    unemp_rank = jnp.argsort(jnp.argsort(jnp.where(~employed, u, 2.0)))
    fired = employed & (emp_rank < n_fire)
    hired = (~employed) & (unemp_rank < n_hire)
    return (employed & ~fired) | hired


def _panel_mean(x, axis_name):
    """Mean over the (possibly device-sharded) agent axis: local mean, then
    ``pmean`` over the mesh axis — the TPU equivalent of the reference's
    ``np.mean(aNow)`` aggregation (``Aiyagari_Support.py:1868``)."""
    m = jnp.mean(x)
    if axis_name is not None:
        m = jax.lax.pmean(m, axis_name)
    return m


def simulate_panel(policy: KSPolicy, cal: KSCalibration, mrkv_hist: jnp.ndarray,
                   init: PanelState, key: jax.Array, axis_name=None):
    """Run the full panel history as one scan (act_T periods).

    Scan step = the reference's period (SURVEY.md §3.3): labor/employment
    shocks -> market resources -> consumption via the state-indexed policy ->
    savings -> mill (factor prices from mean assets and ``mrkv_hist[t]``).

    ``axis_name``: mesh axis the agent panel is sharded over (inside
    ``shard_map``); aggregation then rides a ``pmean`` collective.  The
    exact-count employment machinery applies per shard — shard counts sum to
    the global invariant up to rounding.
    """
    logp_tauchen = jnp.log(cal.tauchen_transition)

    def step(state: PanelState, inputs):
        z_t, k = inputs
        k_labor, k_emp = jax.random.split(k)
        # --- shocks (get_shocks, :1217-1256)
        ls_new = jax.random.categorical(k_labor, logp_tauchen[state.labor_state])
        emp_new = _transition_employment_exact(
            k_emp, state.employed, state.mrkv, z_t, cal)
        # In reference-parity (Aiyagari) mode labor income ignores employment
        # (everyone supplies their labor level, Aiyagari_Support.py:991-1018
        # comment trail); in true-KS mode the unemployed earn zero.
        eff_labor = cal.labor_levels[ls_new]
        if cal.ks_employment:
            eff_labor = eff_labor * emp_new
        # --- states (get_states, :1259-1283)
        m = state.R_now * state.assets + state.W_now * eff_labor
        # --- controls (get_controls, :1286-1409): state index 4*ls + 2*z + emp
        s_idx = 4 * ls_new + 2 * state.mrkv + emp_new.astype(jnp.int32)
        c = eval_policy_agents(m, s_idx, state.M_now, cal.m_grid,
                               policy.m_knots, policy.c_knots)
        # --- poststates (get_poststates, :1411-1415)
        a_new = m - c
        # --- mill (calc_R_and_W, :1839-1894) consuming mrkv_hist[t]
        A_prev = _panel_mean(a_new, axis_name)
        urate_real = 1.0 - _panel_mean(emp_new.astype(a_new.dtype), axis_name)
        R_new, W_new, M_new = mill_aggregates(cal, A_prev, z_t)
        out = (z_t, A_prev, M_new, urate_real)
        new_state = PanelState(assets=a_new, labor_state=ls_new,
                               employed=emp_new, M_now=M_new, R_now=R_new,
                               W_now=W_new, mrkv=z_t)
        return new_state, out

    keys = jax.random.split(key, mrkv_hist.shape[0])
    final, (mrkv, A_prev, M_now, urate) = jax.lax.scan(
        step, init, (mrkv_hist, keys))
    return PanelHistory(mrkv=mrkv, A_prev=A_prev, M_now=M_now, urate=urate), final


# --------------------------------------------------------------------------
# Deterministic distribution-iteration simulator (SURVEY.md §7 step 4): push
# a wealth histogram through the policy + transition operator instead of
# sampling a 350-agent panel.  Same per-period timing and mill as
# ``simulate_panel``, zero Monte-Carlo noise — the 1 bp r* equivalence
# budget cannot be met through MC noise (SURVEY.md §7 "Hard parts"), and
# the reference's small panel is the dominant noise source.
# --------------------------------------------------------------------------


class DistPanelState(NamedTuple):
    """Histogram analog of ``PanelState``: mass over (end-of-period assets,
    labor state, employment status)."""

    dist: jnp.ndarray        # [D, N, 2]
    M_now: jnp.ndarray
    R_now: jnp.ndarray
    W_now: jnp.ndarray
    mrkv: jnp.ndarray


def make_sim_dist_grid(cal: KSCalibration, dist_count: int = 500,
                       top_factor: float = 2.0) -> jnp.ndarray:
    """Histogram support for the simulator: 0 (borrowing limit) then an
    exp-mult grid up to ``top_factor`` x the policy grid's top, so the
    ergodic right tail is not clipped at the solution grid boundary."""
    from ..ops.grids import make_grid_exp_mult  # grid-ok: KS panel histogram, reference parity

    inner = make_grid_exp_mult(1e-3, top_factor * float(cal.a_grid[-1]),  # grid-ok
                               dist_count - 1, 2, dtype=cal.a_grid.dtype)
    return jnp.concatenate([jnp.zeros((1,), dtype=inner.dtype), inner])


def initial_distribution_panel(cal: KSCalibration, dist_grid: jnp.ndarray,
                               mrkv_init: int,
                               k0=None) -> DistPanelState:
    """Histogram analog of ``initial_panel``: all mass at capital ``k0``
    (default: the steady state; two-point lottery onto the grid), labor
    states uniform, employment at the initial aggregate state's unemployment
    rate.  Prices are milled from ``k0`` so the first simulated period sees
    the same factor prices a panel started at ``k0`` would."""
    from ..ops.interp import locate_in_grid

    n = cal.labor_levels.shape[0]
    ss = cal.steady_state
    k0 = ss.K if k0 is None else jnp.asarray(k0)
    urate = cal.urate_by_agg[mrkv_init]
    r0, w0, m0 = mill_aggregates(cal, k0, mrkv_init)
    idx, w = locate_in_grid(jnp.asarray(k0, dtype=dist_grid.dtype),
                            dist_grid)
    asset_col = (jnp.zeros((dist_grid.shape[0],), dtype=dist_grid.dtype)
                 .at[idx].add(1.0 - w).at[idx + 1].add(w))
    emp_w = jnp.stack([urate, 1.0 - urate]).astype(dist_grid.dtype)
    dist = asset_col[:, None, None] * (1.0 / n) * emp_w[None, None, :]
    dist = jnp.broadcast_to(dist, (dist_grid.shape[0], n, 2))
    return DistPanelState(
        dist=dist, M_now=m0.astype(dist_grid.dtype),
        R_now=r0.astype(dist_grid.dtype),
        W_now=w0.astype(dist_grid.dtype), mrkv=jnp.asarray(mrkv_init))


def initial_distribution_fan(cal: KSCalibration, dist_grid: jnp.ndarray,
                             mrkv_init: int, fan: int,
                             spread: float = 0.75) -> DistPanelState:
    """A fan of ``fan`` histogram initial states with initial capital spread
    geometrically over ``[spread, 1/spread] x KSS`` (stacked on a leading
    axis, ready for ``jax.vmap`` over ``simulate_distribution_history``).

    Why: with the aggregate shock switched off (the Aiyagari configuration,
    ``Aiyagari_Support.py:1538-1547``), a *deterministic* simulated path sits
    exactly at its fixed point after the transient, so the Krusell-Smith
    ``log A on log M`` regression has no variation to identify the slope —
    in the reference that identification is supplied accidentally by
    Monte-Carlo sampling noise.  The fan restores identification
    deterministically: each path's transient traces the true aggregate map
    ``M -> A'`` through a neighborhood of the fixed point.
    """
    ss = cal.steady_state
    factors = (jnp.geomspace(spread, 1.0 / spread, fan)
               if fan > 1 else jnp.ones((1,)))
    inits = [initial_distribution_panel(cal, dist_grid, mrkv_init,
                                        k0=f * ss.K)
             for f in factors]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *inits)


def simulate_distribution_history(policy: KSPolicy, cal: KSCalibration,
                                  mrkv_hist: jnp.ndarray,
                                  dist_grid: jnp.ndarray,
                                  init: DistPanelState | None = None,
                                  fixed_K=None):
    """Run the full history by pushing the histogram through each period.

    Mirrors ``simulate_panel`` step for step — labor mixing (Tauchen row
    mix), conditional employment flows (expected mass instead of
    exact-count draws), consumption at state index ``4 ls + 2 z_prev + e``,
    the same mill — but deterministically: no keys, no sampling noise.
    Aggregates are exact expectations; the two-point lottery preserves the
    mean, so ``A_prev`` equals the pre-scatter expectation exactly.
    Returns the same ``(PanelHistory, final state)`` contract.

    ``fixed_K``: mill factor prices from this capital stock instead of the
    realized ``A_prev`` — the fixed-price relaxation the slope-pinned
    secant needs.  Motivation (measured at the notebook calibration): with
    realized-price feedback, histogram-top truncation caps the measured
    mean capital, realized r reads ABOVE the 1/beta - 1 supply cap, beta*R
    exceeds one, every wealth level drifts upward, and the clipped tail
    re-feeds the truncation — a self-consistent pseudo-equilibrium (r
    4.32% with 2.3% of mass parked at the grid top).  Under fixed prices
    the simulated path is exactly the household supply curve A(r(K)), so
    the secant's fixed point is the bisection engine's market-clearing
    equation, and r can never run above the cap at a root.  ``history``
    still records the realized ``A_prev``; ``M_now``/prices record what
    households actually faced.
    """
    from ..ops.interp import eval_policy_agents, locate_in_grid

    if init is None:
        # mrkv_hist[0] may be traced (inside jit) — initial_distribution_panel
        # only indexes with it, so no concretization is needed
        init = initial_distribution_panel(cal, dist_grid, mrkv_hist[0])
    if fixed_K is not None:
        r0, w0, m0 = mill_aggregates(cal, fixed_K, init.mrkv)
        dt = dist_grid.dtype
        init = init._replace(R_now=r0.astype(dt), W_now=w0.astype(dt),
                             M_now=m0.astype(dt))
    d_size, n = dist_grid.shape[0], cal.labor_levels.shape[0]

    def step(state: DistPanelState, z_t):
        # --- labor transition (categorical draw -> row mix)
        dist_l = jnp.einsum("dne,nm->dme", state.dist,
                            cal.tauchen_transition,
                            precision=jax.lax.Precision.HIGHEST)
        # --- employment flows conditional on the aggregate move (expected
        # mass instead of the panel's exact-count draws)
        p_u_to_e, p_e_to_u = _conditional_emp_probs(state.mrkv, z_t, cal)
        unemp = dist_l[:, :, 0]
        emp = dist_l[:, :, 1]
        new_unemp = unemp * (1.0 - p_u_to_e) + emp * p_e_to_u
        new_emp = emp * (1.0 - p_e_to_u) + unemp * p_u_to_e
        dist_le = jnp.stack([new_unemp, new_emp], axis=-1)   # [D, N, 2]
        # --- resources and consumption (same state index as the panel)
        eff = cal.labor_levels[None, :, None] * jnp.ones((1, 1, 2))
        if cal.ks_employment:
            eff = eff * jnp.asarray([0.0, 1.0])[None, None, :]
        m = state.R_now * dist_grid[:, None, None] + state.W_now * eff
        ls_idx = jnp.broadcast_to(jnp.arange(n)[None, :, None],
                                  m.shape)
        e_idx = jnp.broadcast_to(jnp.arange(2)[None, None, :], m.shape)
        s_idx = 4 * ls_idx + 2 * state.mrkv + e_idx
        c = eval_policy_agents(m.ravel(), s_idx.ravel(), state.M_now,
                               cal.m_grid, policy.m_knots, policy.c_knots)
        a_new = jnp.clip(m - c.reshape(m.shape), 0.0, dist_grid[-1])
        # --- aggregates (exact expectations, pre-scatter)
        A_prev = jnp.sum(dist_le * a_new)
        urate_real = jnp.sum(dist_le[:, :, 0])
        # --- scatter savings back onto the histogram support
        idx, w = locate_in_grid(a_new, dist_grid)

        def scatter_col(mass_col, idx_col, w_col):
            z = jnp.zeros((d_size,), dtype=mass_col.dtype)
            z = z.at[idx_col].add(mass_col * (1.0 - w_col))
            z = z.at[idx_col + 1].add(mass_col * w_col)
            return z

        flat = lambda x: x.reshape(d_size, n * 2)   # noqa: E731
        new_dist = jax.vmap(scatter_col, in_axes=1, out_axes=1)(
            flat(dist_le), flat(idx), flat(w)).reshape(d_size, n, 2)
        # --- mill (identical to simulate_panel; fixed_K pins the price
        # feedback to the perceived stock — see the docstring)
        R_new, W_new, M_new = mill_aggregates(
            cal, A_prev if fixed_K is None else fixed_K, z_t)
        out = (z_t, A_prev, M_new, urate_real)
        return DistPanelState(dist=new_dist, M_now=M_new, R_now=R_new,
                              W_now=W_new, mrkv=z_t), out

    final, (mrkv, A_prev, M_now, urate) = jax.lax.scan(step, init, mrkv_hist)
    return PanelHistory(mrkv=mrkv, A_prev=A_prev, M_now=M_now,
                        urate=urate), final
