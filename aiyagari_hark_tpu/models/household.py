"""Household problem on the compact (asset x labor-state) space: EGM backward
step, infinite-horizon fixed point, and the stationary wealth distribution.

This is the *native* state space of the Aiyagari model: N labor states, no
aggregate-state machinery.  The reference runs the same economics through a
4N-state Krusell-Smith apparatus with the aggregate shock switched off
(SURVEY.md §0) — a documented 4x compute waste.  The KS-parity path lives in
``models.ks_model``; this module is the fast path used by the bisection
equilibrium and the Table II sweep.

Math contract (same as the reference's one-period solver,
``Aiyagari_Support.py:1423-1520``, minus the degenerate aggregate dimension):
    vP'(a_i, s') = u'(c_next(R a_i + W l_{s'}))
    EndOfPrdvP(a_i, s) = beta * R * sum_{s'} P[s,s'] vP'(a_i, s')
    c = EndOfPrdvP^(-1/crra);  m = a + c        (endogenous gridpoints)
    prepend the borrowing-constraint knot (~0, ~0)   (:1503-1504)
iterated to the infinite-horizon fixed point.  A policy is a pair of knot
arrays [N, A+1]; evaluation is the batched interp kernel.  Everything is
jit/vmap-safe: ``crra``/``R``/``W`` may be traced (calibration sweeps vmap
over them), shapes are static, loops are ``lax.while_loop``.
"""

from __future__ import annotations

from typing import NamedTuple

import functools

import jax
import jax.numpy as jnp

import numpy as np

from ..ops.grids import build_asset_grids, resolve_grid
from ..ops.interp import (
    append_tail_knot,
    interp1d,
    interp1d_rowwise,
    locate_in_grid,
)
from ..ops.markov import (
    normalized_labor_states,
    stationary_distribution,
    tauchen_labor_process,
)
from ..ops.utility import (
    asymptotic_mpc,
    inverse_marginal_utility,
    marginal_utility,
)
from ..solver_health import (
    NONFINITE,
    STALLED,
    call_step,
    classify_fixed_point_exit,
    inject_fault,
)
from ..utils.config import resolve_precision

# The reference's borrowing-constraint knot value (Aiyagari_Support.py:1503).
CONSTRAINT_EPS = 1e-7


# First-tail-segment slope blend (DESIGN §5b): s_bar = kappa +
# TAIL_SLOPE_BLEND * (s_local - kappa).  The true tail slope decays from
# the local MPC toward the limit MPC; both pure endpoints are provably
# biased by concavity (kappa-only understates tail consumption, measured
# -0.65bp of r* at the worst golden cell; local-slope overstates it,
# +0.31bp), so the blend sits inside the bracketing band — 3/4 centers
# the measured drift across the 12 golden cells (worst cell +0.01bp) and
# reflects that an exponentially-decaying slope spends most of the
# segment near its initial value.
TAIL_SLOPE_BLEND = 0.75


def perfect_foresight_human_wealth(R, W, labor_levels, transition):
    """Per-state expected PV of future labor income discounted at ``R``
    — the intercept of the consumption function's asymptote (DESIGN
    §5b): Ma-Stachurski-Toda (arXiv:2002.09108) give ``c(m) -> kappa (m
    + h_s)``, with ``h`` solving ``h = P (y + h) / R`` for ``y = W l``.
    ``R`` is floored just above 1 — a transient bisection probe at a
    negative rate has no convergent PV, and the tail only needs a finite
    monotone surrogate there (the final root sits at r > 0)."""
    y = W * labor_levels
    dt = y.dtype
    R_eff = jnp.maximum(jnp.asarray(R, dtype=dt), 1.0 + 1e-3)
    n = labor_levels.shape[0]
    rhs = jnp.matmul(transition, y[:, None],
                     precision=jax.lax.Precision.HIGHEST,
                     preferred_element_type=dt)[:, 0] / R_eff
    return jnp.linalg.solve(jnp.eye(n, dtype=dt) - transition / R_eff,
                            rhs)


def _append_analytic_tail(m_knots, c_knots, R, W, disc_fac, crra,
                          labor_levels, transition):
    """Close a consumption policy with the TWO-knot analytic linear tail
    (DESIGN §5b): ride the LOCAL MPC (the last endogenous segment's
    slope) from the truncation knot until it meets the perfect-foresight
    asymptote ``c = kappa (m + h_s)`` (slope = the model's MPC limit
    ``ops.utility.asymptotic_mpc``, intercept = per-state human wealth),
    then ride the asymptote — which, being the LAST segment, also
    governs extrapolation to arbitrary wealth (``ops.interp.interp1d``).

    Rationale: the true consumption function is concave, approaching the
    asymptote from BELOW with local MPC decaying toward ``kappa`` from
    above — so a pure-``kappa`` tail anchored at the truncation knot
    understates tail consumption (measured −0.65bp of r* at the worst
    golden cell) while riding the local secant slope overstates it
    (+0.31bp).  The first tail segment therefore uses the BLENDED slope
    ``kappa + TAIL_SLOPE_BLEND * (s_local - kappa)`` — the 3/4 weight
    that centers the measured drift band (see the constant's rationale)
    — capped by the asymptote itself (an upper bound on a concave
    function approached from below);
    the second segment runs at exactly ``kappa``, which, being the LAST
    segment, also governs extrapolation to arbitrary wealth.  Knot
    POSITIONS are fixed one grid-span apart (no crossing-point division
    — a near-parallel local slope must not amplify tolerance-scale
    iterate noise into knot movement, which would stall the fixed
    point's sup-norm certificate).  Slopes are clipped into (0, 1] so a
    transient probe at a pathological rate (negative r makes the PF MPC
    negative) still produces a strictly monotone, positive-consumption
    tail.
    """
    kappa = asymptotic_mpc(R, disc_fac, crra)
    h = perfect_foresight_human_wealth(R, W, labor_levels, transition)
    return _append_analytic_tail_knots(m_knots, c_knots, kappa, h)


def _append_analytic_tail_knots(m_knots, c_knots, kappa, h):
    """The tail closure given its two model-level ingredients — the RAW
    asymptotic MPC ``kappa`` (clipped here) and the per-state human
    wealth ``h``.  Split out of ``_append_analytic_tail`` so the fused
    Pallas megakernel (DESIGN §4c) can close the tail in-kernel: ``h``
    needs an [N, N] linear solve Mosaic cannot lower, but it depends
    only on (R, W, P) — constant across the fixed point — so the kernel
    dispatch computes it once outside and passes it in, while the
    elementwise ``kappa`` is computed wherever the closure runs.  Same
    ops in the same order as before the split: the XLA compact path is
    bit-identical."""
    dt = m_knots.dtype
    tiny = jnp.asarray(np.finfo(np.float64).tiny, dtype=dt)
    kappa = jnp.clip(kappa, 1e-3, 0.999).astype(dt)
    m_top = m_knots[:, -1]
    c_top = c_knots[:, -1]
    span = jnp.maximum(m_top - m_knots[:, 0], 1.0)
    s_loc = ((c_knots[:, -1] - c_knots[:, -2])
             / jnp.maximum(m_knots[:, -1] - m_knots[:, -2], tiny))
    s_bar = jnp.clip(kappa + TAIL_SLOPE_BLEND * (s_loc - kappa),
                     kappa, 1.0)
    m1 = m_top + span
    c1 = jnp.minimum(c_top + s_bar * span, kappa * (m1 + h))
    c1 = jnp.maximum(c1, c_top + kappa * span)   # monotone floor
    m2 = m1 + span
    c2 = c1 + kappa * span
    return (jnp.concatenate([m_knots, m1[:, None], m2[:, None]], axis=1),
            jnp.concatenate([c_knots, c1[:, None], c2[:, None]], axis=1))


class HouseholdPolicy(NamedTuple):
    """Consumption policy as data: per-state endogenous knots, [N, A+1]."""

    m_knots: jnp.ndarray
    c_knots: jnp.ndarray


class SimpleModel(NamedTuple):
    """Static calibration arrays for the compact household problem."""

    a_grid: jnp.ndarray          # [A] end-of-period asset grid
    labor_levels: jnp.ndarray    # [N] normalized labor supply per state
    transition: jnp.ndarray      # [N, N] labor-state Markov matrix
    labor_stationary: jnp.ndarray  # [N] stationary distribution of labor states
    dist_grid: jnp.ndarray       # [D] wealth-histogram support
    borrow_limit: jnp.ndarray = 0.0   # scalar b <= 0: a >= b each period


def build_simple_model(labor_states: int = 7, labor_ar: float = 0.6,
                       labor_sd: float = 0.2, labor_bound: float = 3.0,
                       a_min: float = 0.001, a_max: float = 50.0,
                       a_count: int = 32, a_nest_fac: int = 2,
                       dist_count: int = 500, borrow_limit: float = 0.0,
                       grid="reference", grid_tail: str = "analytic",
                       dtype=None) -> SimpleModel:
    """Assemble the calibration arrays.  ``labor_ar``/``labor_sd`` may be
    traced scalars (sweep axes); grid sizes are static.

    ``borrow_limit`` b <= 0 shifts both grids so end-of-period assets live
    in [b, a_max] with the exp-mult point density concentrated just above
    the constraint (Huggett-style ad-hoc debt limits; b = 0 reproduces the
    reference's no-borrowing Aiyagari setup exactly).  The caller must keep
    b above the natural limit at the prices it solves under
    (``-W l_min / r`` for r > 0), else the constrained agent cannot service
    debt and consumption turns negative.

    ``grid`` (ISSUE 12, DESIGN §5b): the grid policy, resolved through
    the ``ops.grids.build_asset_grids`` seam — "reference" (default)
    builds the historical grids bit-identically; "compact"/"adaptive"
    spend the (smaller) point budget on the curved low-wealth region
    only and close the top with a linear tail.  ``grid_tail`` picks the
    tail contract: "analytic" (the solver appends a knot at the
    asymptotic MPC slope — ``solve_household``'s EGM path) or "anchors"
    (sparse geometric solution points close [a_hat, a_max] structurally
    — solvers without a tail contract, e.g. Epstein-Zin).
    """
    a_grid, dist_grid, _ = build_asset_grids(
        grid, a_min, a_max, a_count, a_nest_fac, dist_count,
        borrow_limit=borrow_limit, dtype=dtype, tail=grid_tail)
    tauchen = tauchen_labor_process(labor_states, labor_ar, labor_sd,
                                    bound=labor_bound, dtype=dtype)
    levels = normalized_labor_states(tauchen.grid)
    pi = stationary_distribution(tauchen.transition)
    return SimpleModel(a_grid=a_grid, labor_levels=levels,
                       transition=tauchen.transition, labor_stationary=pi,
                       dist_grid=dist_grid,
                       borrow_limit=jnp.asarray(borrow_limit,
                                                dtype=a_grid.dtype))


def initial_distribution(model) -> jnp.ndarray:
    """Cold-start wealth histogram: all mass at the borrowing limit, labor
    states at their ergodic weights.  Works for any model carrying
    ``dist_grid`` and ``labor_stationary`` (SimpleModel, PortfolioModel)."""
    d_size = model.dist_grid.shape[0]
    n = model.labor_stationary.shape[0]
    return (jnp.zeros((d_size, n), dtype=model.dist_grid.dtype)
            .at[0, :].set(model.labor_stationary))


def initial_policy(model: SimpleModel,
                   analytic_tail: bool = False) -> HouseholdPolicy:
    """Terminal guess c(m) = m - b (consume all resources above the debt
    limit) — the reference's ``IdentityFunction`` terminal solution
    (``Aiyagari_Support.py:898``) expressed as knots with slope 1, shifted
    so consumption stays positive under a negative borrowing limit.

    ``analytic_tail`` (grid compaction, DESIGN §5b): append the TWO
    linear tail knots so the initial iterate already carries the compact
    policy shape ``[N, A+3]``; the identity guess's tail slopes are 1
    (the first EGM step replaces them with the local-MPC/asymptote
    pair)."""
    n = model.labor_levels.shape[0]
    eps = jnp.asarray(CONSTRAINT_EPS, dtype=model.a_grid.dtype)
    b = jnp.asarray(model.borrow_limit, dtype=model.a_grid.dtype)
    m_row = jnp.concatenate([b[None] + eps, model.a_grid + eps])
    m_knots = jnp.tile(m_row, (n, 1))
    c_knots = m_knots - b
    if analytic_tail:
        one = jnp.asarray(1.0, dtype=m_knots.dtype)
        m_knots, c_knots = append_tail_knot(m_knots, c_knots, one)
        m_knots, c_knots = append_tail_knot(m_knots, c_knots, one)
    return HouseholdPolicy(m_knots=m_knots, c_knots=c_knots)


def egm_step(policy: HouseholdPolicy, R, W, model: SimpleModel,
             disc_fac, crra,
             matmul_precision=jax.lax.Precision.HIGHEST,
             analytic_tail: bool = False,
             foc_dtype=None) -> HouseholdPolicy:
    """One EGM backward step on the [A, N] block.  The expectation over next
    states is a single [A,N']x[N',N] matmul (MXU-friendly), replacing the
    reference's per-state Python loop (``Aiyagari_Support.py:1479-1485``).

    ``matmul_precision``: HIGHEST by default — the TPU bf16 matmul default
    loses ~3 decimal digits, which the EGM fixed point bakes into the
    policy (r* moves >1bp) when EVERY step runs that way.  The mixed-
    precision ladder's descent phase (DESIGN §5) passes DEFAULT instead:
    bf16 matmul inputs, accumulation pinned to the iterate dtype via
    ``preferred_element_type``, with the polish phase erasing the drift.

    ``analytic_tail`` (grid compaction, DESIGN §5b — static): the model's
    asset grid is the curved low-wealth region only, and the policy is
    closed above its top endogenous knot by the TWO-knot analytic tail
    (``_append_analytic_tail``: blended-slope approach segment, then the
    asymptotic-MPC line ``ops.utility.asymptotic_mpc``) — every
    evaluation above the knee (the ``c_next`` queries at high ``R a + W
    l`` here, the distribution push-forward in ``wealth_transition``)
    then rides the asymptotic linear form instead of grid interpolation.
    Policy shape is ``[N, A+3]`` (constraint knot + A endogenous + two
    tail knots).

    ``foc_dtype`` (ISSUE 13, the bf16 descent rung — DESIGN §4c): run
    the ``x^(-1/gamma)`` FOC inversion in this dtype and cast the result
    back to the iterate dtype.  The inversion's fractional power is the
    one step of the backward pass whose relative error bf16 amplifies
    (SURVEY §"Precision" — the rest of the step is linear/monotone), so
    the bf16 rung pins it to f32 while everything else runs in the
    rung's dtype.  ``None`` (default) inverts in the iterate dtype —
    bit-identical to the pre-rung step."""
    a = model.a_grid                                  # [A]
    m_next = R * a[:, None] + W * model.labor_levels[None, :]   # [A, N']
    # c_next(m) per next-state: rowwise interp with per-state knots.
    c_next = interp1d_rowwise(m_next.T, policy.m_knots, policy.c_knots).T
    vp_next = marginal_utility(c_next, crra)          # [A, N']
    end_of_prd_vp = disc_fac * R * jnp.matmul(
        vp_next, model.transition.T, precision=matmul_precision,
        preferred_element_type=vp_next.dtype)
    if foc_dtype is not None and end_of_prd_vp.dtype != jnp.dtype(foc_dtype):
        c_now = inverse_marginal_utility(
            end_of_prd_vp.astype(foc_dtype), crra).astype(
                end_of_prd_vp.dtype)
    else:
        c_now = inverse_marginal_utility(end_of_prd_vp, crra)
    m_now = a[:, None] + c_now
    # borrowing-constraint knot: at m = b + eps the agent consumes eps and
    # carries a = b; interpolation below the first endogenous knot then has
    # slope ~1 in c — the exact constrained policy c = m - b
    eps = jnp.full((1, c_now.shape[1]), CONSTRAINT_EPS, dtype=c_now.dtype)
    b = jnp.asarray(model.borrow_limit, dtype=c_now.dtype)
    c_knots = jnp.concatenate([eps, c_now], axis=0).T   # [N, A+1]
    m_knots = jnp.concatenate([b + eps, m_now], axis=0).T
    if analytic_tail:
        m_knots, c_knots = _append_analytic_tail(
            m_knots, c_knots, R, W, disc_fac, crra, model.labor_levels,
            model.transition)
    return HouseholdPolicy(m_knots=m_knots, c_knots=c_knots)


def anderson_rate(d1, d2, lam_max: float = 0.995):
    """Dominant contraction rate from two successive increments —
    the Anderson(1)/Aitken estimate lam = <d2,d1>/<d1,d1>, clipped to
    [0, lam_max].  The extrapolation factor is lam/(1-lam).  The ONE
    implementation shared by the policy, distribution, and rate-path
    (credit-crunch tatonnement) accelerators; each site keeps its own
    domain safeguards (knot monotonicity / mass renormalization /
    bracket clipping)."""
    lam = jnp.sum(d2 * d1) / jnp.maximum(jnp.sum(d1 * d1),
                                         jnp.finfo(d2.dtype).tiny)
    return jnp.clip(lam, 0.0, lam_max)


def accelerated_policy_fixed_point(step_fn, p0, tol: float, max_iter: int,
                                   accel_every: int = 32):
    """EGM fixed point with certified Anderson(1)/Aitken acceleration, for
    any policy NamedTuple whose fields are knot arrays with ``m_knots``
    first among them (the compact ``HouseholdPolicy``, the 4N-state
    ``KSPolicy``, and ``EZPolicy``).

    ``step_fn``: one EGM backward step, policy -> policy.  Convergence is
    sup-norm over ALL fields — not consumption alone: a field the step's
    own feedback is blind to must not escape uncertified (EZPolicy's
    value scale is exactly such a mode — homogeneity cancels it inside
    the Euler weights, so it decays at the plain rate no matter how fast
    c converges; certifying c only was measured to leave V ~40x less
    converged).  For the CRRA policies the broadened certificate changes
    nothing: m = a + c on a fixed a-grid, so the m-diff IS the c-diff.

    Every ``accel_every`` steps one extrapolation along the dominant
    contraction mode (rate ~ disc_fac, so plain iteration needs
    ~log(tol)/log(beta) steps) is applied to EVERY field, with the rate
    estimated over the whole tree.  Safety mirrors the distribution
    iterator's: the extrapolation is only the next ITERATE (any error is
    washed out by subsequent exact EGM steps), it is rejected wholesale
    if it breaks the strict monotonicity of the endogenous grid
    (``searchsorted`` needs sorted knots) or the positivity of any
    non-grid field (consumption, value), and the loop returns the last
    PLAIN iterate its diff certifies — a ``max_iter`` exit landing on an
    acceleration step can never hand the caller an unevaluated
    extrapolation.  ``accel_every=0`` disables.  Returns
    (policy, n_iter, final_diff, status).

    Solver health: a non-finite sup-norm diff (NaN compares False against
    ``tol``, so it would otherwise exit looking exactly like convergence;
    +inf would burn the whole ``max_iter`` budget) trips the in-carry
    finiteness flag and exits immediately; the trailing ``status`` is a
    ``solver_health`` code (CONVERGED / MAX_ITER / NONFINITE here — this
    loop has no stall exit).  ``step_fn`` may advertise
    ``takes_iteration`` to receive the iteration index
    (``solver_health.inject_fault``).
    """
    big = jnp.asarray(jnp.finfo(p0.c_knots.dtype).max,
                      dtype=p0.c_knots.dtype)
    fields = p0._fields

    def tree_diff(a, b):
        return jnp.max(jnp.asarray(
            [jnp.max(jnp.abs(getattr(a, f) - getattr(b, f)))
             for f in fields]))

    def flat(a, b):
        return jnp.concatenate(
            [(getattr(a, f) - getattr(b, f)).ravel() for f in fields])

    def cond(state):
        _, _, _, diff, it, finite = state
        return (diff > tol) & (it < max_iter) & finite

    def step(policy, prev, it):
        new = call_step(step_fn, policy, it)
        return new, policy, new, tree_diff(new, policy), it + 1

    def step_accel(policy, prev, it):
        new = call_step(step_fn, policy, it)
        diff = tree_diff(new, policy)
        lam = anderson_rate(flat(policy, prev), flat(new, policy))
        fac = lam / (1.0 - lam)
        extr = {f: getattr(new, f) + fac * (getattr(new, f)
                                            - getattr(policy, f))
                for f in fields}
        ok = (jnp.all(jnp.diff(extr["m_knots"], axis=-1) > 0)
              & jnp.all(jnp.asarray(
                  [jnp.all(extr[f] > 0) for f in fields
                   if f != "m_knots"]))
              & (diff > tol))
        out = new._replace(**{f: jnp.where(ok, extr[f], getattr(new, f))
                              for f in fields})
        return out, new, new, diff, it + 1

    def body(state):
        policy, prev, _, _, it, _ = state
        use_accel = (accel_every > 0) & (jnp.mod(it + 1,
                                                 max(accel_every, 1)) == 0)
        policy, prev, certified, diff, it = jax.lax.cond(
            use_accel, step_accel, step, policy, prev, it)
        return policy, prev, certified, diff, it, jnp.isfinite(diff)

    _, _, certified, diff, it, _ = jax.lax.while_loop(
        cond, body, (p0, p0, p0, big, jnp.asarray(0), jnp.asarray(True)))
    return certified, it, diff, classify_fixed_point_exit(diff, tol, it,
                                                          max_iter)


# ---------------------------------------------------------------------------
# Mixed-precision fixed-point ladder (DESIGN §5).
# ---------------------------------------------------------------------------

# Descent-phase matmul contraction: DEFAULT lets the TPU MXU take bf16
# inputs (one pass instead of HIGHEST's six); accumulation stays in the
# iterate dtype via ``preferred_element_type`` at every call site.  On CPU
# the cheapness comes from the f32 iterate instead (twice the SIMD lanes).
DESCENT_MATMUL_PRECISION = jax.lax.Precision.DEFAULT

# Coarse-tolerance scales (units of the descent dtype's eps): how deep the
# cheap phase can CERTIFY a sup-norm diff before rounding noise floors it.
# Policy knots span the asset grid (values up to ~a_max = 50, f32 spacing
# ~4e-6 there), so the policy loop needs a wide margin; histogram masses
# are <= 1 with an observed f32 update floor of 1e-8..3e-8, so one eps is
# already conservative.
POLICY_DESCENT_TOL_SCALE = 256.0
DIST_DESCENT_TOL_SCALE = 1.0

# Bisection-level switch width (units of the cheap dtype's eps; see
# ``equilibrium.solve_equilibrium_lean``): the bracket width below which
# midpoint evaluations switch from descent-only inner solves to the full
# ladder.  256 eps_f32 ~ 3e-5 in r units — ~30x the measured f32
# root-placement noise (~1e-6; the 0.097 bp f32-vs-f64 drift across all
# 12 Table II cells, BENCH r5), and the re-bracketing margin in
# ``solve_equilibrium_lean`` widens the polish bracket by half its width
# on each side on top of that.  Measured on the 12-cell CPU sweep:
# polish_frac ~0.2 at zero r* drift vs the reference policy.
R_DESCENT_WIDTH_SCALE = 256.0


def descent_dtype(dtype):
    """The cheap dtype of the ladder's descent phase: f64 models descend
    in f32; f32 (and narrower) models keep their dtype — their descent
    cheapness is the DEFAULT-precision matmul path, not a narrower
    iterate (bf16 iterates cannot certify any useful tolerance — which
    is exactly why the bf16 RUNG below is a separate, coarser-tolerance
    phase under ``kernel="fused"``, not a replacement descent dtype)."""
    return jnp.float32 if jnp.dtype(dtype) == jnp.dtype("float64") else dtype


# -- the bf16 descent rung (ISSUE 13 leg 3, DESIGN §4c) ----------------------
#
# Under ``kernel="fused"`` with a two-phase precision policy the ladder
# gains one more rung BELOW the f32 descent: a bf16-iterate phase run to
# a very coarse tolerance (bf16 eps is 2^-7 ≈ 0.0078 — it can certify
# only the cheap early shape of the fixed point), whose iterate seeds the
# f32 descent.  PAPERS 2002.09108's asymptotic linearity is the license:
# errors in the near-linear region are cheap to polish away, so the
# earliest (most expensive, least accurate-needing) iterations may run at
# the narrowest dtype the MXU natively eats.  The x^(-1/gamma) FOC
# inversion stays f32 (``egm_step(foc_dtype=)`` — SURVEY §"Precision");
# a NONFINITE/STALLED bf16 rung escalates to the f32 descent from the
# caller's initial iterate, exactly the PRECISION_ESCALATED contract one
# level down, and is counted in the same ``PrecisionPhases.escalated``
# slot.  TPU-only at the solver seam (``bf16_rung_active``): off-TPU the
# narrow iterate buys nothing (no bf16 SIMD win) and costs conversions.
BF16_POLICY_RUNG_TOL_SCALE = 4.0    # units of bf16 eps: ~0.03 in knot sup-norm
BF16_DIST_RUNG_TOL_SCALE = 1.0      # histogram masses <= 1: one eps ≈ 0.0078
BF16_RUNG_BACKENDS = ("tpu", "axon")   # tests monkeypatch to drill on CPU


def bf16_rung_active(kspec, backend: str | None = None) -> bool:
    """Whether the fused kernel policy's bf16 descent rung runs here:
    the policy asks for it AND the backend is a TPU (``kspec`` is a
    ``utils.config.KernelSpec``)."""
    if not kspec.bf16_descent:
        return False
    if backend is None:
        backend = jax.default_backend()
    return backend in BF16_RUNG_BACKENDS


def descent_tolerance(tol, cheap_dtype, scale: float) -> float:
    """The descent phase's coarse certification target: the requested tol,
    floored at what the cheap dtype can certify (``scale`` eps)."""
    return max(float(tol), scale * float(jnp.finfo(cheap_dtype).eps))


def cast_floating(tree, dtype):
    """Cast every floating-point array leaf of a pytree (model, policy,
    transition) to ``dtype``; integer/bool leaves pass through.  The ONE
    down/up-cast used by every ladder entry point, so descent programs
    cannot half-cast a model."""
    def cast(leaf):
        arr = jnp.asarray(leaf)
        if jnp.issubdtype(arr.dtype, jnp.floating):
            return arr.astype(dtype)
        return leaf
    return jax.tree.map(cast, tree)


class PrecisionPhases(NamedTuple):
    """Per-phase step counters of one mixed-precision ladder solve.

    ``descent_steps``/``polish_steps`` are the iterations each phase took
    (reference-policy solves report all steps as polish — every step ran
    at reference precision).  ``escalated`` is True when the descent
    phase exited NONFINITE or STALLED and the polish restarted from the
    caller's initial iterate — a pure-reference solve
    (``solver_health.PRECISION_ESCALATED``)."""

    descent_steps: jnp.ndarray
    polish_steps: jnp.ndarray
    escalated: jnp.ndarray


def reference_phases(it) -> PrecisionPhases:
    """The phase accounting of a single-phase reference solve."""
    it = jnp.asarray(it)
    return PrecisionPhases(descent_steps=jnp.zeros_like(it),
                           polish_steps=it,
                           escalated=jnp.asarray(False))


def _with_phases(out, want_phases: bool, phases=None):
    """Append the trailing ``PrecisionPhases`` element iff the caller asked
    for it — the ONE place the optional-arity return is assembled, so the
    operator-precedence trap of inlining ``out + (...) if want else out``
    cannot recur at each solver exit.  ``phases=None`` means the solve was
    single-phase (``reference_phases`` of its iteration count)."""
    if not want_phases:
        return out
    return out + ((reference_phases(out[1]) if phases is None else phases),)


def _polish_cadence(accel_every: int) -> int:
    """Anderson cadence of the polish phase: tighter than the descent's.
    The polish starts NEAR the fixed point, where the dominant-rate
    estimate is accurate and extrapolation is safest (the distribution
    iterator's own lam_max reasoning), so extrapolating more often there
    cuts the reference-precision step count — the ladder's whole point —
    without touching the certification semantics (convergence is still a
    plain-step diff below tol)."""
    return max(8, int(accel_every) // 4) if accel_every > 0 else 0


def ladder_policy_fixed_point(step_cheap, step_ref, p0, tol: float,
                              descent_tol: float, max_iter: int,
                              accel_every: int = 32, polish: bool = True,
                              cheap_dtype=None, step_bf16=None,
                              bf16_tol: float | None = None):
    """Two-phase EGM fixed point: cheap-dtype descent to ``descent_tol``,
    reference-precision polish to ``tol`` — one jitted program, two
    ``while_loop``s (DESIGN §5).

    ``step_cheap`` must be the EGM step over CHEAP-dtype operands (the
    caller casts the model once with ``cast_floating``); ``step_ref`` the
    reference step.  Escalation: a NONFINITE descent (poisoned iterate)
    or a STALLED one (the coarse tolerance sat below the cheap dtype's
    rounding floor — its best iterate is uncertified noise) restarts the
    polish from ``p0`` with the full budget: a pure-reference solve, so
    quarantine only ever sees failures the reference path would also
    have produced.  A MAX_ITER descent is NOT escalated — its iterate is
    finite and certified to wherever it got, the polish continues from
    it.  ``polish=False`` is the "fast" policy: descent only, tolerance
    contract relaxed to the cheap floor (the caller documents this).

    Returns ``(policy, total_iters, diff, status, PrecisionPhases)`` —
    ``status``/``diff`` are the final phase's, so the caller's tolerance
    contract and solver_health semantics are unchanged under ``polish``.

    ``step_bf16``/``bf16_tol`` (ISSUE 13 leg 3): when given, one MORE
    rung runs below the cheap descent — a bf16-iterate phase to
    ``bf16_tol`` whose cast-up result seeds the descent.  A
    NONFINITE/STALLED bf16 rung escalates to the descent from ``p0``
    (the caller's initial iterate) and rides the same ``escalated``
    flag; its steps count as descent steps (they are descent work at a
    cheaper dtype still).
    """
    ref_dt = p0.c_knots.dtype
    dt = ref_dt if cheap_dtype is None else cheap_dtype
    p0_cheap = cast_floating(p0, dt)
    it_b = jnp.asarray(0)
    esc_b = jnp.asarray(False)
    if step_bf16 is not None:
        p0_b = cast_floating(p0, jnp.bfloat16)   # dtype-ok: the bf16 rung's
        #                                          own definition site
        pol_b, it_b, _, status_b = accelerated_policy_fixed_point(
            step_bf16, p0_b, bf16_tol, max_iter, accel_every)
        esc_b = (status_b == NONFINITE) | (status_b == STALLED)
        p0_cheap = jax.tree.map(
            lambda cold, warm: jnp.where(esc_b, cold, warm),
            p0_cheap, cast_floating(pol_b, dt))
    pol_d, it_d, diff_d, status_d = accelerated_policy_fixed_point(
        step_cheap, p0_cheap, descent_tol, max_iter, accel_every)
    it_d = it_d + it_b
    pol_up = cast_floating(pol_d, ref_dt)
    if not polish:
        phases = PrecisionPhases(descent_steps=it_d,
                                 polish_steps=jnp.zeros_like(it_d),
                                 escalated=esc_b)
        return pol_up, it_d, diff_d.astype(ref_dt), status_d, phases
    # polish restarts cold only on a DESCENT failure (a bf16-rung failure
    # already restarted the descent cold — its certified result stands);
    # the phases flag records either escalation.
    esc_d = (status_d == NONFINITE) | (status_d == STALLED)
    start = jax.tree.map(lambda cold, warm: jnp.where(esc_d, cold, warm),
                         p0, pol_up)
    pol, it_p, diff, status = accelerated_policy_fixed_point(
        step_ref, start, tol, max_iter, _polish_cadence(accel_every))
    phases = PrecisionPhases(descent_steps=it_d, polish_steps=it_p,
                             escalated=esc_b | esc_d)
    return pol, it_d + it_p, diff, status, phases


def ladder_distribution_fixed_point(push_cheap, push_ref, dist0, tol: float,
                                    descent_tol: float, max_iter: int,
                                    accel_every: int = 64,
                                    polish: bool = True, cheap_dtype=None,
                                    push_bf16=None,
                                    bf16_tol: float | None = None):
    """Two-phase stationary-distribution fixed point — the distribution
    twin of ``ladder_policy_fixed_point`` (same escalation contract,
    same optional bf16 rung below the descent — ISSUE 13 leg 3).
    The cast-up iterate is exactly renormalized before the polish (the
    cheap phase conserved mass only to its own rounding; the bf16 rung's
    before the descent, for the same reason)."""
    ref_dt = dist0.dtype
    dt = ref_dt if cheap_dtype is None else cheap_dtype
    d0_cheap = dist0.astype(dt)
    it_b = jnp.asarray(0)
    esc_b = jnp.asarray(False)
    if push_bf16 is not None:
        d_b, it_b, _, status_b = accelerated_distribution_fixed_point(
            push_bf16, dist0.astype(jnp.bfloat16),   # dtype-ok: bf16 rung
            bf16_tol, max_iter, accel_every)
        esc_b = (status_b == NONFINITE) | (status_b == STALLED)
        d_b_up = d_b.astype(dt)
        d_b_up = d_b_up / jnp.sum(d_b_up)
        d0_cheap = jnp.where(esc_b, d0_cheap, d_b_up)
    d_cheap, it_d, diff_d, status_d = accelerated_distribution_fixed_point(
        push_cheap, d0_cheap, descent_tol, max_iter, accel_every)
    it_d = it_d + it_b
    d_up = d_cheap.astype(ref_dt)
    d_up = d_up / jnp.sum(d_up)
    if not polish:
        phases = PrecisionPhases(descent_steps=it_d,
                                 polish_steps=jnp.zeros_like(it_d),
                                 escalated=esc_b)
        return d_up, it_d, diff_d.astype(ref_dt), status_d, phases
    esc_d = (status_d == NONFINITE) | (status_d == STALLED)
    start = jnp.where(esc_d, dist0, d_up)
    dist, it_p, diff, status = accelerated_distribution_fixed_point(
        push_ref, start, tol, max_iter, _polish_cadence(accel_every))
    phases = PrecisionPhases(descent_steps=it_d, polish_steps=it_p,
                             escalated=esc_b | esc_d)
    return dist, it_d + it_p, diff, status, phases


# ---------------------------------------------------------------------------
# Coarse-to-fine grid ladder (ISSUE 12, DESIGN §5b).
# ---------------------------------------------------------------------------

def _coarse_knot_indices(a_count: int) -> np.ndarray:
    """Static subsample of a compact asset grid for the ladder's coarse
    descent phase: every other point plus the top point (both endpoints
    kept, so prolongation never extrapolates)."""
    idx = np.arange(0, int(a_count), 2)
    if idx[-1] != a_count - 1:
        idx = np.append(idx, a_count - 1)
    return idx


def _restrict_policy(policy: HouseholdPolicy,
                     idx: np.ndarray) -> HouseholdPolicy:
    """Restrict a tail-closed fine policy ``[N, A+3]`` to the coarse knot
    subset ``[N, Ac+3]``: constraint knot, the subsampled endogenous
    knots, the two tail knots (recomputed analytically by the next EGM
    step)."""
    k = policy.m_knots.shape[1]
    cols = np.concatenate([[0], 1 + idx, [k - 2, k - 1]])
    return HouseholdPolicy(m_knots=policy.m_knots[:, cols],
                           c_knots=policy.c_knots[:, cols])


def _prolong_policy(pol_c: HouseholdPolicy, a_coarse, a_fine,
                    borrow_limit, close_tail) -> HouseholdPolicy:
    """Monotone prolongation of a coarse-grid policy onto the fine grid
    (the ladder's coarse->fine hand-off): the coarse endogenous knot
    curves ``a -> (m, c)`` are strictly increasing in ``a``, so linear
    interpolation at the fine gridpoints (a superset containing both
    endpoints) is strictly increasing too; the constraint knot is rebuilt
    exactly and the analytic tail re-appended by ``close_tail``
    (``_append_analytic_tail`` — the linear-tail extension).  Purely an
    initial ITERATE for the polish phase — any prolongation error is
    erased by subsequent exact EGM steps, convergence is still certified
    by a plain-step diff."""
    m_endo_c = pol_c.m_knots[:, 1:-2]                 # [N, Ac]
    c_endo_c = pol_c.c_knots[:, 1:-2]
    n = m_endo_c.shape[0]
    dt = m_endo_c.dtype
    aq = jnp.broadcast_to(jnp.asarray(a_fine, dtype=dt),
                          (n,) + a_fine.shape)
    ac = jnp.broadcast_to(jnp.asarray(a_coarse, dtype=dt),
                          (n,) + a_coarse.shape)
    m_endo = interp1d_rowwise(aq, ac, m_endo_c)
    c_endo = interp1d_rowwise(aq, ac, c_endo_c)
    eps = jnp.full((n, 1), CONSTRAINT_EPS, dtype=dt)
    b = jnp.asarray(borrow_limit, dtype=dt)
    m_k = jnp.concatenate([b + eps, m_endo], axis=1)
    c_k = jnp.concatenate([eps, c_endo], axis=1)
    m_k, c_k = close_tail(m_k, c_k)
    return HouseholdPolicy(m_knots=m_k, c_knots=c_k)


@functools.lru_cache(maxsize=None)
def _pallas_egm_fixed_point_vmappable(tol: float, max_iter: int,
                                      accel_every: int):
    """The Pallas EGM policy fixed point with a custom batching rule —
    the POLICY-loop twin of ``_pallas_fixed_point_vmappable``.

    A plain ``vmap`` over ``egm_policy_pallas`` would trace every lane
    into ONE kernel invocation running lock-step; ``custom_vmap``
    reroutes a batched call to ``egm_policy_pallas_grid`` instead: one
    program instance per lane, each exiting at its OWN convergence, so a
    converged calibration cell stops burning MXU cycles instead of
    running masked EGM steps until the slowest sweep lane's policy
    converges (ISSUE 2 tentpole).  Nested batch axes collapse into the
    lane axis exactly like the distribution grid dispatch."""
    from ..ops.pallas_kernels import egm_policy_pallas, egm_policy_pallas_grid

    def _bcast(axis_size, in_batched, *args):
        return tuple(a if b else jnp.broadcast_to(a, (axis_size,) + a.shape)
                     for b, a in zip(in_batched, args))

    @jax.custom_batching.custom_vmap
    def fp_grid(m0, c0, a_grid, levels, P, scalars):
        return egm_policy_pallas_grid(m0, c0, a_grid, levels, P, scalars,
                                      tol, max_iter, accel_every)

    @fp_grid.def_vmap
    def _grid_batched(axis_size, in_batched, *args):  # noqa: ANN001
        args = _bcast(axis_size, in_batched, *args)
        b, c = args[0].shape[0], args[0].shape[1]
        flat = tuple(a.reshape((b * c,) + a.shape[2:]) for a in args)
        m, cc, iters, diffs = fp_grid(*flat)
        return ((m.reshape((b, c) + m.shape[1:]),
                 cc.reshape((b, c) + cc.shape[1:]),
                 iters.reshape(b, c), diffs.reshape(b, c)),
                (True, True, True, True))

    @jax.custom_batching.custom_vmap
    def fp(m0, c0, a_grid, levels, P, scalars):
        return egm_policy_pallas(m0, c0, a_grid, levels, P, scalars,
                                 tol, max_iter, accel_every)

    @fp.def_vmap
    def _batched(axis_size, in_batched, *args):  # noqa: ANN001
        args = _bcast(axis_size, in_batched, *args)
        return fp_grid(*args), (True, True, True, True)

    return fp


def solve_household(R, W, model: SimpleModel, disc_fac, crra,
                    tol: float = 1e-6, max_iter: int = 3000,
                    init_policy: HouseholdPolicy | None = None,
                    accel_every: int = 32, method: str = "xla",
                    precision: str = "reference",
                    grid="reference",
                    kernel="reference",
                    state="replicated",
                    return_phases: bool = False,
                    descent_fault_iter: int | None = None,
                    descent_fault_mode: str = "nan"):
    """Infinite-horizon EGM fixed point via ``lax.while_loop``.

    Convergence is sup-norm on the consumption knots — the array analog of
    HARK's ConsumerSolution distance the reference's agent loop uses
    (SURVEY.md §3.1).  Returns (policy, n_iter, final_diff, status) with
    ``status`` a ``solver_health`` code; with ``return_phases=True`` a
    trailing ``PrecisionPhases`` rides along (descent/polish step split +
    the escalation flag — all zeros-descent under "reference").

    ``init_policy`` warm-starts the iteration (e.g. the previous bisection
    midpoint's policy — nearby prices → nearby fixed points → far fewer
    backward steps than the identity terminal guess).  Acceleration
    semantics: ``accelerated_policy_fixed_point``.

    ``method``: "xla" (default) runs the fixed point as a ``while_loop``
    — under ``vmap`` every lane steps until the slowest converges;
    "pallas" runs it as a VMEM-resident kernel whose ``custom_vmap``
    batching rule grids one program instance per lane, each exiting at
    its own convergence (``_pallas_egm_fixed_point_vmappable`` — the
    sweep's straggler answer extended to the policy loop); "auto" picks
    "pallas" on a TPU backend whose probe passes, else "xla".  Both
    engines run the SAME iteration code (``accelerated_policy_fixed_point``
    + ``egm_step``), so they take the same iteration path (same step
    count, same status); values agree to float-fusion noise.

    ``precision`` (DESIGN §5, ``utils.config.PRECISION_POLICIES``):
    "reference" (default) is today's single-phase solve, bit-identical;
    "mixed" runs the two-phase ladder (cheap-dtype descent to a coarse
    tolerance, reference polish to ``tol`` — contract unchanged); "fast"
    is descent-only (tolerance relaxed to the cheap floor).  The VMEM
    kernel runs a single-precision program, so non-reference policies
    demote ``method`` to "xla".  ``descent_fault_iter`` (tests) wraps the
    DESCENT step with ``solver_health.inject_fault`` from that iteration
    — the deterministic trigger for the escalation path (precision AND
    grid ladders alike).

    ``grid`` (DESIGN §5b, ``utils.config.GRID_POLICIES``): "reference"
    (default) solves on the model's grid as-is, bit-identical.
    "compact"/"adaptive" expect a compact model
    (``build_simple_model(grid=...)``) and (a) close every policy iterate
    with the ANALYTIC linear-tail knot (slope = the asymptotic MPC), and
    (b) run the coarse-to-fine grid ladder inside the jitted program:
    descend on a static subsample of the compact grid to a floored
    tolerance (``GridSpec.coarse_tol_factor`` x tol — composed with the
    precision ladder: under "mixed" the coarse phase runs in the cheap
    dtype), prolong the policy monotonically onto the compact grid
    (``_prolong_policy``), and polish to the ORIGINAL ``tol`` at the
    contract precision.  A NONFINITE/STALLED coarse phase escalates: the
    polish restarts from the caller's initial iterate with the full
    budget (``solver_health.GRID_ESCALATED`` note; counted in the
    returned phases' ``escalated`` flag, the same slot the precision
    escalation uses — the quarantine-level fallback to the dense
    reference grid is the sweep ladder's job).  The VMEM kernel runs the
    fixed reference knot layout, so compact grids demote ``method`` to
    "xla" exactly like non-reference precision does.

    ``kernel`` (ISSUE 13, ``utils.config.KERNEL_POLICIES``): "reference"
    (default) keeps the engine selection above, bit-identical.  "fused"
    opts into the device-resident kernel path — under a single-phase
    precision policy the VMEM EGM kernel runs wherever it is eligible
    (probe-gated on TPU, INTERPRET-mode on CPU — the CI correctness
    path; compact grids stay on the XLA tail/ladder path, whose
    in-kernel twin lives in the FUSED supply megakernel only); under a
    two-phase policy the descent ladder gains the bf16 rung
    (``bf16_rung_active`` — TPU-only, FOC inversion pinned f32, failed
    rung escalates into the same ``escalated`` slot).

    ``state`` (ISSUE 20, ``utils.config.STATE_POLICIES``): validated and
    threaded for the end-to-end policy contract, but the POLICY iterate
    itself stays replicated in both layouts — its footprint is
    O(N·A), dominated ~D²/A-fold by the wealth operator the
    DISTRIBUTION loop shards (``stationary_wealth(state=)``), so
    sharding it would add collectives to every EGM step for no memory
    relief (the partition-rule table reserves the ``policy`` rule for
    the day a family's policy object outgrows a device).
    """
    from ..utils.config import resolve_kernel, resolve_state

    spec = resolve_precision(precision)
    gspec = resolve_grid(grid)
    kspec = resolve_kernel(kernel)
    resolve_state(state)   # validate; policy iterate stays replicated
    tail = gspec.compact
    if tail and method in ("pallas", "auto"):
        method = "xla"
    p0 = (initial_policy(model, analytic_tail=tail)
          if init_policy is None else init_policy)
    if not spec.two_phase and not gspec.ladder:
        if kspec.fused and method in ("xla", "auto") and not tail:
            # the fused policy's single-loop engine: the VMEM kernel,
            # interpret-mode off-TPU, probe-gated on TPU (XLA fallback)
            from ..ops.pallas_kernels import probe_kernel
            on_tpu = jax.default_backend() in ("tpu", "axon")
            method = ("pallas" if not on_tpu or probe_kernel("egm_grid")
                      else "xla")
        elif method == "auto":
            from ..ops.pallas_kernels import pallas_egm_grid_tpu_available
            on_tpu = jax.default_backend() in ("tpu", "axon")
            method = ("pallas" if on_tpu and pallas_egm_grid_tpu_available()
                      else "xla")
        if method == "pallas":
            dt = model.a_grid.dtype
            scalars = jnp.stack([jnp.asarray(R, dtype=dt),
                                 jnp.asarray(W, dtype=dt),
                                 jnp.asarray(disc_fac, dtype=dt),
                                 jnp.asarray(crra, dtype=dt),
                                 jnp.asarray(model.borrow_limit, dtype=dt)])
            fp = _pallas_egm_fixed_point_vmappable(float(tol), int(max_iter),
                                                   int(accel_every))
            m, c, it, diff = fp(p0.m_knots, p0.c_knots, model.a_grid,
                                model.labor_levels, model.transition,
                                scalars)
            # status reconstructed outside the kernel boundary: this loop
            # has no stall exit, so (iters, diff) classify it exactly
            out = (HouseholdPolicy(m_knots=m, c_knots=c), it, diff,
                   classify_fixed_point_exit(diff, tol, it, max_iter))
            return _with_phases(out, return_phases)
        if method != "xla":
            raise ValueError(f"method must be 'xla', 'pallas' or 'auto', "
                             f"got {method!r}")
        out = accelerated_policy_fixed_point(
            lambda p: egm_step(p, R, W, model, disc_fac, crra,
                               analytic_tail=tail),
            p0, tol, max_iter, accel_every)
        return _with_phases(out, return_phases)

    if method not in ("xla", "auto", "pallas"):
        raise ValueError(f"method must be 'xla', 'pallas' or 'auto', "
                         f"got {method!r}")

    if not gspec.ladder:
        # -- mixed / fast: the two-phase precision ladder (DESIGN §5) ------
        cheap = descent_dtype(model.a_grid.dtype)
        model_c = cast_floating(model, cheap)
        Rc = jnp.asarray(R).astype(cheap)
        Wc = jnp.asarray(W).astype(cheap)
        bc = jnp.asarray(disc_fac).astype(cheap)
        cc = jnp.asarray(crra).astype(cheap)

        def step_cheap(p):
            return egm_step(p, Rc, Wc, model_c, bc, cc,
                            matmul_precision=DESCENT_MATMUL_PRECISION,
                            analytic_tail=tail)

        # The bf16 descent rung (ISSUE 13 leg 3): one more rung below
        # the cheap descent, TPU-gated; the FOC inversion stays f32.
        rung_kw = {}
        if bf16_rung_active(kspec):
            bf16 = jnp.bfloat16   # dtype-ok: the bf16 rung's solver seam
            model_b = cast_floating(model, bf16)
            Rb = jnp.asarray(R).astype(bf16)
            Wb = jnp.asarray(W).astype(bf16)
            bb = jnp.asarray(disc_fac).astype(bf16)
            cb = jnp.asarray(crra).astype(bf16)

            def step_bf16(p):
                return egm_step(p, Rb, Wb, model_b, bb, cb,
                                matmul_precision=DESCENT_MATMUL_PRECISION,
                                analytic_tail=tail,
                                foc_dtype=jnp.float32)

            rung_kw = dict(step_bf16=step_bf16,
                           bf16_tol=descent_tolerance(
                               tol, bf16, BF16_POLICY_RUNG_TOL_SCALE))
        if descent_fault_iter is not None:
            step_cheap = inject_fault(
                step_cheap, descent_fault_mode,
                at_iter=descent_fault_iter,
                amplitude=10.0 * descent_tolerance(
                    tol, cheap, POLICY_DESCENT_TOL_SCALE))
            if "step_bf16" in rung_kw:
                # the drill must exercise the NEW rung first: the same
                # injection poisons the bf16 phase, whose escalation
                # restarts the f32 descent cold (which the injection
                # then poisons too, escalating to the reference polish —
                # the full ladder walks itself, deterministically)
                rung_kw["step_bf16"] = inject_fault(
                    rung_kw["step_bf16"], descent_fault_mode,
                    at_iter=descent_fault_iter,
                    amplitude=10.0 * rung_kw["bf16_tol"])
        pol, it, diff, status, phases = ladder_policy_fixed_point(
            step_cheap,
            lambda p: egm_step(p, R, W, model, disc_fac, crra,
                               analytic_tail=tail),
            p0, tol,
            descent_tolerance(tol, cheap, POLICY_DESCENT_TOL_SCALE),
            max_iter, accel_every, polish=spec.polish, cheap_dtype=cheap,
            **rung_kw)
        return _with_phases((pol, it, diff, status), return_phases, phases)

    # -- coarse-to-fine grid ladder, composed with the precision ladder ----
    # (DESIGN §5b): ONE descent phase — subsampled grid, cheap dtype when
    # the precision policy is two-phase — then ONE polish phase on the
    # compact grid at the contract precision ("fast" keeps the cheap
    # dtype and its relaxed tolerance, honestly).
    ref_dt = model.a_grid.dtype
    a_count = model.a_grid.shape[0]
    idx = _coarse_knot_indices(a_count)
    coarse_model = model._replace(a_grid=model.a_grid[idx])
    cheap = descent_dtype(ref_dt) if spec.two_phase else ref_dt
    mat_prec = (DESCENT_MATMUL_PRECISION if spec.two_phase
                else jax.lax.Precision.HIGHEST)
    cm_c = cast_floating(coarse_model, cheap)
    Rc = jnp.asarray(R).astype(cheap)
    Wc = jnp.asarray(W).astype(cheap)
    bc = jnp.asarray(disc_fac).astype(cheap)
    cc = jnp.asarray(crra).astype(cheap)

    def step_coarse(p):
        return egm_step(p, Rc, Wc, cm_c, bc, cc,
                        matmul_precision=mat_prec, analytic_tail=True)

    tol_d = gspec.coarse_tol_factor * float(tol)
    if spec.two_phase:
        tol_d = max(tol_d, descent_tolerance(tol, cheap,
                                             POLICY_DESCENT_TOL_SCALE))
    if descent_fault_iter is not None:
        step_coarse = inject_fault(step_coarse, descent_fault_mode,
                                   at_iter=descent_fault_iter,
                                   amplitude=10.0 * tol_d)
    p0_c = cast_floating(_restrict_policy(p0, idx), cheap)
    pol_d, it_d, diff_d, status_d = accelerated_policy_fixed_point(
        step_coarse, p0_c, tol_d, max_iter, accel_every)

    ref_polish = spec.polish or not spec.two_phase
    pol_dt = ref_dt if ref_polish else cheap
    pol_model = model if ref_polish else cast_floating(model, cheap)
    Rp = jnp.asarray(R).astype(pol_dt)
    Wp = jnp.asarray(W).astype(pol_dt)
    bp = jnp.asarray(disc_fac).astype(pol_dt)
    cp = jnp.asarray(crra).astype(pol_dt)

    def step_fine(p):
        return egm_step(p, Rp, Wp, pol_model, bp, cp,
                        matmul_precision=(jax.lax.Precision.HIGHEST
                                          if ref_polish else mat_prec),
                        analytic_tail=True)

    tol_p = (float(tol) if ref_polish
             else descent_tolerance(tol, cheap, POLICY_DESCENT_TOL_SCALE))
    # Escalation (GRID_ESCALATED): a poisoned or floored coarse phase
    # must not seed the polish — restart from the caller's initial
    # iterate with the full budget, a pure compact-grid solve; the
    # quarantine rung's grid="reference" re-solve is the dense-grid
    # fallback beyond this.
    escalated = (status_d == NONFINITE) | (status_d == STALLED)

    def close_tail(mk, ck):
        return _append_analytic_tail(mk, ck, Rp, Wp, bp, cp,
                                     pol_model.labor_levels,
                                     pol_model.transition)

    prolonged = _prolong_policy(
        cast_floating(pol_d, pol_dt), coarse_model.a_grid, model.a_grid,
        model.borrow_limit, close_tail)
    p0_fine = cast_floating(p0, pol_dt)
    start = jax.tree.map(
        lambda cold, warm: jnp.where(escalated, cold, warm),
        p0_fine, prolonged)
    pol, it_p, diff, status = accelerated_policy_fixed_point(
        step_fine, start, tol_p, max_iter, _polish_cadence(accel_every))
    pol = cast_floating(pol, ref_dt)
    phases = PrecisionPhases(descent_steps=it_d, polish_steps=it_p,
                             escalated=escalated)
    return _with_phases((pol, it_d + it_p, diff.astype(ref_dt), status),
                        return_phases, phases)


def consumption_at(policy: HouseholdPolicy, m, state_idx=None):
    """Evaluate c(m) — rowwise if ``m`` is [N or batch]-shaped per state."""
    if state_idx is None:
        return interp1d_rowwise(m, policy.m_knots, policy.c_knots)
    return interp1d(m, policy.m_knots[state_idx], policy.c_knots[state_idx])


class WealthTransition(NamedTuple):
    """Precomputed Young-method lottery: where each (wealth-gridpoint, state)
    cell's savings land on the histogram support."""

    idx: jnp.ndarray     # [D, N] left-neighbor index into dist_grid
    weight: jnp.ndarray  # [D, N] mass share on the right neighbor
    a_next: jnp.ndarray  # [D, N] savings policy on the distribution grid


def wealth_transition(policy: HouseholdPolicy, R, W,
                      model: SimpleModel) -> WealthTransition:
    """Savings policy evaluated on the histogram support, split into lottery
    weights (Young 2010 non-stochastic simulation — the deterministic
    replacement for the reference's 350-agent Monte Carlo panel)."""
    x = model.dist_grid                                  # [D] capital today
    m = R * x[:, None] + W * model.labor_levels[None, :]  # [D, N]
    c = interp1d_rowwise(m.T, policy.m_knots, policy.c_knots).T
    a_next = jnp.clip(m - c, model.borrow_limit, model.dist_grid[-1])
    idx, w = locate_in_grid(a_next, model.dist_grid)
    return WealthTransition(idx=idx, weight=w, a_next=a_next)


def dense_wealth_operator(trans: WealthTransition,
                          d_size: int) -> jnp.ndarray:
    """The asset-lottery as a dense per-state operator ``S [N, D, D]``:
    column d of ``S[n]`` carries source gridpoint d's two-point lottery.

    TPU-native reformulation of the push-forward: XLA lowers the
    ``.at[].add`` scatter poorly on TPU (serialized updates), whereas
    ``moved[:, n] = S[n] @ dist[:, n]`` is a batched matvec the MXU eats —
    and at (D=500, N=7, f32) the whole operator is ~7 MB, small enough to
    stay VMEM-resident across thousands of fixed-point iterations (see
    ``ops.pallas_kernels``).  Built once per policy; the scatter below runs
    once, not per iteration."""
    n = trans.idx.shape[1]
    d_idx = jnp.arange(d_size)
    rows = jnp.arange(n)[:, None]
    S = jnp.zeros((n, d_size, d_size), dtype=trans.weight.dtype)
    S = S.at[rows, trans.idx.T, d_idx[None, :]].add(1.0 - trans.weight.T)
    S = S.at[rows, trans.idx.T + 1, d_idx[None, :]].add(trans.weight.T)
    return S


def _push_forward_dense(dist, S, transition_matrix,
                        matmul_precision=jax.lax.Precision.HIGHEST):
    """One distribution step as dense matmuls: per-state lottery matvec,
    then the labor-state mixing matmul.  HIGHEST by default (thousands of
    push-forward steps compound the TPU bf16 matmul default into visible
    mass error); the ladder's descent phase passes DEFAULT — bf16 MXU
    inputs, accumulation pinned to the iterate dtype (DESIGN §5): this is
    the matmul the MXU-eligibility claim is about."""
    moved = jnp.einsum("ndk,kn->dn", S, dist, precision=matmul_precision,
                       preferred_element_type=dist.dtype)
    return jnp.matmul(moved, transition_matrix, precision=matmul_precision,
                      preferred_element_type=dist.dtype)


def _push_forward(dist, trans: WealthTransition, transition_matrix,
                  matmul_precision=jax.lax.Precision.HIGHEST):
    """One distribution-iteration step: scatter mass along the asset lottery,
    then mix labor states with a [D,N]x[N,N] matmul."""
    d_size = dist.shape[0]

    def scatter_one_state(d_col, idx_col, w_col):
        z = jnp.zeros((d_size,), dtype=d_col.dtype)
        z = z.at[idx_col].add(d_col * (1.0 - w_col))
        z = z.at[idx_col + 1].add(d_col * w_col)
        return z

    moved = jax.vmap(scatter_one_state, in_axes=1, out_axes=1)(
        dist, trans.idx, trans.weight)
    # precision semantics: _push_forward_dense
    return jnp.matmul(moved, transition_matrix, precision=matmul_precision,
                      preferred_element_type=dist.dtype)


@functools.lru_cache(maxsize=None)
def _pallas_fixed_point_vmappable(tol: float, max_iter: int,
                                  accel_every: int):
    """The Pallas stationary fixed point with a custom batching rule.

    A plain ``vmap`` over ``stationary_dense_pallas`` puts every lane
    inside ONE kernel invocation, whose combined operators blow the scoped
    VMEM budget (the round-2 reason the sweep could not use the kernel).
    ``custom_vmap`` reroutes a batched call to
    ``stationary_dense_pallas_grid`` instead: one program instance per
    lane, each VMEM-resident for its own iterations and exiting at its own
    convergence — which is how the kernel beats lock-step ``vmap(dense)``
    on straggler-skewed sweeps (12-cell Table II sweep end-to-end:
    1.85 s vs 2.75 s on one v5e chip; measurement notes in
    ``scripts/pallas_ab.py`` and DESIGN §4).
    Nested batching (e.g. ``heterogeneity``'s beta-dist sweep vmapped over
    cells) is handled by the grid dispatch's OWN batching rule, which
    collapses each extra batch axis into the lane axis — a doubly-vmapped
    caller runs one flat lane grid instead of dying at Mosaic compile time
    on a ``vmap``-batched ``pallas_call`` whose grid rank no longer
    matches its dimension semantics (round-3 review).
    """
    from ..ops.pallas_kernels import (
        stationary_dense_pallas,
        stationary_dense_pallas_grid,
    )

    def _bcast(axis_size, in_batched, *args):
        return tuple(a if b else jnp.broadcast_to(a, (axis_size,) + a.shape)
                     for b, a in zip(in_batched, args))

    @jax.custom_batching.custom_vmap
    def fp_grid(S, P, d0):
        return stationary_dense_pallas_grid(S, P, d0, tol, max_iter,
                                            accel_every)

    @fp_grid.def_vmap
    def _grid_batched(axis_size, in_batched, S, P, d0):  # noqa: ANN001
        S, P, d0 = _bcast(axis_size, in_batched, S, P, d0)
        b, c = S.shape[0], S.shape[1]
        dist, iters, diffs = fp_grid(
            S.reshape((b * c,) + S.shape[2:]),
            P.reshape((b * c,) + P.shape[2:]),
            d0.reshape((b * c,) + d0.shape[2:]))
        return ((dist.reshape((b, c) + dist.shape[1:]),
                 iters.reshape(b, c), diffs.reshape(b, c)),
                (True, True, True))

    @jax.custom_batching.custom_vmap
    def fp(S, P, d0):
        return stationary_dense_pallas(S, P, d0, tol, max_iter, accel_every)

    @fp.def_vmap
    def _batched(axis_size, in_batched, S, P, d0):  # noqa: ANN001
        S, P, d0 = _bcast(axis_size, in_batched, S, P, d0)
        return fp_grid(S, P, d0), (True, True, True)

    return fp


# ---------------------------------------------------------------------------
# Fused EGM + push-forward supply evaluation (ISSUE 13 tentpole).
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _fused_cell_vmappable(tol: float, max_iter: int, accel_every: int,
                          dist_tol: float, dist_max_iter: int,
                          dist_accel: int, tail: bool):
    """The fused megakernel with a custom batching rule — the
    whole-supply-evaluation twin of ``_pallas_egm_fixed_point_vmappable``
    / ``_pallas_fixed_point_vmappable``: a plain ``vmap`` would trace
    every lane into ONE kernel invocation (lock-step, blown VMEM);
    ``custom_vmap`` reroutes a batched call to
    ``fused_cell_pallas_grid`` instead — one program instance per lane,
    each running its EGM fixed point AND its push-forward device-resident
    and exiting at its own convergence.  Nested batch axes collapse into
    the lane axis exactly like the per-loop grid dispatches."""
    from ..ops.pallas_kernels import fused_cell_pallas, fused_cell_pallas_grid

    def _bcast(axis_size, in_batched, *args):
        return tuple(a if b else jnp.broadcast_to(a, (axis_size,) + a.shape)
                     for b, a in zip(in_batched, args))

    n_out = 7

    @jax.custom_batching.custom_vmap
    def fp_grid(m0, c0, a, dg, lvl, P, scal, h, d0):
        return fused_cell_pallas_grid(m0, c0, a, dg, lvl, P, scal, h, d0,
                                      tol, max_iter, accel_every, dist_tol,
                                      dist_max_iter, dist_accel, tail)

    @fp_grid.def_vmap
    def _grid_batched(axis_size, in_batched, *args):  # noqa: ANN001
        args = _bcast(axis_size, in_batched, *args)
        b, c = args[0].shape[0], args[0].shape[1]
        flat = tuple(a.reshape((b * c,) + a.shape[2:]) for a in args)
        outs = fp_grid(*flat)
        return (tuple(o.reshape((b, c) + o.shape[1:]) for o in outs),
                (True,) * n_out)

    @jax.custom_batching.custom_vmap
    def fp(m0, c0, a, dg, lvl, P, scal, h, d0):
        return fused_cell_pallas(m0, c0, a, dg, lvl, P, scal, h, d0,
                                 tol, max_iter, accel_every, dist_tol,
                                 dist_max_iter, dist_accel, tail)

    @fp.def_vmap
    def _batched(axis_size, in_batched, *args):  # noqa: ANN001
        args = _bcast(axis_size, in_batched, *args)
        return fp_grid(*args), (True,) * n_out

    return fp


def fused_supply_phases(R, W, model: SimpleModel, disc_fac, crra,
                        egm_tol: float, dist_tol: float,
                        init_policy_knots: HouseholdPolicy | None = None,
                        init_dist=None, egm_max_iter: int = 3000,
                        egm_accel: int = 32, dist_max_iter: int = 20000,
                        dist_accel: int = 64, grid="reference"):
    """One supply evaluation's BOTH inner fixed points as ONE fused
    kernel launch (ISSUE 13 tentpole, DESIGN §4c): the EGM policy
    iteration and the distribution push-forward run device-resident back
    to back (``ops.pallas_kernels.fused_cell_pallas{,_grid}``), instead
    of the reference path's two separately-launched loops stitched by
    the host-visible XLA program.

    Under a compact ``grid`` policy the analytic linear tail closes
    every policy iterate IN-KERNEL (the human-wealth intercept is
    computed here — it needs an [N, N] solve and depends only on
    (R, W, P)); the coarse-to-fine grid LADDER is an XLA-path feature
    and does not run — the fused engine solves the compact grid
    directly, inside the same certified-tolerance contract.

    Returns ``(policy, dist, egm_iters, dist_iters, egm_status,
    dist_status)`` with both statuses reconstructed exactly from the
    kernel's (iters, diff) pairs (``classify_fixed_point_exit``)."""
    gspec = resolve_grid(grid)
    tail = gspec.compact
    p0 = (initial_policy(model, analytic_tail=tail)
          if init_policy_knots is None else init_policy_knots)
    d0 = initial_distribution(model) if init_dist is None else init_dist
    dt = model.a_grid.dtype
    R_ = jnp.asarray(R, dtype=dt)
    W_ = jnp.asarray(W, dtype=dt)
    scalars = jnp.stack([R_, W_, jnp.asarray(disc_fac, dtype=dt),
                         jnp.asarray(crra, dtype=dt),
                         jnp.asarray(model.borrow_limit, dtype=dt)])
    if tail:
        h = perfect_foresight_human_wealth(R_, W_, model.labor_levels,
                                           model.transition)
    else:
        h = jnp.zeros_like(model.labor_levels)
    fp = _fused_cell_vmappable(float(egm_tol), int(egm_max_iter),
                               int(egm_accel), float(dist_tol),
                               int(dist_max_iter), int(dist_accel),
                               bool(tail))
    m, c, dist, egm_it, egm_diff, dist_it, dist_diff = fp(
        p0.m_knots, p0.c_knots, model.a_grid, model.dist_grid,
        model.labor_levels, model.transition, scalars, h, d0)
    return (HouseholdPolicy(m_knots=m, c_knots=c), dist, egm_it, dist_it,
            classify_fixed_point_exit(egm_diff, egm_tol, egm_it,
                                      egm_max_iter),
            classify_fixed_point_exit(dist_diff, dist_tol, dist_it,
                                      dist_max_iter))


def stationary_wealth(policy: HouseholdPolicy, R, W, model: SimpleModel,
                      tol: float = 1e-11, max_iter: int = 20000,
                      init_dist=None, accel_every: int = 64,
                      method: str = "auto", precision: str = "reference",
                      kernel="reference", state="replicated",
                      return_phases: bool = False,
                      descent_fault_iter: int | None = None,
                      descent_fault_mode: str = "nan"):
    """Stationary joint distribution over (wealth, labor state), [D, N].

    Returns (dist, n_iter, final_diff, status) — ``status`` a
    ``solver_health`` code.  ``tol`` is on the sup-norm of the
    distribution update; mass is conserved exactly by the lottery scatter
    and restored exactly after each extrapolation.

    ``init_dist`` warm-starts the push-forward iteration; the chain is
    ergodic, so any proper initial distribution converges to the same fixed
    point — a nearby one (previous bisection midpoint) gets there in a
    fraction of the steps the degenerate all-at-zero start needs.

    ``accel_every``: every that many push-forward steps, apply one
    Anderson(1)/Aitken extrapolation ``d* ≈ d_t + λ/(1-λ) (d_t - d_{t-1})``
    with the dominant contraction rate λ estimated from the last two
    increments.  The wealth chain mixes slowly (λ ≈ 0.99+ near the
    equilibrium r), so plain power iteration needs thousands of steps; the
    extrapolation jumps along the slow mode and typically cuts them by
    ~2-4x.  Safe by construction: the result is clipped to ≥0, exactly
    renormalized, and only used as the next ITERATE (any extrapolation
    error is washed out by subsequent exact push-forwards; convergence is
    still certified by a plain-step sup-norm diff < tol).  Set
    ``accel_every=0`` to disable.

    ``method``: "scatter" iterates the two-point lottery with
    ``.at[].add`` (cheapest op count — the CPU choice); "dense" builds the
    per-state lottery operator once and iterates batched matvecs
    (MXU-friendly — the TPU choice when ``N·D²`` fits on chip, see
    ``dense_wealth_operator``); "pallas" runs the whole dense fixed point
    VMEM-resident in one kernel (``ops.pallas_kernels``); "solve" replaces
    the fixed point with one dense LU solve + refinement (uniform cost per
    cell — the skew-free choice under a vmapped sweep, see
    ``_stationary_solve``); "auto" picks by backend and size.

    ``precision`` (DESIGN §5): "reference" (default) is the single-phase
    solve, bit-identical to pre-ladder behavior; "mixed" runs the
    cheap-dtype descent + reference polish ladder (tolerance contract
    unchanged); "fast" is descent-only.  Under a non-reference policy the
    VMEM kernel demotes to "dense" (the kernel runs a single-precision
    program) and "auto" prefers "dense" on accelerators — the descent
    phase's DEFAULT-precision matmuls are what makes the dense operator
    MXU-eligible; "solve" ignores the ladder (LU + certified refinement
    is already a direct-then-polish scheme).  ``return_phases`` appends a
    ``PrecisionPhases``; ``descent_fault_iter`` (tests) poisons the
    descent phase via ``solver_health.inject_fault``.

    Grid-policy note (DESIGN §5b): this loop deliberately does NOT run
    a coarse-to-fine support ladder.  It was built and measured: under
    the bisection's warm-start carry every midpoint arrives with a
    near-converged fine distribution, and restricting it to a coarse
    support forces the slow accumulation mode to re-converge from the
    O(h^2) coarse/fine stationary gap at every midpoint — 3x the total
    steps and 2x the wall on the 12-cell golden sweep.  Compaction
    reaches this loop through the model build instead (the compacted
    histogram support itself); the coarse-to-fine ladder lives in the
    POLICY loop, whose prolongation error the warm carry does not pay
    repeatedly.

    ``kernel`` (ISSUE 13, DESIGN §4c): "fused" prefers the VMEM kernel
    engine wherever the precision policy is single-phase — interpret
    mode off-TPU (the CI correctness path), probe-gated compiled Mosaic
    on TPU with "dense"/"scatter" fallback; under a two-phase policy the
    ladder gains the bf16 descent rung (TPU-only,
    ``bf16_rung_active``).

    ``state`` (ISSUE 20, DESIGN §6b): "replicated" (default) keeps
    today's layout, bit-identical.  "sharded" — when a state mesh with
    ``state`` axis > 1 is ACTIVE (``parallel.mesh.active_state_mesh``;
    without one the policy degrades to the replicated layout) — routes
    EVERY push-forward form (scatter, dense, pallas) through the
    row-block-sharded contraction (``ops.markov.
    sharded_wealth_push_forward``): the distribution's wealth rows and
    the dense operator's source blocks live 1/M per device, the fixed
    point iterates on sharded residents, and one all-reduce per step is
    the only cross-device traffic.  The wealth grid ``D`` must divide
    the shard count (typed error otherwise — no silent demotion).  NOT
    bit-identical to replicated (the row-block reduction order — the
    ``tiled_wealth_push_forward`` carve-out); quarantine rungs force
    "replicated".
    """
    from ..utils.config import resolve_kernel, resolve_state

    spec = resolve_precision(precision)
    kspec = resolve_kernel(kernel)
    sspec = resolve_state(state)
    trans = wealth_transition(policy, R, W, model)
    dist0 = initial_distribution(model) if init_dist is None else init_dist
    d_size = model.dist_grid.shape[0]
    n = model.labor_levels.shape[0]
    state_mesh_active = None
    if sspec.sharded:
        from ..parallel.mesh import (STATE_AXIS, constrain_state,
                                     current_state_mesh, mesh_axis_size)

        smesh = current_state_mesh()
        n_state = mesh_axis_size(smesh, STATE_AXIS)
        if n_state > 1:
            if d_size % n_state:
                raise ValueError(
                    f"state='sharded' needs the wealth grid divisible by "
                    f"the state axis: D={d_size} rows across {n_state} "
                    f"state shards (pad the grid or change state_shards)")
            state_mesh_active = smesh
            # every engine routes through the ONE sharded contraction:
            # the scatter form serializes under a sharded carry and the
            # VMEM kernel is a single-device program by construction
            method = "dense"
    if kspec.fused and not spec.two_phase and method == "auto":
        from ..ops.pallas_kernels import probe_kernel
        on_tpu = jax.default_backend() in ("tpu", "axon")
        op_bytes = n * d_size * d_size * dist0.dtype.itemsize
        if not on_tpu:
            method = "pallas"        # interpret-mode kernel: the CI path
        elif op_bytes <= 8 * 2 ** 20 and probe_kernel("dense_grid"):
            method = "pallas"
        elif op_bytes <= 2 ** 31:
            method = "dense"
        else:
            method = "scatter"
    if spec.two_phase and method in ("auto", "pallas"):
        # the ladder's method table: the kernel runs ONE precision, so the
        # descent/polish split needs the XLA paths; on accelerators the
        # dense operator is the MXU path, everywhere else scatter wins
        on_tpu = jax.default_backend() in ("tpu", "axon")
        op_bytes = n * d_size * d_size * dist0.dtype.itemsize
        method = "dense" if (on_tpu and op_bytes <= 2 ** 31) else "scatter"
    if method == "auto":
        # TPU backends ("axon" is the tunneled TPU platform here) prefer the
        # VMEM-resident Pallas kernel, probed once per process because Mosaic
        # lowering gaps vary by TPU generation / jax version; if it is
        # unusable they still take the MXU-friendly dense-matmul path rather
        # than the scatter path (XLA serializes .at[].add on TPU).  CPU (and
        # any other backend) takes the scatter path that works everywhere.
        on_tpu = jax.default_backend() in ("tpu", "axon")
        op_bytes = n * d_size * d_size * dist0.dtype.itemsize
        fits_vmem = op_bytes <= 8 * 2 ** 20
        fits_hbm = op_bytes <= 2 ** 31   # dense operator must be buildable
        if on_tpu and fits_vmem:
            # probe the lane-GRID kernel (which subsumes the single-lane
            # probe): an "auto" caller may be vmapped later (the sweep),
            # where the custom_vmap rule dispatches the grid kernel —
            # passing on the single-lane probe alone could die at sweep
            # compile time
            from ..ops.pallas_kernels import pallas_grid_tpu_available
            method = ("pallas" if pallas_grid_tpu_available() else "dense")
        elif on_tpu and fits_hbm:
            method = "dense"
        else:
            method = "scatter"   # CPU, or operator too large to materialize
    if method == "pallas":
        S = dense_wealth_operator(trans, d_size)
        fp = _pallas_fixed_point_vmappable(float(tol), int(max_iter),
                                           int(accel_every))
        dist, it, diff = fp(S, model.transition, dist0)
        # The kernel's stats contract stays (iters, diff); the status is
        # fully reconstructible outside: a finite diff > tol before
        # max_iter can only be the stall window.
        out = (dist, it, diff, classify_fixed_point_exit(diff, tol, it,
                                                         max_iter))
        return _with_phases(out, return_phases)
    if method == "solve":
        S = dense_wealth_operator(trans, d_size)
        out = _stationary_solve(S, model.transition, dist0, tol)
        return _with_phases(out, return_phases)
    if method == "dense":
        S = dense_wealth_operator(trans, d_size)
        if state_mesh_active is not None:
            from ..ops.markov import sharded_wealth_push_forward

            smesh = state_mesh_active
            dist0 = constrain_state(dist0, smesh, "distribution")
            push = lambda d: sharded_wealth_push_forward(  # noqa: E731
                d, S, model.transition, smesh)
        else:
            push = lambda d: _push_forward_dense(d, S, model.transition)  # noqa: E731
    elif method == "scatter":
        push = lambda d: _push_forward(d, trans, model.transition)  # noqa: E731
    else:
        raise ValueError(f"method must be 'auto', 'scatter', 'dense', "
                         f"'pallas' or 'solve', got {method!r}")

    if not spec.two_phase:
        out = accelerated_distribution_fixed_point(
            push, dist0, tol, max_iter, accel_every)
        return _with_phases(out, return_phases)

    # -- mixed / fast: the two-phase ladder (DESIGN §5) --------------------
    cheap = descent_dtype(dist0.dtype)
    P_c = model.transition.astype(cheap)
    if method == "dense" and state_mesh_active is not None:
        from ..ops.markov import sharded_wealth_push_forward

        S_c = S.astype(cheap)
        push_cheap = lambda d: sharded_wealth_push_forward(  # noqa: E731
            d, S_c, P_c, state_mesh_active,
            matmul_precision=DESCENT_MATMUL_PRECISION)
    elif method == "dense":
        S_c = S.astype(cheap)
        push_cheap = lambda d: _push_forward_dense(  # noqa: E731
            d, S_c, P_c, matmul_precision=DESCENT_MATMUL_PRECISION)
    else:
        trans_c = cast_floating(trans, cheap)
        push_cheap = lambda d: _push_forward(  # noqa: E731
            d, trans_c, P_c, matmul_precision=DESCENT_MATMUL_PRECISION)
    # bf16 descent rung (ISSUE 13 leg 3): one rung below the cheap
    # descent under kernel="fused", TPU-gated — same escalation contract.
    rung_kw = {}
    if bf16_rung_active(kspec):
        bf16 = jnp.bfloat16   # dtype-ok: the bf16 rung's solver seam
        P_b = model.transition.astype(bf16)
        if method == "dense" and state_mesh_active is not None:
            from ..ops.markov import sharded_wealth_push_forward

            S_b = S.astype(bf16)
            push_bf16 = lambda d: sharded_wealth_push_forward(  # noqa: E731
                d, S_b, P_b, state_mesh_active,
                matmul_precision=DESCENT_MATMUL_PRECISION)
        elif method == "dense":
            S_b = S.astype(bf16)
            push_bf16 = lambda d: _push_forward_dense(  # noqa: E731
                d, S_b, P_b, matmul_precision=DESCENT_MATMUL_PRECISION)
        else:
            trans_b = cast_floating(trans, bf16)
            push_bf16 = lambda d: _push_forward(  # noqa: E731
                d, trans_b, P_b, matmul_precision=DESCENT_MATMUL_PRECISION)
        rung_kw = dict(push_bf16=push_bf16,
                       bf16_tol=descent_tolerance(
                           tol, bf16, BF16_DIST_RUNG_TOL_SCALE))
    if descent_fault_iter is not None:
        push_cheap = inject_fault(
            push_cheap, descent_fault_mode, at_iter=descent_fault_iter,
            amplitude=10.0 * descent_tolerance(tol, cheap,
                                               DIST_DESCENT_TOL_SCALE))
        if "push_bf16" in rung_kw:
            rung_kw["push_bf16"] = inject_fault(
                rung_kw["push_bf16"], descent_fault_mode,
                at_iter=descent_fault_iter,
                amplitude=10.0 * rung_kw["bf16_tol"])
    dist, it, diff, status, phases = ladder_distribution_fixed_point(
        push_cheap, push, dist0, tol,
        descent_tolerance(tol, cheap, DIST_DESCENT_TOL_SCALE),
        max_iter, accel_every, polish=spec.polish, cheap_dtype=cheap,
        **rung_kw)
    return _with_phases((dist, it, diff, status), return_phases, phases)


def _stationary_solve(S, transition, dist0, tol, refine: int = 2,
                      polish_max_iter: int = 20000):
    """Stationary distribution by a DIRECT linear solve instead of power
    iteration: the fixed point satisfies ``(I - A) x = 0`` with ``A`` the
    dense push-forward operator, made nonsingular by replacing one equation
    with the normalization ``sum x = 1`` (bordered system), then LU-solved.

    Why: power iteration's cost is the chain's mixing time — the
    high-persistence Table II cells (rho = 0.9) need ~10x the distribution
    steps of the easy cells, and under the sweep's vmap-of-while every lane
    waits for the slowest (the iteration-skew the bench records).  The
    direct solve costs the same O((D N)^3) LU for every cell — MXU-friendly
    and skew-free — at D*N = 3500 that is ~28 GFLOP, well under the
    slow-mixing cells' iteration cost.

    Accuracy: the bordered matrix's conditioning is ~1/(1 - lambda_2), poor
    in f32 exactly for slow-mixing chains, so the solve gets ``refine``
    rounds of iterative refinement (reusing the LU) and then a certified
    warm-started fixed-point continuation down to ``tol`` — the caller's
    tolerance contract holds exactly as for the iterative methods, with the
    continuation normally exiting after a couple of push-forwards.
    """
    n, d, _ = S.shape
    dtype = dist0.dtype
    T = jnp.transpose(S, (1, 2, 0))                       # [D', D, N]
    A = (T[:, None, :, :]
         * transition.T[None, :, None, :]).reshape(d * n, d * n)
    B = (jnp.eye(d * n, dtype=dtype) - A).at[-1, :].set(1.0)
    rhs = jnp.zeros((d * n,), dtype=dtype).at[-1].set(1.0)
    lu, piv = jax.scipy.linalg.lu_factor(B)
    x = jax.scipy.linalg.lu_solve((lu, piv), rhs)
    for _ in range(refine):
        resid = rhs - jnp.matmul(B, x, precision=jax.lax.Precision.HIGHEST,
                                 preferred_element_type=x.dtype)
        x = x + jax.scipy.linalg.lu_solve((lu, piv), resid)
    x = jnp.clip(x, 0.0, None)
    dist = (x / jnp.sum(x)).reshape(d, n)
    # Certified continuation to the REQUESTED tol: warm-started accelerated
    # power iteration from the solved point.  When the LU+refinement was
    # accurate (the usual case) this exits in a couple of push-forwards and
    # the per-cell cost stays uniform; when f32 conditioning left residual
    # error (slow-mixing chains), it iterates it away instead of silently
    # returning a distribution that misses the caller's dist_tol — the
    # bisection relies on every midpoint meeting the full tolerance.
    push = lambda dd: _push_forward_dense(dd, S, transition)   # noqa: E731
    dist, it, diff, status = accelerated_distribution_fixed_point(
        push, dist, tol, polish_max_iter)
    return dist, it + jnp.asarray(refine + 1), diff, status


def accelerated_distribution_fixed_point(push, dist0, tol, max_iter,
                                         accel_every: int = 64,
                                         lam_max: float = 0.995):
    """Iterate ``dist <- push(dist)`` to its fixed point with periodic
    Anderson(1)/Aitken extrapolation (see ``stationary_wealth``), for any
    mass-conserving push-forward operator.  Returns
    (dist, n_iter, diff, status) with ``status`` a ``solver_health`` code:
    a non-finite step diff trips the in-carry finiteness flag and exits
    immediately as NONFINITE (NaN would otherwise masquerade as
    convergence, +inf would burn the budget), the stall window exits
    STALLED, the budget MAX_ITER, a certified residual CONVERGED.
    ``push`` may advertise ``takes_iteration``
    (``solver_health.inject_fault``).

    ``lam_max`` caps the estimated contraction rate (extrapolation factor
    ``lam/(1-lam)``).  The default is conservative for cold starts; a
    warm start that is already near the fixed point (e.g. the direct-solve
    continuation) can afford a cap much closer to 1 — the extrapolation is
    clipped to nonnegative mass and renormalized, and convergence is still
    certified by a plain-step diff, so an overshoot costs iterations, not
    correctness.

    Stall exit: if the certified diff makes no new best for 512 consecutive
    steps, the iteration stops — the requested ``tol`` may sit below the
    dtype's rounding floor for a slow-mixing chain (observed in f32 around
    1e-8..3e-8), and burning ``max_iter`` steps against an unreachable
    tolerance starves every other lane of a vmapped batch.  The BEST
    certified (iterate, diff) pair seen is what is returned (the current
    iterate can be worse, e.g. mid-recovery from an extrapolation
    overshoot), so callers always get the honest best residual.
    """
    big = jnp.asarray(jnp.finfo(dist0.dtype).max, dtype=dist0.dtype)
    stall_window = 512

    def cond(state):
        _, _, diff, it, _, _, since, finite = state
        return ((diff > tol) & (it < max_iter) & (since < stall_window)
                & finite)

    def step(dist, prev, it):
        new = call_step(push, dist, it)
        diff = jnp.max(jnp.abs(new - dist))
        # last element: the iterate the certified diff describes
        return new, dist, diff, it + 1, new

    def step_accel(dist, prev, it):
        new = call_step(push, dist, it)
        diff = jnp.max(jnp.abs(new - dist))
        d1 = dist - prev                    # increment t-1
        d2 = new - dist                     # increment t
        lam = anderson_rate(d1, d2, lam_max)
        extrap = jnp.clip(new + lam / (1.0 - lam) * d2, 0.0, None)
        extrap = extrap / jnp.sum(extrap)
        # If this plain step already converged, the loop exits now — carry
        # the CERTIFIED iterate, not the unchecked extrapolation.
        out = jnp.where(diff <= tol, new, extrap)
        return out, new, diff, it + 1, new

    def body(state):
        dist, prev, _, it, best, best_dist, since, _ = state
        use_accel = (accel_every > 0) & (jnp.mod(it + 1, max(accel_every, 1))
                                         == 0)
        dist, prev, diff, it, certified = jax.lax.cond(
            use_accel, step_accel, step, dist, prev, it)
        improved = diff < best
        best_dist = jnp.where(improved, certified, best_dist)
        best = jnp.minimum(best, diff)
        since = jnp.where(improved, 0, since + 1)
        return (dist, prev, diff, it, best, best_dist, since,
                jnp.isfinite(diff))

    _, _, diff, it, best, best_dist, _, _ = jax.lax.while_loop(
        cond, body,
        (dist0, dist0, big, jnp.asarray(0), big, dist0, jnp.asarray(0),
         jnp.asarray(True)))
    # Classify on the BEST certified residual (what the returned iterate
    # honestly achieves), except that a non-finite LAST diff means the
    # iteration itself was poisoned — that must surface as NONFINITE even
    # though the returned best iterate predates the poisoning.
    status = jnp.where(~jnp.isfinite(diff), jnp.int32(NONFINITE),
                       classify_fixed_point_exit(best, tol, it, max_iter))
    return best_dist, it, best, status


def aggregate_capital(dist: jnp.ndarray, model: SimpleModel) -> jnp.ndarray:
    """E[a] under the stationary distribution — household capital supply."""
    return jnp.sum(dist * model.dist_grid[:, None])


def aggregate_labor(model: SimpleModel) -> jnp.ndarray:
    """Effective labor supply E[l] under the stationary labor distribution.
    Not exactly 1.0: the reference normalizes levels by the unweighted grid
    mean (``Aiyagari_Support.py:985``), so the stationary mean differs."""
    return jnp.sum(model.labor_stationary * model.labor_levels)
