"""Cobb-Douglas production side: factor pricing and the perfect-foresight
steady state.

Reference: ``AiyagariEconomy.update`` computes the steady-state objects
(``Aiyagari_Support.py:1606-1615``) and ``calc_R_and_W`` prices factors each
simulated period (``Aiyagari_Support.py:1886-1890``):
    R = 1 + Z * alpha * (K/L)^(alpha-1) - delta
    W = Z * (1-alpha) * (K/L)^alpha
All closed forms, elementwise, jit/vmap-safe (inputs may be traced).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp


def interest_factor(k_to_l, cap_share, depr_fac, prod=1.0):
    """Gross return on capital R = 1 + Z a (K/L)^(a-1) - d."""
    return 1.0 + prod * cap_share * k_to_l ** (cap_share - 1.0) - depr_fac


def wage_rate(k_to_l, cap_share, prod=1.0):
    """Wage W = Z (1-a) (K/L)^a."""
    return prod * (1.0 - cap_share) * k_to_l ** cap_share


def k_to_l_from_r(r, cap_share, depr_fac, prod=1.0):
    """Invert the marginal product of capital: the K/L ratio at which the net
    interest rate is ``r`` — the firm's capital demand per unit labor."""
    return ((r + depr_fac) / (prod * cap_share)) ** (1.0 / (cap_share - 1.0))


def output(k, l, cap_share, prod=1.0):
    """Gross output Y = Z K^a L^(1-a)."""
    return prod * k ** cap_share * l ** (1.0 - cap_share)


def aggregate_resources(k, l, cap_share, depr_fac, prod=1.0):
    """M = (1-d) K + Z K^a L^(1-a) (``Aiyagari_Support.py:975-976``)."""
    return (1.0 - depr_fac) * k + output(k, l, cap_share, prod)


class SteadyState(NamedTuple):
    k_to_l: jnp.ndarray
    K: jnp.ndarray
    W: jnp.ndarray
    R: jnp.ndarray
    M: jnp.ndarray


def perfect_foresight_steady_state(disc_fac, cap_share, depr_fac,
                                   lbr_ind=1.0) -> SteadyState:
    """The representative-agent steady state used to seed the simulation and
    center the M grid (``Aiyagari_Support.py:1606-1615``): R = 1/beta pins
    down K/L."""
    k_to_l = ((1.0 / disc_fac - (1.0 - depr_fac)) / cap_share) ** (
        1.0 / (cap_share - 1.0))
    K = k_to_l * lbr_ind
    W = wage_rate(k_to_l, cap_share)
    R = interest_factor(k_to_l, cap_share, depr_fac)
    M = K * R + W * lbr_ind
    return SteadyState(k_to_l=jnp.asarray(k_to_l), K=jnp.asarray(K),
                       W=jnp.asarray(W), R=jnp.asarray(R), M=jnp.asarray(M))
