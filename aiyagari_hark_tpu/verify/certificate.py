"""A posteriori equilibrium certification (DESIGN §9).

The solvers certify their own exits (``solver_health``: tolerance met,
budget exhausted, non-finite), but a *silent* failure — a bit-flipped
packed row, a device computing subtly wrong lanes, a torn store entry that
still parses — produces finite, plausible numbers that no exit code can
flag.  The theory supplies cheap independent oracles: an Aiyagari
equilibrium is fully characterized by Euler-equation optimality of the
policy and stationarity/market-clearing of the distribution (Ma–
Stachurski–Toda arXiv:1812.01320; Cao–Luo–Nie arXiv:1905.13045), so every
solution can be certified AFTER the fact by a code path that did not
produce it.

``certify_equilibrium`` recomputes, via independent straightforward
evaluations (never the EGM inverse update, never the lean in-loop carry):

* **euler** — the relative Euler-equation residual of the consumption
  policy at OFF-GRID midpoints of the endogenous knots (EGM satisfies the
  Euler equation at the knots by construction, so the knots alone cannot
  catch a policy that is wrong between them), masked to the
  constraint-slack region where the equation holds with equality;
* **stationarity / mass** — ``‖Γ′μ − μ‖∞`` under a fresh push-forward of
  the transition implied by the policy, and ``|Σμ − 1|`` mass
  conservation;
* **market_clearing** — ``|K_supply(r*) − K_demand(r*)| / K`` with the
  supply re-evaluated through the FULL (not lean) path
  (policy solve at r*, stationary distribution, aggregation);
* **capital** — the solution's reported capital against the re-evaluated
  supply (the lean solver reports supply at the last bisection midpoint,
  within one bracket width of A(r*) — corruption of the capital field
  shows up here);
* **shape / lorenz** — structural invariants: strictly increasing
  endogenous knots, positive nondecreasing consumption, nonnegative
  masses with a monotone cumulative-wealth (Lorenz) curve;
* **recompute** — the certifier's own inner solves' ``solver_health``
  exits (a certificate built on a diverged recomputation certifies
  nothing).

Each check yields a residual compared against a typed threshold ladder
(``CertThresholds`` — defaults scale with the solver tolerances the same
way ``equilibrium._bisection_setup`` scales them with dtype), producing a
severity-ordered verdict per check and overall:

    CERTIFIED (0) < MARGINAL (1) < FAILED (2)

combined by ``max`` exactly like ``solver_health.combine_status``.  The
verdicts thread into ``SweepResult.cert_level``, ``StoredSolution`` /
``ServedResult`` (``serve``), and the ``--integrity-smoke`` bench record.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from functools import lru_cache
from typing import NamedTuple, Optional

import numpy as np

from ..solver_health import is_failure
from ..utils.fingerprint import hashable_kwargs

# Severity-ordered certificate levels; combine with max().
CERTIFIED = 0
MARGINAL = 1
FAILED = 2
# Store sentinel: no certificate was ever computed for this entry.
UNCERTIFIED = -1

CERT_LEVEL_NAMES = ("CERTIFIED", "MARGINAL", "FAILED")

# Fixed residual-vector layout shared by the jitted recompute certifier,
# the eager object certifier, the bench record (integrity_max_<check>),
# and the tests.  Order is load-bearing — never reorder, only append.
CERT_CHECKS = ("euler", "stationarity", "mass", "market_clearing",
               "capital", "shape", "lorenz", "recompute")


def cert_level_name(level: int) -> str:
    level = int(level)
    if level == UNCERTIFIED:
        return "UNCERTIFIED"
    if 0 <= level < len(CERT_LEVEL_NAMES):
        return CERT_LEVEL_NAMES[level]
    return f"UNKNOWN({level})"


class CheckResult(NamedTuple):
    """One certification check's outcome."""

    name: str
    residual: float
    threshold: float      # CERTIFIED bound; MARGINAL up to marginal_factor x
    level: int

    def __repr__(self) -> str:
        return (f"CheckResult({self.name}: {cert_level_name(self.level)}, "
                f"residual={self.residual:.3e} vs tol={self.threshold:.3e})")


class Certificate(NamedTuple):
    """Severity-ordered a posteriori certificate of one equilibrium."""

    level: int                 # worst check level (max)
    checks: tuple              # of CheckResult, CERT_CHECKS order

    @property
    def certified(self) -> bool:
        return self.level == CERTIFIED

    @property
    def failed(self) -> bool:
        return self.level >= FAILED

    def residuals(self) -> dict:
        return {c.name: c.residual for c in self.checks}

    def worst(self) -> CheckResult:
        return max(self.checks, key=lambda c: (c.level,
                                               c.residual / max(c.threshold,
                                                                1e-300)))

    def summary(self) -> str:
        w = self.worst()
        return (f"{cert_level_name(self.level)}"
                + ("" if self.level == CERTIFIED
                   else f" (worst: {w.name} residual {w.residual:.3e} "
                        f"vs tol {w.threshold:.3e})"))


@dataclass(frozen=True)
class CertThresholds:
    """CERTIFIED bounds per check; a residual within ``marginal_factor``
    of its bound certifies MARGINAL, beyond that FAILED.

    Defaults are calibrated for the float64 default solver tolerances
    (r_tol=1e-10, egm_tol=1e-6, dist_tol=1e-11) with ~an order of
    magnitude of headroom over the measured committed-golden residuals;
    ``for_solver`` rescales the tolerance-coupled bounds for other
    configurations (a bisection root is only located to its bracket
    width, so the market-clearing bound MUST widen with r_tol — the
    certificate certifies the solution against *its own* contract, not
    a tighter one it never promised).

    * ``euler`` is dominated by piecewise-linear interpolation curvature
      error between knots, O(h²) in the local grid spacing — solver- and
      r_tol-independent.
    * ``stationarity`` floors at the distribution fixed point's own exit
      (≤ a small multiple of dist_tol; the stall window can leave it a
      few x above).
    * ``mass`` is accumulation noise: D·eps-scale.
    * ``market_clearing``/``capital`` scale with r_tol times the excess
      map's relative slope (O(10–100) on the Table II lattice), floored
      at the inner-solver noise the supply evaluation itself carries.
    * ``shape``/``lorenz`` are structural: any true violation fails, the
      tiny nonzero bounds only absorb cumsum rounding.
    * ``recompute`` maps the certifier's own inner ``solver_health``
      exits: CONVERGED certifies, STALLED is marginal, failures fail.
    """

    euler: float = 0.08
    stationarity: float = 1e-8
    mass: float = 5e-10
    market_clearing: float = 1e-2
    capital: float = 1e-2
    shape: float = 0.0
    lorenz: float = 1e-12
    recompute: float = 0.5
    marginal_factor: float = 8.0

    @classmethod
    def for_solver(cls, dtype=None, r_tol: Optional[float] = None,
                   egm_tol: Optional[float] = None,
                   dist_tol: Optional[float] = None,
                   precision: str = "reference",
                   grid="reference",
                   **overrides) -> "CertThresholds":
        """Thresholds matched to a solver configuration's own tolerance
        contract — the same dtype-aware defaults as
        ``equilibrium._bisection_setup``.

        ``precision``: a non-reference ladder policy (DESIGN §5) legally
        wobbles the root by its cheap-phase noise (the descent's f32
        excess evaluations steer the early bracket; measured ~4e-6 in r
        on the committed-golden config, ~1.4e-2 in relative excess), so
        the market-clearing/capital bounds widen 4x — certifying a mixed
        solution against reference-noise bounds would reject its own
        documented contract, not corruption.

        ``grid``: a compact grid policy (DESIGN §5b) crosses the tail on
        ONE analytic segment, so the off-grid Euler midpoint check — the
        compaction's designated referee — now probes the middle of that
        long segment, where the residual is the asymptotic-linearity
        error itself rather than local interpolation curvature; the
        euler bound widens 4x to grade that contract (measured ~2-3x
        the reference residual on the committed-golden config), and the
        market/capital bounds widen 2x for the documented sub-0.1bp
        root drift the truncated histogram legally carries.  Everything
        else — stationarity, mass, shape, Lorenz — holds at full
        reference tightness: the compact solve is certified against the
        same structural invariants."""
        f64 = np.dtype(dtype if dtype is not None else np.float64) \
            == np.float64
        if r_tol is None:
            r_tol = 1e-10 if f64 else 1e-6
        if dist_tol is None:
            dist_tol = 1e-11 if f64 else 1e-8
        if egm_tol is None:
            egm_tol = 1e-6 if f64 else 1e-5
        eps = float(np.finfo(np.float64 if f64 else np.float32).eps)
        # Two noise sources bound how well an honest root can clear the
        # market: (1) the bracket — r* is located to r_tol and the excess
        # map's measured relative slope reaches ~600 on the Table II
        # lattice (σ=1 cells); (2) the inner solves — the EGM fixed point
        # converges to egm_tol per-step, i.e. ~egm_tol/(1-β) true policy
        # error, which the slow-mixing stationary distribution amplifies
        # by ~1/(1-λ_mix) into the aggregate (measured: up to ~1.7e-3
        # relative at egm_tol=1e-6 on the committed-golden config).  The
        # bound takes the worse of the two with ~5x headroom; corruption
        # below it is the checksum chain's and the bitwise SDC recheck's
        # job — the certificate is the last line for SEMANTIC error.
        market = max(1e4 * float(egm_tol), 1500.0 * float(r_tol))
        from ..utils.config import resolve_grid, resolve_precision

        if resolve_precision(precision).two_phase:
            market *= 4.0
        euler = max(0.08, 20.0 * float(egm_tol))
        if resolve_grid(grid).compact:
            euler *= 4.0
            market *= 2.0
        return cls(
            euler=euler,
            stationarity=max(300.0 * float(dist_tol), 200.0 * eps),
            mass=max(5e-10 if f64 else 5e-5, 2e5 * eps),
            market_clearing=market,
            capital=market,
        ).replace(**overrides)

    def replace(self, **kw) -> "CertThresholds":
        return replace(self, **kw)

    def bound(self, name: str) -> float:
        return float(getattr(self, name))

    def grade(self, name: str, residual: float) -> CheckResult:
        """One residual -> one severity-graded CheckResult.  A non-finite
        residual (the recomputation itself produced garbage) fails.

        The ``recompute`` check carries a raw ``solver_health`` status
        code, not a continuous residual, so it gets its OWN band —
        CONVERGED certifies, STALLED is marginal, MAX_ITER/NONFINITE
        fail — instead of the shared ``marginal_factor``, which would
        grade a diverged recomputation (status 2-3) MARGINAL and let it
        through the certify-before-cache gate."""
        tol = self.bound(name)
        r = float(residual)
        if name == "recompute":
            marginal_bound = 1.5      # STALLED (1) and nothing above
        else:
            marginal_bound = self.marginal_factor * tol
        if not np.isfinite(r):
            level = FAILED
        elif r <= tol:
            level = CERTIFIED
        elif r <= marginal_bound:
            level = MARGINAL
        else:
            level = FAILED
        return CheckResult(name=name, residual=r, threshold=tol, level=level)

    def certificate(self, residuals) -> Certificate:
        """Grade a CERT_CHECKS-ordered residual vector."""
        checks = tuple(self.grade(name, r)
                       for name, r in zip(CERT_CHECKS, residuals))
        return Certificate(level=max(c.level for c in checks),
                           checks=checks)


# ---------------------------------------------------------------------------
# The independent residual evaluations (jit/vmap-safe; jax imported lazily
# so importing the certificate vocabulary costs nothing).
# ---------------------------------------------------------------------------

def euler_residual_midpoints(policy, R, W, model, disc_fac, crra):
    """Max relative Euler-equation residual of ``policy`` at the OFF-GRID
    midpoints of its endogenous knots, over the constraint-slack region.

    Straightforward forward evaluation — interpolate consumption at the
    midpoint, push savings through the budget, take the expectation of
    marginal utility with a plain einsum, invert the FOC — never the EGM
    update, so a policy that merely *looks* like an EGM output cannot
    satisfy it by construction."""
    import jax.numpy as jnp

    from ..models.household import consumption_at
    from ..ops.utility import inverse_marginal_utility, marginal_utility

    m_k, c_k = policy.m_knots, policy.c_knots            # [N, K]
    n = m_k.shape[0]
    # midpoints of the ENDOGENOUS segments (skip the prepended
    # borrowing-constraint segment [0, 1], where c = m - b exactly)
    m_mid = 0.5 * (m_k[:, 1:-1] + m_k[:, 2:])            # [N, J]
    c_mid = consumption_at(policy, m_mid)                # [N, J]
    a_end = m_mid - c_mid                                # savings
    m_next = (R * a_end[:, :, None]
              + W * model.labor_levels[None, None, :])   # [N, J, N']
    mq = jnp.moveaxis(m_next, 2, 0).reshape(n, -1)       # [N', N*J]
    vp = marginal_utility(consumption_at(policy, mq), crra)
    vp = vp.reshape(n, n, m_mid.shape[1])                # [N'(k), N, J]
    evp = jnp.einsum("nk,knj->nj", model.transition, vp)
    c_star = inverse_marginal_utility(disc_fac * R * evp, crra)
    # equality only where the constraint is slack at the midpoint AND at
    # the Euler-implied optimum (binding points satisfy an inequality)
    floor = model.a_grid[0]
    slack = (a_end > floor) & ((m_mid - c_star) > floor)
    tiny = jnp.asarray(np.finfo(np.float64).tiny, dtype=c_mid.dtype)
    rel = jnp.abs(c_mid - c_star) / jnp.maximum(c_mid, tiny)
    return jnp.max(jnp.where(slack, rel, 0.0))


def stationarity_residuals(policy, dist, R, W, model):
    """(‖Γ′μ − μ‖∞, |Σμ − 1|): one fresh scatter push-forward of the
    transition implied by ``policy`` applied to ``dist`` — independent of
    whichever distribution engine (dense/pallas/LU) produced ``dist``."""
    import jax.numpy as jnp

    from ..models.household import _push_forward, wealth_transition

    trans = wealth_transition(policy, R, W, model)
    pushed = _push_forward(dist, trans, model.transition)
    return (jnp.max(jnp.abs(pushed - dist)),
            jnp.abs(jnp.sum(dist) - 1.0))


def shape_residual(policy):
    """Structural violation magnitude of a consumption policy: endogenous
    knots must strictly increase, consumption must be positive and
    nondecreasing in resources.  0.0 for a healthy policy."""
    import jax.numpy as jnp

    zero = jnp.zeros((), dtype=policy.c_knots.dtype)
    dm = jnp.diff(policy.m_knots, axis=1)
    dc = jnp.diff(policy.c_knots, axis=1)
    return (jnp.maximum(jnp.max(-dm), zero)
            + jnp.maximum(jnp.max(-dc), zero)
            + jnp.maximum(jnp.max(-policy.c_knots), zero))


def lorenz_residual(dist, model):
    """Lorenz-curve monotonicity of the stationary wealth histogram:
    nonnegative masses and a nondecreasing cumulative-wealth curve over
    the nonnegative-wealth support, as a relative violation magnitude."""
    import jax.numpy as jnp

    m = jnp.sum(dist, axis=1) if dist.ndim == 2 else dist
    zero = jnp.zeros((), dtype=m.dtype)
    neg_mass = jnp.maximum(jnp.max(-m), zero)
    w = jnp.clip(m, 0.0, None) * model.dist_grid
    cw = jnp.cumsum(w)
    # only the nonnegative-wealth region is Lorenz-monotone by theory (a
    # negative borrowing limit legitimately decrements the running sum)
    ok_region = model.dist_grid[1:] >= 0
    dec = jnp.maximum(jnp.max(jnp.where(ok_region, -jnp.diff(cw), 0.0)),
                      zero)
    tiny = jnp.asarray(np.finfo(np.float64).tiny, dtype=m.dtype)
    return neg_mass + dec / jnp.maximum(cw[-1], tiny)


# Kwarg vocabulary split (mirrors ``equilibrium._solve_cell``): what the
# certifier NEEDS (model structure, prices, inner tolerances) vs the
# production solver's METHOD knobs (dist_method, egm_method, root_method,
# accel_every, bracket_pad, max_bisect, precision, warm seeds, fault
# hooks), which the certifier deliberately ignores — independence means
# certifying with its own straightforward evaluation paths no matter how
# the solution was produced.
_MODEL_KEYS = ("labor_states", "labor_bound", "a_min", "a_max", "a_count",
               "a_nest_fac", "dist_count", "borrow_limit", "grid")
_PRICE_DEFAULTS = {"disc_fac": 0.96, "cap_share": 0.36, "depr_fac": 0.08,
                   "prod": 1.0}


def _split_kwargs(model_kwargs: dict):
    build = {k: model_kwargs[k] for k in _MODEL_KEYS if k in model_kwargs}
    price = {k: float(model_kwargs.get(k, v))
             for k, v in _PRICE_DEFAULTS.items()}
    f64 = True
    dt = model_kwargs.get("__dtype__")
    if dt is not None:
        f64 = np.dtype(dt) == np.float64
    egm_tol = model_kwargs.get("egm_tol") or (1e-6 if f64 else 1e-5)
    dist_tol = model_kwargs.get("dist_tol") or (1e-11 if f64 else 1e-8)
    return build, price, float(egm_tol), float(dist_tol)


def _cert_dist_method(build: dict) -> str:
    """The certifier's distribution engine: the DIRECT linear solve
    (``household._stationary_solve`` — non-iterative, uniform cost) when
    the bordered matrix is small enough to factor comfortably, the
    scatter power iteration beyond that."""
    d = int(build.get("dist_count", 500))
    n = int(build.get("labor_states", 7))
    return "solve" if d * n <= 4096 else "scatter"


def _recompute_residuals(crra, rho, sd, r_star, capital, dtype,
                         model_kwargs: dict):
    """The re-solve certification body (jit/vmap-safe): rebuild the model,
    re-evaluate the FULL supply path at ``r_star`` (EGM policy solve +
    direct stationary solve — NOT the lean in-loop carry), and return the
    CERT_CHECKS residual vector."""
    import jax.numpy as jnp

    from ..models import firm
    from ..models.household import (
        aggregate_capital,
        aggregate_labor,
        build_simple_model,
        solve_household,
        stationary_wealth,
    )
    from ..solver_health import combine_status

    build, price, egm_tol, dist_tol = _split_kwargs(
        {**model_kwargs, "__dtype__": dtype})
    model = build_simple_model(labor_ar=rho, labor_sd=sd, dtype=dtype,
                               **build)
    k_to_l = firm.k_to_l_from_r(r_star, price["cap_share"],
                                price["depr_fac"], price["prod"])
    W = firm.wage_rate(k_to_l, price["cap_share"], price["prod"])
    R = 1.0 + r_star
    policy, _, _, egm_status = solve_household(
        R, W, model, price["disc_fac"], crra, tol=egm_tol, method="xla",
        precision="reference", grid=build.get("grid", "reference"))
    dist, _, _, dist_status = stationary_wealth(
        policy, R, W, model, tol=dist_tol,
        method=_cert_dist_method(build), precision="reference")

    supply = aggregate_capital(dist, model)
    labor = aggregate_labor(model)
    demand = k_to_l * labor
    tiny = jnp.asarray(np.finfo(np.float64).tiny, dtype=supply.dtype)
    denom = jnp.maximum(jnp.abs(supply), tiny)
    station, mass = stationarity_residuals(policy, dist, R, W, model)
    resids = jnp.stack([
        euler_residual_midpoints(policy, R, W, model, price["disc_fac"],
                                 crra),
        station,
        mass,
        jnp.abs(supply - demand) / denom,
        jnp.abs(capital - supply) / denom,
        shape_residual(policy),
        lorenz_residual(dist, model),
        combine_status(egm_status, dist_status).astype(supply.dtype),
    ])
    return resids.astype(jnp.float64) if resids.dtype != jnp.float64 \
        else resids


@lru_cache(maxsize=None)
def _recompute_certifier(dtype, kwargs_items=()):
    """Jitted vmapped re-solve certifier, memoized per solver group like
    ``parallel.sweep._batched_solver`` (same cache discipline: ``dtype``
    must be canonical).  Maps ``(crra, rho, sd, r_star, capital) ->
    [len(CERT_CHECKS)]`` float64 residual rows."""
    import jax

    model_kwargs = dict(kwargs_items)

    def one(crra, rho, sd, r_star, capital):
        return _recompute_residuals(crra, rho, sd, r_star, capital,
                                    dtype, model_kwargs)

    return jax.jit(jax.vmap(one))


def _thresholds_from_kwargs(thresholds, dtype, model_kwargs: dict):
    if thresholds is not None:
        return thresholds
    return CertThresholds.for_solver(
        dtype=dtype, r_tol=model_kwargs.get("r_tol"),
        egm_tol=model_kwargs.get("egm_tol"),
        dist_tol=model_kwargs.get("dist_tol"),
        precision=model_kwargs.get("precision", "reference"),
        grid=model_kwargs.get("grid", "reference"))


def certify_packed_rows(rows, cells, dtype, kwargs_items,
                        thresholds: Optional[CertThresholds] = None,
                        schema=None):
    """Certify a block of packed device rows for the given (σ, ρ, sd)
    cells — the sweep/store/serve form.  One vmapped launch for the whole
    block.  Returns a list of ``Certificate``; a row whose solver status
    is already a failure certifies FAILED trivially (it is loudly
    NaN-masked upstream — the certificate records the verdict without
    wasting a recomputation).

    ``schema`` is the row layout (``scenarios.RowSchema``; ISSUE 9
    satellite — the status/root/capital columns are read by NAME, never
    by hard-coded index).  None resolves the Aiyagari layout, whose
    solver family this recompute certifier belongs to."""
    from ..obs.runtime import active_span

    if schema is None:
        from ..scenarios.aiyagari import AIYAGARI_SCHEMA as schema
    status_col = schema.idx(schema.status)
    root_col = schema.idx(schema.root)
    cap_col = (schema.idx("capital") if schema.has("capital")
               else root_col)
    rows = np.asarray(rows, dtype=np.float64)
    cells = np.asarray(cells, dtype=np.float64)
    model_kwargs = dict(kwargs_items)
    thr = _thresholds_from_kwargs(thresholds, dtype, model_kwargs)
    healthy = ~np.asarray([is_failure(int(np.rint(r[status_col])))
                           for r in rows])
    out: list = [None] * len(rows)
    if healthy.any():
        import jax.numpy as jnp

        idx = np.nonzero(healthy)[0]
        fn = _recompute_certifier(dtype, kwargs_items)
        # certification span on the ACTIVE obs scope (ISSUE 7): the
        # sweep/serve callers own the cell-attributed verdict events;
        # this span times the one vmapped recompute launch itself
        with active_span("verify/certify_rows", rows=int(len(idx))):
            resids = np.asarray(fn(
                jnp.asarray(cells[idx, 0], dtype=dtype),
                jnp.asarray(cells[idx, 1], dtype=dtype),
                jnp.asarray(cells[idx, 2], dtype=dtype),
                jnp.asarray(rows[idx, root_col], dtype=dtype),
                jnp.asarray(rows[idx, cap_col], dtype=dtype)),
                dtype=np.float64)
        for j, i in enumerate(idx):
            out[int(i)] = thr.certificate(resids[j])
    for i in np.nonzero(~healthy)[0]:
        status = int(np.rint(rows[i][status_col]))
        # the full CERT_CHECKS-ordered vector (every consumer zips
        # against it): the unevaluated checks carry NaN residuals —
        # "could not certify" grades FAILED, never CERTIFIED-by-default
        resids = np.full(len(CERT_CHECKS), np.nan)
        resids[CERT_CHECKS.index("recompute")] = float(status)
        out[int(i)] = thr.certificate(resids)
    return out


def certify_equilibrium(result, crra=None, labor_ar=None, labor_sd=0.2,
                        thresholds: Optional[CertThresholds] = None,
                        dtype=None, **model_kwargs) -> Certificate:
    """A posteriori certificate of one solved equilibrium (module
    docstring for the check battery).

    ``result`` may be:

    * a full ``models.equilibrium.EquilibriumResult`` — its OWN policy
      and distribution are certified directly (the strongest form: the
      served artifacts themselves are checked, so a perturbed policy or
      distribution cannot hide behind a clean recomputation);
    * a ``LeanEquilibrium`` / ``serve.ServedResult`` / packed-row-like
      object with ``r_star`` and ``capital`` — scalars only, so the
      policy and distribution are re-derived at ``r_star`` through the
      full supply path and the residuals certify the (r*, K) pair;
    * a bare float ``r_star``.

    ``crra``/``labor_ar``/``labor_sd`` locate the calibration cell;
    ``model_kwargs`` is the same vocabulary as
    ``equilibrium.solve_calibration`` (grid sizes, tolerances, prices) —
    method knobs are deliberately ignored (independence).  ``thresholds``
    defaults to ``CertThresholds.for_solver`` of this configuration.
    """
    from ..obs.runtime import active_span, emit_event
    from ..parallel.sweep import _canonical_dtype

    if crra is None or labor_ar is None:
        raise TypeError("certify_equilibrium needs the calibration cell: "
                        "pass crra= and labor_ar= (and labor_sd=)")
    dtype = _canonical_dtype(dtype)
    thr = _thresholds_from_kwargs(thresholds, dtype, model_kwargs)
    policy = getattr(result, "policy", None)
    distribution = getattr(result, "distribution", None)
    r_star = result if np.isscalar(result) else result.r_star
    capital = (None if np.isscalar(result)
               else getattr(result, "capital", None))

    def _graded(cert: Certificate) -> Certificate:
        # verdict event on the active obs scope (ISSUE 7): the
        # standalone certification API journals its own failures —
        # sweep/serve batch paths attribute theirs at the call site
        if cert.failed:
            emit_event("CERT_FAILED",
                       cell=(float(crra), float(labor_ar),
                             float(labor_sd)),
                       summary=cert.summary(), where="certify")
        return cert

    if policy is not None and distribution is not None:
        with active_span("verify/certify", form="objects"):
            resids = _object_residuals(
                float(np.asarray(r_star)), policy, distribution,
                float(crra), float(labor_ar), float(labor_sd), dtype,
                model_kwargs)
        return _graded(thr.certificate(resids))

    import jax.numpy as jnp

    kwargs_items = hashable_kwargs(model_kwargs)
    fn = _recompute_certifier(dtype, kwargs_items)
    cap = r_star if capital is None else capital
    with active_span("verify/certify", form="recompute"):
        resids = np.array(fn(
            jnp.asarray([crra], dtype=dtype),
            jnp.asarray([labor_ar], dtype=dtype),
            jnp.asarray([labor_sd], dtype=dtype),
            jnp.asarray([np.asarray(r_star)], dtype=dtype),
            jnp.asarray([np.asarray(cap)], dtype=dtype)),
            dtype=np.float64)[0]
    if capital is None:
        # a bare r* has no capital claim to check: mirror the supply
        resids[CERT_CHECKS.index("capital")] = 0.0
    return _graded(thr.certificate(resids))


def _object_residuals(r_star, policy, distribution, crra, labor_ar,
                      labor_sd, dtype, model_kwargs: dict) -> np.ndarray:
    """Certify PROVIDED solution objects (policy + distribution) against
    the model directly — eager evaluation, no inner solves, so the
    ``recompute`` check is trivially clean."""
    import jax.numpy as jnp

    from ..models import firm
    from ..models.household import (
        aggregate_capital,
        aggregate_labor,
        build_simple_model,
    )

    build, price, _, _ = _split_kwargs({**model_kwargs, "__dtype__": dtype})
    model = build_simple_model(labor_ar=labor_ar, labor_sd=labor_sd,
                               dtype=dtype, **build)
    k_to_l = firm.k_to_l_from_r(r_star, price["cap_share"],
                                price["depr_fac"], price["prod"])
    W = firm.wage_rate(k_to_l, price["cap_share"], price["prod"])
    R = 1.0 + r_star
    supply = aggregate_capital(distribution, model)
    demand = k_to_l * aggregate_labor(model)
    denom = max(abs(float(supply)), np.finfo(np.float64).tiny)
    station, mass = stationarity_residuals(policy, distribution, R, W,
                                           model)
    return np.asarray([
        float(euler_residual_midpoints(policy, R, W, model,
                                       price["disc_fac"], crra)),
        float(station),
        float(mass),
        abs(float(supply) - float(demand)) / denom,
        0.0,   # supply IS aggregate_capital(distribution): no second claim
        float(shape_residual(policy)),
        float(lorenz_residual(distribution, model)),
        0.0,
    ], dtype=np.float64)
