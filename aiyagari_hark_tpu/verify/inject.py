"""Deterministic corruption injection (DESIGN §9) — the integrity layer's
analogue of ``solver_health.inject_fault`` (numeric faults) and
``resilience.TransientInjector`` (process faults): every silent-corruption
detection path must be exercisable on CPU in tier-1, not waited for.

Nothing in production calls these.  Each injector corrupts exactly one
artifact deterministically, so a test (or ``bench.py --integrity-smoke``)
can assert injected == detected counts:

* ``flip_row_bit`` / ``perturb_row`` — in-memory packed-row corruption
  (the SDC model: a device or DMA flips a mantissa bit post-solve);
* ``corrupt_ledger_row`` — rewrite one solved row's bytes inside a saved
  resume ledger WITHOUT updating its solve-time checksum (a bit flip
  between record and flush, or rot at rest) — resume must quarantine it;
* ``corrupt_store_entry`` — truncate / zero / perturb one disk-tier
  solution-store npz: truncation exercises the unreadable-file path,
  perturbation the parses-fine-wrong-bytes checksum path;
* ``perturbed_policy`` — an off-by-one grid shift or small lane noise on
  a consumption policy: finite, monotone, plausible — exactly what only
  a posteriori certification can catch.
"""

from __future__ import annotations

import os

import numpy as np


def flip_row_bit(row, field: int = 0, bit: int = 20) -> np.ndarray:
    """One packed row with mantissa ``bit`` of ``row[field]`` flipped
    (float64 bit-cast) — the canonical single-event-upset model."""
    row = np.array(row, dtype=np.float64)
    bits = row.view(np.uint64)
    bits[field] ^= np.uint64(1) << np.uint64(bit)
    return row


def perturb_row(row, field: int = 0, amplitude: float = 1e-6) -> np.ndarray:
    """One packed row with ``amplitude`` added to ``row[field]`` — the
    subtly-wrong-lane model (finite, plausible, off)."""
    row = np.array(row, dtype=np.float64)
    row[field] += amplitude
    return row


def _rewrite_npz_leaf(path: str, leaf_index: int, mutate) -> None:
    """Rewrite one ``save_pytree`` leaf in place, preserving every other
    leaf and the treedef BYTE-FOR-BYTE — the file still parses and still
    claims its solve-time checksums, which is precisely the corruption
    the checksum boundary exists to catch."""
    with np.load(path) as data:   # integrity-ok: the corruption injector
        arrays = {k: np.array(data[k]) for k in data.files}
    key = f"leaf_{leaf_index:06d}"
    if key not in arrays:
        raise KeyError(f"{path} has no leaf {leaf_index}")
    arrays[key] = mutate(arrays[key])
    with open(path, "wb") as f:   # atomic-ok: deliberate corruption injector
        np.savez(f, **arrays)


def corrupt_ledger_row(path: str, cell: int, field: int = 0,
                       bit: int = 20) -> None:
    """Flip one bit of solved cell ``cell``'s packed row inside a saved
    sweep resume ledger, leaving its recorded checksum untouched.
    ``LedgerState.resume`` must detect the mismatch and quarantine the
    cell (recompute), never reassemble the corrupt bits."""
    from ..utils.resilience import SweepLedger

    def mutate(packed):
        packed = np.array(packed)
        packed[cell] = flip_row_bit(packed[cell], field=field, bit=bit)
        return packed

    _rewrite_npz_leaf(path, SweepLedger._fields.index("packed"), mutate)


def corrupt_store_entry(disk_path: str, key: int = None,
                        mode: str = "perturb",
                        amplitude: float = 1e-3) -> str:
    """Corrupt one disk-tier ``SolutionStore`` entry; returns the path.

    ``mode="truncate"`` halves the file (unreadable npz — the
    ``CORRUPT_NPZ_ERRORS`` path), ``"zero"`` zeroes it, ``"perturb"``
    adds ``amplitude`` to the stored row's r* while keeping the file
    well-formed and its checksum field untouched (the silent-corruption
    path only checksum verification can catch).  ``key=None`` corrupts
    the lexicographically first entry."""
    from ..serve.store import StoredSolution

    if key is None:
        names = sorted(n for n in os.listdir(disk_path)
                       if n.startswith("sol_") and n.endswith(".npz"))
        if not names:
            raise FileNotFoundError(f"no store entries under {disk_path}")
        path = os.path.join(disk_path, names[0])
    else:
        path = os.path.join(
            disk_path, f"sol_{int(key) & 0xFFFFFFFFFFFFFFFF:016x}.npz")
    if mode == "truncate":
        raw = open(path, "rb").read()
        with open(path, "wb") as f:   # atomic-ok: corruption injector
            f.write(raw[:max(1, len(raw) // 2)])
    elif mode == "zero":
        size = os.path.getsize(path)
        with open(path, "wb") as f:   # atomic-ok: corruption injector
            f.write(b"\x00" * size)
    elif mode == "perturb":
        _rewrite_npz_leaf(
            path, StoredSolution._fields.index("packed"),
            lambda row: perturb_row(row, field=0, amplitude=amplitude))
    else:
        raise ValueError(f"corrupt_store_entry mode must be 'truncate', "
                         f"'zero' or 'perturb', got {mode!r}")
    return path


def perturbed_policy(policy, mode: str = "noise",
                     amplitude: float = 1e-6, seed: int = 0):
    """A deliberately wrong consumption policy that every structural
    check passes — the certification oracle's job:

    * ``mode="shift"``: off-by-one grid shift — each endogenous knot
      takes its RIGHT neighbor's consumption (over-consuming by one grid
      step; still monotone, still positive);
    * ``mode="noise"``: deterministic ``amplitude`` lane noise on the
      consumption knots (small enough to keep monotonicity, large enough
      that the stationarity oracle sees a different lottery).
    """
    import jax.numpy as jnp

    c = np.asarray(policy.c_knots, dtype=np.float64)
    if mode == "shift":
        shifted = np.concatenate([c[:, :1], c[:, 2:], c[:, -1:]], axis=1)
    elif mode == "noise":
        rng = np.random.default_rng(seed)
        shifted = c + amplitude * rng.standard_normal(c.shape)
    else:
        raise ValueError(f"perturbed_policy mode must be 'shift' or "
                         f"'noise', got {mode!r}")
    return policy._replace(
        c_knots=jnp.asarray(shifted, dtype=policy.c_knots.dtype))
