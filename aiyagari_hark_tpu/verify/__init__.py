"""Solution-integrity subsystem (DESIGN §9): a posteriori certification,
the checksummed artifact chain, and silent-corruption defense.

Three pillars:

* **Certification** (``certificate``): ``certify_equilibrium`` re-checks
  a solved equilibrium through independent straightforward evaluations —
  Euler residuals at off-grid midpoints, stationarity and mass of the
  wealth distribution, full-path market clearing, shape and Lorenz
  invariants — returning a severity-ordered ``Certificate``
  (CERTIFIED < MARGINAL < FAILED).
* **Checksummed artifact chain** (``utils.fingerprint``
  ``packed_row_checksum``/``content_checksum``/``IntegrityError``):
  content checksums computed at solve time and verified at every
  boundary a solution later crosses — resume-ledger restore, scheduler
  sidecar load, ``SolutionStore`` memory/disk tiers, serve responses —
  so corruption surfaces as a typed error that degrades (recompute /
  evict / quarantine) instead of propagating.
* **SDC spot-checks + injection** (``parallel.sweep``
  ``SweepConfig(recheck_fraction=)``; ``inject``): deterministic
  re-solves of a fingerprint-sampled cell subset in permuted lane
  positions, compared bitwise (the packing-independence contract), plus
  the deterministic corruption injectors that exercise every detection
  path in tier-1.
"""

from ..utils.fingerprint import (  # noqa: F401
    IntegrityError,
    content_checksum,
    packed_row_checksum,
    packed_row_checksums,
    verify_packed_row,
)
from .certificate import (  # noqa: F401
    CERT_CHECKS,
    CERT_LEVEL_NAMES,
    CERTIFIED,
    FAILED,
    MARGINAL,
    UNCERTIFIED,
    Certificate,
    CertThresholds,
    CheckResult,
    cert_level_name,
    certify_equilibrium,
    certify_packed_rows,
    euler_residual_midpoints,
    lorenz_residual,
    shape_residual,
    stationarity_residuals,
)
from .inject import (  # noqa: F401
    corrupt_ledger_row,
    corrupt_store_entry,
    flip_row_bit,
    perturb_row,
    perturbed_policy,
)
