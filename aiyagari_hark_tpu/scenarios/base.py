"""The Scenario abstraction: pluggable model families for the whole run
stack (ISSUE 9, DESIGN §12).

Everything built in PRs 1-8 — quarantine, the balanced scheduler, resume
ledgers, the SolutionStore/serving engine, the precision ladder,
certification, obs, and overload control — was hard-wired to the Aiyagari
cell solver.  A ``Scenario`` bundles what that infrastructure actually
needs from a model family, so Huggett, Epstein-Zin, lifecycle, and future
high-dimensional families (PAPERS 2202.06555) ride the same machinery:

* a **packed-row batched solver** — a jitted vmapped ``(cells...) ->
  [B, W]`` program packing every per-cell output into ONE stacked float
  row (the one-transfer-per-launch discipline of
  ``parallel.sweep._batched_solver``);
* a declarative **RowSchema** — the named row layout generalizing the
  fixed ``config.PACKED_ROW_FIELDS``, with the semantic roles (root,
  status, counters, precision phases, failure masking) the engine,
  ledger, store, and certifier read instead of hard-coded indices;
* a **CellSpace** descriptor — parameter names, the normalization scale
  nearest-neighbor donor ranking uses, and the work heuristic the PR 2
  scheduler buckets by;
* **warm-start semantics** — ``BracketWarmStart`` (verified dyadic
  bracket seeding, the Aiyagari/Huggett mode) or ``None`` (cold-only);
* a **quarantine retry ladder** (``retry_rungs``) and a
  **certification hook** (``certify_rows``) for ``verify``.

Scenario identity is part of EVERY fingerprint (sidecar, resume ledger,
store key, serve group — ``utils.fingerprint``), so a cache entry solved
under one family is structurally unaddressable from another even at
numerically identical parameters.

Layering: this module is host-side vocabulary (numpy + stdlib); concrete
scenarios import their solvers lazily inside the bundled callables so
``import aiyagari_hark_tpu.scenarios`` stays cheap.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional, Tuple

import numpy as np

from ..utils.fingerprint import config_fingerprint


class ScenarioError(ValueError):
    """Base of the scenario registry's typed errors."""


class UnknownScenarioError(ScenarioError, KeyError):
    """A scenario name is not registered.  Subclasses ``KeyError`` too so
    dict-minded callers degrade naturally, but carries the registry's
    vocabulary in the message."""

    def __init__(self, name, known):
        self.name = name
        self.known = tuple(known)
        super().__init__(
            f"unknown scenario {name!r}; registered scenarios: "
            f"{sorted(self.known)}")


class DuplicateScenarioError(ScenarioError):
    """``register`` refused to overwrite an existing scenario name —
    silently replacing a family would re-key every fingerprint that
    hashes the name while old artifacts still carry it."""


# The framework's cell spaces are (currently) 3-dimensional: every
# registered family sweeps a (param0, param1, param2) lattice and the
# shared fingerprints/stores address cells as triples.  Opening a
# genuinely high-dimensional family (ROADMAP item 3 / 2202.06555) is the
# next format change; widening this is deliberate, not accidental.
CELL_DIM = 3


@dataclass(frozen=True)
class RowSchema:
    """Declarative layout of one scenario's packed device row.

    ``fields`` generalizes ``config.PACKED_ROW_FIELDS``: the batched
    solver stacks exactly these values per cell, in order, in the compute
    float dtype (counters/status ride exactly — values ≪ 2^24).  The
    roles tell the engine, ledger, sidecar, store, and certifier WHICH
    columns to read, replacing the hard-coded indices the Aiyagari-only
    stack used:

    * ``root`` — the solved scalar warm-start seeding and donor
      nomination target (``r_star`` everywhere so far);
    * ``status`` — the ``solver_health`` code column (quarantine,
      failure masking, store refusal all key on it);
    * ``counters`` — exactly (bisect-like, egm-like, dist-like) work
      counters, in that order: the resume ledger and the scheduler
      sidecar persist these three named columns;
    * ``work`` — the counter subset summed into the scheduler's
      measured-work model;
    * ``phases`` — optional (descent, polish, escalations) triple for
      precision-ladder accounting (None = the scenario does not split
      phases; engine/metrics skip phase accounting);
    * ``mask_on_failure`` — value columns NaN-masked when a cell fails
      every quarantine retry (a failed cell must poison its own entries
      loudly, never the table silently).

    ``checksum()`` fingerprints the layout + roles: ledgers and store
    entries record it, so a stale layout refuses to resume / drops
    instead of feeding wrong-shaped rows downstream.
    """

    fields: Tuple[str, ...]
    root: str = "r_star"
    status: str = "status"
    counters: Tuple[str, str, str] = ("bisect_iters", "egm_iters",
                                      "dist_iters")
    work: Tuple[str, ...] = ("egm_iters", "dist_iters")
    phases: Optional[Tuple[str, str, str]] = None
    mask_on_failure: Tuple[str, ...] = ("r_star",)

    def __post_init__(self):
        if len(set(self.fields)) != len(self.fields):
            raise ScenarioError(f"RowSchema fields repeat: {self.fields}")
        named = ((self.root, self.status) + tuple(self.counters)
                 + tuple(self.work) + tuple(self.phases or ())
                 + tuple(self.mask_on_failure))
        missing = [n for n in named if n not in self.fields]
        if missing:
            raise ScenarioError(
                f"RowSchema roles name fields not in the layout: "
                f"{missing} (fields: {self.fields})")
        if len(self.counters) != 3:
            raise ScenarioError(
                "RowSchema.counters must be exactly (bisect-like, "
                f"egm-like, dist-like), got {self.counters}")
        # cache the layout fingerprint once: the serving hot path reads
        # it per query (store schema validation) and md5 per hit would
        # be a silly tax on the sub-ms budget
        object.__setattr__(self, "_checksum", config_fingerprint(
            "row-schema", repr(self.fields), self.root, self.status,
            repr(self.counters), repr(self.work),
            repr(self.phases), repr(self.mask_on_failure)))

    @property
    def width(self) -> int:
        return len(self.fields)

    def idx(self, name: str) -> int:
        try:
            return self.fields.index(name)
        except ValueError:
            raise ScenarioError(
                f"row field {name!r} not in schema {self.fields}") from None

    def has(self, name: str) -> bool:
        return name in self.fields

    def checksum(self) -> int:
        """Layout + role fingerprint (int64) — recorded by store entries
        so stale layouts drop loudly (cached at construction)."""
        return self._checksum


@dataclass(frozen=True)
class CellSpace:
    """The scenario's parameter lattice descriptor.

    ``names`` label the ``CELL_DIM`` cell coordinates (display/docs);
    ``scale`` normalizes per-axis distances for nearest-neighbor donor
    ranking (one rule shared by sweep seeding and the serving store —
    the ``parallel.sweep.NEIGHBOR_CELL_SCALE`` contract, per scenario);
    ``work`` maps ``[C, CELL_DIM] -> [C]`` relative predicted work (the
    PR 2 scheduler's cold-start cost model and the overload layer's
    queue weight); ``perturb_axis`` is the column benchmark reruns nudge
    (``run_sweep(perturb=)``)."""

    names: Tuple[str, ...]
    scale: Tuple[float, ...]
    work: Callable[[np.ndarray], np.ndarray]
    perturb_axis: int = 1

    def __post_init__(self):
        if len(self.names) != CELL_DIM or len(self.scale) != CELL_DIM:
            raise ScenarioError(
                f"cell spaces are {CELL_DIM}-dimensional (names="
                f"{self.names}, scale={self.scale})")
        if not 0 <= self.perturb_axis < CELL_DIM:
            raise ScenarioError(
                f"perturb_axis {self.perturb_axis} out of range")

    def normalize(self, cell) -> Tuple[float, ...]:
        """``cell`` in normalized (scale-free) coordinates — THE
        normalization rule the serving tier's neighbor machinery
        operates in (ISSUE 17): ``serve.cellindex.CellIndex`` buckets
        by these units, ``parallel.sweep.neighbor_distance`` is the L1
        norm over them, and the surrogate tier's local fit regresses on
        offsets in them.  One rule, owned here per scenario."""
        return tuple(float(c) / float(s)
                     for c, s in zip(cell, self.scale))


@dataclass(frozen=True)
class BracketWarmStart:
    """Verified-bracket warm-start semantics (the Aiyagari mode): the
    host replays the device's dyadic bisection arithmetic toward a known
    root (``parallel.sweep.dyadic_bracket``) and the solver verifies the
    seed in-program, falling back to the cold trajectory on a bad seed.

    ``host_bracket(model_kwargs, dtype) -> (lo, hi)`` must reproduce the
    compiled program's economic bracket endpoints bit-exactly;
    ``host_r_tol(model_kwargs, dtype)`` its effective tolerance;
    ``max_levels(model_kwargs)`` how deep descent may go.  ``mode`` is
    the declared semantics label ("bracket" here; a scenario without a
    ``warm`` spec is "cold-only", and one whose solver replays recorded
    seeds verbatim would declare "seed-replay")."""

    host_bracket: Callable
    host_r_tol: Callable
    max_levels: Callable
    mode: str = "bracket"


@dataclass(frozen=True)
class Scenario:
    """One registered model family — everything the sweep/serve/verify
    stack needs, with the family's own solvers behind stable callables.

    * ``batched_solver(dtype, kwargs_items, fault_mode, warm)`` returns
      the jitted vmapped packed-row program (memoize per configuration —
      the engine calls it per bucket/launch and relies on executable
      reuse; ``dtype`` arrives canonical).  ``warm`` is only requested
      when ``warm`` semantics exist; ``fault_mode`` (static) compiles in
      the deterministic fault hook or is None.
    * ``eager_row(cell, dtype, model_kwargs) -> np.ndarray [width]`` —
      one trusted serial solve for quarantine rungs (blocks until the
      row is on host).
    * ``retry_rungs(model_kwargs) -> tuple[dict, ...]`` — the bounded
      quarantine ladder, safest-last (scenario-supplied; the engine
      truncates to ``max_retries``).
    * ``prepare_kwargs(model_kwargs) -> dict`` — apply the family's
      sweep-level kwarg defaults IN PLACE (e.g. Aiyagari's backend-aware
      ``dist_method``/``egm_method``) and return the method metadata the
      result should record.
    * ``certify_rows(rows, cells, dtype, kwargs_items, thresholds)`` —
      a posteriori certification of packed rows (``verify`` vocabulary:
      a list of ``Certificate``), or None when the family has no
      certifier yet (``SweepConfig.certify`` then raises).
    """

    name: str
    schema: RowSchema
    cells: CellSpace
    batched_solver: Callable
    eager_row: Callable
    retry_rungs: Callable
    prepare_kwargs: Callable = field(default=lambda kw: {})
    warm: Optional[BracketWarmStart] = None
    certify_rows: Optional[Callable] = None

    def __post_init__(self):
        if not self.name or not isinstance(self.name, str):
            raise ScenarioError(f"scenario name must be a non-empty "
                                f"string, got {self.name!r}")

    @property
    def warm_mode(self) -> str:
        return "cold-only" if self.warm is None else self.warm.mode
