"""The Epstein-Zin scenario: recursive preferences (risk aversion
decoupled from the EIS, ``models.epstein_zin``; PAPERS "The EGM for
Epstein-Zin Preferences", 2601.04438) as a registered sweep/serve
workload.

Cells are (gamma, rho, sd): the RISK-AVERSION axis replaces CRRA as the
first coordinate (at gamma == the ``ez_rho`` kwarg the family collapses
to CRRA — the test oracle); the intertemporal-substitution parameter
rides as the static sweep kwarg ``ez_rho``.  The bisection solves COLD at
every midpoint by design (``solve_ez_equilibrium``'s determinism
rationale: a warm-started inner fixed point makes the excess map
history-dependent at the reported-residual level), so warm-start
semantics are declared **cold-only** — the serving engine's store still
gives exact hits and the sweep still buckets/quarantines/resumes; there
is simply no bracket seeding to replay.
"""

from __future__ import annotations

from functools import lru_cache
from typing import NamedTuple

import numpy as np

from .base import CellSpace, RowSchema, Scenario
from .registry import register

EZ_FIELDS = ("r_star", "capital", "labor", "bisect_iters", "egm_iters",
             "dist_iters", "status")

EZ_SCHEMA = RowSchema(
    fields=EZ_FIELDS,
    root="r_star",
    status="status",
    counters=("bisect_iters", "egm_iters", "dist_iters"),
    work=("egm_iters", "dist_iters"),
    phases=None,
    mask_on_failure=("r_star", "capital"),
)


class EZLean(NamedTuple):
    """Scalar-only Epstein-Zin equilibrium for packed sweeps."""

    r_star: object
    capital: object
    labor: object
    bisect_iters: object
    egm_iters: object
    dist_iters: object
    status: object


def solve_ez_lean(model, disc_fac, gamma, ez_rho, cap_share, depr_fac,
                  r_tol=None, max_bisect: int = 60, egm_tol=None,
                  dist_tol=None, dist_method: str = "auto",
                  accel_every: int = 32, kernel="reference",
                  fault_iter=None, fault_mode=None) -> EZLean:
    """Bracketed bisection on r with the EZ household inside, scalar
    outputs only — jit/vmap-able, with the sweep-stack contract
    (accumulated counters, combined ``solver_health`` status with a
    non-finite tripwire, deterministic fault hook).  Every midpoint
    solves COLD (see module docstring).

    ``kernel`` (ISSUE 13, DESIGN §4c): the EZ value recursion has no
    fused-kernel contract (the structural analogue of its "anchors"
    grid tail), so the policy loop runs unchanged; the DISTRIBUTION
    loop rides the kernel policy through ``stationary_wealth`` — under
    "fused" single-phase it prefers the VMEM kernel engine, and the
    quarantine rungs force "reference" like every family's."""
    import jax
    import jax.numpy as jnp

    from ..models.epstein_zin import as_household_policy, \
        solve_ez_household
    from ..models.equilibrium import _bisection_setup
    from ..models.firm import k_to_l_from_r, wage_rate
    from ..models.household import (
        aggregate_capital,
        aggregate_labor,
        stationary_wealth,
    )
    from ..solver_health import (
        CONVERGED,
        MAX_ITER,
        NONFINITE,
        combine_status,
    )

    dtype = model.a_grid.dtype
    r_tol, egm_tol, dist_tol, r_lo, r_hi = _bisection_setup(
        model, disc_fac, depr_fac, r_tol, egm_tol, dist_tol)
    labor = aggregate_labor(model)
    zi = jnp.asarray(0, jnp.int32)

    def excess_at(r):
        k_to_l = k_to_l_from_r(r, cap_share, depr_fac)
        W = wage_rate(k_to_l, cap_share)
        pol, e_it, _, e_st = solve_ez_household(
            1.0 + r, W, model, disc_fac, ez_rho, gamma, tol=egm_tol,
            accel_every=accel_every)
        dist, d_it, _, d_st = stationary_wealth(
            as_household_policy(pol), 1.0 + r, W, model, tol=dist_tol,
            method=dist_method, kernel=kernel)
        supply = aggregate_capital(dist, model)
        ex = supply - k_to_l * labor
        st = combine_status(e_st, d_st,
                            jnp.where(jnp.isfinite(ex), CONVERGED,
                                      NONFINITE))
        return ex, supply, jnp.asarray(e_it, jnp.int32), \
            jnp.asarray(d_it, jnp.int32), st

    if fault_iter is None:
        fault_iter = jnp.asarray(-1, jnp.int32)

    def cond(state):
        lo, hi, it, st = state[0], state[1], state[2], state[5]
        return ((hi - lo) > r_tol) & (it < max_bisect) & (st < NONFINITE)

    def body(state):
        lo, hi, it, e_a, d_a, st = state
        mid = 0.5 * (lo + hi)
        ex, _, e_it, d_it, st2 = excess_at(mid)
        if fault_mode is not None:
            trip = (fault_iter >= 0) & (it == fault_iter)
            ex = jnp.where(trip, jnp.asarray(jnp.nan, dtype=dtype), ex)
            st2 = combine_status(
                st2, jnp.where(trip, NONFINITE, CONVERGED))
        finite = jnp.isfinite(ex)
        take_hi = ex > 0
        lo = jnp.where(finite & ~take_hi, mid, lo)
        hi = jnp.where(finite & take_hi, mid, hi)
        return (lo, hi, it + 1, e_a + e_it, d_a + d_it,
                combine_status(st, st2))

    lo, hi, iters, e_acc, d_acc, st_acc = jax.lax.while_loop(
        cond, body, (r_lo, r_hi, zi, zi, zi,
                     jnp.asarray(CONVERGED, jnp.int32)))

    st_exit = jnp.where((hi - lo) <= r_tol, CONVERGED, MAX_ITER)
    r_star = 0.5 * (lo + hi)
    _, supply, e_it, d_it, st2 = excess_at(r_star)
    status = combine_status(st_acc, st2, st_exit)
    return EZLean(r_star=r_star, capital=supply, labor=labor,
                  bisect_iters=iters + 1, egm_iters=e_acc + e_it,
                  dist_iters=d_acc + d_it, status=status)


def solve_ez_cell(gamma, rho, sd=0.2, dtype=None, disc_fac=0.96,
                  ez_rho=2.0, cap_share=0.36, depr_fac=0.08,
                  labor_states=7, labor_bound=3.0, a_min=0.001,
                  a_max=50.0, a_count=32, a_nest_fac=2, dist_count=500,
                  grid="reference",
                  **solver_kwargs) -> EZLean:
    """Build the model for one (gamma, rho, sd) cell and run the lean EZ
    solver.  ``ez_rho`` (1/EIS) is a static sweep kwarg; gamma is the
    swept risk-aversion axis."""
    from ..models.household import build_simple_model

    # EZ has no analytic-tail contract (the recursive value's tail
    # form is not the CRRA MPC line), so compact grids take the
    # STRUCTURAL tail: thinned reference anchors close [a_hat, a_max]
    # and the solver runs unchanged on the compacted knots (DESIGN §5b)
    model = build_simple_model(
        labor_states=labor_states, labor_ar=rho, labor_sd=sd,
        labor_bound=labor_bound, a_min=a_min, a_max=a_max,
        a_count=a_count, a_nest_fac=a_nest_fac, dist_count=dist_count,
        grid=grid, grid_tail="anchors", dtype=dtype)
    return solve_ez_lean(model, disc_fac, gamma, ez_rho, cap_share,
                         depr_fac, **solver_kwargs)


@lru_cache(maxsize=None)
def batched_ez_solver(dtype, kwargs_items=(), fault_mode=None,
                      warm=False):
    """Jitted vmapped EZ cell solver (the shared-executable discipline).
    ``warm`` must be False — the scenario declares cold-only semantics
    and the engine never requests a warm executable for it."""
    import jax
    import jax.numpy as jnp

    if warm:
        raise ValueError("the epstein_zin scenario is cold-only: no warm "
                         "executable exists (Scenario.warm is None)")
    model_kwargs = dict(kwargs_items)

    def pack(res: EZLean):
        f = res.r_star.dtype
        return jnp.stack([res.r_star, res.capital, res.labor,
                          res.bisect_iters.astype(f),
                          res.egm_iters.astype(f),
                          res.dist_iters.astype(f),
                          res.status.astype(f)])

    def solve_cell(gamma, rho, sd, fault_it=None):
        extra = {}
        if fault_mode is not None:
            extra.update(fault_iter=fault_it, fault_mode=fault_mode)
        return pack(solve_ez_cell(gamma, rho, sd, dtype=dtype, **extra,
                                  **model_kwargs))

    if fault_mode is None:
        def solve_one(gamma, rho, sd):
            return solve_cell(gamma, rho, sd)
    else:
        def solve_one(gamma, rho, sd, fault_it):
            return solve_cell(gamma, rho, sd, fault_it=fault_it)

    return jax.jit(jax.vmap(solve_one))


def _eager_row(cell, dtype, model_kwargs) -> np.ndarray:
    import jax
    import jax.numpy as jnp

    from ..utils.fingerprint import hashable_kwargs

    fn = batched_ez_solver(dtype, hashable_kwargs(model_kwargs), None,
                           False)
    out = jax.block_until_ready(fn(
        jnp.asarray([cell[0]], dtype=dtype),
        jnp.asarray([cell[1]], dtype=dtype),
        jnp.asarray([cell[2]], dtype=dtype)))
    return np.asarray(out, dtype=np.float64)[0]


def _retry_rungs(model_kwargs: dict) -> tuple:
    prior = model_kwargs.get("dist_method", "auto")
    alternate = "dense" if prior in ("auto", "scatter") else "scatter"
    rungs = (
        {"dist_method": alternate},
        {"dist_method": alternate, "accel_every": 0},
        # the EZ certainty-equivalent powers overflow before the bracket
        # does; more bisection budget is the honest last rung
        {"dist_method": alternate, "accel_every": 0,
         "max_bisect": int(model_kwargs.get("max_bisect", 60)) + 20},
    )
    # grid escalation (DESIGN §5b): quarantine re-solves on the dense
    # reference grid, the one layout the goldens certify
    if model_kwargs.get("grid", "reference") != "reference":
        rungs = tuple({**r, "grid": "reference"} for r in rungs)
    # kernel escalation (ISSUE 13, DESIGN §4c): quarantine re-solves on
    # the launch-per-loop reference engines
    if model_kwargs.get("kernel", "reference") != "reference":
        rungs = tuple({**r, "kernel": "reference"} for r in rungs)
    return rungs


def _prepare_kwargs(model_kwargs: dict) -> dict:
    return {"dist_method": str(model_kwargs.get("dist_method", "auto"))}


@lru_cache(maxsize=None)
def _ez_certifier(dtype, kwargs_items=()):
    """Independent recompute certifier for EZ rows: re-solve the EZ
    household COLD at the reported rate, direct/fresh distribution, and
    grade market clearing + the capital claim + structural invariants.
    The ``euler`` slot reports 0.0 — the EZ Euler equation with its
    risk-adjustment weights has no cheap independent oracle here (the
    certifier would have to replay the producer's own update); market
    clearing, stationarity, and shape are the binding checks."""
    import jax
    import jax.numpy as jnp

    from ..models.epstein_zin import as_household_policy, \
        solve_ez_household
    from ..models.firm import k_to_l_from_r, wage_rate
    from ..models.household import (
        aggregate_capital,
        aggregate_labor,
        build_simple_model,
        stationary_wealth,
    )
    from ..solver_health import combine_status
    from ..verify.certificate import (
        _cert_dist_method,
        _split_kwargs,
        lorenz_residual,
        shape_residual,
        stationarity_residuals,
    )

    model_kwargs = dict(kwargs_items)
    ez_rho = float(model_kwargs.get("ez_rho", 2.0))

    def one(gamma, rho, sd, r_star, capital):
        build, price, egm_tol, dist_tol = _split_kwargs(
            {**model_kwargs, "__dtype__": dtype})
        model = build_simple_model(labor_ar=rho, labor_sd=sd,
                                   grid_tail="anchors", dtype=dtype,
                                   **build)
        k_to_l = k_to_l_from_r(r_star, price["cap_share"],
                               price["depr_fac"])
        W = wage_rate(k_to_l, price["cap_share"])
        R = 1.0 + r_star
        pol, _, _, e_st = solve_ez_household(
            R, W, model, price["disc_fac"], ez_rho, gamma, tol=egm_tol)
        hpol = as_household_policy(pol)
        dist, _, _, d_st = stationary_wealth(
            hpol, R, W, model, tol=dist_tol,
            method=_cert_dist_method(build), precision="reference")
        supply = aggregate_capital(dist, model)
        demand = k_to_l * aggregate_labor(model)
        tiny = jnp.asarray(np.finfo(np.float64).tiny,
                           dtype=supply.dtype)
        denom = jnp.maximum(jnp.abs(supply), tiny)
        station, mass = stationarity_residuals(hpol, dist, R, W, model)
        resids = jnp.stack([
            jnp.zeros((), dtype=supply.dtype),   # euler: no cheap oracle
            station,
            mass,
            jnp.abs(supply - demand) / denom,
            jnp.abs(capital - supply) / denom,
            shape_residual(hpol),
            lorenz_residual(dist, model),
            combine_status(e_st, d_st).astype(supply.dtype),
        ])
        return resids.astype(jnp.float64) \
            if resids.dtype != jnp.float64 else resids

    return jax.jit(jax.vmap(one))


def _certify_rows(rows, cells, dtype, kwargs_items, thresholds=None):
    from ..solver_health import is_failure
    from ..verify.certificate import (
        CERT_CHECKS,
        _thresholds_from_kwargs,
    )

    rows = np.asarray(rows, dtype=np.float64)
    cells = np.asarray(cells, dtype=np.float64)
    schema = EZ_SCHEMA
    status_col = schema.idx("status")
    thr = _thresholds_from_kwargs(thresholds, dtype, dict(kwargs_items))
    healthy = ~np.asarray([is_failure(int(np.rint(r[status_col])))
                           for r in rows])
    out: list = [None] * len(rows)
    if healthy.any():
        import jax.numpy as jnp

        from ..obs.runtime import active_span

        idx = np.nonzero(healthy)[0]
        fn = _ez_certifier(dtype, kwargs_items)
        with active_span("verify/certify_rows", rows=int(len(idx)),
                         scenario="epstein_zin"):
            resids = np.asarray(fn(
                jnp.asarray(cells[idx, 0], dtype=dtype),
                jnp.asarray(cells[idx, 1], dtype=dtype),
                jnp.asarray(cells[idx, 2], dtype=dtype),
                jnp.asarray(rows[idx, schema.idx("r_star")], dtype=dtype),
                jnp.asarray(rows[idx, schema.idx("capital")],
                            dtype=dtype)),
                dtype=np.float64)
        for j, i in enumerate(idx):
            out[int(i)] = thr.certificate(resids[j])
    for i in np.nonzero(~healthy)[0]:
        status = int(np.rint(rows[i][status_col]))
        resids = np.full(len(CERT_CHECKS), np.nan)
        resids[CERT_CHECKS.index("recompute")] = float(status)
        out[int(i)] = thr.certificate(resids)
    return out


def _heuristic_work(cells):
    from ..parallel.sweep import heuristic_cell_work

    return heuristic_cell_work(cells)


EPSTEIN_ZIN = Scenario(
    name="epstein_zin",
    schema=EZ_SCHEMA,
    cells=CellSpace(
        names=("gamma", "rho", "sd"),
        scale=(8.0, 0.9, 0.4),    # gamma sweeps wider than CRRA's 4-span
        work=_heuristic_work,
        perturb_axis=1,
    ),
    batched_solver=batched_ez_solver,
    eager_row=_eager_row,
    retry_rungs=_retry_rungs,
    prepare_kwargs=_prepare_kwargs,
    warm=None,                       # cold-only (module docstring)
    certify_rows=_certify_rows,
)

register(EPSTEIN_ZIN)
