"""Pluggable model families riding the whole sweep/serve/verify stack
(ISSUE 9, DESIGN §12).  Importing this package registers the built-in
scenarios; ``run_sweep(scenario=...)`` / ``serve.make_query(scenario=...)``
resolve names through ``get_scenario``."""

from .base import (
    CELL_DIM,
    BracketWarmStart,
    CellSpace,
    DuplicateScenarioError,
    RowSchema,
    Scenario,
    ScenarioError,
    UnknownScenarioError,
)
from .registry import get_scenario, register, scenario_names, unregister

# built-in families self-register on import
from . import aiyagari  # noqa: E402,F401
from . import huggett  # noqa: E402,F401
from . import epstein_zin  # noqa: E402,F401

__all__ = [
    "CELL_DIM",
    "BracketWarmStart",
    "CellSpace",
    "DuplicateScenarioError",
    "RowSchema",
    "Scenario",
    "ScenarioError",
    "UnknownScenarioError",
    "get_scenario",
    "register",
    "scenario_names",
    "unregister",
]
