"""The Huggett scenario: the pure-exchange bond economy
(``models.huggett``) as a first-class sweep/serve/verify workload.

``solve_huggett_lean`` is the packed-row form of
``models.huggett.solve_huggett_equilibrium`` — the same bracketed
bisection on the bond rate with the same warm-started inner fixed points,
but scalar-only outputs, accumulated work counters, ``solver_health``
status (non-finite tripwires included), a deterministic fault-injection
hook, and a VERIFIED ``bracket_init`` continuation so the serving
engine's near-hit path and the sweep's warm brackets work exactly as they
do for Aiyagari: a seeded bracket is accepted only after both endpoints
are re-evaluated in-program (net demand <= 0 at the low end, > 0 at the
high end); a bad seed falls back to the cold establishment (lower-end
widening toward -90%).

Cells are (crra, rho, sd) — the same lattice coordinates as Aiyagari,
which is exactly why scenario identity lives in every fingerprint: a
Huggett query at (3, 0.6, 0.2) must never be served an Aiyagari entry at
numerically identical parameters.
"""

from __future__ import annotations

from functools import lru_cache
from typing import NamedTuple

import numpy as np

from .base import BracketWarmStart, CellSpace, RowSchema, Scenario
from .registry import register

HUGGETT_FIELDS = ("r_star", "net_demand", "borrower_share",
                  "bisect_iters", "egm_iters", "dist_iters", "status")

HUGGETT_SCHEMA = RowSchema(
    fields=HUGGETT_FIELDS,
    root="r_star",
    status="status",
    counters=("bisect_iters", "egm_iters", "dist_iters"),
    work=("egm_iters", "dist_iters"),
    phases=None,                      # no precision-phase split (yet)
    mask_on_failure=("r_star", "net_demand", "borrower_share"),
)

# Model-structure kwargs (consumed by build_simple_model) vs solver
# kwargs (consumed by solve_huggett_lean) — the split mirrors
# ``equilibrium._solve_cell``.
_BUILD_DEFAULTS = dict(labor_states=7, a_min=0.001, a_max=50.0,
                       a_count=32, a_nest_fac=2, dist_count=500,
                       borrow_limit=-2.0)


class HuggettLean(NamedTuple):
    """Scalar-only Huggett equilibrium for packed sweeps (the
    ``HuggettEquilibrium`` analogue of ``LeanEquilibrium``)."""

    r_star: object
    net_demand: object       # E[a] at r_star (~0 when bracketed)
    borrower_share: object   # stationary mass with a < 0
    bisect_iters: object     # net-demand evaluations actually performed
    egm_iters: object        # total EGM steps across all evaluations
    dist_iters: object       # total distribution steps
    status: object           # solver_health code (worst inner exit,
    #                          bracket certificate, non-finite tripwire)


def solve_huggett_lean(model, disc_fac, crra, r_tol=None,
                       max_bisect: int = 60, egm_tol=None, dist_tol=None,
                       r_lo: float = -0.10, dist_method: str = "auto",
                       accel_every: int = 32,
                       precision: str = "reference",
                       grid="reference",
                       kernel="reference",
                       state="replicated",
                       bracket_init=None, fault_iter=None,
                       fault_mode=None) -> HuggettLean:
    """Bisect the bond rate until the credit market clears (E[a] = 0),
    scalar outputs only — jit/vmap-able.

    Mirrors ``solve_huggett_equilibrium``'s economics (lower-end bracket
    validation/widening, warm-started inner fixed points across
    midpoints) and adds the sweep-stack contract: accumulated counters,
    severity-combined ``solver_health`` status with an in-loop
    non-finite tripwire (a NaN net demand exits typed instead of
    one-siding the bracket), ``fault_iter``/``fault_mode`` (poison the
    k-th midpoint evaluation — the deterministic quarantine drill), and
    ``bracket_init=(lo, hi, levels)`` — a warm bracket accepted only
    after BOTH endpoints verify in-program (``levels`` trips count
    against ``max_bisect`` exactly like the Aiyagari continuation); a
    failed verification degrades to the cold establishment path."""
    import jax
    import jax.numpy as jnp

    from ..models.household import (
        aggregate_capital,
        initial_distribution,
        initial_policy,
        solve_household,
        stationary_wealth,
    )
    from ..solver_health import (
        CONVERGED,
        MAX_ITER,
        NONFINITE,
        combine_status,
    )

    dtype = model.a_grid.dtype
    f64 = dtype == jnp.float64
    if r_tol is None:
        r_tol = 1e-10 if f64 else 1e-6
    if egm_tol is None:
        egm_tol = 1e-6 if f64 else 1e-5
    if dist_tol is None:
        dist_tol = 1e-11 if f64 else 1e-8
    hi_full = jnp.asarray(1.0 / disc_fac - 1.0 - 1e-4, dtype=dtype)
    lo_cold = jnp.asarray(r_lo, dtype=dtype)
    # compact grid policies (DESIGN §5b) close the carried policy with the
    # analytic tail knot — the initial iterate must share that shape
    from ..utils.config import resolve_grid

    p0 = initial_policy(model, analytic_tail=resolve_grid(grid).compact)
    d0 = initial_distribution(model)
    zi = jnp.asarray(0, dtype=jnp.int32)

    def demand(r, pol_in, dist_in):
        # kernel policy (ISSUE 13, DESIGN §4c) threads into both inner
        # fixed points — the family rides the fused/bf16 engines through
        # the same per-loop seams as the Aiyagari household
        policy, e_it, _, e_st = solve_household(
            1.0 + r, 1.0, model, disc_fac, crra, tol=egm_tol,
            init_policy=pol_in, accel_every=accel_every,
            precision=precision, grid=grid, kernel=kernel, state=state)
        dist, d_it, _, d_st = stationary_wealth(
            policy, 1.0 + r, 1.0, model, tol=dist_tol,
            init_dist=dist_in, method=dist_method, precision=precision,
            kernel=kernel, state=state)
        ex = aggregate_capital(dist, model)
        st = combine_status(e_st, d_st,
                            jnp.where(jnp.isfinite(ex), CONVERGED,
                                      NONFINITE))
        return ex, policy, dist, jnp.asarray(e_it, jnp.int32), \
            jnp.asarray(d_it, jnp.int32), st

    # -- bracket establishment ---------------------------------------------
    if bracket_init is None:
        ex_lo, _, _, e_acc, d_acc, st_acc = demand(lo_cold, None, None)
        lo, hi = lo_cold, hi_full
        it0 = zi
        n_eval = jnp.asarray(1, jnp.int32)
    else:
        lo_s, hi_s, lev = bracket_init
        lo_s = jnp.asarray(lo_s, dtype=dtype)
        hi_s = jnp.asarray(hi_s, dtype=dtype)
        ex_l, _, _, e1, d1, s1 = demand(lo_s, None, None)
        ex_h, _, _, e2, d2, s2 = demand(hi_s, None, None)
        e_acc, d_acc = e1 + e2, d1 + d2
        st_acc = combine_status(s1, s2)
        ok = (ex_l <= 0) & (ex_h > 0)
        # verified: continue from the seed with its descent budget spent;
        # failed: cold-establish downward from the seed's low end (the
        # widening walk below) against the full upper endpoint
        lo = lo_s
        hi = jnp.where(ok, hi_s, hi_full)
        ex_lo = ex_l
        it0 = jnp.where(ok, jnp.asarray(lev, jnp.int32), zi)
        n_eval = jnp.asarray(2, jnp.int32)

    # validate / widen the lower bracket end: walk lo toward -90% until
    # net demand turns negative (bounded — each probe is a full solve);
    # a verified warm seed enters with ex_lo <= 0 and skips the loop
    def widen_cond(state):
        lo, ex, k = state[0], state[1], state[2]
        return (ex > 0) & (k < 6) & (lo > -0.9)

    def widen_body(state):
        lo, _, k, e_a, d_a, st, n = state
        lo = jnp.maximum(jnp.asarray(-0.9, dtype=dtype),
                         lo - (2.0 ** k) * 0.1)
        ex, _, _, e_it, d_it, st2 = demand(lo, None, None)
        return (lo, ex, k + 1, e_a + e_it, d_a + d_it,
                combine_status(st, st2), n + 1)

    lo, ex_lo, _, e_acc, d_acc, st_acc, n_eval = jax.lax.while_loop(
        widen_cond, widen_body,
        (lo, ex_lo, zi, e_acc, d_acc, st_acc, n_eval))
    bracketed = ex_lo <= 0

    # -- bisection ----------------------------------------------------------
    if fault_iter is None:
        fault_iter = jnp.asarray(-1, jnp.int32)

    def cond(state):
        lo, hi, it, st = state[0], state[1], state[2], state[7]
        return ((hi - lo) > r_tol) & (it < max_bisect) & (st < NONFINITE)

    def body(state):
        lo, hi, it, policy, dist, e_a, d_a, st, n = state
        mid = 0.5 * (lo + hi)
        ex, policy, dist, e_it, d_it, st2 = demand(mid, policy, dist)
        if fault_mode is not None:
            trip = (fault_iter >= 0) & (it == fault_iter)
            ex = jnp.where(trip, jnp.asarray(jnp.nan, dtype=dtype), ex)
            st2 = combine_status(
                st2, jnp.where(trip, NONFINITE, CONVERGED))
        # a non-finite excess must not one-side the bracket (PR 1): the
        # bracket stays put and the status tripwire exits the loop
        finite = jnp.isfinite(ex)
        take_hi = ex > 0
        lo = jnp.where(finite & ~take_hi, mid, lo)
        hi = jnp.where(finite & take_hi, mid, hi)
        return (lo, hi, it + 1, policy, dist, e_a + e_it, d_a + d_it,
                combine_status(st, st2), n + 1)

    lo, hi, iters, policy, dist, e_acc, d_acc, st_acc, n_eval = \
        jax.lax.while_loop(cond, body, (lo, hi, it0, p0, d0, e_acc,
                                        d_acc, st_acc, n_eval))

    # bracket certificate: width within r_tol says the root is located;
    # an unbracketed market (lower end never turned negative) is a typed
    # failure, not a plausible number
    st_exit = jnp.where((hi - lo) <= r_tol, CONVERGED, MAX_ITER)
    st_brk = jnp.where(bracketed, CONVERGED, MAX_ITER)

    r_star = 0.5 * (lo + hi)
    ex, policy, dist, e_it, d_it, st2 = demand(r_star, policy, dist)
    borrowers = jnp.sum(jnp.where(model.dist_grid[:, None] < 0, dist,
                                  0.0))
    status = combine_status(st_acc, st2, st_exit, st_brk)
    return HuggettLean(
        r_star=r_star, net_demand=ex, borrower_share=borrowers,
        bisect_iters=n_eval + 1, egm_iters=e_acc + e_it,
        dist_iters=d_acc + d_it, status=status)


def solve_huggett_cell(crra, rho, sd=0.2, dtype=None, disc_fac=0.96,
                       labor_states=7, labor_bound=3.0, a_min=0.001,
                       a_max=50.0, a_count=32, a_nest_fac=2,
                       dist_count=500, borrow_limit=-2.0,
                       grid="reference",
                       **solver_kwargs) -> HuggettLean:
    """Build the bond-economy model for one (crra, rho, sd) cell and run
    the lean solver — the Huggett analogue of
    ``equilibrium.solve_calibration_lean``."""
    from ..models.household import build_simple_model

    model = build_simple_model(
        labor_states=labor_states, labor_ar=rho, labor_sd=sd,
        labor_bound=labor_bound, a_min=a_min, a_max=a_max,
        a_count=a_count, a_nest_fac=a_nest_fac, dist_count=dist_count,
        borrow_limit=borrow_limit, grid=grid, dtype=dtype)
    return solve_huggett_lean(model, disc_fac, crra, grid=grid,
                              **solver_kwargs)


@lru_cache(maxsize=None)
def batched_huggett_solver(dtype, kwargs_items=(), fault_mode=None,
                           warm=False):
    """Jitted vmapped Huggett cell solver, memoized per configuration —
    the ``parallel.sweep._batched_solver`` discipline (one executable per
    (dtype, kwargs, fault, warm); ``dtype`` arrives canonical)."""
    import jax
    import jax.numpy as jnp

    model_kwargs = dict(kwargs_items)

    def pack(res: HuggettLean):
        f = res.r_star.dtype
        # layout: HUGGETT_FIELDS — one stacked row per cell, one
        # device->host transfer per launch
        return jnp.stack([res.r_star, res.net_demand, res.borrower_share,
                          res.bisect_iters.astype(f),
                          res.egm_iters.astype(f),
                          res.dist_iters.astype(f),
                          res.status.astype(f)])

    def solve_cell(crra, rho, sd, bracket_init=None, fault_it=None):
        extra = {} if bracket_init is None else {"bracket_init":
                                                 bracket_init}
        if fault_mode is not None:
            extra.update(fault_iter=fault_it, fault_mode=fault_mode)
        return pack(solve_huggett_cell(crra, rho, sd, dtype=dtype,
                                       **extra, **model_kwargs))

    if fault_mode is None and not warm:
        def solve_one(crra, rho, sd):
            return solve_cell(crra, rho, sd)
    elif fault_mode is None:
        def solve_one(crra, rho, sd, lo0, hi0, it0):
            return solve_cell(crra, rho, sd, bracket_init=(lo0, hi0, it0))
    elif not warm:
        def solve_one(crra, rho, sd, fault_it):
            return solve_cell(crra, rho, sd, fault_it=fault_it)
    else:
        def solve_one(crra, rho, sd, lo0, hi0, it0, fault_it):
            return solve_cell(crra, rho, sd, bracket_init=(lo0, hi0, it0),
                              fault_it=fault_it)

    return jax.jit(jax.vmap(solve_one))


def _eager_row(cell, dtype, model_kwargs) -> np.ndarray:
    """One trusted serial solve for quarantine rungs: a batch-of-1
    launch of the cold executable (packing-independent by the serve
    contract, so batch-of-1 IS the trusted reference)."""
    import jax
    import jax.numpy as jnp

    from ..utils.fingerprint import hashable_kwargs

    fn = batched_huggett_solver(dtype, hashable_kwargs(model_kwargs),
                                None, False)
    out = jax.block_until_ready(fn(
        jnp.asarray([cell[0]], dtype=dtype),
        jnp.asarray([cell[1]], dtype=dtype),
        jnp.asarray([cell[2]], dtype=dtype)))
    return np.asarray(out, dtype=np.float64)[0]


def _retry_rungs(model_kwargs: dict) -> tuple:
    """Quarantine ladder (ISSUE 9 satellite: scenario-supplied): the same
    escalation reasoning as Aiyagari's — an ALTERNATE distribution method
    kept on every rung, then damped (unaccelerated) EGM, then extra
    lower-bracket headroom (an unbracketed market is the family's
    r_lo-too-tight failure mode, the analogue of Aiyagari's padded
    bracket)."""
    prior = model_kwargs.get("dist_method", "auto")
    alternate = "dense" if prior in ("auto", "scatter") else "scatter"
    rungs = (
        {"dist_method": alternate},
        {"dist_method": alternate, "accel_every": 0},
        {"dist_method": alternate, "accel_every": 0, "r_lo": -0.5},
    )
    if model_kwargs.get("precision", "reference") != "reference":
        rungs = tuple({**r, "precision": "reference"} for r in rungs)
    # grid escalation (DESIGN §5b): quarantine re-solves on the dense
    # reference grid, the one layout the goldens certify
    if model_kwargs.get("grid", "reference") != "reference":
        rungs = tuple({**r, "grid": "reference"} for r in rungs)
    # kernel escalation (ISSUE 13, DESIGN §4c): quarantine re-solves on
    # the launch-per-loop reference engines
    if model_kwargs.get("kernel", "reference") != "reference":
        rungs = tuple({**r, "kernel": "reference"} for r in rungs)
    return rungs


def _prepare_kwargs(model_kwargs: dict) -> dict:
    # the bond economy's inner loops run the same engines; the scatter
    # push-forward ("auto") is the right CPU default and dense the
    # accelerator one — but nothing here is backend-probed yet, so the
    # recorded method is simply what will run
    return {"dist_method": str(model_kwargs.get("dist_method", "auto"))}


def _host_bracket(model_kwargs, dtype):
    """The economic bracket in host arithmetic, bit-identical to the
    compiled program's endpoints (same Python-float expressions, one cast
    to ``dtype``) — the dyadic-descent replay contract."""
    ft = np.dtype(dtype).type
    disc_fac = float(model_kwargs.get("disc_fac", 0.96))
    r_lo = float(model_kwargs.get("r_lo", -0.10))
    return ft(r_lo), ft(1.0 / disc_fac - 1.0 - 1e-4)


def _host_r_tol(model_kwargs, dtype) -> float:
    rt = model_kwargs.get("r_tol")
    if rt is not None:
        return float(rt)
    return 1e-10 if np.dtype(dtype) == np.float64 else 1e-6


def _max_levels(model_kwargs) -> int:
    return max(0, int(model_kwargs.get("max_bisect", 60)) - 6)


@lru_cache(maxsize=None)
def _huggett_certifier(dtype, kwargs_items=()):
    """Jitted vmapped independent recompute certifier: cold policy solve
    at the reported rate, DIRECT stationary distribution, fresh
    push-forward — never the lean warm carry that produced the row."""
    import jax
    import jax.numpy as jnp

    from ..models.household import (
        aggregate_capital,
        build_simple_model,
        solve_household,
        stationary_wealth,
    )
    from ..solver_health import combine_status
    from ..verify.certificate import (
        _cert_dist_method,
        _split_kwargs,
        euler_residual_midpoints,
        lorenz_residual,
        shape_residual,
        stationarity_residuals,
    )

    model_kwargs = dict(kwargs_items)

    def one(crra, rho, sd, r_star, net_claim):
        build, price, egm_tol, dist_tol = _split_kwargs(
            {**model_kwargs, "__dtype__": dtype})
        build.setdefault("borrow_limit",
                         _BUILD_DEFAULTS["borrow_limit"])
        model = build_simple_model(labor_ar=rho, labor_sd=sd,
                                   dtype=dtype, **build)
        R = 1.0 + r_star
        # the certifier re-solves on the SAME grid layout the production
        # solve used (DESIGN §5b): under a compact policy the reference
        # policy must carry the analytic tail closure too, exactly as
        # the aiyagari recompute certifier does
        policy, _, _, e_st = solve_household(
            R, 1.0, model, price["disc_fac"], crra, tol=egm_tol,
            method="xla", precision="reference",
            grid=build.get("grid", "reference"))
        dist, _, _, d_st = stationary_wealth(
            policy, R, 1.0, model, tol=dist_tol,
            method=_cert_dist_method(build), precision="reference")
        net = aggregate_capital(dist, model)
        gross = jnp.sum(dist * jnp.abs(model.dist_grid)[:, None])
        tiny = jnp.asarray(np.finfo(np.float64).tiny, dtype=net.dtype)
        denom = jnp.maximum(gross, tiny)
        station, mass = stationarity_residuals(policy, dist, R, 1.0,
                                               model)
        resids = jnp.stack([
            euler_residual_midpoints(policy, R, 1.0, model,
                                     price["disc_fac"], crra),
            station,
            mass,
            jnp.abs(net) / denom,            # market clearing: E[a] ~ 0
            jnp.abs(net_claim - net) / denom,  # the row's claim re-checked
            shape_residual(policy),
            lorenz_residual(dist, model),
            combine_status(e_st, d_st).astype(net.dtype),
        ])
        return resids.astype(jnp.float64) \
            if resids.dtype != jnp.float64 else resids

    return jax.jit(jax.vmap(one))


def _certify_rows(rows, cells, dtype, kwargs_items, thresholds=None):
    """A posteriori certification of Huggett packed rows — the
    ``verify.certify_packed_rows`` contract (CERT_CHECKS-ordered
    residuals, severity-graded; failed statuses certify FAILED
    trivially), with the market-clearing/capital residuals normalized by
    GROSS bond positions (net demand is ~0 by construction, so a
    relative-to-net residual would be meaningless)."""
    from ..solver_health import is_failure
    from ..verify.certificate import (
        CERT_CHECKS,
        _thresholds_from_kwargs,
    )

    rows = np.asarray(rows, dtype=np.float64)
    cells = np.asarray(cells, dtype=np.float64)
    schema = HUGGETT_SCHEMA
    status_col = schema.idx("status")
    thr = _thresholds_from_kwargs(thresholds, dtype, dict(kwargs_items))
    healthy = ~np.asarray([is_failure(int(np.rint(r[status_col])))
                           for r in rows])
    out: list = [None] * len(rows)
    if healthy.any():
        import jax.numpy as jnp

        from ..obs.runtime import active_span

        idx = np.nonzero(healthy)[0]
        fn = _huggett_certifier(dtype, kwargs_items)
        with active_span("verify/certify_rows", rows=int(len(idx)),
                         scenario="huggett"):
            resids = np.asarray(fn(
                jnp.asarray(cells[idx, 0], dtype=dtype),
                jnp.asarray(cells[idx, 1], dtype=dtype),
                jnp.asarray(cells[idx, 2], dtype=dtype),
                jnp.asarray(rows[idx, schema.idx("r_star")], dtype=dtype),
                jnp.asarray(rows[idx, schema.idx("net_demand")],
                            dtype=dtype)),
                dtype=np.float64)
        for j, i in enumerate(idx):
            out[int(i)] = thr.certificate(resids[j])
    for i in np.nonzero(~healthy)[0]:
        status = int(np.rint(rows[i][status_col]))
        resids = np.full(len(CERT_CHECKS), np.nan)
        resids[CERT_CHECKS.index("recompute")] = float(status)
        out[int(i)] = thr.certificate(resids)
    return out


def _heuristic_work(cells):
    # the same (σ, ρ, sd)-shaped mixing-time economics drive the bond
    # economy's inner loops; only the RANKING matters for bucketing, and
    # a sidecar replaces this with measured counters cell-for-cell
    from ..parallel.sweep import heuristic_cell_work

    return heuristic_cell_work(cells)


HUGGETT = Scenario(
    name="huggett",
    schema=HUGGETT_SCHEMA,
    cells=CellSpace(
        names=("crra", "rho", "sd"),
        scale=(4.0, 0.9, 0.4),
        work=_heuristic_work,
        perturb_axis=1,
    ),
    batched_solver=batched_huggett_solver,
    eager_row=_eager_row,
    retry_rungs=_retry_rungs,
    prepare_kwargs=_prepare_kwargs,
    warm=BracketWarmStart(host_bracket=_host_bracket,
                          host_r_tol=_host_r_tol,
                          max_levels=_max_levels),
    certify_rows=_certify_rows,
)

register(HUGGETT)
