"""The Aiyagari scenario: the existing Table II cell solver as a
registered ``Scenario`` — byte-for-byte the pre-scenario behavior.

Everything here delegates to the machinery the sweep/serve stack always
used (``parallel.sweep._batched_solver`` IS the executable factory, so
the scenario shares its lru_cache with every direct caller;
``models.equilibrium.solve_calibration_lean`` is the quarantine path;
``verify.certificate.certify_packed_rows`` the certifier) — the scenario
object only names the seams the engine used to hard-code.
"""

from __future__ import annotations

import numpy as np

from ..utils.config import PACKED_ROW_FIELDS
from .base import BracketWarmStart, CellSpace, RowSchema, Scenario
from .registry import register

# The canonical Aiyagari packed-row layout (``config.PACKED_ROW_FIELDS``
# is its definition site; this RowSchema is how every other subsystem now
# reads it — scripts/check_row_schema.py bans fresh direct imports).
AIYAGARI_SCHEMA = RowSchema(
    fields=tuple(PACKED_ROW_FIELDS),
    root="r_star",
    status="status",
    counters=("bisect_iters", "egm_iters", "dist_iters"),
    work=("egm_iters", "dist_iters"),
    phases=("descent_steps", "polish_steps", "precision_escalations"),
    mask_on_failure=("r_star", "capital"),
)


def _batched_solver(dtype, kwargs_items=(), fault_mode=None, warm=False):
    from ..parallel.sweep import _batched_solver as factory

    return factory(dtype, kwargs_items, fault_mode, warm)


def _eager_row(cell, dtype, model_kwargs) -> np.ndarray:
    """One trusted serial solve (the quarantine rung path): the eager
    ``solve_calibration_lean`` call the pre-scenario engine made, its
    scalars packed into the row layout."""
    import jax

    from ..models.equilibrium import solve_calibration_lean

    lean = jax.block_until_ready(solve_calibration_lean(
        cell[0], cell[1], labor_sd=cell[2], dtype=dtype, **model_kwargs))
    return np.asarray(
        [float(lean.r_star), float(lean.capital), float(lean.labor),
         int(lean.bisect_iters), int(lean.egm_iters),
         int(lean.dist_iters), int(lean.status),
         int(lean.descent_steps), int(lean.polish_steps),
         int(lean.escalations)], dtype=np.float64)


def _retry_rungs(model_kwargs: dict) -> tuple:
    from ..parallel.sweep import _retry_ladder

    return _retry_ladder(model_kwargs)


def _prepare_kwargs(model_kwargs: dict) -> dict:
    """The sweep-level method defaulting the engine used to inline
    (backend-aware dist/egm engine selection; DESIGN §4b/§5) — applied in
    place, the resolved choices returned as result metadata."""
    import jax

    two_phase = model_kwargs.get("precision", "reference") != "reference"
    compacted = model_kwargs.get("grid", "reference") != "reference"
    fused = model_kwargs.get("kernel", "reference") != "reference"
    if fused and not two_phase:
        # kernel="fused" single-phase (ISSUE 13, DESIGN §4c): both inner
        # loops run inside the fused megakernel, so the per-loop method
        # knobs are moot — default them without burning the per-loop
        # Mosaic probes (the fused path carries its own probe + XLA
        # fallback inside household_capital_supply)
        model_kwargs.setdefault("dist_method", "auto")
        model_kwargs.setdefault("egm_method", "xla")
    if "dist_method" not in model_kwargs:
        # Sweep-level default, distinct from stationary_wealth's "auto".
        # On accelerators: "pallas" — the lane-grid kernel (one program
        # instance per cell via the custom_vmap batching rule,
        # ``household._pallas_fixed_point_vmappable``) lets every cell's
        # distribution fixed point exit at its OWN convergence instead of
        # vmap-of-while lock-step, measured 1.26 s vs dense's 2.16 s on
        # the 12-cell sweep (one v5e chip, identical r*).  Fallback
        # "dense" (batched MXU matvecs) when Mosaic can't compile the
        # kernel.  NOT "solve" — with the EGM Anderson acceleration and
        # the stall exit in place, iterating the dense operator beats
        # paying a (D*N)^3 LU per midpoint (measured: dense 2.8s vs solve
        # 4.8s).  On CPU, "auto" (scatter) — dense/LU/pallas are the
        # wrong trade there.
        if jax.default_backend() in ("tpu", "axon"):
            if two_phase:
                # the precision ladder needs the two-phase XLA paths (the
                # VMEM kernel runs one precision end-to-end); dense IS the
                # ladder's MXU path, so record what actually runs
                model_kwargs["dist_method"] = "dense"
            else:
                from ..ops.pallas_kernels import pallas_grid_tpu_available
                model_kwargs["dist_method"] = (
                    "pallas" if pallas_grid_tpu_available() else "dense")
        else:
            model_kwargs["dist_method"] = "auto"
    if "egm_method" not in model_kwargs:
        # Same default logic for the POLICY loop (ISSUE 2 tentpole): the
        # lane-grid EGM kernel lets a converged cell stop burning MXU
        # cycles instead of lock-stepping to the slowest lane; probe-gated
        # with the XLA while_loop as the universal fallback.  A compact
        # grid policy (DESIGN §5b) demotes to "xla" like non-reference
        # precision: the VMEM kernel runs the fixed reference knot
        # layout, not the tail-closed compact one.
        if (jax.default_backend() in ("tpu", "axon") and not two_phase
                and not compacted):
            from ..ops.pallas_kernels import pallas_egm_grid_tpu_available
            model_kwargs["egm_method"] = (
                "pallas" if pallas_egm_grid_tpu_available() else "xla")
        else:
            model_kwargs["egm_method"] = "xla"
    return {"dist_method": str(model_kwargs["dist_method"]),
            "egm_method": str(model_kwargs["egm_method"]),
            "kernel": str(model_kwargs.get("kernel", "reference"))}


def _host_bracket(model_kwargs, dtype):
    from ..parallel.sweep import _host_bracket as hb

    return hb(model_kwargs, dtype)


def _host_r_tol(model_kwargs, dtype):
    from ..parallel.sweep import _host_r_tol as ht

    return ht(model_kwargs, dtype)


def _max_levels(model_kwargs):
    return max(0, int(model_kwargs.get("max_bisect", 60)) - 6)


def _certify_rows(rows, cells, dtype, kwargs_items, thresholds=None):
    from ..verify.certificate import certify_packed_rows

    return certify_packed_rows(rows, cells, dtype, kwargs_items,
                               thresholds=thresholds,
                               schema=AIYAGARI_SCHEMA)


def _heuristic_work(cells):
    from ..parallel.sweep import heuristic_cell_work

    return heuristic_cell_work(cells)


AIYAGARI = Scenario(
    name="aiyagari",
    schema=AIYAGARI_SCHEMA,
    cells=CellSpace(
        names=("crra", "rho", "sd"),
        scale=(4.0, 0.9, 0.4),      # == parallel.sweep.NEIGHBOR_CELL_SCALE
        work=_heuristic_work,
        perturb_axis=1,
    ),
    batched_solver=_batched_solver,
    eager_row=_eager_row,
    retry_rungs=_retry_rungs,
    prepare_kwargs=_prepare_kwargs,
    warm=BracketWarmStart(host_bracket=_host_bracket,
                          host_r_tol=_host_r_tol,
                          max_levels=_max_levels),
    certify_rows=_certify_rows,
)

register(AIYAGARI)
