"""The scenario registry: name -> ``Scenario``, with typed failure.

One process-global table (scenarios are stateless bundles of callables;
there is nothing per-run to scope).  The built-in families register
themselves when ``aiyagari_hark_tpu.scenarios`` is imported;
``get_scenario`` lazily triggers that import so callers deep in the
stack (``parallel.sweep``, ``serve.service``) can resolve names without
import-order ceremony.
"""

from __future__ import annotations

from typing import Dict, Tuple

from .base import DuplicateScenarioError, Scenario, UnknownScenarioError

_REGISTRY: Dict[str, Scenario] = {}


def register(scenario: Scenario, replace: bool = False) -> Scenario:
    """Add ``scenario`` to the registry.  A duplicate name raises the
    typed ``DuplicateScenarioError`` (silently replacing a family would
    re-key every fingerprint hashing the name while stored artifacts
    still carry it); ``replace=True`` is the explicit test escape hatch
    and returns the PREVIOUS scenario so fixtures can restore it."""
    name = scenario.name
    prior = _REGISTRY.get(name)
    if prior is not None and not replace:
        raise DuplicateScenarioError(
            f"scenario {name!r} is already registered; pass replace=True "
            "only if you really mean to re-key it")
    _REGISTRY[name] = scenario
    return prior if prior is not None else scenario


def unregister(name: str) -> None:
    """Remove a scenario (test fixtures restoring a clean registry)."""
    _REGISTRY.pop(name, None)


def _ensure_builtins() -> None:
    # the built-in families self-register at package import; resolving a
    # name before anyone imported the package must still find them
    if "aiyagari" not in _REGISTRY:
        from . import aiyagari, epstein_zin, huggett  # noqa: F401


def get_scenario(scenario) -> Scenario:
    """Resolve a scenario name (or pass a ``Scenario`` through).  An
    unknown name raises the typed ``UnknownScenarioError`` listing what
    IS registered — a typo must never silently address a fresh cache
    namespace."""
    if isinstance(scenario, Scenario):
        return scenario
    _ensure_builtins()
    try:
        return _REGISTRY[scenario]
    except (KeyError, TypeError):
        raise UnknownScenarioError(scenario, _REGISTRY.keys()) from None


def scenario_names() -> Tuple[str, ...]:
    """Registered names, sorted (built-ins included)."""
    _ensure_builtins()
    return tuple(sorted(_REGISTRY))
