"""Solver-health layer: typed convergence status for every fixed point.

Every fixed-point loop in the engine — the EGM policy iteration, the
stationary-distribution push, the interest-rate bisection, the KS outer
loop — exits on ``diff > tol`` or ``max_iter``.  Before this layer none of
them *reported* which exit it took, and ``NaN > tol`` evaluates False, so a
NaN-poisoned iterate terminated a ``lax.while_loop`` looking exactly like
convergence and propagated garbage into sweep results silently.  Cao-Luo-Nie
(arXiv:1905.13045) and Ma-Stachurski-Toda (arXiv:1812.01320) both show the
Aiyagari supply map loses contraction near the bracket edges, so
non-convergence under aggressive parameters (sigma=5, rho=0.9, fine grids)
is an expected operating condition, not a bug to hope away.

The contract:

* every fixed point returns a trailing **status code** (int32, jit/vmap
  safe).  Codes are ordered by severity, so the worst status of a composite
  solve is ``jnp.maximum`` over the components (``combine_status``):

      CONVERGED (0) < STALLED (1) < MAX_ITER (2) < NONFINITE (3)

  - ``CONVERGED``: the certified residual met the tolerance.
  - ``STALLED``: the loop's stall window fired — the residual stopped
    improving above tol (typically the dtype rounding floor for a
    slow-mixing chain).  The returned iterate is the honest best; benign
    but worth surfacing.
  - ``MAX_ITER``: the iteration budget ran out with ``diff > tol`` (or the
    bisection bracket still wider than ``r_tol``).  The result is
    uncertified — treat as a failure.
  - ``NONFINITE``: a non-finite iterate tripped the in-loop
    ``isfinite(diff)`` tripwire.  The numbers are garbage.

* ``is_failure(status)`` is the caller-side gate: True for ``MAX_ITER`` and
  ``NONFINITE``; the batched sweep quarantines and retries exactly those
  cells (``parallel.sweep``), and the facade raises
  ``SolverDivergenceError`` instead of returning silent garbage.

* the deterministic fault-injection hook (``inject_fault``) wraps a step
  function to emit a NaN or a stall at iteration k, so every tripwire and
  retry path is exercisable in CPU tests without waiting for natural
  divergence.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Severity-ordered status codes: combine with jnp.maximum.
CONVERGED = 0
STALLED = 1
MAX_ITER = 2
NONFINITE = 3
# Process-level (never emitted by a jitted loop): a run stopped at a safe
# boundary on a shutdown request (``utils.resilience.Interrupted``).  The
# result is uncertified, so it sits on the failure side of ``is_failure``;
# "worse" than NONFINITE only in the trivial sense that no numbers were
# produced at all.
INTERRUPTED = 4
# Process-level: a serving query's deadline expired before its batch
# launched (``serve.DeadlineExceeded`` — ISSUE 6 SLO satellite).  Like
# INTERRUPTED, no numbers were produced: uncertified by construction,
# failure side of ``is_failure``.
DEADLINE_EXCEEDED = 5
# Process-level overload family (ISSUE 8, DESIGN §11): the serving
# engine's typed saturation outcomes.  No numbers were produced for any
# of them, so all sit on the failure side of ``is_failure``; severity
# ordering among them is nominal (they never enter ``combine_status``).
# OVERLOADED: admission control rejected the query fail-fast (class
# budget, unmeetable deadline, or full queue) — the error carries
# depth + estimated wait so callers can retry-after.
OVERLOADED = 6
# LOAD_SHED: a queued lower-priority pending was displaced by a
# higher-priority arrival under pressure (``serve.LoadShed``).
LOAD_SHED = 7
# CIRCUIT_OPEN: the query's (σ, ρ, sd) region has an open circuit
# breaker after repeated solve/certification failures; fast-failed
# without touching the queue (``serve.CircuitOpen``).
CIRCUIT_OPEN = 8
# BACKEND_FAULT (ISSUE 16): a fleet lease-backend/store substrate
# operation failed or degraded (partitioned read, dropped CAS
# connection, a held lease found lost) — the distributed-robustness
# tier's process-level code, journaled as ``LEASE_BACKEND_FAULT``.  No
# numbers were produced: uncertified, failure side of ``is_failure``.
BACKEND_FAULT = 9

STATUS_NAMES = ("CONVERGED", "STALLED", "MAX_ITER", "NONFINITE",
                "INTERRUPTED", "DEADLINE_EXCEEDED", "OVERLOADED",
                "LOAD_SHED", "CIRCUIT_OPEN", "BACKEND_FAULT")

# NOTE marker, not a status code (it never enters ``combine_status``): a
# mixed-precision ladder's DESCENT phase exited NONFINITE or STALLED and
# the fixed point fell back to a pure-reference solve before quarantine
# could see a failure (DESIGN §5).  The final status is the reference
# polish's honest exit; this note records that the cheap phase was
# abandoned.  Surfaces as ``SweepResult.precision_escalations`` /
# ``ServeMetrics`` counters and in status-trail dicts under the
# ``"note"`` key.
PRECISION_ESCALATED = "PRECISION_ESCALATED"

# NOTE marker, same family (DESIGN §5b): a grid ladder's COARSE phase
# exited NONFINITE or STALLED and the polish restarted cold on the
# compact grid with the full budget — the in-program escalation.  The
# out-of-program escalation to the DENSE REFERENCE grid is the sweep
# quarantine ladder's job (every rung forces ``grid="reference"``), so a
# cell can only ever fail at the configuration the goldens certify.
# Counted in the same ladder-escalation slot as PRECISION_ESCALATED
# (``PrecisionPhases.escalated`` / ``SweepResult.precision_escalations``
# — one counter of "the cheap phase was abandoned", whichever ladder it
# belonged to).
GRID_ESCALATED = "GRID_ESCALATED"


def status_name(code) -> str:
    """Host-side pretty name for one integer status code."""
    code = int(code)
    if 0 <= code < len(STATUS_NAMES):
        return STATUS_NAMES[code]
    return f"UNKNOWN({code})"


def combine_status(*codes):
    """Worst (most severe) of several status codes — elementwise, so it
    works on per-cell status arrays as well as scalars."""
    out = jnp.asarray(codes[0], dtype=jnp.int32)
    for c in codes[1:]:
        out = jnp.maximum(out, jnp.asarray(c, dtype=jnp.int32))
    return out


def classify_fixed_point_exit(diff, tol, it, max_iter):
    """Status code from a fixed-point loop's exit state, jit/vmap safe.

    ``diff`` is the loop's LAST certified residual (non-finite iff the
    tripwire fired), ``it`` the iterations taken.  The residual order of
    the tests matters: a non-finite diff must not read as anything else,
    and ``diff <= tol`` is False for NaN.  An exit with a finite
    ``diff > tol`` before ``max_iter`` can only be a stall window.
    """
    diff = jnp.asarray(diff)
    return jnp.where(
        ~jnp.isfinite(diff), jnp.int32(NONFINITE),
        jnp.where(diff <= tol, jnp.int32(CONVERGED),
                  jnp.where(it >= max_iter, jnp.int32(MAX_ITER),
                            jnp.int32(STALLED))))


def is_failure(status):
    """True where a status means the result is uncertified or garbage
    (``MAX_ITER`` or ``NONFINITE``).  Works on numpy/JAX arrays and ints;
    ``STALLED`` is deliberately benign — the stall exit returns the honest
    best iterate when the tolerance sits below the dtype floor."""
    return status >= MAX_ITER


class SolverDivergenceError(RuntimeError):
    """A solve produced an uncertified or non-finite result.

    Carries the machine-readable context so callers can escalate instead
    of parsing the message: ``status`` (the worst status code observed)
    and ``trail`` (a list of per-stage/per-iteration dicts describing what
    was tried and how each attempt exited)."""

    def __init__(self, message: str, status=None, trail=None):
        super().__init__(message)
        self.status = None if status is None else int(status)
        self.trail = list(trail) if trail is not None else []


def inject_fault(step_fn, mode: str = "nan", at_iter: int = 0,
                 amplitude: float = 1e-3):
    """Deterministic fault-injection hook for the accelerated fixed points.

    Wraps a ``x -> x'`` step function into an iteration-aware one (the
    loops detect the ``takes_iteration`` attribute and pass the current
    iteration index) that misbehaves from iteration ``at_iter`` onward:

    * ``mode="nan"``: every leaf of the output becomes NaN — exercises the
      ``NONFINITE`` tripwire (the loop must exit immediately, not
      masquerade as converged).
    * ``mode="stall"``: adds an alternating-sign ``amplitude`` offset to
      every leaf, pinning the sup-norm diff near ``2*amplitude`` forever —
      exercises the ``MAX_ITER`` exit (policy loop) and the stall-window
      ``STALLED`` exit (distribution loop).  Pick ``amplitude > tol``.
      The offset is uniform across a leaf, so strictly-monotone knot grids
      stay monotone.

    Purely a test/diagnostic helper: nothing in the production paths calls
    it.  The sweep-level analogue is ``run_table2_sweep(inject_fault=...)``,
    which poisons one cell inside the jitted bisection.
    """
    if mode not in ("nan", "stall"):
        raise ValueError(f"inject_fault mode must be 'nan' or 'stall', "
                         f"got {mode!r}")

    def wrapped(x, it):
        out = step_fn(x)
        hit = it >= at_iter
        if mode == "nan":
            return jax.tree.map(
                lambda leaf: jnp.where(hit, jnp.nan, leaf), out)
        sign = jnp.where(jnp.mod(it, 2) == 0, 1.0, -1.0)
        return jax.tree.map(
            lambda leaf: leaf + jnp.where(hit, sign * amplitude,
                                          0.0).astype(leaf.dtype), out)

    wrapped.takes_iteration = True
    return wrapped


def call_step(step_fn, x, it):
    """Invoke a fixed-point step, passing the iteration index iff the step
    advertises ``takes_iteration`` (the ``inject_fault`` wrapper does).
    The shared shim of both accelerated fixed points."""
    if getattr(step_fn, "takes_iteration", False):
        return step_fn(x, it)
    return step_fn(x)
