"""The run-scoped observability bundle: config, lifecycle, active scope
(ISSUE 7).

``ObsConfig`` is the one switch callers thread (``SweepConfig.obs``,
``EquilibriumService(obs=...)``, bench flags); ``Obs`` bundles the three
pillars — tracer, metrics registry, event journal — under one
``run_id`` so every artifact of a run correlates.  Disabled is the
default and near-free:

* ``NULL_OBS`` is a process singleton whose ``span()`` returns THE
  cached null context manager (``trace.NULL_SPAN_CM`` — no allocation,
  no clock read), whose ``event()`` is a constant no-op, and whose
  instrument accessors return a shared no-op instrument.
* ``emit_event`` — the module-level hook deep seams use
  (``utils.resilience`` retries, ``SolutionStore`` evictions,
  ``utils.fingerprint`` integrity raises) — costs ONE empty-list truth
  test when no run is active.

An enabled ``Obs`` additionally registers itself as the ACTIVE scope
(``activate()``) for the duration of a run, so instrumented layers too
deep to thread a handle through (signal handlers, checksum primitives,
the store called from a service that predates the run) still land their
events in the right journal.  The active scope is a PER-THREAD stack:
nested runs (a sweep inside a bench phase) journal to the innermost,
and concurrent runs on different threads never blend.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
from typing import List, Optional

from .journal import EventJournal
from .metrics import MetricsRegistry
from .trace import NULL_SPAN_CM, Tracer, new_run_id


@dataclasses.dataclass(frozen=True)
class ObsConfig:
    """Observability knobs for one run (hashable, rides frozen configs).

    * ``enabled`` — master switch; False (default) resolves to
      ``NULL_OBS`` and changes ZERO solver bits (pinned by
      ``tests/test_obs.py``).
    * ``trace`` / ``metrics`` — record spans / counters (both on when
      enabled; the journal is governed by ``journal_path`` alone).
    * ``trace_path`` — write the Chrome-trace JSON here on close
      (load it in chrome://tracing or https://ui.perfetto.dev).
    * ``journal_path`` — append typed lifecycle events to this JSONL.
    * ``run_id`` — correlation id; auto-generated when None.
    * ``device_trace_dir`` — opt-in bridge to ``utils.timing
      .device_trace``: spans created with ``device_profile=True``
      capture an XLA profiler dump under this directory.
    * ``profile`` — the performance tier (ISSUE 10, DESIGN §10b): a
      ``obs.profile.CostLedger`` capturing each profiled executable's
      XLA cost analysis and lowering/compile walls and aggregating
      launch walls into achieved-FLOP/s + roofline numbers, plus
      ``DeviceTelemetry`` sampling per-device ``memory_stats()`` at
      sweep bucket seams and serve batch flushes.  Off by default —
      capture AOT-compiles each executable once, a cost the disabled
      path must never pay.
    * ``flight_path`` — where the flight recorder dumps its ring as a
      crash artifact when a typed failure escalates past the quarantine
      ladder (``Obs.dump_flight``).  None derives a sibling of
      ``journal_path`` (``<journal>.flight.json``) when that is set,
      else disables dumping (the in-memory ring still records).
    * ``flight_limit`` — bounded size of the flight-recorder ring."""

    enabled: bool = False
    trace: bool = True
    metrics: bool = True
    trace_path: Optional[str] = None
    journal_path: Optional[str] = None
    run_id: Optional[str] = None
    device_trace_dir: Optional[str] = None
    profile: bool = False
    flight_path: Optional[str] = None
    flight_limit: int = 256

    def replace(self, **kwargs) -> "ObsConfig":
        return dataclasses.replace(self, **kwargs)


class _NullInstrument:
    """Accepts every instrument mutation, records nothing."""

    __slots__ = ()
    kind = "null"
    value = 0.0
    count = 0
    sum = 0.0

    def inc(self, amount: float = 1.0) -> None:
        pass

    def set(self, value: float) -> None:
        pass

    def observe(self, value: float) -> None:
        pass


NULL_INSTRUMENT = _NullInstrument()
_NULL_ACTIVATE_CM = contextlib.nullcontext(None)


class FlightRecorder:
    """A bounded ring of a run's most recent lifecycle entries (ISSUE
    10): every journal event and every completed span lands here (plus
    externally-timed ``record_span`` latencies), so when a typed failure
    escalates past the quarantine ladder the run can dump "what just
    happened" as one crash artifact — the post-mortem the PR 1/3/6
    failure modes never had.  Oldest entries fall off; ``dropped``
    counts them so a dump can never silently read as complete."""

    def __init__(self, limit: int = 256, clock=time.time):
        import collections

        self.limit = max(1, int(limit))
        self._ring = collections.deque(maxlen=self.limit)
        self._clock = clock
        self._lock = threading.Lock()
        self.noted = 0

    def note(self, kind: str, payload: dict) -> None:
        rec = {"t": round(float(self._clock()), 6), "kind": str(kind)}
        rec.update(payload)
        with self._lock:
            self._ring.append(rec)
            self.noted += 1

    @property
    def dropped(self) -> int:
        with self._lock:
            return max(0, self.noted - len(self._ring))

    def entries(self) -> list:
        with self._lock:
            return list(self._ring)


class Obs:
    """One run's observability bundle (build via ``build_obs``)."""

    enabled = True

    def __init__(self, run_id: Optional[str] = None,
                 tracer: Optional[Tracer] = None,
                 registry: Optional[MetricsRegistry] = None,
                 journal: Optional[EventJournal] = None,
                 trace_path: Optional[str] = None,
                 cost_ledger=None, telemetry=None,
                 flight: Optional[FlightRecorder] = None,
                 flight_path: Optional[str] = None):
        self.run_id = run_id if run_id is not None else new_run_id()
        self.tracer = tracer
        self.registry = registry
        self.journal = journal
        self.trace_path = trace_path
        self.cost_ledger = cost_ledger    # obs.profile.CostLedger | None
        self.telemetry = telemetry        # obs.profile.DeviceTelemetry
        self.flight = flight
        self.flight_path = flight_path
        self._closed = False

    # -- spans --------------------------------------------------------------

    def span(self, name: str, **attrs):
        if self.tracer is None:
            return NULL_SPAN_CM
        cm = self.tracer.span(name, **attrs)
        if self.flight is None:
            return cm
        return self._flight_span(cm, name)

    @contextlib.contextmanager
    def _flight_span(self, cm, name: str):
        """Wrap a tracer span so its completion also lands in the flight
        ring (name + wall; full attrs stay in the trace — the ring is a
        post-mortem digest, not a second trace)."""
        sp = None
        try:
            with cm as sp:
                yield sp
        finally:
            if sp is not None and sp.t1 is not None:
                self.flight.note("span", {"name": name,
                                          "wall_s": sp.duration()})

    def record_span(self, name: str, duration_s: float, **attrs) -> None:
        if self.tracer is not None:
            self.tracer.record(name, duration_s, **attrs)
        if self.flight is not None:
            self.flight.note("span", {"name": name,
                                      "wall_s": float(duration_s),
                                      "external": True})

    # -- events -------------------------------------------------------------

    def event(self, etype: str, **attrs) -> None:
        if self.journal is not None:
            self.journal.emit(etype, **attrs)
        if self.flight is not None:
            from .trace import _jsonable

            self.flight.note("event", {"event": etype,
                                       **{str(k): _jsonable(v)
                                          for k, v in attrs.items()}})

    # -- metrics ------------------------------------------------------------

    def counter(self, name: str, help: str = ""):
        if self.registry is None:
            return NULL_INSTRUMENT
        return self.registry.counter(name, help)

    def gauge(self, name: str, help: str = ""):
        if self.registry is None:
            return NULL_INSTRUMENT
        return self.registry.gauge(name, help)

    def histogram(self, name: str, help: str = "", **kw):
        if self.registry is None:
            return NULL_INSTRUMENT
        return self.registry.histogram(name, help, **kw)

    # -- performance tier (ISSUE 10) ----------------------------------------

    def sample_devices(self, where: str = "") -> int:
        """Sample per-device ``memory_stats()`` into gauges + high-water
        events (``obs.profile.DeviceTelemetry``).  No-op (returns 0)
        unless the profile pillar is on — the sampling sites (sweep
        bucket seams, serve batch flushes) call unconditionally."""
        if self.telemetry is None:
            return 0
        return self.telemetry.sample(self, where=where)

    def dump_flight(self, reason: str, **attrs) -> Optional[str]:
        """Dump the flight-recorder ring as a crash artifact (atomic
        JSON via ``utils.checkpoint``) and journal FLIGHT_RECORD_DUMP.
        Returns the path written, or None when the recorder is off or no
        dump path is configured.  The dump embeds the metrics-registry
        snapshot — the "recent metric samples" leg of the ring — and the
        ring's drop count, so a truncated window reads as truncated."""
        if self.flight is None or self.flight_path is None:
            return None
        from ..utils.checkpoint import atomic_write_json
        from .trace import _jsonable

        payload = {
            "run_id": self.run_id,
            "reason": str(reason),
            "dumped_at": round(float(self.flight._clock()), 6),
            "attrs": {str(k): _jsonable(v) for k, v in attrs.items()},
            "entries": self.flight.entries(),
            "entries_dropped": self.flight.dropped,
            "metrics": (self.registry.snapshot()
                        if self.registry is not None else None),
        }
        atomic_write_json(self.flight_path, payload)
        self.event("FLIGHT_RECORD_DUMP", path=self.flight_path,
                   reason=str(reason), entries=len(payload["entries"]))
        return self.flight_path

    # -- lifecycle ----------------------------------------------------------

    def activate(self):
        """Context manager making this the ACTIVE scope for module-level
        ``emit_event``/``active_obs`` callers (deep seams without a
        threaded handle)."""
        return _activation(self)

    def close(self) -> None:
        """Flush run-end artifacts: the cost-ledger summary
        (PROFILE_SNAPSHOT event + registry mirror), the RUN_END journal
        event, and the Chrome trace (atomic write).  Idempotent — a run
        interrupted between seams may close through more than one
        ``finally``."""
        if self._closed:
            return
        self._closed = True
        if self.cost_ledger is not None:
            snap = self.cost_ledger.snapshot()
            self.cost_ledger.publish(self.registry)
            self.event("PROFILE_SNAPSHOT",
                       executables=snap["executables"],
                       launches=snap["launches"],
                       launch_wall_s=snap["launch_wall_s"],
                       measured_flops_total=snap["measured_flops_total"],
                       achieved_flops_per_sec=snap[
                           "achieved_flops_per_sec"],
                       roofline=snap["roofline"],
                       cost_sources=snap["cost_sources"])
        self.event("RUN_END")
        if self.tracer is not None and self.trace_path is not None:
            self.tracer.save_chrome_trace(self.trace_path)


class _NullObs(Obs):
    """The disabled bundle: one process-wide instance, every operation a
    constant-time no-op (the ISSUE 7 near-zero-disabled-overhead
    contract)."""

    enabled = False

    def __init__(self):
        super().__init__(run_id="run-disabled")

    def span(self, name: str, **attrs):
        return NULL_SPAN_CM

    def record_span(self, name: str, duration_s: float, **attrs) -> None:
        pass

    def event(self, etype: str, **attrs) -> None:
        pass

    def counter(self, name: str, help: str = ""):
        return NULL_INSTRUMENT

    gauge = counter
    histogram = counter

    def activate(self):
        return _NULL_ACTIVATE_CM

    def close(self) -> None:
        pass


NULL_OBS = _NullObs()

# The active-scope stack: appended under ``activate()``, innermost
# last.  PER-THREAD (``threading.local``) — two runs on two threads (a
# sweep while a service warms, two concurrent sweeps) each see only
# their own scope, so a deep seam can never journal thread A's event
# under thread B's run_id.  A worker thread servicing a run it did not
# start (the serve batch worker) re-activates the owning bundle around
# its launches.
_ACTIVE = threading.local()


def _active_stack() -> List[Obs]:
    stack = getattr(_ACTIVE, "stack", None)
    if stack is None:
        stack = _ACTIVE.stack = []
    return stack


@contextlib.contextmanager
def _activation(obs: Obs):
    stack = _active_stack()
    stack.append(obs)
    try:
        yield obs
    finally:
        try:
            stack.remove(obs)
        except ValueError:
            pass


def active_obs() -> Obs:
    """This thread's innermost active bundle, or ``NULL_OBS``."""
    stack = getattr(_ACTIVE, "stack", None)
    return stack[-1] if stack else NULL_OBS


def emit_event(etype: str, **attrs) -> None:
    """Journal one event into the active scope — the hook for seams too
    deep to thread an ``Obs`` handle (retry backoffs, checksum
    failures, signal-flag polls).  One attribute read plus a truth-test
    when no run is active."""
    stack = getattr(_ACTIVE, "stack", None)
    if not stack:
        return
    stack[-1].event(etype, **attrs)


def active_span(name: str, **attrs):
    """A span on the active scope (cached null CM when none)."""
    stack = getattr(_ACTIVE, "stack", None)
    if not stack:
        return NULL_SPAN_CM
    return stack[-1].span(name, **attrs)


def build_obs(config: Optional[ObsConfig]) -> Obs:
    """Materialize a bundle from a config: ``None`` or
    ``enabled=False`` give ``NULL_OBS``."""
    if config is None or not config.enabled:
        return NULL_OBS
    run_id = config.run_id if config.run_id is not None else new_run_id()
    tracer = (Tracer(run_id=run_id,
                     device_trace_dir=config.device_trace_dir)
              if config.trace else None)
    registry = MetricsRegistry() if config.metrics else None
    journal = (EventJournal(config.journal_path, run_id)
               if config.journal_path is not None else None)
    cost_ledger = telemetry = None
    if config.profile:
        from .profile import CostLedger, DeviceTelemetry

        cost_ledger = CostLedger()
        telemetry = DeviceTelemetry()
    flight = FlightRecorder(limit=config.flight_limit)
    flight_path = config.flight_path
    if flight_path is None and config.journal_path is not None:
        flight_path = str(config.journal_path) + ".flight.json"
    obs = Obs(run_id=run_id, tracer=tracer, registry=registry,
              journal=journal, trace_path=config.trace_path,
              cost_ledger=cost_ledger, telemetry=telemetry,
              flight=flight, flight_path=flight_path)
    obs.event("RUN_START")
    return obs


def resolve_obs(obj) -> tuple:
    """Normalize a caller-facing ``obs`` argument to ``(Obs, owned)``:

    * ``None`` → ``(NULL_OBS, False)``;
    * an ``ObsConfig`` → a freshly built bundle, OWNED by the callee
      (who must ``close()`` it when the run ends);
    * an ``Obs`` → passed through un-owned (the caller's run spans
      several subsystems — e.g. the bench tracing sweep AND serve under
      one run_id — and closes it itself)."""
    if obj is None:
        return NULL_OBS, False
    if isinstance(obj, ObsConfig):
        obs = build_obs(obj)
        return obs, obs is not NULL_OBS
    if isinstance(obj, Obs):
        return obj, False
    raise TypeError(
        f"obs must be None, an ObsConfig, or an Obs bundle; got "
        f"{type(obj).__name__}")
