"""Structured event journal: typed lifecycle events as append-only JSONL
(ISSUE 7).

Every seam the previous PRs built — quarantine (PR 1), bucket launches
and warm starts (PR 2), preemption/retry/resume (PR 3), serving paths
and deadlines (PR 4/6), precision escalation (PR 5), certification and
corruption eviction (PR 6) — used to announce itself through
``warnings.warn`` / ``logging`` prose: human-greppable, machine-opaque.
The journal gives each of those seams ONE typed, machine-readable line:

    {"ts": ..., "run_id": "run-...", "event": "QUARANTINE",
     "cell": 7, "crra": 5.0, ...}

* **Typed**: ``event`` must be a member of ``EVENT_TYPES`` — an unknown
  type raises at the emit site, so event names cannot drift per caller
  (the contract ``scripts/check_obs_events.py`` lints and
  ``tests/test_obs.py`` exercises drill-by-drill).
* **Append-only, crash-consistent**: lines go through
  ``utils.checkpoint.append_jsonl`` — one ``os.write`` of one complete
  newline-terminated line per event to an ``O_APPEND`` descriptor.  A
  SIGKILL can tear at most the final line, which ``read_journal`` (and
  ``utils.timing.read_records_jsonl``) detect and skip; it can never
  interleave or truncate earlier events.
* **Run-scoped**: every line carries the ``run_id`` shared with the
  trace and the metrics snapshot, so one grep correlates a quarantined
  cell with its bucket span and its retry counter.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Optional

# The typed lifecycle vocabulary (DESIGN §10).  Grouped by the subsystem
# that owns the seam; adding a member is an API change — document it in
# DESIGN §10 and cover it in tests/test_obs.py.
EVENT_TYPES = (
    # run lifecycle (obs runtime)
    "RUN_START", "RUN_END",
    # sweep scheduler (parallel.sweep)
    "BUCKET_LAUNCH", "QUARANTINE", "SDC_SUSPECTED",
    # resilience layer (utils.resilience)
    "RETRY_TRANSIENT", "INTERRUPTED", "RESUME_RESTORE",
    # precision ladder (DESIGN §5)
    "PRECISION_ESCALATED",
    # integrity / certification (verify, utils.fingerprint)
    "CERT_FAILED", "INTEGRITY_FAILED",
    # serving (serve.service / serve.store)
    "STORE_EVICT_CORRUPT", "DEADLINE_EXCEEDED",
    # serving overload layer (ISSUE 8, serve.service / serve.overload):
    # fail-fast admission rejection, priority displacement of a queued
    # pending, a degraded nearest-neighbor answer, breaker transitions
    # (OPEN covers reopen-after-failed-probe), the half-open probe
    # admission, and each fast-fail on an already-open breaker
    "OVERLOADED", "LOAD_SHED", "DEGRADED_ANSWER",
    "CIRCUIT_OPEN", "CIRCUIT_PROBE", "CIRCUIT_CLOSE", "CIRCUIT_REJECT",
    # typed solver divergence escaping to a caller (models, facade)
    "SOLVER_DIVERGED",
    # fleet tier (ISSUE 15, serve.store / serve.service): the claim/
    # lease election (one FLEET_CLAIM per lease won), the exactly-once
    # publish completing a claim (FLEET_PUBLISH carries the solving
    # query's speculative flag — prefetch attribution), a stale lease
    # broken past its TTL (crashed-winner reclaim), and each
    # speculative neighbor query issued by the prefetcher
    "FLEET_CLAIM", "FLEET_PUBLISH", "FLEET_LEASE_RECLAIM",
    "PREFETCH_ISSUED",
    # fleet robustness tier (ISSUE 16, serve.{store,chaos,fleet,
    # loadgen}): a chaos-drill fault actually FIRING (the detection
    # ledger's injected side), a hedged read issued for a known-
    # published fingerprint / the hedge's answer winning the race, a
    # worker entering or leaving the pool mid-load (the elasticity
    # schedule), and a lease-backend operation degrading typed (substrate
    # fault, injected partition, or a held lease found lost/stolen)
    "FLEET_CHAOS_INJECT", "FLEET_HEDGE_ISSUED", "FLEET_HEDGE_WON",
    "WORKER_JOIN", "WORKER_LEAVE", "LEASE_BACKEND_FAULT",
    # performance-observability tier (ISSUE 10, obs.profile/obs.regress):
    # the run's cost-ledger summary at close, a bench-regression sentinel
    # finding graded REGRESSED, the flight-recorder crash artifact
    # written after a quarantine-ladder exhaustion, and a per-device
    # memory high-water mark growing
    "PROFILE_SNAPSHOT", "REGRESSION_FLAGGED",
    "FLIGHT_RECORD_DUMP", "DEVICE_MEM_HIGH_WATER",
    # surrogate tier (ISSUE 17, serve.{service,store,surrogate,
    # cellindex}): an off-lattice query answered by the certified
    # local-linear surrogate (bound + donors attached), a surrogate-
    # eligible query escalated to a real solve (too few / too far
    # donors, bound over budget, or the seeded audit draw), an
    # escalated solve published as a parameter-space refinement point
    # (audit escalations carry the a-posteriori bound check), and the
    # store's cell index (re)built from the metadata tier (restart,
    # scale change, or occupancy-driven rewidth)
    "SURROGATE_SERVED", "SURROGATE_ESCALATED", "LATTICE_REFINED",
    "INDEX_REBUILD",
    # durability / disaster-recovery tier (ISSUE 18, serve.{wal,
    # replicated,store} + utils.checkpoint): a CAS replica recovering
    # its version map from WAL+snapshot at start (torn tails and
    # applied-record counts attached), a snapshot compaction truncating
    # the WAL, anti-entropy repair pushing a rejoined/stale replica
    # back to the quorum's state, a replicated backend losing its
    # majority (typed CoordinationUnavailable at the caller), the
    # solution store degrading to memory-only after a failed disk
    # publish, and a disk write failing typed (injected ENOSPC/EIO or
    # a real full/failing disk)
    "WAL_REPLAY", "SNAPSHOT_COMPACT", "REPLICA_RESYNC", "QUORUM_LOST",
    "STORE_DEGRADED", "DISK_FAULT",
)


def _jsonable(v):
    from .trace import _jsonable as coerce

    return coerce(v)


class EventJournal:
    """Append-only JSONL journal for one run.

    ``emit`` is thread-safe and durable per event (no buffering: a
    lifecycle event is rare and must survive the preemption it often
    describes).  The file may hold several runs' events (appends never
    truncate) — readers filter by ``run_id``."""

    def __init__(self, path: str, run_id: str, clock=time.time):
        self.path = str(path)
        self.run_id = str(run_id)
        self._clock = clock
        self._lock = threading.Lock()
        self.emitted = 0

    def emit(self, etype: str, **attrs) -> dict:
        """Append one typed event; returns the record written.  Raises
        ``ValueError`` on an event type outside ``EVENT_TYPES`` — the
        journal's vocabulary is closed by design."""
        if etype not in EVENT_TYPES:
            raise ValueError(
                f"unknown journal event type {etype!r}; add it to "
                f"obs.journal.EVENT_TYPES if it is a new lifecycle seam "
                f"(known: {', '.join(EVENT_TYPES)})")
        rec = {"ts": round(float(self._clock()), 6),
               "run_id": self.run_id, "event": etype}
        for k, v in attrs.items():
            rec[str(k)] = _jsonable(v)
        from ..utils.checkpoint import append_jsonl

        with self._lock:
            append_jsonl(self.path, [json.dumps(rec)])
            self.emitted += 1
        return rec


def read_journal(path: str, run_id: Optional[str] = None,
                 event: Optional[str] = None) -> list:
    """Read a journal back as a list of dicts, optionally filtered by
    ``run_id`` and/or ``event`` type.

    A line that does not parse is SKIPPED, not fatal
    (``utils.checkpoint.read_jsonl_tolerant`` — the shared reader half
    of ``append_jsonl``'s crash contract): a journal must stay readable
    after the very preemption it recorded.  Skips are warned with a
    count, never silent.  A missing file reads as an empty journal."""
    import warnings

    from ..utils.checkpoint import read_jsonl_tolerant

    try:
        records, bad = read_jsonl_tolerant(path)
    except OSError:
        return []
    if bad:
        warnings.warn(
            f"event journal {path}: skipped {bad} unparseable line(s) "
            "(torn tail from a hard kill, or external corruption)",
            stacklevel=2)
    return [rec for rec in records
            if (run_id is None or rec.get("run_id") == run_id)
            and (event is None or rec.get("event") == event)]
