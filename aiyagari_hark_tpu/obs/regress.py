"""Bench-regression sentinel: robust baselines over the committed
BENCH history, typed severity-ordered findings (ISSUE 10, DESIGN §10b).

The repo commits one bench record per round (``BENCH_r01.json`` ...) and
nothing watches the trajectory: a PR that silently halves
``egm_gridpoints_per_sec_per_chip`` lands green.  The sentinel closes
that hole with the same discipline ``solver_health`` gave numeric
failure — a CLOSED severity vocabulary and one declared
direction-of-goodness per metric:

* per metric, the baseline is the MEDIAN of the last ``window`` prior
  values and the noise band is ``max(IQR, rel_floor * |baseline|,
  abs_floor)`` — robust to the committed history's machine-to-machine
  swings (the r02 CPU round is 6x faster than r03's; a mean would be
  garbage);
* a value flags only when it is worse than BOTH the baseline+band AND
  the worst value history already contains (a number no worse than a
  committed round is by construction not a new regression);
* severity: ``OK < NOISE < REGRESSED`` — NOISE is outside the band but
  under ``regress_frac`` relative movement (suspicious, not
  actionable); REGRESSED is a >= ``regress_frac`` (default 10%) move in
  the bad direction, so the ISSUE 10 acceptance drill (a 20% injected
  slowdown) always lands REGRESSED on a stable metric;
* every numeric bench field must resolve in the direction-of-goodness
  table below (``direction_of_goodness(field, strict=True)`` raises
  ``UnknownMetricError`` otherwise — ``tests/test_regress.py`` pins
  completeness over the whole committed history), so a new bench field
  cannot ride along unclassified.

``scripts/check_bench_regress.py`` runs the sentinel in tier-1 against
the committed history; a REGRESSED finding under an active obs scope
also journals a typed ``REGRESSION_FLAGGED`` event.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

# Severity order (matching solver_health's small-int convention: higher
# is worse, comparisons are meaningful).
OK = 0
NOISE = 1
REGRESSED = 2
SEVERITY_NAMES = {OK: "OK", NOISE: "NOISE", REGRESSED: "REGRESSED"}

# Directions of goodness.
UP = "up"          # bigger is better (throughput, speedup, MFU)
DOWN = "down"      # smaller is better (walls, skew, error margins)
NEUTRAL = "neutral"  # informational (device counts, sizes, ids)

# -- the direction-of-goodness table (ONE place, DESIGN §10b) ---------------
# Explicit field names first; fields not listed resolve through the
# suffix rules below.  ``last_tpu.<field>`` recurses on ``<field>``.
DIRECTION_EXPLICIT: Dict[str, str] = {
    "value": DOWN,                 # the headline sweep wall (seconds)
    "vs_baseline": UP,             # speedup factor over the reference
    "mfu_pct": UP,                 # bare spelling ("_mfu_pct" suffixed
    #                                fields resolve via the suffix rule)
    "iteration_skew": DOWN,
    "iteration_skew_scheduled": DOWN,
    "scheduled_iteration_skew": DOWN,
    "n_devices": NEUTRAL,
    "n_buckets": NEUTRAL,
    "lanes": NEUTRAL,
    "backend_attempts": NEUTRAL,
    "exact_bits": NEUTRAL,
    # multi-chip scaling leg (ISSUE 11, bench --chips-scaling): the
    # device-count-suffixed speedups defeat the _speedup suffix rule
    # (they end in _Ndev), so they are declared here — the sentinel
    # grades the chips_* record from its first committed round instead
    # of raising unclassified.  chips_cells_per_sec_{N}dev needs no
    # entry: the 'cells_per_sec' affix rule already resolves it UP.
    "chips_speedup_2dev": UP,
    "chips_speedup_4dev": UP,
    "chips_speedup_8dev": UP,
    "chips_mem_stats_devices": NEUTRAL,
    # state-axis sharding leg (ISSUE 20, bench --state-scaling): the
    # shard-count-suffixed throughputs defeat the _per_sec suffix rule
    # (they end in _Mshard), so they are declared here, UP.  The
    # per-device RESIDENT RATIO (sharded/replicated model resident at
    # the largest grid, ~1/M) is the tentpole's whole point — DOWN,
    # overriding the neutral _ratio suffix rule; likewise the ledger-
    # sourced sharding overhead share overrides the neutral _frac rule,
    # DOWN.  The drill's grid size and the device-with-memory-stats
    # count are facts, NEUTRAL; drift resolves DOWN via the _bp suffix
    # and residents/budget NEUTRAL via _bytes.
    "state_gridpoints_per_sec_1shard": UP,
    "state_gridpoints_per_sec_2shard": UP,
    "state_gridpoints_per_sec_4shard": UP,
    "state_resident_ratio": DOWN,
    "state_collective_share_frac": DOWN,
    "state_overflow_grid": NEUTRAL,
    "state_mem_stats_devices": NEUTRAL,
    # grid-compaction leg (ISSUE 12, bench --compaction-smoke): the
    # sentinel grades the grid_* record from its first committed round —
    # gridpoints DOWN is good (the compaction's whole point), reductions
    # and certified counts UP.  grid_total_inner_steps_* and
    # grid_*_wall_s resolve through the _steps/_s suffix rules;
    # grid_r_drift_max_bp through _max_bp.
    "grid_points_reference": NEUTRAL,     # config constant, not a metric
    "grid_points_compact": DOWN,
    "grid_total_inner_steps_reference": NEUTRAL,   # baseline side
    "grid_total_inner_steps_compact": DOWN,
    "grid_effective_gridpoint_steps_reference": NEUTRAL,
    "grid_effective_gridpoint_steps_compact": DOWN,
    "grid_point_reduction": UP,
    "grid_step_reduction": UP,
    "grid_wall_reduction": UP,
    "grid_effective_reduction": UP,
    "grid_cells_certified": UP,
    "grid_escalations": DOWN,
    "grid_knee": NEUTRAL,
    # fused-kernel leg (ISSUE 13, bench --kernel-smoke): the sentinel
    # grades the kernel_* record from its first committed round.  Walls
    # and drift resolve through the _wall_s/_max_bp suffix rules and
    # throughputs through _per_sec_per_chip; the remaining fields are
    # declared here — reductions and certified counts UP, escalations
    # DOWN, launch counts informational.
    "kernel_cells": NEUTRAL,
    "kernel_wall_reduction": UP,
    "kernel_cells_certified": UP,
    "kernel_escalations": DOWN,
    "kernel_drill_escalations": NEUTRAL,   # the injected drill's count
    #                                        is a contract, not a trend
    "kernel_drill_max_knot_diff": NEUTRAL,  # bounded by the drill's own
    #                                         acceptance, not a trend
    "kernel_fused_executables": NEUTRAL,
    "kernel_fused_launches": NEUTRAL,
    # fleet serving leg (ISSUE 15, bench --fleet-smoke) + the serve
    # snapshot's prefetch/fleet counters (they ride every serve_* record
    # via ``ServeMetrics.snapshot``).  The load-bearing declarations:
    # the DEDUP RATIO is cold solves / distinct cold fingerprints — 1.0
    # is exactly-once, every increase is duplicated solve work, so DOWN
    # (the explicit entry overrides the neutral ``_ratio`` suffix rule);
    # leaked leases and unresolved arrivals are failures of the
    # protocol, DOWN from the first committed record; prefetch
    # CONVERSIONS are the prefetcher earning its solves, UP.  Fleet
    # p50/p99 fields resolve through the ``_ms`` suffix rule and the
    # wall through ``_s``.
    "fleet_dedup_ratio": DOWN,
    "fleet_leases_leaked": DOWN,
    "fleet_unresolved": DOWN,
    "fleet_prefetch_issued": NEUTRAL,
    "fleet_prefetch_converted": UP,
    "fleet_remote_hits": UP,
    "fleet_claims_won": NEUTRAL,
    "fleet_claims_lost": NEUTRAL,
    "fleet_publishes": NEUTRAL,
    "fleet_lease_reclaims": DOWN,
    "fleet_workers": NEUTRAL,
    "fleet_requests": NEUTRAL,
    "fleet_served": UP,
    "fleet_served_hit": NEUTRAL,     # traffic-mix facts, not goodness
    "fleet_served_near": NEUTRAL,
    "fleet_served_cold": NEUTRAL,
    "fleet_cold_solves": NEUTRAL,
    "fleet_distinct_fingerprints": NEUTRAL,
    "fleet_drill_rc": NEUTRAL,
    "fleet_value_mismatches": DOWN,
    "fleet_value_divergence": DOWN,
    "fleet_seeded_compares": NEUTRAL,
    # chaos smoke (ISSUE 16, bench --chaos-smoke): graded from their
    # FIRST committed record.  AVAILABILITY is served/submitted under
    # churn + drills — the headline robustness number, UP.  Leaked
    # leases, unresolved arrivals, value divergence, and recovery-phase
    # duplicate publishes are protocol violations, DOWN from record one.
    # The drilled dedup ratio excludes the drills' EXPECTED duplicates
    # (a stalled winner's late publish, a skew-forced double election)
    # — what remains must stay 1.0, so any increase is a real
    # exactly-once regression, DOWN.  Reclaims/kills/joins/leaves are
    # the drill script's own doing, facts not goodness — NEUTRAL;
    # injected/detected counts resolve NEUTRAL via the affix rules and
    # are pinned equal by the acceptance gate instead.  Hedge counts
    # are traffic facts, NEUTRAL (the hedge's latency win shows up in
    # the p99 fields, which resolve DOWN via the _ms suffix).
    "chaos_availability": UP,
    "chaos_dedup_ratio": DOWN,
    "chaos_recovery_dup_publishes": DOWN,
    "chaos_leases_leaked": DOWN,
    "chaos_unresolved": DOWN,
    "chaos_value_divergence": DOWN,
    "chaos_reclaims": NEUTRAL,
    "chaos_workers": NEUTRAL,
    "chaos_arrivals": NEUTRAL,
    "chaos_served": UP,
    "chaos_joins": NEUTRAL,
    "chaos_leaves": NEUTRAL,
    "chaos_kills": NEUTRAL,
    "chaos_hedges_issued": NEUTRAL,
    "chaos_hedges_won": NEUTRAL,
    "chaos_value_mismatches": DOWN,
    "chaos_seeded_compares": NEUTRAL,
    "chaos_recovery_served": NEUTRAL,
    "chaos_backend_faults": NEUTRAL,  # injected partitions land here
    # disaster-recovery smoke (ISSUE 18, bench --dr-smoke): the
    # replicated-CAS fleet's full-fleet-SIGKILL drill.  The dedup ratio
    # (expected drill duplicates excluded) must stay 1.0 — any rise is
    # an exactly-once regression, DOWN.  Leaked leases, unresolved
    # arrivals, value mismatches/divergence, and per-replica recovered-
    # state mismatches are protocol violations, DOWN from record one.
    # WAL replays / compactions / reclaims / recovered-key counts are
    # facts of the script, NEUTRAL; injected/detected counts resolve
    # NEUTRAL via the affix rules and are pinned equal by the
    # acceptance gate; the recovery wall resolves DOWN via ``_wall_s``.
    "dr_replicas": NEUTRAL,
    "dr_workers": NEUTRAL,
    "dr_arrivals": NEUTRAL,
    "dr_served": UP,
    "dr_dedup_ratio": DOWN,
    "dr_unresolved": DOWN,
    "dr_leases_leaked": DOWN,
    "dr_value_mismatches": DOWN,
    "dr_value_divergence": DOWN,
    "dr_seeded_compares": NEUTRAL,
    "dr_state_mismatches": DOWN,
    "dr_recovered_keys": NEUTRAL,
    "dr_wal_replays": NEUTRAL,
    "dr_snapshot_compacts": NEUTRAL,
    "dr_reclaims": NEUTRAL,
    "serve_prefetch_issued": NEUTRAL,
    "serve_prefetch_converted": UP,
    "serve_prefetch_suppressed": NEUTRAL,
    # surrogate tier + cell index (ISSUE 17, bench --surrogate-smoke;
    # the surrogate_* snapshot counters also ride every serve record via
    # ``ServeMetrics.snapshot``).  HIT RATE is the tier earning its keep
    # (answers served without a solve), UP; the ESCALATION RATE is the
    # fraction of surrogate-eligible queries that fell back to a cold
    # solve, DOWN — together with the bound percentiles (the tier's own
    # claimed error, DOWN: a tighter model is a better model) they are
    # the headline numbers.  Audit failures are answers outside their
    # own certified bound, DOWN from record one.  Audits and lattice
    # refinements are policy-driven facts, NEUTRAL.  INDEX speedups are
    # the sublinear store index's whole point, UP (the scale-suffixed
    # names defeat the ``_speedup`` suffix rule, same as chips_*);
    # linear-scan timings are the baseline side, NEUTRAL.
    "surrogate_hit_rate": UP,
    "surrogate_escalation_rate": DOWN,
    "surrogate_escalations": DOWN,
    "surrogate_audits": NEUTRAL,
    "surrogate_audit_failures": DOWN,
    "surrogate_refinements": NEUTRAL,
    "surrogate_bound_p50": DOWN,
    "surrogate_bound_p95": DOWN,
    "surrogate_bound_max": DOWN,
    "surrogate_err_max": DOWN,
    "surrogate_queries": NEUTRAL,
    "surrogate_served": UP,
    "surrogate_refined_published": NEUTRAL,
    "surrogate_events_served": NEUTRAL,
    "surrogate_events_escalated": NEUTRAL,
    "index_entries": NEUTRAL,
    "index_rebuilds": NEUTRAL,
    "index_speedup_1e4": UP,
    "index_speedup_5e4": UP,
    "index_grid_ms_1e4": DOWN,
    "index_grid_ms_5e4": DOWN,
    "index_linear_ms_1e4": NEUTRAL,
    "index_linear_ms_5e4": NEUTRAL,
}

# Suffix/affix rules, first match wins.  Kept coarse on purpose: bench
# fields are named by convention (units in the suffix), and the rules
# make the convention load-bearing.
DIRECTION_SUFFIX_RULES: Tuple[Tuple[str, str], ...] = (
    ("_wall_s", DOWN), ("_walls_s", DOWN), ("_seconds", DOWN),
    ("_wait_s", DOWN), ("_roundtrip_s", DOWN), ("_s", DOWN),
    ("_ms", DOWN), ("_us", DOWN),
    ("_per_sec_per_chip", UP), ("_per_sec", UP), ("_per_chip", UP),
    ("_mfu_pct", UP), ("_speedup", UP), ("_hit_rate", UP),
    ("_max_bp", DOWN), ("_bp", DOWN), ("_skew", DOWN),
    ("_overhead_frac", DOWN), ("_frac", NEUTRAL),
    ("_pct", NEUTRAL), ("_ratio", NEUTRAL),
    ("_count", NEUTRAL), ("_cells", NEUTRAL), ("_events", NEUTRAL),
    ("_bytes", NEUTRAL), ("_evals", DOWN), ("_steps", DOWN),
    ("_iters", DOWN), ("_compiles", DOWN), ("_misses", DOWN),
    ("_retries", DOWN), ("_errors", DOWN), ("_violations", DOWN),
    ("_failures", DOWN), ("_expirations", DOWN), ("_evictions", DOWN),
)
# Prefix rules (checked after suffixes): counts and ids are neutral.
DIRECTION_PREFIX_RULES: Tuple[Tuple[str, str], ...] = (
    ("n_", NEUTRAL), ("num_", NEUTRAL),
)
# Affix (anywhere) rules, last resort before UnknownMetricError.
DIRECTION_AFFIX_RULES: Tuple[Tuple[str, str], ...] = (
    ("mfu", UP), ("flops_per_sec", UP), ("cells_per_sec", UP),
    ("p50", DOWN), ("p95", DOWN), ("p99", DOWN),
    ("wall", DOWN), ("compile", DOWN), ("overhead", DOWN),
    ("drift", DOWN), ("residual", DOWN), ("corrupt", DOWN),
    ("injected", NEUTRAL), ("detected", NEUTRAL),
)


class UnknownMetricError(KeyError):
    """A numeric bench field with no declared direction of goodness —
    the table above must grow an entry (or the field a conventional
    suffix) before the sentinel can grade it."""


def direction_of_goodness(field: str, strict: bool = True) -> str:
    """Resolve one bench field to ``"up"``/``"down"``/``"neutral"``.

    ``strict=True`` raises ``UnknownMetricError`` on an unclassifiable
    field (the completeness contract tests pin); ``strict=False``
    degrades to NEUTRAL — the sentinel's runtime choice, so a brand-new
    field shows up as ungraded rather than crashing CI (the strict test
    is what forces the table entry)."""
    name = field.rsplit(".", 1)[-1]   # "last_tpu.compile_s" -> "compile_s"
    if name in DIRECTION_EXPLICIT:
        return DIRECTION_EXPLICIT[name]
    for suffix, direction in DIRECTION_SUFFIX_RULES:
        if name.endswith(suffix):
            return direction
    for prefix, direction in DIRECTION_PREFIX_RULES:
        if name.startswith(prefix):
            return direction
    for affix, direction in DIRECTION_AFFIX_RULES:
        if affix in name:
            return direction
    if strict:
        raise UnknownMetricError(
            f"bench field {field!r} has no direction of goodness; add it "
            "to obs.regress.DIRECTION_EXPLICIT (or use a conventional "
            "suffix: _wall_s/_per_sec/_mfu_pct/...)")
    return NEUTRAL


def flatten_record(record: dict, prefix: str = "") -> Dict[str, float]:
    """Numeric scalar fields of one bench record, nested dicts flattened
    with dotted keys (``last_tpu.compile_s``); bools, strings, lists
    (e.g. ``lanes_scaling``) are skipped — the sentinel grades scalars."""
    out: Dict[str, float] = {}
    for k, v in record.items():
        key = prefix + str(k)
        if isinstance(v, bool):
            continue
        if isinstance(v, dict):
            out.update(flatten_record(v, key + "."))
        elif isinstance(v, (int, float)):
            out[key] = float(v)
    return out


def _median(vals: Sequence[float]) -> float:
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def _iqr(vals: Sequence[float]) -> float:
    """Interquartile range by linear interpolation (numpy's default
    percentile method, stdlib-only so the sentinel can run anywhere)."""
    s = sorted(vals)
    n = len(s)
    if n < 2:
        return 0.0

    def q(p: float) -> float:
        idx = p * (n - 1)
        lo = int(idx)
        hi = min(lo + 1, n - 1)
        return s[lo] + (s[hi] - s[lo]) * (idx - lo)

    return q(0.75) - q(0.25)


@dataclasses.dataclass(frozen=True)
class MetricFinding:
    """One metric's grade against its robust baseline."""

    metric: str
    severity: int                 # OK < NOISE < REGRESSED
    direction: str                # up | down | neutral
    value: Optional[float]
    baseline: Optional[float]     # median of the prior window
    band: Optional[float]         # noise half-width around the baseline
    worst_prior: Optional[float]  # worst value history already contains
    delta_frac: Optional[float]   # signed relative move, + = worse
    n_prior: int = 0
    note: str = ""

    @property
    def severity_name(self) -> str:
        return SEVERITY_NAMES[self.severity]


@dataclasses.dataclass
class RegressionReport:
    """Severity-ordered findings for the latest bench record against its
    history.  ``worst`` is the report's headline grade; ``regressed()``
    / ``noisy()`` slice by severity; ``summary()`` renders the one-line
    digest the CI log shows."""

    findings: List[MetricFinding]
    n_records: int
    latest_round: str
    baseline_rounds: List[str]
    unknown_fields: List[str]

    @property
    def worst(self) -> int:
        return max((f.severity for f in self.findings), default=OK)

    def regressed(self) -> List[MetricFinding]:
        return [f for f in self.findings if f.severity == REGRESSED]

    def noisy(self) -> List[MetricFinding]:
        return [f for f in self.findings if f.severity == NOISE]

    def summary(self) -> str:
        n_reg, n_noise = len(self.regressed()), len(self.noisy())
        return (f"bench-regress [{self.latest_round} vs "
                f"{len(self.baseline_rounds)} prior]: "
                f"{SEVERITY_NAMES[self.worst]} "
                f"({n_reg} regressed, {n_noise} noise, "
                f"{len(self.findings) - n_reg - n_noise} ok"
                + (f", {len(self.unknown_fields)} ungraded"
                   if self.unknown_fields else "") + ")")


def grade_metric(metric: str, value: float, priors: Sequence[float],
                 direction: Optional[str] = None,
                 window: int = 5, rel_floor: float = 0.05,
                 abs_floor: float = 1e-12,
                 regress_frac: float = 0.10) -> MetricFinding:
    """Grade one metric value against its prior history (the unit the
    report loops; exposed for tests to pin the severity rules)."""
    if direction is None:
        direction = direction_of_goodness(metric, strict=False)
    priors = [float(p) for p in priors][-int(window):]
    n_prior = len(priors)
    if direction == NEUTRAL or n_prior < 2:
        note = ("neutral" if direction == NEUTRAL
                else f"insufficient history ({n_prior} prior)")
        return MetricFinding(metric, OK, direction, value,
                             _median(priors) if priors else None,
                             None, None, None, n_prior, note)
    baseline = _median(priors)
    band = max(_iqr(priors), rel_floor * abs(baseline), abs_floor)
    worst_prior = max(priors) if direction == DOWN else min(priors)
    # signed badness: positive = moved in the bad direction
    bad_delta = (value - baseline) if direction == DOWN \
        else (baseline - value)
    delta_frac = (bad_delta / abs(baseline)) if baseline else None
    beyond_band = bad_delta > band
    beyond_worst = (value > worst_prior if direction == DOWN
                    else value < worst_prior)
    if not (beyond_band and beyond_worst):
        return MetricFinding(metric, OK, direction, value, baseline,
                             band, worst_prior, delta_frac, n_prior)
    severity = (REGRESSED if delta_frac is not None
                and delta_frac >= regress_frac else NOISE)
    return MetricFinding(metric, severity, direction, value, baseline,
                         band, worst_prior, delta_frac, n_prior)


def evaluate_history(history: Sequence[Tuple[str, dict]],
                     window: int = 5, rel_floor: float = 0.05,
                     regress_frac: float = 0.10) -> RegressionReport:
    """The sentinel: grade the LAST record of ``history`` (a sequence of
    ``(round_name, record_dict)``, oldest first) against the robust
    baseline of the earlier ones, emitting ``REGRESSION_FLAGGED`` into
    the active obs scope for every REGRESSED finding."""
    from .runtime import emit_event

    if not history:
        return RegressionReport([], 0, "<none>", [], [])
    flat = [(name, flatten_record(rec)) for name, rec in history]
    latest_name, latest = flat[-1]
    prior_rounds = [name for name, _ in flat[:-1]]
    findings: List[MetricFinding] = []
    unknown: List[str] = []
    for metric in sorted(latest):
        try:
            direction = direction_of_goodness(metric, strict=True)
        except UnknownMetricError:
            unknown.append(metric)
            direction = NEUTRAL
        priors = [f[metric] for _, f in flat[:-1] if metric in f]
        finding = grade_metric(metric, latest[metric], priors,
                               direction=direction, window=window,
                               rel_floor=rel_floor,
                               regress_frac=regress_frac)
        findings.append(finding)
        if finding.severity == REGRESSED:
            emit_event("REGRESSION_FLAGGED", metric=metric,
                       value=finding.value, baseline=finding.baseline,
                       band=finding.band,
                       delta_frac=finding.delta_frac,
                       direction=finding.direction,
                       latest_round=latest_name)
    findings.sort(key=lambda f: (-f.severity,
                                 -(f.delta_frac or 0.0), f.metric))
    return RegressionReport(findings, len(flat), latest_name,
                            prior_rounds, unknown)


def load_bench_history(repo_dir: str) -> List[Tuple[str, dict]]:
    """The committed ``BENCH_r*.json`` history, oldest first, as
    ``(round, record)`` pairs.  Each file wraps the bench's JSON record
    under ``"parsed"`` (None when that round's bench failed — skipped,
    the sentinel grades measurements, not absences)."""
    import glob
    import json
    import os
    import re

    out: List[Tuple[str, dict]] = []
    paths = glob.glob(os.path.join(repo_dir, "BENCH_r*.json"))

    def round_key(p: str) -> int:
        m = re.search(r"BENCH_r(\d+)\.json$", p)
        return int(m.group(1)) if m else 0

    for path in sorted(paths, key=round_key):
        with open(path) as fh:
            wrapper = json.load(fh)
        rec = wrapper.get("parsed")
        if isinstance(rec, dict):
            out.append((os.path.basename(path)[len("BENCH_"):-len(".json")],
                        rec))
    return out
