"""Typed metrics registry: counters, gauges, histograms (ISSUE 7).

One process-wide vocabulary for the numbers the framework already
counts in four disconnected shapes — ``serve.ServeMetrics`` fields,
``utils.timing.CompileCounter`` totals, per-``SweepResult`` retry /
escalation / SDC counters, bench record scalars.  Existing dataclasses
keep their public APIs; they MIRROR into a registry
(``ServeMetrics.publish``, the sweep's post-solve mirror) so one
snapshot answers "what did this run count" in two standard encodings:

* ``snapshot()`` — a plain JSON dict that round-trips losslessly
  through ``MetricsRegistry.restore`` (the bench's ``obs_*`` record
  rides it);
* ``prometheus_text()`` — the Prometheus exposition format, so the
  ROADMAP item 4 serving tier can expose ``/metrics`` without a new
  encoding.

Instruments are created get-or-create by name (``registry.counter``)
and are thread-safe; a name re-used with a different type raises — a
counter silently shadowed by a gauge is exactly the class of drift
this module exists to end.  Kept stdlib-only at module scope so the
hot paths that record into it (serve hits budget < 1 ms) never pay a
jax/numpy import.
"""

from __future__ import annotations

import re
import threading
from typing import Dict, Optional, Tuple

# Prometheus metric-name grammar — enforced at creation so a snapshot is
# exposition-valid by construction.
_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")

# Default histogram bounds (seconds): spans the serving hit budget
# (sub-ms) through multi-minute sweep walls.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.005, 0.025, 0.1, 0.5, 2.0, 10.0, 60.0, 300.0)


class Counter:
    """Monotonically non-decreasing count (``inc`` with a negative
    amount raises — that is a gauge's job)."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        amount = float(amount)
        if amount < 0.0:
            raise ValueError(
                f"counter {self.name} cannot decrease (inc({amount})); "
                "use a gauge for values that go down")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """A value that goes up and down (queue depth, overhead fraction,
    last-run wall)."""

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += float(amount)

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics: ``le`` bounds,
    each bucket counts observations <= its bound, plus ``+Inf``)."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Tuple[float, ...] = DEFAULT_BUCKETS):
        self.name = name
        self.help = help
        self.bounds = tuple(sorted(float(b) for b in buckets))
        if not self.bounds:
            raise ValueError(f"histogram {name} needs >= 1 bucket bound")
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.bounds) + 1)   # [+Inf] last
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self._sum += value
            self._count += 1
            for i, b in enumerate(self.bounds):
                if value <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def cumulative_counts(self) -> list:
        """Counts per ``le`` bound, cumulative, ``+Inf`` last."""
        with self._lock:
            out, acc = [], 0
            for c in self._counts:
                acc += c
                out.append(acc)
            return out


class MetricsRegistry:
    """Named instrument registry with JSON and Prometheus export.

    ``counter``/``gauge``/``histogram`` are get-or-create: repeated
    calls with the same name return the same instrument; the same name
    with a different type raises ``ValueError``."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def _get_or_create(self, name: str, factory, kind: str):
        if not _NAME_RE.match(name):
            raise ValueError(
                f"metric name {name!r} is not Prometheus-valid "
                "([a-zA-Z_:][a-zA-Z0-9_:]*)")
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = factory()
            elif m.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {kind}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(name, lambda: Counter(name, help),
                                   "counter")

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(name, lambda: Gauge(name, help),
                                   "gauge")

    def histogram(self, name: str, help: str = "",
                  buckets: Tuple[float, ...] = DEFAULT_BUCKETS
                  ) -> Histogram:
        return self._get_or_create(
            name, lambda: Histogram(name, help, buckets), "histogram")

    def names(self) -> list:
        with self._lock:
            return sorted(self._metrics)

    # -- export -------------------------------------------------------------

    def snapshot(self) -> dict:
        """Every instrument as one JSON-able dict, keyed by name.  The
        inverse is ``restore``: ``restore(snapshot()).snapshot()`` is
        equal — the round-trip contract the ``--obs-smoke`` asserts."""
        out = {}
        with self._lock:
            metrics = dict(self._metrics)
        for name in sorted(metrics):
            m = metrics[name]
            if m.kind == "histogram":
                out[name] = {"type": "histogram", "help": m.help,
                             "buckets": list(m.bounds),
                             "counts": m.cumulative_counts(),
                             "sum": m.sum, "count": m.count}
            else:
                out[name] = {"type": m.kind, "help": m.help,
                             "value": m.value}
        return out

    @classmethod
    def restore(cls, snapshot: dict) -> "MetricsRegistry":
        """Rebuild a registry from a ``snapshot()`` dict (counts and
        values restored exactly)."""
        reg = cls()
        for name, entry in snapshot.items():
            kind = entry["type"]
            if kind == "counter":
                reg.counter(name, entry.get("help", ""))._value = float(
                    entry["value"])
            elif kind == "gauge":
                reg.gauge(name, entry.get("help", "")).set(entry["value"])
            elif kind == "histogram":
                h = reg.histogram(name, entry.get("help", ""),
                                  tuple(entry["buckets"]))
                cum = list(entry["counts"])
                h._counts = [cum[0]] + [cum[i] - cum[i - 1]
                                        for i in range(1, len(cum))]
                h._sum = float(entry["sum"])
                h._count = int(entry["count"])
            else:
                raise ValueError(f"unknown metric type {kind!r} "
                                 f"for {name!r}")
        return reg

    def prometheus_text(self) -> str:
        """The Prometheus text exposition of every instrument."""
        lines = []
        snap = self.snapshot()
        for name, entry in snap.items():
            if entry.get("help"):
                lines.append(f"# HELP {name} {entry['help']}")
            lines.append(f"# TYPE {name} {entry['type']}")
            if entry["type"] == "histogram":
                for bound, c in zip(entry["buckets"], entry["counts"]):
                    lines.append(f'{name}_bucket{{le="{bound:g}"}} {c}')
                lines.append(f'{name}_bucket{{le="+Inf"}} '
                             f'{entry["counts"][-1]}')
                lines.append(f"{name}_sum {entry['sum']:g}")
                lines.append(f"{name}_count {entry['count']}")
            else:
                lines.append(f"{name} {entry['value']:g}")
        return "\n".join(lines) + ("\n" if lines else "")


# Process-global default registry: ambient consumers (the compile-counter
# mirror, one-off scripts) share it; run-scoped consumers build their own
# via ``ObsConfig`` so two concurrent runs' numbers cannot blend.
_DEFAULT: Optional[MetricsRegistry] = None
_DEFAULT_LOCK = threading.Lock()


def default_registry() -> MetricsRegistry:
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = MetricsRegistry()
        return _DEFAULT


def reset_default_registry() -> None:
    """Drop the process-global registry (tests)."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        _DEFAULT = None
