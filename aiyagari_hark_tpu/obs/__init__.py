"""Unified observability layer (ISSUE 7, DESIGN §10): run-scoped tracing
spans, a typed metrics registry, and a structured event journal, all
correlated by one ``run_id``.

Three pillars, one switch:

* ``trace`` — nestable host-side spans with Chrome-trace/Perfetto
  export and an opt-in ``utils.timing.device_trace`` bridge;
* ``metrics`` — typed counters/gauges/histograms with Prometheus-text
  and round-tripping JSON snapshots, into which the existing
  ``ServeMetrics``/``CompileCounter``/sweep counters mirror;
* ``journal`` — append-only JSONL of typed lifecycle events
  (QUARANTINE, RETRY_TRANSIENT, CERT_FAILED, ...) emitted at every seam
  the previous PRs built, enforced by ``scripts/check_obs_events.py``.

Off by default, near-zero disabled overhead (``NULL_OBS``; the no-op
span is one cached null context manager).  Enable via ``ObsConfig`` on
``SweepConfig(obs=...)`` / ``EquilibriumService(obs=...)`` /
``bench.py --obs-smoke``.  Everything here is stdlib-only at import —
recording a serve hit must stay microseconds.
"""

from .journal import EVENT_TYPES, EventJournal, read_journal  # noqa: F401
from .metrics import (  # noqa: F401
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
    reset_default_registry,
)
from .profile import (  # noqa: F401
    ROOFLINE_CLASSES,
    ROOFLINE_COMPUTE,
    ROOFLINE_LATENCY,
    ROOFLINE_MEMORY,
    ROOFLINE_UNKNOWN,
    CostEntry,
    CostLedger,
    DeviceTelemetry,
    classify_roofline,
    peak_membw_per_chip,
)
from .regress import (  # noqa: F401
    NOISE,
    OK,
    REGRESSED,
    MetricFinding,
    RegressionReport,
    UnknownMetricError,
    direction_of_goodness,
    evaluate_history,
    flatten_record,
)
from .runtime import (  # noqa: F401
    NULL_INSTRUMENT,
    NULL_OBS,
    FlightRecorder,
    Obs,
    ObsConfig,
    active_obs,
    active_span,
    build_obs,
    emit_event,
    resolve_obs,
)
from .trace import (  # noqa: F401
    NULL_SPAN,
    NULL_SPAN_CM,
    Span,
    Tracer,
    new_run_id,
    trace_nesting_ok,
)
