"""Run-scoped tracing spans with Chrome-trace export (ISSUE 7).

The framework's wall-clock story used to live in four disconnected
places — ``utils.timing.PhaseTimer`` totals, per-``SweepResult`` launch
walls, ``ServeMetrics`` latency histograms, and ~60 ad-hoc bench record
fields — none of which can answer "where did THIS run's time go, in
order, with the cell/bucket attached".  A ``Tracer`` records lightweight
nestable spans (``with tracer.span("sweep/bucket", bucket=2): ...``)
with monotonic walls and arbitrary JSON-able attributes, correlated by a
per-run ``run_id`` shared with the metrics registry and the event
journal, and exports the standard Chrome-trace JSON that
``chrome://tracing`` and Perfetto load directly.

Design constraints, in order:

* **Near-zero disabled overhead.**  The disabled path must never show up
  in a solve's wall: ``NULL_SPAN_CM`` is ONE cached
  ``contextlib.nullcontext`` reused by every disabled call site — no
  allocation, no clock read, no lock (the ISSUE 7 no-op contract,
  pinned by ``tests/test_obs.py``).
* **No tracing inside jit.**  Spans bracket host-side seams (bucket
  launches, batch flushes, quarantine rungs); the phase structure INSIDE
  a jitted program is reconstructed after the fact from the counters the
  solvers already return (``Span.subdivide`` — synthetic child spans
  splitting the parent wall in proportion to descent/polish step
  counts, marked ``synthetic`` so a reader never mistakes them for
  measured boundaries).
* **Thread-safe.**  The serve worker and the caller thread trace into
  one ``Tracer``; nesting is tracked per thread (thread-local stacks)
  and each thread renders as its own Chrome-trace ``tid`` row.

The opt-in bridge to device-level profiling: a span created with
``device_profile=True`` on a tracer constructed with
``device_trace_dir`` wraps the span body in
``utils.timing.device_trace`` — the XLA profiler's perfetto dump lands
under that directory, correlated to the span by the run id and the
span's recorded wall.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, List, Optional


def new_run_id() -> str:
    """A fresh run correlation id: sortable timestamp + random suffix,
    filesystem- and grep-safe.  Every artifact of one run — trace,
    journal lines, metrics snapshot, bench record — carries the same
    value (the correlation contract, DESIGN §10)."""
    import secrets

    return (time.strftime("run-%Y%m%dT%H%M%S-")
            + secrets.token_hex(4))


class _NullSpan:
    """The disabled span: every mutator is a no-op.  A single instance
    rides inside the single cached null context manager."""

    __slots__ = ()

    def annotate(self, **attrs) -> None:
        pass

    def subdivide(self, parts, prefix: str = "") -> None:
        pass


NULL_SPAN = _NullSpan()
# THE cached no-op context manager (ISSUE 7 tentpole): ``nullcontext`` is
# stateless across __enter__/__exit__, so one instance serves every
# disabled ``span()`` call in the process, re-entrantly.
NULL_SPAN_CM = contextlib.nullcontext(NULL_SPAN)


class Span:
    """One live (or finished) span.  Mutable so the body can attach
    attributes discovered during the work (``annotate``) and phase
    splits known only from returned counters (``subdivide``)."""

    __slots__ = ("name", "attrs", "t0", "t1", "tid", "parent",
                 "synthetic", "external", "_parts")

    def __init__(self, name: str, attrs: dict, t0: float, tid: int,
                 parent: Optional["Span"], synthetic: bool = False,
                 external: bool = False):
        self.name = name
        self.attrs = attrs
        self.t0 = t0
        self.t1: Optional[float] = None
        self.tid = tid
        self.parent = parent
        self.synthetic = synthetic
        self.external = external
        self._parts = None

    def annotate(self, **attrs) -> None:
        """Attach attributes to the span (merged into Chrome-trace
        ``args``)."""
        self.attrs.update(attrs)

    def subdivide(self, parts: dict, prefix: str = "") -> None:
        """Declare a proportional phase split of this span's wall —
        e.g. ``{"descent": d_steps, "polish": p_steps}`` from a fixed
        point's returned counters.  At span exit the tracer materializes
        one SYNTHETIC child span per non-zero part, partitioning
        ``[t0, t1]`` by weight.  The jit-boundary answer to "phase spans
        from returned counters": the interior of a compiled program is
        not traceable, but its phase budget is."""
        self._parts = (dict(parts), prefix)

    def duration(self) -> Optional[float]:
        return None if self.t1 is None else self.t1 - self.t0


class Tracer:
    """Run-scoped span recorder.  ``span()`` is a context manager;
    completed spans accumulate until ``chrome_trace()`` /
    ``save_chrome_trace()`` export them."""

    # Completed-span cap: a long-lived traced service records one
    # external span per served query, and an unbounded list would grow
    # without limit at the serving scale the ROADMAP targets (and choke
    # the trace viewer long before memory).  Past the cap new spans are
    # DROPPED and counted — the count rides the export metadata, so a
    # truncated trace can never read as a complete one.
    DEFAULT_MAX_SPANS = 200_000

    def __init__(self, run_id: Optional[str] = None,
                 clock=time.perf_counter,
                 device_trace_dir: Optional[str] = None,
                 max_spans: int = DEFAULT_MAX_SPANS):
        self.run_id = run_id if run_id is not None else new_run_id()
        self._clock = clock
        self._t_base = clock()
        self._lock = threading.Lock()
        self._local = threading.local()
        self._tids: Dict[int, int] = {}
        self.device_trace_dir = device_trace_dir
        self.max_spans = int(max_spans)
        self.dropped = 0
        self.spans: List[Span] = []
        # counter-track samples (ISSUE 10): (name, t, values) triples
        # exported as Chrome "C" events — the cost ledger's per-launch
        # achieved-FLOP/s stream renders as its own counter row in
        # Perfetto.  Same bounded-and-counted policy as spans.
        self._counters: List[tuple] = []
        self.counters_dropped = 0

    def _append(self, sp: Span) -> None:
        # Dropping the NEWEST keeps nesting exportable — children
        # complete (and append) before their parents, so a kept child
        # never dangles above a dropped ancestor's sibling.
        with self._lock:
            if len(self.spans) >= self.max_spans:
                self.dropped += 1
            else:
                self.spans.append(sp)

    # -- recording ----------------------------------------------------------

    def now(self) -> float:
        return self._clock()

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _tid(self) -> int:
        ident = threading.get_ident()
        with self._lock:
            if ident not in self._tids:
                self._tids[ident] = len(self._tids)
            return self._tids[ident]

    @contextlib.contextmanager
    def span(self, name: str, device_profile: bool = False, **attrs):
        """Open a nested span.  ``device_profile=True`` additionally
        captures an XLA device profile for the span body when the tracer
        was built with ``device_trace_dir`` (the ``utils.timing
        .device_trace`` bridge) — opt-in twice, because a profiler dump
        costs real wall and disk."""
        stack = self._stack()
        parent = stack[-1] if stack else None
        sp = Span(name, dict(attrs), self._clock(), self._tid(), parent)
        stack.append(sp)
        profile_dir = (self.device_trace_dir
                       if device_profile and self.device_trace_dir
                       else None)
        try:
            if profile_dir is not None:
                from ..utils.timing import device_trace

                sp.attrs.setdefault("device_trace_dir", profile_dir)
                with device_trace(profile_dir):
                    yield sp
            else:
                yield sp
        finally:
            sp.t1 = self._clock()
            stack.pop()
            self._append(sp)

    @staticmethod
    def _materialized_parts(sp: Span) -> list:
        """Synthetic child spans from a ``subdivide`` declaration —
        computed at EXPORT time, because the counters that define the
        split are typically attached right after the span's ``with``
        block exits (the launch must finish before its phase totals
        exist)."""
        if sp._parts is None or sp.t1 is None:
            return []
        parts, prefix = sp._parts
        total = float(sum(max(0.0, float(v)) for v in parts.values()))
        if total <= 0.0:
            return []
        out = []
        t = sp.t0
        wall = sp.t1 - sp.t0
        for part_name, weight in parts.items():
            w = max(0.0, float(weight))
            if w == 0.0:
                continue
            child = Span(f"{prefix}{part_name}",
                         {"synthetic": True, "weight": w},
                         t, sp.tid, sp, synthetic=True)
            t = min(sp.t1, t + wall * (w / total))
            child.t1 = t
            out.append(child)
        return out

    def counter(self, name: str, **values) -> None:
        """Record one counter-track sample at now (Chrome-trace "C"
        event): ``tracer.counter("profile/sweep/achieved_flops_per_sec",
        value=2.6e8)``.  Values must be numeric; each distinct ``name``
        renders as its own counter row in the trace viewer."""
        t = self._clock()
        sample = (name, t, {str(k): float(v) for k, v in values.items()})
        with self._lock:
            if len(self._counters) >= self.max_spans:
                self.counters_dropped += 1
            else:
                self._counters.append(sample)

    def record(self, name: str, duration_s: float, **attrs) -> None:
        """Record an externally-timed span ending now — for paths whose
        start predates any tracer involvement (a serve query's
        submit→resolve latency, timed by the service's own clock).

        External spans are NOT stack spans: several queries resolved by
        one batch flush genuinely overlap in the resolving thread, so
        they export as Chrome ASYNC events (``ph: "b"/"e"``), which
        viewers draw on their own track and ``trace_nesting_ok``'s
        same-row containment invariant deliberately ignores."""
        t1 = self._clock()
        sp = Span(name, dict(attrs), t1 - max(0.0, float(duration_s)),
                  self._tid(), None, external=True)
        sp.t1 = t1
        self._append(sp)

    # -- export -------------------------------------------------------------

    def chrome_trace(self) -> dict:
        """The Chrome-trace (Perfetto) JSON object: stack spans as
        complete ("X") events in microseconds relative to the tracer's
        epoch (one tid row per recording thread), externally-timed
        ``record`` spans as async begin/end ("b"/"e") pairs on their own
        track, attributes in ``args`` with the ``run_id`` stamped on
        every begin/complete event and in ``metadata``."""
        import os

        pid = os.getpid()
        events = []
        with self._lock:
            spans = list(self.spans)
            dropped = self.dropped
            counters = list(self._counters)
            counters_dropped = self.counters_dropped
        expanded = []
        for sp in spans:
            expanded.append(sp)
            expanded.extend(self._materialized_parts(sp))
        for n_async, sp in enumerate(expanded):
            if sp.t1 is None:
                continue        # still open (another thread): skip
            args = {k: _jsonable(v) for k, v in sp.attrs.items()}
            args["run_id"] = self.run_id
            ts0 = round((sp.t0 - self._t_base) * 1e6, 3)
            ts1 = round((sp.t1 - self._t_base) * 1e6, 3)
            if sp.external:
                # externally-timed spans (``record``) overlap freely —
                # async begin/end pairs, matched by (cat, id, name)
                base = {"name": sp.name, "cat": "external",
                        "id": f"0x{n_async:x}", "pid": pid,
                        "tid": sp.tid}
                events.append({**base, "ph": "b", "ts": ts0,
                               "args": args})
                events.append({**base, "ph": "e", "ts": ts1})
                continue
            events.append({
                "name": sp.name,
                "ph": "X",
                "ts": ts0,
                # duration of the ROUNDED endpoints (not a third
                # independent rounding): ts + dur is then exactly ts1,
                # so a synthetic child sharing its parent's t1 can never
                # export an end a rounding-ulp past the parent's
                "dur": round(ts1 - ts0, 3),
                "pid": pid,
                "tid": sp.tid,
                "args": args,
            })
        for name, t, values in counters:
            events.append({
                "name": name,
                "ph": "C",
                "ts": round((t - self._t_base) * 1e6, 3),
                "pid": pid,
                "tid": 0,
                "args": values,
            })
        events.sort(key=lambda e: e["ts"])
        meta = {"run_id": self.run_id}
        if dropped:
            meta["spans_dropped"] = dropped   # never a silent cap
        if counters_dropped:
            meta["counters_dropped"] = counters_dropped
        return {"traceEvents": events,
                "displayTimeUnit": "ms",
                "metadata": meta}

    def save_chrome_trace(self, path: str) -> None:
        """Write the trace crash-consistently (``atomic_write_json``) —
        a preempted run must leave either the previous trace or a valid
        one, never a torn JSON that chrome://tracing rejects."""
        from ..utils.checkpoint import atomic_write_json

        atomic_write_json(path, self.chrome_trace())


def _jsonable(v):
    """Coerce an attribute value to something ``json.dumps`` accepts:
    numpy scalars/arrays become Python numbers/lists, everything else
    unknown becomes ``str``.  Kept dependency-free (no numpy import
    unless the value needs it)."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    tolist = getattr(v, "tolist", None)
    if tolist is not None:
        try:
            return _jsonable(tolist())
        except Exception:   # noqa: BLE001 — attribute coercion best-effort
            pass
    item = getattr(v, "item", None)
    if item is not None:
        try:
            return _jsonable(item())
        except Exception:   # noqa: BLE001
            pass
    return str(v)


def trace_nesting_ok(trace: dict) -> bool:
    """Structural sanity of an exported Chrome trace: every complete
    ("X") event has a non-negative duration, and within each tid row
    they are properly nested (any two either disjoint or one containing
    the other — the invariant a span STACK guarantees and a
    torn/mixed-up export breaks).  Async ("b"/"e") pairs — externally
    timed ``record`` spans, which legitimately overlap — are exempt.
    Used by the ``--obs-smoke`` acceptance and ``tests/test_obs.py``."""
    by_tid: dict = {}
    for e in trace.get("traceEvents", []):
        if e.get("ph") != "X":
            continue
        if e.get("dur", -1) < 0:
            return False
        by_tid.setdefault(e.get("tid"), []).append(
            (float(e["ts"]), float(e["ts"]) + float(e["dur"])))
    eps = 0.5   # µs slack: exported timestamps are rounded to 1e-3 µs
    for intervals in by_tid.values():
        # containers before their same-start children: sort by start
        # ascending, then LONGEST first, so a child beginning exactly at
        # its parent's start nests instead of reading as an overlap
        intervals.sort(key=lambda iv: (iv[0], -iv[1]))
        stack: list = []
        for (t0, t1) in intervals:
            while stack and t0 >= stack[-1] - eps:
                stack.pop()
            if stack and t1 > stack[-1] + eps:
                return False    # overlap without containment
            stack.append(t1)
    return True
