"""Measured performance attribution: the cost ledger, the roofline, and
per-device telemetry (ISSUE 10, DESIGN §10b).

Until now every MFU/FLOP number in the bench came from ONE source — the
analytic ``utils.timing.model_flops`` step-count model — and nothing in
the framework ever read XLA's own opinion of the programs it compiles.
A drifted analytic model is invisible: the MFU denominator quietly stops
describing the executable.  This module is the measured half:

* ``CostLedger`` — keyed by the compile-cache work fingerprint
  (``utils.fingerprint.work_fingerprint`` + executable flavor + padded
  shape: the same identity the jit/persistent caches deduplicate on), it
  captures at COMPILE time each jitted executable's XLA
  ``cost_analysis()`` (flops, bytes accessed, transcendentals) plus the
  lowering and compile walls, and aggregates at LAUNCH time the wall,
  launch count, achieved FLOP/s, arithmetic intensity, and a roofline
  classification against ``utils.timing.peak_flops_per_chip``.  Capture
  is strictly best-effort: a backend without cost analysis records WHY
  (``cost_source``), never crashes a solve, and never changes the bits
  the real launch produces (the profiled program is compiled AOT on the
  side; the solve still runs through the jit cache — with the persistent
  compilation cache enabled the XLA work is shared, so the capture costs
  one lowering plus a cache-served compile per executable, once).
* ``classify_roofline`` — the deterministic latency/memory/compute
  taxonomy (table pinned by ``tests/test_profile.py``): an executable
  whose achieved fraction of its roofline ceiling is below
  ``latency_util_frac`` is LATENCY-bound (the measured ~0.06% MFU sweep
  regime — dispatch and serialization, not silicon); otherwise its
  arithmetic intensity against the ridge (peak FLOP/s ÷ peak bytes/s)
  separates MEMORY- from COMPUTE-bound.
* ``DeviceTelemetry`` — per-device ``memory_stats()`` gauges sampled at
  sweep bucket seams and serve batch flushes (graceful None off-TPU: a
  CPU device reports no stats and the sample records only its own
  count), with a per-device high-water mark that journals
  ``DEVICE_MEM_HIGH_WATER`` whenever it grows — the evidence trail a
  1→8-chip scaling claim needs.

Everything here rides the ISSUE 7 obs substrate: ledger totals mirror
into the metrics registry, per-launch samples land as Chrome-trace
COUNTER tracks (``Tracer.counter``), and the run's ``PROFILE_SNAPSHOT``
journal line carries the ledger summary under the run_id.
"""

from __future__ import annotations

import re
import threading
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

# Roofline classification outcomes (a closed vocabulary, like the journal
# event set: downstream consumers switch on these strings).
ROOFLINE_UNKNOWN = "unknown"
ROOFLINE_LATENCY = "latency"
ROOFLINE_MEMORY = "memory"
ROOFLINE_COMPUTE = "compute"
ROOFLINE_CLASSES = (ROOFLINE_UNKNOWN, ROOFLINE_LATENCY,
                    ROOFLINE_MEMORY, ROOFLINE_COMPUTE)

# Achieved/ceiling fraction below which an executable is latency-bound:
# it is not meaningfully engaging EITHER roof, so the binding constraint
# is dispatch/serialization, not silicon (the 12-cell sweep measures
# ~6e-4 of peak on TPU — two orders below this line).
LATENCY_UTIL_FRAC = 0.02
# Off-accelerator fallback when no peak is known: a per-launch wall at or
# under this is dominated by dispatch, not execution.
LATENCY_WALL_FLOOR_S = 1e-3
# Ridge (FLOP/byte) used when the backend publishes no peak pair — the
# order of magnitude shared by modern CPUs and accelerators; only the
# memory/compute SIDE depends on it, never a number in the record.
DEFAULT_RIDGE_FLOPS_PER_BYTE = 10.0


def peak_membw_per_chip(backend: str) -> Tuple[Optional[float], bool]:
    """Nominal peak HBM bytes/s of one chip for the roofline ridge, with
    an ``assumed`` flag mirroring ``utils.timing.peak_flops_per_chip``'s
    honesty contract (v5e 819 GB/s, v4 1228 GB/s, v5p 2765 GB/s; None
    off-accelerator — a host's effective bandwidth has no honest
    single-number peak)."""
    if backend not in ("tpu", "axon"):
        return None, False
    try:
        import jax
        kind = jax.devices()[0].device_kind.lower()
    except Exception:   # noqa: BLE001 — device query is best-effort
        kind = ""
    if "v5 lite" in kind or "v5e" in kind or "v5lite" in kind:
        return 819e9, False
    if "v4" in kind:
        return 1228e9, False
    if "v5p" in kind or "v5" in kind:
        return 2765e9, False
    return 819e9, True      # unknown TPU: the v5e class guess, flagged


def classify_roofline(flops, bytes_accessed, wall_s, launches,
                      peak_flops=None, peak_bytes_per_s=None,
                      latency_util_frac: float = LATENCY_UTIL_FRAC,
                      latency_wall_floor_s: float = LATENCY_WALL_FLOOR_S,
                      default_ridge: float = DEFAULT_RIDGE_FLOPS_PER_BYTE
                      ) -> str:
    """The deterministic roofline taxonomy (DESIGN §10b, pinned by the
    classification table in ``tests/test_profile.py``):

    1. ``unknown`` — no cost analysis (flops/bytes missing) or no
       measured launches, so no classification is honest;
    2. ``latency`` — the achieved fraction of the roofline ceiling
       ``min(peak_flops, AI * peak_bw)`` is under ``latency_util_frac``
       (or, with no published peak, the per-launch wall sits at/under
       ``latency_wall_floor_s``): the program never engages a roof;
    3. ``compute`` / ``memory`` — arithmetic intensity (FLOP/byte) at or
       above / below the ridge (``peak_flops / peak_bytes_per_s``, or
       ``default_ridge`` when the backend publishes no peak pair).
    """
    if (flops is None or bytes_accessed is None or not flops > 0.0
            or not bytes_accessed > 0.0 or not launches
            or wall_s is None or not wall_s > 0.0):
        return ROOFLINE_UNKNOWN
    ai = float(flops) / float(bytes_accessed)
    achieved = float(flops) * float(launches) / float(wall_s)
    if peak_flops is not None and peak_flops > 0.0:
        ceiling = peak_flops
        if peak_bytes_per_s is not None and peak_bytes_per_s > 0.0:
            ceiling = min(peak_flops, ai * peak_bytes_per_s)
        if achieved / ceiling < latency_util_frac:
            return ROOFLINE_LATENCY
        ridge = (peak_flops / peak_bytes_per_s
                 if peak_bytes_per_s is not None and peak_bytes_per_s > 0.0
                 else default_ridge)
    else:
        if wall_s / float(launches) <= latency_wall_floor_s:
            return ROOFLINE_LATENCY
        ridge = default_ridge
    return ROOFLINE_COMPUTE if ai >= ridge else ROOFLINE_MEMORY


def _parse_cost_analysis(ca) -> dict:
    """Normalize ``Compiled.cost_analysis()`` across jax versions (dict,
    or a one-element list of dicts) to the three fields the ledger
    records.  Missing keys are None, not 0 — absence must stay visible."""
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        raise TypeError(f"unexpected cost_analysis payload: {type(ca)}")

    def get(name):
        v = ca.get(name)
        return None if v is None else float(v)

    return {"flops": get("flops"),
            "bytes_accessed": get("bytes accessed"),
            "transcendentals": get("transcendentals")}


_SLUG_RE = re.compile(r"[^a-zA-Z0-9_]+")


def _slug(label: str) -> str:
    """Prometheus-safe metric-name fragment from a free-form label."""
    return _SLUG_RE.sub("_", str(label)).strip("_").lower() or "exe"


@dataclass
class CostEntry:
    """One executable's measured cost record (one per ledger key).

    ``cost_source`` is the provenance honesty bit: ``xla_cost_analysis``
    when the numbers came from the compiled executable itself,
    ``"unavailable: <reason>"`` when the backend/version could not serve
    them (the fields stay None and every downstream consumer must treat
    them as absent-with-a-reason, never as zero)."""

    key: tuple
    label: str
    lowering_s: Optional[float] = None
    compile_s: Optional[float] = None
    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None
    transcendentals: Optional[float] = None
    cost_source: str = "uncaptured"
    launches: int = 0
    launch_wall_s: float = 0.0

    def achieved_flops_per_sec(self) -> Optional[float]:
        if (self.flops is None or not self.launches
                or not self.launch_wall_s > 0.0):
            return None
        return self.flops * self.launches / self.launch_wall_s

    def arithmetic_intensity(self) -> Optional[float]:
        if (self.flops is None or self.bytes_accessed is None
                or not self.bytes_accessed > 0.0):
            return None
        return self.flops / self.bytes_accessed

    def roofline(self, peak_flops=None, peak_bytes_per_s=None) -> str:
        return classify_roofline(
            self.flops, self.bytes_accessed, self.launch_wall_s,
            self.launches, peak_flops=peak_flops,
            peak_bytes_per_s=peak_bytes_per_s)


@dataclass
class _Peaks:
    flops: Optional[float] = None
    flops_assumed: bool = False
    bytes_per_s: Optional[float] = None
    bytes_assumed: bool = False


class CostLedger:
    """Measured cost attribution for every profiled executable of a run.

    ``capture(key, fn, args)`` is memoized per key (the compile-cache
    work-fingerprint identity): the first call lowers and AOT-compiles
    the jitted ``fn`` at ``args``' shapes — timed, so the record carries
    the real lowering/compile walls — and reads the compiled
    executable's ``cost_analysis()``; later calls are a dict hit.
    ``record_launch(key, wall_s)`` aggregates the measured launch walls
    and optionally drops a Chrome-trace counter sample on a tracer.
    Both are exception-tight: profiling must never take down a solve.
    """

    def __init__(self, backend: Optional[str] = None,
                 peak_flops: Optional[float] = None,
                 peak_bytes_per_s: Optional[float] = None):
        self._lock = threading.Lock()
        self._entries: Dict[tuple, CostEntry] = {}
        self._peaks: Optional[_Peaks] = None
        if peak_flops is not None or peak_bytes_per_s is not None:
            self._peaks = _Peaks(peak_flops, False, peak_bytes_per_s,
                                 False)
        self._backend = backend

    # -- peaks (lazy: jax.default_backend may not be initialized yet) ------

    def peaks(self) -> _Peaks:
        if self._peaks is None:
            backend = self._backend
            if backend is None:
                try:
                    import jax
                    backend = jax.default_backend()
                except Exception:   # noqa: BLE001 — probing is best-effort
                    backend = "cpu"
            from ..utils.timing import peak_flops_per_chip

            pf = peak_flops_per_chip(backend)
            bw, bw_assumed = peak_membw_per_chip(backend)
            self._peaks = _Peaks(pf.value, pf.assumed, bw, bw_assumed)
        return self._peaks

    # -- capture / launch ---------------------------------------------------

    def capture(self, key: tuple, fn, args, label: str = "") -> CostEntry:
        """Compile-time capture for ``key``, once: lowering wall, compile
        wall, and the XLA cost analysis of the executable ``fn`` compiles
        for ``args``' shapes."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                return entry
            entry = self._entries[key] = CostEntry(
                key=key, label=str(label) or "exe")
        from ..utils.timing import stopwatch

        try:
            with stopwatch() as sw_low:
                lowered = fn.lower(*args)
            entry.lowering_s = sw_low.seconds
            with stopwatch() as sw_comp:
                compiled = lowered.compile()
            entry.compile_s = sw_comp.seconds
            cost = _parse_cost_analysis(compiled.cost_analysis())
        except Exception as e:   # noqa: BLE001 — profiling is best-effort
            entry.cost_source = (f"unavailable: "
                                 f"{type(e).__name__}: {e}"[:200])
            return entry
        entry.flops = cost["flops"]
        entry.bytes_accessed = cost["bytes_accessed"]
        entry.transcendentals = cost["transcendentals"]
        entry.cost_source = "xla_cost_analysis"
        return entry

    def record_launch(self, key: tuple, wall_s: float,
                      tracer=None) -> None:
        """Aggregate one measured launch wall onto ``key``'s entry (which
        ``capture`` must have created) and, with a ``tracer``, sample the
        entry's achieved FLOP/s and launch wall onto Chrome-trace counter
        tracks."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                entry = self._entries[key] = CostEntry(
                    key=key, label="exe")
            entry.launches += 1
            entry.launch_wall_s += float(wall_s)
        if tracer is not None:
            slug = _slug(entry.label)
            tracer.counter(f"profile/{slug}/launch_wall_s",
                           value=float(wall_s))
            achieved = entry.achieved_flops_per_sec()
            if achieved is not None:
                tracer.counter(f"profile/{slug}/achieved_flops_per_sec",
                               value=achieved)

    def entries(self) -> list:
        with self._lock:
            return list(self._entries.values())

    # -- aggregation / export ----------------------------------------------

    def measured_flops_total(self) -> Optional[float]:
        """Sum of per-launch XLA flops x launches over entries that have
        cost analysis; None when NO entry has it (absence must not read
        as zero work)."""
        totals = [e.flops * e.launches for e in self.entries()
                  if e.flops is not None and e.launches]
        return sum(totals) if totals else None

    def flops_model_vs_measured_ratio(self, analytic_flops
                                      ) -> Optional[float]:
        """The cross-check headline: analytic ``model_flops`` over XLA's
        own count for the same launches.  1.0 means the hand model and
        the compiler agree; drift in the MFU denominator is THIS number
        moving (note: XLA counts a while-loop body once — a large ratio
        on iterative solvers is expected and documents exactly how much
        of the analytic count rides trip counts XLA cannot see)."""
        measured = self.measured_flops_total()
        if measured is None or not measured > 0.0 or analytic_flops is None:
            return None
        return float(analytic_flops) / measured

    def snapshot(self) -> dict:
        """The ledger as one JSON-able dict: per-entry records plus run
        totals with the roofline classification — the payload behind the
        ``profile_*`` bench fields and the PROFILE_SNAPSHOT journal
        event."""
        peaks = self.peaks()
        entries = self.entries()
        per = {}
        for e in entries:
            # slugs must stay one-per-entry: two keys can share a label
            # (e.g. the same executable with and without a fault hook),
            # and a silent merge would break the executable-ladder audit
            slug = base = _slug(e.label)
            n = 2
            while slug in per:
                slug = f"{base}_{n}"
                n += 1
            per[slug] = {
                "label": e.label,
                "launches": e.launches,
                "launch_wall_s": e.launch_wall_s,
                "lowering_s": e.lowering_s,
                "compile_s": e.compile_s,
                "flops": e.flops,
                "bytes_accessed": e.bytes_accessed,
                "transcendentals": e.transcendentals,
                "cost_source": e.cost_source,
                "achieved_flops_per_sec": e.achieved_flops_per_sec(),
                "arithmetic_intensity": e.arithmetic_intensity(),
                "roofline": e.roofline(peaks.flops, peaks.bytes_per_s),
            }
        wall = sum(e.launch_wall_s for e in entries)
        launches = sum(e.launches for e in entries)
        flops_total = self.measured_flops_total()
        bytes_totals = [e.bytes_accessed * e.launches for e in entries
                        if e.bytes_accessed is not None and e.launches]
        bytes_total = sum(bytes_totals) if bytes_totals else None
        achieved = (flops_total / wall
                    if flops_total is not None and wall > 0.0 else None)
        ai = (flops_total / bytes_total
              if flops_total is not None and bytes_total else None)
        # classify on PER-LAUNCH flops/bytes (the totals already carry
        # the launch multiplier; classify_roofline multiplies by
        # ``launches`` itself — feeding it totals would inflate the
        # achieved rate by the launch count)
        roofline = classify_roofline(
            None if flops_total is None else flops_total / max(launches,
                                                              1),
            None if bytes_total is None else bytes_total / max(launches,
                                                               1),
            wall, launches,
            peak_flops=peaks.flops, peak_bytes_per_s=peaks.bytes_per_s)
        mfu = (None if peaks.flops is None or achieved is None
               else 100.0 * achieved / peaks.flops)
        sources = {}
        for e in entries:
            tag = e.cost_source.split(":", 1)[0]
            sources[tag] = sources.get(tag, 0) + 1
        return {
            "executables": len(entries),
            "launches": launches,
            "launch_wall_s": wall,
            "lowering_wall_s": sum(e.lowering_s or 0.0 for e in entries),
            "compile_wall_s": sum(e.compile_s or 0.0 for e in entries),
            "measured_flops_total": flops_total,
            "bytes_accessed_total": bytes_total,
            "achieved_flops_per_sec": achieved,
            "arithmetic_intensity": ai,
            "roofline": roofline,
            "mfu_pct": mfu,
            "peak_flops_per_chip": peaks.flops,
            "peak_flops_assumed": peaks.flops_assumed,
            "peak_bytes_per_s_per_chip": peaks.bytes_per_s,
            "peak_bytes_assumed": peaks.bytes_assumed,
            "cost_sources": sources,
            "entries": per,
        }

    def publish(self, registry, prefix: str = "aiyagari_profile_"
                ) -> None:
        """Mirror the ledger into a metrics registry (totals as gauges,
        plus per-executable launch wall / launches / achieved FLOP/s
        under slugged names) — levels, re-publishable, matching the
        ``ServeMetrics.publish`` convention."""
        if registry is None:
            return
        snap = self.snapshot()
        for name, help_text in (
                ("executables", "profiled executables this run"),
                ("launches", "profiled launches this run"),
                ("launch_wall_s", "summed profiled launch wall"),
                ("compile_wall_s", "summed AOT compile wall"),
                ("lowering_wall_s", "summed lowering wall")):
            registry.gauge(prefix + name, help_text).set(
                float(snap[name] or 0.0))
        if snap["achieved_flops_per_sec"] is not None:
            registry.gauge(prefix + "achieved_flops_per_sec",
                           "measured FLOP/s over profiled launches").set(
                snap["achieved_flops_per_sec"])
        for slug, e in snap["entries"].items():
            registry.gauge(f"{prefix}launch_wall_s_{slug}",
                           f"launch wall: {e['label']}").set(
                e["launch_wall_s"])
            registry.gauge(f"{prefix}launches_{slug}",
                           f"launches: {e['label']}").set(e["launches"])
            if e["achieved_flops_per_sec"] is not None:
                registry.gauge(
                    f"{prefix}achieved_flops_per_sec_{slug}",
                    f"achieved FLOP/s: {e['label']}").set(
                    e["achieved_flops_per_sec"])


class DeviceTelemetry:
    """Per-device memory telemetry with a journaled high-water mark.

    ``sample(obs, where=...)`` reads every device's ``memory_stats()``
    (None off-TPU — the sample still counts, the device just contributes
    no gauges), mirrors ``bytes_in_use`` / ``peak_bytes_in_use`` /
    ``bytes_limit`` into the run's registry, and emits ONE
    ``DEVICE_MEM_HIGH_WATER`` journal event per device each time its
    observed high-water mark grows — a bounded, monotone event stream
    (at most one line per actual new peak, never one per sample)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._high_water: Dict[int, float] = {}
        self.samples = 0
        self.devices_without_stats = 0

    def sample(self, obs, where: str = "") -> int:
        """Sample all devices once; returns how many had stats."""
        try:
            import jax
            devices = jax.devices()
        except Exception:   # noqa: BLE001 — telemetry is best-effort
            return 0
        with self._lock:
            self.samples += 1
        with_stats = 0
        for i, dev in enumerate(devices):
            try:
                stats = dev.memory_stats()
            except Exception:   # noqa: BLE001
                stats = None
            if not stats:
                with self._lock:
                    self.devices_without_stats += 1
                continue
            with_stats += 1
            in_use = float(stats.get("bytes_in_use", 0) or 0)
            peak = float(stats.get("peak_bytes_in_use", in_use) or in_use)
            limit = stats.get("bytes_limit")
            obs.gauge(f"aiyagari_device{i}_mem_bytes_in_use",
                      "device bytes in use at the last sample").set(in_use)
            obs.gauge(f"aiyagari_device{i}_mem_peak_bytes_in_use",
                      "device peak bytes in use").set(peak)
            if limit:
                obs.gauge(f"aiyagari_device{i}_mem_bytes_limit",
                          "device memory limit").set(float(limit))
            hw = max(in_use, peak)
            with self._lock:
                prev = self._high_water.get(i, 0.0)
                grew = hw > prev
                if grew:
                    self._high_water[i] = hw
            if grew:
                obs.event("DEVICE_MEM_HIGH_WATER", device=int(i),
                          bytes=int(hw), where=where,
                          **({} if not limit
                             else {"bytes_limit": int(limit)}))
        return with_stats

    def high_water(self) -> dict:
        with self._lock:
            return dict(self._high_water)
