"""Notebook-compatible facade: ``AiyagariType`` / ``AiyagariEconomy`` classes
exposing the reference's driver interface (SURVEY.md §1, L5→L4) on top of the
TPU-native engine.

The reference notebook drives the model as (``Aiyagari-HARK.py:234-258``):

    economy = AiyagariEconomy(**econ_dict); economy.verbose = False
    agent = AiyagariType(**agent_dict); agent.cycles = 0
    agent.get_economy_data(economy)
    economy.agents = [agent]
    economy.make_Mrkv_history()
    economy.solve()
    economy.sow_state['Rnow'|'Mnow']; economy.reap_state['aNow']
    economy.AFunc[j](M); agent.solution[0].cFunc[s](m, M)
    agent.solution[0].cFunc[s].xInterpolators   # per-M 1D plots

This module reproduces that surface exactly — same attribute names, same
parameter-dict spelling (``init_Aiyagari_agents``/``init_Aiyagari_economy``,
``Aiyagari_Support.py:752-757, 1525-1551``), same steady-state attributes
(``KtoLSS/KSS/WSS/RSS/MSS``, ``Aiyagari_Support.py:1606-1615``) — while
``solve`` runs the jitted Krusell-Smith fixed point of ``models.ks_solver``.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .models import firm
from .models.ks_solver import KSSolution, solve_ks_economy
from .models.simulate import simulate_markov_history
from .ops.interp import interp1d, interp_on_interp
from .ops.markov import aggregate_markov_matrix
from .solver_health import (
    NONFINITE,
    SolverDivergenceError,
    status_name,
)
from .utils.config import (
    MGRID_BASE_DEFAULT,
    AgentConfig,
    EconomyConfig,
)


def quantile_resample(grid, weights, n_agents: int) -> np.ndarray:
    """Equal-weight agent panel from an exact histogram, notebook-style.

    Midpoint-CDF quantile draw over the zero-mass-trimmed support, with the
    top agent pinned to the highest gridpoint whose upper-tail mass is at
    least half an agent's share (0.5/n).  Rationale and failure modes of
    the simpler rules are documented at the call site in
    ``AiyagariEconomy.solve``; unit-tested directly in
    ``tests/test_facade.py``."""
    grid = np.asarray(grid)
    weights = np.asarray(weights)
    pos = weights > 0
    w_pos = weights[pos] / weights.sum()
    # Trim the negligible truncation tail BEFORE building the cdf: any
    # trailing bin whose upper-tail mass is below half an agent's share
    # (0.5/n) cannot honestly be stood on by an equal-weight agent, and
    # leaving such bins in the interp support drags every high quantile
    # toward the empty gap, not just the top agent (round-4 review).
    # Total trimmed mass is < 0.5/n by construction; renormalize.
    tail = np.cumsum(w_pos[::-1])[::-1]          # mass at & above each point
    keep = tail >= 0.5 / n_agents                # nonempty: tail[0] == 1
    g = grid[pos][keep]
    w = w_pos[keep] / w_pos[keep].sum()
    cdf = np.cumsum(w) - 0.5 * w
    q = (np.arange(n_agents) + 0.5) / n_agents
    a_now = np.interp(q, cdf, g)
    a_now[-1] = g[-1]                            # midpoints top out at
    return a_now                                 # (n-0.5)/n; pin support max


def init_aiyagari_agents() -> dict:
    """The reference's agent parameter dict, reference spelling
    (``init_Aiyagari_agents``, ``Aiyagari_Support.py:752-757``)."""
    a = AgentConfig()
    return {
        "LaborStatesNo": a.labor_states, "aMin": a.a_min, "aMax": a.a_max,
        "aCount": a.a_count, "aNestFac": a.a_nest_fac,
        "AgentCount": a.agent_count, "MgridBase": np.array(MGRID_BASE_DEFAULT),
    }


def init_aiyagari_economy() -> dict:
    """The reference's economy parameter dict, reference spelling
    (``init_Aiyagari_economy``, ``Aiyagari_Support.py:1525-1551``)."""
    e = EconomyConfig()
    return {
        "verbose": e.verbose, "LaborStatesNo": e.labor_states,
        "LaborAR": e.labor_ar, "LaborSD": e.labor_sd, "act_T": e.act_T,
        "T_discard": e.t_discard, "DampingFac": e.damping_fac,
        "intercept_prev": list(e.intercept_prev),
        "slope_prev": list(e.slope_prev),
        "DiscFac": e.disc_fac, "CRRA": e.crra, "LbrInd": e.lbr_ind,
        "ProdB": e.prod_b, "ProdG": e.prod_g, "CapShare": e.cap_share,
        "DeprFac": e.depr_fac, "DurMeanB": e.dur_mean_b,
        "DurMeanG": e.dur_mean_g, "SpellMeanB": e.spell_mean_b,
        "SpellMeanG": e.spell_mean_g, "UrateB": e.urate_b,
        "UrateG": e.urate_g, "RelProbBG": e.rel_prob_bg,
        "RelProbGB": e.rel_prob_gb, "MrkvNow_init": e.mrkv_now_init,
    }


class AggregateSavingRule:
    """The perceived aggregate law of motion ``A = exp(i + s log M)``
    (``AggregateSavingRule.__call__``, ``Aiyagari_Support.py:1991-2005``)."""

    distance_criteria = ["slope", "intercept"]

    def __init__(self, intercept: float, slope: float):
        self.intercept = float(intercept)
        self.slope = float(slope)

    def __call__(self, Mnow):
        return np.exp(self.intercept + self.slope * np.log(Mnow))

    def distance(self, other: "AggregateSavingRule") -> float:
        """HARK MetricObject distance: max over the criteria attributes."""
        return max(abs(self.slope - other.slope),
                   abs(self.intercept - other.intercept))


class StatePolicy:
    """One discrete state's consumption function c(m, M) — the facade over a
    ``[Mcount, A+1]`` knot block (the reference's ``LinearInterpOnInterp1D``
    of 15 ``LinearInterp`` columns, ``Aiyagari_Support.py:1509-1516``)."""

    def __init__(self, m_knots: np.ndarray, c_knots: np.ndarray,
                 m_grid: np.ndarray):
        self._m_knots = np.asarray(m_knots)
        self._c_knots = np.asarray(c_knots)
        self._m_grid = np.asarray(m_grid)

    def __call__(self, m, M):
        m = np.asarray(m, dtype=np.float64)
        M = np.asarray(M, dtype=np.float64)
        if M.ndim == 0:
            out = interp_on_interp(m, M, self._m_grid, self._m_knots,
                                   self._c_knots)
            return np.asarray(out)
        # array-valued M (HARK interpolators accept paired (m, M) arrays,
        # e.g. consumption along a simulated path): evaluate pointwise
        # (jnp copies of the knots — numpy arrays can't be indexed by the
        # vmap tracer)
        m_b, M_b = np.broadcast_arrays(m, M)
        grid, mk, ck = (jnp.asarray(self._m_grid), jnp.asarray(self._m_knots),
                        jnp.asarray(self._c_knots))
        out = jax.vmap(
            lambda mi, Mi: interp_on_interp(mi, Mi, grid, mk, ck)
        )(m_b.ravel(), M_b.ravel())
        return np.asarray(out).reshape(m_b.shape)

    @property
    def xInterpolators(self) -> List:
        """Per-M-gridpoint 1D functions m -> c, as the notebook plots them
        (``plot_funcs(...cFunc[4j].xInterpolators``, ``Aiyagari-HARK.py:275``)."""
        def make(k):
            def f(m):
                return np.asarray(interp1d(np.asarray(m), self._m_knots[k],
                                           self._c_knots[k]))
            return f
        return [make(k) for k in range(self._m_grid.shape[0])]


class AiyagariSolution:
    """``type.solution[0]`` facade: per-state consumption policies."""

    def __init__(self, cFunc: List[StatePolicy]):
        self.cFunc = cFunc


class AiyagariType:
    """Household-type facade (reference ``AiyagariType``,
    ``Aiyagari_Support.py:759-804``): a parameter bag plus, after the economy
    solves, ``solution[0].cFunc``."""

    def __init__(self, **kwds):
        params = init_aiyagari_agents()
        params.update(kwds)
        self.parameters = params
        self._explicit = set(kwds)   # keys the caller actually set
        for k, v in params.items():
            setattr(self, k, v)
        self.cycles = 0          # infinite horizon (Aiyagari-HARK.py:237)
        self.solution: Optional[List[AiyagariSolution]] = None
        self.economy: Optional["AiyagariEconomy"] = None

    def get_economy_data(self, economy: "AiyagariEconomy") -> None:
        """Import economy-level objects (the reference copies KSS, Mgrid,
        AFunc, transition matrices onto the agent,
        ``Aiyagari_Support.py:817-873``; here the link suffices — the jitted
        calibration is built from both parameter sets at solve time)."""
        self.economy = economy
        self.Mgrid = economy.MSS * np.asarray(self.MgridBase)
        self.kInit = economy.KSS

    def agent_config(self) -> AgentConfig:
        return AgentConfig.from_reference_dict(self.parameters)


class AiyagariEconomy:
    """Economy/market facade (reference ``AiyagariEconomy``,
    ``Aiyagari_Support.py:1555-1964``): construct → ``make_Mrkv_history`` →
    ``solve`` → read ``sow_state``/``reap_state``/``AFunc``/``history``."""

    sow_vars = ["Mnow", "Aprev", "Mrkv", "Rnow", "Wnow"]
    reap_vars = ["aNow", "EmpNow"]
    track_vars = ["Mrkv", "Aprev", "Mnow", "Urate"]
    dyn_vars = ["AFunc"]

    def __init__(self, agents=None, tolerance: float = 0.01,
                 backend: Optional[str] = None, **kwds):
        params = init_aiyagari_economy()
        params.update(kwds)
        self.parameters = params
        self._explicit = set(kwds)   # keys the caller actually set
        # North-star backend flag: "cpu" (x64 oracle), "tpu" (f32 + highest
        # matmul precision), "auto", or None = leave the platform alone
        # (tests pick their own via conftest).  Resolved lazily at solve().
        self.backend = backend
        for k, v in params.items():
            setattr(self, k, v)
        self.agents = list(agents) if agents is not None else []
        self.tolerance = tolerance
        self.max_loops = int(kwds.get("max_loops", 40))
        self.seed = int(kwds.get("seed", 0))
        self.sow_state: dict = {}
        self.reap_state: dict = {}
        self.history: dict = {}
        self.MrkvNow_hist: Optional[np.ndarray] = None
        self.solution: Optional[KSSolution] = None
        self.update()

    # -- construction ------------------------------------------------------
    def update(self) -> None:
        """Steady-state objects and initial saving-rule guesses
        (``Aiyagari_Support.py:1593-1629``)."""
        self.AFunc = [AggregateSavingRule(self.intercept_prev[j],
                                          self.slope_prev[j])
                      for j in range(2)]
        ss = firm.perfect_foresight_steady_state(
            self.DiscFac, self.CapShare, self.DeprFac, self.LbrInd)
        self.KtoLSS = float(ss.k_to_l)
        self.KSS = float(ss.K)
        self.WSS = float(ss.W)
        self.RSS = float(ss.R)
        self.MSS = float(ss.M)
        self.KtoYSS = self.KtoLSS ** (1.0 - self.CapShare)
        self.sow_init = {"KtoLnow": self.KtoLSS, "Mnow": self.MSS,
                         "Aprev": self.KSS, "Rnow": self.RSS,
                         "Wnow": self.WSS, "Mrkv": self.MrkvNow_init}

    # Preference/process parameters a user may legitimately set on EITHER
    # the agent or the economy dict (in the reference, HARK's solver reads
    # them off the agent instance while the economy dict also carries them;
    # round-1 silently used the economy default — VERDICT r1 weak-item 5).
    _SHARED_KEYS = ("CRRA", "DiscFac", "LaborAR", "LaborSD", "LaborStatesNo")

    def economy_config(self) -> EconomyConfig:
        cfg = EconomyConfig.from_reference_dict(self.parameters)
        return cfg.replace(tolerance=float(self.tolerance),
                           verbose=bool(self.verbose),
                           max_loops=self.max_loops)

    def _economy_config_for(self, agent: AiyagariType) -> EconomyConfig:
        """Economy config with agent-level overrides honored: a key the user
        explicitly passed to ``AiyagariType(...)`` wins over the economy
        default; an explicit *conflict* between the two dicts is an error
        rather than a silent pick."""
        cfg = self.economy_config()
        from .utils.config import _ECONOMY_KEY_MAP
        for key in self._SHARED_KEYS:
            if key not in agent._explicit:
                continue
            agent_val = agent.parameters[key]
            if key in self._explicit and self.parameters[key] != agent_val:
                raise ValueError(
                    f"{key} set explicitly on both AiyagariType "
                    f"({agent_val!r}) and AiyagariEconomy "
                    f"({self.parameters[key]!r}); set it in one place")
            cfg = cfg.replace(**{_ECONOMY_KEY_MAP[key]: agent_val})
        return cfg

    def make_Mrkv_history(self, seed: Optional[int] = None) -> np.ndarray:
        """Draw the aggregate Bad/Good chain (``make_Mrkv_history``,
        ``Aiyagari_Support.py:1793-1805``; the reference uses
        ``MarkovProcess(..., seed=0)``)."""
        seed = self.seed if seed is None else seed
        agg = aggregate_markov_matrix(self.DurMeanB, self.DurMeanG)
        hist = simulate_markov_history(agg, self.MrkvNow_init, self.act_T,
                                       jax.random.PRNGKey(seed))
        self.MrkvNow_hist = np.asarray(hist)
        return self.MrkvNow_hist

    # -- solve -------------------------------------------------------------
    def solve(self, ks_employment: bool = False, dtype=None,
              **solve_kwargs) -> KSSolution:
        """Run the Krusell-Smith fixed point and populate the reference's
        result surface.  With ``backend`` set on the economy, the platform/
        dtype/precision are resolved coherently first (utils.backend).

        Solver health: a diverged solve raises
        ``solver_health.SolverDivergenceError`` — carrying the per-
        iteration status trail — instead of returning silent garbage:
        either from inside ``solve_ks_economy`` (non-finite saving-rule
        regression) or here, when the solved history/prices come back
        non-finite.  A merely-unconverged solve (``max_loops`` exhausted)
        still returns, with ``solution.converged=False`` and
        ``solution.status`` carrying the ``solver_health`` code.

        Extra keyword arguments flow to ``solve_ks_economy`` — notably
        ``sim_method="distribution"`` selects the deterministic histogram
        simulator; ``reap_state["aNow"]`` then carries an equal-weight
        quantile resample of the exact wealth distribution (so unweighted
        notebook consumers keep working), with the exact histogram under
        ``reap_state["aNowGrid"]``/``["aNowWeights"]``."""
        if not self.agents:
            raise ValueError("economy.agents is empty — assign "
                             "[AiyagariType(...)] before solve()")
        if self.backend is not None:
            from .utils.backend import select_backend
            info = select_backend(self.backend)
            if dtype is None:
                dtype = info.dtype
        agent = self.agents[0]
        sol = solve_ks_economy(
            agent.agent_config(), self._economy_config_for(agent),
            seed=self.seed, ks_employment=ks_employment, dtype=dtype,
            mrkv_hist=self.MrkvNow_hist, **solve_kwargs)
        # the regression tripwire inside solve_ks_economy catches rule
        # divergence; this guard catches garbage that never reaches the
        # rule (e.g. a non-finite simulated price path on the final pass)
        final_vals = np.asarray([float(sol.history.A_prev[-1]),
                                 float(sol.history.M_now[-1])])
        if sol.status == NONFINITE or not np.isfinite(final_vals).all():
            from .obs.runtime import emit_event

            emit_event("SOLVER_DIVERGED", where="facade",
                       status=status_name(sol.status))
            raise SolverDivergenceError(
                f"economy.solve() produced non-finite results "
                f"(status={status_name(sol.status)}, final A/M="
                f"{final_vals.tolist()}); the status trail is attached — "
                f"refusing to populate sow_state/reap_state with garbage",
                status=NONFINITE,
                trail=[{"iteration": r.iteration, "distance": r.distance,
                        "egm_status": r.egm_status,
                        "egm_status_name": status_name(r.egm_status)}
                       for r in sol.records])
        self.solution = sol
        self._populate_results(sol, agent)
        return sol

    def _populate_results(self, sol: KSSolution, agent: AiyagariType) -> None:
        hist = sol.history
        final = sol.final_panel
        self.AFunc = [AggregateSavingRule(float(sol.afunc.intercept[j]),
                                          float(sol.afunc.slope[j]))
                      for j in range(2)]
        # push the final parameters back as the next run's initial guesses —
        # the reference's in-place intercept_prev/slope_prev update
        # (Aiyagari_Support.py:1949-1951), made explicit here (parameters is
        # what economy_config() reads, so a repeat solve() warm-starts)
        self.intercept_prev = [float(x) for x in sol.afunc.intercept]
        self.slope_prev = [float(x) for x in sol.afunc.slope]
        self.parameters["intercept_prev"] = self.intercept_prev
        self.parameters["slope_prev"] = self.slope_prev
        self.sow_state = {
            "Mnow": float(final.M_now), "Aprev": float(hist.A_prev[-1]),
            "Mrkv": int(final.mrkv), "Rnow": float(final.R_now),
            "Wnow": float(final.W_now),
        }
        if hasattr(final, "assets"):      # Monte-Carlo panel (PanelState)
            self.reap_state = {
                "aNow": [np.asarray(final.assets)],
                "EmpNow": [np.asarray(final.employed)],
            }
        else:                             # DistPanelState histogram
            masses = np.asarray(final.dist)          # [D, N, 2]
            grid = np.asarray(sol.dist_grid)
            weights = masses.sum(axis=(1, 2))
            # "aNow" keeps the notebook contract in BOTH modes: an
            # equal-weight agent array (np.mean/np.std just work).  For the
            # histogram simulator it is a deterministic quantile resample
            # of the exact distribution; the exact (support, weights) pair
            # rides alongside for weighted analytics.  Round-2 shipped the
            # support itself under "aNow", which silently broke unweighted
            # consumers (VERDICT r2 weak-item 6).
            n_agents = int(agent.parameters["AgentCount"])
            # Midpoint-CDF quantile draw with the negligible truncation
            # tail trimmed (any trailing bins carrying < 0.5/n of the mass
            # in total) and the top agent pinned to the trimmed support
            # max.  Trimming protects the unweighted mean from ~1e-12
            # truncation buckets (measured: one of 100 agents teleported
            # to the a_max gridpoint and dragged the panel mean 14% off
            # the weighted mean); the pin keeps max(aNow) from
            # systematically understating a materially-occupied top bin
            # (round-3 review).  Rules and edge cases: quantile_resample.
            a_now = quantile_resample(grid, weights, n_agents)
            self.reap_state = {
                "aNow": [a_now],
                "aNowGrid": [grid],
                "aNowWeights": [weights],
                "EmpNow": [masses[:, :, 1].sum()],   # employed mass share
            }
        self.history = {
            "Mrkv": np.asarray(hist.mrkv), "Aprev": np.asarray(hist.A_prev),
            "Mnow": np.asarray(hist.M_now), "Urate": np.asarray(hist.urate),
        }
        cal = sol.calibration
        m_grid = np.asarray(cal.m_grid)
        cfuncs = [StatePolicy(sol.policy.m_knots[s], sol.policy.c_knots[s],
                              m_grid)
                  for s in range(sol.policy.m_knots.shape[0])]
        agent.solution = [AiyagariSolution(cFunc=cfuncs)]
