"""Typed configuration for agents, economies, and sweeps.

The reference configures everything through two plain dicts whose keys become
instance attributes: ``init_Aiyagari_agents`` (``Aiyagari_Support.py:752-757``)
and ``init_Aiyagari_economy`` (``Aiyagari_Support.py:1525-1551``), overridden
ad hoc by the notebook.  Here the same keys and defaults live in frozen
dataclasses (hashable, so they can ride through ``jax.jit`` as static
arguments); ``from_reference_dict`` accepts the reference's key spelling so
the notebook-style workflow runs unchanged through the facade.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Optional, Tuple

from ..obs.runtime import ObsConfig

# The reference's MgridBase (Aiyagari_Support.py:755-756): multiples of the
# steady-state aggregate market resources at which the aggregate state is
# gridded, clustered around 1.0.
MGRID_BASE_DEFAULT: Tuple[float, ...] = (
    0.1, 0.3, 0.6, 0.8, 0.9, 0.95, 0.98, 1.0, 1.02, 1.05, 1.1, 1.2, 1.6, 2.0, 3.0,
)

_AGENT_KEY_MAP = {
    "LaborStatesNo": "labor_states",
    "LaborAR": "labor_ar",
    "LaborSD": "labor_sd",
    "DiscFac": "disc_fac",
    "CRRA": "crra",
    "LbrInd": "lbr_ind",
    "aMin": "a_min",
    "aMax": "a_max",
    "aCount": "a_count",
    "aNestFac": "a_nest_fac",
    "AgentCount": "agent_count",
    "MgridBase": "mgrid_base",
}

_ECONOMY_KEY_MAP = {
    "verbose": "verbose",
    "LaborStatesNo": "labor_states",
    "LaborAR": "labor_ar",
    "LaborSD": "labor_sd",
    "act_T": "act_T",
    "T_discard": "t_discard",
    "DampingFac": "damping_fac",
    "intercept_prev": "intercept_prev",
    "slope_prev": "slope_prev",
    "DiscFac": "disc_fac",
    "CRRA": "crra",
    "LbrInd": "lbr_ind",
    "ProdB": "prod_b",
    "ProdG": "prod_g",
    "CapShare": "cap_share",
    "DeprFac": "depr_fac",
    "DurMeanB": "dur_mean_b",
    "DurMeanG": "dur_mean_g",
    "SpellMeanB": "spell_mean_b",
    "SpellMeanG": "spell_mean_g",
    "UrateB": "urate_b",
    "UrateG": "urate_g",
    "RelProbBG": "rel_prob_bg",
    "RelProbGB": "rel_prob_gb",
    "MrkvNow_init": "mrkv_now_init",
    "tolerance": "tolerance",
}


# -- precision policy (DESIGN §5) -------------------------------------------
#
# The hot fixed points (EGM expectation, distribution push-forward,
# stationary power iteration) historically forced ``precision=HIGHEST``
# end-to-end because fixed-point error compounds.  Measured (BENCH r5):
# that buys 0.097 bp of f32-vs-f64 drift at 0.059% TPU MFU — reference
# precision paid for thousands of descent iterations whose error the last
# few iterations erase.  The precision POLICY makes that trade explicit:
#
# * ``"reference"`` (default) — today's behavior, bit-identical: every
#   fixed point runs in the model dtype with HIGHEST-precision matmuls.
# * ``"mixed"`` — two-phase ladder inside one jitted program: a DESCENT
#   phase in a cheap dtype (f32 iterates for f64 models; bf16 matmul
#   inputs with f32 accumulation via ``preferred_element_type`` +
#   ``precision=DEFAULT`` on TPU) iterated to a coarse tolerance, then a
#   POLISH phase that casts the iterate up and continues in the reference
#   dtype with HIGHEST matmuls to the ORIGINAL tolerance.  The final
#   tolerance contract and solver_health semantics are unchanged; a
#   NONFINITE/STALLED descent falls back to a pure-reference solve
#   (escalation — ``solver_health.PRECISION_ESCALATED``).
# * ``"fast"`` — descent only: the cheap phase runs to the caller's
#   tolerance floored at what the cheap dtype can certify, and NO polish
#   runs.  This RELAXES the tolerance contract to the cheap-dtype floor —
#   approximate answers for exploratory sweeps, never for goldens.

PRECISION_POLICIES = ("reference", "mixed", "fast")

# Measured relative cost of one descent-phase step vs one reference step
# (CPU f32-vs-f64 vectorization roughly halves per-step cost; the TPU
# bf16 MXU path is cheaper still, so 0.5 is the conservative weight).
# Used wherever phase counters are collapsed into one work number: the
# scheduler's sidecar work model (``checkpoint.SweepSidecar.total_work``)
# and the reference-equivalent-step acceptance in ``tests/test_precision``.
DESCENT_STEP_COST = 0.5


@dataclass(frozen=True)
class PrecisionSpec:
    """Resolved ladder knobs for one precision policy (DESIGN §5)."""

    policy: str
    two_phase: bool          # a cheap-dtype descent phase runs
    polish: bool             # the reference-precision polish phase runs
    descent_step_cost: float  # per-step cost of a descent step, relative
    #                           to a reference-precision step


_PRECISION_SPECS = {
    "reference": PrecisionSpec("reference", two_phase=False, polish=True,
                               descent_step_cost=1.0),
    "mixed": PrecisionSpec("mixed", two_phase=True, polish=True,
                           descent_step_cost=DESCENT_STEP_COST),
    "fast": PrecisionSpec("fast", two_phase=True, polish=False,
                          descent_step_cost=DESCENT_STEP_COST),
}


def resolve_precision(policy) -> PrecisionSpec:
    """Validate a precision policy name (or pass a spec through)."""
    if isinstance(policy, PrecisionSpec):
        return policy
    try:
        return _PRECISION_SPECS[policy]
    except (KeyError, TypeError):
        raise ValueError(
            f"precision policy must be one of {PRECISION_POLICIES}, "
            f"got {policy!r}") from None


# -- grid policy (ISSUE 12, DESIGN §5b) --------------------------------------
#
# Every fixed point, device transfer, and compile in the framework scales
# with the grid sizes, and the reference spends dense gridpoints on the
# high-wealth region where the consumption function is provably almost
# linear (Ma-Stachurski-Toda arXiv:2002.09108: the curved region is
# confined to low wealth; the policy approaches a line whose slope is the
# perfect-foresight MPC).  The grid POLICY makes that trade explicit, the
# exact shape of the precision policy above:
#
# * ``"reference"`` (default) — today's grids, bit-identical: the full
#   exp-mult asset/histogram grids of ``ops.grids.make_asset_grid``.
# * ``"compact"`` — spend the point budget only on the curved low-wealth
#   region [a_min, a_hat] and close the top with an ANALYTIC linear tail:
#   above the knee, policy evaluation and the distribution push-forward
#   ride a linear segment whose slope is the model's asymptotic MPC
#   (``ops.utility.asymptotic_mpc``) instead of grid interpolation.  A
#   coarse-to-fine grid ladder runs inside the jitted program (descend on
#   a subsampled grid to a floored tolerance, prolong monotonically,
#   polish on the compact grid — composed with the precision ladder's
#   phases).  Scenario solvers without a tail contract (Epstein-Zin) get
#   the structural variant: sparse geometric anchors close [a_hat, a_max].
# * ``"adaptive"`` — like "compact" with the knee chosen from the
#   reference grid's own point-density profile (the wealth level below
#   which the reference already spends ``knee_density`` of its points)
#   and a slightly tighter point budget.
#
# The tolerance/certification contract is UNCHANGED under every policy:
# ``verify.certify_equilibrium``'s off-grid Euler midpoint check is the
# referee (the tail segment's midpoint directly measures the linearity
# error), and a failed/STALLED coarse phase escalates
# (``solver_health.GRID_ESCALATED``; quarantine rungs force
# ``grid="reference"`` — the dense-grid fallback).

GRID_POLICIES = ("reference", "compact", "adaptive")


@dataclass(frozen=True)
class GridSpec:
    """Resolved knobs for one grid policy (DESIGN §5b).

    Compaction is TRUNCATION-based: the compact grids keep the reference
    gridpoints below the knee BIT-exactly (nested grids — the curved
    region's discretization, and therefore its contribution to r*, is
    the goldens' own) and drop/thin only the asymptotically-linear tail.

    ``compact`` — compaction is active: the solver grid is truncated at
    the knee and closed with a linear tail; the histogram keeps its
    reference density below the knee and crosses the tail on a thinned
    point subset.  ``ladder`` — the in-program coarse-to-fine policy
    ladder runs (subsampled descent, monotone prolongation, compact-grid
    polish).  ``knee_frac`` — static knee position as a fraction of the
    grid span (None = density knee); ``knee_density`` — the reference
    solver-grid point quantile the density knee sits at (0.85 = the knee
    is where the reference has already spent 85% of its points — above
    it the exp-mult spacing is wide and the policy provably near-linear).
    ``dist_tail_frac`` — the fraction of reference HISTOGRAM tail points
    kept (evenly thinned, top point always kept so the support span is
    unchanged).  ``tail_points`` — minimum tail points, and the anchor
    count for the structural ("anchors") solver-tail variant.
    ``coarse_tol_factor`` — the grid ladder's descent-tolerance
    relaxation over the requested tol."""

    policy: str
    compact: bool
    ladder: bool
    knee_frac: Optional[float] = None
    knee_density: float = 0.85
    dist_tail_frac: float = 0.5
    tail_points: int = 6
    coarse_tol_factor: float = 50.0


_GRID_SPECS = {
    "reference": GridSpec("reference", compact=False, ladder=False),
    "compact": GridSpec("compact", compact=True, ladder=True,
                        knee_frac=None, knee_density=0.85,
                        dist_tail_frac=0.5, tail_points=6,
                        coarse_tol_factor=50.0),
    "adaptive": GridSpec("adaptive", compact=True, ladder=True,
                         knee_frac=None, knee_density=0.75,
                         dist_tail_frac=0.34, tail_points=6,
                         coarse_tol_factor=50.0),
}


def resolve_grid(policy) -> GridSpec:
    """Validate a grid policy name (or pass a spec through) — the ONE
    validation surface, mirrored on ``resolve_precision``: an unknown
    policy raises here, before it can alias a real one in any cache key
    (``utils.fingerprint.hashable_kwargs`` routes through this)."""
    if isinstance(policy, GridSpec):
        return policy
    try:
        return _GRID_SPECS[policy]
    except (KeyError, TypeError):
        raise ValueError(
            f"grid policy must be one of {GRID_POLICIES}, "
            f"got {policy!r}") from None


# -- kernel policy (ISSUE 13, DESIGN §4c) ------------------------------------
#
# PR 10's CostLedger measured the sweep LATENCY-bound at ~0.06% MFU: the
# hot loops are dominated by many tiny per-iteration launches, not by
# arithmetic.  The kernel POLICY makes the fix opt-in, the exact shape of
# the precision/grid policies above:
#
# * ``"reference"`` (default) — today's engine selection, bit-identical:
#   the XLA while_loop paths (and the probe-gated per-loop Pallas
#   kernels where the existing method knobs pick them).
# * ``"fused"`` — the device-resident fused solve path, two legs gated
#   by the ambient precision policy:
#   (a) under a SINGLE-phase precision policy, the EGM policy iteration
#       and the distribution push-forward of every supply evaluation run
#       as ONE Pallas megakernel per lane (``ops.pallas_kernels.
#       fused_cell_pallas{,_grid}``): shared VMEM residency of the
#       grids/transition matrix, per-lane early exit, the analytic-tail
#       closure applied in-kernel for compact grids, and the push-forward
#       restructured into one tile-shaped MXU contraction per step
#       (``ops.markov.tiled_wealth_push_forward``).  Probe-gated on TPU
#       with the XLA paths as fallback; interpret-mode on CPU (the CI
#       correctness path).
#   (b) under a TWO-phase precision policy ("mixed"/"fast") the ladders
#       gain the bf16 DESCENT rung below f32 (TPU-only; the x^(-1/gamma)
#       FOC inversion stays f32) — a NONFINITE/STALLED bf16 rung
#       escalates to the f32 descent exactly as a failed descent
#       escalates to the reference polish today (the PRECISION_ESCALATED
#       slot).
#
# The tolerance/certification contract is UNCHANGED: the fused engines
# run the SAME iteration code to the same tolerances (values agree to
# float-fusion noise — the tiled contraction reorders reductions), and
# quarantine rungs force ``kernel="reference"`` (the launch-per-loop
# fallback, the one configuration the goldens certify).

KERNEL_POLICIES = ("reference", "fused")


@dataclass(frozen=True)
class KernelSpec:
    """Resolved knobs for one kernel policy (ISSUE 13, DESIGN §4c).

    ``fused`` — the EGM+push-forward megakernel path runs wherever the
    ambient precision policy is single-phase (the kernel runs one
    precision end-to-end).  ``bf16_descent`` — under a two-phase
    precision policy the descent ladder gains the bf16 rung (gated
    TPU-only at the solver seam, ``models.household.bf16_rung_active``).
    """

    policy: str
    fused: bool
    bf16_descent: bool


_KERNEL_SPECS = {
    "reference": KernelSpec("reference", fused=False, bf16_descent=False),
    "fused": KernelSpec("fused", fused=True, bf16_descent=True),
}


def resolve_kernel(policy) -> KernelSpec:
    """Validate a kernel policy name (or pass a spec through) — the ONE
    validation surface, mirrored on ``resolve_precision``/``resolve_grid``:
    an unknown policy raises here, before it can alias a real one in any
    cache key (``utils.fingerprint.hashable_kwargs`` routes through
    this)."""
    if isinstance(policy, KernelSpec):
        return policy
    try:
        return _KERNEL_SPECS[policy]
    except (KeyError, TypeError):
        raise ValueError(
            f"kernel policy must be one of {KERNEL_POLICIES}, "
            f"got {policy!r}") from None


# -- state-sharding policy (ISSUE 20, DESIGN §6b) ----------------------------
#
# Every scaling lever through PR 18 parallelizes over sweep CELLS; the
# per-cell state — the distribution [D, N] and the dense wealth-transition
# operator [N, D, D] — is replicated and must fit one device, which caps
# asset-grid resolution.  The STATE policy partitions those tensors along
# the wealth axis across a second, orthogonal mesh axis ("state",
# ``parallel.mesh.STATE_AXIS``):
#
# * ``"replicated"`` (default) — today's layout, bit-identical: no state
#   mesh consulted, no sharding constraints emitted.
# * ``"sharded"`` — distribution rows and operator row-blocks placed per
#   the partition-rule table (``parallel.mesh.STATE_PARTITION_RULES``);
#   the push-forward becomes a row-block contraction with ONE all-reduce
#   per step (GSPMD places it from the constraints).  NOT bit-identical
#   to replicated — the sharded contraction reorders the wealth-axis
#   reduction — but r* agrees to <0.1bp (the acceptance gate
#   ``bench.py --state-scaling`` measures).  Quarantine rungs force
#   ``"replicated"`` (the certified configuration).

STATE_POLICIES = ("replicated", "sharded")


@dataclass(frozen=True)
class StateSpec:
    """Resolved knobs for one state-sharding policy (ISSUE 20, DESIGN §6b).

    ``sharded`` — place distribution rows / operator row-blocks on the
    "state" mesh axis and run the push-forward as a row-block contraction.
    Inert without an ACTIVE state mesh of size > 1
    (``parallel.mesh.active_state_mesh``): policy resolution is pure
    config, geometry comes from the mesh seam."""

    policy: str
    sharded: bool


_STATE_SPECS = {
    "replicated": StateSpec("replicated", sharded=False),
    "sharded": StateSpec("sharded", sharded=True),
}


def resolve_state(policy) -> StateSpec:
    """Validate a state-sharding policy name (or pass a spec through) —
    the ONE validation surface, mirrored on ``resolve_precision``/
    ``resolve_grid``/``resolve_kernel``: an unknown policy raises here,
    before it can alias a real one in any cache key
    (``utils.fingerprint.hashable_kwargs`` routes through this)."""
    if isinstance(policy, StateSpec):
        return policy
    try:
        return _STATE_SPECS[policy]
    except (KeyError, TypeError):
        raise ValueError(
            f"state policy must be one of {STATE_POLICIES}, "
            f"got {policy!r}") from None


# Packed device-row layout of the AIYAGARI batched cell solver: ONE
# stacked float row per cell means ONE device->host transfer per launch
# (the round-5 packing rationale, ``parallel.sweep._batched_solver``).
# This tuple is the DEFINITION SITE only (ISSUE 9): every consumer —
# sweep engine, resume ledger, serving store, certifier — now reads the
# layout through the scenario's ``scenarios.RowSchema`` (built from this
# constant in ``scenarios/aiyagari.py``), and the ledger fingerprint
# hashes the schema's field names (an old-layout ledger refuses to
# resume instead of crashing a restarted sweep).  Direct imports outside
# ``scenarios/`` are banned by ``scripts/check_row_schema.py``.
PACKED_ROW_FIELDS = ("r_star", "capital", "labor", "bisect_iters",
                     "egm_iters", "dist_iters", "status",
                     "descent_steps", "polish_steps",
                     "precision_escalations")
PACKED_ROW_WIDTH = len(PACKED_ROW_FIELDS)


@dataclass(frozen=True)
class AgentConfig:
    """Household-side parameters.  Defaults mirror ``init_Aiyagari_agents``
    (``Aiyagari_Support.py:752-757``)."""

    labor_states: int = 7
    labor_ar: float = 0.6
    labor_sd: float = 0.2
    labor_bound: float = 3.0
    disc_fac: float = 0.96
    crra: float = 1.0
    lbr_ind: float = 1.0
    a_min: float = 0.001
    a_max: float = 50.0
    a_count: int = 32
    a_nest_fac: int = 2
    agent_count: int = 140
    mgrid_base: Tuple[float, ...] = MGRID_BASE_DEFAULT

    @classmethod
    def from_reference_dict(cls, d: dict) -> "AgentConfig":
        kwargs = {}
        for ref_key, our_key in _AGENT_KEY_MAP.items():
            if ref_key in d:
                v = d[ref_key]
                if our_key == "mgrid_base":
                    v = tuple(float(x) for x in v)
                kwargs[our_key] = v
        return cls(**kwargs)

    def replace(self, **kwargs) -> "AgentConfig":
        return dataclasses.replace(self, **kwargs)


@dataclass(frozen=True)
class EconomyConfig:
    """Economy-side parameters.  Defaults mirror ``init_Aiyagari_economy``
    (``Aiyagari_Support.py:1525-1551``) plus the ``tolerance`` ctor kwarg
    (``Aiyagari_Support.py:1574``)."""

    verbose: bool = True
    labor_states: int = 7
    labor_ar: float = 0.6
    labor_sd: float = 0.2
    labor_bound: float = 3.0
    act_T: int = 11000
    t_discard: int = 1000
    damping_fac: float = 0.5
    intercept_prev: Tuple[float, float] = (0.0, 0.0)
    slope_prev: Tuple[float, float] = (1.0, 1.0)
    disc_fac: float = 0.96
    crra: float = 1.0
    lbr_ind: float = 1.0
    prod_b: float = 1.0
    prod_g: float = 1.0
    cap_share: float = 0.36
    depr_fac: float = 0.08
    dur_mean_b: float = 8.0
    dur_mean_g: float = 8.0
    spell_mean_b: float = 2.5
    spell_mean_g: float = 1.5
    urate_b: float = 0.0
    urate_g: float = 0.0
    rel_prob_bg: float = 0.75
    rel_prob_gb: float = 1.25
    mrkv_now_init: int = 0
    tolerance: float = 0.01
    max_loops: int = 40

    @classmethod
    def from_reference_dict(cls, d: dict) -> "EconomyConfig":
        kwargs = {}
        for ref_key, our_key in _ECONOMY_KEY_MAP.items():
            if ref_key in d:
                v = d[ref_key]
                if our_key in ("intercept_prev", "slope_prev"):
                    v = tuple(float(x) for x in v)
                kwargs[our_key] = v
        return cls(**kwargs)

    def replace(self, **kwargs) -> "EconomyConfig":
        return dataclasses.replace(self, **kwargs)


def notebook_run_configs() -> Tuple[AgentConfig, EconomyConfig]:
    """The configuration of the reference's *executed* notebook run (cells
    16-17; SURVEY.md §6): LaborAR=0.3, LaborSD=0.2, CRRA=1.0, AgentCount=350.
    (The stale .py export instead carries CRRA=5, rho=0.9 — see SURVEY §2.2 D5.)
    """
    agent = AgentConfig(labor_ar=0.3, labor_sd=0.2, crra=1.0, agent_count=350)
    econ = EconomyConfig(labor_ar=0.3, labor_sd=0.2, crra=1.0)
    return agent, econ


@dataclass(frozen=True)
class SweepConfig:
    """A calibration sweep over (CRRA sigma, labor AR rho) cells — Aiyagari
    Table II (sigma in {1,3,5} x rho in {0,0.3,0.6,0.9}, BASELINE.json).

    ``labor_sd`` may be a tuple to add the stationary-s.d. panel axis:
    ``labor_sd=(0.2, 0.4)`` runs BOTH of Aiyagari's Table II panels as
    one batched program (24 cells).

    Scheduler knobs (ISSUE 2; mechanics in ``parallel.sweep`` and DESIGN
    §4b):

    * ``schedule`` — "locked": the whole batch as ONE vmapped launch
      (every lane lock-steps until the slowest cell converges);
      "balanced": cells sorted by predicted work into ``n_buckets``
      work-homogeneous buckets solved as separate launches of one shared
      executable, un-permuted before ``SweepResult`` (bit-order-identical
      output); "auto" (default): "balanced" for >= 8 cells on
      non-accelerator backends, else "locked" (bucketing a tiny batch
      only adds dispatches, and through the tunneled TPU each launch
      costs ~0.7 s round trip — accelerator callers opt in explicitly).
    * ``n_buckets`` — bucket count for "balanced"; 0 = auto (~C/3,
      capped at 8).
    * ``warm_brackets`` — seed each cell's bisection bracket by dyadic
      descent toward a known root (sidecar same-cell root, else the
      nearest already-solved neighbor in (σ, ρ, sd)); every seed is
      verified in-program before it is trusted.  Off by default: it
      changes inner-loop trajectories (answers move at inner-solver
      noise, certified tolerance untouched), so golden-pinned runs keep
      the cold path unless they opt in.
    * ``warm_margin`` — half-width (in r units) the descended bracket
      must keep around the seed root; 0.0 = auto (tight for sidecar
      same-cell seeds, conservative for neighbor seeds).
    * ``work_model`` — "sidecar": require prior-run counters
      (``sidecar_path``); "heuristic": the (σ, ρ, sd) regression;
      "auto": sidecar when present and fingerprint-valid, else
      heuristic.
    * ``sidecar_path`` — npz path for prior-run counters/roots
      (``utils.checkpoint.SweepSidecar``); written after every scheduled
      solve, read before.  None disables persistence.
    * ``compilation_cache`` — enable jax's persistent XLA compilation
      cache (``utils.backend.enable_compilation_cache``; dir from
      ``$AIYAGARI_CACHE_DIR``, kill switch ``$AIYAGARI_COMPILATION_CACHE=0``)
      before compiling sweep programs, so repeated processes skip XLA
      entirely.
    * ``resume_path`` — npz path for the durable resume ledger (ISSUE 3,
      ``utils.resilience.SweepLedger``): solved buckets and quarantine
      outcomes are flushed there atomically as the sweep progresses, and
      a restarted identical run (fingerprint-checked) skips completed
      work, reassembling a bit-identical ``SweepResult``.  Deleted on
      successful completion.  None (default) disables persistence; the
      ``run_table2_sweep(resume_path=)`` argument overrides.

    Integrity knobs (ISSUE 6, DESIGN §9):

    * ``recheck_fraction`` — SDC spot-check rate: deterministically
      re-solve a fingerprint-sampled ``ceil(fraction * C)`` subset of
      cells in a PERMUTED lane position after the batched solve and
      compare the packed rows bitwise (the packing-independence
      contract makes any mismatch a silent-data-corruption signal, not
      noise).  A mismatching cell is recorded ``sdc_suspected`` and
      routed through the quarantine retry ladder for a trusted re-solve.
      0.0 (default) disables; the recheck runs outside the timed wall.
    * ``certify`` — a posteriori certification of every cell after the
      solve (``verify.certify_equilibrium`` recompute path): Euler /
      stationarity / market-clearing / shape residuals against
      ``verify.CertThresholds`` for this configuration, recorded
      per-cell in ``SweepResult.cert_level``.

    Grid knob (ISSUE 12, DESIGN §5b):

    * ``grid`` — the grid policy every cell solves under
      (``GRID_POLICIES``): "reference" (default, bit-identical dense
      grids), "compact"/"adaptive" (curved-region point budget +
      analytic linear tail + in-program coarse-to-fine ladder).
      Applied as a model-kwarg default — an explicit
      ``run_sweep(..., grid=...)`` kwarg wins — so it rides every
      fingerprint (sidecar, resume ledger, store keys) through the
      same ``hashable_kwargs`` normalization as ``precision``.
      Quarantine rungs force ``grid="reference"`` (the dense-grid
      escalation).

    Kernel knob (ISSUE 13, DESIGN §4c):

    * ``kernel`` — the kernel policy every cell solves under
      (``KERNEL_POLICIES``): "reference" (default, bit-identical
      launch-per-loop engines), "fused" (the device-resident
      EGM+push-forward megakernel per supply evaluation under
      single-phase precision; the bf16 descent rung under two-phase —
      probe-gated on TPU, interpret-mode on CPU, XLA fallback).
      Applied as a model-kwarg default exactly like ``grid`` — an
      explicit ``run_sweep(..., kernel=...)`` kwarg wins — so it rides
      every fingerprint (sidecar, resume ledger, store keys) through
      ``hashable_kwargs``.  Quarantine rungs force
      ``kernel="reference"`` (the launch-per-loop escalation).

    State-sharding knob (ISSUE 20, DESIGN §6b):

    * ``state_shards`` — how many ways each cell's STATE (distribution
      rows, wealth-operator row blocks) is partitioned across the
      second mesh axis ("state").  1 (default) keeps today's replicated
      layout bit-identical; M > 1 builds a 2-D (cells × state) mesh,
      activates it around the sweep (``parallel.mesh.active_state_mesh``)
      and applies ``state="sharded"`` as a model-kwarg default exactly
      like ``grid``/``kernel`` — an explicit ``run_sweep(..., state=...)``
      kwarg wins — so the policy rides every fingerprint through
      ``hashable_kwargs`` and the ledger fingerprint hashes BOTH mesh
      axes (an N×M ledger refuses to resume under N'×M').  Quarantine
      rungs force ``state="replicated"`` (the certified layout).

    Observability knob (ISSUE 7, DESIGN §10):

    * ``obs`` — an ``obs.ObsConfig``: run-scoped tracing spans
      (per-bucket launches, quarantine rungs, recheck/certify),
      metrics-registry mirrors of the sweep counters, and typed journal
      events (BUCKET_LAUNCH, QUARANTINE, SDC_SUSPECTED, ...) correlated
      by one ``run_id``.  None (default) disables with near-zero
      overhead and changes ZERO solver bits; the
      ``run_table2_sweep(obs=)`` argument overrides (pass a shared
      ``obs.Obs`` bundle to correlate several subsystems under one
      run)."""

    crra_values: Tuple[float, ...] = (1.0, 3.0, 5.0)
    rho_values: Tuple[float, ...] = (0.0, 0.3, 0.6, 0.9)
    labor_sd: float | Tuple[float, ...] = 0.2
    schedule: str = "auto"
    n_buckets: int = 0
    warm_brackets: bool = False
    warm_margin: float = 0.0
    work_model: str = "auto"
    sidecar_path: str | None = None
    compilation_cache: bool = True
    resume_path: str | None = None
    recheck_fraction: float = 0.0
    certify: bool = False
    grid: str = "reference"
    kernel: str = "reference"
    state_shards: int = 1
    obs: Optional[ObsConfig] = None

    def replace(self, **kwargs) -> "SweepConfig":
        return dataclasses.replace(self, **kwargs)

    def sd_values(self) -> Tuple[float, ...]:
        # normalize sequences to tuples (same policy as the sweep's
        # _hashable_kwargs) so a list doesn't leak into cells() and die
        # in np.asarray with an unhelpful error
        if isinstance(self.labor_sd, (tuple, list)):
            return tuple(float(s) for s in self.labor_sd)
        return (float(self.labor_sd),)

    def cells(self):
        return [(s, r, sd) for sd in self.sd_values()
                for s in self.crra_values for r in self.rho_values]


@dataclass(frozen=True)
class AdmissionPolicy:
    """Overload management for the serving engine (ISSUE 8, DESIGN §11).

    ``EquilibriumService(admission=AdmissionPolicy(...))`` turns
    saturation into a first-class typed state: fail-fast admission
    instead of unbounded queueing, priority load shedding, degraded
    nearest-neighbor answers, and per-region circuit breakers.  ``None``
    (the service default) disables the whole layer — behavior, and every
    served bit, is identical to the pre-overload engine.

    Admission (``serve.service.Overloaded``):

    * ``max_work`` — total queue-occupancy budget in predicted-work
      units (``parallel.sweep.heuristic_cell_work``; a baseline
      σ=1, ρ=0 cell weighs ~1.0).  Weighted occupancy over budget
      rejects fail-fast with depth + estimated wait (retry-after).
    * ``class_shares`` — nested per-priority-class budgets, indexed by
      ``serve.Priority`` (INTERACTIVE=0 > BATCH=1 > SPECULATIVE=2):
      classes >= c together may hold at most
      ``max_work * class_shares[c]``, so background work can never
      starve interactive headroom.
    * ``shed`` — when a class budget rejects an arrival, displace the
      least-important/youngest queued pending instead (its future fails
      with the typed ``LoadShed``) — strictly-lower classes only.
    * ``deadline_aware`` — reject at submit (not at the batch seam) any
      query whose ``deadline`` is shorter than the estimated wait
      (queued batches ahead x recent batch latency).
    * ``est_batch_s`` — fixed modeled batch latency for the wait
      estimate; ``None`` uses a measured EWMA (the load harness pins
      this so admission decisions replay bit-identically).
    * ``work_unit_s`` — modeled seconds of batch wall per predicted-work
      unit, the EWMA's COLD-START seed (ISSUE 15 satellite): before the
      first batch ever flushes there is no measured latency, so the
      first ``Overloaded.est_wait_s`` used to collapse to the batcher's
      ``max_wait_s`` (milliseconds against a multi-second solve — a
      degenerate retry-after).  The first admission-checked submit seeds
      the EWMA with its own ``heuristic_cell_work`` predicted wall
      (``weight * work_unit_s``), which the first measured flush then
      starts correcting.

    Degraded answers (PAPERS 2002.09108 — consumption functions are
    asymptotically linear, so a near neighbor is a principled brown-out
    response):

    * ``degraded_pressure`` — occupancy fraction past which an opt-in
      ``degraded_ok`` query is answered from the store's nearest
      neighbor instead of queueing a cold solve.
    * ``degraded_distance`` — normalized (σ, ρ, sd) distance budget
      (``parallel.sweep.neighbor_distance`` units) beyond which the
      degraded path declines and the query falls through to admission.
    * ``degraded_require_certified`` — only donors with a
      CERTIFIED/MARGINAL ``verify`` certificate may answer.

    Regional circuit breakers (``serve.overload.CircuitBreaker``):

    * ``breaker_failures`` — consecutive failures (NONFINITE/MAX_ITER
      solves, failed certifications) in one (σ, ρ, sd) region that open
      its breaker (typed ``CircuitOpen`` fast-fail until a probe
      succeeds).
    * ``breaker_cooldown_s`` — open -> half-open probe delay in clock
      units, doubling per reopen up to ``breaker_backoff_cap`` x.
    * ``breaker_region_scale`` — quantization of (σ, ρ, sd) into
      breaker regions (a region is a neighborhood, not a single cell).
    """

    max_work: float = 64.0
    class_shares: Tuple[float, ...] = (1.0, 0.5, 0.25)
    shed: bool = True
    deadline_aware: bool = True
    est_batch_s: Optional[float] = None
    work_unit_s: float = 0.25
    degraded_pressure: float = 0.7
    degraded_distance: float = 0.25
    degraded_require_certified: bool = False
    breaker_failures: int = 3
    breaker_cooldown_s: float = 1.0
    breaker_backoff_cap: int = 8
    breaker_region_scale: Tuple[float, float, float] = (2.0, 0.3, 0.1)

    def replace(self, **kwargs) -> "AdmissionPolicy":
        return dataclasses.replace(self, **kwargs)


# -- named benchmark configurations (BASELINE.json "configs") ---------------

def baseline_cell_kwargs() -> dict:
    """BASELINE.json config 1 — "Baseline Aiyagari: sigma=3, rho=0.6,
    7-state Tauchen, 100-pt asset grid": (crra, labor_ar) plus solver
    kwargs for ``models.equilibrium.solve_calibration``."""
    return dict(crra=3.0, labor_ar=0.6, labor_states=7, a_count=100,
                dist_count=500)


def fine_grid_kwargs() -> dict:
    """BASELINE.json config 2 — "Fine-grid baseline: 1000-pt asset grid,
    15-state income Markov".  A pure shape change for the N-generic batched
    solver (the reference hard-codes 7 states everywhere, SURVEY.md
    §3.6-2, and could not run this)."""
    return dict(crra=3.0, labor_ar=0.6, labor_states=15, a_count=1000,
                dist_count=1000)
