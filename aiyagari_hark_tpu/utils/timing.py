"""Profiling and structured observability.

The reference records wall-clock with bare ``time.time()`` pairs written to
``runtime.txt`` (``Aiyagari-HARK.py:184-185, 352-361``) and prints regression
parameters when ``verbose`` (SURVEY.md §5).  Here: named phase timers with an
accumulating report, a JSON-lines writer for iteration records, and an
optional ``jax.profiler`` trace context for device-level traces (perfetto).
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import time
from collections import defaultdict
from typing import Dict, Iterable


class PhaseTimer:
    """Accumulating named timers: ``with timer.phase("solve"): ...``.

    ``report()`` returns {phase: seconds}; ``counts`` holds invocation
    counts.  Wall-clock only (device work should be bracketed with
    ``block_until_ready`` by the caller, as the solvers do).
    """

    def __init__(self):
        self.seconds: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.seconds[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def report(self) -> Dict[str, float]:
        return dict(self.seconds)

    def summary(self) -> str:
        total = sum(self.seconds.values())
        lines = [f"{name:>12s}: {sec:8.3f}s x{self.counts[name]:<4d} "
                 f"({100.0 * sec / total:5.1f}%)"
                 for name, sec in sorted(self.seconds.items(),
                                         key=lambda kv: -kv[1])]
        return "\n".join(lines + [f"{'total':>12s}: {total:8.3f}s"])


def write_records_jsonl(path: str, records: Iterable) -> None:
    """Persist iteration records (e.g. ``KSIterationRecord`` dataclasses or
    dicts) as JSON lines — the structured replacement for the reference's
    ``verbose`` prints (``Aiyagari_Support.py:1954-1962``)."""
    with open(path, "w") as f:
        for rec in records:
            if dataclasses.is_dataclass(rec) and not isinstance(rec, type):
                rec = dataclasses.asdict(rec)
            f.write(json.dumps(rec) + "\n")


def read_records_jsonl(path: str):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


@contextlib.contextmanager
def device_trace(log_dir: str | None):
    """``jax.profiler`` trace context (perfetto dump under ``log_dir``);
    no-op when ``log_dir`` is None so call sites need no branching."""
    if log_dir is None:
        yield
        return
    import jax
    with jax.profiler.trace(log_dir):
        yield
