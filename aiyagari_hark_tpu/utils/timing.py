"""Profiling and structured observability.

The reference records wall-clock with bare ``time.time()`` pairs written to
``runtime.txt`` (``Aiyagari-HARK.py:184-185, 352-361``) and prints regression
parameters when ``verbose`` (SURVEY.md §5).  Here: named phase timers with an
accumulating report, a JSON-lines writer for iteration records, and an
optional ``jax.profiler`` trace context for device-level traces (perfetto).
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import time
from collections import defaultdict
from typing import Dict, Iterable, NamedTuple


class PhaseTimer:
    """Accumulating named timers: ``with timer.phase("solve"): ...``.

    ``report()`` returns {phase: seconds}; ``counts`` holds invocation
    counts.  Wall-clock only (device work should be bracketed with
    ``block_until_ready`` by the caller, as the solvers do).
    """

    def __init__(self):
        self.seconds: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.seconds[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def report(self) -> Dict[str, float]:
        return dict(self.seconds)

    def summary(self) -> str:
        total = sum(self.seconds.values())
        lines = [f"{name:>12s}: {sec:8.3f}s x{self.counts[name]:<4d} "
                 f"({100.0 * sec / total:5.1f}%)"
                 for name, sec in sorted(self.seconds.items(),
                                         key=lambda kv: -kv[1])]
        return "\n".join(lines + [f"{'total':>12s}: {total:8.3f}s"])


class Stopwatch:
    """A started wall timer: ``elapsed()`` reads the running interval,
    ``seconds`` is filled at exit when used through ``stopwatch()`` (NaN
    while still running)."""

    __slots__ = ("t0", "seconds")

    def __init__(self):
        self.t0 = time.perf_counter()
        self.seconds = float("nan")

    def elapsed(self) -> float:
        """Seconds since construction (monotonic clock)."""
        return time.perf_counter() - self.t0


@contextlib.contextmanager
def stopwatch():
    """Measure one wall interval: ``with stopwatch() as sw: ...`` then
    read ``sw.seconds`` (or construct ``Stopwatch()`` directly and poll
    ``elapsed()`` for loop-shaped measurement).  The ONE blessed
    ad-hoc-timing primitive for hot modules (ISSUE 10:
    ``scripts/check_timing_discipline.py`` bans bare
    ``time.perf_counter()``/``time.time()`` pairs in ``parallel/``,
    ``serve/``, ``obs/``, ``models/`` — a measured wall must either be a
    ``Tracer`` span or flow through here, so every timing read uses the
    same monotonic clock and the same exception-safe fill-on-exit
    semantics)."""
    sw = Stopwatch()
    try:
        yield sw
    finally:
        sw.seconds = time.perf_counter() - sw.t0


def write_records_jsonl(path: str, records: Iterable,
                        append: bool = False) -> None:
    """Persist iteration records (e.g. ``KSIterationRecord`` dataclasses or
    dicts) as JSON lines — the structured replacement for the reference's
    ``verbose`` prints (``Aiyagari_Support.py:1954-1962``).  Routed
    through the ``utils.checkpoint`` writer family in BOTH modes
    (ISSUE 7 satellite; ``scripts/check_atomic_writes.py`` bans bare
    write- AND append-mode handles on artifact paths):

    * ``append=False`` (default) — whole-file replace via
      ``atomic_write_text`` (tmp + ``os.replace``): a kill mid-write
      leaves the previous file, never a truncated hybrid.
    * ``append=True`` — ``checkpoint.append_jsonl``: one ``os.write``
      per complete line to an ``O_APPEND`` descriptor, so a growing
      bench/iteration stream survives SIGTERM with at most a torn FINAL
      line — which ``read_records_jsonl`` detects and skips."""
    from .checkpoint import append_jsonl, atomic_write_text

    lines = []
    for rec in records:
        if dataclasses.is_dataclass(rec) and not isinstance(rec, type):
            rec = dataclasses.asdict(rec)
        lines.append(json.dumps(rec) + "\n")
    if append:
        append_jsonl(path, lines)
    else:
        atomic_write_text(path, "".join(lines))


def read_records_jsonl(path: str):
    """Read a records JSONL back, SKIPPING unparseable lines
    (``checkpoint.read_jsonl_tolerant`` — the shared reader half of
    ``append_jsonl``'s crash contract): a bench resuming after the
    preemption it recorded must still read its own history.  Skips are
    warned with a count, never silent."""
    from .checkpoint import read_jsonl_tolerant

    out, bad = read_jsonl_tolerant(path)
    if bad:
        import warnings

        warnings.warn(
            f"records jsonl {path}: skipped {bad} unparseable line(s) "
            "(torn tail from a hard kill mid-append?)", stacklevel=2)
    return out


def model_flops(egm_iters: float, dist_iters: float, a_count: int,
                n_states: int, d_count: int, dense_dist: bool) -> float:
    """Model FLOPs executed by counted inner-loop work — the ONE accounting
    shared by the sweep headline, the lanes-scaling entries, and the
    fine-grid phase (moved here from ``bench.py`` so the fine-grid capture
    can be reconstructed from counters wherever they were measured —
    VERDICT r5 flagged the still-null ``fine_grid_mfu_pct`` /
    ``fine_grid_flops_per_sec`` fields twice).

    Per EGM backward step (``household.egm_step``): the expectation matmul
    ``[A,N] x [N,N]`` is 2*A*N^2 FLOPs; interp/elementwise add ~12*A*N.
    Per distribution step: the dense path (``_push_forward_dense``) runs the
    per-state lottery matvecs ``[N,D,D] x [D]`` (2*N*D^2) plus the labor-mix
    matmul ``[D,N] x [N,N]`` (2*D*N^2); the scatter path replaces the D^2
    matvecs with an O(D*N) scatter (~6 FLOPs/point), keeping the mix matmul.
    """
    egm = egm_iters * (2.0 * a_count * n_states ** 2
                       + 12.0 * a_count * n_states)
    per_dist = 2.0 * d_count * n_states ** 2
    per_dist += (2.0 * n_states * d_count ** 2 if dense_dist
                 else 6.0 * d_count * n_states)
    return egm + dist_iters * per_dist


class PeakFlops(NamedTuple):
    """The MFU denominator and its provenance: ``assumed=True`` means the
    chip kind was not recognized and ``value`` is a class GUESS — record
    it as ``peak_flops_assumed``, never pass it off as measured."""

    value: float | None
    assumed: bool


_ASSUMED_PEAK_WARNED: set = set()


def peak_flops_per_chip(backend: str) -> PeakFlops:
    """Nominal peak FLOP/s of one chip for the MFU denominator, with an
    ``assumed`` flag for unrecognized accelerators.

    TPU v5-lite (v5e): 197e12 bf16 MXU peak — the honest ceiling even
    though this framework runs f32 matmuls at ``precision=HIGHEST`` (which
    costs multiple bf16 passes), because MFU is about how much of the
    silicon the problem could engage.  CPU gets no MFU (no meaningful
    single-number peak for this host).  An UNKNOWN TPU kind used to get
    197e12 silently — an MFU built on a guessed denominator read exactly
    like a measured one; now the guess warns once per kind and callers
    must surface ``assumed`` in their records (``peak_flops_assumed``,
    bench/serve — ISSUE 4 satellite).
    """
    if backend not in ("tpu", "axon"):
        return PeakFlops(None, False)
    try:
        import jax
        kind = jax.devices()[0].device_kind.lower()
    except Exception:   # noqa: BLE001 — device query is best-effort
        kind = ""
    if "v5 lite" in kind or "v5e" in kind or "v5lite" in kind:
        return PeakFlops(197e12, False)
    if "v4" in kind:
        return PeakFlops(275e12, False)
    if "v5p" in kind or "v5" in kind:
        return PeakFlops(459e12, False)
    # unknown TPU: assume the v5e class this repo targets, loudly
    if kind not in _ASSUMED_PEAK_WARNED:
        _ASSUMED_PEAK_WARNED.add(kind)
        import warnings

        warnings.warn(
            f"unrecognized TPU device kind {kind!r}: assuming the v5e "
            "peak (197e12 FLOP/s) for MFU — treat mfu_pct as approximate "
            "(peak_flops_assumed=True in records)", stacklevel=2)
    return PeakFlops(197e12, True)


def flop_report(egm_iters: float, dist_iters: float, wall_s: float,
                a_count: int, n_states: int, d_count: int,
                dense_dist: bool, backend: str,
                measured_flops: float | None = None) -> dict:
    """Achieved FLOP rate + MFU for one measured phase, as record fields:
    ``{"flops_per_sec": ..., "mfu_pct": ..., "peak_flops_assumed": ...,
    "flops_provenance": ...}`` (mfu None off-accelerator;
    ``peak_flops_assumed`` True when the MFU denominator is the
    unknown-chip class guess).  Never raises on a degenerate wall — a
    broken phase records nulls, not a crashed bench.

    ``measured_flops`` is the optional MEASURED numerator (ISSUE 10): a
    total FLOP count from XLA's own cost analysis
    (``obs.profile.CostLedger.measured_flops_total``) used INSTEAD of
    the analytic step-count model.  ``flops_provenance`` records which
    source produced the fields — ``"analytic"`` (the ``model_flops``
    hand model) or ``"xla_cost_analysis"`` — so ``peak_flops_assumed``
    is no longer the only honesty bit on an MFU number: a reader can now
    see whether BOTH sides of the ratio were measured."""
    if wall_s is None or not wall_s > 0:
        return {"flops_per_sec": None, "mfu_pct": None,
                "peak_flops_assumed": False, "flops_provenance": None}
    if measured_flops is not None:
        flops = float(measured_flops)
        provenance = "xla_cost_analysis"
    else:
        flops = model_flops(egm_iters, dist_iters, a_count, n_states,
                            d_count, dense_dist)
        provenance = "analytic"
    peak = peak_flops_per_chip(backend)
    return {"flops_per_sec": round(flops / wall_s),
            "mfu_pct": (None if peak.value is None
                        else round(100.0 * flops / wall_s / peak.value, 4)),
            "peak_flops_assumed": peak.assumed,
            "flops_provenance": provenance}


def record_flop_fields(record: dict, prefix: str, egm_iters: float,
                       dist_iters: float, wall_s: float, a_count: int,
                       n_states: int, d_count: int, dense_dist: bool,
                       backend: str,
                       measured_flops: float | None = None) -> dict:
    """Stamp one phase's ``flop_report`` onto a bench record under
    ``prefix`` (``record[prefix + "flops_per_sec"]`` etc., provenance
    included) and return the record — the ONE spelling every bench
    phase uses, so no phase can strand a null field or omit the
    provenance bit again (ISSUE 10 satellite; the fine-grid fields went
    null twice before ``model_flops`` was centralized)."""
    rep = flop_report(egm_iters, dist_iters, wall_s, a_count, n_states,
                      d_count, dense_dist, backend,
                      measured_flops=measured_flops)
    for key, value in rep.items():
        record[prefix + key] = value
    return record


# -- XLA compile counting (jax.monitoring) ----------------------------------

_ACTIVE_COMPILE_COUNTERS: list = []
_COMPILE_LISTENERS_INSTALLED = False


def _install_compile_listeners() -> None:
    """Register the process-global jax.monitoring listeners feeding every
    active ``CompileCounter``.  Registration is permanent (jax.monitoring
    has no unregister), so this runs exactly once per process."""
    global _COMPILE_LISTENERS_INSTALLED
    if _COMPILE_LISTENERS_INSTALLED:
        return
    import jax

    def on_event(name: str, **kw) -> None:
        for c in _ACTIVE_COMPILE_COUNTERS:
            if name == "/jax/compilation_cache/cache_misses":
                c.cache_misses += 1
            elif name == "/jax/compilation_cache/cache_hits":
                c.cache_hits += 1

    def on_duration(name: str, secs: float, **kw) -> None:
        if name != "/jax/core/compile/backend_compile_duration":
            return
        for c in _ACTIVE_COMPILE_COUNTERS:
            c.compile_events += 1
            c.compile_seconds += secs

    jax.monitoring.register_event_listener(on_event)
    jax.monitoring.register_event_duration_secs_listener(on_duration)
    _COMPILE_LISTENERS_INSTALLED = True


class CompileCounter:
    """Counts XLA compilation activity inside a ``with`` block, via
    ``jax.monitoring`` events.

    * ``compile_events`` / ``compile_seconds`` — backend compile requests
      and their wall (fires for BOTH real compiles and persistent-cache
      hits; an in-memory jit/lru cache hit fires nothing).
    * ``cache_misses`` — programs XLA actually compiled from scratch
      (persistent compilation cache missed).  THE "new compiles" number:
      a warm relaunch contract is ``cache_misses == 0``.
    * ``cache_hits`` — compilations served from the persistent cache.

    The cache_* events only fire while jax's compilation cache is enabled
    (``utils.backend.enable_compilation_cache``); callers asserting on
    them must enable it first.  Nesting/overlap is fine — every active
    counter sees every event."""

    def __init__(self):
        self.compile_events = 0
        self.compile_seconds = 0.0
        self.cache_misses = 0
        self.cache_hits = 0

    def __enter__(self) -> "CompileCounter":
        _install_compile_listeners()
        _ACTIVE_COMPILE_COUNTERS.append(self)
        return self

    def __exit__(self, *exc) -> None:
        _ACTIVE_COMPILE_COUNTERS.remove(self)

    def publish(self, registry, prefix: str = "aiyagari_xla_") -> None:
        """Mirror the totals into an ``obs.MetricsRegistry`` (ISSUE 7)
        without changing this class's public API.  Counters in
        Prometheus terms — but a CompileCounter is a window total that
        can be re-published, so they land as gauges (levels), matching
        ``ServeMetrics.publish``'s convention."""
        if registry is None:
            return
        registry.gauge(prefix + "compile_events",
                       "backend compile requests").set(self.compile_events)
        registry.gauge(prefix + "compile_seconds",
                       "backend compile wall").set(self.compile_seconds)
        registry.gauge(prefix + "cache_misses",
                       "programs compiled from scratch").set(
            self.cache_misses)
        registry.gauge(prefix + "cache_hits",
                       "compilations served from the persistent "
                       "cache").set(self.cache_hits)


@contextlib.contextmanager
def device_trace(log_dir: str | None):
    """``jax.profiler`` trace context (perfetto dump under ``log_dir``);
    no-op when ``log_dir`` is None so call sites need no branching."""
    if log_dir is None:
        yield
        return
    import jax
    with jax.profiler.trace(log_dir):
        yield
