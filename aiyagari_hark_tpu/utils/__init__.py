from .config import AgentConfig, EconomyConfig, SweepConfig, notebook_run_configs

__all__ = ["AgentConfig", "EconomyConfig", "SweepConfig", "notebook_run_configs"]
