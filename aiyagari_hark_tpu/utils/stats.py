"""Wealth-distribution analytics: weighted percentiles, Lorenz curves, Gini,
and the notebook's SCF-comparison measures.

The reference pulls these from HARK (``get_lorenz_shares``/``get_percentiles``
at ``Aiyagari-HARK.py:299``, SCF data via ``load_SCF_wealth_weights`` at
``:303``) and computes a Euclidean Lorenz distance (``:332-333``).  These are
host-side post-processing (plots and scalar diagnostics), so they are plain
NumPy — the device path ends at the simulated panel / stationary histogram.
"""

from __future__ import annotations

import csv
import os
from typing import NamedTuple, Optional, Tuple

import numpy as np

DEFAULT_PCTILES = np.linspace(0.01, 0.999, 15)   # Aiyagari-HARK.py:312


def _sorted_weighted(data, weights, presorted: bool = False):
    data = np.asarray(data, dtype=np.float64).ravel()
    if weights is None:
        weights = np.ones_like(data)
    else:
        weights = np.asarray(weights, dtype=np.float64).ravel()
    if presorted:
        return data, weights
    order = np.argsort(data)
    return data[order], weights[order]


def get_percentiles(data, weights=None,
                    percentiles=(0.5,), presorted: bool = False):
    """Weighted empirical quantiles, HARK ``get_percentiles`` semantics:
    linear interpolation of the sorted data against the plain normalized
    cumulative weights (no midpoint shift — e.g. [1,2,3,4] at p=0.5 gives
    2.0, matching HARK, not the midpoint variant's 2.5)."""
    d, w = _sorted_weighted(data, weights, presorted)
    cum = np.cumsum(w) / np.sum(w)
    return np.interp(np.asarray(percentiles), cum, d)


def get_lorenz_shares(data, weights=None, percentiles=None,
                      presorted: bool = False) -> np.ndarray:
    """Cumulative wealth share held below each population percentile — the
    Lorenz curve sampled at ``percentiles`` (HARK ``get_lorenz_shares``)."""
    if percentiles is None:
        percentiles = DEFAULT_PCTILES
    d, w = _sorted_weighted(data, weights, presorted)
    cum_pop = np.cumsum(w) / np.sum(w)
    cum_wealth = np.cumsum(d * w)
    cum_wealth = cum_wealth / cum_wealth[-1]
    return np.interp(np.asarray(percentiles), cum_pop, cum_wealth)


def lorenz_distance(data_a, data_b, weights_a=None, weights_b=None,
                    percentiles=None) -> float:
    """Euclidean distance between two Lorenz curves on a percentile grid —
    the notebook's simulated-vs-SCF measure (``Aiyagari-HARK.py:332-333``)."""
    la = get_lorenz_shares(data_a, weights_a, percentiles)
    lb = get_lorenz_shares(data_b, weights_b, percentiles)
    return float(np.sqrt(np.sum((la - lb) ** 2)))


def gini(data, weights=None) -> float:
    """Gini coefficient of a (weighted) sample: 1 - 2 * area under Lorenz."""
    d, w = _sorted_weighted(data, weights)
    cum_pop = np.concatenate([[0.0], np.cumsum(w) / np.sum(w)])
    cw = np.cumsum(d * w)
    cum_wealth = np.concatenate([[0.0], cw / cw[-1]])
    area = np.trapezoid(cum_wealth, cum_pop)
    return float(1.0 - 2.0 * area)


class WealthStats(NamedTuple):
    """The notebook's simulated-wealth readout (cell 24 output; BASELINE.md
    reference values 22.046 / 5.439 / 3.697 / 4.718)."""

    max: float
    mean: float
    std: float
    median: float


def wealth_stats(assets, weights=None) -> WealthStats:
    a = np.asarray(assets, dtype=np.float64).ravel()
    if weights is None:
        return WealthStats(max=float(a.max()), mean=float(a.mean()),
                           std=float(a.std()), median=float(np.median(a)))
    w = np.asarray(weights, dtype=np.float64).ravel()
    mean = float(np.average(a, weights=w))
    var = float(np.average((a - mean) ** 2, weights=w))
    # max over the OCCUPIED support: histogram inputs carry zero-weight
    # grid nodes above the ergodic right tail
    occupied = a[w > 1e-12 * w.sum()]
    return WealthStats(max=float(occupied.max()), mean=mean, std=var ** 0.5,
                       median=float(get_percentiles(a, w, (0.5,))[0]))


def histogram_sample(dist_grid, masses) -> Tuple[np.ndarray, np.ndarray]:
    """Flatten a stationary histogram ``[D, N]`` (or ``[D]``) over the wealth
    grid into a (values, weights) pair for the analytics above — the
    deterministic replacement for the reference's simulated agent panel."""
    g = np.asarray(dist_grid, dtype=np.float64)
    m = np.asarray(masses, dtype=np.float64)
    if m.ndim == 2:
        m = m.sum(axis=1)
    return g, m


class SCFLorenz(NamedTuple):
    """The SCF Lorenz curve at the notebook's 15-point percentile grid, plus
    the reference's own simulated curve from the same figure (useful as an
    extraction self-check: their distance reproduces the 0.9714 golden)."""

    pctiles: np.ndarray
    scf_shares: np.ndarray
    ref_sim_shares: np.ndarray


_SCF_LORENZ_CSV = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "data", "scf_lorenz.csv")


def load_scf_lorenz(path: Optional[str] = None) -> SCFLorenz:
    """SCF Lorenz shares at ``DEFAULT_PCTILES``, vendored from the
    reference's committed vector figure.

    The reference computes these from HARK's bundled SCF sample
    (``Aiyagari-HARK.py:303,313``); that dataset is unavailable here, so the
    curve was recovered from the path data of the reference's committed
    ``Figures/wealth_distribution_1.svg`` (a matplotlib vector figure; see
    ``scripts/extract_scf_lorenz.py`` for the method and its built-in
    verification against the printed 0.9714 golden).  Good to ~1e-5 per
    share — the Lorenz *distance* computation only ever needs the curve at
    this grid, not the raw microdata.
    """
    path = path or _SCF_LORENZ_CSV
    rows = []
    with open(path, newline="") as f:
        for row in csv.reader(f):
            if not row or row[0].startswith("#") or row[0] == "pctile":
                continue
            rows.append([float(v) for v in row[:3]])
    arr = np.asarray(rows, dtype=np.float64)
    return SCFLorenz(pctiles=arr[:, 0], scf_shares=arr[:, 1],
                     ref_sim_shares=arr[:, 2])


def lorenz_distance_vs_scf(sim_wealth, sim_weights=None,
                           path: Optional[str] = None) -> float:
    """The notebook's headline inequality measure: Euclidean distance
    between the simulated wealth Lorenz curve and the SCF curve on the
    15-point percentile grid (``Aiyagari-HARK.py:332-333``; reference
    golden 0.9714)."""
    scf = load_scf_lorenz(path)
    sim = get_lorenz_shares(sim_wealth, weights=sim_weights,
                            percentiles=scf.pctiles)
    return float(np.sqrt(np.sum((scf.scf_shares - sim) ** 2)))


def synthetic_scf_wealth(n: int = 20000,
                         seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic synthetic stand-in for the SCF wealth sample, so the
    Lorenz-comparison code path is exercisable without the real data (which
    the reference gets from HARK's bundled dataset,
    ``load_SCF_wealth_weights``, ``Aiyagari-HARK.py:303`` — unavailable
    here: no network, HARK not vendored).

    NOT the real SCF: a lognormal with sigma=1.9, whose Gini (~0.82)
    matches the well-known top-heaviness of U.S. net worth.  Any distance
    computed against it is a smoke value, not the reference's 0.9714
    golden — ``reproduce.py`` labels it accordingly.
    """
    rng = np.random.default_rng(seed)
    wealth = rng.lognormal(mean=0.0, sigma=1.9, size=n)
    weights = np.ones(n)
    return wealth, weights


def load_scf_wealth_weights(path: Optional[str] = None
                            ) -> Tuple[np.ndarray, np.ndarray]:
    """U.S. Survey of Consumer Finances wealth observations + sample weights.

    The reference loads these from HARK's bundled dataset
    (``load_SCF_wealth_weights``, ``Aiyagari-HARK.py:303``); that package
    (and network access) is unavailable here, so this reads a two-column CSV
    ``wealth,weight`` supplied by the user (or ``$SCF_WEALTH_CSV``).
    """
    path = path or os.environ.get("SCF_WEALTH_CSV")
    if not path or not os.path.exists(path):
        raise FileNotFoundError(
            "SCF wealth data not bundled (no network in this build). Export "
            "it from HARK.datasets.load_SCF_wealth_weights() to a csv with "
            "columns wealth,weight and pass its path (or set "
            "$SCF_WEALTH_CSV).")
    wealth, weights = [], []
    with open(path, newline="") as f:
        for row in csv.reader(f):
            if not row:
                continue
            try:
                v = float(row[0])
            except ValueError:   # header or comment line
                continue
            wealth.append(v)
            weights.append(float(row[1]) if len(row) > 1 else 1.0)
    if not wealth:
        raise ValueError(f"no numeric wealth rows parsed from {path}")
    return np.asarray(wealth), np.asarray(weights)
