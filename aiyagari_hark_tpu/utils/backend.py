"""Coherent backend selection: platform + dtype + matmul precision in one
entry point (the north-star ``backend={"cpu","tpu"}`` flag, SURVEY.md §5
"Config / flags").

Round 1 left platform choice to env vars, and both driver artifacts died on
it (VERDICT.md): the axon TPU tunnel can hang backend *initialization*
indefinitely, and setting ``JAX_PLATFORMS=cpu`` in the environment hangs the
interpreter itself (the sitecustomize PJRT registration chokes on it).  The
working recipe — probe the ambient platform in a throwaway subprocess, then
pin this process with ``jax.config.update`` — lives here so every entry
point (facade, bench, reproduce, tests) shares it.

Modes:
 - ``"cpu"``:  CPU platform, float64 enabled — the oracle configuration
   every golden/parity number is pinned against.
 - ``"tpu"``:  requires a live accelerator (probed with a timeout);
   float32 with HIGHEST-precision matmuls (f32 accumulation on the MXU
   instead of bf16 passes — needed to hold the 1 bp r* budget).
 - ``"auto"``: TPU if the probe finds a live accelerator, else CPU.

Call ``select_backend`` before anything touches a jax device.  It is
idempotent per process for the same mode; switching modes after device use
only works CPU->CPU (the backend re-initializes lazily after
``_clear_backends``) — x64 cannot be enabled once arrays exist, so pick the
mode once at process start.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import NamedTuple, Optional


class BackendInfo(NamedTuple):
    """Resolved backend: the platform jax reports, the working dtype every
    model array should use, and whether x64 is on."""

    name: str          # jax.default_backend() after selection
    dtype: object      # jnp.float64 (cpu oracle) or jnp.float32
    x64: bool

    @property
    def is_oracle(self) -> bool:
        return self.x64


def enable_compilation_cache(path: Optional[str] = None) -> str:
    """Turn on jax's persistent compilation cache so repeated entry-point
    runs (bench, reproduce, sweeps) skip XLA compilation entirely.

    The bench's Table II program compiles in ~40s on the tunneled TPU and
    runs in ~5s — without the cache every invocation pays 8x its runtime
    in compilation.  The cache key covers the HLO and the jaxlib/backend
    version, so code changes recompile automatically.  Default location:
    ``$AIYAGARI_CACHE_DIR`` or ``<repo>/.jax_cache`` (gitignored).

    Every sweep launch enables this by default
    (``SweepConfig.compilation_cache``); ``AIYAGARI_COMPILATION_CACHE=0``
    (or ``off``/``false``) is the global kill switch — it returns ""
    without touching jax config, for debugging cache-related wedges or
    read-only filesystems.
    """
    import jax

    if os.environ.get("AIYAGARI_COMPILATION_CACHE", "").lower() in (
            "0", "off", "false"):
        return ""
    if path is None:
        path = os.environ.get(
            "AIYAGARI_CACHE_DIR",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))), ".jax_cache"))
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    return path


def default_probe_timeout_s() -> float:
    """Shared probe-timeout default: ``AIYAGARI_PROBE_TIMEOUT_S`` env
    override, else 180 s.  Raised from 120 s because two rounds of
    driver-time bench captures fell back to CPU on probe timeout while the
    tunnel was merely slow to init, not down (VERDICT r4 minor item 6) —
    a longer wait costs one extra minute when the tunnel is genuinely
    down, but buys headline freshness when it is up.  Lives HERE so every
    prober (bench, reproduce/facade via ``select_backend``) inherits it,
    not just one wrapper.  A malformed env value falls back to the
    default with a warning instead of killing the caller."""
    raw = os.environ.get("AIYAGARI_PROBE_TIMEOUT_S")
    if raw is None:
        return 180.0
    try:
        return float(raw)
    except ValueError:
        print(f"[backend] ignoring malformed AIYAGARI_PROBE_TIMEOUT_S="
              f"{raw!r}; using 180", file=sys.stderr)
        return 180.0


def probe_ambient_backend(timeout_s: Optional[float] = None) -> Optional[str]:
    """Name of the backend the ambient environment would initialize, probed
    in a subprocess so a hung TPU tunnel cannot wedge the caller.  None on
    timeout/failure.  ``timeout_s=None`` uses the shared
    ``default_probe_timeout_s`` (env-tunable)."""
    if timeout_s is None:
        timeout_s = default_probe_timeout_s()
    code = "import jax; print('BACKEND=' + jax.default_backend())"
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=timeout_s)
    except (subprocess.TimeoutExpired, OSError):
        return None
    for line in out.stdout.splitlines():
        if line.startswith("BACKEND="):
            return line.split("=", 1)[1].strip()
    return None


def force_cpu_platform(n_devices: Optional[int] = None) -> None:
    """Pin this process to the CPU platform (optionally with ``n_devices``
    virtual devices), dropping an already-initialized backend if necessary.
    Must run before x64 state matters; see module docstring."""
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n_devices}"
            ).strip()

    import jax
    from jax._src import xla_bridge as xb

    if xb.backends_are_initialized():
        if (jax.default_backend() != "cpu"
                or (n_devices is not None
                    and len(jax.devices()) < n_devices)):
            xb._clear_backends()
            jax.clear_caches()
    jax.config.update("jax_platforms", "cpu")


_RESOLVED: dict = {}


def select_backend(backend: str = "auto",
                   probe_timeout_s: Optional[float] = None) -> BackendInfo:
    """Resolve ``backend`` ∈ {"auto", "cpu", "tpu"} into a live platform +
    dtype + precision configuration.  Raises RuntimeError for ``"tpu"`` when
    no accelerator answers the probe.

    Memoized per mode: the subprocess probe (seconds normally, up to the
    timeout on a hung tunnel) runs at most once per process — repeated
    ``solve(backend="auto")`` calls are free after the first."""
    import jax
    import jax.numpy as jnp

    if backend not in ("auto", "cpu", "tpu"):
        raise ValueError(f"backend must be 'auto', 'cpu' or 'tpu', "
                         f"got {backend!r}")
    if backend in _RESOLVED:
        return _RESOLVED[backend]

    if backend in ("auto", "tpu"):
        ambient = probe_ambient_backend(probe_timeout_s)
        accel = ambient is not None and ambient != "cpu"
        if backend == "tpu" and not accel:
            raise RuntimeError(
                f"backend='tpu' requested but the ambient platform probe "
                f"returned {ambient!r} (tunnel down or CPU-only host); use "
                f"backend='auto' to fall back to CPU")
        if accel:
            # f32 everywhere, but force full-precision matmul accumulation:
            # the FOC inversion and log-log regression cannot hold the 1 bp
            # r* budget through bf16 MXU passes (SURVEY.md §7 "Precision").
            jax.config.update("jax_default_matmul_precision", "highest")
            info = BackendInfo(name=jax.default_backend(),
                               dtype=jnp.float32, x64=False)
            _RESOLVED[backend] = info
            return info

    # CPU oracle: force the platform and enable float64.
    force_cpu_platform()
    jax.config.update("jax_enable_x64", True)
    info = BackendInfo(name="cpu", dtype=jnp.float64, x64=True)
    _RESOLVED[backend] = info
    return info
