"""Preemption-tolerant run layer: durable resume, graceful shutdown,
transient-fault retry (ISSUE 3).

PR 1 made *numerical* failure typed and recoverable (``solver_health``:
status codes, quarantine, the retry ladder); this module does the same for
*process and device* failure, which preemptible TPU slices make an expected
operating condition rather than an accident: a multi-minute Table II sweep
or KS fixed point must survive a SIGTERM, a transient XLA/RPC hiccup, and a
kill mid-write (the preemption-tolerance story of high-dimensional DSGE
solving, Scheidegger et al. arXiv:2202.06555).  Three pillars:

* **Durable sweep resume** (``SweepLedger``/``LedgerState``): the sweep
  persists a fingerprinted per-bucket ledger — every solved bucket's packed
  ``SweepResult`` rows plus quarantine/retry state — atomically
  (``utils.checkpoint.save_pytree``) after each bucket launch and each
  quarantine rung.  A restarted ``run_table2_sweep(resume_path=...)`` skips
  completed buckets and already-retried cells and replays only the rest;
  the assembled ``SweepResult`` is **bit-identical** to an uninterrupted
  run (same discipline as the scheduler's lock-step parity: the per-cell
  computation never depends on *when* it ran).  The fingerprint covers
  everything that shapes the bits — cells, solver kwargs, dtype, schedule,
  fault injection, and the warm-start sidecar's content — so a stale
  ledger degrades loudly to a fresh run, never to silent garbage.

* **Graceful shutdown** (``preemption_guard``): a context manager that
  installs SIGTERM/SIGINT handlers setting a flag
  (``interrupt_requested``) which long loops poll at safe boundaries —
  sweep bucket seams, KS outer iterations, calibration evaluations.  The
  loop then flushes a valid checkpoint/ledger and raises the typed
  ``Interrupted`` (status ``solver_health.INTERRUPTED``) instead of dying
  mid-write.  A second signal escalates to ``KeyboardInterrupt`` so a
  wedged run can still be killed.

* **Transient-fault retry** (``retry_transient``): deterministic
  exponential backoff around device/compile/RPC calls, gated by
  ``classify_transient`` — UNAVAILABLE-style runtime errors are retried,
  while ``SolverDivergenceError``/``NONFINITE`` is **never** retried here
  (numeric divergence is the PR 1 quarantine ladder's job; retrying it
  would mask real bugs and double-spend the budget on deterministic
  failures).  ``TransientInjector`` (raise-at-call-k) makes every retry
  path exercisable deterministically on CPU.

Everything here is host-side and dependency-free (signal/os/numpy); the
jitted programs never see it.
"""

from __future__ import annotations

import os
import signal
import time
import warnings
from dataclasses import dataclass, field
from typing import Callable, NamedTuple, Optional

import numpy as np

from ..obs.runtime import emit_event
from ..solver_health import INTERRUPTED, SolverDivergenceError
from .checkpoint import (
    CORRUPT_NPZ_ERRORS,
    gc_orphaned_tmp,
    load_pytree,
    save_pytree,
)
from .fingerprint import packed_row_checksum, packed_row_checksums


class Interrupted(BaseException):
    """A long-running solve stopped at a safe boundary on a shutdown
    request (SIGTERM/SIGINT or ``request_interrupt``), after flushing its
    checkpoint/ledger.  Typed so drivers can distinguish "preempted,
    resume me" (exit code ``EX_TEMPFAIL``-style) from a real failure.

    Derives from ``BaseException`` — the same reasoning that puts
    ``KeyboardInterrupt``/``SystemExit`` there: a shutdown request must
    sail through the entry points' broad ``except Exception`` fault
    handlers (the bench's attempt/fallback ladder, phase guards) instead
    of being "recovered" into a CPU retry while the scheduler is pulling
    the node.

    Fields:

    * ``status`` — ``solver_health.INTERRUPTED`` (an uncertified exit;
      ``is_failure`` is True for it).
    * ``resume_path`` — where the flushed state lives (ledger or KS
      checkpoint); ``None`` when the caller ran without persistence.
    * ``signum`` — the signal that requested the shutdown, if any.
    * ``progress`` — a small dict of where the run stopped (e.g.
      ``{"completed_buckets": 2, "n_buckets": 4}``).
    """

    def __init__(self, message: str, resume_path: Optional[str] = None,
                 signum: Optional[int] = None, progress: Optional[dict] = None):
        super().__init__(message)
        self.status = INTERRUPTED
        self.resume_path = resume_path
        self.signum = signum
        self.progress = dict(progress) if progress else {}


# ---------------------------------------------------------------------------
# Graceful shutdown: the preemption flag and its signal plumbing.
# ---------------------------------------------------------------------------

# Module-level so loops can poll without threading a token through every
# call signature.  Set by the guard's signal handler or request_interrupt;
# cleared when the outermost guard exits (or via clear_interrupt).
_INTERRUPT = {"flag": False, "signum": None}
_GUARD_DEPTH = 0


def interrupt_requested() -> bool:
    """True once a shutdown has been requested; long loops poll this at
    safe boundaries (bucket seams, outer iterations) and exit via
    ``Interrupted`` after flushing state."""
    return _INTERRUPT["flag"]


def request_interrupt(signum: Optional[int] = None) -> None:
    """Set the shutdown flag programmatically — the deterministic test
    injection for the polling paths (the production setter is the signal
    handler ``preemption_guard`` installs)."""
    _INTERRUPT["flag"] = True
    _INTERRUPT["signum"] = signum


def clear_interrupt() -> None:
    """Reset the shutdown flag (tests; also the outermost guard's exit)."""
    _INTERRUPT["flag"] = False
    _INTERRUPT["signum"] = None


def raise_if_interrupted(what: str, resume_path: Optional[str] = None,
                         progress: Optional[dict] = None) -> None:
    """The poll used at loop boundaries: raise the typed ``Interrupted``
    when a shutdown was requested.  Callers flush their checkpoint/ledger
    BEFORE polling, so the exception always leaves valid state behind."""
    if _INTERRUPT["flag"]:
        sig = _INTERRUPT["signum"]
        name = ("" if sig is None
                else f" ({signal.Signals(sig).name})")
        emit_event("INTERRUPTED", what=what, signum=sig,
                   resume_path=resume_path, progress=progress or {})
        raise Interrupted(
            f"{what} interrupted at a safe boundary{name}"
            + (f"; resume from {resume_path}" if resume_path else ""),
            resume_path=resume_path, signum=sig, progress=progress)


class preemption_guard:
    """Context manager installing SIGTERM/SIGINT handlers that request a
    graceful shutdown instead of killing the process mid-write.

    The first signal sets the flag (``interrupt_requested``) — polled at
    loop boundaries, which flush and raise ``Interrupted``.  A second
    signal raises ``KeyboardInterrupt`` immediately: graceful shutdown
    must never make a wedged run unkillable.  Handlers are restored on
    exit; when the outermost guard exits the flag is cleared, so one
    preempted run cannot poison the next solve in the same process.

    ``gc_paths``: checkpoint/ledger paths whose directories are swept for
    orphaned ``tmp*.npz.tmp``-style atomic-writer temp files on teardown
    (``checkpoint.gc_orphaned_tmp`` — a hard kill between a writer's
    write and rename strands one).

    Guards nest (the inner install is a no-op); outside the main thread
    — where CPython forbids ``signal.signal`` — the guard degrades to
    flag-only mode (``request_interrupt`` still works)."""

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT),
                 gc_paths=(), max_tmp_age_s: float = 3600.0):
        self._signals = tuple(signals)
        self._gc_paths = tuple(gc_paths)
        self._max_tmp_age_s = max_tmp_age_s
        self._previous: dict = {}

    def _handler(self, signum, frame):
        if _INTERRUPT["flag"]:
            # second request: the polite exit is not happening — escalate
            raise KeyboardInterrupt(
                f"second {signal.Signals(signum).name} during graceful "
                f"shutdown")
        request_interrupt(signum)

    def __enter__(self):
        global _GUARD_DEPTH
        for s in self._signals:
            try:
                self._previous[s] = signal.signal(s, self._handler)
            except ValueError:
                # not the main thread: flag-only mode
                break
        _GUARD_DEPTH += 1
        return self

    def __exit__(self, exc_type, exc, tb):
        global _GUARD_DEPTH
        for s, prev in self._previous.items():
            try:
                signal.signal(s, prev)
            except ValueError:
                pass
        self._previous.clear()
        _GUARD_DEPTH = max(0, _GUARD_DEPTH - 1)
        if _GUARD_DEPTH == 0:
            clear_interrupt()
        for p in self._gc_paths:
            gc_orphaned_tmp(p, max_age_s=self._max_tmp_age_s)
        return False


def fire_preemption(mode: str = "signal") -> None:
    """Deterministic preemption injection for tests and drills:
    ``"signal"`` delivers a real SIGTERM to this process (requires an
    active ``preemption_guard``, exactly like production), ``"flag"``
    sets the flag directly (no guard needed)."""
    if mode == "signal":
        os.kill(os.getpid(), signal.SIGTERM)
        # CPython runs the handler at the next bytecode boundary in the
        # main thread; a no-op call guarantees we cross one before the
        # caller's poll.
        time.sleep(0)
    elif mode == "flag":
        request_interrupt()
    else:
        raise ValueError(f"fire_preemption mode must be 'signal' or "
                         f"'flag', got {mode!r}")


# ---------------------------------------------------------------------------
# Transient-fault retry with deterministic backoff.
# ---------------------------------------------------------------------------

# gRPC-style status codes that mark a runtime error transient — matched
# CASE-SENSITIVELY (the RPC stack shouts them; deterministic Python
# messages that merely contain words like "aborted" must not match).
TRANSIENT_CODE_PATTERNS = (
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "RESOURCE_EXHAUSTED",
    "ABORTED",
    "CANCELLED",
)
# Lowercase failure signatures the tunneled-TPU rounds actually logged.
# Deliberately a short, auditable list — an unknown error is NOT retried.
TRANSIENT_MESSAGE_PATTERNS = (
    "socket closed",
    "connection reset",
    "failed to connect",
    "broken pipe",
    "rst_stream",
    "preempted",
    "transient",
)
# RESOURCE_EXHAUSTED carve-out: on a single-tenant accelerator the common
# RESOURCE_EXHAUSTED is device OOM ("Attempting to allocate ...") — a
# DETERMINISTIC property of the program, not a hiccup; replaying it just
# re-pays the launch max_attempts times and buries the real diagnosis.
_DETERMINISTIC_EXHAUSTION = ("allocat", "out of memory", "oom", "hbm")


class InjectedTransientError(RuntimeError):
    """The deterministic stand-in for a device/RPC fault
    (``TransientInjector``); its message matches the transient classifier
    by construction."""


def classify_transient(exc: BaseException) -> bool:
    """True when ``exc`` is worth retrying: a transient device, RPC, or
    compile-service failure.

    The hard rule: ``SolverDivergenceError`` (and thus ``NONFINITE``) is
    NEVER transient — numeric divergence is deterministic, owned by the
    solver-health quarantine ladder, and retrying it at this layer would
    mask real bugs.  ``Interrupted`` is a requested shutdown, not a fault.
    Everything else is matched conservatively by type
    (``ConnectionError``), by SHOUTED gRPC status code
    (``TRANSIENT_CODE_PATTERNS``, case-sensitive so prose containing
    "aborted" cannot match), or by logged failure signature
    (``TRANSIENT_MESSAGE_PATTERNS``) — except a RESOURCE_EXHAUSTED that
    reads as device OOM, which is deterministic and not retried."""
    if isinstance(exc, (SolverDivergenceError, Interrupted)):
        return False
    if not isinstance(exc, Exception):        # KeyboardInterrupt/SystemExit
        return False
    if isinstance(exc, (InjectedTransientError, ConnectionError)):
        return True
    if isinstance(exc, (ValueError, TypeError, KeyError, AttributeError)):
        return False                          # programming errors: never
    raw = str(exc)
    msg = raw.lower()
    if "RESOURCE_EXHAUSTED" in raw and any(
            p in msg for p in _DETERMINISTIC_EXHAUSTION):
        return False                          # device OOM: deterministic
    return (any(p in raw for p in TRANSIENT_CODE_PATTERNS)
            or any(p in msg for p in TRANSIENT_MESSAGE_PATTERNS))


@dataclass
class RetryPolicy:
    """Deterministic exponential-backoff schedule: attempt ``i`` (0-based)
    that fails transiently sleeps ``min(base_delay * multiplier**i,
    max_delay)`` before attempt ``i+1``; at most ``max_attempts`` total
    attempts.  No jitter — reproducibility beats thundering-herd
    avoidance for a single-tenant solver, and tests can assert the exact
    schedule.  ``sleep`` is injectable so tests capture delays instead of
    paying them."""

    max_attempts: int = 3
    base_delay: float = 0.5
    multiplier: float = 2.0
    max_delay: float = 30.0
    sleep: Callable[[float], None] = field(default=time.sleep, repr=False)

    def delay(self, attempt: int) -> float:
        return float(min(self.base_delay * self.multiplier ** attempt,
                         self.max_delay))


class TransientInjector:
    """Raise-at-call-k fault injection for the retry layer: the k-th
    guarded call (0-based, counted across every ``retry_transient``
    invocation sharing this injector, retries included) raises
    ``InjectedTransientError``, ``times`` times in a row.

    ``times=1`` exercises the retry-then-succeed path;
    ``times >= max_attempts`` exhausts the policy so the fault escapes —
    the resume path's test harness.  Purely a test/diagnostic hook, like
    ``solver_health.inject_fault`` for the numeric layer."""

    def __init__(self, at_call: int = 0, times: int = 1,
                 message: str = "UNAVAILABLE: injected transient fault"):
        self.at_call = int(at_call)
        self.remaining = int(times)
        self.message = message
        self.calls = 0

    @classmethod
    def from_spec(cls, spec) -> "TransientInjector":
        """Build from the entry points' dict form
        (``inject_transient={"at_call": k, "times": n}``); an existing
        injector passes through (so one counter spans warm-up + timed
        runs when a caller wants that)."""
        if isinstance(spec, cls):
            return spec
        return cls(**dict(spec))

    def before_call(self) -> None:
        k = self.calls
        self.calls += 1
        if self.remaining > 0 and k >= self.at_call:
            self.remaining -= 1
            raise InjectedTransientError(f"{self.message} (call {k})")


def retry_transient(fn: Callable[[], object],
                    policy: Optional[RetryPolicy] = None,
                    classify: Callable[[BaseException], bool] = None,
                    inject: Optional[TransientInjector] = None,
                    label: str = "device call"):
    """Call ``fn()`` with transient-fault retry under ``policy``.

    A failure classified transient (``classify_transient`` by default) is
    retried after the policy's deterministic backoff, with a warning per
    retry; a non-transient failure — including ``SolverDivergenceError``,
    per the never-retry-NONFINITE rule — re-raises immediately.  The last
    transient failure re-raises once ``max_attempts`` is exhausted.

    Retrying is safe exactly because the guarded calls are pure device
    launches (jitted XLA programs of immutable inputs): a replay computes
    the same bits, so retry composes with the sweep's bit-identity
    contract."""
    policy = policy or RetryPolicy()
    classify = classify or classify_transient
    attempts = max(1, int(policy.max_attempts))
    for attempt in range(attempts):
        try:
            if inject is not None:
                inject.before_call()
            return fn()
        except BaseException as e:   # noqa: BLE001 — classifier decides
            if not classify(e) or attempt == attempts - 1:
                raise
            d = policy.delay(attempt)
            emit_event("RETRY_TRANSIENT", label=label,
                       attempt=attempt + 1, max_attempts=attempts,
                       delay_s=d,
                       error=f"{type(e).__name__}: {str(e)[:160]}")
            warnings.warn(
                f"transient fault in {label} (attempt {attempt + 1}/"
                f"{attempts}): {type(e).__name__}: {str(e)[:200]} — "
                f"retrying in {d:g}s", stacklevel=2)
            policy.sleep(d)
    raise AssertionError("unreachable")       # loop always returns/raises


# ---------------------------------------------------------------------------
# Durable sweep resume: the per-bucket ledger.
# ---------------------------------------------------------------------------

class SweepLedger(NamedTuple):
    """On-disk form of a sweep-in-progress (one atomic npz via
    ``save_pytree``): per-cell packed solver outputs in ORIGINAL cell
    order plus the solved/retried bookkeeping the resume needs.

    ``packed`` rows are the batched solver's exact device outputs in the
    ``config.PACKED_ROW_FIELDS`` layout (float64 round-trips npz
    bit-exactly), so a resumed assembly is bit-identical to an
    uninterrupted one.  ``fingerprint`` covers everything that shapes
    those bits — cells (perturb included), solver kwargs, dtype, schedule
    knobs, fault injection, the warm-start sidecar's content, AND the
    row layout itself (a pre-widening ledger must refuse to resume) — a
    mismatch degrades loudly to a fresh run.

    ``checksums`` (DESIGN §9) are per-row ``packed_row_checksum`` values
    recorded at SOLVE time, before the first flush — the fingerprint
    certifies *which run* wrote the ledger, the checksums certify that
    each row's BYTES are still the bytes that run solved.  A resumed load
    verifies every solved/retried row; a mismatched row (bit flip, torn
    npz that still parses) is quarantined — its solved/retried flags are
    cleared so the sweep recomputes it — instead of reassembling silent
    garbage into a "bit-identical" result."""

    packed: np.ndarray       # [C, W] float64 in the run's scenario row
    #                          layout (``scenarios.RowSchema``); NaN rows
    #                          = not yet solved
    solved: np.ndarray       # [C] bool — batched result present
    bucket: np.ndarray       # [C] int64 launch group (-1 = unassigned)
    pred: np.ndarray         # [C] float64 scheduler work model
    retries: np.ndarray      # [C] int64 quarantine rungs consumed
    retried: np.ndarray      # [C] bool — quarantine outcome is final
    checksums: np.ndarray    # [C] int64 solve-time row checksums (0=unset)
    fingerprint: np.ndarray  # scalar int64


def _ledger_template(n: int, width: int) -> SweepLedger:
    return SweepLedger(
        packed=np.full((n, int(width)), np.nan),
        solved=np.zeros(n, dtype=bool),
        bucket=np.full(n, -1, dtype=np.int64),
        pred=np.full(n, np.nan),
        retries=np.zeros(n, dtype=np.int64),
        retried=np.zeros(n, dtype=bool),
        checksums=np.zeros(n, dtype=np.int64),
        fingerprint=np.zeros((), np.int64))


class LedgerState:
    """Host-side mutable wrapper around ``SweepLedger``: the sweep records
    progress here and ``flush()``es after every bucket launch and every
    quarantine rung — each flush one atomic replace, so a kill at ANY
    point leaves either the previous or the new valid ledger, never a
    torn one.  ``complete()`` removes the file: a finished run must not
    satisfy the next run's launches silently."""

    def __init__(self, path: str, fingerprint: int, n_cells: int,
                 width: int = 10):
        # ``width`` is the run's scenario row width
        # (``scenarios.RowSchema.width``); the default is the Aiyagari
        # layout's, kept literal so this module never imports a row
        # layout constant directly (scripts/check_row_schema.py) — the
        # ledger fingerprint hashes the actual field names, so a wrong
        # width can never silently resume anyway.
        self.path = path
        self.fingerprint = int(fingerprint)
        self.width = int(width)
        t = _ledger_template(n_cells, width)
        self.packed = t.packed
        self.solved = t.solved
        self.bucket = t.bucket
        self.pred = t.pred
        self.retries = t.retries
        self.retried = t.retried
        self.checksums = t.checksums
        self.resumed = False      # a prior run's progress was restored
        self.corrupt_cells = []   # cells quarantined by resume-time
        #                           checksum verification (recomputed)

    @classmethod
    def resume(cls, path: str, fingerprint: int, n_cells: int,
               width: int = 10) -> "LedgerState":
        """Fresh state, or the prior run's — when ``path`` holds a ledger
        for the SAME run (fingerprint match).  A missing file is the
        normal first-run state; a corrupt/mismatched one warns and starts
        fresh (it will be overwritten at the first flush) — resume must
        degrade to recompute, never to wrong bits."""
        self = cls(path, fingerprint, n_cells, width=width)
        gc_orphaned_tmp(path)     # a prior hard kill may have stranded tmps
        if not os.path.exists(path):
            return self
        try:
            led = load_pytree(path, _ledger_template(n_cells, width))
        except CORRUPT_NPZ_ERRORS as e:
            warnings.warn(f"sweep resume ledger {path} unreadable ({e}); "
                          f"starting fresh", stacklevel=2)
            return self
        if int(led.fingerprint) != int(fingerprint):
            warnings.warn(
                f"sweep resume ledger {path} was written by a different "
                f"run (fingerprint {int(led.fingerprint)} vs "
                f"{int(fingerprint)}); starting fresh", stacklevel=2)
            return self
        self.packed = np.array(led.packed)
        self.solved = np.array(led.solved)
        self.bucket = np.array(led.bucket)
        self.pred = np.array(led.pred)
        self.retries = np.array(led.retries)
        self.retried = np.array(led.retried)
        self.checksums = np.array(led.checksums)
        self._verify_rows()
        self.resumed = bool(self.solved.any() or self.retried.any())
        if self.resumed:
            emit_event("RESUME_RESTORE", path=path,
                       cells_restored=int(self.solved.sum()),
                       cells_retried=int(self.retried.sum()),
                       corrupt_cells=list(self.corrupt_cells))
        return self

    def _verify_rows(self) -> None:
        """Resume-time integrity verification (DESIGN §9): every row the
        ledger claims solved/retried must still hash to its solve-time
        checksum.  A mismatching row — silent corruption that parsed
        fine — is QUARANTINED: its flags are cleared so the restarted
        sweep recomputes it (and its bucket), and the event is warned
        loudly with the cell indices.  Other cells' restored bits are
        untouched — corruption must never poison its neighbors."""
        claimed = self.solved | self.retried
        bad = [int(i) for i in np.nonzero(claimed)[0]
               if packed_row_checksum(self.packed[i])
               != int(self.checksums[i])]
        if not bad:
            return
        for i in bad:
            self.packed[i] = np.nan
            self.solved[i] = False
            self.retried[i] = False
            self.retries[i] = 0
            self.bucket[i] = -1
            self.checksums[i] = 0
        self.corrupt_cells = bad
        emit_event("INTEGRITY_FAILED", boundary="ledger",
                   path=self.path, cells=bad)
        warnings.warn(
            f"sweep resume ledger {self.path}: row checksum verification "
            f"failed for cell(s) {bad} — silent corruption; those cells "
            "are quarantined and will be recomputed", stacklevel=3)

    def record_bucket(self, cells: np.ndarray, rows: np.ndarray,
                      bucket_id: int) -> None:
        """A bucket launch finished: store its cells' packed rows, with
        content checksums taken NOW — at solve time, before any flush —
        so every later boundary can verify the bytes."""
        self.packed[cells] = rows
        self.solved[cells] = True
        self.bucket[cells] = bucket_id
        self.checksums[cells] = packed_row_checksums(rows)

    def record_retry(self, cell: int, row: np.ndarray,
                     attempts: int) -> None:
        """A quarantined cell's ladder walk finished (recovered or
        exhausted): its outcome is final for this run."""
        self.packed[cell] = row
        self.retries[cell] = attempts
        self.retried[cell] = True
        self.checksums[cell] = packed_row_checksum(row)

    def flush(self) -> None:
        """Persist the ledger.  A disk fault (ENOSPC/EIO — injected or
        real, ISSUE 18) SKIPS the flush loudly instead of killing the
        sweep: the in-memory ledger stays authoritative, the solve
        continues, and only resume-after-crash coverage is degraded
        until the next flush succeeds."""
        try:
            save_pytree(self.path, SweepLedger(
                packed=self.packed, solved=self.solved, bucket=self.bucket,
                pred=self.pred, retries=self.retries, retried=self.retried,
                checksums=self.checksums,
                fingerprint=np.asarray(self.fingerprint, np.int64)))
        except OSError as e:
            emit_event("DISK_FAULT", op="ledger_flush", path=self.path,
                       error=str(e), injected=False)
            warnings.warn(
                f"sweep ledger flush to {self.path} failed ({e}); "
                "skipping this flush — the sweep continues from memory "
                "and resume coverage lags until a flush lands",
                stacklevel=3)

    def complete(self) -> None:
        try:
            os.remove(self.path)
        except OSError:
            pass
