"""One fingerprint vocabulary for every cache key in the framework.

Four subsystems key durable state by "a hash of the configuration that
produced it": the sweep scheduler's warm-start sidecar
(``checkpoint.SweepSidecar``), the preemption ledger
(``resilience.SweepLedger``), the KS checkpoint stale-resume guard, and —
new with the serving subsystem — the content-addressed
``serve.SolutionStore``.  They used to each assemble their key inline from
the shared ``config_fingerprint`` primitive, which is exactly how cache
keys drift: two call sites disagree about whether dtype is hashed as
``str(np.dtype(d))`` or ``repr(d)`` and a sidecar written by one subsystem
silently never matches in another.  This module owns the primitive AND the
per-subsystem key builders, so the encoding decisions live (and are
tested) in one place.

Layering: pure host-side (hashlib/json/numpy), imported by
``utils.checkpoint``, ``utils.resilience``, ``parallel.sweep`` and
``serve`` — it must not import any of them back.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json

import numpy as np

from .config import PACKED_ROW_FIELDS, resolve_precision


def config_fingerprint(*objs) -> int:
    """Deterministic int64 fingerprint of configs/arrays, used to detect
    state written under a different setup (stale-resume guard, cache
    keys).  Dataclasses hash their sorted field dict, arrays their
    dtype/shape/bytes, everything else its ``repr``."""
    parts = []
    for o in objs:
        if o is None:
            parts.append("none")
        elif dataclasses.is_dataclass(o) and not isinstance(o, type):
            parts.append(json.dumps(dataclasses.asdict(o), sort_keys=True,
                                    default=repr))
        elif isinstance(o, np.ndarray) or hasattr(o, "__array__"):
            a = np.asarray(o)
            parts.append(f"{a.dtype}{a.shape}"
                         + hashlib.md5(a.tobytes()).hexdigest())
        else:
            parts.append(repr(o))
    digest = hashlib.md5("|".join(parts).encode()).digest()
    return int.from_bytes(digest[:8], "little", signed=True)


def hashable_kwargs(model_kwargs: dict) -> tuple:
    """Normalize solver kwargs into a canonical, hashable, SORTED items
    tuple — the one spelling every fingerprint below hashes, and the
    ``lru_cache`` key of the batched solver.  Sequences become tuples;
    anything still unhashable gets a clear error instead of ``lru_cache``'s
    bare TypeError.  Sorting makes the fingerprints insensitive to the
    caller's keyword order.

    Precision-policy normalization (DESIGN §5): an EXPLICIT
    ``precision="reference"`` is dropped — it is the default, and the two
    spellings produce bit-identical programs, so they must share one
    executable cache entry and one fingerprint (sidecar work predictions,
    sweep ledgers, and ``SolutionStore`` entries must never split — or
    mix — on a no-op spelling).  Non-default policies stay in the items
    and therefore key every cache downstream (the cross-policy inequality
    pinned by ``tests/test_fingerprint.py``); an unknown policy fails
    here, before it can silently alias a real one."""
    items = []
    for k, v in sorted(model_kwargs.items()):
        if k == "precision":
            # ONE validation surface: resolve_precision is the authority
            # (an unknown policy raises here, before it can alias a real
            # one in any cache key); hash the canonical policy name
            v = resolve_precision(v).policy
            if v == "reference":
                continue
        if isinstance(v, (list, np.ndarray)):
            arr = np.asarray(v)
            if arr.ndim > 1:
                raise TypeError(
                    f"sweep kwarg {k!r} has shape {arr.shape}; only scalars "
                    "and 1-D sequences can be forwarded to the cell solver")
            v = tuple(arr.tolist())
        try:
            hash(v)
        except TypeError:
            raise TypeError(
                f"sweep kwarg {k!r}={v!r} is not hashable; pass scalars or "
                "tuples (grids are rebuilt per cell from scalar settings)"
            ) from None
        items.append((k, v))
    return tuple(items)


def work_fingerprint(kwargs_items: tuple, dtype) -> int:
    """Solver-configuration key: the method choices, tolerances, and grid
    sizes that shape a cell's counters and root, plus the dtype.  Cell
    triples are NOT part of the key — rows/entries are matched per cell.

    Shared verbatim by the sweep sidecar (``checkpoint.SweepSidecar``) and
    the serving store's donor groups (``serve.SolutionStore``): a sidecar
    and a store entry written under the same solver configuration MUST
    carry the same group key, or warm starts silently stop flowing between
    the batch and serving paths."""
    return config_fingerprint(str(np.dtype(dtype)), repr(kwargs_items))


def solution_fingerprint(crra, labor_ar, labor_sd, kwargs_items: tuple,
                         dtype) -> int:
    """Content address of ONE equilibrium solution: the solver group
    (``work_fingerprint`` inputs) plus the calibration cell.  The serving
    store's exact-hit key — two queries collide iff every input that can
    move a bit of the answer matches."""
    return config_fingerprint(
        str(np.dtype(dtype)), repr(kwargs_items),
        float(crra), float(labor_ar), float(labor_sd))


def ledger_fingerprint(crra, rho, sd, kwargs_items: tuple, dtype,
                       schedule: str, n_buckets: int, warm_brackets: bool,
                       warm_margin: float, fault_mode, fault_iters,
                       max_retries: int, quarantine: bool,
                       sidecar) -> int:
    """Validity key of the sweep resume ledger (``resilience.SweepLedger``):
    everything that shapes the result bits — cells (perturb included),
    solver kwargs, dtype, schedule knobs, fault injection, and the
    warm-start sidecar's CONTENT (seeds read it live, so a sidecar swapped
    between interrupt and resume would silently change trajectories) — and
    the packed-row LAYOUT (``config.PACKED_ROW_FIELDS``): a ledger written
    under an older row width must refuse to resume instead of feeding
    wrong-shaped rows into a restarted sweep."""
    return config_fingerprint(
        repr(PACKED_ROW_FIELDS),
        crra, rho, sd, repr(kwargs_items), str(np.dtype(dtype)),
        schedule, int(n_buckets), bool(warm_brackets),
        float(warm_margin), str(fault_mode),
        "none" if fault_iters is None else fault_iters,
        int(max_retries), bool(quarantine),
        *(tuple(sidecar) if sidecar is not None else ("no-sidecar",)))
