"""One fingerprint vocabulary for every cache key in the framework.

Four subsystems key durable state by "a hash of the configuration that
produced it": the sweep scheduler's warm-start sidecar
(``checkpoint.SweepSidecar``), the preemption ledger
(``resilience.SweepLedger``), the KS checkpoint stale-resume guard, and —
new with the serving subsystem — the content-addressed
``serve.SolutionStore``.  They used to each assemble their key inline from
the shared ``config_fingerprint`` primitive, which is exactly how cache
keys drift: two call sites disagree about whether dtype is hashed as
``str(np.dtype(d))`` or ``repr(d)`` and a sidecar written by one subsystem
silently never matches in another.  This module owns the primitive AND the
per-subsystem key builders, so the encoding decisions live (and are
tested) in one place.

Layering: pure host-side (hashlib/json/numpy), imported by
``utils.checkpoint``, ``utils.resilience``, ``parallel.sweep`` and
``serve`` — it must not import any of them back.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json

import numpy as np

from .config import (resolve_grid, resolve_kernel, resolve_precision,
                     resolve_state)


class IntegrityError(RuntimeError):
    """A content checksum failed verification at an artifact boundary —
    the stored bytes are not the bytes that were solved (bit flip, torn
    write that still parses, stale partial overwrite).

    Deliberately NOT a ``ValueError``/``OSError`` subclass: the broad
    best-effort loaders (``checkpoint.CORRUPT_NPZ_ERRORS``) must not
    swallow it by accident — every boundary that can see one decides its
    own degrade explicitly (recompute for store/serve, quarantine for
    resume, heuristic for the sidecar) and logs what it evicted.

    ``boundary`` names the verification site ("ledger", "sidecar",
    "store-mem", "store-disk", "serve"); ``key`` the entry/cell involved
    when there is one."""

    def __init__(self, message: str, boundary: str | None = None,
                 key=None):
        super().__init__(message)
        self.boundary = boundary
        self.key = None if key is None else int(key)


def content_checksum(*arrays) -> int:
    """Deterministic int64 checksum over the CANONICAL bytes of one or
    more numeric arrays: every array is materialized as little-endian
    float64 (which holds every narrower compute dtype exactly and
    round-trips npz bit-exactly — the packed-row persistence rationale),
    C-contiguous, shape included.  The one spelling every integrity
    boundary hashes (ledger rows, sidecar content, store entries), so a
    checksum computed at solve time verifies at every later load no
    matter which subsystem did the storing."""
    h = hashlib.md5()
    for a in arrays:
        a = np.ascontiguousarray(np.asarray(a, dtype="<f8"))
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return int.from_bytes(h.digest()[:8], "little", signed=True)


def packed_row_checksum(row) -> int:
    """Content checksum of ONE packed device row
    (``config.PACKED_ROW_FIELDS`` layout) — computed at solve time,
    verified at every boundary the row later crosses (ledger
    flush/restore, store tiers, serve responses)."""
    return content_checksum(row)


def packed_row_checksums(rows) -> np.ndarray:
    """Per-row checksums of a ``[C, W]`` packed block, int64.  NaN rows
    (quarantined / not-yet-solved) checksum deterministically too — IEEE
    NaN payloads produced by the same program are the same bits."""
    rows = np.asarray(rows, dtype=np.float64)
    return np.asarray([packed_row_checksum(r) for r in rows],
                      dtype=np.int64)


def verify_packed_row(row, expected: int, boundary: str,
                      key=None) -> None:
    """Raise a typed ``IntegrityError`` unless ``row``'s content checksum
    matches ``expected`` (an int64 recorded at solve time)."""
    got = packed_row_checksum(row)
    if int(got) != int(expected):
        from ..obs.runtime import emit_event

        emit_event("INTEGRITY_FAILED", boundary=boundary,
                   key=None if key is None else int(key))
        where = "" if key is None else f" (entry {int(key)})"
        raise IntegrityError(
            f"packed-row checksum mismatch at the {boundary} "
            f"boundary{where}: stored bytes hash to {got}, solve-time "
            f"checksum was {int(expected)} — silent corruption",
            boundary=boundary, key=key)


def fingerprint_hex(key: int) -> str:
    """Canonical filename spelling of a signed int64 fingerprint: the
    two's-complement bits, zero-padded hex — stable, glob-able, and
    shared by every artifact named after a fingerprint (the store's
    ``sol_<hex>.npz`` entries and the fleet tier's ``lease_<hex>.lease``
    claim files MUST agree on the spelling, or a claim guards the wrong
    entry)."""
    return f"{int(key) & 0xFFFFFFFFFFFFFFFF:016x}"


def config_fingerprint(*objs) -> int:
    """Deterministic int64 fingerprint of configs/arrays, used to detect
    state written under a different setup (stale-resume guard, cache
    keys).  Dataclasses hash their sorted field dict, arrays their
    dtype/shape/bytes, everything else its ``repr``."""
    parts = []
    for o in objs:
        if o is None:
            parts.append("none")
        elif dataclasses.is_dataclass(o) and not isinstance(o, type):
            parts.append(json.dumps(dataclasses.asdict(o), sort_keys=True,
                                    default=repr))
        elif isinstance(o, np.ndarray) or hasattr(o, "__array__"):
            a = np.asarray(o)
            parts.append(f"{a.dtype}{a.shape}"
                         + hashlib.md5(a.tobytes()).hexdigest())
        else:
            parts.append(repr(o))
    digest = hashlib.md5("|".join(parts).encode()).digest()
    return int.from_bytes(digest[:8], "little", signed=True)


def hashable_kwargs(model_kwargs: dict) -> tuple:
    """Normalize solver kwargs into a canonical, hashable, SORTED items
    tuple — the one spelling every fingerprint below hashes, and the
    ``lru_cache`` key of the batched solver.  Sequences become tuples;
    anything still unhashable gets a clear error instead of ``lru_cache``'s
    bare TypeError.  Sorting makes the fingerprints insensitive to the
    caller's keyword order.

    Precision-policy normalization (DESIGN §5): an EXPLICIT
    ``precision="reference"`` is dropped — it is the default, and the two
    spellings produce bit-identical programs, so they must share one
    executable cache entry and one fingerprint (sidecar work predictions,
    sweep ledgers, and ``SolutionStore`` entries must never split — or
    mix — on a no-op spelling).  Non-default policies stay in the items
    and therefore key every cache downstream (the cross-policy inequality
    pinned by ``tests/test_fingerprint.py``); an unknown policy fails
    here, before it can silently alias a real one.

    Grid-policy normalization (DESIGN §5b): the IDENTICAL rule for
    ``grid`` — explicit "reference" dropped (no-drift pin), non-default
    policies hashed by canonical name so compacted solves key their own
    sidecars/ledgers/store entries (a ledger or store entry written
    under one grid layout is structurally unaddressable from another),
    unknown policies raise via ``resolve_grid`` before they can alias.

    Kernel-policy normalization (ISSUE 13, DESIGN §4c): the same rule a
    third time for ``kernel`` — explicit "reference" dropped, "fused"
    hashed by canonical name so fused solves key their own executables,
    sidecars, ledgers, and store entries (the CostLedger's
    ``work_fingerprint`` keying therefore attributes cost per FUSED
    executable for free), unknown policies raise via
    ``resolve_kernel``.

    State-policy normalization (ISSUE 20, DESIGN §6b): the same rule a
    fourth time for ``state`` — explicit "replicated" dropped (the
    default, bit-identical by construction), "sharded" hashed by
    canonical name so state-sharded solves key their own executables,
    sidecars, ledgers, and store entries; unknown policies raise via
    ``resolve_state``."""
    items = []
    for k, v in sorted(model_kwargs.items()):
        if k == "precision":
            # ONE validation surface: resolve_precision is the authority
            # (an unknown policy raises here, before it can alias a real
            # one in any cache key); hash the canonical policy name
            v = resolve_precision(v).policy
            if v == "reference":
                continue
        if k == "grid":
            # same authority pattern: resolve_grid validates and
            # canonicalizes (DESIGN §5b)
            v = resolve_grid(v).policy
            if v == "reference":
                continue
        if k == "kernel":
            # same authority pattern again (ISSUE 13, DESIGN §4c)
            v = resolve_kernel(v).policy
            if v == "reference":
                continue
        if k == "state":
            # same authority pattern a fourth time (ISSUE 20, DESIGN §6b)
            v = resolve_state(v).policy
            if v == "replicated":
                continue
        if isinstance(v, (list, np.ndarray)):
            arr = np.asarray(v)
            if arr.ndim > 1:
                raise TypeError(
                    f"sweep kwarg {k!r} has shape {arr.shape}; only scalars "
                    "and 1-D sequences can be forwarded to the cell solver")
            v = tuple(arr.tolist())
        try:
            hash(v)
        except TypeError:
            raise TypeError(
                f"sweep kwarg {k!r}={v!r} is not hashable; pass scalars or "
                "tuples (grids are rebuilt per cell from scalar settings)"
            ) from None
        items.append((k, v))
    return tuple(items)


# Scenario identity (ISSUE 9, DESIGN §12): every durable key below hashes
# the scenario NAME, default "aiyagari".  A sidecar, ledger, store entry,
# or serve group produced under one model family is therefore structurally
# unaddressable from another — two scenarios colliding would require a
# full md5 collision on inputs differing in the scenario token, never a
# mere coincidence of numerically identical cell parameters.
DEFAULT_SCENARIO = "aiyagari"


def _scenario_token(scenario: str) -> str:
    return f"scenario:{scenario}"


def work_fingerprint(kwargs_items: tuple, dtype,
                     scenario: str = DEFAULT_SCENARIO) -> int:
    """Solver-configuration key: the scenario (model family), the method
    choices, tolerances, and grid sizes that shape a cell's counters and
    root, plus the dtype.  Cell triples are NOT part of the key —
    rows/entries are matched per cell.

    Shared verbatim by the sweep sidecar (``checkpoint.SweepSidecar``) and
    the serving store's donor groups (``serve.SolutionStore``): a sidecar
    and a store entry written under the same solver configuration MUST
    carry the same group key, or warm starts silently stop flowing between
    the batch and serving paths."""
    return config_fingerprint(_scenario_token(scenario),
                              str(np.dtype(dtype)), repr(kwargs_items))


def solution_fingerprint(crra, labor_ar, labor_sd, kwargs_items: tuple,
                         dtype, scenario: str = DEFAULT_SCENARIO) -> int:
    """Content address of ONE equilibrium solution: the solver group
    (``work_fingerprint`` inputs, scenario included) plus the calibration
    cell.  The serving store's exact-hit key — two queries collide iff
    every input that can move a bit of the answer matches, and a huggett
    query can never address an aiyagari entry at the same (σ, ρ, sd)."""
    return config_fingerprint(
        _scenario_token(scenario),
        str(np.dtype(dtype)), repr(kwargs_items),
        float(crra), float(labor_ar), float(labor_sd))


def ledger_fingerprint(cells, kwargs_items: tuple, dtype,
                       schedule: str, n_buckets: int, warm_brackets: bool,
                       warm_margin: float, fault_mode, fault_iters,
                       max_retries: int, quarantine: bool,
                       sidecar, scenario: str = DEFAULT_SCENARIO,
                       row_fields=None, mesh_shards: int = 1,
                       state_shards: int = 1) -> int:
    """Validity key of the sweep resume ledger (``resilience.SweepLedger``):
    everything that shapes the result bits — the scenario, cells (perturb
    included; a ``[C, k]`` array), solver kwargs, dtype, schedule knobs,
    fault injection, and the warm-start sidecar's CONTENT (seeds read it
    live, so a sidecar swapped between interrupt and resume would silently
    change trajectories) — and the packed-row LAYOUT (``row_fields``, the
    scenario's ``RowSchema.fields``; None resolves the registered
    scenario's): a ledger written under an older row layout must refuse
    to resume instead of feeding wrong-shaped rows into a restarted
    sweep.

    ``mesh_shards`` is the lane-axis device count the sweep ran under
    (ISSUE 11): the per-lane BITS are mesh-independent (property-tested),
    but the bucket padding and lane layout are not, so a ledger written
    on an N-device mesh refuses-to-resume (typed warn + recompute) under
    an M-device mesh instead of silently restoring rows whose launch
    geometry the restarted run cannot reproduce.

    ``state_shards`` extends that guard to the SECOND mesh axis
    (ISSUE 20): state-sharded solve bits depend on the row-block
    reduction order, so a ledger written under (cells=N, state=M)
    geometry refuses to resume under any other (N', M') and the restarted
    run recomputes bit-identically under its own geometry."""
    if row_fields is None:
        from ..scenarios.registry import get_scenario

        row_fields = get_scenario(scenario).schema.fields
    return config_fingerprint(
        _scenario_token(scenario), repr(tuple(row_fields)),
        np.asarray(cells, dtype=np.float64),
        repr(kwargs_items), str(np.dtype(dtype)),
        schedule, int(n_buckets), bool(warm_brackets),
        float(warm_margin), str(fault_mode),
        "none" if fault_iters is None else fault_iters,
        int(max_retries), bool(quarantine),
        (int(mesh_shards), int(state_shards)),
        *(tuple(sidecar) if sidecar is not None else ("no-sidecar",)))
