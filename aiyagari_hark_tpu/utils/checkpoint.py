"""Checkpoint / resume of fixed-point state as flat npz pytrees.

The reference has no persistence at all — partial progress survives only
through accidental in-place mutation of ``intercept_prev``/``slope_prev``
(``Aiyagari_Support.py:1949-1951``; SURVEY.md §5).  Here any pytree of
arrays (an ``AFuncParams``, a ``KSPolicy``, a ``PanelState``, a whole
``KSCheckpoint``) round-trips through one ``.npz`` file, so the multi-minute
Krusell-Smith fixed point and long sweeps are resumable.
"""

from __future__ import annotations

import errno
import os
import tempfile
import threading
import zipfile
from typing import NamedTuple

import jax
import numpy as np

# What np.load/load_pytree raise on a missing, truncated, or trashed npz:
# BadZipFile/EOFError are what a half-written or zeroed file produces —
# neither is an OSError (learned the hard way once; encode it ONCE so
# every best-effort loader degrades on the same set).
CORRUPT_NPZ_ERRORS = (OSError, ValueError, EOFError, zipfile.BadZipFile)


# -- deterministic disk-fault injection (ISSUE 18) ---------------------------
#
# Every writer in this module consults ``_maybe_disk_fault(op, path)``
# before touching the disk.  Unarmed (the default, and the only state
# outside drills/tests) that is one dict truth-test.  Armed via
# ``arm_disk_fault``, the next ``count`` matching writes raise the real
# ``OSError`` a full/failing disk would produce (ENOSPC/EIO, with the
# target path attached) — so every degrade path upstream (store
# memory-only fallback, ledger flush skip, WAL append/snapshot degrade)
# is exercised against the exact exception shape of the real fault,
# deterministically, without filling a filesystem.  Ops are the writer
# family's names: ``save_pytree``, ``atomic_write_text``,
# ``atomic_write_json``, ``append_jsonl``.

_DISK_FAULTS: dict = {}          # op -> {"errno", "count", "match"}
_DISK_FAULT_LOCK = threading.Lock()
_DISK_FAULT_TLS = threading.local()


def arm_disk_fault(op: str, kind: str = "ENOSPC", count: int = 1,
                   match: str = "") -> None:
    """Arm the next ``count`` ``op`` writes (optionally only on paths
    containing ``match``) to raise ``OSError(errno.<kind>)``."""
    code = getattr(errno, str(kind).upper(), None)
    if code is None:
        raise ValueError(f"unknown errno name {kind!r}")
    with _DISK_FAULT_LOCK:
        _DISK_FAULTS[str(op)] = {"errno": int(code),
                                 "count": max(0, int(count)),
                                 "match": str(match)}


def disarm_disk_faults() -> None:
    """Drop every armed fault (drill teardown; idempotent)."""
    with _DISK_FAULT_LOCK:
        _DISK_FAULTS.clear()


def _fire_disk_fault(op: str, path: str, code: int) -> None:
    """The injection seam (covered by ``check_obs_events``): journal
    ``DISK_FAULT`` for the detection ledger, then raise the fault —
    callers see exactly what a real full/failing disk throws."""
    kind = errno.errorcode.get(code, str(code))
    _DISK_FAULT_TLS.active = True     # the event append must not re-fault
    try:
        from ..obs.runtime import emit_event

        emit_event("DISK_FAULT", op=str(op), path=str(path),
                   errno=int(code), kind=kind, injected=True)
    finally:
        _DISK_FAULT_TLS.active = False
    raise OSError(code, f"injected disk fault ({kind})", str(path))


def _maybe_disk_fault(op: str, path: str) -> None:
    if not _DISK_FAULTS or getattr(_DISK_FAULT_TLS, "active", False):
        return
    with _DISK_FAULT_LOCK:
        plan = _DISK_FAULTS.get(op)
        if (plan is None or plan["count"] <= 0
                or plan["match"] not in str(path)):
            return
        plan["count"] -= 1
        code = plan["errno"]
    _fire_disk_fault(op, path, code)


def _fsync_dir(path: str) -> None:
    """fsync the directory holding ``path`` so a rename/create itself
    survives power loss (the second half of a durable write)."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return      # e.g. a platform that cannot open directories
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class CheckpointMismatchError(ValueError):
    """A checkpoint was written by a different run (seed or config
    fingerprint mismatch) and resuming from it is refused.  A typed
    subclass so callers with a legitimate degrade-to-cold path (e.g. a
    committed warm-start checkpoint gone stale after a config change) can
    catch exactly this, not every ValueError the resume machinery might
    raise."""


def save_pytree(path: str, tree, durable: bool = False) -> None:
    """Write a pytree of arrays/scalars to ``path`` (npz, atomic rename).
    The treedef repr rides along so a load against the wrong template is a
    hard error, not a silent leaf reinterpretation.  ``durable=True``
    additionally fsyncs the bytes and the directory entry (ISSUE 18) —
    crash-consistency against POWER LOSS, not just process death."""
    _maybe_disk_fault("save_pytree", path)
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    arrays = {f"leaf_{i:06d}": np.asarray(leaf)
              for i, leaf in enumerate(leaves)}
    arrays["__treedef__"] = np.asarray(str(treedef))
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
            if durable:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
        if durable:
            _fsync_dir(path)
    except BaseException:
        if os.path.exists(tmp):
            os.remove(tmp)
        raise


def _atomic_write_text(path: str, text: str, suffix: str,
                       durable: bool = False) -> None:
    """tmp + ``os.replace`` in the target's directory — the same
    crash-consistency discipline as ``save_pytree``: a kill at any point
    leaves either the old file or the new one, never a truncated hybrid.
    ``durable=True`` fsyncs file + directory (power-loss durability)."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=suffix)
    try:
        with os.fdopen(fd, "w") as f:   # atomic-ok: the blessed writer
            f.write(text)
            if durable:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
        if durable:
            _fsync_dir(path)
    except BaseException:
        if os.path.exists(tmp):
            os.remove(tmp)
        raise


def atomic_write_text(path: str, text: str, durable: bool = False) -> None:
    """Crash-consistent replacement for ``open(path, "w").write(text)``
    on artifact paths (sentinels, runtime summaries): see
    ``atomic_write_json`` for why bare writes are banned
    (``scripts/check_atomic_writes.py`` enforces it)."""
    _maybe_disk_fault("atomic_write_text", path)
    _atomic_write_text(path, text, suffix=".txt.tmp", durable=durable)


def atomic_write_json(path: str, obj, indent: int = 2,
                      sort_keys: bool = False,
                      trailing_newline: bool = True,
                      durable: bool = False) -> None:
    """Crash-consistent JSON artifact write (tmp + ``os.replace``).

    Entry points used to write records with bare ``open(path, "w")`` +
    ``json.dump`` — a kill mid-write leaves a truncated record, and for
    ``bench_tpu_last.json`` a poisoned evidence file that a later CPU
    fallback would embed as "the committed TPU record".  All JSON/txt
    artifacts go through here (or ``atomic_write_text``); the static lint
    ``scripts/check_atomic_writes.py`` keeps bare writes from regressing
    in."""
    import json

    _maybe_disk_fault("atomic_write_json", path)
    text = json.dumps(obj, indent=indent, sort_keys=sort_keys)
    _atomic_write_text(path, text + ("\n" if trailing_newline else ""),
                       suffix=".json.tmp", durable=durable)


def append_jsonl(path: str, lines, durable: bool = False) -> None:
    """Append-safe JSONL writer — the APPEND member of the atomic-writer
    family (the ``atomic_write_*`` functions replace whole files; a
    journal/bench record stream must instead grow without rewriting its
    history on every event).

    Each complete newline-terminated line is written with ONE
    ``os.write`` to an ``O_APPEND`` descriptor: a kill between lines
    loses nothing, a kill mid-write can tear at most the FINAL line —
    which readers (``obs.journal.read_journal``,
    ``utils.timing.read_records_jsonl``) detect as unparseable and
    skip — and concurrent appenders (two processes journaling to one
    file) never interleave bytes within a line.  Bare append-mode
    ``open`` is banned by ``scripts/check_atomic_writes.py`` for the
    same reason bare ``"w"`` is: a buffered handle flushes a long line
    in chunks, and a SIGTERM between chunks tears mid-record.

    ``durable=True`` (ISSUE 18) fsyncs the descriptor after the batch —
    and the directory entry when this call CREATED the file — so a
    write-ahead log's acknowledged records survive power loss, not just
    process death."""
    _maybe_disk_fault("append_jsonl", path)
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    created = durable and not os.path.exists(path)
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    try:
        for line in lines:
            if not line.endswith("\n"):
                line += "\n"
            os.write(fd, line.encode("utf-8"))
        if durable:
            os.fsync(fd)
    finally:
        os.close(fd)
    if created:
        _fsync_dir(path)


# -- fleet leases (ISSUE 15, DESIGN §14) ------------------------------------
#
# The shared-store fleet tier needs one more write primitive beyond the
# replace/append family: EXCLUSIVE CREATION.  A claim file per solution
# fingerprint is how N worker processes racing the same cold miss elect
# exactly one solver: ``os.open(O_CREAT | O_EXCL)`` is atomic on POSIX —
# precisely one process wins the create, every other raises
# ``FileExistsError`` — and the winner's single ``os.write`` of a short
# owner payload cannot tear across the visibility boundary (losers key
# off the file's EXISTENCE, which the O_EXCL create made atomic; the
# payload is diagnostic).  Staleness is judged by the file's mtime (the
# one timestamp a crashed owner cannot fail to have written), honest for
# the single-host-N-process scope the fleet tier targets.

LEASE_SUFFIX = ".lease"


def acquire_lease(path: str, owner: str = "") -> bool:
    """Try to create the lease file at ``path`` exclusively.  Returns
    True iff THIS caller created it (and now owns the lease); False when
    it already exists (someone else holds it).  Never blocks."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    try:
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o644)
    except FileExistsError:
        return False
    try:
        import json

        os.write(fd, (json.dumps({"owner": str(owner)}) + "\n").encode())
    finally:
        os.close(fd)
    return True


def read_lease(path: str):
    """The lease's owner payload (``{"owner": ...}``) or None when the
    file is missing; an unreadable/torn payload reads as ``{"owner":
    None}`` — the lease still EXISTS (existence is the contract, the
    payload is diagnostic)."""
    import json

    try:
        with open(path, "rb") as f:
            return json.loads(f.read().decode("utf-8"))
    except FileNotFoundError:
        return None
    except (ValueError, OSError, UnicodeDecodeError):
        return {"owner": None}


def lease_age_s(path: str, now=None):
    """Seconds since the lease file was created (mtime), or None when it
    is missing.  Wall-clock (``time.time``): leases coordinate
    PROCESSES, which share the host's wall clock — the injectable
    monotonic clocks the serving layer uses elsewhere do not cross a
    fork.

    Clamped at zero (ISSUE 16 satellite): a wall clock stepped BACKWARD
    (NTP slew, VM migration) makes ``now - mtime`` negative; a negative
    age must read as "fresh", never poison a staleness comparison
    downstream."""
    import time

    try:
        mtime = os.path.getmtime(path)
    except OSError:
        return None
    return max(0.0, (time.time() if now is None else float(now)) - mtime)


def release_lease(path: str) -> bool:
    """Remove the lease file; True iff this call removed it (False when
    already gone — release is idempotent)."""
    try:
        os.remove(path)
        return True
    except FileNotFoundError:
        return False


def break_stale_lease(path: str, ttl_s: float, now=None,
                      tolerance_s: float = 0.0) -> bool:
    """Reclaim a lease whose age exceeds ``ttl_s`` (a crashed owner must
    not wedge its fingerprint forever): remove-if-stale, True iff this
    call removed it.  A concurrent remove (another reclaimer, or the
    owner's own release racing the reclaim) reads as False — the caller
    re-runs its acquire either way, so double reclaim is harmless.

    ``tolerance_s`` (ISSUE 16 satellite) widens the staleness threshold
    to ``ttl_s + tolerance_s``: a reclaimer whose wall clock runs AHEAD
    of the owner's sees inflated ages, and the tolerance absorbs skew up
    to that bound before a live owner's lease can be stolen.  Backward
    steps are already harmless — ``lease_age_s`` clamps negative ages to
    zero, so a fresh lease can never look stale under a clock that
    jumped back."""
    age = lease_age_s(path, now=now)
    if age is None or age <= float(ttl_s) + max(0.0, float(tolerance_s)):
        return False
    return release_lease(path)


def read_jsonl_tolerant(path: str) -> tuple:
    """Read a JSONL stream back as ``(records, skipped)``, skipping
    unparseable lines instead of raising — the reader half of
    ``append_jsonl``'s crash contract, shared by
    ``obs.journal.read_journal`` and ``utils.timing
    .read_records_jsonl`` so the tear semantics live in ONE place.  The
    writer's one crash artifact is a torn FINAL line (kill
    mid-``os.write``); a file whose history must survive the preemption
    it recorded cannot afford a fatal parse.  ``skipped`` > 0 is the
    caller's cue to warn — a torn line anywhere but the tail means
    external corruption and must not pass silently.

    Read as binary, decoded per line: the writer always emits UTF-8
    regardless of locale, and a line torn INSIDE a multibyte character
    must count as one more skipped line, not raise ``UnicodeDecodeError``
    before the parse attempt is even reached."""
    import json

    records, skipped = [], 0
    with open(path, "rb") as f:
        for raw in f:
            raw = raw.strip()
            if not raw:
                continue
            try:
                records.append(json.loads(raw.decode("utf-8")))
            except (ValueError, UnicodeDecodeError):
                skipped += 1
    return records, skipped


def gc_orphaned_tmp(path: str, max_age_s: float = 3600.0) -> list:
    """Remove stale atomic-writer temp files next to ``path``.

    ``save_pytree``/``atomic_write_*`` clean their tmp on any in-process
    failure, but a HARD kill (SIGKILL, OOM, power) between the write and
    the rename strands a ``tmp*.npz.tmp``-style sibling forever.  Sweep
    resume and ``preemption_guard`` teardown call this on their
    checkpoint/ledger paths: age-gated (default 1 h — never race a
    concurrent writer's in-flight tmp) and logged.  Returns the removed
    paths."""
    import glob
    import time

    d = os.path.dirname(os.path.abspath(path)) or "."
    removed = []
    now = time.time()
    # only THIS module's writers' signatures — a shared directory (/tmp!)
    # holds other applications' mkstemp files, which are not ours to
    # delete no matter how stale
    ours = [os.path.join(d, f"tmp*{s}")
            for s in (".npz.tmp", ".json.tmp", ".txt.tmp")]
    for tmp in sorted(t for pat in ours for t in glob.glob(pat)):
        try:
            if now - os.path.getmtime(tmp) >= max_age_s:
                os.remove(tmp)
                removed.append(tmp)
        except OSError:
            continue
    if removed:
        import warnings
        warnings.warn(
            f"removed {len(removed)} orphaned checkpoint tmp file(s) "
            f"next to {path}: " + ", ".join(os.path.basename(r)
                                            for r in removed),
            stacklevel=2)
    return removed


def _canonical_treedef(s: str) -> str:
    """Treedef repr with NamedTuple class names erased.

    Validation must be *structural*: the stored repr embeds the writer's
    class name, and migration templates are necessarily aliases with
    different names (a file written by round-2's 8-field ``KSCheckpoint``
    must load into today's ``_KSCheckpointV3``).  Comparing raw strings
    made every cross-version migration tier dead code — the load raised on
    the name before structure was ever considered (round-3 review
    finding).  Shapes/dtypes still come from the file; config fingerprints
    guard semantic compatibility."""
    import re

    return re.sub(r"namedtuple\[\w+\]", "namedtuple[_]", s)


def load_pytree(path: str, like, strict: bool = True):
    """Read a pytree saved by ``save_pytree`` into the structure of ``like``
    (validated against the stored treedef; leaf shapes/dtypes come from the
    file).  Leaf keys are ordered numerically by their index, so the count
    is unbounded (no lexicographic rollover at 4 digits).

    ``strict=True`` (default) requires the exact treedef repr, NamedTuple
    class names included — two structurally isomorphic but semantically
    different NamedTuples must not silently load into each other.
    ``strict=False`` erases NamedTuple class names before comparing
    (``_canonical_treedef``) — reserved for *migration* loaders like
    ``load_ks_checkpoint``, whose version-tier templates are necessarily
    aliases with different names (round-3 review scoped this relaxation
    here; it used to apply to every caller)."""
    treedef = jax.tree_util.tree_structure(like)
    n = treedef.num_leaves
    with np.load(path) as data:
        stored_def = (str(data["__treedef__"])
                      if "__treedef__" in data.files else None)
        keys = sorted((k for k in data.files if k.startswith("leaf_")),
                      key=lambda k: int(k[5:]))
        if stored_def is not None:
            want = str(treedef)
            match = (stored_def == want if strict else
                     _canonical_treedef(stored_def)
                     == _canonical_treedef(want))
            if not match:
                raise ValueError(
                    f"checkpoint {path} was written for pytree structure\n  "
                    f"{stored_def}\nbut the template is\n  {treedef}")
        if len(keys) != n:
            raise ValueError(
                f"checkpoint {path} holds {len(keys)} leaves, template "
                f"expects {n} — wrong template or corrupted file")
        leaves = [data[k] for k in keys]
    return jax.tree_util.tree_unflatten(treedef, leaves)


class SweepSidecar(NamedTuple):
    """Warm-start sidecar for the Table II sweep scheduler: one prior run's
    per-cell work counters and roots, keyed by the (σ, ρ, sd) triples and
    fingerprinted against the solver configuration that produced them.

    Two consumers (``parallel.sweep``): the WORK MODEL reads
    ``total_work()`` to sort/bucket cells by measured — not guessed — cost,
    and the WARM-BRACKET seeder reads ``r_star`` to descend each cell's
    bisection bracket toward its known root before the batch launches.
    Rows with a failure status carry NaN ``r_star`` (never seed from a
    quarantined cell) but keep their counters (a failed cell's work is
    still the best cost estimate available).

    ``descent_steps``/``polish_steps`` split the counters by
    precision-ladder phase (DESIGN §5; all zeros for a "reference"-policy
    run), so ``total_work()`` can weight a cheap descent step by its
    measured relative cost (``config.DESCENT_STEP_COST``) — without the
    weighting a mixed-policy sidecar would overstate the cost of cells
    whose work is mostly cheap steps and the scheduler's buckets would
    drift off balance.  Adding the columns is a sidecar format change:
    an old-format file fails the pytree template load and the scheduler
    degrades to its heuristic, exactly like any corrupt sidecar.

    ``checksum`` (DESIGN §9) is the ``fingerprint.content_checksum`` of
    every content array above, computed at save time: warm-bracket seeds
    READ ``r_star`` live, so a bit-flipped row that still parses would
    silently move a descended bracket (the seed verification would catch
    the junk target at the cost of two wasted solves per lane — but a
    corrupted COUNTER row would skew the bucket plan with no verification
    downstream at all).  ``load_sweep_sidecar`` verifies it and raises
    the typed ``IntegrityError``; the scheduler degrades to its
    heuristic, same as any corrupt sidecar."""

    cells: np.ndarray         # [C, 3] (σ, ρ, sd), float64
    r_star: np.ndarray        # [C] net rate at the certified root; NaN=failed
    bisect_iters: np.ndarray  # [C] int64 excess evaluations
    egm_iters: np.ndarray     # [C] int64 total EGM backward steps
    dist_iters: np.ndarray    # [C] int64 total distribution steps
    descent_steps: np.ndarray  # [C] int64 cheap-phase inner steps
    polish_steps: np.ndarray   # [C] int64 reference-phase inner steps
    status: np.ndarray        # [C] int64 solver_health codes
    fingerprint: np.ndarray   # scalar int64 — solver-config hash
    # scalar int64 content checksum (DESIGN §9); the default (0 = unset)
    # keeps hand-built sidecars (tests, tooling) constructible — the
    # blessed writer always stamps the real checksum
    checksum: np.ndarray = np.zeros((), np.int64)

    def content_checksum(self) -> int:
        """The checksum the stored content SHOULD carry — one canonical
        hashing order, shared by the writer and the verifier."""
        from .fingerprint import content_checksum

        return content_checksum(self.cells, self.r_star, self.bisect_iters,
                                self.egm_iters, self.dist_iters,
                                self.descent_steps, self.polish_steps,
                                self.status)

    def total_work(self) -> np.ndarray:
        """Reference-precision-equivalent per-cell work: every step, with
        descent-phase steps weighted by their measured relative cost."""
        from .config import DESCENT_STEP_COST

        total = (self.egm_iters + self.dist_iters).astype(np.float64)
        return total - (1.0 - DESCENT_STEP_COST) * self.descent_steps

    def lookup(self, cell, decimals: int = 9):
        """Row index of ``cell`` = (σ, ρ, sd) (rounded match), or None."""
        key = np.round(np.asarray(cell, dtype=np.float64), decimals)
        hits = np.nonzero(
            (np.round(self.cells, decimals) == key[None, :]).all(axis=1))[0]
        return int(hits[0]) if len(hits) else None


def save_sweep_sidecar(path: str, cells, r_star, bisect_iters, egm_iters,
                       dist_iters, status, fingerprint: int,
                       descent_steps=None, polish_steps=None) -> None:
    """Persist a sweep's per-cell record for the next run's scheduler
    (atomic npz via ``save_pytree``).  ``descent_steps``/``polish_steps``
    default to the all-reference split (zero descent)."""
    n = len(np.asarray(r_star))
    if descent_steps is None:
        descent_steps = np.zeros(n, dtype=np.int64)
    if polish_steps is None:
        polish_steps = (np.asarray(egm_iters, dtype=np.int64)
                        + np.asarray(dist_iters, dtype=np.int64))
    side = SweepSidecar(
        cells=np.asarray(cells, dtype=np.float64),
        r_star=np.asarray(r_star, dtype=np.float64),
        bisect_iters=np.asarray(bisect_iters, dtype=np.int64),
        egm_iters=np.asarray(egm_iters, dtype=np.int64),
        dist_iters=np.asarray(dist_iters, dtype=np.int64),
        descent_steps=np.asarray(descent_steps, dtype=np.int64),
        polish_steps=np.asarray(polish_steps, dtype=np.int64),
        status=np.asarray(status, dtype=np.int64),
        fingerprint=np.asarray(fingerprint, np.int64),
        checksum=np.zeros((), np.int64))
    save_pytree(path, side._replace(
        checksum=np.asarray(side.content_checksum(), np.int64)))


def load_sweep_sidecar(path: str, fingerprint: int) -> SweepSidecar:
    """Load a scheduler sidecar, refusing one written under a different
    solver configuration or with corrupted content.

    Raises ``CheckpointMismatchError`` on a fingerprint mismatch, the
    typed ``fingerprint.IntegrityError`` on a content-checksum mismatch
    (the stored counters/roots are not the bytes that were solved), and
    lets OSError/ValueError from a missing or corrupt file propagate —
    the scheduler catches all of these and degrades to its (σ, ρ, sd)
    heuristic: a stale or corrupted work model must never be silently
    trusted for warm brackets (the bracket seeds would fail verification
    and waste two evaluations per lane), and a missing sidecar is the
    normal first-run state."""
    from .fingerprint import IntegrityError

    n = 1   # template leaf shapes come from the file; any row count loads
    tmpl = SweepSidecar(
        cells=np.zeros((n, 3)), r_star=np.zeros(n),
        bisect_iters=np.zeros(n, np.int64), egm_iters=np.zeros(n, np.int64),
        dist_iters=np.zeros(n, np.int64),
        descent_steps=np.zeros(n, np.int64),
        polish_steps=np.zeros(n, np.int64), status=np.zeros(n, np.int64),
        fingerprint=np.zeros((), np.int64), checksum=np.zeros((), np.int64))
    side = load_pytree(path, tmpl)
    if int(side.fingerprint) != int(fingerprint):
        raise CheckpointMismatchError(
            f"sweep sidecar {path} was written under solver-config "
            f"fingerprint {int(side.fingerprint)}, current is "
            f"{int(fingerprint)}; refusing a stale work model")
    want = side.content_checksum()
    if int(side.checksum) != int(want):
        from ..obs.runtime import emit_event

        emit_event("INTEGRITY_FAILED", boundary="sidecar", path=path)
        raise IntegrityError(
            f"sweep sidecar {path} failed content-checksum verification "
            f"(stored {int(side.checksum)}, content hashes to {want}) — "
            "silent corruption; refusing the work model",
            boundary="sidecar")
    return side


class KSCheckpoint(NamedTuple):
    """Resumable state of the Krusell-Smith outer loop: the perceived rule,
    how many outer iterations produced it, the RNG seed that generated the
    shock panel, a fingerprint of the configuration that produced it
    (SURVEY.md §5 'Checkpoint / resume'), and — for the slope-pinned
    deterministic mode — the secant iteration's memory (previous iterate,
    previous residual, bracket), so a resumed run continues the same
    trajectory instead of re-probing from scratch."""

    intercept: np.ndarray    # [2]
    slope: np.ndarray        # [2]
    iteration: np.ndarray    # scalar int
    seed: np.ndarray         # scalar int
    converged: np.ndarray    # scalar bool
    fingerprint: np.ndarray  # scalar int64 — config hash
    secant: np.ndarray       # [4] (i_prev, g_prev, lo, hi); NaN = unset
    last_distance: np.ndarray  # scalar: rule distance at the saved iteration
    last_residual: np.ndarray  # scalar: pinned |g| at the saved iteration
    #                            (+inf when not pinned / unknown)


def ks_checkpoint_template() -> KSCheckpoint:
    return KSCheckpoint(
        intercept=np.zeros(2), slope=np.zeros(2),
        iteration=np.zeros((), np.int64), seed=np.zeros((), np.int64),
        converged=np.zeros((), np.bool_),
        fingerprint=np.zeros((), np.int64),
        secant=np.full((4,), np.nan),
        last_distance=np.full((), np.inf),
        last_residual=np.full((), np.inf))


# The fingerprint primitive lives in ``utils.fingerprint`` now (one
# vocabulary for sidecar/ledger/KS/store keys — ISSUE 4 satellite); the
# historic import path stays valid for existing callers.
from .fingerprint import config_fingerprint  # noqa: F401,E402  (re-export)


def save_ks_checkpoint(path: str, afunc, iteration: int, seed: int,
                       converged: bool, fingerprint: int = 0,
                       secant=None, last_distance: float = np.inf,
                       last_residual: float = np.inf) -> None:
    save_pytree(path, KSCheckpoint(
        intercept=np.asarray(afunc.intercept),
        slope=np.asarray(afunc.slope),
        iteration=np.asarray(iteration, np.int64),
        seed=np.asarray(seed, np.int64),
        converged=np.asarray(converged, np.bool_),
        fingerprint=np.asarray(fingerprint, np.int64),
        secant=(np.full((4,), np.nan) if secant is None
                else np.asarray(secant, np.float64)),
        last_distance=np.asarray(last_distance, np.float64),
        last_residual=np.asarray(last_residual, np.float64)))


class _KSCheckpointV1(NamedTuple):
    """Round-1 layout (no secant memory, no last_distance)."""

    intercept: np.ndarray
    slope: np.ndarray
    iteration: np.ndarray
    seed: np.ndarray
    converged: np.ndarray
    fingerprint: np.ndarray


class _KSCheckpointV2(NamedTuple):
    """Intermediate layout (secant memory, no last_distance)."""

    intercept: np.ndarray
    slope: np.ndarray
    iteration: np.ndarray
    seed: np.ndarray
    converged: np.ndarray
    fingerprint: np.ndarray
    secant: np.ndarray


class _KSCheckpointV3(NamedTuple):
    """Round-2 layout (last_distance, no last_residual)."""

    intercept: np.ndarray
    slope: np.ndarray
    iteration: np.ndarray
    seed: np.ndarray
    converged: np.ndarray
    fingerprint: np.ndarray
    secant: np.ndarray
    last_distance: np.ndarray


def load_ks_checkpoint(path: str) -> KSCheckpoint:
    """Load a KS checkpoint, migrating older layouts in place of failing.

    Missing fields get conservative defaults: ``secant`` unset (the pinned
    iteration re-probes) and ``last_distance`` +inf — a migrated
    "converged" checkpoint therefore re-runs at least one outer iteration
    against the CURRENT tolerance instead of short-circuiting, which costs
    one iteration and can never return a stale convergence claim."""
    try:
        return load_pytree(path, ks_checkpoint_template())
    except ValueError:
        pass
    zeros6 = (np.zeros(2), np.zeros(2), np.zeros((), np.int64),
              np.zeros((), np.int64), np.zeros((), np.bool_),
              np.zeros((), np.int64))
    try:
        old = load_pytree(path, _KSCheckpointV3(*zeros6, secant=np.zeros(4),
                                                last_distance=np.zeros(())),
                          strict=False)
        return KSCheckpoint(*old, last_residual=np.asarray(np.inf))
    except ValueError:
        pass
    try:
        old = load_pytree(path, _KSCheckpointV2(*zeros6,
                                                secant=np.zeros(4)),
                          strict=False)
        return KSCheckpoint(*old, last_distance=np.asarray(np.inf),
                            last_residual=np.asarray(np.inf))
    except ValueError:
        old = load_pytree(path, _KSCheckpointV1(*zeros6), strict=False)
        return KSCheckpoint(*old, secant=np.full((4,), np.nan),
                            last_distance=np.asarray(np.inf),
                            last_residual=np.asarray(np.inf))
