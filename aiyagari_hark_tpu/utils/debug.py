"""Sanitizers: NaN/Inf detection inside jitted kernels and invariant checks.

The reference has no sanitizers (single-threaded NumPy; SURVEY.md §5 "Race
detection / sanitizers").  The TPU-native equivalents here:

 - ``checked_call``: run any jitted computation under ``jax.experimental
   .checkify`` float checks, so a NaN/Inf produced INSIDE a
   ``lax.while_loop``/``scan`` (where ``jax_debug_nans`` cannot look)
   surfaces as a Python exception naming the failing primitive instead of
   silently propagating into the fixed point.
 - ``nan_guard``: a context manager toggling ``jax_debug_nans`` for
   eager/debug runs of host-side code.
 - ``validate_policy`` / ``validate_distribution``: host-side invariant
   checks (finite, monotone knots, positive consumption; mass one,
   non-negative) for use at phase boundaries — cheap enough to leave on in
   drivers, precise enough to localize corruption to a phase.
"""

from __future__ import annotations

import contextlib

import numpy as np


def checked_call(fn, *args, **kwargs):
    """Execute ``fn(*args, **kwargs)`` under checkify float checks and
    throw on any NaN/Inf/div-by-zero generated anywhere inside — including
    within ``lax.while_loop`` bodies, which ``jax_debug_nans`` cannot
    instrument.  Returns ``fn``'s outputs unchanged on success.

    Debug tool: the checkify transform blocks some fusions, so expect a
    slowdown; use on failing configurations, not in production runs."""
    import jax
    from jax.experimental import checkify

    checked = checkify.checkify(
        fn, errors=checkify.float_checks | checkify.user_checks)
    # args flow through jit as traced arguments (not baked-in constants),
    # so repeated debug calls on different data reuse the compilation
    err, out = jax.jit(checked)(*args, **kwargs)
    err.throw()
    return out


@contextlib.contextmanager
def nan_guard():
    """Enable ``jax_debug_nans`` within the block (eager/debuggable code
    paths; for jitted fixed-point loops use ``checked_call``)."""
    import jax

    prev = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", True)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", prev)


def validate_policy(policy, name: str = "policy") -> None:
    """Host-side invariants of a consumption policy (single-asset
    ``HouseholdPolicy``, KS ``KSPolicy`` per state, or the consumption part
    of a ``PortfolioPolicy``): finite knots, strictly increasing endogenous
    m-knots (EGM output must be sortable), positive consumption."""
    m = np.asarray(policy.m_knots)
    c = np.asarray(policy.c_knots)
    if not np.isfinite(m).all() or not np.isfinite(c).all():
        raise ValueError(f"{name}: non-finite knots "
                         f"(m finite={np.isfinite(m).all()}, "
                         f"c finite={np.isfinite(c).all()})")
    if not (c > 0).all():
        raise ValueError(f"{name}: non-positive consumption knots "
                         f"(min={c.min()})")
    dm = np.diff(m, axis=-1)
    if not (dm > 0).all():
        bad = int((dm <= 0).sum())
        raise ValueError(f"{name}: {bad} non-increasing m-knot segments — "
                         f"EGM grid not sortable (crossing policy update)")


def validate_distribution(dist, name: str = "distribution",
                          atol: float = 1e-8) -> None:
    """Host-side invariants of a wealth histogram: non-negative, total mass
    one (the lottery scatter conserves mass exactly; violation means a
    corrupted transition or an unnormalized extrapolation)."""
    d = np.asarray(dist)
    if not np.isfinite(d).all():
        raise ValueError(f"{name}: non-finite mass entries")
    if (d < -atol).any():
        raise ValueError(f"{name}: negative mass (min={d.min()})")
    total = float(d.sum())
    if abs(total - 1.0) > atol:
        raise ValueError(f"{name}: total mass {total} != 1")
