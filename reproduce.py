#!/usr/bin/env python
"""End-to-end reproduction driver: the reference notebook's full pipeline
(``Aiyagari-HARK.ipynb`` cells 13-30 / ``Aiyagari-HARK.py:234-361``) run
through this framework's facade.

    build economy + agent  ->  make_Mrkv_history  ->  solve
    -> print equilibrium return & savings rate        (cells 19-20)
    -> per-state consumption-function figure          (cell 21)
    -> aggregate saving rule figure                   (cell 22, make_figs
       'aggregate_savings', Aiyagari-HARK.py:290)
    -> simulated wealth stats                         (cell 24)
    -> Lorenz curve vs SCF + Euclidean distance       (cells 25-27,
       make_figs 'wealth_distribution_1', :326)
    -> runtime.txt + results.json                     (cell 30, :357-359)

Reference golden numbers (BASELINE.md): r* 4.178%, saving rate 23.649%,
wealth max/mean/std/median 22.046/5.439/3.697/4.718, Lorenz-vs-SCF 0.9714,
solve wall-clock 27.12 min (this framework: well under a minute on CPU).

Like the reference's ``make_figs`` (HARK.utilities), each figure is written
in four formats (png/jpg/pdf/svg) into ``--figures-dir``.

Usage:
    python reproduce.py                   # full notebook-parity run
    python reproduce.py --quick           # small-config smoke (~seconds)
    python reproduce.py --backend cpu     # force the x64 CPU oracle
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def make_figs(fig, name: str, figures_dir: str) -> list:
    """Persist ``fig`` as png/jpg/pdf/svg under ``figures_dir`` — the
    reference's ``make_figs`` output contract (``Figures/`` holds 2 figures
    x 4 formats; ``Aiyagari-HARK.py:290,326``).

    Output is byte-deterministic for identical data: matplotlib embeds a
    creation date in pdf/svg and randomizes svg element ids by default, so
    every rerun used to churn ~470 diff lines of pure metadata in the
    committed artifacts (round-4 review).  Pinning ``svg.hashsalt`` and
    stripping the date metadata makes a real figure change visible as a
    real diff."""
    import os

    import matplotlib

    os.makedirs(figures_dir, exist_ok=True)
    paths = []
    for ext in ("png", "jpg", "pdf", "svg"):
        p = os.path.join(figures_dir, f"{name}.{ext}")
        # each backend names its date keys differently; png/jpg writers
        # reject date keys entirely
        metadata = {"pdf": {"CreationDate": None, "ModDate": None},
                    "svg": {"Date": None}}.get(ext)
        # rc_context: the salt must not leak into other SVG saves of an
        # importing process (round-4 review)
        with matplotlib.rc_context({"svg.hashsalt": "aiyagari-hark-tpu"}):
            fig.savefig(p, metadata=metadata)
        paths.append(p)
    return paths


def _run_irf_extra(args, econ_dict, info, depr, n_states, timer, plt, np):
    """Beyond-parity: GE impulse response to a TFP shock
    (models/transition + models/jacobian; Figures/impulse_response.*) —
    the nonlinear MIT-shock path overlaid with the sequence-space Jacobian
    linearization, on the notebook's (CRRA, labor-process) calibration at
    illustration-size grids."""
    with timer.phase("irf"):
        import jax.numpy as jnp

        from aiyagari_hark_tpu.models.equilibrium import (
            solve_bisection_equilibrium,
        )
        from aiyagari_hark_tpu.models.household import build_simple_model
        from aiyagari_hark_tpu.models.jacobian import (
            linear_impulse_response,
            sequence_jacobians,
        )
        from aiyagari_hark_tpu.models.transition import solve_transition

        horizon = 24 if args.quick else 48
        irf_model = build_simple_model(
            labor_states=min(n_states, 5), labor_ar=econ_dict["LaborAR"],
            labor_sd=econ_dict["LaborSD"],
            a_count=16 if args.quick else 40,
            dist_count=60 if args.quick else 200, dtype=info.dtype)
        crra = econ_dict["CRRA"]
        beta, alpha = econ_dict["DiscFac"], econ_dict["CapShare"]
        eq = solve_bisection_equilibrium(irf_model, beta, crra, alpha, depr)
        dz = 0.01 * 0.8 ** np.arange(horizon)
        jac = sequence_jacobians(irf_model, beta, crra, alpha, depr, eq,
                                 horizon)
        lin = linear_impulse_response(jac, jnp.asarray(dz))
        nl = solve_transition(irf_model, beta, crra, alpha, depr,
                              init_dist=eq.distribution,
                              terminal_policy=eq.policy,
                              k_terminal=eq.capital, horizon=horizon,
                              prod_path=1.0 + dz)
        k_ss = float(eq.capital)
        dk_nl = 100.0 * (np.asarray(nl.k_path) / k_ss - 1.0)
        dk_lin = 100.0 * np.asarray(lin.dk) / k_ss
        fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(9, 3.6))
        t = np.arange(horizon)
        ax1.plot(t, 100.0 * dz, "k--", label="TFP shock (%)")
        ax1.plot(t, dk_nl, label="K, nonlinear (MIT shock)")
        ax1.plot(t, dk_lin, ":", label="K, linear (Jacobian)")
        ax1.set_xlabel("quarters"), ax1.set_ylabel("% dev from SS")
        ax1.legend(fontsize=8)
        ax2.plot(t, 100.0 * np.asarray(lin.dc) / float(jac.y_ss),
                 label="C (linear)")
        ax2.plot(t, 100.0 * np.asarray(lin.dy) / float(jac.y_ss),
                 label="Y (linear)")
        ax2.set_xlabel("quarters"), ax2.set_ylabel("% of SS output")
        ax2.legend(fontsize=8)
        fig.suptitle("GE impulse response to a 1% transitory TFP shock")
        fig.tight_layout()
        irf_paths = make_figs(fig, "impulse_response", args.figures_dir)
        plt.close(fig)
        irf_gap = float(np.abs(dk_lin - dk_nl).max())
    print(f"IRF figure written (linear-vs-nonlinear peak gap "
          f"{irf_gap:.4f} pp of K)")
    return irf_paths, {
        "horizon": horizon, "shock_pct": 1.0,
        "k_peak_pct": float(np.abs(dk_nl).max()),
        "linear_nonlinear_gap_pp": irf_gap,
        "r_star_bisection_pct": 100.0 * float(eq.r_star)}


def _solve_histogram_engine(args, econ_dict, agent_dict, info, timer,
                            phase: str):
    """Solve the deterministic (pinned-histogram) engine at the main run's
    calibration.  Shared by the den Haan side-by-side (default path) and
    the ``--extras`` histogram block so the engine is solved once per
    reproduction, not once per consumer."""
    from aiyagari_hark_tpu import AiyagariEconomy, AiyagariType

    with timer.phase(phase):
        economy = AiyagariEconomy(seed=args.seed, **econ_dict)
        agent = AiyagariType(**agent_dict)
        agent.cycles = 0
        agent.get_economy_data(economy)
        economy.agents = [agent]
        economy.make_Mrkv_history()
        sol = economy.solve(dtype=info.dtype, sim_method="distribution")
    return sol, economy


def _pinned_den_haan(args, econ_dict, agent_dict, info, timer):
    """den Haan side-by-side (VERDICT r4 weak-item 4): solve the
    deterministic pinned-histogram engine at the same calibration and
    report its dynamic-forecast stats NEXT TO the panel rule's, so the
    committed artifact no longer quotes a 2.28% max error against a
    "fraction of a percent" standard without the engine that meets it.
    The pinned rule is a constant (slope 0), so it has no off-path slope
    to be wrong about — its forecast error is bounded by the secant
    tolerance plus settled-path drift; the reference-parity MC panel
    rule's slope (~1.11) is errors-in-variables-attenuated and compounds
    percent-level drift when iterated without feedback
    (``models/diagnostics.py``, DESIGN §3).

    Returns ``((sol, economy), fields)`` so ``--extras`` can reuse the
    solve."""
    from aiyagari_hark_tpu.models.diagnostics import den_haan_forecast

    sol, economy = _solve_histogram_engine(args, econ_dict, agent_dict,
                                           info, timer, "den_haan_pinned")
    dh = den_haan_forecast(sol, t_start=econ_dict["T_discard"])
    fields = {
        "den_haan_pinned_max_error_pct": float(dh.max_error_pct),
        "den_haan_pinned_mean_error_pct": float(dh.mean_error_pct),
        "den_haan_pinned_converged": bool(sol.converged),
    }
    print(f"den Haan dynamic forecast error (pinned-histogram engine): "
          f"max {fields['den_haan_pinned_max_error_pct']:.3f} %  "
          f"mean {fields['den_haan_pinned_mean_error_pct']:.3f} %  "
          f"(panel rule above: the MC-fit slope's off-path drift; "
          f"see models/diagnostics.py)")
    return (sol, economy), fields


def _run_histogram_extra(args, econ_dict, agent_dict, info, timer, stats,
                         solved=None):
    """Beyond-parity: the deterministic histogram engine's own fixed point
    on the same calibration, so results.json reports BOTH simulators'
    wealth statistics (VERDICT r2 next-round item 3).  Skipped when the
    main run already used the distribution engine.  ``solved``: an
    already-computed ``(sol, economy)`` pair from the den Haan
    side-by-side, reused instead of re-solving."""
    if args.sim_method == "distribution":
        return None
    if solved is not None:
        sol, economy = solved
    else:
        sol, economy = _solve_histogram_engine(args, econ_dict, agent_dict,
                                               info, timer,
                                               "histogram_engine")
    with timer.phase("histogram_stats"):
        grid = economy.reap_state["aNowGrid"][0]
        w = economy.reap_state["aNowWeights"][0]
        ws = stats.wealth_stats(grid, w)
        out = {
            "converged": bool(sol.converged),
            "r_pct": (economy.sow_state["Rnow"] - 1.0) * 100.0,
            "wealth_stats": {"max": ws.max, "mean": ws.mean,
                             "std": ws.std, "median": ws.median},
            "lorenz_distance": stats.lorenz_distance_vs_scf(grid, w),
        }
    print(f"Histogram engine (extras): r*={out['r_pct']:.4f}% "
          f"mean={ws.mean:.3f} std={ws.std:.3f} median={ws.median:.3f} "
          f"lorenz={out['lorenz_distance']:.4f}")
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--backend", default="auto",
                    choices=["auto", "cpu", "tpu"],
                    help="platform+dtype+precision (utils.backend)")
    ap.add_argument("--quick", action="store_true",
                    help="small config smoke run (seconds, not parity)")
    ap.add_argument("--figures-dir", default="Figures")
    ap.add_argument("--output-dir", default=".",
                    help="where runtime.txt / results.json go")
    ap.add_argument("--seed", type=int, default=1,
                    help="shock-stream seed; default 1 IS the committed "
                         "artifacts' draw (results.json/Figures), chosen "
                         "near the center of the measured 32-seed Lorenz "
                         "sampling band (PARITY.md §6; the seed-0 draw "
                         "sits at the band's edge, z≈-1.8)")
    ap.add_argument("--sim-method", default="panel",
                    choices=["panel", "distribution"],
                    help="'panel' = reference-parity Monte-Carlo agents; "
                         "'distribution' = deterministic histogram "
                         "simulator + slope-pinned secant equilibrium "
                         "(matches the bisection engine, not the "
                         "reference's MC-attenuated KS fit)")
    ap.add_argument("--scf-csv", default=None,
                    help="optional wealth,weight CSV exported from HARK's "
                         "load_SCF_wealth_weights; without it the Lorenz "
                         "comparison uses the SCF curve vendored from the "
                         "reference's committed vector figure "
                         "(aiyagari_hark_tpu/data/scf_lorenz.csv)")
    ap.add_argument("--no-den-haan-pinned", action="store_true",
                    help="skip the pinned-histogram den Haan side-by-side "
                         "(a second full engine solve, ~2.5 min on CPU at "
                         "parity size) — the default pipeline pays it only "
                         "because the committed results.json carries the "
                         "side-by-side fields; use this flag for iteration "
                         "runs that don't regenerate the artifact")
    ap.add_argument("--resume", default=None, metavar="PATH",
                    help="KS checkpoint path (utils.checkpoint) for the "
                         "main solve: written every outer iteration, "
                         "resumed from when the file exists — a "
                         "preempted run restarted with the same path "
                         "continues its trajectory instead of starting "
                         "over (utils.resilience; SIGTERM/SIGINT exit "
                         "gracefully at the next iteration boundary with "
                         "code 75)")
    ap.add_argument("--extras", action="store_true",
                    help="also run the beyond-parity reporting (GE impulse "
                         "response figure, the histogram engine's "
                         "wealth-stats readout); off by default so the "
                         "'solve' phase in runtime.txt stays the "
                         "reference-comparable notebook pipeline.  One "
                         "diagnostic runs regardless (unless "
                         "--no-den-haan-pinned): the pinned-engine "
                         "den Haan side-by-side, in its own "
                         "'den_haan_pinned' timer phase — compare the "
                         "reference's 27.12 min against 'solve', not "
                         "against the total")
    args = ap.parse_args(argv)
    if args.scf_csv and not os.path.exists(args.scf_csv):
        ap.error(f"--scf-csv {args.scf_csv!r} does not exist")

    from aiyagari_hark_tpu.utils.resilience import (
        Interrupted,
        preemption_guard,
    )
    try:
        with preemption_guard(
                gc_paths=(args.resume,) if args.resume else ()):
            return _run_pipeline(args)
    except Interrupted as e:
        print(f"[reproduce] preempted at a safe boundary: {e}"
              + (f"; rerun with --resume {e.resume_path} to continue"
                 if e.resume_path else ""), file=sys.stderr)
        sys.exit(75)           # EX_TEMPFAIL: supervisors restart on this


def _run_pipeline(args):
    start_time = time.time()

    from aiyagari_hark_tpu.utils.backend import (enable_compilation_cache,
                                                 select_backend)
    enable_compilation_cache()
    info = select_backend(args.backend)
    print(f"[reproduce] backend={info.name} "
          f"dtype={'f64' if info.x64 else 'f32'}")

    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    import numpy as np

    from aiyagari_hark_tpu import (
        AiyagariEconomy,
        AiyagariType,
        init_aiyagari_agents,
        init_aiyagari_economy,
    )
    from aiyagari_hark_tpu.utils import stats
    from aiyagari_hark_tpu.utils.timing import PhaseTimer

    timer = PhaseTimer()

    # -- build (notebook cells 16-18: LaborAR=0.3, CRRA=1.0, AgentCount=350)
    econ_dict = init_aiyagari_economy()
    econ_dict.update(LaborAR=0.3, LaborSD=0.2, CRRA=1.0, verbose=False)
    agent_dict = init_aiyagari_agents()
    agent_dict.update(AgentCount=350)
    if args.quick:
        econ_dict.update(LaborStatesNo=5, act_T=600, T_discard=120)
        agent_dict.update(LaborStatesNo=5, AgentCount=100, aCount=16)

    economy = AiyagariEconomy(seed=args.seed, **econ_dict)
    agent = AiyagariType(**agent_dict)
    agent.cycles = 0
    agent.get_economy_data(economy)
    economy.agents = [agent]
    with timer.phase("mrkv_history"):
        economy.make_Mrkv_history()

    # -- solve (cell 19)
    n_states = econ_dict["LaborStatesNo"]
    print(f"Now solving for the equilibrium of the {n_states}-State "
          f"Aiyagari (1994) model...")
    t0 = time.time()
    with timer.phase("solve"):
        sol = economy.solve(dtype=info.dtype, sim_method=args.sim_method,
                            checkpoint_path=args.resume)
    solve_minutes = (time.time() - t0) / 60.0
    print(f"Solving the Aiyagari model took {solve_minutes:.3f} minutes "
          f"(reference: 27.12 minutes). converged={sol.converged}")
    from aiyagari_hark_tpu.utils.debug import validate_policy
    validate_policy(sol.policy, "solved KS policy")   # sanitizer boundary

    # -- equilibrium stats (cell 20 / Aiyagari-HARK.py:257-258).
    # Distribution mode: use the EXACT histogram pair (aNowGrid/Weights);
    # "aNow" itself is notebook-compatible (equal-weight) in both modes.
    depr = econ_dict["DeprFac"]
    if "aNowGrid" in economy.reap_state:
        sim_weights = economy.reap_state["aNowWeights"][0]
        a_mean = float(np.average(economy.reap_state["aNowGrid"][0],
                                  weights=sim_weights))
    else:
        sim_weights = None
        a_mean = float(np.mean(economy.reap_state["aNow"]))
    r_pct = (economy.sow_state["Rnow"] - 1.0) * 100.0
    saving_pct = 100.0 * depr * a_mean / (
        economy.sow_state["Mnow"] - (1.0 - depr) * a_mean)
    print(f"Equilibrium Return to Capital: {r_pct:.4f} % "
          f"(reference 4.178 %)")
    print(f"Equilibrium Savings Rate: {saving_pct:.4f} % "
          f"(reference 23.649 %)")

    # den Haan (2010) dynamic-forecast accuracy of the converged rule —
    # the aggregate-law diagnostic the reference lacks (models/diagnostics)
    from aiyagari_hark_tpu.models.diagnostics import den_haan_forecast
    dh = den_haan_forecast(sol, t_start=econ_dict["T_discard"])
    print(f"den Haan dynamic forecast error: "
          f"max {float(dh.max_error_pct):.3f} %  "
          f"mean {float(dh.mean_error_pct):.3f} %")
    # ... and the same diagnostic for the engine that MEETS the den Haan
    # bar (VERDICT r4 weak-item 4): the deterministic pinned-histogram
    # solve, reported side by side in results.json.
    if args.sim_method == "distribution" or args.no_den_haan_pinned:
        # distribution mode IS the pinned engine (nothing to compare), and
        # --no-den-haan-pinned skips the 151.6 s side-by-side explicitly;
        # results.json then simply lacks the den_haan_pinned_* fields
        # (tests/test_artifacts.py only gates the COMMITTED artifact,
        # which the default full run still regenerates with them)
        hist_solved, dh_pin_fields = None, {}
    else:
        hist_solved, dh_pin_fields = _pinned_den_haan(
            args, econ_dict, agent_dict, info, timer)

    # -- consumption functions by labor-supply state (cell 21)
    with timer.phase("figures"):
        n = n_states
        fig, axes = plt.subplots(1, n, figsize=(3.2 * n, 3.2), sharey=True)
        m = np.linspace(0.0, 50.0, 200)
        for j, ax in enumerate(np.atleast_1d(axes)):
            for interp in agent.solution[0].cFunc[4 * j].xInterpolators:
                ax.plot(m, interp(m), lw=0.9)
            ax.set_title(f"labor state {j + 1}/{n}", fontsize=9)
            ax.set_xlabel(r"$m$")
        np.atleast_1d(axes)[0].set_ylabel(r"Consumption $c$")
        fig.suptitle("Consumption function by aggregate market resources")
        fig.tight_layout()
        cf_paths = make_figs(fig, "consumption_functions", args.figures_dir)
        plt.close(fig)

        # -- aggregate saving rule (cell 22 -> Figures/aggregate_savings.*)
        bottom, top = 0.1, 2.0 * economy.KSS
        x = np.linspace(bottom, top, 1000, endpoint=True)
        fig = plt.figure()
        plt.plot(x, economy.AFunc[0](x), label="AFunc[0] (bad state)")
        plt.plot(x, economy.AFunc[1](x), label="AFunc[1] (good state)")
        plt.xlim([bottom, top])
        plt.xlabel("Aggregate market resources $M$")
        plt.ylabel("Aggregate savings $A$")
        plt.title("Aggregate savings as a function of "
                  "aggregate market resources")
        plt.legend()
        agg_paths = make_figs(fig, "aggregate_savings", args.figures_dir)
        plt.close(fig)

    # -- wealth stats (cell 24)
    sim_wealth = np.asarray(
        economy.reap_state["aNowGrid" if sim_weights is not None
                           else "aNow"][0])
    ws = stats.wealth_stats(sim_wealth, sim_weights)
    print(f"Simulated wealth: max={ws.max:.3f} mean={ws.mean:.3f} "
          f"std={ws.std:.3f} median={ws.median:.3f} "
          f"(reference 22.046 / 5.439 / 3.697 / 4.718)")

    # -- Lorenz vs SCF (cells 25-27 -> Figures/wealth_distribution_1.*)
    with timer.phase("lorenz"):
        pctiles = np.linspace(0.01, 0.999, 15)   # Aiyagari-HARK.py:312
        if args.scf_csv:
            scf_wealth, scf_weights = stats.load_scf_wealth_weights(
                args.scf_csv)
            scf_lorenz = stats.get_lorenz_shares(
                scf_wealth, weights=scf_weights, percentiles=pctiles)
            scf_label = "SCF (raw microdata)"
        else:
            scf_lorenz = stats.load_scf_lorenz().scf_shares
            scf_label = "SCF"
        sim_lorenz = stats.get_lorenz_shares(sim_wealth, weights=sim_weights,
                                             percentiles=pctiles)
        lorenz_dist = float(np.sqrt(np.sum((scf_lorenz - sim_lorenz) ** 2)))

        fig = plt.figure(figsize=(5, 5))
        plt.title("Wealth Distribution")
        plt.plot(pctiles, scf_lorenz, "--k", label=scf_label)
        plt.plot(pctiles, sim_lorenz, "-b", label="Aiyagari")
        plt.plot(pctiles, pctiles, "g-.", label="45 Degree")
        plt.xlabel("Percentile of net worth")
        plt.ylabel("Cumulative share of wealth")
        plt.legend(loc=2)
        plt.ylim([0, 1])
        wd_paths = make_figs(fig, "wealth_distribution_1", args.figures_dir)
        plt.close(fig)
    print(f"The Euclidean distance between simulated wealth distribution "
          f"and the {scf_label} estimates is {lorenz_dist:.4f} "
          f"(reference vs real SCF: 0.9714)")

    # -- beyond-parity extras, OFF by default so the reference-comparable
    # pipeline stays separately measured (VERDICT r2 next-round item 8):
    # the committed reference runtime covers only the notebook cells, so
    # the notebook-cell cost must remain legible.  The den Haan
    # side-by-side above is the one default-path exception (VERDICT r4
    # weak-item 4 wants it in the committed artifact); it runs in its own
    # 'den_haan_pinned' timer phase, so the phase breakdown — not the
    # total — is the honest comparison surface ('solve' vs the
    # reference's 27.12 min).
    extras_results: dict = {}
    irf_paths: list = []
    if args.extras:
        irf_paths, extras_results["irf"] = _run_irf_extra(
            args, econ_dict, info, depr, n_states, timer, plt, np)
        extras_results["histogram_engine"] = _run_histogram_extra(
            args, econ_dict, agent_dict, info, timer, stats,
            solved=hist_solved)

    # -- runtime + structured results (cell 30 / runtime.txt:1-2)
    from aiyagari_hark_tpu.utils.checkpoint import (
        atomic_write_json,
        atomic_write_text,
    )
    os.makedirs(args.output_dir, exist_ok=True)
    total_time = time.time() - start_time
    # atomic artifact writes (ISSUE 3 satellite): a kill mid-write must
    # leave the previous runtime.txt/results.json, never a truncated one
    atomic_write_text(
        os.path.join(args.output_dir, "runtime.txt"),
        f"Total runtime: {total_time} seconds\n"
        f"Python version: {sys.version}\n"
        f"Backend: {info.name} ({'f64' if info.x64 else 'f32'})\n"
        f"Phase breakdown:\n{timer.summary()}\n")
    results = {
        "backend": info.name,
        "x64": info.x64,
        "quick": args.quick,
        "seed": args.seed,
        "sim_method": args.sim_method,
        "converged": bool(sol.converged),
        "outer_iterations": len(sol.records),
        "equilibrium_return_pct": r_pct,
        "equilibrium_saving_rate_pct": saving_pct,
        "den_haan_max_error_pct": float(dh.max_error_pct),
        "den_haan_mean_error_pct": float(dh.mean_error_pct),
        **dh_pin_fields,
        "wealth_stats": {"max": ws.max, "mean": ws.mean,
                         "std": ws.std, "median": ws.median},
        "lorenz_distance": lorenz_dist,
        "lorenz_reference": scf_label,
        "afunc_intercept": [a.intercept for a in economy.AFunc],
        "afunc_slope": [a.slope for a in economy.AFunc],
        "solve_minutes": solve_minutes,
        "total_seconds": total_time,
        "phases": timer.report(),
        "extras": extras_results if args.extras else None,
        "figures": cf_paths + agg_paths + wd_paths + irf_paths,
        "reference_goldens": {"r_pct": 4.178, "saving_rate_pct": 23.649,
                              "lorenz_vs_scf": 0.9714,
                              "solve_minutes": 27.12},
    }
    atomic_write_json(os.path.join(args.output_dir, "results.json"),
                      results, indent=2, trailing_newline=False)
    print(f"Total runtime: {total_time:.2f} seconds "
          f"(phase breakdown in runtime.txt)")
    return results


if __name__ == "__main__":
    main()
