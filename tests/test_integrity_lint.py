"""check_integrity_boundaries lint (ISSUE 6 satellite): every raw
ledger/sidecar/store load site must call checksum verification (or carry
an explicit ``# integrity-ok`` waiver) — run in tier-1 so an unverified
load cannot regress in, with fixture tests proving the lint actually
fires on the pattern it guards."""

import importlib.util
import os


def _load_lint():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "check_integrity_boundaries",
        os.path.join(repo, "scripts", "check_integrity_boundaries.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod, repo


def test_integrity_lint_is_clean():
    """The package and entry points contain no unverified raw artifact
    loads — failing here, not in code review."""
    mod, repo = _load_lint()
    findings = mod.scan(repo)
    assert findings == [], "\n".join(
        f"{rel}:{line}: {msg}" for rel, line, msg in findings)


def test_integrity_lint_covers_every_boundary_module():
    """Pin the walk's coverage of the checksummed chain's load sites —
    the resume ledger, the scheduler sidecar, the solution store, the
    verify package itself — instead of trusting it silently."""
    mod, repo = _load_lint()
    rels = {os.path.relpath(t, repo).replace(os.sep, "/")
            for t in mod.scan_targets(repo)}
    for required in ("aiyagari_hark_tpu/utils/resilience.py",
                     "aiyagari_hark_tpu/serve/store.py",
                     "aiyagari_hark_tpu/verify/inject.py",
                     "aiyagari_hark_tpu/verify/certificate.py",
                     "aiyagari_hark_tpu/models/ks_solver.py",
                     "bench.py"):
        assert required in rels, required


def test_lint_fires_on_unverified_load():
    mod, _ = _load_lint()
    findings = mod.scan_source(
        "def restore(path, tmpl):\n"
        "    led = load_pytree(path, tmpl)\n"
        "    return led\n", "fake.py")
    assert [(rel, line) for rel, line, _ in findings] == [("fake.py", 2)]
    # np.load spelling too, including at module level
    findings = mod.scan_source(
        "import numpy as np\n"
        "data = np.load('x.npz')\n", "fake2.py")
    assert [line for _, line, _ in findings] == [2]


def test_lint_accepts_verified_and_waived_loads():
    mod, _ = _load_lint()
    src_verified = (
        "def restore(path, tmpl):\n"
        "    led = load_pytree(path, tmpl)\n"
        "    verify_packed_row(led.packed, led.checksum, 'ledger')\n"
        "    return led\n")
    assert mod.scan_source(src_verified, "ok.py") == []
    src_helper = (
        "class Store:\n"
        "    def get(self, key):\n"
        "        sol = load_pytree(self._file(key), _template())\n"
        "        if not self._verified(sol):\n"
        "            return None\n"
        "        return sol\n")
    assert mod.scan_source(src_helper, "ok2.py") == []
    src_waived = (
        "def migrate(path):\n"
        "    old = load_pytree(path, tmpl)  # integrity-ok\n"
        "    return old\n")
    assert mod.scan_source(src_waived, "ok3.py") == []


def test_lint_end_to_end_on_fake_repo(tmp_path):
    """Through the directory walk: an unverified load dropped into a
    fake repo's serve/ package is a finding; the verified one is not."""
    mod, _ = _load_lint()
    pkg = tmp_path / "aiyagari_hark_tpu" / "serve"
    pkg.mkdir(parents=True)
    (pkg / "bad_loader.py").write_text(
        "def load(path, tmpl):\n"
        "    return load_pytree(path, tmpl)\n")
    (pkg / "good_loader.py").write_text(
        "def load(path, tmpl):\n"
        "    sol = load_pytree(path, tmpl)\n"
        "    verify_packed_row(sol.packed, sol.checksum, 'store')\n"
        "    return sol\n")
    findings = mod.scan(str(tmp_path))
    assert [(rel.replace(os.sep, "/"), line)
            for rel, line, _ in findings] == [
        ("aiyagari_hark_tpu/serve/bad_loader.py", 2)]


def test_atomic_writes_lint_covers_verify_package():
    """ISSUE 6 satellite: the verify/ package's writers are inside the
    atomic-write lint's scope (its injectors carry explicit waivers)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "check_atomic_writes",
        os.path.join(repo, "scripts", "check_atomic_writes.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rels = {os.path.relpath(t, repo).replace(os.sep, "/")
            for t in mod.scan_targets(repo)}
    assert "aiyagari_hark_tpu/verify/inject.py" in rels
    assert "aiyagari_hark_tpu/verify/certificate.py" in rels
    # and the injectors' deliberate raw writes are waived, not findings
    assert mod.scan(repo) == []
