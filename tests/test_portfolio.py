"""Two-asset portfolio-choice solver: FOC zero-crossing machinery against
closed forms, comparative statics (risk aversion, equity premium), and
consistency with the single-asset EGM in the degenerate case."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_hark_tpu.models.household import (
    build_simple_model,
    consumption_at,
    solve_household,
)
from aiyagari_hark_tpu.models.equilibrium import solve_bisection_equilibrium
from aiyagari_hark_tpu.models.portfolio import (
    _optimal_share,
    build_portfolio_model,
    consumption_policy,
    lognormal_risky_returns,
    share_at,
    solve_portfolio_equilibrium,
    solve_portfolio_household,
    stationary_portfolio_wealth,
)

pytestmark = pytest.mark.slow   # heavyweight equilibrium solves (fast profile: -m 'not slow')

R_FREE = 1.02
WAGE = 1.0
BETA = 0.96


def test_lognormal_discretization_moments():
    vals, probs = lognormal_risky_returns(1.08, 0.2, n=21)
    mean = float(jnp.sum(vals * probs))
    var = float(jnp.sum(probs * (vals - mean) ** 2))
    assert mean == pytest.approx(1.08, rel=1e-3)
    assert var ** 0.5 == pytest.approx(0.2, rel=0.08)  # tail-clip bias small


def test_optimal_share_closed_cases():
    grid = jnp.linspace(0.0, 1.0, 11)
    # f decreasing, zero at omega=0.45
    f = 0.45 - grid
    assert float(_optimal_share(f, grid)) == pytest.approx(0.45, abs=1e-6)
    # all negative -> corner 0; all positive -> corner 1
    assert float(_optimal_share(-1.0 - grid, grid)) == 0.0
    assert float(_optimal_share(2.0 - grid, grid)) == 1.0
    # batched leading axes
    batch = jnp.stack([0.45 - grid, 0.8 - grid])
    out = _optimal_share(batch, grid)
    np.testing.assert_allclose(np.asarray(out), [0.45, 0.8], atol=1e-6)


@pytest.fixture(scope="module")
def solved():
    # CRRA high enough for an interior share at reachable wealth (the
    # Merton benchmark (mu-r)/(gamma sigma^2), levered by human wealth,
    # stays corner-1 for gamma=2 at this premium)
    model = build_portfolio_model(labor_states=5, a_count=32,
                                  risky_mean=1.08, risky_std=0.20)
    policy, it, diff = jax.jit(
        lambda: solve_portfolio_household(R_FREE, WAGE, model, BETA, 6.0))()
    assert float(diff) <= 1e-6
    return model, policy


def test_portfolio_policy_sane(solved):
    model, policy = solved
    assert bool(jnp.all(jnp.isfinite(policy.c_knots)))
    assert bool(jnp.all((policy.share >= 0.0) & (policy.share <= 1.0)))
    # consumption increasing in m for every state
    assert bool(jnp.all(jnp.diff(policy.c_knots, axis=1) > 0))


def test_share_declines_with_wealth(solved):
    """With CRRA utility and riskless labor income acting like an implicit
    bond, the risky share falls as financial wealth grows."""
    model, policy = solved
    mid = model.labor_levels.shape[0] // 2
    share_poor = float(share_at(policy, 0.5, model, state_idx=mid))
    share_rich = float(share_at(policy, 30.0, model, state_idx=mid))
    assert share_poor > share_rich
    assert share_poor > 0.9          # near-corner for the wealth-poor
    assert 0.0 <= share_rich < 0.9


def test_higher_risk_aversion_lowers_share():
    model = build_portfolio_model(labor_states=3, a_count=24)
    shares = {}
    for crra in (2.0, 8.0):
        pol, _, _ = jax.jit(lambda c: solve_portfolio_household(
            R_FREE, WAGE, model, BETA, c))(crra)
        shares[crra] = float(share_at(pol, 20.0, model, state_idx=1))
    assert shares[8.0] < shares[2.0]


def test_no_premium_means_zero_share():
    """Risky mean below the safe rate -> nobody holds the risky asset."""
    model = build_portfolio_model(labor_states=3, a_count=24,
                                  risky_mean=1.00, risky_std=0.2)
    pol, _, _ = jax.jit(lambda: solve_portfolio_household(
        R_FREE, WAGE, model, BETA, 2.0))()
    assert float(jnp.max(pol.share)) < 0.05


def test_stationary_portfolio_distribution_properties(solved):
    model, policy = solved
    dist, it, diff, _ = jax.jit(lambda: stationary_portfolio_wealth(
        policy, R_FREE, WAGE, model, tol=1e-9))()
    assert float(jnp.sum(dist)) == pytest.approx(1.0, abs=1e-8)
    assert bool(jnp.all(dist >= -1e-12))
    # labor marginal must match the ergodic distribution of the chain
    np.testing.assert_allclose(np.asarray(jnp.sum(dist, axis=0)),
                               np.asarray(model.labor_stationary), atol=1e-6)
    # some mass away from the borrowing limit
    assert float(jnp.sum(dist[1:, :])) > 0.5


GE_KW = dict(labor_states=3, a_count=16, share_count=15, risky_count=5,
             dist_count=120)


def test_portfolio_equilibrium_degenerate_matches_single_asset():
    """With near-zero return risk and a positive premium the risky asset
    dominates (share -> 1), and the two-asset general equilibrium must
    reproduce the single-asset bisection equilibrium (VERDICT r1 item 5,
    extending the household-level degeneracy test above)."""
    model = build_portfolio_model(risky_mean=1.0, risky_std=1e-5,
                                  labor_ar=0.3, **GE_KW)
    eq = jax.jit(lambda: solve_portfolio_equilibrium(
        model, BETA, 2.0, cap_share=0.36, depr_fac=0.08, premium=0.03))()
    assert float(eq.risky_share_mean) > 0.99
    from aiyagari_hark_tpu.models.household import build_simple_model
    simple = build_simple_model(labor_states=3, labor_ar=0.3, a_count=16,
                                dist_count=120)
    base = jax.jit(lambda: solve_bisection_equilibrium(
        simple, BETA, 2.0, cap_share=0.36, depr_fac=0.08))()
    assert float(eq.r_star) == pytest.approx(float(base.r_star), abs=7e-4)
    assert float(eq.capital) == pytest.approx(float(base.capital), rel=0.03)


def test_portfolio_equilibrium_with_real_risk():
    """Genuine return risk: interior average share, safe rate at the
    documented spread, market cleared, sane saving rate."""
    model = build_portfolio_model(risky_mean=1.0, risky_std=0.15,
                                  labor_ar=0.3, **GE_KW)
    eq = jax.jit(lambda: solve_portfolio_equilibrium(
        model, BETA, 5.0, cap_share=0.36, depr_fac=0.08, premium=0.04))()
    assert 0.0 < float(eq.r_star) < 1.0 / BETA - 1.0
    assert float(eq.r_free) == pytest.approx(float(eq.r_star) - 0.04,
                                             abs=1e-9)
    assert float(jnp.sum(eq.distribution)) == pytest.approx(1.0, abs=1e-7)
    assert 0.0 < float(eq.risky_share_mean) <= 1.0
    assert abs(float(eq.excess)) < 0.05 * float(eq.capital)
    assert 0.05 < float(eq.saving_rate) < 0.6
    # return risk + risk aversion -> some safe holdings -> total > capital
    assert float(eq.total_assets) >= float(eq.capital)


def test_degenerate_risky_asset_matches_single_asset():
    """A zero-variance risky asset paying above R_f makes the portfolio
    model a single-asset problem at the risky return: share -> 1 and the
    consumption policy matches the plain EGM household at R = risky mean."""
    r_risky = 1.04
    model = build_portfolio_model(labor_states=5, a_count=32,
                                  risky_mean=r_risky, risky_std=1e-4,
                                  labor_ar=0.6)
    pol, _, _ = jax.jit(lambda: solve_portfolio_household(
        R_FREE, WAGE, model, BETA, 2.0))()
    assert float(jnp.min(pol.share)) > 0.95
    simple = build_simple_model(labor_states=5, labor_ar=0.6, a_count=32)
    spol, _, _, _ = jax.jit(lambda: solve_household(
        r_risky, WAGE, simple, BETA, 2.0))()
    m = jnp.linspace(1.0, 20.0, 30)
    c_port = consumption_at(consumption_policy(pol),
                            jnp.tile(m, (5, 1)))
    c_single = consumption_at(spol, jnp.tile(m, (5, 1)))
    np.testing.assert_allclose(np.asarray(c_port), np.asarray(c_single),
                               rtol=2e-3)
