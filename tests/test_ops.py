"""Unit tests for the numerics core (SURVEY.md §4: the kernel-level layer of
the test pyramid the reference lacks)."""

import numpy as np
import jax.numpy as jnp
from scipy import stats

from aiyagari_hark_tpu.ops import (
    aggregate_markov_matrix,
    employment_markov_matrix,
    eval_policy_agents,
    full_idiosyncratic_matrix,
    interp1d,
    interp_on_interp,
    locate_in_grid,
    make_grid_exp_mult,
    marginal_utility,
    inverse_marginal_utility,
    crra_utility,
    masked_ols,
    normalized_labor_states,
    stationary_distribution,
    tauchen_ar1,
    tauchen_labor_process,
)


# ---------------------------------------------------------------- grids

def test_exp_mult_grid_endpoints_and_monotonicity():
    g = make_grid_exp_mult(0.001, 50.0, 32, 2)
    assert g.shape == (32,)
    np.testing.assert_allclose(float(g[0]), 0.001, rtol=1e-9)
    np.testing.assert_allclose(float(g[-1]), 50.0, rtol=1e-9)
    assert np.all(np.diff(np.asarray(g)) > 0)
    # multi-exp nesting clusters points near the lower end
    d = np.diff(np.asarray(g))
    assert d[0] < d[-1]


def test_exp_mult_grid_matches_reference_algorithm():
    # Independent NumPy implementation of the nested-log construction.
    ming, maxg, ng, nest = 0.001, 50.0, 32, 2
    lo, hi = ming, maxg
    for _ in range(nest):
        lo, hi = np.log(lo + 1), np.log(hi + 1)
    grid = np.linspace(lo, hi, ng)
    for _ in range(nest):
        grid = np.exp(grid) - 1
    np.testing.assert_allclose(np.asarray(make_grid_exp_mult(ming, maxg, ng, nest)),
                               grid, rtol=1e-9, atol=1e-12)


# ---------------------------------------------------------------- tauchen

def test_tauchen_rows_sum_to_one_and_match_scipy():
    n, sigma, rho, bound = 7, 0.2 * np.sqrt(1 - 0.3 ** 2), 0.3, 3.0
    grid, P = tauchen_ar1(n, sigma, rho, bound)
    P = np.asarray(P)
    grid = np.asarray(grid)
    np.testing.assert_allclose(P.sum(axis=1), np.ones(n), atol=1e-12)
    assert np.all(P >= 0)
    # grid spans ±bound * stationary sd, symmetric
    sd_stat = sigma / np.sqrt(1 - rho ** 2)
    np.testing.assert_allclose(grid[-1], bound * sd_stat, rtol=1e-12)
    np.testing.assert_allclose(grid, -grid[::-1], atol=1e-12)
    # interior masses are CDF differences over half-bins (scipy oracle)
    d = grid[1] - grid[0]
    j, k = 3, 2
    expect = stats.norm.cdf((grid[k] + d / 2 - rho * grid[j]) / sigma) - \
        stats.norm.cdf((grid[k] - d / 2 - rho * grid[j]) / sigma)
    np.testing.assert_allclose(P[j, k], expect, rtol=1e-10)
    # edge columns absorb the tails
    expect0 = stats.norm.cdf((grid[0] + d / 2 - rho * grid[j]) / sigma)
    np.testing.assert_allclose(P[j, 0], expect0, rtol=1e-10)


def test_tauchen_iid_limit():
    # rho=0: every row identical, stationary == rows
    _, P = tauchen_ar1(5, 0.2, 0.0, 3.0)
    P = np.asarray(P)
    for j in range(1, 5):
        np.testing.assert_allclose(P[j], P[0], atol=1e-12)


def test_labor_process_normalization():
    t = tauchen_labor_process(7, 0.3, 0.2)
    levels = normalized_labor_states(t.grid)
    # reference normalizes by the unweighted mean of exp(grid)
    np.testing.assert_allclose(float(jnp.mean(levels)), 1.0, rtol=1e-12)
    assert np.all(np.asarray(levels) > 0)


def test_stationary_distribution_matches_eig():
    _, P = tauchen_labor_process(7, 0.6, 0.2)
    pi = np.asarray(stationary_distribution(P))
    np.testing.assert_allclose(pi.sum(), 1.0, atol=1e-12)
    np.testing.assert_allclose(pi @ np.asarray(P), pi, atol=1e-10)
    # eigen-oracle
    w, v = np.linalg.eig(np.asarray(P).T)
    idx = np.argmin(np.abs(w - 1.0))
    pi_eig = np.real(v[:, idx])
    pi_eig = pi_eig / pi_eig.sum()
    np.testing.assert_allclose(pi, pi_eig, atol=1e-8)


# ---------------------------------------------------------------- markov composition

def test_aggregate_matrix():
    A = np.asarray(aggregate_markov_matrix(8.0, 8.0))
    np.testing.assert_allclose(A.sum(axis=1), [1, 1], atol=1e-15)
    np.testing.assert_allclose(A[0, 1], 1 / 8)


def test_employment_matrix_degenerate_aiyagari():
    # Urate == 0 in both states (the reference's Aiyagari configuration):
    # employed stay employed within-quadrant.
    E = np.asarray(employment_markov_matrix(8.0, 8.0, 2.5, 1.5, 0.0, 0.0, 0.75, 1.25))
    np.testing.assert_allclose(E.sum(axis=1), np.ones(4), atol=1e-12)
    assert E[1, 0] == 0.0  # employed never fired within Bad
    assert E[3, 2] == 0.0


def test_employment_matrix_ks_urates():
    # True KS calibration: unemployment rates are reproduced in expectation.
    ub, ug = 0.10, 0.04
    E = np.asarray(employment_markov_matrix(8.0, 8.0, 2.5, 1.5, ub, ug, 0.75, 1.25))
    np.testing.assert_allclose(E.sum(axis=1), np.ones(4), atol=1e-12)
    assert np.all(E >= -1e-12)
    # Conditional on staying Bad, stationary urate stays at ub:
    # ub * P(U->U|BB) + (1-ub) * P(E->U|BB) = ub * P(B->B)
    lhs = ub * E[0, 0] + (1 - ub) * E[1, 0]
    np.testing.assert_allclose(lhs, ub * (1 - 1 / 8.0), rtol=1e-12)


def test_full_matrix_is_kron_and_stochastic():
    t = tauchen_labor_process(7, 0.6, 0.2)
    E = employment_markov_matrix(8.0, 8.0, 2.5, 1.5, 0.0, 0.0, 0.75, 1.25)
    F = full_idiosyncratic_matrix(t.transition, E)
    assert F.shape == (28, 28)
    F = np.asarray(F)
    np.testing.assert_allclose(F.sum(axis=1), np.ones(28), atol=1e-10)
    # block (i,j) == tauchen[i,j] * E
    np.testing.assert_allclose(F[4 * 2:4 * 3, 4 * 5:4 * 6],
                               np.asarray(t.transition)[2, 5] * np.asarray(E),
                               rtol=1e-12)


# ---------------------------------------------------------------- utility

def test_crra_roundtrip_and_log_case():
    c = jnp.array([0.5, 1.0, 2.0, 7.3])
    for crra in (1.0, 2.0, 5.0):
        vp = marginal_utility(c, crra)
        np.testing.assert_allclose(np.asarray(inverse_marginal_utility(vp, crra)),
                                   np.asarray(c), rtol=1e-12)
    np.testing.assert_allclose(np.asarray(crra_utility(c, 1.0)),
                               np.log(np.asarray(c)), rtol=1e-12)
    np.testing.assert_allclose(np.asarray(crra_utility(c, 3.0)),
                               np.asarray(c) ** (-2.0) / (-2.0), rtol=1e-12)


def test_crra_utility_traced_crra():
    """crra may be a vmapped sweep axis (VERDICT r1 weak-item 4): the traced
    path must match the static path, including exactly at the log pole."""
    import jax

    c = jnp.array([0.5, 1.0, 2.0, 7.3])
    crras = jnp.array([1.0, 2.0, 3.0, 5.0])
    traced = jax.vmap(lambda g: crra_utility(c, g))(crras)
    for i, g in enumerate([1.0, 2.0, 3.0, 5.0]):
        np.testing.assert_allclose(np.asarray(traced[i]),
                                   np.asarray(crra_utility(c, g)), rtol=1e-12)
    # gradient through the pole-guarded branch stays finite
    grad = jax.grad(lambda g: jnp.sum(crra_utility(c, g)))(jnp.asarray(1.0))
    assert np.isfinite(float(grad))


# ---------------------------------------------------------------- interp

def test_interp1d_matches_numpy_inside():
    xp = jnp.array([0.0, 1.0, 2.0, 4.0, 8.0])
    fp = jnp.array([1.0, 3.0, 2.0, 5.0, 4.0])
    x = jnp.linspace(0.0, 8.0, 57)
    np.testing.assert_allclose(np.asarray(interp1d(x, xp, fp)),
                               np.interp(np.asarray(x), np.asarray(xp), np.asarray(fp)),
                               rtol=1e-12)


def test_interp1d_linear_extrapolation():
    xp = jnp.array([1.0, 2.0, 3.0])
    fp = jnp.array([2.0, 4.0, 5.0])
    # above: last-segment slope 1 -> f(5) = 5 + 2
    np.testing.assert_allclose(float(interp1d(jnp.array(5.0), xp, fp)), 7.0)
    # below: first-segment slope 2 -> f(0) = 2 - 2
    np.testing.assert_allclose(float(interp1d(jnp.array(0.0), xp, fp)), 0.0)


def test_interp_on_interp_bilinear_oracle():
    # With per-column knots all equal, two-level interp == bilinear interp.
    Mgrid = jnp.array([1.0, 2.0, 4.0])
    mk = jnp.tile(jnp.array([0.0, 1.0, 2.0]), (3, 1))
    fk = jnp.array([[0.0, 1.0, 2.0], [1.0, 2.0, 3.0], [3.0, 4.0, 5.0]])
    v = interp_on_interp(jnp.array(0.5), jnp.array(3.0), Mgrid, mk, fk)
    # column values at m=0.5: 0.5, 1.5, 3.5 ; M=3 is halfway 2->4: 2.5
    np.testing.assert_allclose(float(v), 2.5, rtol=1e-12)
    # linear extrapolation in M above the top column: columns at M=2,4 give
    # 1.5, 3.5 -> slope 1 -> v(6) = 5.5
    v = interp_on_interp(jnp.array(0.5), jnp.array(6.0), Mgrid, mk, fk)
    np.testing.assert_allclose(float(v), 5.5, rtol=1e-12)


def test_eval_policy_agents_matches_loop():
    rng = np.random.default_rng(0)
    S, Mc, K, N = 6, 4, 9, 8
    m_knots = np.sort(rng.uniform(0, 10, (S, Mc, K)), axis=-1)
    f_knots = np.cumsum(rng.uniform(0, 1, (S, Mc, K)), axis=-1)
    Mgrid = np.array([1.0, 2.0, 3.0, 5.0])
    m = rng.uniform(0, 12, N)
    sidx = rng.integers(0, S, N)
    M = 2.7
    got = np.asarray(eval_policy_agents(jnp.array(m), jnp.array(sidx), jnp.array(M),
                                        jnp.array(Mgrid), jnp.array(m_knots),
                                        jnp.array(f_knots)))
    for i in range(N):
        want = float(interp_on_interp(jnp.array(m[i]), jnp.array(M), jnp.array(Mgrid),
                                      jnp.array(m_knots[sidx[i]]),
                                      jnp.array(f_knots[sidx[i]])))
        np.testing.assert_allclose(got[i], want, rtol=1e-10)


def test_locate_in_grid_weights():
    grid = jnp.array([0.0, 1.0, 3.0])
    i, w = locate_in_grid(jnp.array([0.5, 2.0, -1.0, 9.0]), grid)
    np.testing.assert_allclose(np.asarray(i), [0, 1, 0, 1])
    np.testing.assert_allclose(np.asarray(w), [0.5, 0.5, 0.0, 1.0])


# ---------------------------------------------------------------- regression

def test_masked_ols_matches_scipy_linregress():
    rng = np.random.default_rng(1)
    x = rng.normal(size=200)
    y = 0.7 * x - 1.3 + rng.normal(scale=0.1, size=200)
    mask = rng.uniform(size=200) < 0.6
    res = masked_ols(jnp.array(x), jnp.array(y), jnp.array(mask))
    sp = stats.linregress(x[mask], y[mask])
    np.testing.assert_allclose(float(res.slope), sp.slope, rtol=1e-10)
    np.testing.assert_allclose(float(res.intercept), sp.intercept, rtol=1e-10)
    np.testing.assert_allclose(float(res.r_squared), sp.rvalue ** 2, rtol=1e-10)
