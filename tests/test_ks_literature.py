"""Anchor the true-KS engine against the published Krusell-Smith (1998)
benchmark (VERDICT r2 next-round item 6).

Krusell & Smith (1998, JPE) solve the heterogeneous-agent RBC model with
employment risk (beta=0.99, delta=0.025, alpha=0.36, z in {0.99, 1.01},
unemployment 10%/4% in bad/good times, 8-quarter mean state durations,
1.5/2.5-quarter mean unemployment spells) and report the approximate
aggregate law of motion — their headline "approximate aggregation"
finding — as, for the good state,

    log K' = 0.095 + 0.962 log K      with R^2 = 0.999998.

The SLOPE and R^2 are units-invariant (rescaling K by c shifts only the
intercept, by (1-b) log c), so they anchor any implementation regardless
of labor normalization; the intercept is checked through the law's
implied steady state against the simulated mean capital instead.

This framework's numbers (deterministic histogram simulator, the
N-generic employment matrices of ``ops/markov.py`` at the reference's KS
identities, labor_states=1 so income risk is employment only):
slope 0.968/0.970 (good/bad), R^2 = 0.9996 in both states, documented
tolerances below.  R^2 sits slightly under KS's Monte-Carlo 0.999998
because the exact histogram resolves distribution-shape movements their
5000-agent panel's sampling noise swamps; > 0.999 still demonstrates
approximate aggregation, which is the anchored claim.
"""

import numpy as np
import pytest

from aiyagari_hark_tpu.models.ks_solver import solve_ks_economy
from fixture_configs import SOLVE_KWARGS, ks98_configs

pytestmark = pytest.mark.slow   # heavyweight equilibrium solves (fast profile: -m 'not slow')


KS_SLOPE_GOOD = 0.962     # Krusell-Smith (1998), good-state law
SLOPE_TOL = 0.02          # discretization/estimator differences
R2_FLOOR = 0.999          # approximate aggregation (KS report 0.999998)


@pytest.fixture(scope="module")
def ks98_solution():
    # Config + committed warm start: tests/fixture_configs.py.
    agent, econ = ks98_configs()
    return solve_ks_economy(agent, econ, **SOLVE_KWARGS["ks98"])


def _k_law(sol, state):
    """Per-state OLS of log K_{t+1} on log K_t, conditioning on the
    aggregate state of the DECISION period (the period whose savings
    produce K_{t+1}) — KS's convention."""
    a_prev = np.asarray(sol.history.A_prev)[1000:]
    z = np.asarray(sol.history.mrkv)[1000:]
    la = np.log(a_prev)
    mask = z[1:] == state
    x, y = la[:-1][mask], la[1:][mask]
    slope, intercept = np.polyfit(x, y, 1)
    resid = y - (intercept + slope * x)
    r2 = 1.0 - (resid ** 2).sum() / ((y - y.mean()) ** 2).sum()
    return intercept, slope, r2


@pytest.mark.slow
def test_ks98_approximate_aggregation_law(ks98_solution):
    sol = ks98_solution
    assert sol.converged
    # no histogram truncation: the law must not be a clip artifact
    assert float(np.asarray(sol.final_panel.dist)[-1].sum()) < 1e-8

    laws = {s: _k_law(sol, s) for s in (0, 1)}
    for s, (intercept, slope, r2) in laws.items():
        # units-invariant anchors: slope and fit quality
        assert abs(slope - KS_SLOPE_GOOD) < SLOPE_TOL, (s, slope)
        assert r2 > R2_FLOOR, (s, r2)
        # intercept via the law's implied steady state, in this model's
        # own units: exp(a / (1-b)) must sit at the simulated mean capital
        k_law_ss = np.exp(intercept / (1.0 - slope))
        k_mean = float(np.asarray(sol.history.A_prev)[1000:].mean())
        assert abs(k_law_ss / k_mean - 1.0) < 0.15, (s, k_law_ss, k_mean)

    # capital is procyclical: the good-state law sits above the bad-state
    # law at the same K (KS report 0.095 good vs lower bad intercepts at
    # near-equal slopes)
    (i0, b0, _), (i1, b1, _) = laws[0], laws[1]
    k_mid = np.log(float(np.asarray(sol.history.A_prev)[1000:].mean()))
    assert i1 + b1 * k_mid > i0 + b0 * k_mid
