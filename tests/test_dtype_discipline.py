"""Dtype-discipline lint (ISSUE 5 satellite): the hot-loop modules must
pin every matmul's accumulation dtype and never hard-code a compute
dtype — enforced in tier-1 next to the atomic-write lint, with one
fixture per violation class so the regexes cannot silently rot."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))

from check_dtype_discipline import (  # noqa: E402
    HOT_MODULES,
    scan,
    scan_source,
    scan_targets,
)


def test_repo_hot_modules_are_clean():
    assert scan() == []


def test_scan_covers_the_ladder_modules():
    """The lint must actually look at the four hot modules — a dropped
    entry would silently stop enforcing the ladder contract there."""
    targets = {os.path.basename(t) for t in scan_targets()}
    assert {"household.py", "equilibrium.py", "markov.py",
            "pallas_kernels.py"} <= targets
    for rel in HOT_MODULES:
        assert os.path.exists(os.path.join(
            os.path.dirname(__file__), "..", rel)), rel


# -- fixture per violation class --------------------------------------------

def _messages(src):
    return [msg for _, _, msg in scan_source(src, "fixture.py")]


def test_flags_matmul_without_preferred_element_type():
    bad = "x = jnp.matmul(a, b, precision=jax.lax.Precision.HIGHEST)\n"
    msgs = _messages(bad)
    assert len(msgs) == 1 and "preferred_element_type" in msgs[0]


def test_flags_multiline_einsum_without_preferred_element_type():
    bad = ("y = jnp.einsum('ij,jk->ik', a,\n"
           "               b,\n"
           "               precision=prec)\n")
    msgs = _messages(bad)
    assert len(msgs) == 1 and "einsum" in msgs[0]


def test_accepts_matmul_with_preferred_element_type():
    good = ("x = jnp.matmul(a, b, precision=prec,\n"
            "               preferred_element_type=a.dtype)\n"
            "y = jnp.dot(a, b, preferred_element_type=jnp.float32)\n")
    assert _messages(good) == []


def test_flags_infix_matmul_operator():
    msgs = _messages("moved = S[i] @ dist[:, i]\n")
    assert len(msgs) == 1 and "'@'" in msgs[0]


def test_decorators_and_docstrings_are_not_infix_matmul():
    good = ('@jax.custom_batching.custom_vmap\n'
            'def f(x):\n'
            '    """prose example: moved = S @ d per state."""\n'
            '    return x\n')
    assert _messages(good) == []


def test_flags_hardcoded_float64_literal():
    msgs = _messages("z = jnp.zeros((3,), dtype=jnp.float64)\n")
    assert len(msgs) == 1 and "float64" in msgs[0]


def test_waiver_comment_suppresses_each_class():
    waived = (
        "x = jnp.matmul(a, b)  # dtype-ok: fixture\n"
        "y = a @ b  # dtype-ok: fixture\n"
        "f64 = dtype == jnp.float64  # dtype-ok: dispatch\n"
        "b16 = jnp.bfloat16  # dtype-ok: rung seam fixture\n"
        "z = lax.dot_general(a, b, dims)  # dtype-ok: fixture\n")
    assert _messages(waived) == []


# -- ISSUE 13 satellite: kernel-body accumulation + bf16 rung rules ---------

def test_flags_bare_dot_general():
    """``lax.dot_general`` is the hand-lowered matmul spelling (the
    tiled contraction, kernel bodies) — bare accumulation there is the
    same violation as a bare ``jnp.matmul``."""
    for spelling in ("jax.lax.dot_general", "lax.dot_general"):
        bad = (f"out = {spelling}(a, b,\n"
               "    (((1,), (0,)), ((), ())))\n")
        msgs = _messages(bad)
        assert len(msgs) == 1 and "dot_general" in msgs[0], spelling


def test_accepts_dot_general_with_preferred_element_type():
    good = ("out = jax.lax.dot_general(a, b, dims,\n"
            "    preferred_element_type=a.dtype)\n")
    assert _messages(good) == []


def test_flags_hardcoded_bfloat16_literal():
    """A bare bf16 literal outside the waived rung seams would smuggle
    the narrow dtype past the KernelPolicy ladder contract (no coarse
    floor, no escalation, no TPU gate — DESIGN §4c)."""
    msgs = _messages("x = arr.astype(jnp.bfloat16)\n")
    assert len(msgs) == 1 and "bfloat16" in msgs[0]


def test_bf16_rung_definition_sites_are_waived_not_unchecked():
    """The real rung seams in ``models.household`` carry ``# dtype-ok``
    waivers — the module must scan clean WITH the bf16 rule active, and
    must actually contain waived bf16 literals (if the rung moves files,
    this pins that the waiver moved with it)."""
    path = os.path.join(os.path.dirname(__file__), "..",
                        "aiyagari_hark_tpu", "models", "household.py")
    with open(path) as fh:
        src = fh.read()
    assert "jnp.bfloat16" in src
    assert scan_source(src, "household.py") == []
