"""Dtype-discipline lint (ISSUE 5 satellite): the hot-loop modules must
pin every matmul's accumulation dtype and never hard-code a compute
dtype — enforced in tier-1 next to the atomic-write lint, with one
fixture per violation class so the regexes cannot silently rot."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))

from check_dtype_discipline import (  # noqa: E402
    HOT_MODULES,
    scan,
    scan_source,
    scan_targets,
)


def test_repo_hot_modules_are_clean():
    assert scan() == []


def test_scan_covers_the_ladder_modules():
    """The lint must actually look at the four hot modules — a dropped
    entry would silently stop enforcing the ladder contract there."""
    targets = {os.path.basename(t) for t in scan_targets()}
    assert {"household.py", "equilibrium.py", "markov.py",
            "pallas_kernels.py"} <= targets
    for rel in HOT_MODULES:
        assert os.path.exists(os.path.join(
            os.path.dirname(__file__), "..", rel)), rel


# -- fixture per violation class --------------------------------------------

def _messages(src):
    return [msg for _, _, msg in scan_source(src, "fixture.py")]


def test_flags_matmul_without_preferred_element_type():
    bad = "x = jnp.matmul(a, b, precision=jax.lax.Precision.HIGHEST)\n"
    msgs = _messages(bad)
    assert len(msgs) == 1 and "preferred_element_type" in msgs[0]


def test_flags_multiline_einsum_without_preferred_element_type():
    bad = ("y = jnp.einsum('ij,jk->ik', a,\n"
           "               b,\n"
           "               precision=prec)\n")
    msgs = _messages(bad)
    assert len(msgs) == 1 and "einsum" in msgs[0]


def test_accepts_matmul_with_preferred_element_type():
    good = ("x = jnp.matmul(a, b, precision=prec,\n"
            "               preferred_element_type=a.dtype)\n"
            "y = jnp.dot(a, b, preferred_element_type=jnp.float32)\n")
    assert _messages(good) == []


def test_flags_infix_matmul_operator():
    msgs = _messages("moved = S[i] @ dist[:, i]\n")
    assert len(msgs) == 1 and "'@'" in msgs[0]


def test_decorators_and_docstrings_are_not_infix_matmul():
    good = ('@jax.custom_batching.custom_vmap\n'
            'def f(x):\n'
            '    """prose example: moved = S @ d per state."""\n'
            '    return x\n')
    assert _messages(good) == []


def test_flags_hardcoded_float64_literal():
    msgs = _messages("z = jnp.zeros((3,), dtype=jnp.float64)\n")
    assert len(msgs) == 1 and "float64" in msgs[0]


def test_waiver_comment_suppresses_each_class():
    waived = (
        "x = jnp.matmul(a, b)  # dtype-ok: fixture\n"
        "y = a @ b  # dtype-ok: fixture\n"
        "f64 = dtype == jnp.float64  # dtype-ok: dispatch\n")
    assert _messages(waived) == []
