"""Configs of the warm-started heavyweight fixtures, shared between the
test modules and ``scripts/refresh_warm_starts.py``.

The suite's dominant cost is Krusell-Smith outer loops re-converging the
aggregate saving rule from the cold reference guesses (intercept 0,
slope 1) — 8-10 outer iterations of solve+simulate+regress per fixture
(VERDICT r3 weak-item 5).  Each fixture here instead seeds
``intercept_prev``/``slope_prev`` from the committed registry
``tests/data/warm_starts.json``; the solver then re-certifies convergence
(the distance/tolerance gate is unchanged), normally in 1-2 iterations.
Assertions are untouched — a warm start is an initial guess, never a
result.  ``AIYAGARI_COLD_START=1`` ignores the registry, and a registry
miss silently runs cold, so correctness never depends on this file.

Keeping the configs HERE (imported by both sides) means the registry can
never drift from what the tests actually solve: the refresh script solves
exactly these configs cold and rewrites the registry.  Run

    python scripts/refresh_warm_starts.py

after any change to solver semantics or to these configs.
"""

import json
import os

from aiyagari_hark_tpu.utils.config import (
    AgentConfig,
    EconomyConfig,
    notebook_run_configs,
)

DATA = os.path.join(os.path.dirname(__file__), "data")
REGISTRY = os.path.join(DATA, "warm_starts.json")

# test_cross_engine.py constants (the fixture must keep using these)
CROSS_ENGINE_SPELL = 8.0
CROSS_ENGINE_TFP_GAP = 0.02

# The solve kwargs each warm-started fixture passes to solve_ks_economy
# (or, for facade cases, to the facade drive).  Owned HERE, next to the
# configs, and imported by BOTH the tests and the refresh script — solve
# kwargs change the compiled program and the fixed point just as much as
# the configs do, so hand-duplicating them across the two sides would
# reintroduce exactly the registry drift this module exists to prevent
# (round-4 review).
SOLVE_KWARGS = {
    "cross_engine": dict(sim_method="panel"),
    "ks98": dict(ks_employment=True, sim_method="distribution",
                 dist_count=500, seed=0),
    "diag_parity": dict(seed=0),
    "diag_pinned": dict(seed=0, sim_method="distribution", dist_count=300),
    "diag_true_ks": dict(seed=0, ks_employment=True,
                         sim_method="distribution", dist_count=150),
    "dist_method": dict(seed=0, sim_method="distribution", dist_count=300),
    "facade_dist": dict(AgentCount=100, aCount=16, tolerance=1e-3,
                        sim_method="distribution", dist_count=200),
}


CHECKPOINTS = os.path.join(DATA, "checkpoints")


def committed_checkpoint(key: str, tmp_dir, tag: str = "a"):
    """Path to a TMP COPY of the committed near-converged checkpoint for
    ``key`` (plus its distribution sidecar), or ``None`` when absent or
    ``AIYAGARI_COLD_START=1``.

    The committed file is the cold trajectory frozen TWO iterations
    before convergence (``scripts/refresh_warm_starts.py``), so a resume
    runs the final iterations — and the convergence certification —
    for real, rather than short-circuiting through the solver's
    idempotent converged-reload path.  A copy, because resume rewrites
    the file every iteration; the committed artifact must stay pristine.
    If the committed checkpoint has gone stale (config drift), the
    solver raises ``ValueError`` on the fingerprint — callers fall back
    to a cold solve."""
    if os.environ.get("AIYAGARI_COLD_START"):
        return None
    src = os.path.join(CHECKPOINTS, key + ".npz")
    if not os.path.exists(src):
        return None
    import shutil
    dst = os.path.join(str(tmp_dir), f"{key}_{tag}.npz")
    shutil.copy(src, dst)
    if os.path.exists(src + ".dist.npz"):
        shutil.copy(src + ".dist.npz", dst + ".dist.npz")
    return dst


def solve_with_committed_checkpoint(key: str, tmp_dir, solve_fn,
                                    tag: str = "a"):
    """Run ``solve_fn(checkpoint_path)`` resumed from the committed
    near-converged checkpoint for ``key``, degrading to a cold
    ``solve_fn(None)`` when the checkpoint is absent, bypassed
    (``AIYAGARI_COLD_START``), or stale (the solver's typed
    ``CheckpointMismatchError`` — config drift; rerun
    ``scripts/refresh_warm_starts.py --only <key>``).  Any other
    exception propagates: it is a resume-path regression, not
    staleness.  One helper so every CHECKPOINT_CASES test shares one
    staleness semantics (round-4 review)."""
    from aiyagari_hark_tpu.utils.checkpoint import CheckpointMismatchError

    ck = committed_checkpoint(key, tmp_dir, tag)
    if ck is not None:
        try:
            return solve_fn(ck)
        except CheckpointMismatchError:
            import warnings
            warnings.warn(
                f"committed {key} checkpoint is stale (config drift?) — "
                f"cold-solving; rerun scripts/refresh_warm_starts.py "
                f"--only {key}", stacklevel=2)
    return solve_fn(None)


def warm_start(key: str) -> dict:
    """``{"intercept_prev": (...), "slope_prev": (...)}`` for the key, or
    ``{}`` when the registry lacks it / ``AIYAGARI_COLD_START=1``."""
    if os.environ.get("AIYAGARI_COLD_START"):
        return {}
    try:
        with open(REGISTRY) as f:
            entry = json.load(f).get(key)
    except (OSError, ValueError):
        return {}
    if not entry:
        return {}
    return {"intercept_prev": tuple(entry["intercept"]),
            "slope_prev": tuple(entry["slope"])}


def cross_engine_configs():
    """test_cross_engine.ks_moments: panel-mode true-KS solve."""
    agent = AgentConfig(labor_states=3, a_count=24, agent_count=2000,
                        mgrid_base=(0.7, 0.85, 0.95, 1.0, 1.05, 1.15, 1.3))
    econ = EconomyConfig(labor_states=3,
                         prod_b=1.0 - CROSS_ENGINE_TFP_GAP / 2,
                         prod_g=1.0 + CROSS_ENGINE_TFP_GAP / 2,
                         urate_b=0.0, urate_g=0.0,
                         dur_mean_b=CROSS_ENGINE_SPELL,
                         dur_mean_g=CROSS_ENGINE_SPELL,
                         act_T=7000, t_discard=1000, verbose=False)
    return agent, econ.replace(**warm_start("cross_engine"))


def ks98_configs():
    """test_ks_literature.ks98_solution: KS-1998 calibration, histogram."""
    agent = AgentConfig(labor_states=1, disc_fac=0.99, crra=1.0,
                        a_max=300.0, a_count=48)
    econ = EconomyConfig(labor_states=1, disc_fac=0.99, crra=1.0,
                         depr_fac=0.025, prod_b=0.99, prod_g=1.01,
                         urate_b=0.10, urate_g=0.04,
                         act_T=11000, t_discard=1000,
                         tolerance=1e-3, max_loops=60, verbose=False)
    return agent, econ.replace(**warm_start("ks98"))


def diag_parity_configs():
    """test_diagnostics.parity_solution: panel-mode notebook parity."""
    agent, econ = notebook_run_configs()
    econ = econ.replace(act_T=1500, t_discard=300, verbose=False)
    return agent, econ.replace(**warm_start("diag_parity"))


def diag_pinned_configs():
    """test_diagnostics pinned-rule forecast: distribution mode."""
    agent, econ = notebook_run_configs()
    econ = econ.replace(act_T=1200, t_discard=240, verbose=False,
                        tolerance=1e-3)
    return agent, econ.replace(**warm_start("diag_pinned"))


def diag_true_ks_configs():
    """test_diagnostics stochastic-forecast economy."""
    econ = EconomyConfig(labor_states=3, act_T=800, t_discard=160,
                         verbose=False, tolerance=0.02,
                         prod_b=0.99, prod_g=1.01,
                         urate_b=0.10, urate_g=0.04)
    agent = AgentConfig(labor_states=3, agent_count=200, a_count=16)
    return agent, econ.replace(**warm_start("diag_true_ks"))


def dist_method_configs():
    """test_distribution_sim.test_solve_ks_economy_distribution_method."""
    agent, econ = notebook_run_configs()
    econ = econ.replace(act_T=1500, t_discard=300, verbose=False,
                        max_loops=15, tolerance=1e-3)
    return agent, econ.replace(**warm_start("dist_method"))


# Facade fixture builds reference-spelling dicts; the warm start merges in
# as list-valued dict entries (the facade accepts the reference spelling).
# test_facade's ``solved`` fixture deliberately stays COLD: its
# ``test_repeat_solve_warm_starts`` asserts the cold solve takes > 1 outer
# iteration (the reference's in-place continuation quirk, SURVEY §3.6-7).

def facade_distribution_updates():
    """test_facade.test_solve_distribution_method_through_facade."""
    upd = dict(LaborStatesNo=5, act_T=800, T_discard=160, verbose=False,
               LaborAR=0.3, CRRA=1.0)
    ws = warm_start("facade_dist")
    if ws:
        upd["intercept_prev"] = list(ws["intercept_prev"])
        upd["slope_prev"] = list(ws["slope_prev"])
    return upd
