"""Grid compaction end to end (ISSUE 12, DESIGN §5b).

The contracts under test:

* ``grid="reference"`` (the default) is BIT-identical to an unspecified
  grid — the explicit spelling shares the executable cache entry, the
  fingerprints, and the bits (the committed packing/resume/precision
  goldens pin the default path's values untouched; this file pins the
  spelling equivalence).
* the ANALYTIC TAIL: on every golden (σ, ρ) cell, the compact policy's
  consumption agrees with the dense reference policy's across the tail
  region (above the knee, where the compact grid has no points and
  evaluation rides the asymptotic linear form) to the asymptotic
  linearity tolerance — and the tail slope is the model's MPC limit,
  inside the committed ``afunc_slope`` artifact's ordering band.
* the coarse-to-fine ladder escalates deterministically: a NaN injected
  into the COARSE phase restarts the polish cold on the compact grid
  (``GRID_ESCALATED`` — same escalation slot as the precision ladder)
  with a healthy final status; at the sweep level quarantine rungs
  force ``grid="reference"`` (the dense-grid fallback).
* compacted sweeps key their own fingerprints: a compact solve can
  never collide with a reference solve in any sidecar/ledger/store.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_hark_tpu.models.equilibrium import solve_calibration_lean
from aiyagari_hark_tpu.models.household import (
    build_simple_model,
    consumption_at,
    initial_policy,
    solve_household,
)
from aiyagari_hark_tpu.ops.utility import asymptotic_mpc
from aiyagari_hark_tpu.parallel.sweep import run_table2_sweep
from aiyagari_hark_tpu.solver_health import CONVERGED, GRID_ESCALATED
from aiyagari_hark_tpu.utils.config import SweepConfig

# The tier-1 workload: the full 12-cell Table II lattice at smoke grid
# sizes (the compaction claims are about tail structure and ladder
# phases; full-size drift/certification is the bench's grid_* phase).
KW = dict(a_count=10, dist_count=32, labor_states=3, r_tol=1e-5,
          max_bisect=24)
GOLDEN_CELLS = [(s, r) for s in (1.0, 3.0, 5.0)
                for r in (0.0, 0.3, 0.6, 0.9)]


@pytest.fixture(scope="module")
def sweeps():
    bare = run_table2_sweep(SweepConfig(), **KW)
    explicit = run_table2_sweep(SweepConfig(), grid="reference", **KW)
    compact = run_table2_sweep(SweepConfig(), grid="compact", **KW)
    return bare, explicit, compact


def test_reference_default_and_explicit_are_bit_identical(sweeps):
    bare, explicit, _ = sweeps
    for f in ("r_star_pct", "capital", "egm_iters", "dist_iters",
              "status", "descent_steps", "polish_steps"):
        a, b = getattr(bare, f), getattr(explicit, f)
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes(), f


def test_sweep_config_grid_field_is_a_kwarg_default(sweeps):
    _, _, compact = sweeps
    via_config = run_table2_sweep(SweepConfig(grid="compact"), **KW)
    assert np.asarray(via_config.r_star_pct).tobytes() \
        == np.asarray(compact.r_star_pct).tobytes()


def test_compact_sweep_converges_near_reference(sweeps):
    bare, _, compact = sweeps
    assert not compact.failed_cells().size
    # tiny-grid discretizations legally differ more than the golden
    # config's (the 0.1bp acceptance lives in bench --compaction-smoke,
    # at the committed-golden sizes); this pins the sane-agreement band
    drift_bp = np.max(np.abs(compact.r_star_pct - bare.r_star_pct)) * 100
    assert drift_bp < 50.0


def test_analytic_tail_matches_dense_reference_on_all_golden_cells():
    """Policy values in the TAIL region: compact-grid + analytic tail vs
    the dense reference policy, across all 12 golden cells.  One jitted
    program, executed per cell (sigma/rho are traced scalars)."""
    import jax as _jax

    mod_probe = build_simple_model(labor_states=3, a_count=10,
                                   dist_count=32, grid="compact")
    knee = float(np.asarray(mod_probe.a_grid)[-1])
    top = float(np.asarray(mod_probe.dist_grid)[-1])
    q = jnp.linspace(knee, 1.2 * top, 64)
    qs = jnp.broadcast_to(q, (3, 64))

    @_jax.jit
    def tail_pair(sig, rho):
        mod_r = build_simple_model(labor_states=3, labor_ar=rho,
                                   a_count=10, dist_count=32)
        mod_c = build_simple_model(labor_states=3, labor_ar=rho,
                                   a_count=10, dist_count=32,
                                   grid="compact")
        R, W = 1.03, 1.2
        pol_r, _, _, st_r = solve_household(R, W, mod_r, 0.96, sig)
        pol_c, _, _, st_c = solve_household(R, W, mod_c, 0.96, sig,
                                            grid="compact")
        return (consumption_at(pol_r, qs), consumption_at(pol_c, qs),
                st_r, st_c)

    for sig, rho in GOLDEN_CELLS:
        c_r, c_c, st_r, st_c = tail_pair(sig, rho)
        assert int(st_r) == CONVERGED and int(st_c) == CONVERGED
        c_r, c_c = np.asarray(c_r), np.asarray(c_c)
        rel = np.max(np.abs(c_c - c_r) / np.maximum(c_r, 1e-12))
        assert rel < 0.05, (sig, rho, rel)


def test_tail_slope_is_the_mpc_limit_inside_the_artifact_band():
    """The appended tail segment's slope equals the analytic asymptotic
    MPC, and the implied savings slope sits in the committed
    ``afunc_slope`` artifact's ordering band (0, 1.2)."""
    mod = build_simple_model(labor_states=3, a_count=10, dist_count=32,
                             grid="compact")
    R, beta, sig = 1.03, 0.96, 3.0
    pol, _, _, st = solve_household(R, 1.2, mod, beta, sig,
                                    grid="compact")
    assert int(st) == CONVERGED
    kappa = float(asymptotic_mpc(R, beta, sig))
    assert 0.0 < kappa < 1.0
    m = np.asarray(pol.m_knots)
    c = np.asarray(pol.c_knots)
    tail_slope = (c[:, -1] - c[:, -2]) / (m[:, -1] - m[:, -2])
    np.testing.assert_allclose(tail_slope, kappa, rtol=1e-10)
    # the analytic savings slope d a'/d a = (beta R)^(1/sigma) — the
    # ordering the committed afunc_slope artifact pins for the
    # aggregate law (tests/test_artifacts.py band)
    savings_slope = R * (1.0 - kappa)
    assert 0.0 < savings_slope < 1.2


def test_compact_policy_shapes_carry_the_tail_knots():
    mod = build_simple_model(labor_states=3, a_count=10, dist_count=32,
                             grid="compact")
    a_pts = int(np.asarray(mod.a_grid).shape[0])
    p0 = initial_policy(mod, analytic_tail=True)
    assert p0.m_knots.shape[0] == 3
    assert p0.m_knots.shape[1] > a_pts + 1   # constraint + endo + tail
    assert bool(jnp.all(jnp.diff(p0.m_knots, axis=1) > 0))


def test_grid_ladder_coarse_fault_escalates_inside(sweeps=None):
    """A NaN injected into the COARSE phase escalates in-program
    (GRID_ESCALATED): healthy final status, escalation counted, values
    reference-grade."""
    assert isinstance(GRID_ESCALATED, str)   # note marker, like
    #                                          PRECISION_ESCALATED
    clean = solve_calibration_lean(3.0, 0.6, grid="compact", **KW)
    faulted = solve_calibration_lean(3.0, 0.6, grid="compact",
                                     descent_fault_iter=1, **KW)
    assert not bool(np.isnan(float(faulted.r_star)))
    assert int(faulted.status) == CONVERGED
    assert int(faulted.escalations) > 0
    # the escalated solve lands on the same root (cold compact restart)
    assert abs(float(faulted.r_star) - float(clean.r_star)) < 1e-4


def test_quarantine_rungs_force_reference_grid():
    from aiyagari_hark_tpu.parallel.sweep import _retry_ladder

    rungs = _retry_ladder({"grid": "compact"})
    assert all(r.get("grid") == "reference" for r in rungs)
    rungs_ref = _retry_ladder({})
    assert all("grid" not in r for r in rungs_ref)
    # the scenario bundles carry the same rule
    from aiyagari_hark_tpu.scenarios.epstein_zin import (
        _retry_rungs as ez_rungs,
    )
    from aiyagari_hark_tpu.scenarios.huggett import (
        _retry_rungs as hug_rungs,
    )

    assert all(r.get("grid") == "reference"
               for r in hug_rungs({"grid": "compact"}))
    assert all(r.get("grid") == "reference"
               for r in ez_rungs({"grid": "adaptive"}))


def test_compact_sweep_quarantine_recovers_on_the_dense_grid(sweeps):
    """An injected persistent fault routes a compact cell through the
    quarantine ladder, whose rungs re-solve at grid='reference'; the
    other cells stay bit-identical to the clean compact sweep."""
    ref, _, clean = sweeps
    res = run_table2_sweep(SweepConfig(), grid="compact",
                           inject_fault={"cell": 5, "at_iter": 0,
                                         "mode": "nan"}, **KW)
    assert int(res.retries[5]) >= 1
    assert int(res.status[5]) == CONVERGED
    mask = np.ones(len(res.r_star_pct), dtype=bool)
    mask[5] = False
    assert np.asarray(res.r_star_pct)[mask].tobytes() \
        == np.asarray(clean.r_star_pct)[mask].tobytes()
    # the rung re-solved on the DENSE grid, so the recovered root is the
    # reference discretization's (to the bracket width — the rung's
    # alternate dist method may land the last bisection trips
    # differently), not the compact one's
    assert float(res.r_star_pct[5]) == pytest.approx(
        float(ref.r_star_pct[5]), abs=2 * KW["r_tol"] * 100)


def test_huggett_and_ez_cells_ride_compact_grids():
    from aiyagari_hark_tpu.scenarios.epstein_zin import solve_ez_cell
    from aiyagari_hark_tpu.scenarios.huggett import solve_huggett_cell

    tiny = dict(labor_states=3, a_count=10, dist_count=32)
    hug_r = solve_huggett_cell(2.0, 0.3, r_tol=1e-5, **tiny)
    hug_c = solve_huggett_cell(2.0, 0.3, r_tol=1e-5, grid="compact",
                               **tiny)
    assert int(hug_c.status) == CONVERGED
    assert abs(float(hug_c.r_star) - float(hug_r.r_star)) < 5e-3
    ez_r = solve_ez_cell(3.0, 0.3, r_tol=1e-4, max_bisect=24, **tiny)
    ez_c = solve_ez_cell(3.0, 0.3, r_tol=1e-4, max_bisect=24,
                         grid="compact", **tiny)
    assert int(ez_c.status) == CONVERGED
    assert abs(float(ez_c.r_star) - float(ez_r.r_star)) < 5e-3


def test_compact_certifies_under_grid_aware_thresholds():
    from aiyagari_hark_tpu.verify import CertThresholds, certify_equilibrium

    lean = solve_calibration_lean(3.0, 0.6, grid="compact", **KW)
    cert = certify_equilibrium(lean, crra=3.0, labor_ar=0.6,
                               grid="compact", **KW)
    assert cert.level <= 1   # CERTIFIED or MARGINAL at tiny grids
    thr_ref = CertThresholds.for_solver()
    thr_cmp = CertThresholds.for_solver(grid="compact")
    assert thr_cmp.euler > thr_ref.euler
    assert thr_cmp.market_clearing > thr_ref.market_clearing


def test_grid_spec_resolution_on_serve_queries():
    """grid rides serve-query kwargs through the same normalization —
    distinct fingerprints, validated at build time."""
    from aiyagari_hark_tpu.serve import make_query

    q_ref = make_query(3.0, 0.6, **KW)
    q_cmp = make_query(3.0, 0.6, grid="compact", **KW)
    assert q_ref.key() != q_cmp.key()
    assert q_ref.group() != q_cmp.group()
    q_expl = make_query(3.0, 0.6, grid="reference", **KW)
    assert q_expl.key() == q_ref.key()
    with pytest.raises(ValueError, match="grid policy"):
        make_query(3.0, 0.6, grid="bogus", **KW)
