"""Mixed-precision fixed-point ladder (ISSUE 5, DESIGN §5).

The contract under test:

* ``precision="reference"`` (the default) is BIT-identical to an
  unspecified precision — the explicit spelling shares the executable
  cache entry, the fingerprints, and the bits (the pre-PR goldens in
  ``test_table2``/``test_wealth_goldens`` pin the default path's values
  untouched).
* ``precision="mixed"`` keeps the acceptance numbers on the 12-cell CPU
  sweep: r* within 0.25 bp of the reference policy, polish_frac <= 0.25,
  and fewer reference-precision-equivalent steps
  (``polish + DESCENT_STEP_COST * descent``) than the reference sweep's
  total.
* parity holds beyond the Aiyagari sweep: one Huggett bond-economy solve
  and one 4N-state KS household solve agree across policies.
* a NaN injected into the DESCENT phase escalates to a pure-reference
  solve inside the ladder (``PRECISION_ESCALATED``) — the caller sees a
  healthy status and reference-grade values, quarantine sees nothing.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_hark_tpu.models.equilibrium import solve_calibration_lean
from aiyagari_hark_tpu.models.household import (
    PrecisionPhases,
    build_simple_model,
    solve_household,
    stationary_wealth,
)
from aiyagari_hark_tpu.parallel.sweep import run_table2_sweep
from aiyagari_hark_tpu.solver_health import (
    CONVERGED,
    PRECISION_ESCALATED,
    STALLED,
    is_failure,
)
from aiyagari_hark_tpu.utils.config import (
    DESCENT_STEP_COST,
    PACKED_ROW_FIELDS,
    SweepConfig,
    resolve_precision,
)

# The tier-1 sweep workload: the full 12-cell Table II lattice at smoke
# grid sizes (the ladder claims are about phases and tolerances, not
# grid resolution; full-size parity is the bench's precision_* phase).
KW = dict(a_count=10, dist_count=32, labor_states=3, r_tol=1e-5,
          max_bisect=24)
TINY = dict(labor_states=3, a_count=10, dist_count=32)


def test_resolve_precision_policies():
    assert resolve_precision("reference").two_phase is False
    assert resolve_precision("mixed").polish is True
    assert resolve_precision("fast").polish is False
    assert 0.0 < resolve_precision("mixed").descent_step_cost <= 1.0
    with pytest.raises(ValueError):
        resolve_precision("bf16")


def test_packed_row_layout_pin():
    """The device-row layout shared by sweep/ledger/store — widening it
    again must be a deliberate, fingerprint-bumping change."""
    assert PACKED_ROW_FIELDS == (
        "r_star", "capital", "labor", "bisect_iters", "egm_iters",
        "dist_iters", "status", "descent_steps", "polish_steps",
        "precision_escalations")


# ---------------------------------------------------------------------------
# The 12-cell acceptance block.
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def sweeps():
    ref = run_table2_sweep(SweepConfig(), **KW)
    mixed = run_table2_sweep(SweepConfig(), precision="mixed", **KW)
    return ref, mixed


def test_reference_default_and_explicit_are_bit_identical(sweeps):
    ref, _ = sweeps
    expl = run_table2_sweep(SweepConfig(), precision="reference", **KW)
    for field in ("r_star_pct", "saving_rate_pct", "capital", "excess",
                  "bisect_iters", "egm_iters", "dist_iters", "status",
                  "descent_steps", "polish_steps",
                  "precision_escalations"):
        assert np.array_equal(getattr(ref, field), getattr(expl, field)), field
    # reference phase accounting: zero descent, every step is polish
    assert (ref.descent_steps == 0).all()
    assert np.array_equal(ref.polish_steps, ref.total_work())
    assert ref.polish_frac() == 1.0


def test_mixed_12_cell_acceptance(sweeps):
    ref, mixed = sweeps
    assert not is_failure(mixed.status).any()
    # escalation is allowed (a slow-mixing cell's descent can stall at the
    # cheap-dtype floor and fall back — that is the contract working), but
    # it must stay the exception and never surface as a failure
    assert int(mixed.precision_escalations.sum()) <= 2
    # r* agreement: <= 0.25 bp per cell (r_star_pct is in percent; 1 bp =
    # 0.01 percentage points)
    max_bp = float(np.abs(mixed.r_star_pct - ref.r_star_pct).max()) * 100.0
    assert max_bp <= 0.25, max_bp
    # polish fraction: at most a quarter of the steps still pay reference
    # precision
    assert mixed.polish_frac() <= 0.25, mixed.polish_frac()
    # reference-equivalent work strictly below the reference sweep's
    ref_equiv = (float(mixed.polish_steps.sum())
                 + DESCENT_STEP_COST * float(mixed.descent_steps.sum()))
    assert ref_equiv < float(ref.total_work().sum())
    # phase counters are an exact partition of the total work
    assert np.array_equal(mixed.descent_steps + mixed.polish_steps,
                          mixed.total_work())


def test_fast_policy_is_cheap_and_approximate(sweeps):
    ref, _ = sweeps
    fast = run_table2_sweep(SweepConfig(), precision="fast", **KW)
    assert (fast.polish_steps == 0).all()
    assert float(fast.total_work().sum()) < 0.8 * float(
        ref.total_work().sum())
    # descent-only answers: within the relaxed (cheap-floor) tolerance —
    # a few bp, not reference-grade, but nowhere near garbage
    max_bp = float(np.abs(fast.r_star_pct - ref.r_star_pct).max()) * 100.0
    assert max_bp < 5.0, max_bp


# ---------------------------------------------------------------------------
# Parity beyond the sweep: Huggett and Krusell-Smith.
# ---------------------------------------------------------------------------

def test_huggett_mixed_matches_reference():
    from aiyagari_hark_tpu.models.huggett import solve_huggett_equilibrium

    model = build_simple_model(borrow_limit=-1.0, **TINY)
    ref = solve_huggett_equilibrium(model, 0.96, 2.0, r_tol=1e-6)
    mix = solve_huggett_equilibrium(model, 0.96, 2.0, r_tol=1e-6,
                                    precision="mixed")
    assert bool(ref.bracketed) and bool(mix.bracketed)
    assert abs(float(ref.r_star) - float(mix.r_star)) * 1e4 <= 0.25  # bp


def test_ks_household_mixed_matches_reference():
    from aiyagari_hark_tpu.models.ks_model import (
        AFuncParams,
        build_ks_calibration,
        solve_ks_household,
    )
    from aiyagari_hark_tpu.utils.config import AgentConfig, EconomyConfig

    agent = AgentConfig(labor_states=3, a_count=12,
                        mgrid_base=(0.7, 0.9, 1.0, 1.1, 1.3))
    econ = EconomyConfig(labor_states=3)
    cal = build_ks_calibration(agent, econ)
    afunc = AFuncParams(intercept=jnp.zeros(2), slope=jnp.ones(2))
    pol_ref, _, _, st_ref = solve_ks_household(afunc, cal, tol=1e-6)
    pol_mix, _, _, st_mix = solve_ks_household(afunc, cal, tol=1e-6,
                                               precision="mixed")
    assert int(st_ref) == CONVERGED and int(st_mix) == CONVERGED
    # both converged to the same fixed point to ladder-noise: the polish
    # certifies the same sup-norm tolerance the reference run does
    diff = float(jnp.max(jnp.abs(pol_ref.c_knots - pol_mix.c_knots)))
    assert diff <= 50 * 1e-6, diff


# ---------------------------------------------------------------------------
# Escalation: descent-phase faults are absorbed inside the ladder.
# ---------------------------------------------------------------------------

def test_policy_descent_nan_escalates_to_reference(sweeps=None):
    model = build_simple_model(**TINY)
    ref_pol, _, _, ref_status = solve_household(1.02, 1.0, model, 0.96, 2.0,
                                                tol=1e-6)
    pol, _, _, status, phases = solve_household(
        1.02, 1.0, model, 0.96, 2.0, tol=1e-6, precision="mixed",
        return_phases=True, descent_fault_iter=0)
    assert isinstance(phases, PrecisionPhases)
    assert bool(phases.escalated), PRECISION_ESCALATED
    # the fallback IS a reference-grade solve: healthy status, and the
    # answer matches the reference fixed point to its tolerance
    assert int(status) == CONVERGED == int(ref_status)
    assert not is_failure(int(status))
    # both certify the same sup-norm update tolerance; the fixed-point
    # error bound is tol/(1-beta) ~ 2.5e-5, and the escalated polish runs
    # a tighter Anderson cadence than the plain reference loop
    assert float(jnp.max(jnp.abs(pol.c_knots - ref_pol.c_knots))) <= 5e-5


def test_distribution_descent_stall_escalates_to_reference():
    model = build_simple_model(**TINY)
    pol, _, _, _ = solve_household(1.02, 1.0, model, 0.96, 2.0, tol=1e-6)
    d_ref, _, _, st_ref = stationary_wealth(pol, 1.02, 1.0, model, tol=1e-11)
    # a stall pinned into the DESCENT phase (alternating offset above the
    # coarse tolerance) must trip the stall window there and fall back
    d_mix, _, _, st_mix, phases = stationary_wealth(
        pol, 1.02, 1.0, model, tol=1e-11, precision="mixed",
        return_phases=True, descent_fault_iter=0,
        descent_fault_mode="stall")
    assert bool(phases.escalated)
    assert int(st_mix) == int(st_ref) == CONVERGED
    assert float(jnp.max(jnp.abs(d_ref - d_mix))) <= 1e-9
    # uninjected control: no escalation, same answer
    d_ok, _, _, st_ok, ph_ok = stationary_wealth(
        pol, 1.02, 1.0, model, tol=1e-11, precision="mixed",
        return_phases=True)
    assert not bool(ph_ok.escalated) and int(st_ok) == CONVERGED
    assert float(jnp.max(jnp.abs(d_ref - d_ok))) <= 1e-9


def test_sweep_quarantine_never_sees_descent_faults():
    """End-to-end: a mixed-policy sweep whose every descent phase is
    healthy reports zero retries — and the bisection-level NaN injection
    (which poisons the REFERENCE excess too) still reaches quarantine,
    exactly as under the default policy."""
    smoke = SweepConfig(crra_values=(1.0, 3.0), rho_values=(0.3, 0.6))
    res = run_table2_sweep(smoke, precision="mixed", **KW)
    assert (res.retries == 0).all()
    assert not is_failure(res.status).any()
    # sweep-level fault injection under mixed: the poisoned cell fails
    # loudly (NaN-masked after the retry ladder, which retries at full
    # reference precision), its neighbors stay healthy
    bad = run_table2_sweep(smoke, precision="mixed", quarantine=True,
                           max_retries=0,
                           inject_fault={"cell": 1, "at_iter": 0,
                                         "mode": "nan"}, **KW)
    assert is_failure(bad.status[1])
    assert np.isnan(bad.r_star_pct[1])
    healthy = [0, 2, 3]
    assert np.allclose(bad.r_star_pct[healthy], res.r_star_pct[healthy],
                       rtol=0, atol=1e-10)


# ---------------------------------------------------------------------------
# Stationary power iteration (ops.markov) ladder.
# ---------------------------------------------------------------------------

def test_markov_stationary_distribution_ladder_parity():
    from aiyagari_hark_tpu.ops.markov import (
        stationary_distribution,
        tauchen_labor_process,
    )

    P = tauchen_labor_process(5, 0.6, 0.2).transition
    ref = stationary_distribution(P)
    mix = stationary_distribution(P, precision="mixed")
    fast = stationary_distribution(P, precision="fast")
    # "mixed" deliberately equals "reference" here: this fixed point is a
    # handful of tiny matmuls, and no affordable polish can repair cheap
    # squaring error on a persistent chain — so mixed keeps the certified
    # contract instead of pretending to descend (see the docstring)
    assert np.array_equal(np.asarray(ref), np.asarray(mix))
    # descent-only ("fast") is approximate but normalized
    assert float(jnp.abs(jnp.sum(fast) - 1.0)) <= 1e-6
    assert float(jnp.max(jnp.abs(ref - fast))) <= 1e-4


def test_solver_health_exposes_the_escalation_note():
    assert PRECISION_ESCALATED == "PRECISION_ESCALATED"
    assert STALLED < 2  # the note is NOT a status code; severity untouched
