"""Huggett (1993) bond economy (models/huggett.py) and the borrowing-limit
generalization it rides on.  Oracles: the autarky/complete-markets bound
r* < (1-beta)/beta, exact market clearing, comparative statics in the debt
limit, and exactness of the b = 0 reduction (the Aiyagari goldens pin that
separately in test_table2)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_hark_tpu.models.household import (
    build_simple_model,
    consumption_at,
    solve_household,
    stationary_wealth,
)
from aiyagari_hark_tpu.models.huggett import solve_huggett_equilibrium

pytestmark = pytest.mark.slow   # heavyweight equilibrium solves (fast profile: -m 'not slow')


BETA, CRRA = 0.96, 2.0


@pytest.fixture(scope="module")
def huggett_model():
    return build_simple_model(labor_states=5, labor_ar=0.9, labor_sd=0.2,
                              a_count=48, a_max=30.0, borrow_limit=-4.0,
                              dist_count=400)


@pytest.fixture(scope="module")
def equilibrium(huggett_model):
    return solve_huggett_equilibrium(huggett_model, BETA, CRRA)


def test_borrowing_constrained_policy_is_exact(huggett_model):
    """Below the first endogenous knot the policy must be c = m - b (consume
    everything above the debt limit).  The constrained zone is thin — it
    ends at m1 = (b + a_min) + c(b + a_min), a few cents above the limit —
    so test just inside it; beyond it the household is *optimally* interior
    (c < m - b, a > b), which a separate assertion checks."""
    b = -4.0
    policy, _, diff, _ = solve_household(1.03, 1.0, huggett_model, BETA, CRRA)
    assert float(diff) < 1e-6
    for s in range(5):
        m1 = float(policy.m_knots[s, 1])       # state's constraint kink
        assert m1 > b + 0.05                   # a genuine constrained zone
        m_in = jnp.linspace(b + 0.02, m1 - 0.02, 5)
        c = np.asarray(consumption_at(policy, m_in, state_idx=s))
        np.testing.assert_allclose(c, np.asarray(m_in) - b, rtol=5e-3)
        # above the kink the unconstrained optimum takes over: c < m - b
        m_out = jnp.asarray([m1 + 0.3, m1 + 1.0])
        c_out = np.asarray(consumption_at(policy, m_out, state_idx=s))
        assert (c_out < np.asarray(m_out) - b - 1e-3).all()


def test_wealth_distribution_reaches_negative_assets(huggett_model):
    policy, _, _, _ = solve_household(1.03, 1.0, huggett_model, BETA, CRRA)
    dist, _, _, _ = stationary_wealth(policy, 1.03, 1.0, huggett_model)
    d = np.asarray(dist)
    grid = np.asarray(huggett_model.dist_grid)
    assert grid[0] == pytest.approx(-4.0)
    np.testing.assert_allclose(d.sum(), 1.0, atol=1e-9)
    assert d[grid < 0, :].sum() > 0.05   # real mass in debt


def test_equilibrium_clears_credit_market(equilibrium):
    eq = equilibrium
    r = float(eq.r_star)
    # liquidity premium: r* strictly below the complete-markets rate
    assert r < 1.0 / BETA - 1.0
    assert abs(float(eq.net_demand)) < 1e-3
    # both sides of the market populated
    assert 0.2 < float(eq.borrower_share) < 0.9


def test_looser_debt_limit_raises_rate(equilibrium):
    """Easier credit lowers precautionary bond demand, so a higher rate is
    needed to clear the market (Huggett's comparative static)."""
    tight = build_simple_model(labor_states=5, labor_ar=0.9, labor_sd=0.2,
                               a_count=48, a_max=30.0, borrow_limit=-2.0,
                               dist_count=400)
    eq_tight = solve_huggett_equilibrium(tight, BETA, CRRA)
    assert float(eq_tight.r_star) < float(equilibrium.r_star)


def test_huggett_is_jittable(huggett_model):
    f = jax.jit(lambda: solve_huggett_equilibrium(huggett_model, BETA, CRRA,
                                                  max_bisect=20))
    eq = f()
    assert np.isfinite(float(eq.r_star))


def test_tight_limit_auto_widens_bracket():
    """With a very tight debt limit, net demand at the default r_lo is
    still positive; the solver must widen the bracket (or honestly report
    bracketed=False), never return a non-clearing r* labeled as an
    equilibrium."""
    tight = build_simple_model(labor_states=5, labor_ar=0.9, labor_sd=0.2,
                               a_count=48, a_max=30.0, borrow_limit=-0.05,
                               dist_count=300)
    eq = solve_huggett_equilibrium(tight, BETA, CRRA)
    assert bool(eq.bracketed)
    assert abs(float(eq.net_demand)) < 1e-3
    # near-autarky: the rate must fall far below the loose-limit values
    assert float(eq.r_star) < 0.0


# ---------------------------------------------------------------------------
# Credit-crunch transition (Guerrieri-Lorenzoni 2017-style deleveraging)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def credit_crunch():
    from aiyagari_hark_tpu.models.huggett import solve_credit_crunch

    loose = build_simple_model(labor_states=3, a_count=30, a_max=20.0,
                               borrow_limit=-2.0, dist_count=120)
    tight = build_simple_model(labor_states=3, a_count=30, a_max=20.0,
                               borrow_limit=-1.5, dist_count=120)
    eq0 = solve_huggett_equilibrium(loose, BETA, CRRA)
    eqT = solve_huggett_equilibrium(tight, BETA, CRRA)
    T = 100
    phase = np.minimum(np.arange(T) / 24.0, 1.0)
    res = solve_credit_crunch(loose, BETA, CRRA, -2.0 + 0.5 * phase,
                              eq0.distribution, eqT.policy,
                              eq0.r_star, eqT.r_star)
    return eq0, eqT, res


def test_credit_crunch_clears_every_market(credit_crunch):
    _, _, res = credit_crunch
    assert bool(res.converged), float(res.max_excess)
    assert np.abs(np.asarray(res.excess_path)[:-1]).max() < 1e-6


def test_credit_crunch_rate_overshoots(credit_crunch):
    """GL's headline result: during deleveraging the clearing rate dips
    BELOW its new (lower) long-run level, then recovers to it."""
    eq0, eqT, res = credit_crunch
    r = np.asarray(res.r_path)
    r_pre, r_new = float(eq0.r_star), float(eqT.r_star)
    assert r_new < r_pre                       # tighter limit lowers r*
    assert r.min() < r_new - 5e-4              # the overshoot (>5bp)
    np.testing.assert_allclose(r[-1], r_new, atol=5e-4)


def test_credit_crunch_deleveraging(credit_crunch):
    """Gross household debt contracts toward the tight-limit level; and
    Walras's law holds along the path — with the bond in zero net
    supply and every market cleared, aggregate consumption equals the
    aggregate endowment at EVERY date (the crunch reshuffles who
    consumes, not how much in total — the GL consumption drop needs
    endogenous output, which the pure-exchange model rules out)."""
    _, eqT, res = credit_crunch
    debt = np.asarray(res.debt_path)
    assert debt[-1] < debt[0] - 0.05
    c = np.asarray(res.c_agg_path)
    assert (c.max() - c.min()) / c.mean() < 1e-3
