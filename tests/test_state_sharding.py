"""State-axis sharding (ISSUE 20, DESIGN §6b).

The contract under test:

* ``state="replicated"`` (the default) is BIT-identical to an
  unspecified state policy — the explicit spelling shares the
  fingerprints, the executable cache entries, and the bits.
* ``state="sharded"`` under an active 2-D state mesh partitions the
  per-cell wealth state across devices and keeps r* within 0.1 bp of
  the replicated run, with identical statuses (the contraction is NOT
  bit-identical — one all-reduce reorders the row-block sums).
* geometry is typed everywhere: ``make_mesh`` names impossible grids,
  ``state_mesh`` rejects shard counts < 1, an indivisible wealth grid
  refuses loudly, and the resume ledger fingerprints the full
  (cells, state) geometry — a ledger written under one geometry warns
  ("different run") and recomputes bit-identically under another.
* quarantine rungs force ``state="replicated"`` so a sharded-contraction
  pathology can never poison its own retry ladder.
* the serving engine activates the state mesh around every flush;
  ``state_shards`` and a multi-lane mesh are mutually exclusive (typed).
"""

import jax
import numpy as np
import pytest

from aiyagari_hark_tpu.models.equilibrium import household_capital_supply
from aiyagari_hark_tpu.models.household import build_simple_model
from aiyagari_hark_tpu.parallel.mesh import (
    STATE_AXIS,
    active_state_mesh,
    balanced_lane_order,
    constrain_state,
    current_state_mesh,
    make_mesh,
    match_partition_rules,
    mesh_axis_size,
    pad_to_multiple,
    resolve_mesh,
    state_mesh,
    state_sharding,
)
from aiyagari_hark_tpu.parallel.sweep import run_table2_sweep
from aiyagari_hark_tpu.solver_health import is_failure
from aiyagari_hark_tpu.utils.config import (
    STATE_POLICIES,
    SweepConfig,
    resolve_state,
)
from aiyagari_hark_tpu.utils.fingerprint import (
    hashable_kwargs,
    ledger_fingerprint,
    work_fingerprint,
)
from aiyagari_hark_tpu.utils.resilience import Interrupted, preemption_guard

# The tier-1 sweep workload shared with tests/test_precision.py — same
# lru/jit cache keys, so this module rides the same warm compiles.
KW = dict(a_count=10, dist_count=32, labor_states=3, r_tol=1e-5,
          max_bisect=24)
SMALL = SweepConfig(crra_values=(1.0, 5.0), rho_values=(0.0, 0.9),
                    schedule="balanced", n_buckets=2)
# 4-cell lattice for the sweep-level numerics — the policy contract is
# config-agnostic, and the full 12-cell lattice would push tier-1 past
# its wall budget (the bench leg sweeps the full lattice instead)
CFG = SweepConfig(crra_values=(1.0, 3.0), rho_values=(0.3, 0.6))


# ---------------------------------------------------------------------------
# The policy seam.
# ---------------------------------------------------------------------------

def test_resolve_state_policies():
    assert STATE_POLICIES == ("replicated", "sharded")
    assert resolve_state("replicated").sharded is False
    assert resolve_state("sharded").sharded is True
    spec = resolve_state("sharded")
    assert resolve_state(spec) is spec          # spec passes through
    with pytest.raises(ValueError, match="state policy must be one of"):
        resolve_state("bogus")
    with pytest.raises(ValueError):
        resolve_state(None)


# ---------------------------------------------------------------------------
# Mesh geometry: construction, typed errors, the partition-rule table.
# ---------------------------------------------------------------------------

def test_state_mesh_geometry():
    n = len(jax.devices())
    assert n == 8, "tier-1 runs on 8 forced-host devices (conftest)"
    sm = state_mesh(4)
    assert mesh_axis_size(sm, STATE_AXIS) == 4
    assert mesh_axis_size(sm, "cells") == n // 4
    # the degenerate case is EXACTLY the pre-existing 1-D lane geometry
    assert state_mesh(1).shape == resolve_mesh("auto").shape
    with pytest.raises(ValueError, match="state_shards must be >= 1"):
        state_mesh(0)


def test_make_mesh_typed_errors():
    devs = jax.devices()
    # more than one -1 names the grid instead of dying in numpy reshape
    with pytest.raises(ValueError, match="at most one"):
        make_mesh(("cells", "state"), (-1, -1), devices=devs)
    # a device count not divisible by the known sizes names BOTH shapes
    with pytest.raises(ValueError) as ei:
        make_mesh(("cells", "state"), (-1, 3), devices=devs)
    assert "'state': 3" in str(ei.value) and "8 devices" in str(ei.value)


def test_partition_rule_table():
    from jax.sharding import PartitionSpec as P   # mesh-ok: expectations

    assert match_partition_rules("distribution") == P(STATE_AXIS, None)
    assert match_partition_rules("wealth_operator") == P(None, None,
                                                         STATE_AXIS)
    assert match_partition_rules("policy") == P(None, STATE_AXIS)
    # rules match path-style names too (first regex wins)
    assert match_partition_rules("household/distribution") == P(
        STATE_AXIS, None)
    with pytest.raises(ValueError, match="no state partition rule"):
        match_partition_rules("nope")


def test_constrain_state_noop_degeneracies():
    x = np.ones((8, 3))
    assert constrain_state(x, None, "distribution") is x
    assert constrain_state(x, state_mesh(1), "distribution") is x
    sm = state_mesh(2)
    y = constrain_state(jax.numpy.asarray(x), sm, "distribution")
    assert np.array_equal(np.asarray(y), x)
    # the sharding the constraint requested is the table's
    assert state_sharding(sm, "distribution").spec == \
        match_partition_rules("distribution")


def test_active_state_mesh_context():
    assert current_state_mesh() is None
    sm = state_mesh(2)
    with active_state_mesh(sm):
        assert current_state_mesh() is sm
        with active_state_mesh(None):      # nested deactivation restores
            assert current_state_mesh() is None
        assert current_state_mesh() is sm
    assert current_state_mesh() is None


# ---------------------------------------------------------------------------
# Mesh-helper property tests (ISSUE 20 satellite).
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n,multiple,axis", [
    (5, 4, 0), (8, 4, 0), (5, 1, 0), (7, 3, 1), (4, 4, 1),
])
def test_pad_to_multiple_properties(n, multiple, axis):
    shape = [3, 3]
    shape[axis] = n
    rng = np.random.default_rng(n * 10 + multiple)
    x = rng.normal(size=shape)
    padded, orig = pad_to_multiple(x, multiple, axis=axis)
    assert orig == n
    assert padded.shape[axis] % multiple == 0
    assert padded.shape[axis] - n < multiple          # minimal padding
    # original content is untouched, padding edge-replicates
    sl = [slice(None)] * x.ndim
    sl[axis] = slice(0, n)
    assert np.array_equal(padded[tuple(sl)], x)
    if padded.shape[axis] > n:
        edge = [slice(None)] * x.ndim
        edge[axis] = slice(n - 1, n)
        pad = [slice(None)] * x.ndim
        pad[axis] = slice(n, None)
        assert np.array_equal(
            padded[tuple(pad)],
            np.repeat(x[tuple(edge)], padded.shape[axis] - n, axis=axis))
    # multiple=1 and aligned sizes are exact no-ops
    if multiple == 1 or n % multiple == 0:
        assert padded.shape[axis] == n


@pytest.mark.parametrize("work,n_shards", [
    ([1.0] * 8, 4),                 # full ties
    ([3.0, 3.0, 1.0, 1.0], 2),     # paired ties
    ([5.0, 1.0, 1.0, 1.0, 4.0, 2.0, 2.0, 2.0], 2),
])
def test_balanced_lane_order_is_a_valid_permutation(work, n_shards):
    perm = balanced_lane_order(np.asarray(work), n_shards)
    assert sorted(perm.tolist()) == list(range(len(work)))
    # every shard gets exactly len/n_shards lanes (contiguous blocks)
    per = len(work) // n_shards
    loads = [sum(np.asarray(work)[perm[i * per:(i + 1) * per]])
             for i in range(n_shards)]
    # LPT guarantee: max load within 4/3 of the uniform bound + one lane
    assert max(loads) <= (4.0 / 3.0) * (sum(work) / n_shards) + max(work)


def test_resolve_mesh_rejects_missing_axis():
    sm = state_mesh(2)     # axes ("cells", "state")
    with pytest.raises(ValueError, match="do not define"):
        resolve_mesh(sm, "lanes")
    with pytest.raises(ValueError, match="'auto'"):
        resolve_mesh("never", "cells")


# ---------------------------------------------------------------------------
# Fingerprints: drop-explicit-default, cross-policy inequality, the 2-D
# ledger geometry.
# ---------------------------------------------------------------------------

def test_hashable_kwargs_state_canonicalization():
    base = hashable_kwargs({"a_count": 10})
    assert hashable_kwargs({"a_count": 10, "state": "replicated"}) == base
    sharded = hashable_kwargs({"a_count": 10, "state": "sharded"})
    assert sharded != base
    assert ("state", "sharded") in sharded
    with pytest.raises(ValueError):
        hashable_kwargs({"state": "bogus"})


def test_work_fingerprint_separates_state_policies():
    base = work_fingerprint(hashable_kwargs(KW), np.float64)
    expl = work_fingerprint(
        hashable_kwargs({**KW, "state": "replicated"}), np.float64)
    shrd = work_fingerprint(
        hashable_kwargs({**KW, "state": "sharded"}), np.float64)
    assert base == expl                  # the no-drift pin
    assert shrd != base                  # sharded keys its own programs


def test_ledger_fingerprint_hashes_2d_geometry():
    cells = [(1.0, 0.3, 0.2)]
    args = dict(cells=cells, kwargs_items=hashable_kwargs(KW),
                dtype=np.float64, schedule="balanced", n_buckets=2,
                warm_brackets=False, warm_margin=0.0, fault_mode=None,
                fault_iters=None, max_retries=1, quarantine=False,
                sidecar=None)
    base = ledger_fingerprint(**args)
    assert ledger_fingerprint(**args, state_shards=1) == base  # default
    assert ledger_fingerprint(**args, state_shards=2) != base
    assert ledger_fingerprint(**args, mesh_shards=8) != \
        ledger_fingerprint(**args, mesh_shards=4, state_shards=2)


# ---------------------------------------------------------------------------
# Numerics: replicated bit-identity, sharded drift, typed divisibility.
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def sweeps():
    ref = run_table2_sweep(CFG, **KW)
    sh2 = run_table2_sweep(CFG.replace(state_shards=2), **KW)
    return ref, sh2


def test_replicated_default_and_explicit_are_bit_identical(sweeps):
    ref, _ = sweeps
    expl = run_table2_sweep(CFG, state="replicated", **KW)
    for field in ("r_star_pct", "saving_rate_pct", "capital", "excess",
                  "bisect_iters", "egm_iters", "dist_iters", "status"):
        assert np.array_equal(np.asarray(getattr(ref, field)),
                              np.asarray(getattr(expl, field)),
                              equal_nan=True), field


@pytest.mark.parametrize("shards", [2, 4])
def test_sharded_sweep_r_star_within_a_tenth_bp(sweeps, shards):
    ref, sh2 = sweeps
    sh = (sh2 if shards == 2
          else run_table2_sweep(CFG.replace(state_shards=4), **KW))
    drift_bp = float(np.abs(np.asarray(sh.r_star_pct)
                            - np.asarray(ref.r_star_pct)).max()) * 100.0
    assert drift_bp < 0.1, f"r* drift {drift_bp} bp at {shards} shards"
    assert np.array_equal(np.asarray(sh.status), np.asarray(ref.status))


def test_sharded_supply_matches_replicated_supply():
    m = build_simple_model(labor_states=3, a_count=12, dist_count=64)
    ref = household_capital_supply(0.02, m, 0.96, 2.0, 0.36, 0.08)
    with active_state_mesh(state_mesh(4)):
        sh = household_capital_supply(0.02, m, 0.96, 2.0, 0.36, 0.08,
                                      state="sharded")
    assert abs(float(ref.supply) - float(sh.supply)) < 1e-9
    # without an active mesh the sharded policy degrades to replicated
    # bits by construction (constrain_state no-ops on mesh None)
    off = household_capital_supply(0.02, m, 0.96, 2.0, 0.36, 0.08,
                                   state="sharded")
    assert float(off.supply) == float(ref.supply)


def test_indivisible_wealth_grid_refuses_loudly():
    m = build_simple_model(labor_states=3, a_count=12, dist_count=66)
    with active_state_mesh(state_mesh(4)):
        with pytest.raises(ValueError, match="divisible by the state"):
            household_capital_supply(0.02, m, 0.96, 2.0, 0.36, 0.08,
                                     state="sharded")


def test_quarantine_rungs_force_replicated(sweeps):
    """A NaN-injected cell under a sharded sweep recovers through the
    ladder: every rung re-solves ``state="replicated"`` (the certified
    layout), so the fault cannot chase the sharded contraction."""
    ref, _ = sweeps
    res = run_table2_sweep(CFG.replace(state_shards=2),
                           inject_fault={"cell": 1, "at_iter": 1,
                                         "mode": "nan"},
                           max_retries=2, **KW)
    assert int(res.retries[1]) >= 1
    assert not is_failure(int(res.status[1]))
    # the rung's replicated re-solve reproduces the replicated root
    assert abs(float(res.r_star_pct[1]) - float(ref.r_star_pct[1])) \
        * 100.0 < 0.1


# ---------------------------------------------------------------------------
# Resume: the ledger refuses a different (cells, state) geometry and
# recomputes bit-identically.
# ---------------------------------------------------------------------------

def test_state_geometry_refuses_resume_and_recomputes(tmp_path):
    # looser solver knobs than KW: the geometry guard is about ledger
    # bits, not root precision, and this test pays for three sweeps
    rkw = dict(KW, r_tol=1e-4, max_bisect=16)
    clean = run_table2_sweep(SMALL, **rkw)    # replicated reference
    ledger = str(tmp_path / "state2_ledger.npz")
    with preemption_guard():
        with pytest.raises(Interrupted):
            run_table2_sweep(
                SMALL.replace(state_shards=2), resume_path=ledger,
                inject_preempt={"after_bucket": 0, "mode": "flag"}, **rkw)
    import os

    assert os.path.exists(ledger)
    # resumed WITHOUT state sharding: the 2-D geometry in the ledger
    # fingerprint mismatches, the sweep warns typed and recomputes — and
    # the recomputed result is bit-identical to an uninterrupted
    # replicated run (a silent resume would have smuggled in rows from
    # a differently-reduced contraction)
    with pytest.warns(UserWarning, match="different run"):
        res = run_table2_sweep(SMALL, resume_path=ledger, **rkw)
    assert not os.path.exists(ledger)
    for f in ("r_star_pct", "capital", "status", "bisect_iters",
              "egm_iters", "dist_iters"):
        assert np.array_equal(np.asarray(getattr(res, f)),
                              np.asarray(getattr(clean, f)),
                              equal_nan=True), f


# ---------------------------------------------------------------------------
# Serving: the state mesh wraps flushes; lane mesh + state shards refuse.
# ---------------------------------------------------------------------------

def test_service_state_shards_and_lane_mesh_are_exclusive():
    from aiyagari_hark_tpu.serve import EquilibriumService

    with pytest.raises(ValueError, match="cannot combine"):
        EquilibriumService(mesh="auto", state_shards=2,
                           start_worker=False)


def test_served_sharded_state_matches_replicated_to_solver_noise():
    from aiyagari_hark_tpu.serve import EquilibriumService, make_query
    from aiyagari_hark_tpu.utils.timing import CompileCounter

    # test_serve.py's KW spelling (r_tol=1e-4, max_bisect=16) so the
    # replicated reference service rides its warmed executables; dense
    # pinned because the sharded contraction forces it
    skw = dict(a_count=10, dist_count=32, labor_states=3, r_tol=1e-4,
               max_bisect=16, dist_method="dense")
    with EquilibriumService(start_worker=False, max_batch=4,
                            max_wait_s=60.0, ladder=(1, 2, 4)) as ref_svc:
        ref = ref_svc.query(3.0, 0.6, **skw)
    with EquilibriumService(start_worker=False, max_batch=4,
                            max_wait_s=60.0, ladder=(1, 2, 4),
                            state_shards=2) as svc:
        res = svc.query(3.0, 0.6, state="sharded", **skw)
        assert res.path == "cold"
        drift_bp = abs(res.r_star - ref.r_star) * 100.0 * 100.0
        assert drift_bp < 0.1
        assert res.status == ref.status
        # exact replay: a store hit, zero new XLA compiles
        with CompileCounter() as c:
            hit = svc.query(3.0, 0.6, state="sharded", **skw)
        assert hit.path == "hit" and c.compile_events == 0
        # the reference path rides the SAME state-mesh context, so its
        # bits agree with the served cold lane's
        q = make_query(3.0, 0.6, state="sharded", **skw)
        refsolve = svc.reference_solve(q, bracket_init=res.bracket_init)
        assert (res.r_star, res.capital, res.status) == \
            (refsolve.r_star, refsolve.capital, refsolve.status)


# ---------------------------------------------------------------------------
# The regression sentinel knows every state_* bench field (satellite).
# ---------------------------------------------------------------------------

def test_regress_directions_cover_the_state_record():
    from aiyagari_hark_tpu.obs.regress import (
        DOWN,
        UP,
        direction_of_goodness,
    )

    record = {
        "state_smoke_cells": 4,
        "state_r_star_drift_bp": 0.0,
        "state_budget_bytes": 4 << 20,
        "state_overflow_grid": 512,
        "state_model_resident_replicated_bytes": 6316032,
        "state_model_resident_sharded_bytes": 1579008,
        "state_resident_ratio": 0.25,
        "state_collective_share_frac": 0.22,
        "state_mem_stats_devices": 0,
        "state_mem_peak_bytes": 1.0,
        "state_gridpoints_per_sec_1shard": 3.4e6,
        "state_gridpoints_per_sec_2shard": 2.4e6,
        "state_gridpoints_per_sec_4shard": 2.7e6,
    }
    for field in record:                      # strict: no unclassified
        direction_of_goodness(field, strict=True)
    assert direction_of_goodness("state_gridpoints_per_sec_4shard") == UP
    assert direction_of_goodness("state_r_star_drift_bp") == DOWN
    assert direction_of_goodness("state_resident_ratio") == DOWN
    assert direction_of_goodness("state_collective_share_frac") == DOWN


def test_regress_grades_a_state_history():
    from aiyagari_hark_tpu.obs.regress import (
        REGRESSED,
        evaluate_history,
    )

    base = {"metric": "state_scaling",
            "state_gridpoints_per_sec_4shard": 1000.0,
            "state_r_star_drift_bp": 0.001}
    prior2 = dict(base, state_gridpoints_per_sec_4shard=1050.0)
    good = dict(base, state_gridpoints_per_sec_4shard=1100.0)
    bad = dict(base, state_gridpoints_per_sec_4shard=400.0,
               state_r_star_drift_bp=0.09)
    history = [("r1", base), ("r2", prior2)]
    assert evaluate_history(history + [("r3", good)]).worst < REGRESSED
    report = evaluate_history(history + [("r3", bad)])
    assert report.worst == REGRESSED
    names = {f.metric for f in report.regressed()}
    assert "state_gridpoints_per_sec_4shard" in names
