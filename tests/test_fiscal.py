"""Fiscal redistribution (models/fiscal.py): revenue-neutral labor
taxation as a static relabeling of the labor states.

Oracles: exact identities of the balanced-budget transforms (mean
preservation, risk compression, limiting cases) plus Aiyagari's own
general-equilibrium mechanism run in reverse — redistribution insures
idiosyncratic risk, precautionary saving falls, and r* rises toward the
complete-markets 1/beta - 1 — and the classic hump-shaped utilitarian
welfare (insurance gains vs capital crowding-out) with an interior
optimum.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_hark_tpu.models.fiscal import (
    build_fiscal_model,
    progressive_labor_levels,
    redistributive_labor_levels,
    solve_fiscal_equilibrium,
)
from aiyagari_hark_tpu.models.household import (
    aggregate_labor,
    build_simple_model,
)

CFG = dict(labor_states=5, labor_ar=0.6, labor_sd=0.3, a_count=24,
           dist_count=120)
BETA, CRRA, ALPHA, DELTA = 0.96, 2.0, 0.36, 0.08


@pytest.fixture(scope="module")
def fiscal_eq():
    """Memoized GE solves at this module's calibration: the three slow
    tests overlap on tau ∈ {0, 0.3}, and each solve_fiscal_equilibrium is
    a full nested bisection — share converged equilibria instead of
    re-solving them (VERDICT r3 weak-item 5).  Keyed on the exact fiscal
    kwargs; assertions are unchanged (a cache hit returns the identical
    object a fresh call would compute — the solver is deterministic)."""
    cache = {}

    def get(**fiscal_kwargs):
        key = tuple(sorted(fiscal_kwargs.items()))
        if key not in cache:
            cache[key] = solve_fiscal_equilibrium(
                BETA, CRRA, ALPHA, DELTA, **fiscal_kwargs, **CFG)
        return cache[key]

    return get


def _sd(levels, pi):
    m = float(jnp.sum(pi * levels))
    return float(jnp.sqrt(jnp.sum(pi * (levels - m) ** 2)))


def test_transforms_preserve_mean_and_compress_risk():
    base = build_simple_model(**CFG)
    pi = base.labor_stationary
    l_bar = float(jnp.sum(pi * base.labor_levels))
    for tau in (0.0, 0.2, 0.7, 1.0):
        lev = redistributive_labor_levels(base.labor_levels, pi, tau)
        assert float(jnp.sum(pi * lev)) == pytest.approx(l_bar, rel=1e-12)
        assert _sd(lev, pi) == pytest.approx(
            (1.0 - tau) * _sd(base.labor_levels, pi), rel=1e-10)
    for p in (0.0, 0.18, 0.5, 1.0):
        lev = progressive_labor_levels(base.labor_levels, pi, p)
        assert float(jnp.sum(pi * lev)) == pytest.approx(l_bar, rel=1e-12)
    # limits: tau=0 / p=0 identity; tau=1 / p=1 full pooling at the mean
    np.testing.assert_allclose(
        redistributive_labor_levels(base.labor_levels, pi, 0.0),
        base.labor_levels)
    np.testing.assert_allclose(
        np.asarray(redistributive_labor_levels(base.labor_levels, pi, 1.0)),
        l_bar, rtol=1e-12)
    np.testing.assert_allclose(
        np.asarray(progressive_labor_levels(base.labor_levels, pi, 1.0)),
        l_bar, rtol=1e-12)


def test_fiscal_model_keeps_firm_side_labor():
    """The firm's labor input must be invariant to the transform (this is
    what makes the budget balance at every bisection midpoint)."""
    base = build_simple_model(**CFG)
    for kwargs in (dict(tax_rate=0.3), dict(progressivity=0.4)):
        fm = build_fiscal_model(**kwargs, **CFG)
        np.testing.assert_allclose(float(aggregate_labor(fm)),
                                   float(aggregate_labor(base)), rtol=1e-12)
        np.testing.assert_allclose(np.asarray(fm.a_grid),
                                   np.asarray(base.a_grid))


@pytest.mark.slow
def test_redistribution_raises_equilibrium_rate(fiscal_eq):
    """Aiyagari's mechanism in reverse: compressing income risk reduces
    precautionary saving, so r* rises monotonically toward 1/beta - 1 and
    capital falls; the budget balances and markets clear at every tau.
    Measured at this config: r* 3.633% -> 3.795% -> 3.923% -> 4.046% for
    tau in {0, .15, .3, .5} against the 4.167% complete-markets cap."""
    r_cap = 1.0 / BETA - 1.0
    prev_r, prev_k = -1.0, np.inf
    for tau in (0.0, 0.15, 0.3, 0.5):
        feq = fiscal_eq(tax_rate=tau)
        eq = feq.equilibrium
        r = float(eq.r_star)
        assert abs(float(eq.excess)) < 1e-6
        assert prev_r < r < r_cap
        assert float(eq.capital) < prev_k
        # balanced budget: transfer == tau * W * L_bar, with L_bar the
        # UNtransformed aggregate labor
        W = float(eq.wage)
        l_bar = float(aggregate_labor(feq.model))
        assert float(feq.transfer) == pytest.approx(tau * W * l_bar,
                                                    rel=1e-10)
        prev_r, prev_k = r, float(eq.capital)
    # HSV progressivity moves the same direction
    feq_p = fiscal_eq(progressivity=0.18)
    feq_0 = fiscal_eq(tax_rate=0.0)
    assert float(feq_p.equilibrium.r_star) > float(feq_0.equilibrium.r_star)


@pytest.mark.slow
def test_tax_sweep_is_one_batched_program(fiscal_eq):
    """``tax_rate_sweep`` vmaps whole GE solves + welfare recovery over
    the tax axis; lanes must agree with serial solves, and the welfare
    argmax sits in the interior (measured optimum tau* = 0.4 on this
    grid at this calibration)."""
    from aiyagari_hark_tpu.models.fiscal import tax_rate_sweep
    from aiyagari_hark_tpu.models.value import (
        aggregate_welfare,
        policy_value,
    )

    taus = np.linspace(0.0, 0.6, 7)
    res = tax_rate_sweep(taus, BETA, CRRA, ALPHA, DELTA, **CFG)
    # lane 3 (tau=0.3) vs the serial path
    feq = fiscal_eq(tax_rate=0.3)
    assert float(res.r_star[3]) == pytest.approx(
        float(feq.equilibrium.r_star), abs=1e-8)
    eq = feq.equilibrium
    vf, _, _ = policy_value(eq.policy, 1.0 + eq.r_star, eq.wage, feq.model,
                            BETA, CRRA)
    w_serial = float(aggregate_welfare(vf, eq.distribution, 1.0 + eq.r_star,
                                       eq.wage, feq.model, CRRA))
    assert float(res.welfare[3]) == pytest.approx(w_serial, rel=1e-8)
    # interior optimum on the hump
    i = int(np.argmax(np.asarray(res.welfare)))
    assert 0 < i < len(taus) - 1
    assert float(res.tax_rates[i]) == pytest.approx(0.4, abs=0.101)


@pytest.mark.slow
def test_utilitarian_welfare_is_hump_shaped(fiscal_eq):
    """The optimal-redistribution trade-off: moderate taxation raises
    utilitarian welfare (insurance of uninsurable risk) but heavy taxation
    crowds out capital enough to reverse the gain — an interior optimum.
    Measured CE vs laissez-faire at this config: +0.157% (tau=.15),
    +0.250% (tau=.3), +0.180% (tau=.6)."""
    from aiyagari_hark_tpu.models.value import (
        aggregate_welfare,
        consumption_equivalent,
        policy_value,
    )

    welf = {}
    for tau in (0.0, 0.3, 0.6):
        feq = fiscal_eq(tax_rate=tau)
        eq = feq.equilibrium
        R = 1.0 + eq.r_star
        vf, _, _ = policy_value(eq.policy, R, eq.wage, feq.model, BETA,
                                CRRA)
        welf[tau] = float(aggregate_welfare(vf, eq.distribution, R,
                                            eq.wage, feq.model, CRRA))
    ce_30 = float(consumption_equivalent(welf[0.0], welf[0.3], CRRA, BETA))
    ce_60 = float(consumption_equivalent(welf[0.0], welf[0.6], CRRA, BETA))
    assert ce_30 > 0.001           # insurance gain, > 0.1% CE
    assert ce_60 < ce_30           # crowding-out bends the hump down
    assert ce_60 > -0.01           # but moderate enough not to collapse
