"""Durable replicated coordination (ISSUE 18 tentpole): the WAL-backed
CAS backend's crash contract, the deterministic disk-fault injector, the
quorum client's unit behaviors (winner rule, read-repair, edge-triggered
quorum loss, anti-entropy resync), the store's bounded close and
memory-only degrade, the sweep ledger's flush degrade, and the dr_*
bench fields' regression-direction coverage.

The WAL recovery edge cases (satellite c) are each pinned explicitly —
torn final record, checksum-corrupt mid-log, snapshot newer than the log
tail, empty WAL + stale snapshot, corrupt snapshot — and the whole
format is property-tested against the in-memory reference backend under
a seeded random op stream with a restart at the end.
"""

import errno
import json
import os
import random
import threading
import time
import warnings

import numpy as np
import pytest

from aiyagari_hark_tpu.obs.journal import read_journal
from aiyagari_hark_tpu.obs.runtime import ObsConfig, build_obs
from aiyagari_hark_tpu.serve.lease import CASServer, MemoryCASBackend
from aiyagari_hark_tpu.serve.replicated import (
    CoordinationUnavailable,
    ReplicatedCASBackend,
)
from aiyagari_hark_tpu.serve.wal import (
    SNAPSHOT_NAME,
    WAL_NAME,
    DurableCASBackend,
    WALCorruptionError,
    _checksum,
)
from aiyagari_hark_tpu.utils.checkpoint import (
    append_jsonl,
    arm_disk_fault,
    atomic_write_json,
    atomic_write_text,
    disarm_disk_faults,
    save_pytree,
)


@pytest.fixture(autouse=True)
def _no_leftover_faults():
    disarm_disk_faults()
    yield
    disarm_disk_faults()


def _state(backend) -> dict:
    """Full record map over the public dump op: key -> (owner, stamp,
    version), tombstones included — the bit-identity comparator."""
    return {int(k): (o, float(t), int(v)) for k, o, t, v in backend.dump()}


def _wal_lines(data_dir: str) -> list:
    with open(os.path.join(data_dir, WAL_NAME), "rb") as f:
        return [ln for ln in f.read().split(b"\n") if ln.strip()]


def _craft_record(seq: int, k: int, o, t: float, v: int) -> bytes:
    payload = {"seq": int(seq), "k": int(k), "o": o, "t": float(t),
               "v": int(v)}
    payload["ck"] = _checksum(payload)
    return (json.dumps(payload) + "\n").encode("utf-8")


# ---------------------------------------------------------------------------
# WAL recovery: the crash contract, edge case by edge case (satellite c).
# ---------------------------------------------------------------------------

def test_restart_recovers_exact_state(tmp_path):
    d = str(tmp_path / "cas")
    b = DurableCASBackend(d, snapshot_every=1000)
    assert b.try_acquire(1, "a")
    assert b.try_acquire(2, "b")
    assert b.release(1, owner="a")           # tombstone: version bumped
    assert b.try_acquire(1, "c")             # re-acquire after release
    before = _state(b)
    assert before[1][0] == "c" and before[1][2] == 3
    reborn = DurableCASBackend(d, snapshot_every=1000)
    assert _state(reborn) == before          # stamps included, bit-exact
    # the sequence counter recovered too: further mutations extend, not
    # collide with, the old log
    assert reborn.try_acquire(3, "d")
    reborn2 = DurableCASBackend(d, snapshot_every=1000)
    assert _state(reborn2)[3][0] == "d"


def test_torn_final_record_skipped_loudly(tmp_path):
    d = str(tmp_path / "cas")
    b = DurableCASBackend(d, snapshot_every=1000)
    assert b.try_acquire(1, "a")
    assert b.try_acquire(2, "b")
    before = _state(b)
    # the hard-kill artifact: a partial final line (no trailing newline)
    with open(os.path.join(d, WAL_NAME), "ab") as f:  # atomic-ok: test writes the torn tail
        f.write(b'{"seq": 3, "k": 9, "o": "to')
    jp = str(tmp_path / "j.jsonl")
    obs = build_obs(ObsConfig(enabled=True, journal_path=jp))
    with pytest.warns(UserWarning, match="torn final"):
        reborn = DurableCASBackend(d, snapshot_every=1000, obs=obs)
    assert _state(reborn) == before          # every acked record replayed
    obs.close()
    (ev,) = read_journal(jp, event="WAL_REPLAY")
    assert ev["torn_skipped"] == 1 and ev["applied"] == 2


def test_midlog_corruption_refuses_typed(tmp_path):
    d = str(tmp_path / "cas")
    b = DurableCASBackend(d, snapshot_every=1000)
    for k in (1, 2, 3):
        assert b.try_acquire(k, "a")
    wal = os.path.join(d, WAL_NAME)
    lines = _wal_lines(d)
    assert len(lines) == 3
    # flip bytes in the MIDDLE record: external damage, outside the
    # torn-tail contract — recovery must refuse, not serve a wrong prefix
    lines[1] = lines[1][:-4] + b"XXX}"
    with open(wal, "wb") as f:  # atomic-ok: test writes the corrupt log
        f.write(b"\n".join(lines) + b"\n")
    with pytest.raises(WALCorruptionError, match="mid-log"):
        DurableCASBackend(d, snapshot_every=1000)


def test_snapshot_newer_than_log_tail_filters_stale_records(tmp_path):
    d = str(tmp_path / "cas")
    b = DurableCASBackend(d, snapshot_every=1000)
    assert b.try_acquire(1, "new-owner")
    assert b.heartbeat(1, "new-owner")
    b.compact()                              # snapshot covers seq 2
    before = _state(b)
    # the crash window between snapshot write and WAL truncation leaves
    # already-covered records in the log: craft a STALE seq-1 record
    # claiming a different owner — replay must filter it by seq
    with open(os.path.join(d, WAL_NAME), "ab") as f:  # atomic-ok: test writes the stale suffix
        f.write(_craft_record(1, 1, "stale-owner", 0.0, 1))
    reborn = DurableCASBackend(d, snapshot_every=1000)
    assert _state(reborn) == before
    assert reborn.owner_of(1) == "new-owner"


def test_empty_wal_with_snapshot_recovers_from_snapshot(tmp_path):
    d = str(tmp_path / "cas")
    b = DurableCASBackend(d, snapshot_every=1000)
    assert b.try_acquire(1, "a")
    assert b.try_acquire(2, "b")
    b.compact()                              # WAL emptied, snapshot holds all
    assert _wal_lines(d) == []
    before = _state(b)
    jp = str(tmp_path / "j.jsonl")
    obs = build_obs(ObsConfig(enabled=True, journal_path=jp))
    reborn = DurableCASBackend(d, snapshot_every=1000, obs=obs)
    assert _state(reborn) == before
    obs.close()
    (ev,) = read_journal(jp, event="WAL_REPLAY")
    assert ev["applied"] == 0 and ev["keys"] == 2


def test_corrupt_snapshot_refuses_typed(tmp_path):
    d = str(tmp_path / "cas")
    b = DurableCASBackend(d, snapshot_every=1000)
    assert b.try_acquire(1, "a")
    b.compact()
    snap = os.path.join(d, SNAPSHOT_NAME)
    with open(snap, "rb") as f:
        body = f.read()
    with open(snap, "wb") as f:  # atomic-ok: test writes the corrupt snapshot
        f.write(body.replace(b'"a"', b'"z"'))   # content no longer matches ck
    with pytest.raises(WALCorruptionError, match="checksum"):
        DurableCASBackend(d, snapshot_every=1000)


def test_fresh_directory_recovers_nothing_and_journals_nothing(tmp_path):
    jp = str(tmp_path / "j.jsonl")
    obs = build_obs(ObsConfig(enabled=True, journal_path=jp))
    b = DurableCASBackend(str(tmp_path / "cas"), obs=obs)
    assert b.list_keys() == []
    obs.close()
    assert read_journal(jp, event="WAL_REPLAY") == []


def test_snapshot_compaction_triggers_and_journals(tmp_path):
    d = str(tmp_path / "cas")
    jp = str(tmp_path / "j.jsonl")
    obs = build_obs(ObsConfig(enabled=True, journal_path=jp))
    b = DurableCASBackend(d, snapshot_every=4, obs=obs)
    for k in range(6):                       # 6 mutations > snapshot_every
        assert b.try_acquire(k, "a")
    assert os.path.exists(os.path.join(d, SNAPSHOT_NAME))
    assert len(_wal_lines(d)) == 2           # only the post-compaction tail
    before = _state(b)
    assert _state(DurableCASBackend(d)) == before
    obs.close()
    (ev,) = read_journal(jp, event="SNAPSHOT_COMPACT")
    assert ev["seq"] == 4 and ev["keys"] == 4


def test_wal_recovery_matches_in_memory_reference(tmp_path):
    """Property test: a seeded random op stream drives the durable
    backend and the in-memory reference in lockstep — every return
    value must agree — then a restart must reproduce the durable
    backend's record map bit-exactly."""
    rng = random.Random(20260807)
    d = str(tmp_path / "cas")
    ref = MemoryCASBackend()
    dur = DurableCASBackend(d, snapshot_every=13)
    keys = list(range(1, 9))
    owners = ["a", "b", "c"]
    for _step in range(300):
        op = rng.choice(("acquire", "release", "release_any",
                         "heartbeat", "backdate", "break"))
        k, o = rng.choice(keys), rng.choice(owners)
        if op == "acquire":
            assert ref.try_acquire(k, o) == dur.try_acquire(k, o)
        elif op == "release":
            assert ref.release(k, owner=o) == dur.release(k, owner=o)
        elif op == "release_any":
            assert ref.release(k) == dur.release(k)
        elif op == "heartbeat":
            assert ref.heartbeat(k, o) == dur.heartbeat(k, o)
        elif op == "backdate":
            ref.backdate(k, 30.0)
            dur.backdate(k, 30.0)
        else:
            assert (ref.break_stale(k, ttl_s=10.0)
                    == dur.break_stale(k, ttl_s=10.0))
    assert ref.list_keys() == dur.list_keys()
    assert ({k: (o, v) for k, o, _t, v in ref.dump()}
            == {k: (o, v) for k, o, _t, v in dur.dump()})
    assert _state(DurableCASBackend(d, snapshot_every=13)) == _state(dur)


def test_wal_append_fault_degrades_but_serves(tmp_path):
    d = str(tmp_path / "cas")
    b = DurableCASBackend(d, snapshot_every=1000)
    assert b.try_acquire(1, "a")
    arm_disk_fault("append_jsonl", kind="ENOSPC", count=1, match=WAL_NAME)
    with pytest.warns(UserWarning, match="WAL append degraded"):
        assert b.try_acquire(2, "b")         # the op itself still serves
    assert b.wal_faults == 1
    assert b.owner_of(2) == "b"              # in memory
    assert b.try_acquire(3, "c")             # fault count exhausted: logs
    # the degraded mutation is NOT in the log — a restart loses exactly
    # that record (its durability was the fault), everything else holds
    reborn = DurableCASBackend(d, snapshot_every=1000)
    assert reborn.owner_of(1) == "a" and reborn.owner_of(3) == "c"
    assert reborn.owner_of(2) is None


def test_snapshot_fault_degrades_and_rearms(tmp_path):
    d = str(tmp_path / "cas")
    b = DurableCASBackend(d, snapshot_every=3)
    arm_disk_fault("atomic_write_json", kind="ENOSPC", count=1,
                   match=SNAPSHOT_NAME)
    with pytest.warns(UserWarning, match="compaction degraded"):
        for k in range(3):
            assert b.try_acquire(k, "a")
    assert b.wal_faults == 1
    assert not os.path.exists(os.path.join(d, SNAPSHOT_NAME))
    for k in range(3, 6):                    # another window: retries, lands
        assert b.try_acquire(k, "a")
    assert os.path.exists(os.path.join(d, SNAPSHOT_NAME))
    assert len(_state(DurableCASBackend(d))) == 6


# ---------------------------------------------------------------------------
# The disk-fault injector (utils.checkpoint) and durable writers.
# ---------------------------------------------------------------------------

def test_disk_fault_injector_fires_counts_and_disarms(tmp_path):
    p = str(tmp_path / "x.json")
    arm_disk_fault("atomic_write_json", kind="ENOSPC", count=2)
    for _ in range(2):
        with pytest.raises(OSError) as ei:
            atomic_write_json(p, {"v": 1})
        assert ei.value.errno == errno.ENOSPC
    atomic_write_json(p, {"v": 2})           # count exhausted
    with open(p) as f:
        assert json.load(f)["v"] == 2
    arm_disk_fault("atomic_write_json", kind="EIO", count=1)
    with pytest.raises(OSError) as ei:
        atomic_write_json(p, {"v": 3})
    assert ei.value.errno == errno.EIO
    arm_disk_fault("atomic_write_json", count=5)
    disarm_disk_faults()
    atomic_write_json(p, {"v": 4})           # disarm clears everything


def test_disk_fault_match_scopes_the_blast_radius(tmp_path):
    arm_disk_fault("atomic_write_text", count=5, match="victim")
    other = str(tmp_path / "bystander.txt")
    atomic_write_text(other, "fine")         # unmatched path: untouched
    with pytest.raises(OSError):
        atomic_write_text(str(tmp_path / "victim.txt"), "boom")
    disarm_disk_faults()


def test_disk_fault_event_journaled(tmp_path):
    jp = str(tmp_path / "j.jsonl")
    obs = build_obs(ObsConfig(enabled=True, journal_path=jp))
    arm_disk_fault("save_pytree", kind="ENOSPC", count=1)
    with obs.activate():
        with pytest.raises(OSError):
            save_pytree(str(tmp_path / "sol.npz"), {"a": np.zeros(2)})
    obs.close()
    (ev,) = read_journal(jp, event="DISK_FAULT")
    assert ev["op"] == "save_pytree" and ev["injected"] is True


@pytest.mark.parametrize("writer,read", [
    (lambda p: atomic_write_text(p, "hello", durable=True),
     lambda p: open(p).read()),
    (lambda p: atomic_write_json(p, {"k": 1}, durable=True),
     lambda p: json.load(open(p))),
    (lambda p: append_jsonl(p, ['{"k": 1}'], durable=True),
     lambda p: json.loads(open(p).read())),
])
def test_durable_writers_roundtrip(tmp_path, writer, read):
    """``durable=True`` (fsync file + parent dir) must not change WHAT
    is written, only how hard it is to lose."""
    p = str(tmp_path / "out.txt")
    writer(p)
    assert read(p) in ("hello", {"k": 1})


def test_save_pytree_durable_roundtrip(tmp_path):
    from aiyagari_hark_tpu.utils.checkpoint import load_pytree

    p = str(tmp_path / "t.npz")
    save_pytree(p, {"a": np.arange(3.0)}, durable=True)
    out = load_pytree(p, {"a": np.zeros(3)})
    np.testing.assert_array_equal(out["a"], np.arange(3.0))


# ---------------------------------------------------------------------------
# Quorum client unit behaviors (replicated.ReplicatedCASBackend).
# ---------------------------------------------------------------------------

def _rec(owner, stamp, version, age=0.0):
    return {"owner": owner, "stamp": stamp, "version": version,
            "age": age}


def test_winner_rule_highest_version_then_most_replicas():
    w = ReplicatedCASBackend._winner
    # highest version wins regardless of replica count
    rec, age, holders = w({0: _rec("a", 1.0, 2, age=0.5),
                           1: _rec("b", 9.0, 1, age=99.0),
                           2: _rec("b", 9.0, 1, age=99.0)})
    assert rec["owner"] == "a" and rec["version"] == 2
    assert age == 0.5 and holders == [0]
    # same version, different variants: most-replicated variant wins
    rec, age, holders = w({0: _rec("a", 1.0, 3, age=7.0),
                           1: _rec("b", 2.0, 3, age=1.0),
                           2: _rec("b", 2.0, 3, age=2.0)})
    assert rec["owner"] == "b" and sorted(holders) == [1, 2]
    assert age == 1.0                        # MIN age over the variant
    # all-absent / all-tombstone-free: no winner
    assert w({0: None, 1: None}) == (None, None, [])


def _quorum(tmp_path, jp=None):
    srvs = [CASServer().start() for _ in range(3)]
    b = ReplicatedCASBackend([s.address for s in srvs])
    if jp is not None:
        obs = build_obs(ObsConfig(enabled=True, journal_path=jp))
        b.attach_obs(obs)
        return srvs, b, obs
    return srvs, b, None


def test_read_repair_converges_a_stale_replica(tmp_path):
    jp = str(tmp_path / "j.jsonl")
    srvs, b, obs = _quorum(tmp_path, jp)
    try:
        assert b.try_acquire(5, "a")         # v1 on all three replicas
        # age replica 2 out-of-band: bump the record on the majority
        # only, leaving 2 a version behind WITHOUT any failed op (no
        # suspect marking — rejoin resync must not be what repairs it)
        assert srvs[0].backend.heartbeat(5, "a")
        assert srvs[1].backend.heartbeat(5, "a")
        stale = srvs[2].backend.get(5)
        win = srvs[0].backend.get(5)
        assert stale["version"] < win["version"]
        assert b.owner_of(5) == "a"          # read sees the laggard...
        rec = srvs[2].backend.get(5)         # ...and repaired it in place
        assert rec["version"] == win["version"]
        assert b.read_repairs >= 1
    finally:
        obs.close()
        b.close()
        for s in srvs:
            s.stop()
    modes = [e["mode"] for e in read_journal(jp, event="REPLICA_RESYNC")]
    assert "read_repair" in modes


def test_quorum_loss_is_edge_triggered_and_typed(tmp_path):
    jp = str(tmp_path / "j.jsonl")
    srvs, b, obs = _quorum(tmp_path, jp)
    try:
        b.set_partition([1, 2])              # minority reachable
        for _ in range(3):                   # every op refuses typed...
            with pytest.raises(CoordinationUnavailable):
                b.try_acquire(1, "a")
        b.set_partition([])
        assert b.try_acquire(1, "a")         # healed: serving again
        b.set_partition([0, 1])
        with pytest.raises(CoordinationUnavailable):
            b.owner_of(1)
    finally:
        obs.close()
        b.close()
        for s in srvs:
            s.stop()
    # ...but journals ONCE per outage: two outages, two events
    assert len(read_journal(jp, event="QUORUM_LOST")) == 2


def test_minority_partition_keeps_serving(tmp_path):
    srvs, b, _ = _quorum(tmp_path)
    try:
        b.set_partition([2])                 # one replica dark: majority up
        assert b.try_acquire(7, "a")
        assert b.owner_of(7) == "a"
        assert b.release(7, owner="a")
    finally:
        b.close()
        for s in srvs:
            s.stop()


def test_rejoin_triggers_anti_entropy_resync(tmp_path):
    jp = str(tmp_path / "j.jsonl")
    srvs, b, obs = _quorum(tmp_path, jp)
    try:
        b.set_partition([2])
        for k in (1, 2, 3):
            assert b.try_acquire(k, "a")     # replica 2 misses all three
        b.set_partition([])
        assert b.owner_of(1) == "a"          # heal: rejoin detection fires
        assert b.resyncs >= 1
        # convergence check over the PUBLIC dump op: once the dust
        # settles every replica holds every record
        for s in srvs:
            keys = {int(k) for k, o, _t, _v in s.backend.dump()
                    if o is not None}
            assert keys == {1, 2, 3}, s.address
    finally:
        obs.close()
        b.close()
        for s in srvs:
            s.stop()
    modes = [e["mode"] for e in read_journal(jp, event="REPLICA_RESYNC")]
    assert "anti_entropy" in modes


# ---------------------------------------------------------------------------
# Store integration: bounded close (satellite a), memory-only degrade.
# ---------------------------------------------------------------------------

def _shared_store(tmp_path, backend, jp):
    from aiyagari_hark_tpu.serve import SolutionStore

    store = SolutionStore(disk_path=str(tmp_path / "store"), shared=True,
                          lease_ttl_s=60.0, owner="t",
                          lease_backend=backend)
    obs = build_obs(ObsConfig(enabled=True, journal_path=jp))
    store.attach_obs(obs)
    return store, obs


def test_close_release_budget_is_bounded_and_journaled(tmp_path):
    jp = str(tmp_path / "j.jsonl")
    store, obs = _shared_store(tmp_path, MemoryCASBackend(), jp)
    assert store.claim(101) == "won"
    assert store.claim(102) == "won"
    t0 = time.monotonic()
    store.close(release_leases=True, timeout_s=0.0)   # budget pre-spent
    assert time.monotonic() - t0 < 5.0
    obs.close()
    faults = read_journal(jp, event="LEASE_BACKEND_FAULT")
    assert any(e["op"] == "close_release"
               and "left for TTL reclaim" in e["detail"] for e in faults)


def test_close_releases_within_budget(tmp_path):
    jp = str(tmp_path / "j.jsonl")
    backend = MemoryCASBackend()
    store, obs = _shared_store(tmp_path, backend, jp)
    assert store.claim(7) == "won"
    store.close(release_leases=True, timeout_s=10.0)
    assert backend.list_keys() == []         # orderly shutdown released it
    obs.close()
    assert not any(e["op"] == "close_release"
                   for e in read_journal(jp, event="LEASE_BACKEND_FAULT"))


def test_put_disk_fault_degrades_memory_only(tmp_path):
    from aiyagari_hark_tpu.serve import make_solution
    from aiyagari_hark_tpu.solver_health import CONVERGED

    jp = str(tmp_path / "j.jsonl")
    store, obs = _shared_store(tmp_path, MemoryCASBackend(), jp)
    packed = np.asarray([0.035, 5.0, 0.9, 11.0, 500.0, 4000.0,
                         float(CONVERGED), 0.0, 4500.0, 0.0])
    sol = make_solution((3.0, 0.6, 0.2), packed, 7, 42)
    arm_disk_fault("save_pytree", kind="ENOSPC", count=1, match="sol_")
    with obs.activate():
        with pytest.warns(UserWarning, match="memory-only"):
            store.put(sol)
    assert store.get(42) is not None         # served from memory
    assert store.fleet_counts()["fleet_store_degraded"] == 1
    store.put(sol)                           # disk healed: persists now
    store.close()
    obs.close()
    degraded = read_journal(jp, event="STORE_DEGRADED")
    assert len(degraded) == 1 and degraded[0]["key"] == 42


def test_ledger_flush_disk_fault_skips_loudly(tmp_path):
    from aiyagari_hark_tpu.utils.resilience import LedgerState

    jp = str(tmp_path / "j.jsonl")
    obs = build_obs(ObsConfig(enabled=True, journal_path=jp))
    led = LedgerState(str(tmp_path / "ledger.npz"), fingerprint=7,
                      n_cells=3)
    arm_disk_fault("save_pytree", kind="EIO", count=1, match="ledger")
    with obs.activate():
        with pytest.warns(UserWarning, match="skipping this flush"):
            led.flush()                      # degrades, does not raise
    assert not os.path.exists(led.path)
    led.flush()                              # next flush lands
    assert os.path.exists(led.path)
    obs.close()
    ops = [e["op"] for e in read_journal(jp, event="DISK_FAULT")]
    assert "ledger_flush" in ops


# ---------------------------------------------------------------------------
# Regression sentinel: every dr_* bench field grades in a declared
# direction (satellite e).
# ---------------------------------------------------------------------------

def test_direction_covers_dr_smoke_record():
    from aiyagari_hark_tpu.obs.regress import (
        DOWN,
        NEUTRAL,
        OK,
        UP,
        direction_of_goodness,
        evaluate_history,
        flatten_record,
    )

    dr_record = {
        "metric": "dr_smoke", "backend": "cpu",
        "dr_replicas": 3, "dr_workers": 4, "dr_arrivals": 38,
        "dr_wall_s": 400.0, "dr_served": 38, "dr_unresolved": 0,
        "dr_drills_injected": 5, "dr_drills_detected": 5,
        "dr_detect_all": True,
        "dr_detected_replica_kill": 1, "dr_detected_torn_wal_tail": 1,
        "dr_detected_snapshot_mid_write": 1,
        "dr_detected_minority_partition": 1,
        "dr_detected_disk_full_publish": 1,
        "dr_state_mismatches": 0, "dr_state_reference_equal": True,
        "dr_recovered_keys": 17, "dr_kill_lease_observed": True,
        "dr_orphan_reclaimed": True, "dr_recovery_wall_s": 42.0,
        "dr_wal_replays": 5, "dr_snapshot_compacts": 2,
        "dr_dedup_ratio": 1.0, "dr_dedup_exact": True,
        "dr_drill_dup_violations": 0,
        "dr_leases_leaked": 0, "dr_reclaims": 1,
        "dr_bit_identical": True, "dr_value_mismatches": 0,
        "dr_value_divergence": 0, "dr_seeded_compares": 12,
        "dr_sentinel_clean": True, "dr_sentinel_worst": "OK",
    }
    for field in flatten_record(dr_record):
        assert direction_of_goodness(field, strict=True) in (
            UP, DOWN, NEUTRAL), field
    assert direction_of_goodness("dr_dedup_ratio") == DOWN
    assert direction_of_goodness("dr_leases_leaked") == DOWN
    assert direction_of_goodness("dr_state_mismatches") == DOWN
    assert direction_of_goodness("dr_recovery_wall_s") == DOWN
    assert direction_of_goodness("dr_drills_detected") == NEUTRAL
    # stable synthetic history grades clean; a dedup-ratio rise (a
    # duplicate publish escaping the drill accounting) flags REGRESSED,
    # and a leaked lease at least NOISE (zero baseline: the sentinel
    # cannot compute a relative move, but it still flags the jump)
    hist = [(f"r{i:02d}", dict(dr_record)) for i in range(4)]
    assert evaluate_history(hist).worst == OK
    worse = dict(dr_record)
    worse["dr_dedup_ratio"] = 1.5
    worse["dr_leases_leaked"] = 2
    report = evaluate_history(hist[:-1] + [("r99", worse)])
    assert "dr_dedup_ratio" in [f.metric for f in report.regressed()]
    assert any(f.metric == "dr_leases_leaked" and f.severity > OK
               for f in report.findings)


def test_new_event_types_are_registered():
    from aiyagari_hark_tpu.obs.journal import EVENT_TYPES

    for ev in ("WAL_REPLAY", "SNAPSHOT_COMPACT", "REPLICA_RESYNC",
               "QUORUM_LOST", "STORE_DEGRADED", "DISK_FAULT"):
        assert ev in EVENT_TYPES, ev
